from adam_tpu.api.datasets import AlignmentDataset

__all__ = ["AlignmentDataset"]
