"""Spark-embedding executor: the per-partition kernel server.

The BASELINE north star wires this framework into the reference's Spark
pipeline as a *backend*: inside ``mapPartitions``, each executor ships
its partition across the Arrow seam, the TPU-side process runs the
requested read transforms, and recalibrated/realigned/marked records
stream back — zero changes to the calling pipeline
(adam-cli/.../Transform.scala:101-163's stage set, driven externally).

Protocol (one process per executor, ``transform -backend spark - -``):

* stdin:  one Arrow IPC *stream*; **each record batch is one Spark
  partition** in the AlignmentRecord column layout
  (io/parquet.to_arrow_alignments — the schema `from_arrow` accepts).
* stdout: one Arrow IPC stream; each input partition produces exactly
  one output batch, in order, so the driver can zip results back to
  partitions.
* stderr: logs.  Exit code 0 on a cleanly drained stream.

Per-partition semantics match Spark's mapPartitions contract: stages
see one partition at a time (the Spark driver owns any cross-partition
shuffle, exactly as it does for the reference's own implementations).
Within a partition, stages run in the reference Transform order:
duplicate marking -> indel realignment -> BQSR.

A py4j/JNI bridge would hand the same batches over a socket; the
stdin/stdout stream is the transport-agnostic core (and what the round
trip test drives).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import BinaryIO, Optional


@dataclass
class StageConfig:
    """Every stage is opt-in, matching the reference Transform flags."""

    mark_duplicates: bool = False
    recalibrate: bool = False
    realign: bool = False
    known_snps: object = None
    known_indels: object = None
    consensus_model: str = "reads"


def apply_stages(ds, cfg: StageConfig):
    # reference composition: markdup -> realign -> BQSR
    # (Transform.scala:121-144)
    if cfg.mark_duplicates:
        ds = ds.mark_duplicates()
    if cfg.realign:
        kw = {}
        if cfg.known_indels is not None:
            kw = dict(consensus_model="knowns",
                      known_indels=cfg.known_indels)
        elif cfg.consensus_model != "reads":
            kw = dict(consensus_model=cfg.consensus_model)
        ds = ds.realign_indels(**kw)
    if cfg.recalibrate:
        ds = ds.recalibrate_base_qualities(known_snps=cfg.known_snps)
    return ds


def serve(cfg: StageConfig, inp: Optional[BinaryIO] = None,
          outp: Optional[BinaryIO] = None) -> int:
    """Drain an Arrow IPC stream of partitions, transform each, stream
    results back.  Returns the number of partitions served."""
    import pyarrow as pa

    from adam_tpu.api.datasets import AlignmentDataset

    inp = inp if inp is not None else sys.stdin.buffer
    outp = outp if outp is not None else sys.stdout.buffer
    reader = pa.ipc.open_stream(inp)
    writer = None
    served = 0
    try:
        for rb in reader:
            ds = AlignmentDataset.from_arrow(rb)
            ds = apply_stages(ds, cfg)
            table = ds.compact().to_arrow().combine_chunks()
            out_rb = (
                table.to_batches()[0]
                if table.num_rows
                else pa.record_batch(
                    [c.combine_chunks() for c in table.columns],
                    schema=table.schema,
                )
            )
            if writer is None:
                writer = pa.ipc.new_stream(outp, out_rb.schema)
            writer.write_batch(out_rb)
            served += 1
    finally:
        if writer is None:
            # zero partitions: still emit a valid (empty) IPC stream so
            # the driver's open_stream on the reply pipe succeeds
            from adam_tpu.io.parquet import to_arrow_alignments
            from adam_tpu.formats.batch import ReadBatch, ReadSidecar
            from adam_tpu.io.sam import SamHeader

            schema = to_arrow_alignments(
                ReadBatch.empty(), ReadSidecar(), SamHeader()
            ).schema
            writer = pa.ipc.new_stream(outp, schema)
        writer.close()
        outp.flush()
    return served
