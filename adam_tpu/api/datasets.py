"""User-facing dataset handles.

The reference enriches Spark RDDs with genomic methods via implicits
(``import ADAMContext._``, rdd/ADAMContext.scala:54-102;
AlignmentRecordRDDFunctions).  Here the handle is an explicit value type:
:class:`AlignmentDataset` bundles the device batch, the host sidecar, and
the header dictionaries, and exposes the transform/save methods of
AlignmentRecordRDDFunctions (rdd/read/AlignmentRecordRDDFunctions.scala:45-588).

Transforms delegate to :mod:`adam_tpu.pipelines` and return new datasets
(immutability mirrors RDD semantics and keeps the device path functional).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import TYPE_CHECKING, Optional

import numpy as np

from adam_tpu.formats.batch import ReadBatch, ReadSidecar

if TYPE_CHECKING:  # avoid io<->api import cycle at runtime
    from adam_tpu.io.sam import SamHeader


@dataclass
class AlignmentDataset:
    batch: ReadBatch
    sidecar: ReadSidecar
    header: "SamHeader"

    # ------------------------------------------------------------------ io
    @staticmethod
    def load(path: str, **kw) -> "AlignmentDataset":
        from adam_tpu.io import context

        return context.load_alignments(path, **kw)

    def save(self, path: str, sort_order: Optional[str] = None,
             compression: str = "zstd") -> None:
        """Dispatch on extension like adamSave/adamSAMSave."""
        p = str(path)
        if p.endswith(".sam"):
            from adam_tpu.io import sam

            sam.write_sam(p, self.batch, self.sidecar, self.header, sort_order)
        elif p.endswith(".bam"):
            from adam_tpu.io import sam

            sam.write_bam(p, self.batch, self.sidecar, self.header, sort_order)
        elif p.endswith((".fq", ".fastq")):
            from adam_tpu.io import fastq

            fastq.write_fastq(p, self.batch, self.sidecar)
        else:
            from adam_tpu.io import parquet

            parquet.save_alignments(p, self.batch, self.sidecar, self.header,
                                    compression=compression)

    def to_arrow(self):
        """-> pyarrow Table (AlignmentRecord layout, header in metadata).

        The Spark-embedding seam (BASELINE north star): record batches
        of this table can cross a py4j/mapPartitions boundary and be
        reconstructed with :meth:`from_arrow` on either side."""
        from adam_tpu.io import parquet

        return parquet.to_arrow_alignments(self.batch, self.sidecar, self.header)

    @staticmethod
    def from_arrow(table_or_batches) -> "AlignmentDataset":
        """pyarrow Table / RecordBatch(es) -> AlignmentDataset."""
        import pyarrow as pa

        from adam_tpu.io import parquet

        t = table_or_batches
        if isinstance(t, pa.RecordBatch):
            t = pa.Table.from_batches([t])
        elif isinstance(t, (list, tuple)):
            t = pa.Table.from_batches(list(t))
        batch, side, header = parquet.from_arrow_alignments(t)
        return AlignmentDataset(batch, side, header)

    def save_paired_fastq(
        self, path1: str, path2: str, stringency="lenient"
    ) -> None:
        from adam_tpu.io import fastq

        fastq.write_paired_fastq(
            path1, path2, self.batch, self.sidecar, stringency=stringency
        )

    # ------------------------------------------------------------- helpers
    def __len__(self) -> int:
        return self.batch.n_valid()

    @property
    def seq_dict(self):
        return self.header.seq_dict

    @property
    def read_groups(self):
        return self.header.read_groups

    def with_batch(
        self, batch: ReadBatch, sidecar: Optional[ReadSidecar] = None
    ) -> "AlignmentDataset":
        return replace(
            self, batch=batch, sidecar=sidecar if sidecar is not None else self.sidecar
        )

    def take_rows(self, idx) -> "AlignmentDataset":
        idx = np.asarray(idx)
        return replace(
            self, batch=self.batch.to_numpy().take(idx), sidecar=self.sidecar.take(idx)
        )

    def compact(self) -> "AlignmentDataset":
        """Drop invalid (padding/filtered) rows."""
        return self.take_rows(np.flatnonzero(np.asarray(self.batch.valid)))

    @staticmethod
    def concat(parts: list["AlignmentDataset"]) -> "AlignmentDataset":
        """Splice datasets sharing a header (window/shard reassembly)."""
        if not parts:
            from adam_tpu.io.sam import SamHeader

            return AlignmentDataset(ReadBatch.empty(), ReadSidecar(), SamHeader())
        if len(parts) == 1:
            return parts[0]
        return AlignmentDataset(
            ReadBatch.concat([p.batch for p in parts]),
            ReadSidecar.concat([p.sidecar for p in parts]),
            parts[0].header,
        )

    # ---------------------------------------------------------- transforms
    def sort_by_reference_position(self) -> "AlignmentDataset":
        from adam_tpu.pipelines import sort

        return sort.sort_by_reference_position(self)

    def mark_duplicates(self, backend: Optional[str] = None) -> "AlignmentDataset":
        """``backend`` picks the per-residue kernel set — ``device`` (jit
        chip kernels, the default when an accelerator is attached),
        ``native`` (threaded C++), or ``numpy``; None defers to
        ``ADAM_TPU_BQSR_BACKEND`` / topology (see
        :func:`adam_tpu.pipelines.bqsr.bqsr_backend`)."""
        from adam_tpu.pipelines import markdup

        return markdup.mark_duplicates(self, backend=backend)

    def recalibrate_base_qualities(
        self, known_snps=None, backend: Optional[str] = None, **kw
    ) -> "AlignmentDataset":
        """``backend`` as in :meth:`mark_duplicates` — one flag selects
        the kernel set for every per-residue pass."""
        from adam_tpu.pipelines.bqsr import recalibrate_base_qualities

        return recalibrate_base_qualities(
            self, known_snps=known_snps, backend=backend, **kw
        )

    def realign_indels(self, **kw) -> "AlignmentDataset":
        from adam_tpu.pipelines.realign import realign_indels

        return realign_indels(self, **kw)

    def trim_reads(self, trim_start: int = -1, trim_end: int = -1) -> "AlignmentDataset":
        from adam_tpu.pipelines import trim

        return trim.trim_reads(self, trim_start, trim_end)

    def trim_low_quality_read_groups(self, phred_threshold: int = 20):
        from adam_tpu.pipelines import trim

        return trim.trim_low_quality_read_groups(self, phred_threshold)

    # ------------------------------------------------------------ analyses
    def flagstat(self):
        from adam_tpu.ops import flagstat

        return flagstat.flagstat(self.batch)

    def count_kmers(self, k: int):
        from adam_tpu.ops import kmer

        return kmer.count_kmers(self.batch, k)

    def count_qmers(self, k: int):
        from adam_tpu.ops import kmer

        return kmer.count_qmers(self.batch, k)


@dataclass
class FeatureDataset:
    """Genomic features handle (GTF/BED/narrowPeak) — the
    FeatureRDDFunctions / GeneFeatureRDDFunctions surface
    (rdd/features/, SURVEY §2 feature rows)."""

    batch: "object"  # formats.features.FeatureBatch

    @staticmethod
    def load(path: str, fmt=None) -> "FeatureDataset":
        from adam_tpu.io import features as fio

        return FeatureDataset(fio.read_features(path, fmt))

    def save(self, path: str) -> None:
        from adam_tpu.io import features as fio

        fio.write_bed(path, self.batch)

    def __len__(self) -> int:
        return len(self.batch)

    def filter_by_overlapping_region(self, contig, start, end):
        return FeatureDataset(
            self.batch.filter_by_overlapping_region(contig, start, end)
        )

    def as_genes(self):
        from adam_tpu.models.genes import as_genes

        return as_genes(self.batch)

    def intervals(self, contig_names=None):
        return self.batch.intervals(contig_names)


@dataclass
class GenotypeDataset:
    """Variant sites + per-sample calls — the VariantContext aggregate.

    Covers the surface of VariantContextRDDFunctions /
    GenotypeRDDFunctions (rdd/variation/VariationRDDFunctions.scala:40-160):
    VCF load/save, callset samples, variant-keyed annotation join, and
    the allele-count analysis. Variants and genotypes stay columnar
    (:mod:`adam_tpu.formats.variants`), linked by ``genotypes.variant_idx``.
    """

    variants: "object"  # formats.variants.VariantBatch
    genotypes: "object"  # formats.variants.GenotypeBatch
    seq_dict: "object"  # SequenceDictionary

    @staticmethod
    def load(path: str, **kw) -> "GenotypeDataset":
        """.vcf(.gz) -> VCF codec; anything else -> genotype Parquet
        directory (the loadVcf / Parquet dispatch of loadGenotypes)."""
        p = str(path)
        if p.endswith((".vcf", ".vcf.gz")):
            from adam_tpu.io import vcf as vcf_io

            v, g, sd = vcf_io.read_vcf(p, **kw)
        else:
            from adam_tpu.io import parquet

            v, g, sd = parquet.load_genotypes(p, **kw)
        return GenotypeDataset(v, g, sd)

    def save(self, path: str, sort_on_save: bool = False) -> None:
        p = str(path)
        if p.endswith((".vcf", ".vcf.gz")):
            from adam_tpu.io import vcf as vcf_io

            vcf_io.write_vcf(
                p, self.variants, self.genotypes, self.seq_dict, sort_on_save
            )
        else:
            from adam_tpu.io import parquet

            ds = self.sorted_by_position() if sort_on_save else self
            parquet.save_genotypes(
                p, ds.variants, ds.genotypes, ds.seq_dict
            )

    def __len__(self) -> int:
        return len(self.variants)

    def sorted_by_position(self) -> "GenotypeDataset":
        """Order variants by (contig, start) and remap genotype links."""
        import numpy as np

        order = np.lexsort((self.variants.start, self.variants.contig_idx))
        inverse = np.empty(len(order), np.int32)
        inverse[order] = np.arange(len(order), dtype=np.int32)
        variants = self.variants.take(order)
        from dataclasses import replace as dc_replace

        genotypes = dc_replace(
            self.genotypes,
            variant_idx=inverse[self.genotypes.variant_idx],
        )
        return GenotypeDataset(variants, genotypes, self.seq_dict)

    @property
    def contig_names(self) -> list:
        return [r.name for r in self.seq_dict.records]

    def callset_samples(self) -> list:
        """Distinct sample ids (getCallsetSamples, :62-68)."""
        return list(self.genotypes.samples)

    def variant_keys(self) -> np.ndarray:
        return self.variants.variant_keys(self.contig_names)

    def join_annotations(self, ann_keys, ann_values) -> list:
        """Left outer join on variant key
        (joinDatabaseVariantAnnotation, :55-60): returns per-site
        annotation values (None where unmatched)."""
        table = dict(zip(list(ann_keys), list(ann_values)))
        return [table.get(k) for k in self.variant_keys()]

    def allele_count(self):
        from adam_tpu.formats.variants import allele_counts

        return allele_counts(self.variants, self.genotypes, self.contig_names)

    def snp_table(self):
        """Known-sites table for BQSR (SnpTable VCF constructor,
        models/SnpTable.scala:77-96: every ref position of every
        variant masks)."""
        from adam_tpu.models.snp_table import SnpTable

        names = self.contig_names
        side = self.variants.sidecar
        pairs = []
        for i in range(len(self.variants)):
            # skip gVCF reference-model rows (alt=None): their END-extended
            # spans are non-variant sequence, not known sites
            if side.alt_allele[i] is None:
                continue
            c = names[self.variants.contig_idx[i]]
            start = int(self.variants.start[i])
            for p in range(start, start + int(self.variants.ref_len[i])):
                pairs.append((c, p))
        return SnpTable.from_variants(pairs)

    def indel_table(self):
        """Known-indels table for realignment
        (IndelTable.apply from variants, models/IndelTable.scala:43-66)."""
        from adam_tpu.models.snp_table import IndelTable

        names = self.contig_names
        side = self.variants.sidecar
        tuples = [
            (
                names[self.variants.contig_idx[i]],
                int(self.variants.start[i]),
                side.ref_allele[i],
                side.alt_allele[i],
            )
            for i in range(len(self.variants))
            if side.alt_allele[i]
        ]
        return IndelTable.from_variants(tuples)
