"""Library submission seam for the multi-job transform service.

The thin front the ROADMAP's always-on-service direction names, sitting
next to :mod:`adam_tpu.api.spark_executor` (the other embedding seam):
callers hand :class:`~adam_tpu.serve.job.JobSpec`s to a
:class:`TransformService` and get typed admission results back — the
in-process analog of a submission RPC.  An HTTP/queue front would wrap
exactly this surface; keeping it transport-free is what lets the CLI,
the tests and the chaos harness drive the same scheduler.

Manifest format (``adam-tpu serve --jobs FILE``)::

    {"jobs": [{"job_id": "tenantA-1", "input": "a.bam",
               "output": "a.adam", "tenant": "A", "weight": 2.0,
               "window_reads": 4096}, ...]}

A bare JSON list of job objects is accepted too.  Field names are the
:class:`JobSpec` dataclass fields; unknown keys are rejected so a
typo'd flag cannot silently no-op.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional, Union

from adam_tpu.serve.job import Admitted, Busy, JobSpec
from adam_tpu.serve.scheduler import JobScheduler
from adam_tpu.utils.retry import DeadlineExceeded, call_with_deadline


def load_jobs_manifest(path: str) -> list:
    """Parse a jobs manifest file into validated :class:`JobSpec`s.

    Raises ``ValueError`` with the offending entry on any malformed
    job — a half-loaded manifest must never submit a prefix."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        doc = doc.get("jobs")
    if not isinstance(doc, list):
        raise ValueError(
            f"jobs manifest {path}: expected a list of job objects or "
            '{"jobs": [...]}'
        )
    specs = []
    seen = set()
    for i, entry in enumerate(doc):
        if not isinstance(entry, dict):
            raise ValueError(
                f"jobs manifest {path}: entry {i} is not an object"
            )
        unknown = set(entry) - set(JobSpec.__dataclass_fields__)
        if unknown:
            raise ValueError(
                f"jobs manifest {path}: entry {i} has unknown "
                f"field(s) {sorted(unknown)}"
            )
        try:
            spec = JobSpec.from_doc(entry)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"jobs manifest {path}: entry {i}: {e}"
            ) from None
        if spec.job_id in seen:
            raise ValueError(
                f"jobs manifest {path}: duplicate job_id "
                f"{spec.job_id!r}"
            )
        seen.add(spec.job_id)
        specs.append(spec)
    return specs


class TransformService:
    """The in-process service facade: one scheduler, typed submissions.

    Thin by design — every method is a one-line delegation plus the
    blocking-submit convenience, so the robustness contract lives in
    exactly one place (:class:`~adam_tpu.serve.scheduler.JobScheduler`).
    """

    def __init__(self, run_root: str, **scheduler_kw):
        self.scheduler = JobScheduler(run_root, **scheduler_kw)

    # ---- submission -----------------------------------------------------
    def submit(self, spec: JobSpec) -> Union[Admitted, Busy]:
        return self.scheduler.submit(spec)

    def submit_blocking(self, spec: JobSpec,
                        deadline_s: Optional[float] = None,
                        poll_s: float = 0.1, *,
                        timeout: Optional[float] = None,
                        ) -> Union[Admitted, Busy]:
        """Submit, politely waiting out ``capacity`` rejections until a
        slot frees (the well-behaved client loop: `has_capacity` gates
        each attempt, so waiting does not spam the admission counters
        or the ``sched.admit`` fault point).  ``draining`` and
        ``duplicate`` rejections return immediately — retrying those
        would spin forever.

        ``deadline_s`` bounds the wait through
        :func:`~adam_tpu.utils.retry.call_with_deadline` — the bound
        holds even when the scheduler itself is WEDGED (a stuck
        ``wait`` under a hung job, not merely slow slot turnover), in
        which case a typed ``Busy(kind="capacity")`` surfaces instead
        of the caller spinning at ``poll_s`` forever.  ``timeout`` is
        the deprecated alias.  ``deadline_s=None`` waits indefinitely
        (the embedding caller owns its own bound)."""
        if deadline_s is None:
            deadline_s = timeout
        if deadline_s is not None and deadline_s <= 0:
            # zero budget = exactly one attempt (call_with_deadline
            # treats <=0 as "no deadline", which would invert this
            # into an unbounded wait)
            return self.scheduler.submit(spec)
        gave_up = threading.Event()
        attempted = threading.Event()
        # terminal submit results the worker reached, deadline or not:
        # an Admitted that lands as the deadline expires must reach
        # the caller — returning Busy for a job that IS running would
        # leak a slot the caller believes was refused
        outcome: list = []

        def wait_for_slot() -> Union[Admitted, Busy]:
            last: Optional[Busy] = None
            while not gave_up.is_set():
                # first pass always submits (duplicate/draining must
                # surface even with zero capacity); later passes gate
                # on has_capacity so the poll doesn't spam rejections
                if last is None or self.scheduler.has_capacity():
                    got = self.scheduler.submit(spec)
                    attempted.set()
                    if isinstance(got, Admitted) or got.kind != "capacity":
                        outcome.append(got)
                        return got
                    last = got
                self.scheduler.wait(timeout=poll_s)
            return last if last is not None else Busy(
                "submission abandoned", kind="capacity",
            )

        if deadline_s is None:
            return wait_for_slot()
        try:
            return call_with_deadline(
                wait_for_slot, deadline_s, site="service.submit_blocking"
            )
        except DeadlineExceeded:
            gave_up.set()
            # grace window: the worker may be INSIDE submit() right
            # now; a short wait collects a just-landed admission.  A
            # genuinely wedged scheduler never reaches outcome, and
            # the residual race (submit outliving the grace) is
            # recoverable by design — re-submitting the same spec
            # surfaces Busy(kind=duplicate), the idempotency signal.
            grace = time.monotonic() + max(poll_s, 0.1)
            while time.monotonic() < grace:
                if outcome:
                    return outcome[0]
                time.sleep(0.005)
            return Busy(
                f"no job slot freed within {deadline_s:.1f}s"
                + ("" if attempted.is_set()
                   else " (scheduler wedged: the admission check never "
                        "completed)"),
                kind="capacity",
            )
        finally:
            # unblock the watchdog's worker so an abandoned attempt
            # stops polling the scheduler instead of leaking a spinner
            gave_up.set()

    def cancel(self, job_id: str) -> bool:
        return self.scheduler.cancel(job_id)

    # ---- lifecycle ------------------------------------------------------
    def recover(self) -> list:
        return self.scheduler.recover()

    def request_drain(self) -> None:
        self.scheduler.request_drain()

    def drain(self, timeout: Optional[float] = None) -> bool:
        return self.scheduler.drain(timeout)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.scheduler.wait(timeout)

    def status(self) -> dict:
        return self.scheduler.status()

    def close(self) -> None:
        self.scheduler.close()
