"""Library submission seam for the multi-job transform service.

The thin front the ROADMAP's always-on-service direction names, sitting
next to :mod:`adam_tpu.api.spark_executor` (the other embedding seam):
callers hand :class:`~adam_tpu.serve.job.JobSpec`s to a
:class:`TransformService` and get typed admission results back — the
in-process analog of a submission RPC.  An HTTP/queue front would wrap
exactly this surface; keeping it transport-free is what lets the CLI,
the tests and the chaos harness drive the same scheduler.

Manifest format (``adam-tpu serve --jobs FILE``)::

    {"jobs": [{"job_id": "tenantA-1", "input": "a.bam",
               "output": "a.adam", "tenant": "A", "weight": 2.0,
               "window_reads": 4096}, ...]}

A bare JSON list of job objects is accepted too.  Field names are the
:class:`JobSpec` dataclass fields; unknown keys are rejected so a
typo'd flag cannot silently no-op.
"""

from __future__ import annotations

import json
import time
from typing import Optional, Union

from adam_tpu.serve.job import Admitted, Busy, JobSpec
from adam_tpu.serve.scheduler import JobScheduler


def load_jobs_manifest(path: str) -> list:
    """Parse a jobs manifest file into validated :class:`JobSpec`s.

    Raises ``ValueError`` with the offending entry on any malformed
    job — a half-loaded manifest must never submit a prefix."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        doc = doc.get("jobs")
    if not isinstance(doc, list):
        raise ValueError(
            f"jobs manifest {path}: expected a list of job objects or "
            '{"jobs": [...]}'
        )
    specs = []
    seen = set()
    for i, entry in enumerate(doc):
        if not isinstance(entry, dict):
            raise ValueError(
                f"jobs manifest {path}: entry {i} is not an object"
            )
        unknown = set(entry) - set(JobSpec.__dataclass_fields__)
        if unknown:
            raise ValueError(
                f"jobs manifest {path}: entry {i} has unknown "
                f"field(s) {sorted(unknown)}"
            )
        try:
            spec = JobSpec.from_doc(entry)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"jobs manifest {path}: entry {i}: {e}"
            ) from None
        if spec.job_id in seen:
            raise ValueError(
                f"jobs manifest {path}: duplicate job_id "
                f"{spec.job_id!r}"
            )
        seen.add(spec.job_id)
        specs.append(spec)
    return specs


class TransformService:
    """The in-process service facade: one scheduler, typed submissions.

    Thin by design — every method is a one-line delegation plus the
    blocking-submit convenience, so the robustness contract lives in
    exactly one place (:class:`~adam_tpu.serve.scheduler.JobScheduler`).
    """

    def __init__(self, run_root: str, **scheduler_kw):
        self.scheduler = JobScheduler(run_root, **scheduler_kw)

    # ---- submission -----------------------------------------------------
    def submit(self, spec: JobSpec) -> Union[Admitted, Busy]:
        return self.scheduler.submit(spec)

    def submit_blocking(self, spec: JobSpec,
                        timeout: Optional[float] = None,
                        poll_s: float = 0.1) -> Union[Admitted, Busy]:
        """Submit, politely waiting out ``capacity`` rejections until a
        slot frees (the well-behaved client loop: `has_capacity` gates
        each attempt, so waiting does not spam the admission counters
        or the ``sched.admit`` fault point).  ``draining`` and
        ``duplicate`` rejections return immediately — retrying those
        would spin forever."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        last = None
        while True:
            if last is None or self.scheduler.has_capacity():
                last = self.scheduler.submit(spec)
                if isinstance(last, Admitted) or last.kind != "capacity":
                    return last
            if deadline is not None and time.monotonic() >= deadline:
                return last
            self.scheduler.wait(timeout=poll_s)

    # ---- lifecycle ------------------------------------------------------
    def recover(self) -> list:
        return self.scheduler.recover()

    def request_drain(self) -> None:
        self.scheduler.request_drain()

    def drain(self, timeout: Optional[float] = None) -> bool:
        return self.scheduler.drain(timeout)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.scheduler.wait(timeout)

    def status(self) -> dict:
        return self.scheduler.status()

    def close(self) -> None:
        self.scheduler.close()
