"""Device mesh construction.

The reference's unit of parallelism is the Spark executor + partition;
ours is a 1-D ``jax.sharding.Mesh`` whose single axis ("shard") carries
both roles the reference splits between data partitioning and shuffle:
read batches are sharded along rows, genome fragments along coordinates,
and cross-shard movement is an XLA collective (psum / all_to_all /
ppermute) over ICI instead of a TCP shuffle (SURVEY.md §2.6).

Multi-host: `initialize_distributed` wires `jax.distributed` so the same
mesh spans hosts over DCN; the device axis ordering keeps intra-host
neighbors adjacent so halo exchanges ride ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"

#: Axis name of the streamed pipeline's per-window data-parallel mesh
#: (parallel/partitioner.MeshPartitioner): each window's [N, L] arrays
#: shard their read-row axis over it, observe histograms psum across it.
BATCH_AXIS = "batch"

# jax moved shard_map from jax.experimental (check_rep) to the top level
# (check_vma) — accept both spellings so the collectives run on every
# toolchain the container ships.
try:  # jax >= 0.6
    from jax import shard_map as _shard_map_impl

    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - toolchain-dependent
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _CHECK_KW = "check_rep"


def shard_map(f=None, **kw):
    """Version-portable ``jax.shard_map`` (keyword-style, decorator-friendly)."""
    if "check_vma" in kw and _CHECK_KW != "check_vma":
        kw[_CHECK_KW] = kw.pop("check_vma")
    if f is None:
        return functools.partial(shard_map, **kw)
    return _shard_map_impl(f, **kw)


def genome_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (SHARD_AXIS,))


def batch_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D ``batch`` mesh over the given (or all local) devices — the
    streamed pipeline's SPMD execution mesh.  Distinct from
    :func:`genome_mesh` only in axis name, so the partitioner's
    shardings read as what they are: data-parallel over read rows."""
    devices = list(devices) if devices is not None else jax.local_devices()
    return Mesh(np.array(devices), (BATCH_AXIS,))


def batch_row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (read-row) axis over the ``batch`` mesh."""
    return NamedSharding(mesh, P(BATCH_AXIS))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (read-row) axis across the mesh."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up (jax.distributed over DCN).

    No-op when single-process (the common test path); mirrors the role of
    the reference's Spark cluster deployment (driver + executors) with
    jax's coordinator + workers.
    """
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
