"""Genome-coordinate partitioning.

Semantics of ``rdd/GenomicPartitioners.scala``:

* :func:`position_partition` — GenomicPositionPartitioner.getPartition
  (:63-85): map (contig, pos) to one of N partitions by cumulative genome
  offset, with one extra partition for unmapped reads (partition N).
* :func:`region_partition` — GenomicRegionPartitioner (:102-121):
  fixed-size coordinate bins per contig.

Both return plain arrays so the result can drive either a host-side
scatter into per-device shards or a device all_to_all exchange.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import numpy as np

from adam_tpu.models.dictionaries import SequenceDictionary


@dataclass(frozen=True)
class GenomeBins:
    """Fixed-size genome binning (ShuffleRegionJoin.scala:140-193).

    Bin ids stack per contig in dictionary order; ``invert`` recovers the
    bin's region. This is the static genome->shard mapping shared by
    :func:`region_partition` and the shuffle region join.
    """

    bin_size: int
    seq_dict: SequenceDictionary

    @cached_property
    def bins_per_contig(self) -> np.ndarray:
        # every contig owns at least one bin, so contigs with undeclared
        # (0) length still have a home in the bin-id space
        return np.maximum(-(-self.seq_dict.lengths // self.bin_size), 1)

    @cached_property
    def bin_offsets(self) -> np.ndarray:
        return np.concatenate([[0], np.cumsum(self.bins_per_contig)])

    @property
    def num_bins(self) -> int:
        return int(self.bin_offsets[-1])

    def start_bin(self, contig_idx, start):
        ci = np.asarray(contig_idx)
        local = np.asarray(start) // self.bin_size
        return self.bin_offsets[ci] + np.minimum(
            local, self.bins_per_contig[ci] - 1
        )

    def end_bin(self, contig_idx, end):
        """Bin of the last covered base (end is exclusive). Clamped to the
        contig's last bin so intervals overhanging a declared contig
        length never spill into the next contig's bin-id range."""
        ci = np.asarray(contig_idx)
        local = np.maximum(np.asarray(end) - 1, 0) // self.bin_size
        return self.bin_offsets[ci] + np.minimum(
            local, self.bins_per_contig[ci] - 1
        )

    def invert(self, bin_id: int):
        """bin id -> (contig_idx, start, end) region of the bin."""
        contig = int(np.searchsorted(self.bin_offsets, bin_id, "right") - 1)
        local = bin_id - int(self.bin_offsets[contig])
        start = local * self.bin_size
        end = max(
            min(start + self.bin_size, int(self.seq_dict.lengths[contig])),
            start,
        )
        return contig, start, end

    def dedupe_region(self, bin_id: int):
        """Like :meth:`invert`, but the last bin of each contig extends to
        +inf: overhanging intervals clamp into that bin, and their starts
        must still satisfy the at-least-one-side-starts-here join rule."""
        contig, start, end = self.invert(bin_id)
        if bin_id == int(self.bin_offsets[contig + 1]) - 1:
            end = np.iinfo(np.int64).max
        return contig, start, end


def position_partition(
    seq_dict: SequenceDictionary,
    contig_idx,
    pos,
    num_partitions: int,
) -> np.ndarray:
    """Partition id per read; unmapped (contig_idx < 0) -> num_partitions.

    Mapped reads land in int(num_partitions * flattened / total_length),
    the cumulative-offset binning of the reference.
    """
    contig_idx = np.asarray(contig_idx)
    pos = np.asarray(pos)
    offsets = seq_dict.offsets
    total = max(seq_dict.total_length, 1)
    safe_idx = np.clip(contig_idx, 0, max(len(seq_dict) - 1, 0))
    flat = offsets[safe_idx] + np.maximum(pos, 0)
    part = (num_partitions * flat) // total
    part = np.clip(part, 0, num_partitions - 1)
    return np.where(contig_idx < 0, num_partitions, part).astype(np.int64)


def region_partition(
    seq_dict: SequenceDictionary,
    contig_idx,
    pos,
    partition_size: int,
) -> np.ndarray:
    """Fixed-size bin id, unique across contigs (bins stack per contig)."""
    contig_idx = np.asarray(contig_idx)
    pos = np.asarray(pos)
    bins = GenomeBins(partition_size, seq_dict)
    safe_idx = np.clip(contig_idx, 0, max(len(seq_dict) - 1, 0))
    out = bins.start_bin(safe_idx, np.maximum(pos, 0))
    return np.where(contig_idx < 0, -1, out).astype(np.int64)


def shard_rows_by_position(
    seq_dict: SequenceDictionary, contig_idx, pos, n_shards: int
) -> list[np.ndarray]:
    """Row indices per shard (unmapped rows appended to the last shard),
    the host-side scatter used to feed a genome-sharded mesh."""
    part = position_partition(seq_dict, contig_idx, pos, n_shards)
    part = np.where(part >= n_shards, n_shards - 1, part)
    return [np.flatnonzero(part == s) for s in range(n_shards)]


def partition_by_contig(contig_idx, n_partitions: int | None = None):
    """Partition rows by contig (rdd/ReferencePartitioner.scala): every
    row of a contig lands on the same partition.

    -> i32[N] partition ids in [0, n_partitions); unplaced rows (-1
    contig) go to the last partition.  Defaults to one partition per
    contig present.
    """
    contig_idx = np.asarray(contig_idx)
    uniq = np.unique(contig_idx[contig_idx >= 0])
    if n_partitions is None:
        n_partitions = max(1, len(uniq)) + 1
    # rank-encode before the modulo: raw ids can be sparse/high, which
    # would collide distinct contigs while leaving partitions empty
    rank = np.searchsorted(uniq, np.clip(contig_idx, 0, None))
    part = np.where(
        contig_idx >= 0,
        rank % max(1, n_partitions - 1),
        n_partitions - 1,
    )
    return part.astype(np.int32)


def shard_rows_by_contig(contig_idx, n_shards: int):
    """Row-index lists per shard under contig partitioning."""
    part = partition_by_contig(contig_idx, n_shards)
    return [np.flatnonzero(part == s) for s in range(n_shards)]
