"""Genome-coordinate partitioning.

Semantics of ``rdd/GenomicPartitioners.scala``:

* :func:`position_partition` — GenomicPositionPartitioner.getPartition
  (:63-85): map (contig, pos) to one of N partitions by cumulative genome
  offset, with one extra partition for unmapped reads (partition N).
* :func:`region_partition` — GenomicRegionPartitioner (:102-121):
  fixed-size coordinate bins per contig.

Both return plain arrays so the result can drive either a host-side
scatter into per-device shards or a device all_to_all exchange.
"""

from __future__ import annotations

import numpy as np

from adam_tpu.models.dictionaries import SequenceDictionary


def position_partition(
    seq_dict: SequenceDictionary,
    contig_idx,
    pos,
    num_partitions: int,
) -> np.ndarray:
    """Partition id per read; unmapped (contig_idx < 0) -> num_partitions.

    Mapped reads land in int(num_partitions * flattened / total_length),
    the cumulative-offset binning of the reference.
    """
    contig_idx = np.asarray(contig_idx)
    pos = np.asarray(pos)
    offsets = seq_dict.offsets
    total = max(seq_dict.total_length, 1)
    safe_idx = np.clip(contig_idx, 0, max(len(seq_dict) - 1, 0))
    flat = offsets[safe_idx] + np.maximum(pos, 0)
    part = (num_partitions * flat) // total
    part = np.clip(part, 0, num_partitions - 1)
    return np.where(contig_idx < 0, num_partitions, part).astype(np.int64)


def region_partition(
    seq_dict: SequenceDictionary,
    contig_idx,
    pos,
    partition_size: int,
) -> np.ndarray:
    """Fixed-size bin id, unique across contigs (bins stack per contig)."""
    contig_idx = np.asarray(contig_idx)
    pos = np.asarray(pos)
    lengths = seq_dict.lengths
    bins_per_contig = -(-lengths // partition_size)
    bin_offsets = np.concatenate([[0], np.cumsum(bins_per_contig)])
    safe_idx = np.clip(contig_idx, 0, max(len(seq_dict) - 1, 0))
    local_bin = np.maximum(pos, 0) // partition_size
    out = bin_offsets[safe_idx] + local_bin
    return np.where(contig_idx < 0, -1, out).astype(np.int64)


def shard_rows_by_position(
    seq_dict: SequenceDictionary, contig_idx, pos, n_shards: int
) -> list[np.ndarray]:
    """Row indices per shard (unmapped rows appended to the last shard),
    the host-side scatter used to feed a genome-sharded mesh."""
    part = position_partition(seq_dict, contig_idx, pos, n_shards)
    part = np.where(part >= n_shards, n_shards - 1, part)
    return [np.flatnonzero(part == s) for s in range(n_shards)]
