"""Genome-coordinate partitioning + streamed execution partitioners.

Genome-coordinate half — semantics of ``rdd/GenomicPartitioners.scala``:

* :func:`position_partition` — GenomicPositionPartitioner.getPartition
  (:63-85): map (contig, pos) to one of N partitions by cumulative genome
  offset, with one extra partition for unmapped reads (partition N).
* :func:`region_partition` — GenomicRegionPartitioner (:102-121):
  fixed-size coordinate bins per contig.

Both return plain arrays so the result can drive either a host-side
scatter into per-device shards or a device all_to_all exchange.

Execution half — how the streamed flagship places per-window device
work (``--partitioner {pool,mesh}`` / ``ADAM_TPU_PARTITIONER``):

* ``pool`` — the PR-3 round-robin :class:`~adam_tpu.parallel.
  device_pool.DevicePool`: window *i*'s kernels land whole on device
  ``i % n``, per-device observe histograms fetch to the host and merge
  in window order at barrier 2.  The fault-tolerance layer
  (eviction/replay, docs/ROBUSTNESS.md) lives here.
* ``mesh`` — :class:`MeshPartitioner`, the SPMD mode: every window's
  [N, L] arrays shard their read-row axis over a 1-D ``batch``
  :class:`jax.sharding.Mesh` spanning ALL the devices, the pass-B
  observe histograms ``psum`` on-device and accumulate into a
  device-resident running table, and only THE merged table (one
  compact [n_rg, 94, 2gl+1, 17] pair per distinct grid width) crosses
  to the host at barrier 2 — instead of one fetched copy per window,
  the measured 74%-of-wall barrier-2 cost (docs/PERF.md).  The solved
  recalibration table is placed once, replicated, and stays
  device-resident through pass C.  On any device failure the mode
  **degrades to the pool path** (bit-identically — the kernels are the
  same math; windows already folded into a suspect accumulator replay
  through the pool/host observe), so PR 4's eviction/replay contract
  holds unchanged.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from functools import cached_property, partial
from typing import Optional, Sequence

import numpy as np

from adam_tpu.models.dictionaries import SequenceDictionary

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class GenomeBins:
    """Fixed-size genome binning (ShuffleRegionJoin.scala:140-193).

    Bin ids stack per contig in dictionary order; ``invert`` recovers the
    bin's region. This is the static genome->shard mapping shared by
    :func:`region_partition` and the shuffle region join.
    """

    bin_size: int
    seq_dict: SequenceDictionary

    @cached_property
    def bins_per_contig(self) -> np.ndarray:
        # every contig owns at least one bin, so contigs with undeclared
        # (0) length still have a home in the bin-id space
        return np.maximum(-(-self.seq_dict.lengths // self.bin_size), 1)

    @cached_property
    def bin_offsets(self) -> np.ndarray:
        return np.concatenate([[0], np.cumsum(self.bins_per_contig)])

    @property
    def num_bins(self) -> int:
        return int(self.bin_offsets[-1])

    def start_bin(self, contig_idx, start):
        ci = np.asarray(contig_idx)
        local = np.asarray(start) // self.bin_size
        return self.bin_offsets[ci] + np.minimum(
            local, self.bins_per_contig[ci] - 1
        )

    def end_bin(self, contig_idx, end):
        """Bin of the last covered base (end is exclusive). Clamped to the
        contig's last bin so intervals overhanging a declared contig
        length never spill into the next contig's bin-id range."""
        ci = np.asarray(contig_idx)
        local = np.maximum(np.asarray(end) - 1, 0) // self.bin_size
        return self.bin_offsets[ci] + np.minimum(
            local, self.bins_per_contig[ci] - 1
        )

    def invert(self, bin_id: int):
        """bin id -> (contig_idx, start, end) region of the bin."""
        contig = int(np.searchsorted(self.bin_offsets, bin_id, "right") - 1)
        local = bin_id - int(self.bin_offsets[contig])
        start = local * self.bin_size
        end = max(
            min(start + self.bin_size, int(self.seq_dict.lengths[contig])),
            start,
        )
        return contig, start, end

    def dedupe_region(self, bin_id: int):
        """Like :meth:`invert`, but the last bin of each contig extends to
        +inf: overhanging intervals clamp into that bin, and their starts
        must still satisfy the at-least-one-side-starts-here join rule."""
        contig, start, end = self.invert(bin_id)
        if bin_id == int(self.bin_offsets[contig + 1]) - 1:
            end = np.iinfo(np.int64).max
        return contig, start, end


def position_partition(
    seq_dict: SequenceDictionary,
    contig_idx,
    pos,
    num_partitions: int,
) -> np.ndarray:
    """Partition id per read; unmapped (contig_idx < 0) -> num_partitions.

    Mapped reads land in int(num_partitions * flattened / total_length),
    the cumulative-offset binning of the reference.
    """
    contig_idx = np.asarray(contig_idx)
    pos = np.asarray(pos)
    offsets = seq_dict.offsets
    total = max(seq_dict.total_length, 1)
    safe_idx = np.clip(contig_idx, 0, max(len(seq_dict) - 1, 0))
    flat = offsets[safe_idx] + np.maximum(pos, 0)
    part = (num_partitions * flat) // total
    part = np.clip(part, 0, num_partitions - 1)
    return np.where(contig_idx < 0, num_partitions, part).astype(np.int64)


def region_partition(
    seq_dict: SequenceDictionary,
    contig_idx,
    pos,
    partition_size: int,
) -> np.ndarray:
    """Fixed-size bin id, unique across contigs (bins stack per contig)."""
    contig_idx = np.asarray(contig_idx)
    pos = np.asarray(pos)
    bins = GenomeBins(partition_size, seq_dict)
    safe_idx = np.clip(contig_idx, 0, max(len(seq_dict) - 1, 0))
    out = bins.start_bin(safe_idx, np.maximum(pos, 0))
    return np.where(contig_idx < 0, -1, out).astype(np.int64)


def shard_rows_by_position(
    seq_dict: SequenceDictionary, contig_idx, pos, n_shards: int
) -> list[np.ndarray]:
    """Row indices per shard (unmapped rows appended to the last shard),
    the host-side scatter used to feed a genome-sharded mesh."""
    part = position_partition(seq_dict, contig_idx, pos, n_shards)
    part = np.where(part >= n_shards, n_shards - 1, part)
    return [np.flatnonzero(part == s) for s in range(n_shards)]


def partition_by_contig(contig_idx, n_partitions: int | None = None):
    """Partition rows by contig (rdd/ReferencePartitioner.scala): every
    row of a contig lands on the same partition.

    -> i32[N] partition ids in [0, n_partitions); unplaced rows (-1
    contig) go to the last partition.  Defaults to one partition per
    contig present.
    """
    contig_idx = np.asarray(contig_idx)
    uniq = np.unique(contig_idx[contig_idx >= 0])
    if n_partitions is None:
        n_partitions = max(1, len(uniq)) + 1
    # rank-encode before the modulo: raw ids can be sparse/high, which
    # would collide distinct contigs while leaving partitions empty
    rank = np.searchsorted(uniq, np.clip(contig_idx, 0, None))
    part = np.where(
        contig_idx >= 0,
        rank % max(1, n_partitions - 1),
        n_partitions - 1,
    )
    return part.astype(np.int32)


def shard_rows_by_contig(contig_idx, n_shards: int):
    """Row-index lists per shard under contig partitioning."""
    part = partition_by_contig(contig_idx, n_shards)
    return [np.flatnonzero(part == s) for s in range(n_shards)]


# ==========================================================================
# Streamed execution partitioners (--partitioner {pool,mesh})
# ==========================================================================
EXECUTION_MODES = ("pool", "mesh")


def resolve_execution_mode(override: Optional[str] = None) -> str:
    """Resolve the streamed pipeline's execution partitioner.

    Order: explicit ``override`` (the ``--partitioner`` flag — invalid
    values are a hard error), then ``ADAM_TPU_PARTITIONER`` (invalid
    values warn and degrade to ``pool``, the tuning-var contract), then
    ``pool`` — the fault-tolerance-hardened default; ``mesh`` is the
    opt-in SPMD mode.
    """
    v = (override or "").strip().lower()
    if v:
        if v not in EXECUTION_MODES:
            raise ValueError(
                f"--partitioner={v!r}: expected one of {EXECUTION_MODES}"
            )
        return v
    v = os.environ.get("ADAM_TPU_PARTITIONER", "").strip().lower()
    if v and v not in EXECUTION_MODES:
        log.warning(
            "ADAM_TPU_PARTITIONER=%r is not one of %s; using 'pool'",
            v, EXECUTION_MODES,
        )
        v = ""
    return v or "pool"


def healthy_subset(devices: Sequence, board=None) -> list:
    """The device subset the mesh should span, per the health
    scoreboard (utils/health.py): probation/evicted chips are excluded
    at CONSTRUCTION time — a collective spans every mesh device, so
    one quietly-bad chip would poison every window, and the mesh has
    no per-chip eviction to fall back on (docs/ROBUSTNESS.md
    "Mesh-mode degradation").  Falls back to the full set when the
    board would empty it (availability beats health) and never shrinks
    below one device."""
    if board is None:
        from adam_tpu.utils.health import BOARD as board
    devs = list(devices)
    ok = [d for d in devs if not board.blocked(d)]
    if ok and len(ok) < len(devs):
        log.warning(
            "mesh construction excluded %d health-blocked device(s); "
            "spanning the %d healthy one(s)", len(devs) - len(ok),
            len(ok),
        )
    return ok if ok else devs


# ---- mesh jit wrappers (module level: ONE executable cache per shape,
# shared by the prewarm and every window's dispatch) -----------------------
def _mesh_specs(n_args: int):
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS

    return tuple(P(BATCH_AXIS) for _ in range(n_args))


def _mesh_observe_jit_builder():
    import jax
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS, shard_map

    @partial(jax.jit, static_argnames=("n_rg", "lmax", "mesh"))
    def run(bases, quals, lengths, flags, rg, res_ok, is_mm, rd_ok,
            n_rg, lmax, mesh):
        from adam_tpu.pipelines.bqsr import observe_kernel

        @partial(
            shard_map, mesh=mesh, in_specs=_mesh_specs(8),
            out_specs=(P(), P()), check_vma=False,
        )
        def body(b, q, le, fl, r, ro, mm, ok):
            # the exact single-chip kernel body per shard; the i64
            # cross-shard psum is the on-device analog of the pool's
            # host-side window-order merge — integer adds, so the sums
            # are bitwise identical in any order
            total, mism = observe_kernel.__wrapped__(
                b, q, le, fl, r, ro, mm, ok, n_rg, lmax
            )
            return (
                jax.lax.psum(total, BATCH_AXIS),
                jax.lax.psum(mism, BATCH_AXIS),
            )

        return body(bases, quals, lengths, flags, rg, res_ok, is_mm, rd_ok)

    return run


def _mesh_apply_jit_builder(donate: bool):
    import jax
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS, shard_map

    def run(bases, quals, lengths, flags, rg, has_qual, valid, table,
            lmax, mesh):
        from adam_tpu.pipelines.bqsr import apply_table_kernel

        @partial(
            shard_map, mesh=mesh, in_specs=_mesh_specs(7) + (P(),),
            out_specs=P(BATCH_AXIS), check_vma=False,
        )
        def body(b, q, le, fl, r, hq, v, tbl):
            return apply_table_kernel.__wrapped__(
                b, q, le, fl, r, hq, v, tbl, lmax
            )

        return body(bases, quals, lengths, flags, rg, has_qual, valid, table)

    kw = {"static_argnames": ("lmax", "mesh")}
    if donate:
        # the new quals alias the old quals' shape/dtype: donating the
        # input buffer keeps pass C's HBM footprint at one [g, gl] u8
        # per in-flight window instead of two
        kw["donate_argnums"] = (1,)
    return partial(jax.jit, **kw)(run)


def _mesh_apply_pack_jit_builder(donate: bool):
    import jax
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS, shard_map

    def run(bases, quals, lengths, flags, rg, has_qual, valid, table,
            lmax, mesh):
        from adam_tpu.pipelines.bqsr import apply_pack_body

        @partial(
            shard_map, mesh=mesh, in_specs=_mesh_specs(7) + (P(),),
            out_specs=P(BATCH_AXIS), check_vma=False,
        )
        def body(b, q, le, fl, r, hq, v, tbl):
            # each shard fuses the gather with the column pack over its
            # own row block (size static at trace: local rows x lanes);
            # the global flat output is shard payloads in shard order —
            # which IS row order, so the host-side concat of the
            # per-shard payload slices is the single-device pack
            return apply_pack_body(
                b, q, le, fl, r, hq, v, tbl, lmax,
                b.shape[0] * b.shape[1],
            )

        return body(bases, quals, lengths, flags, rg, has_qual, valid, table)

    kw = {"static_argnames": ("lmax", "mesh")}
    if donate:
        # the flat packed output matches the donated quals buffer's
        # byte size exactly ([g*gl] u8 vs [g, gl] u8)
        kw["donate_argnums"] = (1,)
    return partial(jax.jit, **kw)(run)


def _mesh_observe_packed_jit_builder(donate: bool):
    import jax
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS, shard_map

    def run(bases, quals, lengths, flags, rg, res_pk, mm_pk, rd_ok,
            n_rg, lmax, mesh):
        from adam_tpu.pipelines.bqsr import observe_packed_body

        @partial(
            shard_map, mesh=mesh, in_specs=_mesh_specs(8),
            out_specs=(P(), P()), check_vma=False,
        )
        def body(b, q, le, fl, r, rp, mp, ok):
            # each shard unpacks its own bit-packed mask rows then runs
            # the exact observe scatter-add; i64 psum keeps the merge
            # bitwise order-free (the plain mesh observe's contract)
            total, mism = observe_packed_body(
                b, q, le, fl, r, rp, mp, ok, n_rg, lmax
            )
            return (
                jax.lax.psum(total, BATCH_AXIS),
                jax.lax.psum(mism, BATCH_AXIS),
            )

        return body(bases, quals, lengths, flags, rg, res_pk, mm_pk, rd_ok)

    kw = {"static_argnames": ("n_rg", "lmax", "mesh")}
    if donate:
        # the bit-packed masks are per-pass temporaries: dead after the
        # unpack, so donating them trims the observe HBM footprint
        kw["donate_argnums"] = (5, 6)
    return partial(jax.jit, **kw)(run)


def _mesh_apply_pack2_jit_builder(donate: bool):
    import jax
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS, shard_map

    def run(bases, quals, lengths, flags, rg, has_qual, valid, table,
            lmax, mesh):
        from adam_tpu.pipelines.bqsr import apply_pack2_body

        @partial(
            shard_map, mesh=mesh, in_specs=_mesh_specs(7) + (P(),),
            out_specs=(P(BATCH_AXIS), P(BATCH_AXIS)), check_vma=False,
        )
        def body(b, q, le, fl, r, hq, v, tbl):
            # the bases half of the packed tail: each shard fuses the
            # gather with BOTH column packs over its own row block; the
            # two global flat outputs are shard payloads in shard order
            # (== row order), so the host-side per-shard slices of each
            # reproduce the single-device packs
            return apply_pack2_body(
                b, q, le, fl, r, hq, v, tbl, lmax,
                b.shape[0] * b.shape[1],
            )

        return body(bases, quals, lengths, flags, rg, has_qual, valid, table)

    kw = {"static_argnames": ("lmax", "mesh")}
    if donate:
        # the resident quals buffer becomes the packed qual column and
        # the resident bases buffer the packed base column (byte sizes
        # match exactly: [g, gl] u8 vs [g*gl] u8 each)
        kw["donate_argnums"] = (0, 1)
    return partial(jax.jit, **kw)(run)


def _mesh_markdup_jit_builder():
    import jax
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS, shard_map

    @partial(jax.jit, static_argnames=("mesh",))
    def run(start, end, flags, ops, lens, n_ops, quals, lengths, mesh):
        from adam_tpu.pipelines.markdup import markdup_columns_local

        @partial(
            shard_map, mesh=mesh, in_specs=_mesh_specs(8),
            out_specs=(P(BATCH_AXIS), P(BATCH_AXIS)),
            check_vma=False,
        )
        def body(s, e, f, o, ln, n, q, le):
            return markdup_columns_local(s, e, f, o, ln, n, q, le)

        return body(start, end, flags, ops, lens, n_ops, quals, lengths)

    return run


def _mesh_fused_bc_jit_builder(donate: bool):
    import jax
    from jax.sharding import PartitionSpec as P

    from adam_tpu.parallel.mesh import BATCH_AXIS, shard_map

    def run(bases, quals, lengths, flags, rg, res_pk, mm_pk, rd_ok,
            has_qual, valid, table, n_rg, lmax, mesh):
        from adam_tpu.pipelines.bqsr import (
            apply_pack2_body, observe_packed_body,
        )

        @partial(
            shard_map, mesh=mesh, in_specs=_mesh_specs(10) + (P(),),
            out_specs=(P(), P(), P(BATCH_AXIS), P(BATCH_AXIS)),
            check_vma=False,
        )
        def body(b, q, le, fl, r, rp, mp, ok, hq, v, tbl):
            # the megakernel tier's mesh twin: each shard runs the
            # bit-packed-mask observe AND the fused apply+pack over its
            # own row block in ONE collective; histograms psum i64
            # (order-free), the two flat packed outputs stay
            # row-sharded in shard order (== row order)
            total, mism = observe_packed_body(
                b, q, le, fl, r, rp, mp, ok, n_rg, lmax
            )
            pq, pb = apply_pack2_body(
                b, q, le, fl, r, hq, v, tbl, lmax,
                b.shape[0] * b.shape[1],
            )
            return (
                jax.lax.psum(total, BATCH_AXIS),
                jax.lax.psum(mism, BATCH_AXIS),
                pq, pb,
            )

        return body(bases, quals, lengths, flags, rg, res_pk, mm_pk,
                    rd_ok, has_qual, valid, table)

    kw = {"static_argnames": ("n_rg", "lmax", "mesh")}
    if donate:
        # same aliases as the separate passes: resident bases/quals
        # become the packed columns, the bit-packed masks are dead
        # after the in-kernel unpack
        kw["donate_argnums"] = (0, 1, 5, 6)
    return partial(jax.jit, **kw)(run)


_MESH_JITS: dict = {}
_MESH_JITS_LOCK = threading.Lock()


def _mesh_jit(kind: str, donate: bool = False):
    """Lazily-built module-level mesh jits (one executable cache each,
    shared by prewarm and dispatch — the device_pool get_columns_jit
    discipline).  Keyed by the kernel backend alongside (kind, donate):
    the shard bodies branch Pallas/XLA at trace time
    (``ops/kernel_backend``), so a backend flip must reach a fresh
    jit."""
    from adam_tpu.ops.kernel_backend import kernel_backend

    key = (kind, donate, kernel_backend())
    fn = _MESH_JITS.get(key)
    if fn is None:
        with _MESH_JITS_LOCK:
            fn = _MESH_JITS.get(key)
            if fn is None:
                builder = {
                    "observe": _mesh_observe_jit_builder,
                    "markdup": _mesh_markdup_jit_builder,
                }.get(kind)
                if builder is not None:
                    fn = builder()
                elif kind == "observe_packed":
                    fn = _mesh_observe_packed_jit_builder(donate)
                elif kind == "apply_pack":
                    fn = _mesh_apply_pack_jit_builder(donate)
                elif kind == "apply_pack2":
                    fn = _mesh_apply_pack2_jit_builder(donate)
                elif kind == "fused_bc":
                    fn = _mesh_fused_bc_jit_builder(donate)
                else:
                    fn = _mesh_apply_jit_builder(donate)
                _MESH_JITS[key] = fn
    return fn


class MeshPartitioner:
    """SPMD execution mode for the streamed pipeline (module docstring).

    Holds the 1-D ``batch`` mesh over the run's device set, the row/
    replicated shardings, and the device-resident pass-B observe
    accumulator — one running (total, mism) i64 pair per distinct grid
    width, so barrier 2 fetches table-scale bytes however many windows
    streamed through.  All placement goes through :meth:`put_rows` /
    :meth:`put_replicated`, which feed the h2d transfer ledger with the
    bytes split per member device (sharded) or counted once per device
    (replicated) — "mesh dispatch sites attributed per device" in
    ``adam-tpu analyze``.  Dispatch *spans* carry ``device="mesh"``:
    collective work occupies every device at once, so it gets its own
    track instead of a fabricated per-chip split.
    """

    def __init__(self, devices: Sequence):
        from adam_tpu.parallel.mesh import batch_mesh

        self.devices = list(devices)
        if not self.devices:
            raise ValueError("MeshPartitioner needs at least one device")
        self.mesh = batch_mesh(self.devices)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from adam_tpu.parallel.mesh import batch_row_sharding

        self._rows = batch_row_sharding(self.mesh)
        self._rep = NamedSharding(self.mesh, P())
        # gl -> [total, mism] replicated device i64 arrays (no lock:
        # observe dispatch and the barrier fetch both run on the
        # streamed pipeline's single driver thread)
        self._acc: dict = {}
        self._dev_ids = [
            getattr(d, "id", i) for i, d in enumerate(self.devices)
        ]

    @property
    def n(self) -> int:
        return len(self.devices)

    def ledger_key(self) -> str:
        """The compile-ledger 'device' key for mesh executables: one
        per mesh width — a 2-device and an 8-device mesh compile
        different programs."""
        return f"mesh:{self.n}"

    def rows_for(self, g: int) -> int:
        """Row count the mesh needs: ``g`` padded up to a multiple of
        the device count (pow2 grids over pow2 meshes are unchanged)."""
        return -(-int(g) // self.n) * self.n

    # ---- placement (the h2d side of the transfer ledger) --------------
    def _put(self, x, sharding, bytes_per_device: int):
        import jax

        from adam_tpu.utils import telemetry as tele

        if not tele.TRACE.recording:
            return jax.device_put(x, sharding)
        t0 = time.monotonic()
        out = jax.device_put(x, sharding)
        dur = time.monotonic() - t0
        for dev_id in self._dev_ids:
            tele.TRACE.record_transfer(
                "h2d", bytes_per_device, dur / self.n, device=dev_id,
            )
        return out

    def put_rows(self, x):
        """Place one row-sharded array (leading axis must divide by
        ``n`` — pad with :meth:`rows_for` first)."""
        nbytes = getattr(x, "nbytes", 0)
        return self._put(x, self._rows, nbytes // self.n)

    def put_replicated(self, x):
        """Place one fully-replicated array (each device holds a copy,
        and the ledger charges each its copy)."""
        return self._put(x, self._rep, getattr(x, "nbytes", 0))

    # ---- pass B: observe + on-device accumulate ------------------------
    def observe_window(self, arrays: tuple, n_rg: int, gl: int):
        """Dispatch one window's observe scatter-add across the mesh ->
        lazy replicated (total, mism) i64 device arrays.

        ``arrays``: the 8 host arrays of the observe kernel signature,
        already padded to (:meth:`rows_for`(g), gl) rows/lanes.
        """
        placed = tuple(self.put_rows(a) for a in arrays)
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (bqsr._observe_device mesh branch and the mesh prewarm) wraps this dispatch in its own track keyed mesh.observe
        return _mesh_jit("observe")(*placed, n_rg=n_rg, lmax=gl,
                                    mesh=self.mesh)

    def accumulate(self, total, mism, gl: int) -> None:
        """Fold one window's lazy histograms into the device-resident
        running table for its grid width (i64 adds: bitwise identical
        to the pool path's host-side window-order merge)."""
        import jax.numpy as jnp

        acc = self._acc.get(int(gl))
        if acc is None:
            self._acc[int(gl)] = [total, mism]
        else:
            acc[0] = jnp.add(acc[0], total)
            acc[1] = jnp.add(acc[1], mism)

    def has_accumulated(self) -> bool:
        return bool(self._acc)

    def fetch_accumulated(self, tracer=None) -> list:
        """Barrier 2: bring the merged tables home — ONE compact
        (total, mism, gl) per distinct grid width, each through the
        chunked transfer helper (d2h ledger + ``device.fetch.observe``
        span, ``device="mesh"`` attributed).  Clears the accumulator."""
        from adam_tpu.utils import telemetry as tele
        from adam_tpu.utils.transfer import device_fetch

        tr = tracer if tracer is not None else tele.TRACE
        out = []
        try:
            for gl in sorted(self._acc):
                total, mism = self._acc[gl]
                with tr.span(tele.SPAN_OBS_FETCH, device="mesh"):
                    out.append(
                        (device_fetch(total), device_fetch(mism), gl)
                    )
        finally:
            self._acc.clear()
        return out

    def reset_accumulator(self) -> None:
        self._acc.clear()

    # ---- pass A: markdup columns ---------------------------------------
    def markdup_window(self, arrays: tuple):
        """Row-sharded [N, L] markdup reductions -> lazy (five, score)
        row-sharded device arrays (padded rows included; caller
        slices)."""
        placed = tuple(self.put_rows(a) for a in arrays)
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (markdup_columns_dispatch mesh branch and the mesh prewarm) wraps this dispatch in its own track keyed mesh.markdup
        return _mesh_jit("markdup")(*placed, mesh=self.mesh)

    def markdup_window_resident(self, rw, fresh: tuple):
        """Resident-window markdup dispatch: quals/lengths/flags come
        from ``rw``'s batch-sharded placement (one ingest h2d, reused
        by every pass) and only the markdup-specific ``fresh``
        (start, end, cigar ops/lens/n) host arrays ship."""
        start, end, ops, lens, n_ops = (
            self.put_rows(a) for a in fresh
        )
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (markdup_columns_dispatch mesh branch) wraps this dispatch in its own track keyed mesh.markdup
        return _mesh_jit("markdup")(
            start, end, rw.get("flags"), ops, lens, n_ops,
            rw.get("quals"), rw.get("lengths"), mesh=self.mesh,
        )

    # ---- resident windows (ingest-once H2D) ----------------------------
    def observe_packed_window(self, placed: tuple, n_rg: int, gl: int):
        """Dispatch the bit-packed-mask observe collective over
        already-placed arrays (the resident dispatch and the prewarm
        share this seam) -> lazy replicated (total, mism)."""
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (bqsr._observe_impl mesh resident branch and the mesh prewarm) wraps this dispatch in its own track keyed mesh.observe_packed
        return _mesh_jit(
            "observe_packed", donate=self.apply_supports_donation()
        )(*placed, n_rg=n_rg, lmax=gl, mesh=self.mesh)

    def observe_window_resident(self, rw, res_pk, mm_pk, read_ok,
                                n_rg: int, gl: int):
        """Resident-window observe: bases/quals/lengths/flags/rg come
        from ``rw``; only the bit-packed per-pass masks and the read
        filter ship (8x + 1x small — the observe h2d ≈ 0 contract)."""
        placed = rw.args() + (
            self.put_rows(res_pk), self.put_rows(mm_pk),
            self.put_rows(read_ok),
        )
        return self.observe_packed_window(placed, n_rg, gl)

    # ---- pass C: apply with the device-resident table ------------------
    def apply_supports_donation(self) -> bool:
        # buffer donation is a no-op (with a warning) on some CPU
        # runtimes: keep the virtual-device test legs quiet and donate
        # where it pays — on real accelerators
        return all(
            getattr(d, "platform", "cpu") != "cpu" for d in self.devices
        )

    def apply_window(self, arrays: tuple, table_dev, gl: int):
        """Dispatch one window's recalibration gather across the mesh
        -> lazy row-sharded u8[g, gl] quals.  ``table_dev`` must come
        from :meth:`put_replicated` — placed once, device-resident for
        every window of pass C (the B→C no-round-trip contract)."""
        placed = tuple(self.put_rows(a) for a in arrays)
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (bqsr apply mesh branch and the mesh prewarm) wraps this dispatch in its own track keyed mesh.apply
        return _mesh_jit("apply", donate=self.apply_supports_donation())(
            *placed, table_dev, lmax=gl, mesh=self.mesh
        )

    def apply_pack_window(self, arrays: tuple, table_dev, gl: int):
        """Fused apply + column pack across the mesh -> lazy flat
        u8[g*gl], row-sharded: shard k's segment starts with exactly
        its rows' packed SANGER qual bytes (``ops/colpack``).  Pair
        with :meth:`packed_payload_slices` to fetch only the real
        column payload — the pass-C d2h shrink."""
        placed = tuple(self.put_rows(a) for a in arrays)
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (bqsr apply_pack mesh branch and the mesh prewarm) wraps this dispatch in its own track keyed mesh.apply_pack
        return _mesh_jit(
            "apply_pack", donate=self.apply_supports_donation()
        )(*placed, table_dev, lmax=gl, mesh=self.mesh)

    def apply_window_resident(self, rw, has_qual, valid, table_dev,
                              gl: int):
        """Resident-window plain apply: the five resident arrays plus
        the post-split ``has_qual``/``valid`` bools (the only per-pass
        h2d) -> lazy row-sharded u8[g, gl] quals."""
        placed = rw.args() + (
            self.put_rows(has_qual), self.put_rows(valid),
        )
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (bqsr apply mesh resident branch) wraps this dispatch in its own track keyed mesh.apply
        return _mesh_jit("apply", donate=self.apply_supports_donation())(
            *placed, table_dev, lmax=gl, mesh=self.mesh
        )

    def apply_pack2_placed(self, placed: tuple, table_dev, gl: int):
        """Dispatch the fused apply + bases+quals pack collective over
        already-placed arrays (resident dispatch and prewarm share this
        seam) -> lazy ``(packed_quals, packed_bases)`` flat u8[g*gl]
        row-sharded pairs."""
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (bqsr apply_pack2 mesh branch and the mesh prewarm) wraps this dispatch in its own track keyed mesh.apply_pack2
        return _mesh_jit(
            "apply_pack2", donate=self.apply_supports_donation()
        )(*placed, table_dev, lmax=gl, mesh=self.mesh)

    def apply_pack2_window(self, rw, has_qual, valid, table_dev,
                           gl: int):
        """Resident-window fused apply + BOTH column packs (the bases
        half of the packed tail): ships only ``has_qual``/``valid``;
        the packed qual AND base payloads come home via
        :meth:`packed_payload_slices` on each output."""
        placed = rw.args() + (
            self.put_rows(has_qual), self.put_rows(valid),
        )
        return self.apply_pack2_placed(placed, table_dev, gl)

    def fused_bc_placed(self, placed: tuple, table_dev, n_rg: int,
                        gl: int):
        """Dispatch the fused B→C megakernel collective over
        already-placed arrays (resident dispatch and prewarm share this
        seam) -> lazy ``(total, mism, packed_quals, packed_bases)`` —
        replicated i64 histograms plus the two row-sharded flat
        payloads."""
        # adam-tpu: noqa[dispatch-ledger] reason=every caller (bqsr.fused_bc_dispatch mesh branch and the mesh prewarm) wraps this dispatch in its own track keyed mesh.fused_bc
        return _mesh_jit(
            "fused_bc", donate=self.apply_supports_donation()
        )(*placed, table_dev, n_rg=n_rg, lmax=gl, mesh=self.mesh)

    def fused_bc_window(self, rw, res_pk, mm_pk, read_ok, has_qual,
                        valid, table_dev, n_rg: int, gl: int):
        """Resident-window fused B→C: bases/quals/lengths/flags/rg come
        from ``rw``; the bit-packed masks, read filter and post-split
        bools are the only per-window h2d, and ONE collective yields
        the window's histograms AND both packed columns."""
        if isinstance(table_dev, np.ndarray):
            table_dev = self.put_replicated(
                np.ascontiguousarray(table_dev, np.uint8)
            )
        placed = rw.args() + (
            self.put_rows(res_pk), self.put_rows(mm_pk),
            self.put_rows(read_ok), self.put_rows(has_qual),
            self.put_rows(valid),
        )
        return self.fused_bc_placed(placed, table_dev, n_rg, gl)

    def packed_payload_slices(self, packed, lens_gm: np.ndarray,
                              gl: int) -> list:
        """Lazy ``(device slice, true bytes)`` pairs covering each
        shard's real packed payload (``lens_gm``: per-row packed byte
        counts padded to the mesh row grid — host-resident, so the
        split needs no device round trip).  Slice lengths are
        bucket-quantized (``colpack.fetch_grid``) so a run compiles a
        handful of slice programs, not one per window; the fetch side
        trims each bucket to its true size.  Empty shards contribute
        no slice; concatenating the trimmed payloads in order
        reproduces the single-device pack."""
        from adam_tpu.ops.colpack import fetch_grid

        rows_local = len(lens_gm) // self.n
        seg = rows_local * gl
        out = []
        for k in range(self.n):
            t_k = int(lens_gm[k * rows_local:(k + 1) * rows_local].sum())
            if t_k:
                cut = min(seg, fetch_grid(t_k))
                out.append((packed[k * seg: k * seg + cut], t_k))
        return out

    # ---- compile prewarm ----------------------------------------------
    def prewarm(self, entries: Sequence[tuple], tracer=None) -> int:
        """Compile the mesh kernel set before the first window's
        dispatch — the mesh analog of ``DevicePool.prewarm``, sharing
        its process-wide dedupe cache keyed by (entry key,
        :meth:`ledger_key`) so warm shapes are never re-compiled.
        ``entries``: ``(key, fn)`` pairs where ``fn(None)`` invokes the
        mesh jit to completion on dummy data."""
        from adam_tpu.parallel import device_pool as dp
        from adam_tpu.utils import compile_ledger
        from adam_tpu.utils import telemetry as tele

        tr = tracer if tracer is not None else tele.TRACE
        todo = []
        with dp._PREWARM_LOCK:
            # backend in the dedupe key, like the pool prewarm and the
            # compile ledger: an XLA-warmed shape says nothing about
            # the pallas executable of the same shape
            backend = compile_ledger.active_backend()
            for key, fn in entries:
                cache_key = (key, self.ledger_key(), backend)
                if cache_key not in dp._PREWARMED:
                    dp._PREWARMED.add(cache_key)
                    todo.append((key, fn, cache_key))
                else:
                    # already warm: re-seed the ledger claim a faulted
                    # run's raising dispatch may have handed back (the
                    # pool prewarm's dedupe-skip does the same)
                    compile_ledger.claim(key, self.ledger_key())
        done = 0
        for key, fn, cache_key in todo:
            try:
                with tr.span(
                    tele.SPAN_POOL_PREWARM_COMPILE, device="mesh",
                    kernel=str(key[0]),
                ), compile_ledger.prewarm_scope(), \
                        tele.pass_scope("prewarm"), \
                        compile_ledger.track(key, self.ledger_key()):
                    fn(None)
            except Exception:
                with dp._PREWARM_LOCK:
                    dp._PREWARMED.discard(cache_key)
                log.warning(
                    "mesh prewarm of %s failed; the shape will compile "
                    "at first dispatch instead", key, exc_info=True,
                )
                continue
            tr.count(tele.C_POOL_PREWARM_COMPILES)
            done += 1
        return done


def mesh_resident_window(b, window: int, part: MeshPartitioner):
    """Place one window's resident payload as batch-sharded mesh arrays
    (the mesh analog of ``device_pool.make_resident_window``): one
    ``NamedSharding`` placement at ingest, reused by every shard_map
    pass.  Rows pad to the mesh width; callers wrap this in
    ``telemetry.pass_scope("ingest")`` for the h2d ledger."""
    from adam_tpu.formats import schema
    from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np
    from adam_tpu.parallel.device_pool import ResidentWindow

    gm = part.rows_for(grid_rows(b.n_rows))
    gl = grid_cols(b.lmax)
    host = {
        "bases": pad_rows_np(b.bases, gm, schema.BASE_PAD, cols=gl),
        "quals": pad_rows_np(b.quals, gm, schema.QUAL_PAD, cols=gl),
        "lengths": pad_rows_np(b.lengths, gm, 0),
        "flags": pad_rows_np(b.flags, gm, schema.FLAG_UNMAPPED),
        "read_group_idx": pad_rows_np(b.read_group_idx, gm, -1),
    }
    nbytes = sum(int(a.nbytes) for a in host.values())
    arrays = {k: part.put_rows(a) for k, a in host.items()}
    return ResidentWindow(window, "mesh", arrays, gm, gl, nbytes)


def mesh_observe_packed_prewarm_entry(b, n_rg: int,
                                      part: MeshPartitioner) -> tuple:
    """Prewarm entry for the mesh bit-packed-mask observe jit (the
    resident-window pass-B dispatch variant) at one window's grid
    shape."""
    import jax

    from adam_tpu.formats.batch import grid_cols, grid_rows
    from adam_tpu.parallel.device_pool import observe_dummy_args

    g = part.rows_for(grid_rows(b.n_rows))
    gl = grid_cols(b.lmax)

    def warm(_dev, g=g, gl=gl):
        base = observe_dummy_args(b, g, gl)
        npk = gl // 8 + (1 if gl % 8 else 0)
        placed = tuple(
            part.put_rows(a) for a in base[:5] + (
                np.zeros((g, npk), np.uint8),
                np.zeros((g, npk), np.uint8),
                base[7],
            )
        )
        jax.block_until_ready(
            part.observe_packed_window(placed, n_rg, gl)
        )

    return (("mesh.observe_packed", g, gl, n_rg), warm)


def mesh_observe_prewarm_entry(b, n_rg: int, part: MeshPartitioner) -> tuple:
    """Prewarm entry for the mesh observe jit at one window's grid
    shape — the same kernel dummy args as the pool entry
    (``device_pool.observe_dummy_args``, the single source of truth per
    kernel signature), only the row count pads to the mesh width."""
    import jax

    from adam_tpu.formats.batch import grid_cols, grid_rows
    from adam_tpu.parallel.device_pool import observe_dummy_args

    g = part.rows_for(grid_rows(b.n_rows))
    gl = grid_cols(b.lmax)

    def warm(_dev, g=g, gl=gl):
        jax.block_until_ready(
            part.observe_window(observe_dummy_args(b, g, gl), n_rg, gl)
        )

    return (("mesh.observe", g, gl, n_rg), warm)


def mesh_markdup_prewarm_entry(b, part: MeshPartitioner) -> tuple:
    """Prewarm entry for the mesh markdup-columns jit at one window's
    grid shape (``device_pool.markdup_dummy_args``)."""
    import jax

    from adam_tpu.formats.batch import (
        grid_cigar_cols, grid_cols, grid_rows,
    )
    from adam_tpu.parallel.device_pool import markdup_dummy_args

    g = part.rows_for(grid_rows(b.n_rows))
    gl = grid_cols(b.lmax)
    gc = grid_cigar_cols(
        b.cigar_ops.shape[1] if b.cigar_ops.ndim == 2 else 1
    )

    def warm(_dev, g=g, gl=gl, gc=gc):
        jax.block_until_ready(
            part.markdup_window(markdup_dummy_args(b, g, gl, gc))
        )

    return (("mesh.markdup", g, gc, gl), warm)


def mesh_apply_prewarm_entry(b, n_rg: int, n_cyc: int,
                             part: MeshPartitioner,
                             pack: bool = False,
                             pack2: bool = False) -> tuple:
    """Prewarm entry for the mesh apply jit keyed by the SOLVED table's
    real cycle width (the pass-C re-warm, device_pool.apply_prewarm_entry
    semantics; ``device_pool.apply_dummy_args``).  ``pack=True`` warms
    the fused apply+pack variant, ``pack2=True`` the resident-window
    bases+quals pack (each its own executable — the key carries the
    kernel name, so all can coexist warm)."""
    import jax

    from adam_tpu.formats.batch import grid_cols, grid_rows
    from adam_tpu.parallel.device_pool import apply_dummy_args
    from adam_tpu.pipelines.bqsr import N_DINUC, N_QUAL

    g = part.rows_for(grid_rows(b.n_rows))
    gl = grid_cols(b.lmax)

    def warm(_dev, g=g, gl=gl):
        tbl = part.put_replicated(
            np.zeros((n_rg, N_QUAL, n_cyc, N_DINUC), np.uint8)
        )
        if pack2:
            placed = tuple(
                part.put_rows(a) for a in apply_dummy_args(b, g, gl)
            )
            jax.block_until_ready(
                part.apply_pack2_placed(placed, tbl, gl)
            )
            return
        runner = part.apply_pack_window if pack else part.apply_window
        jax.block_until_ready(
            runner(apply_dummy_args(b, g, gl), tbl, gl)
        )

    # literal key tuples (not one with a computed kernel name): the
    # dispatch-ledger rule's prewarm cross-check parses these literals
    if pack2:
        return (("mesh.apply_pack2", g, gl, n_rg, n_cyc), warm)
    if pack:
        return (("mesh.apply_pack", g, gl, n_rg, n_cyc), warm)
    return (("mesh.apply", g, gl, n_rg, n_cyc), warm)


def mesh_fused_bc_prewarm_entry(b, n_rg: int, n_cyc: int,
                                part: MeshPartitioner) -> tuple:
    """Prewarm entry for the mesh fused B→C megakernel keyed by the
    known table's real cycle width (``device_pool.fused_bc_dummy_args``
    — the single dummy-construction idiom per kernel signature)."""
    import jax

    from adam_tpu.formats.batch import grid_cols, grid_rows
    from adam_tpu.parallel.device_pool import fused_bc_dummy_args
    from adam_tpu.pipelines.bqsr import N_DINUC, N_QUAL

    g = part.rows_for(grid_rows(b.n_rows))
    gl = grid_cols(b.lmax)

    def warm(_dev, g=g, gl=gl):
        tbl = part.put_replicated(
            np.zeros((n_rg, N_QUAL, n_cyc, N_DINUC), np.uint8)
        )
        placed = tuple(
            part.put_rows(a) for a in fused_bc_dummy_args(b, g, gl)
        )
        jax.block_until_ready(
            part.fused_bc_placed(placed, tbl, n_rg, gl)
        )

    return (("mesh.fused_bc", g, gl, n_rg, n_cyc), warm)
