"""Raw columnar shard spill (Arrow IPC).

The out-of-core shard store for the composed sharded transform
(parallel/sharded.py).  Unlike the Parquet output format — which is the
*interchange* layout (AlignmentRecord field names, ASCII sequences,
CIGAR strings; io/parquet.py) — this spill keeps the framework's own
struct-of-arrays batch columns verbatim: base/qual code matrices ride as
per-row binary values, cigar columns as packed bytes, sidecar strings as
Arrow strings.  Writing is memcpy-speed (no ASCII encode), reading is
memcpy + pad (no tokenize), and the store is still Arrow IPC: appendable
record batches, memory-mappable, readable cross-process (the property
the 2-process harness leans on).

The reference's analog is Spark's shuffle-file format — an internal
serialized block layout, not the public Parquet schema
(SURVEY §2.6; core/.../ShuffleBlockResolver in Spark itself).
"""

from __future__ import annotations

import numpy as np

from adam_tpu.formats.batch import ReadBatch, ReadSidecar


def _binary_rows(mat: np.ndarray) -> "pa.Array":
    """[N, W] u8 matrix -> large_binary array of N W-byte values (one
    memcpy; 64-bit offsets so long-read batches cannot wrap the offset
    arithmetic)."""
    import pyarrow as pa

    mat = np.ascontiguousarray(mat, np.uint8)
    n, w = mat.shape
    offsets = np.arange(n + 1, dtype=np.int64) * w
    return pa.LargeBinaryArray.from_buffers(
        pa.large_binary(), n,
        [None, pa.py_buffer(offsets), pa.py_buffer(mat)],
    )


def _i32_matrix_rows(mat: np.ndarray) -> "pa.Array":
    """[N, C] i32 matrix -> binary array of N 4C-byte values."""
    mat = np.ascontiguousarray(mat, np.int32)
    return _binary_rows(mat.view(np.uint8).reshape(mat.shape[0], -1))


def _string_array(col) -> "pa.Array":
    from adam_tpu.formats.strings import StringColumn

    return StringColumn.of(col).to_arrow()


def batch_to_raw_table(batch: ReadBatch, side: ReadSidecar, header):
    """Valid rows of a columnar batch -> raw-layout arrow table."""
    import jax
    import pyarrow as pa

    from adam_tpu.io.parquet import _header_meta

    b = jax.tree.map(np.asarray, batch)
    valid = np.asarray(b.valid)
    if not valid.all():
        rows = np.flatnonzero(valid)
        b = jax.tree.map(lambda x: np.asarray(x)[rows], b)
        side = side.take(rows)
    cols = {
        "bases": _binary_rows(b.bases),
        "quals": _binary_rows(b.quals),
        "lengths": pa.array(np.asarray(b.lengths, np.int32), pa.int32()),
        "flags": pa.array(np.asarray(b.flags, np.int32), pa.int32()),
        "contig_idx": pa.array(np.asarray(b.contig_idx, np.int32), pa.int32()),
        "start": pa.array(np.asarray(b.start, np.int64), pa.int64()),
        "end": pa.array(np.asarray(b.end, np.int64), pa.int64()),
        "mapq": pa.array(np.asarray(b.mapq, np.int32), pa.int32()),
        "cigar_ops": _binary_rows(b.cigar_ops),
        "cigar_lens": _i32_matrix_rows(b.cigar_lens),
        "cigar_n": pa.array(np.asarray(b.cigar_n, np.int32), pa.int32()),
        "mate_contig_idx": pa.array(
            np.asarray(b.mate_contig_idx, np.int32), pa.int32()
        ),
        "mate_start": pa.array(np.asarray(b.mate_start, np.int64), pa.int64()),
        "tlen": pa.array(np.asarray(b.tlen, np.int32), pa.int32()),
        "read_group_idx": pa.array(
            np.asarray(b.read_group_idx, np.int32), pa.int32()
        ),
        "has_qual": pa.array(np.asarray(b.has_qual, bool), pa.bool_()),
        "names": _string_array(side.names),
        "attrs": _string_array(side.attrs),
        "md": _string_array(side.md),
        "orig_quals": _string_array(side.orig_quals),
        "trimmed_from_start": pa.array(
            np.asarray(side.trimmed_from_start, np.int32), pa.int32()
        ),
        "trimmed_from_end": pa.array(
            np.asarray(side.trimmed_from_end, np.int32), pa.int32()
        ),
    }
    return pa.table(cols).replace_schema_metadata(_header_meta(header))


class RawShardWriter:
    """Appendable raw-spill writer for one shard file."""

    def __init__(self, path: str):
        self.path = path
        self._writer = None

    def append(self, batch: ReadBatch, side: ReadSidecar, header) -> None:
        import pyarrow as pa

        table = batch_to_raw_table(batch, side, header)
        if self._writer is None:
            self._writer = pa.ipc.new_file(self.path, table.schema)
        for rb in table.to_batches():
            self._writer.write_batch(rb)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


def _rows_matrix(chunks, dtype, pad_value, item: int = 1):
    """Binary chunked array -> [N, Wmax/item] matrix of ``dtype``.

    Each chunk's rows share one width (they came from one [N, W]
    matrix), so a chunk reconstructs as a single buffer reshape; chunks
    of differing width pad to the max."""
    widths = []
    parts = []
    for ch in chunks:
        n = len(ch)
        if n == 0:
            continue
        # the frombuffer reads below start at the buffers' position 0,
        # which is only correct for unsliced chunks (all RawShardWriter
        # output is); fail loudly rather than decode shifted garbage
        if ch.offset != 0:
            raise ValueError(
                "_rows_matrix requires unsliced chunks (offset=0); got "
                f"a chunk with offset {ch.offset}"
            )
        buf = np.frombuffer(ch.buffers()[2], np.uint8,
                            ch.buffers()[2].size)
        off = np.frombuffer(ch.buffers()[1], np.int64, n + 1)
        w = int(off[1] - off[0]) if n else 0
        mat = buf[off[0]: off[0] + n * w].reshape(n, w)
        parts.append(mat)
        widths.append(w)
    if not parts:
        return np.zeros((0, item), dtype).reshape(0, -1)
    wmax = max(widths)
    out = []
    for mat in parts:
        if mat.shape[1] < wmax:
            pad = np.full((mat.shape[0], wmax - mat.shape[1]),
                          pad_value, np.uint8)
            if dtype is np.int32:
                # i32 rows pad with whole little-endian elements
                pad = np.zeros((mat.shape[0], wmax - mat.shape[1]), np.uint8)
            mat = np.concatenate([mat, pad], axis=1)
        out.append(mat)
    full = np.concatenate(out, axis=0) if len(out) > 1 else out[0].copy()
    if dtype is np.int32:
        return full.view(np.int32).reshape(full.shape[0], -1)
    return full.astype(dtype, copy=False)


def read_raw_shard(path: str):
    """Raw spill file -> (ReadBatch, ReadSidecar, SamHeader)."""
    import pyarrow as pa

    from adam_tpu.formats import schema
    from adam_tpu.formats.strings import StringColumn
    from adam_tpu.io.parquet import _header_from_meta

    with pa.memory_map(path) as source:
        table = pa.ipc.open_file(source).read_all()
    header = _header_from_meta(table.schema.metadata)
    n = table.num_rows

    def col(name):
        return table.column(name)

    def ints(name, dtype):
        # fresh writable array: downstream transforms mutate columns in
        # place (e.g. trim), and Arrow/mmap-backed views are read-only
        arr = np.asarray(col(name).combine_chunks())
        return arr.astype(dtype, copy=True)

    bases = _rows_matrix(col("bases").chunks, np.uint8, schema.BASE_PAD)
    quals = _rows_matrix(col("quals").chunks, np.uint8, schema.QUAL_PAD)
    cigar_ops = _rows_matrix(col("cigar_ops").chunks, np.uint8,
                             schema.CIGAR_PAD)
    cigar_lens = _rows_matrix(col("cigar_lens").chunks, np.int32, 0, item=4)

    def strings(name):
        return StringColumn.from_arrow(col(name))

    batch = ReadBatch(
        bases=bases,
        quals=quals,
        lengths=ints("lengths", np.int32),
        flags=ints("flags", np.int32),
        contig_idx=ints("contig_idx", np.int32),
        start=ints("start", np.int64),
        end=ints("end", np.int64),
        mapq=ints("mapq", np.int32),
        cigar_ops=cigar_ops,
        cigar_lens=cigar_lens,
        cigar_n=ints("cigar_n", np.int32),
        mate_contig_idx=ints("mate_contig_idx", np.int32),
        mate_start=ints("mate_start", np.int64),
        tlen=ints("tlen", np.int32),
        read_group_idx=ints("read_group_idx", np.int32),
        has_qual=ints("has_qual", bool),
        valid=np.ones(n, bool),
    )
    side = ReadSidecar(
        names=strings("names"),
        attrs=strings("attrs"),
        md=strings("md"),
        orig_quals=strings("orig_quals"),
        trimmed_from_start=ints("trimmed_from_start", np.int32),
        trimmed_from_end=ints("trimmed_from_end", np.int32),
    )
    return batch, side, header
