"""Composed sharded flagship transform (out-of-core, boundary-correct).

The multi-shard form of ``pipelines/streamed.py``'s pass structure, with
genome-bin Parquet shards (``host_shuffle``) as the unit instead of
ingest windows — the single-host embodiment of the reference's
distributed transform (AlignmentRecordRDDFunctions.scala:45-588 over
GenomicPartitioners.scala:63-85):

1. **Shuffle**: the windowed SAM/BAM reader streams into per-genome-bin
   shards keyed by the 5'-clipped position (so PCR duplicate groups
   co-locate; rich/RichAlignmentRecord.scala:104-126).  No whole-dataset
   residency at any point.
2. **Pass A** (per shard, loaded then dropped): duplicate-marking
   summaries + indel events.
3. **Barrier**: global duplicate resolve + target merge — decisions are
   taken over compact spliced summaries, so duplicate groups whose
   mates landed in different bins and realignment targets spanning a
   bin edge resolve exactly as in one batch.
4. **Pass B**: per-shard realignment-candidate split (pre-BQSR quals —
   the reference composes markdup -> realign -> BQSR,
   Transform.scala:121-144) + BQSR observation of each shard's
   remainder under resolved duplicate flags.
5. **Tail**: candidates from all shards realign together (boundary
   targets see all their reads); the realigned part is observed with
   its post-realignment alignments; histograms merge; table solve.
6. **Pass C**: per-shard recalibration apply; parts write to the
   output directory, the realigned part last.

Each pass reads its shards through a bounded LRU cache
(``cache_bytes``, default 4 GiB): shards that fit skip the re-decode on
later passes, eviction keeps resident bytes under the budget, and pass C
additionally pins up to ``n_writers`` shards in the write pool — so peak
memory is O(cache_bytes + a few shards), never O(dataset).  Set
``cache_bytes=0`` for the strict one-shard-resident discipline that lets
one small host per shard drive this same structure over DCN.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.utils.transfer import device_fetch


def transform_sharded(
    path: str,
    out_path: str,
    n_shards: int,
    *,
    mark_duplicates: bool = True,
    recalibrate: bool = True,
    realign: bool = True,
    known_snps=None,
    known_indels=None,
    consensus_model: str = "reads",
    compression: str = "zstd",
    shuffle_dir: str | None = None,
    batch_reads: int = 500_000,
    max_indel_size: int | None = None,
    max_consensus_number: int | None = None,
    lod_threshold: float | None = None,
    max_target_size: int | None = None,
    dump_observations: str | None = None,
    shard_fmt: str = "raw",
    cache_bytes: int = 4 << 30,
) -> dict:
    from adam_tpu.io import context
    from adam_tpu.io.sam import iter_bam_batches, iter_sam_batches
    from adam_tpu.parallel import host_shuffle
    from adam_tpu.pipelines import bqsr as bqsr_mod
    from adam_tpu.pipelines import markdup as md_mod
    from adam_tpu.pipelines import realign as realign_mod
    from adam_tpu.pipelines.streamed import _write_part

    t_start = time.perf_counter()
    stats: dict = {}
    os.makedirs(out_path, exist_ok=True)
    # same crash-consistency contract as the streamed pipeline: part
    # writes stage under out_path/_temporary and a crashed run's
    # leftovers purge here, before any writer is live
    from adam_tpu.io.parquet import purge_stale_staging

    purge_stale_staging(out_path)
    tmp = shuffle_dir or tempfile.mkdtemp(prefix="adam_tpu_shards_")
    own_tmp = shuffle_dir is None
    if known_indels is not None and consensus_model == "reads":
        # supplying known indels implies the knowns consensus model (the
        # reference's -known_indels flag semantics; realign_indels only
        # consults the table under that model)
        consensus_model = "knowns"
    mis, mcn, lod, mts = realign_mod.resolve_tuning(
        max_indel_size, max_consensus_number, lod_threshold, max_target_size
    )

    try:
        # ---- 1. shuffle to genome-bin shards --------------------------
        t = time.perf_counter()
        p = str(path)
        base = p[:-3] if p.endswith(".gz") else p
        reader = (
            iter_bam_batches(p, batch_reads=batch_reads)
            if base.endswith(".bam")
            else iter_sam_batches(p, batch_reads=batch_reads)
        )
        shard_paths = host_shuffle.shuffle_alignments_to_shards(
            reader, n_shards, tmp, compression=compression, fmt=shard_fmt
        )
        stats["shuffle_s"] = time.perf_counter() - t
        if not shard_paths:
            stats["n_reads"] = 0
            stats["total_s"] = time.perf_counter() - t_start
            return stats

        # bounded LRU shard cache: each pass re-reads its shards, so
        # shards that fit the budget skip the decode on passes B/C (the
        # Spark block-manager analog: cache when it fits, spill-backed
        # always).  Out-of-core discipline is preserved — eviction keeps
        # resident bytes under ``cache_bytes`` no matter the dataset.
        from collections import OrderedDict

        _cache: OrderedDict[int, tuple[AlignmentDataset, int]] = OrderedDict()
        _cache_total = [0]

        def _nbytes(ds: AlignmentDataset) -> int:
            import jax

            n = 0
            for leaf in jax.tree.leaves(ds.batch):
                n += getattr(leaf, "nbytes", 0)
            for col in (ds.sidecar.names, ds.sidecar.attrs, ds.sidecar.md,
                        ds.sidecar.orig_quals):
                n += getattr(getattr(col, "buf", None), "nbytes", 0)
            return n

        def load(si: int, insert: bool = True) -> AlignmentDataset:
            hit = _cache.get(si)
            if hit is not None:
                _cache.move_to_end(si)
                return hit[0]
            b, s, h = host_shuffle.iter_shards([shard_paths[si]]).__next__()
            ds = AlignmentDataset(b, s, h)
            nb = _nbytes(ds)
            # the final pass never revisits a shard: inserting there
            # would only evict shards later in this same pass
            if insert and nb <= cache_bytes:
                while _cache and _cache_total[0] + nb > cache_bytes:
                    _, (_, old_nb) = _cache.popitem(last=False)
                    _cache_total[0] -= old_nb
                _cache[si] = (ds, nb)
                _cache_total[0] += nb
            return ds

        def with_dup_flags(ds: AlignmentDataset, si: int) -> AlignmentDataset:
            if dup_slices[si] is None:
                return ds
            b = ds.batch.to_numpy()
            return ds.with_batch(
                b.replace(flags=md_mod.apply_duplicate_flags(
                    np.asarray(b.flags), dup_slices[si]
                ))
            )

        # ---- 2. pass A: summaries + events ----------------------------
        t = time.perf_counter()
        summaries = []
        events = []
        counts = []
        header = None
        for si in range(len(shard_paths)):
            ds = load(si)
            header = ds.header
            counts.append(ds.batch.n_rows)
            if mark_duplicates:
                summaries.append(md_mod.row_summary(ds))
            if realign:
                events.append(
                    realign_mod.extract_indel_event_arrays(
                        ds.batch.to_numpy(), max_indel_size=mis
                    )
                )
        stats["n_reads"] = int(sum(counts))
        stats["summaries_s"] = time.perf_counter() - t

        # ---- 3. barrier: resolve + targets ----------------------------
        t = time.perf_counter()
        dup_slices = [None] * len(shard_paths)
        if mark_duplicates and summaries:
            dup = md_mod.resolve_duplicates(
                md_mod.concat_summaries(summaries)
            )
            off = 0
            for si, n in enumerate(counts):
                dup_slices[si] = dup[off : off + n]
                off += n
            del summaries
        targets = (
            realign_mod.merge_events(
                np.concatenate(events, axis=0) if events
                else np.zeros((0, 5), np.int64),
                header.seq_dict.names, mts,
            )
            if realign
            else []
        )
        stats["resolve_s"] = time.perf_counter() - t

        # ---- 4. pass B: candidate split (pre-BQSR, the reference's
        # markdup -> realign -> BQSR composition, Transform.scala:121-144)
        # + observe each shard's remainder under dup flags --------------
        # remainder datasets are NOT carried across passes (that would
        # pin every shard at once); a per-shard candidate bitmask is —
        # ~n_rows bytes each — so the observe and apply passes mask the
        # same membership without recomputing the target mapping
        t = time.perf_counter()
        candidates = []
        splits = []
        cand_masks: dict[int, np.ndarray] = {}
        for si in range(len(shard_paths)):
            ds = with_dup_flags(load(si), si)
            n_valid = ds.batch.n_rows
            if targets:
                b2 = ds.batch.to_numpy()
                mask = realign_mod.candidate_mask(
                    b2, targets, header.seq_dict.names
                )
                cand_masks[si] = mask
                if mask.any():
                    candidates.append(
                        ds.take_rows(np.flatnonzero(mask))
                    )
                ds = realign_mod.mask_out_candidates(
                    ds, targets, header.seq_dict.names, mask=mask
                )
                n_valid = int(np.asarray(ds.batch.valid).sum())
            splits.append((si, n_valid))
        stats["split_s"] = time.perf_counter() - t

        obs_parts = []

        def _observe_remainders():
            # hidden under the realign sweeps' device drain (remainder
            # rows are untouched by realignment, so observing them on
            # either side of it is equivalent); shards re-read through
            # the LRU cache and re-split by the same rule
            t0 = time.perf_counter()
            if recalibrate:
                for si, n_valid in splits:
                    if not n_valid:
                        continue
                    ds = with_dup_flags(load(si), si)
                    if si in cand_masks:
                        ds = realign_mod.mask_out_candidates(
                            ds, targets, header.seq_dict.names,
                            mask=cand_masks[si],
                        )
                    total, mism, _rg, g = bqsr_mod._observe_device(
                        ds, known_snps
                    )
                    obs_parts.append(
                        (device_fetch(total), device_fetch(mism), g)
                    )
            stats["observe_s"] = time.perf_counter() - t0

        # ---- 5. tail: realign candidates across shard edges (observing
        # shard remainders under the device wait), then observe the
        # realigned part with its post-realignment alignments -----------
        t = time.perf_counter()
        realigned = None
        if candidates:
            cand = AlignmentDataset.concat(candidates)
            realigned = realign_mod.realign_indels(
                cand,
                consensus_model=consensus_model,
                known_indels=known_indels,
                max_indel_size=mis,
                max_consensus_number=mcn,
                lod_threshold=lod,
                max_target_size=mts,
                overlap_work=_observe_remainders,
            )
            if recalibrate and realigned.batch.n_rows:
                total, mism, _rg, g = bqsr_mod._observe_device(
                    realigned, known_snps
                )
                obs_parts.append((device_fetch(total), device_fetch(mism), g))
        else:
            _observe_remainders()
        stats["realign_s"] = (
            time.perf_counter() - t - stats.get("observe_s", 0.0)
        )

        # ---- barrier: merge histograms, solve the table ---------------
        t = time.perf_counter()
        table = None
        gl = 0
        if recalibrate and obs_parts:
            total, mism, gl = bqsr_mod.merge_observations(obs_parts)
            if dump_observations:
                bqsr_mod.dump_observation_csv(
                    total, mism, header.read_groups.names + ["null"], gl,
                    dump_observations,
                )
            table = bqsr_mod.solve_recalibration_table(total, mism)
        stats["solve_s"] = time.perf_counter() - t

        # ---- 6. pass C: apply || part writes --------------------------
        # a writer pool encodes finished shards while the next shard's
        # apply runs (the streamed path's layout; Parquet encode is
        # arrow C++ and releases the GIL around compression/IO)
        from concurrent.futures import ThreadPoolExecutor

        t = time.perf_counter()
        futures = []
        n_writers = 3
        with ThreadPoolExecutor(max_workers=n_writers) as pool:
            def _submit_write(idx, ds):
                # backpressure: each pending future pins a whole shard,
                # so cap in-flight writes to bound pass C's residency at
                # n_writers shards beyond the one being applied
                while sum(1 for f in futures if not f.done()) >= n_writers:
                    next(f for f in futures if not f.done()).result()
                futures.append(pool.submit(
                    _write_part, out_path, idx, ds, compression
                ))

            for si in range(len(shard_paths)):
                ds = with_dup_flags(load(si, insert=False), si)
                ev = _cache.pop(si, None)  # final pass: free as we go
                if ev is not None:
                    _cache_total[0] -= ev[1]
                if si in cand_masks:
                    # mask-only: clear candidate rows' valid bit (the
                    # writers filter on valid; no keep-side copy)
                    ds = realign_mod.mask_out_candidates(
                        ds, targets, header.seq_dict.names,
                        mask=cand_masks[si],
                    )
                if table is not None:
                    ds = bqsr_mod.apply_recalibration(ds, table, gl)
                if int(np.asarray(ds.batch.valid).sum()):
                    _submit_write(si, ds)
            if realigned is not None:
                if table is not None:
                    realigned = bqsr_mod.apply_recalibration(
                        realigned, table, gl
                    )
                _submit_write(len(shard_paths), realigned)
            stats["apply_split_s"] = time.perf_counter() - t

            t = time.perf_counter()
            for f in futures:
                err = f.exception()
                if err is not None:
                    raise err
        stats["write_wait_s"] = time.perf_counter() - t
        stats["total_s"] = time.perf_counter() - t_start
        return stats
    finally:
        if own_tmp:
            shutil.rmtree(tmp, ignore_errors=True)
