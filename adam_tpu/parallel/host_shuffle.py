"""Host-side out-of-core genome shuffle (Arrow/Parquet spill).

SURVEY §2.6: within a pod slice the shuffle role is played by XLA
collectives over ICI (parallel/dist.py), but data that exceeds device
(or even host) memory needs a *host-level* exchange — the role Spark's
TCP shuffle plays for the reference. Here it is: stream columnar batches
(e.g. from the windowed BAM reader), route every read to its genome-bin
shard with the cumulative-offset partitioner, and append each shard's
rows to its own Parquet store through a ParquetWriter. Shards are
re-shardable, independently loadable (one per host/process over DCN),
and never require the whole dataset in memory.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, Optional

import numpy as np

from adam_tpu.parallel.partitioner import position_partition


def shuffle_alignments_to_shards(
    batches: Iterable,
    n_shards: int,
    out_dir: str,
    compression: str = "zstd",
    fmt: str = "parquet",
) -> list[str]:
    """Stream (batch, sidecar, header) triples into per-genome-bin shards.

    -> ordered list of shard paths (``shard-00000.adam`` ... plus a final
    ``shard-unmapped.adam`` when unplaced reads exist). Constant memory:
    only one streamed batch is resident at a time; each shard grows by
    Parquet row groups.

    ``fmt="raw"`` spills the framework's own columnar layout instead of
    the Parquet interchange schema (``shard-*.arrows`` Arrow IPC; see
    parallel/spill.py) — memcpy-speed writes/reads for intermediate
    stores that only this framework re-reads.
    """
    import jax
    import pyarrow.parquet as pq

    from adam_tpu.io.parquet import to_arrow_alignments
    from adam_tpu.parallel import spill

    os.makedirs(out_dir, exist_ok=True)
    writers: dict[int, object] = {}
    paths: dict[int, str] = {}
    raw = fmt == "raw"

    def shard_path(s: int) -> str:
        ext = "arrows" if raw else "adam"
        name = (
            f"shard-{s:05d}.{ext}" if s < n_shards
            else f"shard-unmapped.{ext}"
        )
        return os.path.join(out_dir, name)

    try:
        for batch, side, header in batches:
            b = jax.tree.map(np.asarray, batch)
            valid = np.asarray(b.valid)
            # the 5'-CLIPPED position decides the bin, not `start`
            # (rich/RichAlignmentRecord.scala:104-126): PCR duplicates of
            # one fragment then co-locate regardless of per-copy clipping,
            # which is what makes per-shard duplicate groups whole
            from adam_tpu.ops import cigar as cigar_ops

            five = cigar_ops.five_prime_position_np(
                b.start, b.end, b.flags, b.cigar_ops, b.cigar_lens,
                b.cigar_n,
            )
            part = position_partition(
                header.seq_dict, b.contig_idx, np.maximum(five, 0), n_shards
            )
            for s in np.unique(part[valid]):
                rows = np.flatnonzero(valid & (part == s))
                sub = jax.tree.map(lambda x: x[rows], b)
                sub_side = side.take(rows)
                s = int(s)
                if raw:
                    if s not in writers:
                        paths[s] = shard_path(s)
                        writers[s] = spill.RawShardWriter(paths[s])
                    writers[s].append(sub, sub_side, header)
                    continue
                table = to_arrow_alignments(sub, sub_side, header)
                if s not in writers:
                    from adam_tpu.io.parquet import parquet_codec_kw

                    paths[s] = shard_path(s)
                    writers[s] = pq.ParquetWriter(
                        paths[s], table.schema, **parquet_codec_kw(compression)
                    )
                writers[s].write_table(table)
    finally:
        for w in writers.values():
            w.close()
    return [paths[s] for s in sorted(paths)]


def shuffle_bam_to_shards(
    bam_path: str,
    n_shards: int,
    out_dir: str,
    batch_reads: int = 500_000,
    compression: str = "zstd",
) -> list[str]:
    """Windowed BAM reader -> genome-bin Parquet shards, end to end out
    of core (a WGS BAM never resides in memory)."""
    from adam_tpu.io.sam import iter_bam_batches

    return shuffle_alignments_to_shards(
        iter_bam_batches(bam_path, batch_reads=batch_reads),
        n_shards, out_dir, compression=compression,
    )


def iter_shards(paths: Iterable[str]) -> Iterator:
    """Load shards one at a time -> (ReadBatch, ReadSidecar, SamHeader).

    Dispatches on the shard format: ``.arrows`` raw columnar spill
    (parallel/spill.py) or the Parquet interchange layout."""
    from adam_tpu.io.parquet import load_alignments
    from adam_tpu.parallel import spill

    for p in paths:
        if str(p).endswith(".arrows"):
            yield spill.read_raw_shard(p)
        else:
            yield load_alignments(p)
