"""Distributed (multi-chip) kernels via shard_map + XLA collectives.

Spark-primitive -> collective mapping (SURVEY.md §2.5/§2.6):

* driver ``aggregate`` (flagstat, BQSR observation table, sequence
  dictionaries) -> ``psum`` of fixed-shape metric structs / histograms;
* ``reduceByKey`` over k-mers -> hash-sharded ``all_to_all`` exchange,
  then a local sort/run-length count of each shard's key slice;
* sort ``sortByKey`` -> splitter-based ``all_to_all`` redistribution +
  local sort;
* flanking/halo exchange between genome-adjacent fragments
  (FlankReferenceFragments.scala:26-70) -> ``ppermute`` with the
  neighbor shard.

Everything here runs under ``shard_map`` over a 1-D mesh, so the same
code drives 8 virtual CPU devices in tests, one real TPU chip, or a
multi-host pod (collectives ride ICI within a slice, DCN across hosts).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from adam_tpu.formats.batch import ReadBatch
from adam_tpu.ops import flagstat as fs
from adam_tpu.ops import kmer as kmer_ops
from adam_tpu.parallel.mesh import SHARD_AXIS, genome_mesh, shard_map
from adam_tpu.utils.transfer import device_fetch


def _row_specs(batch: ReadBatch):
    return jax.tree.map(lambda _: P(SHARD_AXIS), batch)


def pad_batch_for_mesh(batch: ReadBatch, n_shards: int) -> ReadBatch:
    """Pad rows so the leading axis divides evenly across shards."""
    n = batch.n_rows
    target = -(-max(n, 1) // n_shards) * n_shards
    return batch.pad_rows(target)


# --------------------------------------------------------------------------
# psum aggregations
# --------------------------------------------------------------------------
def distributed_flagstat(batch: ReadBatch, mesh=None):
    """flagstat over a row-sharded batch; cross-chip combine is one psum
    of the metrics pytree (the reference's tree-aggregate to the driver).
    """
    mesh = mesh or genome_mesh()
    batch = pad_batch_for_mesh(batch, mesh.devices.size).to_device()

    out_struct = jax.eval_shape(fs.flagstat_device.__wrapped__, batch)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_row_specs(batch),),
        out_specs=jax.tree.map(lambda _: P(), out_struct),
        check_vma=False,
    )
    def run(local):
        failed, passed = fs.flagstat_device.__wrapped__(local)
        return jax.tree.map(lambda x: jax.lax.psum(x, SHARD_AXIS), (failed, passed))

    failed, passed = run(batch)
    return failed.to_ints(), passed.to_ints()


def distributed_observe(batch: ReadBatch, residue_ok, is_mismatch, read_ok,
                        n_rg: int, mesh=None):
    """BQSR observation histograms with cross-chip psum combine."""
    from adam_tpu.pipelines.bqsr import observe_kernel

    mesh = mesh or genome_mesh()
    n_shards = mesh.devices.size
    batch = pad_batch_for_mesh(batch, n_shards)
    lmax = batch.lmax

    def pad_rows(x):
        return np.pad(np.asarray(x), [(0, batch.n_rows - x.shape[0])] + [(0, 0)] * (x.ndim - 1))

    residue_ok = pad_rows(residue_ok)
    is_mismatch = pad_rows(is_mismatch)
    read_ok = pad_rows(read_ok)
    b = batch.to_device()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_row_specs(b), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def run(local, res_ok, is_mm, rd_ok):
        total, mism = observe_kernel.__wrapped__(
            local.bases, local.quals, local.lengths, local.flags,
            local.read_group_idx, res_ok, is_mm, rd_ok, n_rg, lmax,
        )
        return (
            jax.lax.psum(total, SHARD_AXIS),
            jax.lax.psum(mism, SHARD_AXIS),
        )

    return run(b, jnp.asarray(residue_ok), jnp.asarray(is_mismatch),
               jnp.asarray(read_ok))


# --------------------------------------------------------------------------
# fixed-capacity all_to_all routing, shared by k-mer count and sort
# --------------------------------------------------------------------------
def _route_all_to_all(values, dest, n_dev: int, pad, cap: int | None = None):
    """Send each value to its destination shard; returns (received,
    n_dropped) where ``received`` is the flat array of values landing on
    this shard (padded with ``pad``).

    ``cap`` bounds the per-destination send buffer: memory is
    O(n_dev * cap) per shard instead of the worst-case O(n_dev * m).
    Values beyond a destination's capacity are dropped and *counted* —
    callers run with a slack-factor capacity and fall back to the exact
    worst-case (cap = m) on the rare overflow (psum'd count > 0), so
    results are always exact.
    """
    m = values.shape[0]
    if cap is None:
        cap = m
    order = jnp.argsort(dest)
    vals_sorted = values[order]
    dest_sorted = dest[order]
    slot = (
        jnp.arange(m)
        - jnp.searchsorted(dest_sorted, jnp.arange(n_dev))[dest_sorted]
    )
    fits = slot < cap
    # overflowing values scatter into a trash slot past the real buffer
    flat = jnp.full(n_dev * cap + 1, pad, dtype=values.dtype)
    idx = jnp.where(fits, dest_sorted * cap + slot, n_dev * cap)
    flat = flat.at[idx].set(vals_sorted)
    buf = flat[: n_dev * cap].reshape(n_dev, cap)
    dropped = jax.lax.psum(jnp.sum(~fits), SHARD_AXIS)
    return jax.lax.all_to_all(buf, SHARD_AXIS, 0, 0).reshape(-1), dropped


def _mix_hash(keys):
    """Bit-mix i64 keys before modular sharding — the raw 3-bit-per-base
    packing puts only codes 0..4 in the low bits, which would starve most
    shards of a power-of-two mesh."""
    h = keys * jnp.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as i64
    return (h >> 32) & jnp.int64(0x7FFFFFFF)


# --------------------------------------------------------------------------
# k-mer counting with hash-sharded all_to_all
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("k", "mesh", "cap"))
def _distributed_kmers_jit(bases, lengths, valid, k: int, mesh, cap=None):
    n_dev = mesh.devices.size

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        check_vma=False,
    )
    def run(b, l, v):
        packed, win_valid = kmer_ops.extract_kmers(b, l, v, k)
        keys = jnp.where(win_valid, packed, jnp.int64(-1)).ravel()
        dest = jnp.where(keys >= 0, _mix_hash(keys) % n_dev, jnp.int64(0))
        mine, dropped = _route_all_to_all(keys, dest, n_dev, jnp.int64(-1), cap)
        s = jnp.sort(mine)
        is_new = jnp.concatenate([jnp.array([True]), s[1:] != s[:-1]])
        is_head = is_new & (s >= 0)
        seg = jnp.cumsum(is_new) - 1
        counts = jax.ops.segment_sum(
            (s >= 0).astype(jnp.int32), seg, num_segments=s.shape[0]
        )
        return s[None], counts[seg][None], is_head[None], dropped

    return run(bases, lengths, valid)


def distributed_count_kmers(batch: ReadBatch, k: int, mesh=None) -> dict[str, int]:
    """Exact global k-mer counts over a row-sharded batch.

    Local extraction -> hash-partitioned all_to_all so each device owns a
    disjoint key slice -> local sort/unique; host merges the per-device
    unique lists (no overlap by construction).
    """
    if batch.n_rows == 0:
        return {}
    mesh = mesh or genome_mesh()
    n_dev = mesh.devices.size
    batch = pad_batch_for_mesh(batch, n_dev).to_device()
    # capacity-bounded routing: 4x-uniform slack, exact-worst-case retry
    m = (batch.n_rows // n_dev) * (batch.lmax - k + 1)
    cap = min(m, 4 * m // n_dev + 64)
    s, counts, heads, dropped = _distributed_kmers_jit(
        batch.bases, batch.lengths, batch.valid, k, mesh, cap
    )
    if int(device_fetch(dropped)) > 0:  # rare: pathological key skew
        s, counts, heads, dropped = _distributed_kmers_jit(
            batch.bases, batch.lengths, batch.valid, k, mesh, m
        )
    s, counts, heads = (
        device_fetch(s), device_fetch(counts), device_fetch(heads)
    )
    out: dict[str, int] = {}
    for d in range(s.shape[0]):
        keys = s[d][heads[d]]
        vals = counts[d][heads[d]]
        for key, v in zip(keys, vals):
            out[kmer_ops.unpack_kmer(int(key), k)] = int(v)
    return out


# --------------------------------------------------------------------------
# distributed sort (splitter-based all_to_all)
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("mesh", "cap"))
def _distributed_sort_jit(keys, mesh, cap=None):
    n_dev = mesh.devices.size
    PAD = jnp.iinfo(jnp.int64).max

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS),),
        out_specs=(P(SHARD_AXIS), P()),
        check_vma=False,
    )
    def run(local):
        local = local.ravel()
        # gather only n_dev local quantiles per shard (n_dev^2 values
        # total), not the full key array — splitter quality is the same
        # and the per-chip all_gather stays O(n_dev^2) instead of O(N)
        local_sorted = jnp.sort(local)
        qidx = (jnp.arange(n_dev) * local.shape[0]) // n_dev
        samples = jax.lax.all_gather(local_sorted[qidx], SHARD_AXIS).ravel()
        samples = jnp.sort(samples)
        # n_dev-1 splitters at even quantiles
        idx = (jnp.arange(1, n_dev) * samples.shape[0]) // n_dev
        splitters = samples[idx]
        dest = jnp.searchsorted(splitters, local, side="right")
        recv, dropped = _route_all_to_all(local, dest, n_dev, PAD, cap)
        return jnp.sort(recv)[None], dropped

    return run(keys)


def distributed_sort_keys(keys, mesh):
    """Globally sort an i64 key array sharded across the mesh.

    Sample-splitter strategy: all_gather a per-shard sample, derive
    n_dev-1 splitters (identical on every shard), route each key to its
    splitter bucket with a capacity-bounded all_to_all (4x-uniform
    slack, exact-worst-case retry on overflow), then sort locally.
    Returns [n_dev, cap] keys per shard (padded with i64 max) whose
    concatenation is globally sorted.
    """
    n_dev = mesh.devices.size
    # shape only — never fetch (keys may span non-addressable devices)
    m = int(np.prod(keys.shape)) // n_dev
    cap = min(m, 4 * m // n_dev + 64)
    out, dropped = _distributed_sort_jit(keys, mesh, cap)
    if int(device_fetch(dropped)) > 0:  # degenerate splitters
        out, dropped = _distributed_sort_jit(keys, mesh, m)
    return out


# --------------------------------------------------------------------------
# sharded sort that carries row payloads
# --------------------------------------------------------------------------
def _route_all_to_all_multi(leaves, dest, n_dev: int, pads, cap: int):
    """:func:`_route_all_to_all` for several arrays sharing one routing:
    the slot layout is computed once from ``dest`` and applied to every
    leaf (2-D leaves route row-wise).  Returns (received leaves, dropped).
    """
    m = dest.shape[0]
    order = jnp.argsort(dest)
    dest_sorted = dest[order]
    slot = (
        jnp.arange(m)
        - jnp.searchsorted(dest_sorted, jnp.arange(n_dev))[dest_sorted]
    )
    fits = slot < cap
    idx = jnp.where(fits, dest_sorted * cap + slot, n_dev * cap)
    received = []
    for leaf, pad in zip(leaves, pads):
        v = leaf[order]
        tail = v.shape[1:]
        flat = jnp.full((n_dev * cap + 1,) + tail, pad, dtype=v.dtype)
        flat = flat.at[idx].set(v)
        buf = flat[: n_dev * cap].reshape((n_dev, cap) + tail)
        out = jax.lax.all_to_all(buf, SHARD_AXIS, 0, 0)
        received.append(out.reshape((n_dev * cap,) + tail))
    dropped = jax.lax.psum(jnp.sum(~fits), SHARD_AXIS)
    return received, dropped


@partial(jax.jit, static_argnames=("mesh", "cap"))
def _distributed_sort_rows_jit(keys, payload, mesh, cap):
    n_dev = mesh.devices.size
    PAD = jnp.iinfo(jnp.int64).max

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), jax.tree.map(lambda _: P(SHARD_AXIS), payload)),
        out_specs=(
            P(SHARD_AXIS),
            jax.tree.map(lambda _: P(SHARD_AXIS), payload),
            P(),
        ),
        check_vma=False,
    )
    def run(local, rows):
        local = local.ravel()
        local_sorted = jnp.sort(local)
        qidx = (jnp.arange(n_dev) * local.shape[0]) // n_dev
        samples = jax.lax.all_gather(local_sorted[qidx], SHARD_AXIS).ravel()
        samples = jnp.sort(samples)
        idx = (jnp.arange(1, n_dev) * samples.shape[0]) // n_dev
        splitters = samples[idx]
        dest = jnp.searchsorted(splitters, local, side="right")
        leaves, treedef = jax.tree.flatten(rows)
        pads = [jnp.zeros((), l.dtype) for l in leaves]
        (rk, *rleaves), dropped = _route_all_to_all_multi(
            [local] + leaves, dest, n_dev, [PAD] + pads, cap
        )
        order = jnp.argsort(rk, stable=True)
        out_rows = jax.tree.unflatten(
            treedef, [l[order][None] for l in rleaves]
        )
        return rk[order][None], out_rows, dropped

    return run(keys, payload)


# --------------------------------------------------------------------------
# device lexsort (the barrier-1 duplicate-resolve sort)
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("n_keys",))
def _lexsort_perm_jit(keys_padded, n_keys: int):
    """Stable lexsort permutation of ``keys_padded`` ([n_keys + 1, m]
    i64; row order = np.lexsort convention, LAST key primary; the final
    row is the pad-validity key that sorts padding strictly last).

    np.lexsort is a cascade of stable sorts from the least-significant
    key up; composing ``perm = perm[argsort(k[perm], stable)]`` per key
    reproduces THE unique stable permutation — so the device result is
    bitwise the host result, not merely an equivalent order.
    """
    m = keys_padded.shape[1]
    perm = jnp.arange(m)
    for i in range(n_keys + 1):
        perm = perm[jnp.argsort(keys_padded[i][perm], stable=True)]
    return perm


def device_lexsort(keys, device=None, info=None):
    """``np.lexsort(keys)`` computed on a device -> i64[n] permutation.

    The single-device member of this module's sort family
    (:func:`distributed_sort_keys` / :func:`distributed_sort_rows` are
    the mesh members): the barrier-1 duplicate-resolve cascade
    (pipelines/markdup.resolve_duplicates) routes its packed summary
    keys through it, moving the measured 1.56 s of pure-host serial
    lexsort onto the chip.  ``keys`` follows the np.lexsort convention
    (sequence of equal-length i64 arrays, last key primary);
    ``device`` commits the sort to an explicit chip (the pool/mesh's
    device 0) or the default device when None.

    Inputs pad to the pow2 row grid (one compiled shape per decade of
    group count, not one per run) with an extra most-significant
    validity key that sorts the padding strictly last — ``perm[:n]`` is
    exactly the host permutation.  Any failure falls back to
    ``np.lexsort`` (bit-parity by construction), so a dead chip costs a
    warning, never a wrong resolve.

    ``info``: optional dict that receives ``{"device_sort": bool}`` —
    whether the device path actually DELIVERED the permutation (False
    on the fallback), so callers report the outcome, not the intent.
    """
    if info is not None:
        info["device_sort"] = False
    keys = [np.ascontiguousarray(k, np.int64) for k in keys]
    n = keys[0].shape[0] if keys else 0
    if n == 0 or not keys:
        return np.lexsort(tuple(keys)) if keys else np.zeros(0, np.int64)
    try:
        from adam_tpu.formats.batch import grid_rows
        from adam_tpu.parallel.device_pool import putter

        g = grid_rows(n)
        stack = np.zeros((len(keys) + 1, g), np.int64)
        for i, k in enumerate(keys):
            stack[i, :n] = k
        stack[len(keys), n:] = 1  # pad rows sort last, real order intact
        # deliberately NOT compile_ledger-tracked, unlike the other
        # streamed dispatch sites: the sort grid derives from the
        # BUCKET count, which only exists at the barrier itself — there
        # is no prewarm point ahead of it, so a ledger entry would
        # permanently flag a structurally unavoidable one-off compile
        # as an in-window "coverage gap" warning and drown the
        # actionable ones.  The jit executable cache still amortizes it
        # process-wide (the bench's warmup-run pattern pays it once).
        perm = _lexsort_perm_jit(putter(device)(stack), len(keys))
        from adam_tpu.utils.transfer import device_fetch

        out = np.asarray(device_fetch(perm[:n]), np.int64)
        if info is not None:
            info["device_sort"] = True
        return out
    except Exception:
        import logging

        logging.getLogger(__name__).warning(
            "device lexsort failed; falling back to the host np.lexsort "
            "(bit-identical)", exc_info=True,
        )
        return np.lexsort(tuple(keys))


def distributed_sort_rows(keys, payload, mesh):
    """Globally sort rows by i64 key across the mesh, *moving the rows*
    (sortByKey with payloads, AlignmentRecordRDDFunctions.scala:245-258 —
    not just the keys).

    ``payload``: pytree of arrays with leading dim == len(keys), sharded
    like ``keys``.  Returns (sorted_keys [n_dev, n_dev*cap], rows pytree
    [n_dev, n_dev*cap, ...], valid mask) — each shard's slice holds its
    splitter bucket locally sorted (capacity cap per sending shard), so
    concatenating shards in order yields the globally key-sorted rows;
    padding slots have key i64-max and are False in the mask.
    """
    n_dev = mesh.devices.size
    m = int(np.prod(np.shape(keys))) // n_dev
    cap = min(m, 4 * m // n_dev + 64)
    k, rows, dropped = _distributed_sort_rows_jit(keys, payload, mesh, cap)
    if int(device_fetch(dropped)) > 0:  # degenerate splitters: retry exact
        k, rows, dropped = _distributed_sort_rows_jit(keys, payload, mesh, m)
    valid = device_fetch(k) != np.iinfo(np.int64).max
    return k, rows, valid


# --------------------------------------------------------------------------
# distributed duplicate marking
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("mesh",))
def _markdup_columns_jit(batch: ReadBatch, mesh):
    from adam_tpu.pipelines.markdup import markdup_columns_local

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_row_specs(batch),),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,
    )
    def run(local):
        # same traced body as the single-chip default path — the mesh
        # variant only adds the sharding
        return markdup_columns_local(
            local.start, local.end, local.flags,
            local.cigar_ops, local.cigar_lens, local.cigar_n,
            local.quals, local.lengths,
        )

    return run(batch)


def distributed_markdup(ds, mesh=None):
    """Duplicate marking over a row-sharded batch: the [N, L] work (5'
    clipped keys via the device CIGAR walk, quality scores via masked
    segment sums) runs sharded on the mesh; only the compact per-row
    columns come home for the group-subgroup-argmax cascade (the same
    driver-side lexsort the reference's groupBy shuffle feeds,
    MarkDuplicates.scala:66-128).  Marks are bitwise those of the
    single-chip :func:`adam_tpu.pipelines.markdup.mark_duplicates`.
    """
    from adam_tpu.pipelines import markdup as md

    mesh = mesh or genome_mesh()
    b = ds.batch.to_numpy()
    n = b.n_rows
    padded = pad_batch_for_mesh(ds.batch, mesh.devices.size).to_device()
    five, score = _markdup_columns_jit(padded, mesh)
    s = md.row_summary(
        ds, b,
        five_prime=device_fetch(five)[:n],
        score=device_fetch(score)[:n],
    )
    dup = md.resolve_duplicates(s)
    return ds.with_batch(
        b.replace(flags=md.apply_duplicate_flags(np.asarray(b.flags), dup))
    )


# --------------------------------------------------------------------------
# multihost telemetry aggregation
# --------------------------------------------------------------------------
def gather_host_telemetry(snapshot: dict | None = None) -> list[dict]:
    """Gather every host's telemetry snapshot at a merge barrier ->
    ``[snapshot_for_process_0, ..., snapshot_for_process_{n-1}]``.

    The observability face of the driver-aggregate pattern: where the
    reference's Spark listener collects per-executor task timings, the
    multihost pipeline calls this at its merge barrier (see
    tests/multihost_harness.py) so the report can show per-host skew
    (``adam_tpu.utils.telemetry.merge_snapshots``).  Snapshots ship as
    length-prefixed JSON bytes over a ``process_allgather`` — control
    plane only, never per-read data.  Must be called by ALL processes
    (it is a collective); single-process runs return ``[snapshot]``
    without touching the collective machinery.
    """
    import json

    from adam_tpu.utils import telemetry

    if snapshot is None:
        snapshot = telemetry.TRACE.snapshot()
    try:
        n_procs = jax.process_count()
    except Exception:
        n_procs = 1
    if n_procs == 1:
        return [snapshot]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(
        json.dumps(snapshot, default=str).encode(), np.uint8
    )
    sizes = np.asarray(
        multihost_utils.process_allgather(np.int64(payload.size))
    ).reshape(-1)
    cap = int(sizes.max())
    buf = np.zeros(max(1, cap), np.uint8)
    buf[: payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    gathered = gathered.reshape(n_procs, -1)
    return [
        json.loads(gathered[p, : int(sizes[p])].tobytes().decode())
        for p in range(n_procs)
    ]


# --------------------------------------------------------------------------
# halo (flank) exchange between genome-adjacent shards
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("flank", "mesh"))
def halo_exchange_right(chunks, mesh, flank: int):
    """Append each shard's first ``flank`` bases to its LEFT neighbor's
    chunk — the ppermute form of fragment flanking
    (FlankReferenceFragments: a fragment is extended with the start of
    the next fragment so windows spanning the boundary are complete).

    chunks: u8[n_shards, width] sharded on axis 0 -> returns
    u8[n_shards, width + flank] sharded the same way; the last shard's
    halo is BASE_PAD.
    """
    from adam_tpu.formats import schema

    n_dev = mesh.devices.size

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS),),
        out_specs=P(SHARD_AXIS),
        check_vma=False,
    )
    def run(local):
        head = local[:, :flank]
        # send my head to my left neighbor (shard i -> i-1)
        perm = [(i, (i - 1) % n_dev) for i in range(n_dev)]
        halo = jax.lax.ppermute(head, SHARD_AXIS, perm)
        me = jax.lax.axis_index(SHARD_AXIS)
        halo = jnp.where(me == n_dev - 1, jnp.uint8(schema.BASE_PAD), halo)
        return jnp.concatenate([local, halo], axis=1)

    return run(chunks)
