from adam_tpu.parallel import dist, mesh, partitioner

__all__ = ["dist", "mesh", "partitioner"]
