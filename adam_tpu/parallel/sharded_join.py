"""Out-of-core distributed region join and depth over genome-bin shards.

The reference's joins are distributed by construction —
``ShuffleRegionJoin.partitionAndJoin``
(rdd/ShuffleRegionJoin.scala:72-134: genome bins + per-bin chromsweep,
dedupe at :262-267) runs with both sides spilled to Spark's shuffle and
each bin joined independently.  :mod:`adam_tpu.pipelines.region_join`
implements the same join shapes over fully-resident arrays; this module
is the out-of-core spine underneath them: the streamed (big) side is
routed through a per-genome-bin interval spill on disk — the same
genome-bin shard layout :mod:`adam_tpu.parallel.host_shuffle` uses for
whole read batches — and each bin is then loaded and chromswept alone,
so peak memory is one ingest window plus one bin, never the dataset.

Halo handling: an interval spanning a bin edge is replicated into every
bin it overlaps (``start_bin..end_bin``), exactly the reference's
replication (:112-121); the pair-level dedupe is the reference's
"at least one side starts in this bin" rule, and for point depth the
site's single owning bin counts all replicas that reach it.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable, Iterator, Optional

import numpy as np

from adam_tpu.models.dictionaries import SequenceDictionary
from adam_tpu.ops import intervals as iv
from adam_tpu.parallel.partitioner import GenomeBins
from adam_tpu.pipelines.region_join import IntervalArrays


class BinnedIntervalSpill:
    """Append-only per-genome-bin spill of (contig, start, end, row_id)
    interval rows as raw little-endian i64 quadruples.

    One file per touched bin; appends replicate each interval into every
    bin it overlaps (the shuffle join's halo).  Constant memory: only
    the appended batch is ever resident.
    """

    _ROW = 4  # i64 fields per spilled interval

    def __init__(self, bins: GenomeBins, workdir: Optional[str] = None):
        self.bins = bins
        self._own = workdir is None
        self._dir = workdir or tempfile.mkdtemp(prefix="adam_tpu_binspill_")
        os.makedirs(self._dir, exist_ok=True)
        # appends run in "ab" mode, so stale bin files from a crashed
        # prior run sharing this workdir would silently corrupt counts
        for name in os.listdir(self._dir):
            if name.startswith("bin-") and name.endswith(".i64"):
                os.unlink(os.path.join(self._dir, name))
        self._counts: dict[int, int] = {}

    def _path(self, b: int) -> str:
        return os.path.join(self._dir, f"bin-{b:06d}.i64")

    def append(self, contig, start, end, row_id) -> None:
        contig = np.asarray(contig, np.int64)
        start = np.asarray(start, np.int64)
        end = np.asarray(end, np.int64)
        row_id = np.asarray(row_id, np.int64)
        if len(contig) == 0:
            return
        lo = self.bins.start_bin(contig, start)
        hi = self.bins.end_bin(contig, end) + 1
        rep, rbin = iv.expand_ranges(lo, hi)
        order = np.argsort(rbin, kind="stable")
        rep, rbin = rep[order], rbin[order]
        edges = np.flatnonzero(
            np.concatenate([[True], rbin[1:] != rbin[:-1]])
        )
        bounds = np.concatenate([edges, [len(rbin)]])
        for k in range(len(edges)):
            b = int(rbin[edges[k]])
            rows = rep[bounds[k]: bounds[k + 1]]
            mat = np.empty((len(rows), self._ROW), np.int64)
            mat[:, 0] = contig[rows]
            mat[:, 1] = start[rows]
            mat[:, 2] = end[rows]
            mat[:, 3] = row_id[rows]
            # open-per-write append: a WGS genome touches thousands of
            # bins, so persistent handles would blow the fd ulimit
            if b not in self._counts:
                self._counts[b] = 0
            with open(self._path(b), "ab") as fh:
                fh.write(mat.tobytes())
            self._counts[b] += len(rows)

    def close(self) -> None:  # appends hold no persistent handles
        pass

    def touched_bins(self) -> list[int]:
        return sorted(self._counts)

    def read_bin(self, b: int) -> tuple[np.ndarray, np.ndarray]:
        """-> (IntervalArrays, row_ids) of one bin's spilled rows."""
        with open(self._path(b), "rb") as fh:
            mat = np.frombuffer(fh.read(), np.int64).reshape(-1, self._ROW)
        ia = IntervalArrays(
            mat[:, 0].copy(), mat[:, 1].copy(), mat[:, 2].copy()
        )
        return ia, mat[:, 3].copy()

    def cleanup(self) -> None:
        self.close()
        for b in list(self._counts):
            try:
                os.unlink(self._path(b))
            except OSError:
                pass
        if self._own:
            try:
                os.rmdir(self._dir)
            except OSError:
                pass


def _spill_batches(
    batches: Iterable, bins: GenomeBins, workdir: Optional[str]
) -> tuple[BinnedIntervalSpill, int]:
    """Stream (ReadBatch, sidecar, header) triples into a binned interval
    spill of their mapped reads -> (spill, total rows consumed).

    Only the coordinate columns are touched — the [N, L] payload
    matrices never convert or copy here (that is the point of the
    spill)."""
    spill = BinnedIntervalSpill(bins, workdir)
    n_contigs = len(bins.seq_dict.names)
    offset = 0
    try:
        for b, _side, _header in batches:
            contig_idx = np.asarray(b.contig_idx)
            # start >= 0 guards records flagged mapped with POS=0
            # (start == -1): without it start_bin lands them one bin
            # before the contig's first, spilling junk bin--00001 files
            keep = np.flatnonzero(
                np.asarray(b.valid)
                & np.asarray(b.is_mapped)
                & (contig_idx >= 0)
                & (contig_idx < n_contigs)
                & (np.asarray(b.start) >= 0)
            )
            spill.append(
                contig_idx[keep],
                np.asarray(b.start)[keep],
                np.asarray(b.end)[keep],
                keep + offset,
            )
            offset += b.n_rows
    except BaseException:
        # a mid-ingest failure must not strand gigabytes of bin files
        spill.cleanup()
        raise
    return spill, offset


def streamed_depth(
    batches: Iterable,
    sites: IntervalArrays,
    seq_dict: SequenceDictionary,
    bin_size: int = 1_000_000,
    workdir: Optional[str] = None,
) -> np.ndarray:
    """Read depth at each site start, out of core -> i64[len(sites)].

    Bit-parity with the monolithic
    ``iv.point_depth(reads..., sites...)`` (the `depth` CLI core): a
    read overlapping a site's position is, by construction of the halo
    replication, present in the site's owning bin, and each site is
    counted in exactly one bin (point sites own one bin).  Peak memory
    is one ingest window + one bin of intervals.
    """
    bins = GenomeBins(bin_size, seq_dict)
    spill, _n = _spill_batches(batches, bins, workdir)
    depth = np.zeros(len(sites), np.int64)
    n_contigs = len(seq_dict.names)
    in_dict = (sites.contig >= 0) & (sites.contig < n_contigs)
    site_bin = np.full(len(sites), -1, np.int64)
    rows = np.flatnonzero(in_dict)
    site_bin[rows] = bins.start_bin(sites.contig[rows], sites.start[rows])
    try:
        for b in spill.touched_bins():
            sel = np.flatnonzero(site_bin == b)
            if len(sel) == 0:
                continue
            reads, _ids = spill.read_bin(b)
            depth[sel] = iv.point_depth(
                reads.contig, reads.start, reads.end,
                sites.contig[sel], sites.start[sel],
            )
    finally:
        spill.cleanup()
    return depth


def streamed_overlap_join(
    batches: Iterable,
    right: IntervalArrays,
    seq_dict: SequenceDictionary,
    bin_size: int = 1_000_000,
    workdir: Optional[str] = None,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Out-of-core shuffle region join: streamed left batches x resident
    right intervals -> per-bin (left_row_id, right_index) overlap pairs.

    Pair-set parity with ``shuffle_region_join``/``overlap_join`` over
    the fully-resident left side: per-bin chromsweep
    (``iv.overlap_join`` is the sorted sweep) plus the reference's
    dedupe rule — a pair is emitted only in bins where at least one side
    *starts* (ShuffleRegionJoin.scala:262-267) — so halo replicas never
    double-emit.  Left row ids are global (cumulative over the stream),
    so callers can re-fetch payload rows from their own store.
    """
    bins = GenomeBins(bin_size, seq_dict)
    spill, _n = _spill_batches(batches, bins, workdir)
    n_contigs = len(seq_dict.names)
    r_keep = np.flatnonzero(
        (right.contig >= 0) & (right.contig < n_contigs)
    )
    r_lo = bins.start_bin(right.contig[r_keep], right.start[r_keep])
    r_hi = bins.end_bin(right.contig[r_keep], right.end[r_keep]) + 1
    rr, rbin = iv.expand_ranges(r_lo, r_hi)
    r_order = np.argsort(rbin, kind="stable")
    rr, rbin_sorted = rr[r_order], rbin[r_order]
    try:
        for b in spill.touched_bins():
            lo = np.searchsorted(rbin_sorted, b)
            hi = np.searchsorted(rbin_sorted, b, "right")
            if lo == hi:
                continue
            rsel = r_keep[rr[lo:hi]]
            reads, ids = spill.read_bin(b)
            pl, pr = iv.overlap_join(
                reads.contig, reads.start, reads.end,
                right.contig[rsel], right.start[rsel], right.end[rsel],
            )
            if len(pl) == 0:
                continue
            gl, gr = ids[pl], rsel[pr]
            _, bstart, bend = bins.dedupe_region(int(b))
            keep = (
                (reads.start[pl] >= bstart) & (reads.start[pl] < bend)
            ) | (
                (right.start[gr] >= bstart) & (right.start[gr] < bend)
            )
            if keep.any():
                yield gl[keep], gr[keep]
    finally:
        spill.cleanup()
