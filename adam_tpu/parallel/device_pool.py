"""Per-window device pool: round-robin dispatch across attached chips.

The streamed flagship (pipelines/streamed.py) drives its per-residue
passes as asynchronous device dispatches, but until this module existed
every dispatch landed on ``jax.devices()[0]`` — one chip did all the
work while the other attached devices idled (the MULTICHIP dry-runs
attach 8).  Windows are independent until the two global barriers, so
their device work can fan out: window *i*'s markdup reductions, observe
scatter-adds and apply table-gathers run on device ``i % n`` while the
single host core keeps doing what only it can (tokenize / encode /
write).

Three pieces:

* :class:`DevicePool` — resolves the device set (``--devices N`` /
  ``ADAM_TPU_DEVICES``, capped at what is attached), hands out the
  round-robin device for a window, and places host arrays onto it
  (``jax.device_put`` commits the inputs, so the jit dispatch follows
  them to the chip).
* **Compile prewarm** — :meth:`DevicePool.prewarm` compiles the
  grid-quantized kernel set once per device, concurrently, *before*
  the first window's device work.  Cold remote compiles cost 20-40 s
  each (docs/PERF.md) and the jit executable cache is keyed per
  device, so without this every chip after the first would pay its
  compiles inside a timed window.  A process-wide cache dedupes:
  re-running the pipeline in the same process (the bench's warmup ->
  timed-run pattern) skips already-warm (kernel, shape, device) triples.
* **Merge shape** — the pool never merges anything itself: per-device
  observe histograms and markdup columns flow back through the same
  compact per-window parts the single-chip path uses, and the merge
  barriers sum them host-side (``bqsr.merge_observations`` fetches each
  part from whichever device holds it).  This is the host-side analog
  of ``parallel/dist.distributed_observe``'s psum — same reduction, no
  mesh required, bitwise order-stable because parts merge in window
  order.

The pool is only engaged by the ``device`` backend with ``n > 1``; the
``n == 1`` case returns ``None`` from :func:`make_pool` and the caller
keeps its single-device path untouched.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence

import numpy as np

from adam_tpu.utils import faults
from adam_tpu.utils import health as health_mod
from adam_tpu.utils import retry as retry_mod
from adam_tpu.utils import telemetry as tele

log = logging.getLogger(__name__)


class AllDevicesEvicted(RuntimeError):
    """Every pool device has been evicted; callers fall back to the
    ``native``/``numpy`` host backend (bit-identical by the backend
    parity contract, tests/test_backend_parity.py)."""

#: Process-wide prewarm cache: (entry key, device id) triples already
#: compiled+invoked.  Keyed per device because the jit executable cache
#: is — warming device 0 does nothing for device 3.
_PREWARMED: set = set()
_PREWARM_LOCK = threading.Lock()


def reset_prewarm_cache() -> None:
    """Test hook: forget which (kernel, shape, device) triples are warm."""
    with _PREWARM_LOCK:
        _PREWARMED.clear()


def resolve_device_count(requested: Optional[int] = None) -> int:
    """How many devices the streamed pipeline should fan out over.

    Order: explicit ``requested`` (the ``--devices`` flag), then
    ``ADAM_TPU_DEVICES``, then every attached device.  Always capped at
    the attached count and floored at 1; a request beyond the topology
    is capped with a warning, not an error (the same command line must
    work on an 8-chip pod and a 1-chip dev box).  Only an explicit
    ``requested < 1`` raises — a malformed env value (non-int, zero,
    negative) warns and falls back to all attached, the same degradation
    every other ``ADAM_TPU_*`` tuning var gets: an env typo must not
    crash a pipeline mid-run.
    """
    if requested is not None and requested < 1:
        raise ValueError(f"--devices must be >= 1 (got {requested})")
    if requested is None:
        raw = os.environ.get("ADAM_TPU_DEVICES", "").strip()
        if raw:
            try:
                requested = int(raw)
            except ValueError:
                requested = None
            if requested is not None and requested < 1:
                requested = None
            if requested is None:
                log.warning(
                    "ADAM_TPU_DEVICES=%r is not a positive int; using all "
                    "attached devices", raw,
                )
    import jax

    try:
        # local_devices, not devices: in a multi-process run this host
        # may only address a slice of the global topology, and the pool
        # must never round-robin onto a chip it cannot drive
        attached = len(jax.local_devices())
    except Exception:
        attached = 1
    if requested is None:
        return max(1, attached)
    if requested > attached:
        log.warning(
            "--devices %d requested but only %d attached; using %d",
            requested, attached, attached,
        )
    return max(1, min(requested, attached))


def _attr_id(dev):
    """The span ``device=`` attribution value for one device: its jax
    id, falling back to ``str(dev)`` — never None, so attribution can't
    silently drop out of the ``device_spans`` aggregation or the
    per-chip Chrome-trace tracks on an exotic backend."""
    dev_id = getattr(dev, "id", None)
    return dev_id if dev_id is not None else str(dev)


# Thread-local replay depth: the streamed recovery paths enter a
# replay_scope() around each replayed window, so every nested dispatch/
# fetch span — in ANY layer, without threading a flag through the bqsr/
# markdup APIs — picks up a ``replay=1`` attr from span_attrs and
# aggregates under the survivor's ``<k>:replay`` device_spans key
# instead of conflating with its organic work.
_REPLAY_TLS = threading.local()


class replay_scope:
    """Marks the current thread as replaying an evicted device's window
    (reentrant; see :func:`span_attrs`)."""

    def __enter__(self):
        _REPLAY_TLS.depth = getattr(_REPLAY_TLS, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _REPLAY_TLS.depth -= 1
        return False


def in_replay() -> bool:
    """True while the current thread is inside a :class:`replay_scope`."""
    return getattr(_REPLAY_TLS, "depth", 0) > 0


def span_attrs(device=None) -> dict:
    """Span attrs for a dispatch/fetch call site: ``{}`` on the
    single-device path (no attribution noise), ``{"device": <id>}``
    otherwise — plus ``replay=1`` inside a :class:`replay_scope`, so
    replayed work aggregates under ``<k>:replay`` and never conflates
    with the survivor's own occupancy.  The one helper every layer
    (markdup, bqsr, streamed) shares, so per-chip attribution cannot
    diverge between passes."""
    if device is None:
        return {}
    attrs = {"device": _attr_id(device)}
    if in_replay():
        attrs["replay"] = 1
    return attrs


def putter(device=None):
    """The host->device placement callable every dispatch site shares:
    ``jnp.asarray`` (default device, uncommitted — the single-chip
    behavior) when ``device`` is None, else a committed
    ``jax.device_put`` onto the given chip so the following jit call
    dispatches there.

    Every placement is also the h2d half of the transfer ledger: bytes
    counted from the host array, wall from the put call (submit-side —
    device_put may complete the copy asynchronously, so the throughput
    histogram is a lower bound on transfer time, not an upper; the
    byte totals are exact either way), attributed to the target device
    and the active :func:`~adam_tpu.utils.telemetry.pass_scope`."""
    if device is None:
        import jax.numpy as jnp

        base = jnp.asarray
        dev_id = None
    else:
        import jax

        def base(x, _dev=device):
            return jax.device_put(x, _dev)

        dev_id = _attr_id(device)

    def put(x):
        if not tele.TRACE.recording:
            return base(x)
        t0 = time.monotonic()
        out = base(x)
        tele.TRACE.record_transfer(
            "h2d", getattr(x, "nbytes", 0), time.monotonic() - t0,
            device=dev_id,
        )
        return out

    return put


def donation_ok(device=None) -> bool:
    """Whether jit buffer donation actually pays on ``device`` (the
    default device when None): CPU runtimes ignore ``donate_argnums``
    with a warning, so the donating jit variants — distinct executables
    — are only built, warmed and dispatched on real accelerators.  The
    ONE donation decision shared by every dispatch site AND the prewarm
    entry builders, so the two can never pick different variants (which
    would cold-compile the dispatched twin inside a window)."""
    try:
        if device is not None:
            return getattr(device, "platform", "cpu") != "cpu"
        import jax

        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def resident_windows_enabled(default: bool = True) -> bool:
    """Resolve the ``ADAM_TPU_RESIDENT`` toggle for device-resident
    windows: ``auto``/unset -> ``default`` (on wherever the device
    backend runs), ``1/on/true`` and ``0/off/false`` force; a typo
    warns and keeps the default (``utils/retry.env_toggle``, the shared
    tuning-var contract — same parser as ``ADAM_TPU_PACKED_COLS``).

    Precedence (documented in docs/PERF.md "Device-resident windows"):
    the backend decides first (``ADAM_TPU_BQSR_BACKEND`` — residency
    exists only under ``device``; host backends have no device to be
    resident on), then ``--partitioner``/``ADAM_TPU_PARTITIONER``
    decides the placement SHAPE (pool: per-device pinned; mesh: one
    batch-sharded placement), and this toggle last decides whether
    windows stay resident at all — off forces the legacy
    re-ship-per-pass path on either partitioner.  ``ADAM_TPU_PACKED_COLS``
    composes the same way: it gates what pass C *fetches* (packed
    columns vs the [N, L] matrix), residency gates what pass A/B/C
    *ship*, and the bases half of the packed tail needs both on."""
    from adam_tpu.utils.retry import env_toggle

    return env_toggle("ADAM_TPU_RESIDENT", default)


class ResidentWindow:
    """One window's ingest-resident device payload: the five arrays
    every per-residue pass reads (``bases``/``quals`` [g, gl] and
    ``lengths``/``flags``/``read_group_idx`` [g], grid-padded), placed
    host->device ONCE when the window is tokenized and dispatched
    against by markdup keys (pass A), BQSR observe (pass B) and the
    recalibration apply (pass C) — the ingest-once H2D contract
    (docs/PERF.md "Device-resident windows"): the ledger's per-pass h2d
    collapses to one ``ingest`` entry per window, and the later passes
    ship only their genuinely per-pass inputs (bit-packed MD masks,
    post-split validity bools).

    The duplicate flags resolved at barrier 1 mutate only the HOST
    batch — safe, because the device kernels read ``flags`` solely for
    the orientation bits (reverse/paired/second-of-pair), which markdup
    never changes; duplicate-dependent filtering enters through the
    per-pass ``read_ok`` mask computed host-side from the updated
    flags.  The same reasoning covers the pass-B candidate split: it
    MASKS rows (geometry preserved), and the updated ``valid``/
    ``has_qual`` bools ship per pass.

    **Refcounted release**: the streamed pipeline holds ONE base
    reference per handle and releases it after the window's pass-C
    fetch, so HBM frees window by window instead of at run end — all
    passes run on the single driver thread and jax pins the buffers of
    in-flight executions internally, so no current consumer needs more
    than that one reference.  :meth:`retain` exists for a consumer
    that must pin the handle across a genuinely concurrent boundary
    (none does today — wire it before adding one).  :meth:`drop` is the
    fault path (device evicted, mesh degraded): the handle dies, every
    later dispatch falls back to re-shipping from the host-retained
    ingest copy (``pipelines/streamed.py`` keeps each window's decoded
    batch until its part publishes — the replay source of truth,
    docs/ROBUSTNESS.md)."""

    FIELDS = ("bases", "quals", "lengths", "flags", "read_group_idx")

    def __init__(self, window: int, device, arrays: dict, g: int,
                 gl: int, nbytes: int):
        self.window = window
        self.device = device  # a jax device, None (default), or "mesh"
        self.g = g
        self.gl = gl
        self.nbytes = nbytes
        self._arrays = arrays
        self._refs = 1
        self._consumed = False
        self._lock = threading.Lock()

    @property
    def alive(self) -> bool:
        with self._lock:
            return self._arrays is not None and not self._consumed

    def get(self, name: str):
        """The resident device array for FIELD ``name`` (raises once
        released/dropped — callers check :attr:`alive` first)."""
        with self._lock:
            if self._arrays is None:
                raise RuntimeError(
                    f"resident window {self.window} already released"
                )
            return self._arrays[name]

    def args(self) -> tuple:
        """The five resident arrays in kernel-signature order."""
        return tuple(self.get(f) for f in self.FIELDS)

    def mark_consumed(self) -> None:
        """A donating dispatch consumed the bases/quals buffers: the
        handle stops offering them (a retry after a partial donating
        execution must re-ship from the host copy, never re-pass a
        deleted buffer)."""
        with self._lock:
            self._consumed = True

    def retain(self) -> None:
        with self._lock:
            if self._arrays is not None:
                self._refs += 1

    def release(self) -> bool:
        """Drop one reference; True when this call freed the arrays."""
        with self._lock:
            if self._arrays is None:
                return False
            self._refs -= 1
            if self._refs > 0:
                return False
            self._arrays = None
            return True

    def drop(self) -> bool:
        """Force-release regardless of refcount (eviction / mesh
        degradation); True when the arrays were still held."""
        with self._lock:
            held = self._arrays is not None
            self._arrays = None
            self._refs = 0
            return held


def make_resident_window(b, window: int, device=None) -> ResidentWindow:
    """Place one window's resident payload on ``device`` (the pool
    path's pinned placement; ``None`` = the single-chip default
    device).  ``b`` is the window batch's numpy view; arrays pad to the
    (pow2-rows, lane-aligned) grid — exactly the pads the markdup/
    observe/apply dispatches would have shipped per pass.  Callers wrap
    this in ``telemetry.pass_scope("ingest")`` so the h2d ledger books
    the one placement under the ingest bucket."""
    from adam_tpu.formats import schema
    from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np

    g = grid_rows(b.n_rows)
    gl = grid_cols(b.lmax)
    _put = putter(device)
    host = {
        "bases": pad_rows_np(b.bases, g, schema.BASE_PAD, cols=gl),
        "quals": pad_rows_np(b.quals, g, schema.QUAL_PAD, cols=gl),
        "lengths": pad_rows_np(b.lengths, g, 0),
        "flags": pad_rows_np(b.flags, g, schema.FLAG_UNMAPPED),
        "read_group_idx": pad_rows_np(b.read_group_idx, g, -1),
    }
    nbytes = sum(int(a.nbytes) for a in host.values())
    arrays = {k: _put(a) for k, a in host.items()}
    return ResidentWindow(window, device, arrays, g, gl, nbytes)


class DevicePool:
    """Round-robin window -> device placement over an explicit device set.

    ``pool.device(i)`` is the device for window ``i`` (``i % n``);
    ``pool.put(tree, i)`` commits host arrays onto it so the following
    jit call dispatches there.  Per-device occupancy/skew reporting
    comes from the ``device=<id>`` span attribution (the snapshot's
    ``device_spans`` section), not from pool-side counters.

    Placement is a pure function of the caller's index — the pool keeps
    no dispatch history.  That statelessness is what both recovery
    layers lean on: eviction replay re-asks for a window's device and
    simply receives the next survivor, and a durable RESUME
    (``pipelines/checkpoint.RunJournal``) that skips journaled windows
    never perturbs where the remaining windows land, because nothing
    here depends on which windows were actually dispatched.  The
    bit-identity invariant never rests on placement anyway (the barrier
    merges are window-ordered and the backends are parity twins), so
    skipped windows, evictions and resumes compose freely.
    """

    def __init__(self, devices: Optional[Sequence] = None,
                 limit: Optional[int] = None):
        import jax

        devs = (
            list(devices) if devices is not None
            else list(jax.local_devices())
        )
        if limit is not None:
            devs = devs[: max(1, limit)]
        if not devs:
            raise ValueError("DevicePool needs at least one device")
        self.devices = devs
        # eviction state: self.devices stays the full original set (so
        # per-device replicas like pass C's dev_tables keep stable
        # indices); round-robin placement runs over the survivors
        self._dead: set = set()
        # live leases (the multi-job scheduler's per-job handles);
        # shares the eviction lock — both are rare-path bookkeeping
        self._leases: set = set()
        self._evict_lock = threading.Lock()
        # the process-wide device-health scoreboard (utils/health.py):
        # placement consults it (probation devices are excluded until
        # their re-admission probe passes), eviction informs it.  One
        # board across pools/leases — health is a hardware property.
        self.health = health_mod.BOARD

    # ---- multi-tenant leasing (adam_tpu/serve) -------------------------
    def lease(self, job: Optional[str] = None) -> "PoolLease":
        """A job-scoped handle onto this shared pool (see
        :class:`PoolLease`).  The pool tracks live leases only so the
        scheduler can PROVE a finished or quarantined job holds no
        devices — placement itself stays stateless."""
        lease = PoolLease(self, job=job)
        with self._evict_lock:
            self._leases.add(lease)
        return lease

    def _drop_lease(self, lease: "PoolLease") -> None:
        with self._evict_lock:
            self._leases.discard(lease)

    def active_leases(self) -> list:
        """Live leases, for the scheduler's status view."""
        with self._evict_lock:
            return list(self._leases)

    @property
    def n(self) -> int:
        """The configured fan-out (evictions do not shrink it — it is
        the stats/queue-depth constant, not the live device count)."""
        return len(self.devices)

    # ---- eviction + health --------------------------------------------
    def survivors(self) -> list:
        """Devices not hard-evicted (health-filter-free): the prewarm
        set — probation devices keep their executables warm so a
        re-admitted chip never cold-compiles inside a window."""
        with self._evict_lock:
            return [
                d for d in self.devices if _device_key(d) not in self._dead
            ]

    def alive_devices(self) -> list:
        """The PLACEABLE device set: survivors minus health-blocked
        (probation/evicted on the scoreboard) chips.  Availability
        beats health: when the scoreboard would empty the set, the
        blocked survivors serve anyway — a poolwide false alarm must
        degrade observability, not the run (the SDC audit still guards
        the pass-C payload those devices produce)."""
        alive = self.survivors()
        if len(alive) <= 1:
            return alive
        ok = [d for d in alive if not self.health.blocked(d)]
        return ok if ok else alive

    def evict(self, device, reason: str = "", tracer=None) -> bool:
        """Remove a failed device from round-robin placement.

        Returns True when this call actually evicted (False: already
        dead, or ``device`` is None — the single-chip default-device
        path has nothing to evict).  Counts ``device.evicted`` on
        ``tracer`` (the streamed run tracer, so the count lands in the
        ``--metrics-json`` snapshot) or the global TRACE.
        """
        if device is None:
            return False
        key = _device_key(device)
        with self._evict_lock:
            if key in self._dead:
                return False
            self._dead.add(key)
            left = len(self.devices) - len(self._dead)
        log.error(
            "evicting device %s after spent retry budget%s; %d of %d "
            "pool device(s) remain%s", key,
            f" ({reason})" if reason else "", left, len(self.devices),
            "" if left else " — falling back to the host backend",
        )
        (tracer if tracer is not None else tele.TRACE).count(
            tele.C_DEVICE_EVICTED
        )
        self.health.mark_evicted(device, tracer=tracer)
        return True

    def _maybe_probe(self, tracer=None) -> None:
        """Run due re-admission probes (probation devices whose
        cooldown elapsed): a passing known-answer dispatch re-admits
        the chip into placement, a failing one graduates it to a real
        eviction.  The ``probe_maybe_due`` fast path is one lock-free
        clock compare — taken BEFORE building the survivor set, so the
        per-window placement call stays cheap."""
        if not self.health.probe_maybe_due():
            return
        survivors = self.survivors()
        # claim only THIS pool's devices: a foreign probation device's
        # cooldown must stay claimable by the pool that can probe it
        due = set(self.health.due_probes(survivors))
        if not due:
            return
        for dev in survivors:
            if _device_key(dev) not in due:
                continue
            if health_mod.probe_known_answer(dev):
                self.health.readmit(dev, tracer=tracer)
            else:
                self.health.probe_failed(dev, tracer=tracer)
                self.evict(dev, reason="re-admission probe failed",
                           tracer=tracer)

    def device_index(self, window: int) -> int:
        """Index of window's device in the ORIGINAL pool order (stable
        under eviction — per-device replicas are keyed by it)."""
        return self.devices.index(self.device(window))

    def device(self, window: int):
        self._maybe_probe()
        alive = self.alive_devices()
        if not alive:
            raise AllDevicesEvicted(
                f"all {len(self.devices)} pool devices evicted"
            )
        return alive[window % len(alive)]

    def device_id(self, window: int):
        """The span ``device=<id>`` attribution value for window's
        device (consistent across every layer via :func:`span_attrs`'s
        ``_attr_id``; on a single host the ids are the pool ordinals)."""
        return _attr_id(self.device(window))

    def put(self, tree, window: int):
        """Commit a pytree of host arrays onto window's device
        (through :func:`putter`, so the h2d ledger sees every leaf)."""
        import jax

        return jax.tree.map(putter(self.device(window)), tree)

    # ---- compile prewarm ----------------------------------------------
    def prewarm(self, entries: Sequence[tuple], tracer=None) -> int:
        """Compile the kernel set on every pool device, concurrently.

        ``entries``: ``(key, fn)`` pairs where ``key`` names a
        (kernel, grid-quantized shape) combination and ``fn(device)``
        builds dummy device-resident args and invokes the kernel to
        completion (populating the per-device jit executable cache).
        Each (key, device) triple compiles **exactly once per process**
        — the bench's warmup run pays the cold compiles, the timed run's
        prewarm finds everything warm and is a no-op.  Returns the
        number of (entry, device) compiles actually performed; spans
        carry ``device=<k>`` attribution and land in ``tracer`` (the
        streamed run tracer) so the telemetry snapshot proves the
        compiles happened outside the timed windows.
        """
        from adam_tpu.utils import compile_ledger

        tr = tracer if tracer is not None else tele.TRACE
        todo: list[tuple] = []
        claimed: set = set()
        with _PREWARM_LOCK:
            # claim under the lock so concurrent prewarms don't compile
            # the same triple twice; a failed compile DISCARDS its claim
            # below — a transient compile/RPC failure must stay
            # retryable, or the next run pays the cold compile inside a
            # timed window with no signal.  Evicted devices are skipped;
            # health-PROBATION devices are still warmed (survivors, not
            # alive_devices) so a probe re-admission never cold-compiles
            # inside the window it rejoins on.  Replayed windows
            # re-prewarm on survivors via the same process-wide cache
            # (already-warm triples dedupe to no-ops).
            # the dedupe key carries the kernel backend (like the
            # compile ledger): an XLA-warmed shape says nothing about
            # the pallas executable of the same shape
            backend = compile_ledger.active_backend()
            for key, fn in entries:
                for dev in self.survivors():
                    cache_key = (key, _device_key(dev), backend)
                    if cache_key not in _PREWARMED and cache_key not in claimed:
                        claimed.add(cache_key)
                        todo.append((key, fn, dev, cache_key))
                    else:
                        # already warm in this process: re-seed the
                        # compile ledger, whose claim a faulted run's
                        # raising dispatch may have handed back
                        compile_ledger.claim(key, dev)
            _PREWARMED.update(claimed)
        if not todo:
            return 0

        def _one(item):
            key, fn, dev, cache_key = item

            def compile_once():
                faults.point("pool.prewarm", device=dev)
                fn(dev)

            from adam_tpu.utils import compile_ledger

            try:
                with tr.span(
                    tele.SPAN_POOL_PREWARM_COMPILE,
                    device=_attr_id(dev), kernel=str(key[0]),
                ), compile_ledger.prewarm_scope(), \
                        tele.pass_scope("prewarm"), \
                        compile_ledger.track(key, dev):
                    # transient compile/RPC failures retry in place
                    # (exponential backoff) before degrading to the
                    # warn-and-compile-in-window fallback below.  The
                    # compile-ledger claim inside the prewarm scope is
                    # what lets the first REAL dispatch of this triple
                    # record a cache hit — and an in-window miss at a
                    # dispatch site is, by elimination, a shape the
                    # prewarm never covered (the coverage boundary).
                    retry_mod.retry_call(
                        compile_once, site="device.pool.prewarm"
                    )
            except Exception:
                # prewarm is purely an optimization: a transient
                # compile/RPC failure must not abort a run that would
                # otherwise succeed (the shape just compiles in-window
                # later).  Discard the claim so a future prewarm retries.
                with _PREWARM_LOCK:
                    _PREWARMED.discard(cache_key)
                log.warning(
                    "prewarm of %s on device %s failed; the shape will "
                    "compile at first dispatch instead",
                    key, _device_key(dev), exc_info=True,
                )
                return 0
            tr.count(tele.C_POOL_PREWARM_COMPILES)
            return 1

        # one thread per device: the compiles are remote-service RPCs
        # (GIL released), so n devices' 20-40 s cold compiles overlap
        # instead of serializing into an n * 30 s stall
        with ThreadPoolExecutor(max_workers=self.n) as ex:
            return sum(ex.map(_one, todo))


class PoolLease:
    """One job's handle onto a shared :class:`DevicePool`.

    The multi-job transform service (``adam_tpu/serve``) runs N
    concurrent streamed jobs against ONE pool; each job receives a
    lease instead of the pool itself.  The lease is interface-identical
    to the pool for everything the streamed pipeline touches
    (``device``/``device_index``/``put``/``prewarm``/``evict``/
    ``alive_devices``/``devices``/``n``) and adds exactly two things:

    * **attribution** — ``job`` labels eviction log lines, so a shared
      chip dying under tenant A's dispatch is debuggable;
    * **release bookkeeping** — :meth:`release` returns the lease to
      the pool (idempotent; called by the scheduler when the job
      reaches any terminal state, quarantine included), so
      ``DevicePool.active_leases`` can prove a quarantined job holds
      no devices.

    Eviction itself stays SHARED: a chip that spent one tenant's retry
    budget is dead hardware for every tenant, and each job replays only
    its own in-flight windows through its own recovery paths — the
    fault-isolation contract (docs/ROBUSTNESS.md) needs no per-lease
    device state for that, precisely because placement is stateless.
    """

    def __init__(self, pool: DevicePool, job: Optional[str] = None):
        self._pool = pool
        self.job = job
        self._released = threading.Event()

    # ---- pool interface (duck-typed by pipelines/streamed.py) ----------
    @property
    def devices(self) -> list:
        return self._pool.devices

    @property
    def n(self) -> int:
        return self._pool.n

    def alive_devices(self) -> list:
        return self._pool.alive_devices()

    def device(self, window: int):
        return self._pool.device(window)

    def device_index(self, window: int) -> int:
        return self._pool.device_index(window)

    def device_id(self, window: int):
        return self._pool.device_id(window)

    def put(self, tree, window: int):
        return self._pool.put(tree, window)

    def prewarm(self, entries: Sequence[tuple], tracer=None) -> int:
        return self._pool.prewarm(entries, tracer=tracer)

    def evict(self, device, reason: str = "", tracer=None) -> bool:
        if self.job and device is not None:
            reason = f"job {self.job}: {reason}" if reason else (
                f"job {self.job}"
            )
        return self._pool.evict(device, reason=reason, tracer=tracer)

    # ---- lease lifecycle ----------------------------------------------
    @property
    def released(self) -> bool:
        return self._released.is_set()

    def release(self) -> None:
        """Return this lease to the pool (idempotent)."""
        if not self._released.is_set():
            self._released.set()
            self._pool._drop_lease(self)


def _device_key(dev) -> str:
    """Stable per-device cache key (id is unique within a process).
    Delegates to :func:`adam_tpu.utils.health.device_key` — the ONE
    key vocabulary shared by the prewarm cache, the eviction set and
    the health scoreboard; a divergence would silently stop the
    board's placement filter from matching pool devices (health.py
    cannot import this module, hence the direction)."""
    return health_mod.device_key(dev)


def make_pool(requested: Optional[int] = None) -> Optional[DevicePool]:
    """Resolve the device count and build a pool — or ``None`` for the
    single-device topologies, so callers fall back to the existing
    single-chip path with zero behavior change."""
    n = resolve_device_count(requested)
    if n <= 1:
        return None
    return DevicePool(limit=n)


# --------------------------------------------------------------------------
# Hedged dispatch (docs/ROBUSTNESS.md "Device health, hedging, and SDC
# audit"): rescue an in-flight window from a straggler chip.
# --------------------------------------------------------------------------
def hedged_call(primary_fn, hedge_fn, threshold_s: float, tracer=None):
    """Run ``primary_fn()`` on a watchdog thread; if it is still in
    flight after ``threshold_s`` (the kernel's
    ``ADAM_TPU_HEDGE_FACTOR`` × p99, Dean & Barroso's hedged-request
    discipline), run ``hedge_fn()`` — the same window re-dispatched on
    another alive device from the host-retained ingest copy — on the
    calling thread.  **First completed result wins**; output is
    byte-identical either way because the kernels are deterministic
    parity twins, so the race decides latency, never bytes.

    Returns ``(result, winner, fired)`` where ``winner`` is
    ``"primary"`` (hedge never fired, or fired and lost) or
    ``"hedge"``, and ``fired`` whether the speculative dispatch
    launched (so callers can keep hedge-inflated walls out of their
    latency statistics).  Counters: ``device.hedge.fired`` when the
    hedge launches, ``device.hedge.won`` when its result is used,
    ``device.hedge.wasted`` when the primary beat it (fired = won +
    wasted).  A hedge that RAISES falls back to waiting out the
    primary — hedging is an optimization and must never turn a slow
    window into a failed one; a primary that raises after a losing
    hedge surfaces its own error to the caller's normal recovery path
    (a primary error swallowed by a WINNING hedge is deliberate: the
    window was rescued, and a genuinely sick chip keeps feeding the
    scoreboard through its other signals).
    """
    tr = tracer if tracer is not None else tele.TRACE
    box: list = []
    done = threading.Event()
    # the primary runs on a helper thread, which carries none of the
    # caller's thread-local telemetry pass/trace scopes — capture and
    # re-enter them there, so the transfer ledger's per-pass attribution
    # (and the fault grammar's pass= selector) see the same pass the
    # un-hedged call would have, and the primary's spans stay stamped
    # with the caller's job trace
    caller_pass = tele.current_pass()
    caller_trace = tele.current_trace()

    def run_primary():
        try:
            with tele.trace_scope(caller_trace):
                if caller_pass is not None:
                    with tele.pass_scope(caller_pass):
                        box.append((True, primary_fn()))
                else:
                    box.append((True, primary_fn()))
        except BaseException as e:  # noqa: BLE001 — relayed below
            box.append((False, e))
        done.set()

    t = threading.Thread(target=run_primary, daemon=True,
                         name="hedge-primary")
    t.start()
    if done.wait(threshold_s):
        ok, val = box[0]
        if ok:
            return val, "primary", False
        raise val
    # the primary is officially late: speculate
    tr.count(tele.C_HEDGE_FIRED)
    from adam_tpu.utils import incidents

    incidents.maybe_record(
        "hedge.fired", trace_id=caller_trace or tr.trace, tracer=tr,
        reason="in-flight window exceeded its %.3fs hedge threshold"
               % threshold_s,
    )
    try:
        hedged = hedge_fn()
    except Exception as e:
        log.warning(
            "hedged re-dispatch failed (%s); waiting out the primary", e,
        )
        # the speculative attempt was launched and discarded: it counts
        # as wasted, keeping fired == won + wasted even on this path
        tr.count(tele.C_HEDGE_WASTED)
        done.wait()
        ok, val = box[0]
        if ok:
            return val, "primary", True
        raise val
    if done.is_set() and box and box[0][0]:
        # the primary finished while the hedge computed: first result
        # wins, the speculative copy is the wasted one
        tr.count(tele.C_HEDGE_WASTED)
        return box[0][1], "primary", True
    tr.count(tele.C_HEDGE_WON)
    return hedged, "hedge", True


# --------------------------------------------------------------------------
# Streamed-pipeline kernel set
# --------------------------------------------------------------------------
def dummy_like(field, shape, fill=0) -> np.ndarray:
    """Prewarm dummy argument: ``fill`` at ``shape`` in FIELD's dtype —
    shapes AND dtypes must match the real dispatches bit-for-bit or the
    jit cache treats the warm call as a different program.  Shared by
    every prewarm entry builder (here and the mesh entries in
    parallel/partitioner.py), so a kernel-signature change has one
    dummy-construction idiom to keep in sync."""
    dt = np.asarray(field).dtype
    return np.full(shape, fill, dtype=dt)


# Per-kernel dummy argument tuples — THE single source of truth for
# each kernel's prewarm signature, shared by the pool entries below and
# the mesh entries in parallel/partitioner.py (which only differ in the
# row count ``g``: the mesh pads it to a device-count multiple).  A
# kernel-signature change edits exactly one of these.
def markdup_dummy_args(b, g: int, gl: int, gc: int) -> tuple:
    """markdup_columns_local's 8 args at grid (g rows, gc cigar ops,
    gl lanes)."""
    from adam_tpu.formats import schema

    _z = dummy_like
    return (
        _z(b.start, (g,), -1), _z(b.end, (g,), -1),
        _z(b.flags, (g,), schema.FLAG_UNMAPPED),
        _z(b.cigar_ops, (g, gc), schema.CIGAR_PAD),
        _z(b.cigar_lens, (g, gc)), _z(b.cigar_n, (g,)),
        _z(b.quals, (g, gl), schema.QUAL_PAD), _z(b.lengths, (g,)),
    )


def observe_dummy_args(b, g: int, gl: int) -> tuple:
    """observe_kernel's 8 array args at grid (g rows, gl lanes) —
    static (n_rg, gl) follow at the call site."""
    from adam_tpu.formats import schema

    _z = dummy_like
    return (
        _z(b.bases, (g, gl), schema.BASE_PAD),
        _z(b.quals, (g, gl), schema.QUAL_PAD),
        _z(b.lengths, (g,)),
        _z(b.flags, (g,), schema.FLAG_UNMAPPED),
        _z(b.read_group_idx, (g,), -1),
        np.zeros((g, gl), bool), np.zeros((g, gl), bool),
        np.zeros((g,), bool),
    )


def apply_dummy_args(b, g: int, gl: int) -> tuple:
    """apply_table_kernel's 7 per-row args at grid (g rows, gl lanes) —
    the u8 table dummy (shape depends on the solved width) and static
    gl follow at the call site."""
    from adam_tpu.formats import schema

    _z = dummy_like
    return (
        _z(b.bases, (g, gl), schema.BASE_PAD),
        _z(b.quals, (g, gl), schema.QUAL_PAD),
        _z(b.lengths, (g,)),
        _z(b.flags, (g,), schema.FLAG_UNMAPPED),
        _z(b.read_group_idx, (g,), -1),
        np.zeros((g,), bool), np.zeros((g,), bool),
    )


def streamed_prewarm_entries(
    b, n_rg: int, *, mark_duplicates: bool = True, recalibrate: bool = True,
    packed_apply: bool = False, resident: bool = False,
    fused_n_cyc: int | None = None,
) -> list[tuple]:
    """The grid-quantized kernel set the streamed device path dispatches,
    as prewarm entries derived from the first window's numpy view ``b``
    (shapes AND dtypes must match the real dispatches bit-for-bit or the
    jit cache treats the warm call as a different program).

    Covers: markdup [N, L] key/score reductions (pass A), the BQSR
    observe scatter-add (pass B), and the apply table-gather (pass C).
    ``resident=True`` warms the resident-window variants the passes
    actually dispatch — the bit-packed-mask observe, the fused
    bases+quals pack2 apply, and (where :func:`donation_ok`) the
    donating twins — ALONGSIDE the plain kernels, which stay warm as
    the replay/fallback path.  ``fused_n_cyc`` (the known table's cycle
    width) additionally warms the fused B→C megakernel the known-table
    tier dispatches (docs/PERF.md "Megakernel tier").
    """
    import jax

    from adam_tpu.formats.batch import (
        grid_cigar_cols, grid_cols, grid_rows,
    )

    g = grid_rows(b.n_rows)
    gl = grid_cols(b.lmax)
    gc = grid_cigar_cols(
        b.cigar_ops.shape[1] if b.cigar_ops.ndim == 2 else 1
    )

    entries: list[tuple] = []
    if mark_duplicates:
        def warm_markdup(dev, g=g, gl=gl, gc=gc):
            from adam_tpu.pipelines.markdup import get_columns_jit

            args = tuple(
                jax.device_put(a, dev)
                for a in markdup_dummy_args(b, g, gl, gc)
            )
            jax.block_until_ready(get_columns_jit()(*args))
            if resident and donation_ok(dev):
                # the resident dispatch donates its per-pass start/
                # n_ops temporaries — a distinct executable
                jax.block_until_ready(get_columns_jit(donate=True)(*(
                    jax.device_put(a, dev)
                    for a in markdup_dummy_args(b, g, gl, gc)
                )))

        entries.append((("markdup.columns", g, gc, gl), warm_markdup))

    if recalibrate:
        def warm_observe(dev, g=g, gl=gl):
            from adam_tpu.pipelines.bqsr import observe_kernel

            out = observe_kernel(*(
                jax.device_put(a, dev)
                for a in observe_dummy_args(b, g, gl)
            ), n_rg, gl)
            jax.block_until_ready(out)

        entries.append((("bqsr.observe", g, gl, n_rg), warm_observe))
        if resident:
            entries.append(observe_packed_prewarm_entry(b, n_rg))
        # pass A can only assume the solved table will match window 0's
        # grid width; pass C re-warms with the REAL merged width via
        # apply_prewarm_entry (same key space, so uniform-lmax inputs
        # dedupe it to a no-op)
        if packed_apply and resident:
            entries.append(_apply_entry(
                b, n_rg, g, gl, 2 * gl + 1, pack=True, resident=True
            ))
        if packed_apply:
            # the quals-only pack stays warm on resident runs too: a
            # residency miss (evicted handle) re-dispatches through it
            entries.append(
                _apply_entry(b, n_rg, g, gl, 2 * gl + 1, pack=True)
            )
        # the plain gather stays warm even on packed runs: the
        # eviction replay path re-applies with pack=False on a
        # survivor, and that dispatch must never cold-compile inside
        # the window it is rescuing
        entries.append(_apply_entry(
            b, n_rg, g, gl, 2 * gl + 1, resident=resident
        ))
        if fused_n_cyc is not None and resident:
            # the fused B→C megakernel, at the KNOWN table's real
            # cycle width (never 2*gl+1: the known table's geometry is
            # the cohort's, not this window's)
            entries.append(fused_bc_prewarm_entry(b, n_rg, fused_n_cyc))
    return entries


def _apply_entry(b, n_rg: int, g: int, gl: int, n_cyc: int,
                 pack: bool = False, resident: bool = False) -> tuple:
    import jax

    def warm_apply(dev):
        from adam_tpu.pipelines.bqsr import N_DINUC, N_QUAL, jit_variant

        def placed_args():
            args = apply_dummy_args(b, g, gl) + (
                np.zeros((n_rg, N_QUAL, n_cyc, N_DINUC), np.uint8),
            )
            return tuple(jax.device_put(a, dev) for a in args)

        donate = resident and donation_ok(dev)
        if pack and resident:
            kinds = ["apply_pack2"]
        elif pack:
            kinds = ["apply_pack"]
        else:
            kinds = ["apply"]
        for kind in kinds:
            if kind == "apply":
                out = jit_variant(kind, donate)(*placed_args(), gl)
            else:
                out = jit_variant(kind, donate)(*placed_args(), gl, g * gl)
            jax.block_until_ready(out)
            if donate:
                # the non-donating twin stays warm beside it: a
                # consumed-handle retry re-dispatches without donation
                if kind == "apply":
                    out = jit_variant(kind, False)(*placed_args(), gl)
                else:
                    out = jit_variant(kind, False)(
                        *placed_args(), gl, g * gl
                    )
                jax.block_until_ready(out)

    # literal key tuples (not one with a computed kernel name): the
    # dispatch-ledger rule's prewarm cross-check parses these literals
    if pack and resident:
        return (("bqsr.apply_pack2", g, gl, n_rg, n_cyc), warm_apply)
    if pack:
        return (("bqsr.apply_pack", g, gl, n_rg, n_cyc), warm_apply)
    return (("bqsr.apply", g, gl, n_rg, n_cyc), warm_apply)


def observe_packed_prewarm_entry(b, n_rg: int) -> tuple:
    """Prewarm entry for the resident-window observe variant: the
    bit-packed-mask kernel (``bqsr.observe_packed_body``), donating its
    mask temporaries where :func:`donation_ok`."""
    import jax

    from adam_tpu.formats.batch import grid_cols, grid_rows

    g = grid_rows(b.n_rows)
    gl = grid_cols(b.lmax)

    def warm_observe_packed(dev, g=g, gl=gl):
        from adam_tpu.pipelines.bqsr import jit_variant

        def placed_args():
            base = observe_dummy_args(b, g, gl)
            # the packed-mask signature: masks ride bit-packed u8
            args = base[:5] + (
                np.zeros((g, gl // 8 + (1 if gl % 8 else 0)), np.uint8),
                np.zeros((g, gl // 8 + (1 if gl % 8 else 0)), np.uint8),
                base[7],
            )
            return tuple(jax.device_put(a, dev) for a in args)

        donate = donation_ok(dev)
        out = jit_variant("observe_packed", donate)(
            *placed_args(), n_rg, gl
        )
        jax.block_until_ready(out)
        if donate:
            out = jit_variant("observe_packed", False)(
                *placed_args(), n_rg, gl
            )
            jax.block_until_ready(out)

    return (("bqsr.observe_packed", g, gl, n_rg), warm_observe_packed)


def fused_bc_dummy_args(b, g: int, gl: int) -> tuple:
    """fused_bc_body's 10 per-row args at grid (g rows, gl lanes) —
    the observe signature's resident five + bit-packed masks + read
    filter, then the apply side's ``has_qual``/``valid``; the u8 table
    dummy and the statics (n_rg, gl, g*gl) follow at the call site."""
    base = observe_dummy_args(b, g, gl)
    npk = gl // 8 + (1 if gl % 8 else 0)
    return base[:5] + (
        np.zeros((g, npk), np.uint8), np.zeros((g, npk), np.uint8),
        base[7],
        np.zeros((g,), bool), np.zeros((g,), bool),
    )


def fused_bc_prewarm_entry(b, n_rg: int, table_n_cyc: int) -> tuple:
    """Prewarm entry for the fused B→C megakernel
    (``bqsr.fused_bc_body``) keyed by the known table's real cycle
    width — dispatched when the recalibration table is available at
    ingest (known-sites runs, discovered-table resumes).  Warms the
    donating twin where :func:`donation_ok` plus the plain twin beside
    it (a consumed-handle retry re-dispatches without donation)."""
    import jax

    from adam_tpu.formats.batch import grid_cols, grid_rows

    g = grid_rows(b.n_rows)
    gl = grid_cols(b.lmax)

    def warm_fused_bc(dev, g=g, gl=gl):
        from adam_tpu.pipelines.bqsr import N_DINUC, N_QUAL, jit_variant

        def placed_args():
            args = fused_bc_dummy_args(b, g, gl) + (
                np.zeros(
                    (n_rg, N_QUAL, table_n_cyc, N_DINUC), np.uint8
                ),
            )
            return tuple(jax.device_put(a, dev) for a in args)

        donate = donation_ok(dev)
        out = jit_variant("fused_bc", donate)(
            *placed_args(), n_rg, gl, g * gl
        )
        jax.block_until_ready(out)
        if donate:
            out = jit_variant("fused_bc", False)(
                *placed_args(), n_rg, gl, g * gl
            )
            jax.block_until_ready(out)

    return (("bqsr.fused_bc", g, gl, n_rg, table_n_cyc), warm_fused_bc)


def apply_prewarm_entry(b, n_rg: int, table_n_cyc: int,
                        pack: bool = False,
                        resident: bool = False) -> tuple:
    """Pass-C re-warm entry: the apply table-gather keyed by the SOLVED
    table's real cycle width.  ``merge_observations`` widens the table
    to the maximum window grid, which can exceed the window-0 width the
    pass-A prewarm assumed — without this, every device would pay the
    apply compile inside pass C on variable-length inputs.  Shares the
    pass-A entry's key space, so the uniform-lmax common case dedupes
    to a no-op against the process-wide cache.  ``pack=True`` warms the
    fused apply+pack kernel (the packed-column pass-C dispatch);
    ``resident=True`` selects the resident-window variants (the
    bases+quals pack2 when packed, and the donating twins where
    :func:`donation_ok`)."""
    from adam_tpu.formats.batch import grid_cols, grid_rows

    return _apply_entry(
        b, n_rg, grid_rows(b.n_rows), grid_cols(b.lmax), table_n_cyc,
        pack=pack, resident=resident,
    )


def observe_prewarm_entry(b, n_rg: int) -> tuple:
    """Observe-only prewarm entry at one batch view's grid shape — the
    long-tail re-warm hook: residual windows and the realigned tail
    part land on grids window 0 never saw (the measured grid-1024
    0.26 s in-window cold compile, docs/PERF.md), so the streamed
    pipeline re-prewarms on first sight of a new shape through the same
    process-wide dedupe cache (already-warm shapes are free)."""
    import jax

    from adam_tpu.formats.batch import grid_cols, grid_rows

    g = grid_rows(b.n_rows)
    gl = grid_cols(b.lmax)

    def warm_observe(dev, g=g, gl=gl):
        from adam_tpu.pipelines.bqsr import observe_kernel

        out = observe_kernel(*(
            jax.device_put(a, dev) for a in observe_dummy_args(b, g, gl)
        ), n_rg, gl)
        jax.block_until_ready(out)

    return (("bqsr.observe", g, gl, n_rg), warm_observe)


# --------------------------------------------------------------------------
# Realign sweep fan-out: weighted round-robin over the pool/mesh devices
# --------------------------------------------------------------------------
#: Process-wide probe cache: device key -> TFLOP/s (one probe per
#: device per process; the probe kernel compiles once and is tiny).
_PROBE_TFLOPS: dict = {}
_PROBE_LOCK = threading.Lock()


def probe_device_tflops(device) -> float:
    """One small timed f32 matmul on ``device`` -> TFLOP/s (cached per
    process).  A deliberately light sibling of bench.py's 4096³ probe:
    it only needs RELATIVE skew between time-sliced chips to pace the
    sweep scheduler, not an absolute ceiling."""
    key = _device_key(device)
    with _PROBE_LOCK:
        got = _PROBE_TFLOPS.get(key)
    if got is not None:
        return got
    try:
        import jax
        import jax.numpy as jnp

        n = 1024
        a = jax.device_put(jnp.ones((n, n), jnp.float32), device)
        jax.block_until_ready(a @ a)  # compile + first run
        # best-of-3: a single timed rep caught mid-stall on a
        # time-sliced chip would mislabel the device for the whole
        # process (the cache below is permanent) and skew the sweep
        # schedule WORSE than plain round-robin
        best_dt = float("inf")
        for _ in range(3):
            t0 = time.monotonic()
            jax.block_until_ready(a @ a)
            best_dt = min(best_dt, max(time.monotonic() - t0, 1e-9))
        tf = 2 * n**3 / best_dt / 1e12
    except Exception:
        # NOT cached: a transient probe error must not permanently
        # disable probe pacing for the process — the next schedule
        # construction re-probes
        return 0.0
    with _PROBE_LOCK:
        _PROBE_TFLOPS[key] = tf
    return tf


def sweep_weights(devices) -> list[float]:
    """Relative throughput weight per device for the sweep scheduler.

    Order: ``ADAM_TPU_SWEEP_TFLOPS`` (comma-separated floats — feed a
    bench artifact's ``per_device_probe_tflops`` straight in; entry k
    weights device **id** k, so eviction-shrunk device lists still pace
    the right chips; ids past the list fall back to the mean, malformed
    values degrade to equal weights), then a one-time in-process matmul
    probe on accelerator devices, then equal weights (virtual-CPU test
    meshes are symmetric by construction — probing them measures
    scheduler noise).
    """
    n = len(devices)
    raw = os.environ.get("ADAM_TPU_SWEEP_TFLOPS", "").strip()
    if raw:
        try:
            vals = [float(v) for v in raw.split(",") if v.strip()]
            if vals and all(v > 0 for v in vals):
                mean = sum(vals) / len(vals)
                # match by device ID, not list position: after an
                # eviction the caller passes the SURVIVORS, and a
                # positional match would pace every chip with its dead
                # neighbor's weight
                out = []
                for i, d in enumerate(devices):
                    dev_id = getattr(d, "id", i)
                    out.append(
                        vals[dev_id]
                        if isinstance(dev_id, int) and 0 <= dev_id < len(vals)
                        else mean
                    )
                return out
        except ValueError:
            pass
        log.warning(
            "ADAM_TPU_SWEEP_TFLOPS=%r is not a comma list of positive "
            "floats; using equal weights", raw,
        )
        return [1.0] * n
    if any(getattr(d, "platform", "cpu") != "cpu" for d in devices):
        probed = [probe_device_tflops(d) for d in devices]
        if all(v > 0 for v in probed):
            return probed
    return [1.0] * n


class SweepSchedule:
    """Deterministic deficit round-robin over a device set: chunk ``k``
    goes to the device with the largest accumulated credit
    (``weight share × chunks seen − chunks assigned``), so a chip with
    2× the probe throughput receives 2× the sweep chunks.  Equal
    weights degrade to plain round-robin.  Placement never affects the
    sweep VALUES (each chunk is self-contained), so pacing is free to
    chase the grant skew run by run."""

    def __init__(self, devices, weights=None):
        self.devices = list(devices)
        w = list(weights) if weights is not None else sweep_weights(
            self.devices
        )
        total = sum(w) or 1.0
        self._share = [v / total for v in w]
        self._credit = [0.0] * len(self.devices)

    def next_device(self):
        for i, s in enumerate(self._share):
            self._credit[i] += s
        i = max(range(len(self._credit)), key=lambda k: self._credit[k])
        self._credit[i] -= 1.0
        return self.devices[i]
