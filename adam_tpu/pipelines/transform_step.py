"""The fused device "transform step" — adam_tpu's flagship kernel.

One jit region covering the per-batch device work of the reference's
flagship ``transform`` pipeline (adam-cli Transform.scala:101-163):
duplicate-marking keys and scores, BQSR observation + recalibration, and
flagstat metrics — everything that does not require host-side strings.
This is what the single-chip compile check and the multi-chip dry run
drive, and the unit the benchmark times.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch
from adam_tpu.ops import cigar as cigar_ops
from adam_tpu.ops import flagstat as fs
from adam_tpu.pipelines import bqsr


@partial(jax.jit, static_argnames=("n_rg", "lmax"))
def transform_step(batch: ReadBatch, residue_ok, is_mismatch,
                   n_rg: int, lmax: int):
    """-> (recalibrated ReadBatch, aux dict of device stats).

    Stages (all fused under one jit):
      1. markdup device columns: 5'-clipped positions + phred>=15 scores
      2. BQSR observe: dense covariate histogram scatter-add
      3. BQSR recalibrate: log-space delta-stack gather
      4. flagstat mask reductions
    """
    flags = batch.flags
    read_ok = (
        batch.valid
        & ((flags & schema.FLAG_UNMAPPED) == 0)
        & ((flags & (schema.FLAG_SECONDARY | schema.FLAG_SUPPLEMENTARY)) == 0)
        & ((flags & schema.FLAG_DUPLICATE) == 0)
        & ((flags & schema.FLAG_FAILED_QC) == 0)
        & batch.has_qual
        & (batch.mapq > 0)
        & (batch.mapq != 255)
    )

    five_prime = cigar_ops.five_prime_position(
        batch.start, batch.end, flags, batch.cigar_ops, batch.cigar_lens,
        batch.cigar_n,
    )
    in_read = jnp.arange(lmax)[None, :] < batch.lengths[:, None]
    dup_score = jnp.sum(
        jnp.where(in_read & (batch.quals >= 15), batch.quals, 0).astype(jnp.int32),
        axis=1,
    )

    total, mism = bqsr.observe_kernel.__wrapped__(
        batch.bases, batch.quals, batch.lengths, flags,
        batch.read_group_idx, residue_ok, is_mismatch, read_ok, n_rg, lmax,
    )
    new_quals = bqsr.recalibrate_kernel.__wrapped__(
        batch.bases, batch.quals, batch.lengths, flags,
        batch.read_group_idx, batch.has_qual, batch.valid, total, mism, lmax,
    )
    failed, passed = fs.flagstat_device.__wrapped__(batch)
    out = batch.replace(quals=new_quals)
    aux = dict(
        five_prime=five_prime,
        dup_score=dup_score,
        obs_total=total,
        obs_mism=mism,
        flagstat=(failed, passed),
    )
    return out, aux


def synthetic_batch(n_reads: int = 2048, read_len: int = 100,
                    n_contigs: int = 4, seed: int = 0) -> ReadBatch:
    """Random mapped reads for compile checks and benchmarks."""
    rng = np.random.default_rng(seed)
    bases = rng.integers(0, 4, size=(n_reads, read_len), dtype=np.uint8)
    quals = rng.integers(2, 41, size=(n_reads, read_len), dtype=np.uint8)
    lengths = np.full(n_reads, read_len, np.int32)
    flags = np.where(rng.random(n_reads) < 0.5, 0, 16).astype(np.int32)
    contig = rng.integers(0, n_contigs, n_reads).astype(np.int32)
    start = rng.integers(0, 1_000_000, n_reads).astype(np.int64)
    cigar_ops_arr = np.full((n_reads, 4), schema.CIGAR_PAD, np.uint8)
    cigar_lens = np.zeros((n_reads, 4), np.int32)
    cigar_ops_arr[:, 0] = schema.CIGAR_M
    cigar_lens[:, 0] = read_len
    return ReadBatch(
        bases=bases,
        quals=quals,
        lengths=lengths,
        flags=flags,
        contig_idx=contig,
        start=start,
        end=start + read_len,
        mapq=np.full(n_reads, 60, np.int32),
        cigar_ops=cigar_ops_arr,
        cigar_lens=cigar_lens,
        cigar_n=np.ones(n_reads, np.int32),
        mate_contig_idx=np.full(n_reads, -1, np.int32),
        mate_start=np.full(n_reads, -1, np.int64),
        tlen=np.zeros(n_reads, np.int32),
        read_group_idx=np.zeros(n_reads, np.int32),
        has_qual=np.ones(n_reads, bool),
        valid=np.ones(n_reads, bool),
    )


def synthetic_masks(batch: ReadBatch, mismatch_rate: float = 0.01, seed: int = 1):
    """Residue masks standing in for the MD-derived columns."""
    rng = np.random.default_rng(seed)
    n, L = batch.bases.shape
    residue_ok = (np.asarray(batch.quals) > 0) & (np.asarray(batch.bases) < 4)
    is_mm = rng.random((n, L)) < mismatch_rate
    return residue_ok, is_mm
