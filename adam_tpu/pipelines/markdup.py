"""Duplicate marking.

Picard-style semantics matching ``rdd/read/MarkDuplicates.scala:66-128``:

1. Bucket reads by (record group, read name) — SingleReadBucket
   (models/SingleReadBucket.scala:30-42).
2. Key each bucket by its 5'-clipped position pair —
   ReferencePositionPair (models/ReferencePositionPair.scala:30-52):
   read1 position is the first first-of-pair read's 5' position (strand
   included); unmapped reads key by their *sequence* so identical
   unplaced pairs group; fragments have no read2 position.
3. Group by (library, left position); within a group, subgroup by right
   position; in each pair-subgroup keep the highest bucket score
   (sum of quals >= 15 over primary reads, :45-47) unmarked — its
   secondaries are still marked — and mark everything else; a
   fragment-subgroup is wholly marked when pair-subgroups co-exist at the
   same left position; unmapped reads are never marked.

TPU formulation: 5' keys and bucket scores are device kernels (fused
CIGAR walks + masked segment sums); the group-subgroup-argmax cascade
becomes one lexsort + run-boundary scan over the bucket table (no
hash shuffles), vectorized in numpy on host today — the same
sort-and-segment shape the distributed path shards by genome position.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch
from adam_tpu.ops import cigar as cigar_ops


@jax.jit
def _device_read_columns(b: ReadBatch):
    """Per-read device kernels: 5' position and quality score."""
    five_prime = cigar_ops.five_prime_position(
        b.start, b.end, b.flags, b.cigar_ops, b.cigar_lens, b.cigar_n
    )
    in_read = jnp.arange(b.lmax)[None, :] < b.lengths[:, None]
    score = jnp.sum(
        jnp.where(in_read & (b.quals >= 15), b.quals, 0).astype(jnp.int32), axis=1
    )
    return five_prime, score


def _bucket_ids(ds: AlignmentDataset) -> tuple[np.ndarray, int]:
    """(rg, name) -> dense bucket id per row (-1 for invalid rows)."""
    b = ds.batch.to_numpy()
    ids = np.full(b.n_rows, -1, dtype=np.int64)
    table: dict[tuple[int, str], int] = {}
    for i in range(b.n_rows):
        if not b.valid[i]:
            continue
        key = (int(b.read_group_idx[i]), ds.sidecar.names[i])
        ids[i] = table.setdefault(key, len(table))
    return ids, len(table)


def mark_duplicates(ds: AlignmentDataset) -> AlignmentDataset:
    b = ds.batch.to_numpy()
    n = b.n_rows
    if n == 0:
        return ds
    five_prime, read_score = jax.tree.map(
        np.asarray, _device_read_columns(ds.batch.to_device())
    )

    bucket_of, n_buckets = _bucket_ids(ds)
    if n_buckets == 0:
        return ds

    flags = np.asarray(b.flags)
    valid = np.asarray(b.valid)
    mapped = (flags & schema.FLAG_UNMAPPED) == 0
    primary = (flags & (schema.FLAG_SECONDARY | schema.FLAG_SUPPLEMENTARY)) == 0
    first = (flags & schema.FLAG_FIRST_OF_PAIR) != 0
    second = (flags & schema.FLAG_SECOND_OF_PAIR) != 0
    reverse = (flags & schema.FLAG_REVERSE) != 0

    # ----- per-bucket left/right keys (ReferencePositionPair.apply) -----
    # Key encoding: (kind, contig_or_hash, pos, strand); kind 0 = none,
    # 1 = mapped position, 2 = sequence-keyed (unmapped read).
    NONE_KEY = (0, 0, 0, 0)

    def read_key(i) -> tuple[int, int, int, int]:
        if mapped[i]:
            return (1, int(b.contig_idx[i]), int(five_prime[i]), int(reverse[i]))
        seq = schema.decode_bases(b.bases[i], int(b.lengths[i]))
        return (2, hash(seq) & 0x7FFFFFFFFFFFFFFF, 0, 0)

    # candidate rows per bucket, in row order (primaryMapped ++ unmapped)
    bucket_first = [[] for _ in range(n_buckets)]
    bucket_second = [[] for _ in range(n_buckets)]
    bucket_frag = [[] for _ in range(n_buckets)]
    bucket_score = np.zeros(n_buckets, dtype=np.int64)
    for i in range(n):
        bid = bucket_of[i]
        if bid < 0:
            continue
        if mapped[i] and primary[i]:
            bucket_score[bid] += int(read_score[i])
        candidate = (mapped[i] and primary[i]) or not mapped[i]
        if not candidate:
            continue
        if first[i]:
            bucket_first[bid].append(i)
        elif second[i]:
            bucket_second[bid].append(i)
        bucket_frag[bid].append(i)  # every candidate (primaryMapped ++ unmapped)

    left_keys = []
    right_keys = []
    for bid in range(n_buckets):
        # primaryMapped ++ unmapped ordering: mapped-primary candidates first
        def ordered(rows):
            return sorted(rows, key=lambda i: (not mapped[i], 0))

        firsts = ordered(bucket_first[bid])
        seconds = ordered(bucket_second[bid])
        if firsts or seconds:
            lk = read_key(firsts[0]) if firsts else NONE_KEY
            rk = read_key(seconds[0]) if seconds else NONE_KEY
        else:
            frags = ordered(bucket_frag[bid])
            lk = read_key(frags[0]) if frags else NONE_KEY
            rk = NONE_KEY
        left_keys.append(lk)
        right_keys.append(rk)

    # library per bucket (library of the first read in the bucket)
    lib_ids = ds.read_groups.library_ids() if len(ds.read_groups) else np.array([], np.int32)
    bucket_lib = np.full(n_buckets, -1, dtype=np.int64)
    for i in range(n):
        bid = bucket_of[i]
        if bid >= 0 and bucket_lib[bid] == -1:
            rg = int(b.read_group_idx[i])
            bucket_lib[bid] = lib_ids[rg] if rg >= 0 else -1

    # ----- group by (library, left), subgroup by right, mark -----
    left_arr = np.array(left_keys, dtype=np.int64)  # [B, 4]
    right_arr = np.array(right_keys, dtype=np.int64)
    group_order = np.lexsort(
        tuple(right_arr[:, k] for k in range(3, -1, -1))
        + tuple(left_arr[:, k] for k in range(3, -1, -1))
        + (bucket_lib,)
    )

    primary_dup = np.zeros(n_buckets, dtype=bool)
    secondary_dup = np.zeros(n_buckets, dtype=bool)

    go = group_order
    sl = np.concatenate([bucket_lib[go, None], left_arr[go]], axis=1)
    sr = right_arr[go]
    new_left = np.ones(len(go), dtype=bool)
    new_left[1:] = (sl[1:] != sl[:-1]).any(axis=1)
    new_right = new_left.copy()
    new_right[1:] |= (sr[1:] != sr[:-1]).any(axis=1)
    left_starts = np.flatnonzero(new_left)
    left_ends = np.append(left_starts[1:], len(go))
    for s, e in zip(left_starts, left_ends):
        rows = go[s:e]
        if left_arr[rows[0], 0] == 0:  # left position None: never duplicates
            continue
        sub_starts = np.flatnonzero(new_right[s:e]) + s
        sub_ends = np.append(sub_starts[1:], e)
        group_count = len(sub_starts)
        for ss, se in zip(sub_starts, sub_ends):
            sub = go[ss:se]
            group_is_fragments = right_arr[sub[0], 0] == 0
            only_fragments = group_is_fragments and group_count == 1
            if only_fragments or not group_is_fragments:
                # keep the highest score; first wins ties (stable order)
                best = sub[np.argmax(bucket_score[sub])]
                primary_dup[sub] = True
                primary_dup[best] = False
                secondary_dup[sub] = True
            else:
                primary_dup[sub] = True
                secondary_dup[sub] = True

    # ----- apply to reads -----
    row_bucket = np.clip(bucket_of, 0, None)
    dup = np.where(
        mapped & primary,
        primary_dup[row_bucket],
        np.where(mapped, secondary_dup[row_bucket], False),
    )
    dup &= valid & (bucket_of >= 0)
    new_flags = np.where(
        dup, flags | schema.FLAG_DUPLICATE, flags & ~schema.FLAG_DUPLICATE
    ).astype(np.int32)
    return ds.with_batch(ds.batch.to_numpy().replace(flags=new_flags))
