"""Duplicate marking.

Picard-style semantics matching ``rdd/read/MarkDuplicates.scala:66-128``:

1. Bucket reads by (record group, read name) — SingleReadBucket
   (models/SingleReadBucket.scala:30-42).
2. Key each bucket by its 5'-clipped position pair —
   ReferencePositionPair (models/ReferencePositionPair.scala:30-52):
   read1 position is the first first-of-pair read's 5' position (strand
   included); unmapped reads key by their *sequence* so identical
   unplaced pairs group; fragments have no read2 position.
3. Group by (library, left position); within a group, subgroup by right
   position; in each pair-subgroup keep the highest bucket score
   (sum of quals >= 15 over primary reads, :45-47) unmarked — its
   secondaries are still marked — and mark everything else; a
   fragment-subgroup is wholly marked when pair-subgroups co-exist at the
   same left position; unmapped reads are never marked.

TPU formulation: 5' keys and bucket scores are vectorized per-window
(masked CIGAR walks + masked segment sums) so they pipeline with ingest;
the group-subgroup-argmax cascade is one lexsort + run-boundary scan
over the *global* bucket table (no hash shuffles).  The split is the
same shape the sharded path uses: compact per-row summaries travel,
[N, L] matrices never do.  No per-read Python anywhere.
"""

from __future__ import annotations

import threading

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.strings import StringColumn
from adam_tpu.ops import cigar as cigar_ops
from adam_tpu.utils.transfer import device_fetch


def markdup_columns_local(
    start, end, flags, ops, lens, n_ops, quals, lengths
):
    """[N, L] duplicate-marking reductions for one (device-resident)
    batch slice -> (five_prime i64[N], score i32[N]).

    Traceable body shared by the single-chip jit wrapper below and the
    mesh ``shard_map`` variant (parallel/dist.distributed_markdup) — the
    5'-clipped key via the device CIGAR walk, the bucket score via a
    masked segment sum.  Only these compact per-row columns ever cross
    the device link; the group-subgroup-argmax cascade stays host-side.
    """
    import jax.numpy as jnp

    five = cigar_ops.five_prime_position(start, end, flags, ops, lens, n_ops)
    in_read = jnp.arange(quals.shape[1])[None, :] < lengths[:, None]
    score = jnp.where(in_read & (quals >= 15), quals, 0).sum(
        axis=1, dtype=jnp.int32
    )
    return five, score


_COLUMNS_JITS: dict = {}  # donate -> lazily-built module-level jit
_COLUMNS_JIT_LOCK = threading.Lock()


def get_columns_jit(donate: bool = False):
    """The module-level jit of :func:`markdup_columns_local` (built
    lazily; shared by the dispatch below and the device pool's prewarm
    so both hit the same executable cache).  Locked: the prewarm calls
    this from one thread per device, and a lost race here would warm a
    discarded wrapper whose executable cache the real dispatches never
    see.  ``donate=True`` is the resident-window variant: with quals/
    lengths/flags read from the window's ingest-resident arrays, the
    per-pass ``start`` temporary (i64[g], the only shipped input whose
    buffer the i64[g] ``five`` output can alias) is donated —
    dispatched only where ``device_pool.donation_ok`` says the runtime
    honors it, and warmed by the same decision."""
    key = bool(donate)
    jit = _COLUMNS_JITS.get(key)
    if jit is None:
        with _COLUMNS_JIT_LOCK:
            jit = _COLUMNS_JITS.get(key)
            if jit is None:
                import jax

                jit = jax.jit(
                    markdup_columns_local,
                    **({"donate_argnums": (0,)} if donate else {}),
                )
                _COLUMNS_JITS[key] = jit
    return jit


def markdup_columns_dispatch(batch, device=None, mesh=None, resident=None):
    """Dispatch the [N, L] markdup reductions on a device -> lazy
    (five, score) device arrays for the batch's real rows.

    Row-padded to the pow2 grid so the compile cache sees a handful of
    shapes; the streamed pipeline dispatches window i+1 here while
    window i's columns are being fetched/summarized (double buffer).
    ``device``: an explicit jax device to commit the inputs to (the
    multi-chip pool's round-robin target); ``None`` keeps the default
    device, exactly the single-chip behavior.  ``mesh``: a
    :class:`~adam_tpu.parallel.partitioner.MeshPartitioner` — the
    [N, L] arrays shard over its ``batch`` axis and every device works
    the same window (SPMD), bitwise the single-chip columns.
    ``resident``: the window's ingest-resident device payload
    (``device_pool.ResidentWindow``) — quals/lengths/flags dispatch
    straight off the handle and only the markdup-specific start/end/
    cigar columns ship; a dead or mismatched handle falls back to the
    legacy re-ship below, bitwise the same columns."""
    from adam_tpu.formats.batch import (
        grid_cigar_cols, grid_cols, grid_rows, pad_rows_np,
    )
    from adam_tpu.parallel.device_pool import (
        donation_ok, putter, span_attrs,
    )
    from adam_tpu.utils import faults
    from adam_tpu.utils import retry as _retry
    from adam_tpu.utils import telemetry as _tele

    _put = putter(device)
    attrs = {"device": "mesh"} if mesh is not None else span_attrs(device)
    with _tele.TRACE.span(
        _tele.SPAN_MD_COLUMNS, backend="device",
        reads=int(batch.n_rows), **attrs,
    ):
        b = batch.to_numpy()
        n = b.n_rows
        g = grid_rows(n)
        # quantize BOTH axes, not just rows: windows differ in lmax and
        # max cigar-op count, and every distinct shape is a fresh
        # trace+compile serialized inside pass A's ingest loop (the
        # walks mask by lengths/cigar_n, so the padding lanes are inert)
        gl = grid_cols(b.lmax)
        gc = grid_cigar_cols(
            b.cigar_ops.shape[1] if b.cigar_ops.ndim == 2 else 1
        )

        if mesh is not None:
            from adam_tpu.utils import compile_ledger

            gm = mesh.rows_for(g)
            rw = resident
            if rw is not None and not (
                rw.alive and rw.device == "mesh"
                and rw.g == gm and rw.gl == gl
            ):
                rw = None

            def dispatch_mesh():
                faults.point("device.dispatch")
                fresh = (
                    pad_rows_np(b.start, gm, -1),
                    pad_rows_np(b.end, gm, -1),
                    pad_rows_np(b.cigar_ops, gm, schema.CIGAR_PAD,
                                cols=gc),
                    pad_rows_np(b.cigar_lens, gm, 0, cols=gc),
                    pad_rows_np(b.cigar_n, gm, 0),
                )
                if rw is not None and rw.alive:
                    return mesh.markdup_window_resident(rw, fresh)
                return mesh.markdup_window((
                    fresh[0], fresh[1],
                    pad_rows_np(b.flags, gm, schema.FLAG_UNMAPPED),
                    fresh[2], fresh[3], fresh[4],
                    # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                    pad_rows_np(b.quals, gm, schema.QUAL_PAD, cols=gl),
                    pad_rows_np(b.lengths, gm, 0),
                ))

            with compile_ledger.track(
                ("mesh.markdup", gm, gc, gl), mesh.ledger_key()
            ):
                five, score = _retry.retry_call(
                    dispatch_mesh, site="markdup.dispatch"
                )
            return five[:n], score[:n]

        rw = resident
        if rw is not None and not (
            rw.alive and rw.device is device and rw.g == g and rw.gl == gl
        ):
            rw = None

        def dispatch():
            # the device_put + jit call is the RPC pair that fails
            # transiently on a tunneled chip; the whole unit re-runs on
            # a retry (device_put is idempotent — a fresh commit; the
            # donated start temporary is re-placed every attempt, so a
            # half-run donating call can never re-pass a dead buffer)
            faults.point("device.dispatch", device=device)
            start = _put(pad_rows_np(b.start, g, -1))
            end = _put(pad_rows_np(b.end, g, -1))
            ops = _put(pad_rows_np(b.cigar_ops, g, schema.CIGAR_PAD,
                                   cols=gc))
            lens = _put(pad_rows_np(b.cigar_lens, g, 0, cols=gc))
            n_ops = _put(pad_rows_np(b.cigar_n, g, 0))
            if rw is not None and rw.alive:
                return get_columns_jit(donate=donation_ok(device))(
                    start, end, rw.get("flags"), ops, lens, n_ops,
                    rw.get("quals"), rw.get("lengths"),
                )
            return get_columns_jit()(
                start, end,
                _put(pad_rows_np(b.flags, g, schema.FLAG_UNMAPPED)),
                ops, lens, n_ops,
                # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                _put(pad_rows_np(b.quals, g, schema.QUAL_PAD, cols=gl)),
                _put(pad_rows_np(b.lengths, g, 0)),
            )

        from adam_tpu.utils import compile_ledger

        # compile-ledger key == the prewarm entry key for this kernel:
        # a miss here is a shape the prewarm never covered, cold-
        # compiling inside pass A's ingest loop
        with compile_ledger.track(("markdup.columns", g, gc, gl), device):
            five, score = _retry.retry_call(
                dispatch, site="markdup.dispatch"
            )
        return five[:n], score[:n]


def markdup_columns_device(batch):
    """Blocking variant of :func:`markdup_columns_dispatch` -> host
    (five i64[N], score i32[N])."""
    five, score = markdup_columns_dispatch(batch)
    return device_fetch(five), device_fetch(score)


def _sequence_hashes(bases: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Deterministic per-read sequence hash (unmapped-read grouping key).

    Polynomial over base codes; identical sequences (incl. length) hash
    equal — the role of the reference's sequence hashCode key for
    unplaced pairs (models/ReferencePositionPair.scala:43-51).
    """
    n, L = bases.shape
    rng = np.random.default_rng(0xADA5)
    w = rng.integers(1, 2**62, size=L, dtype=np.int64) | 1
    codes = bases.astype(np.int64) + 1
    h = (codes * w[None, :]).sum(axis=1)
    h = h ^ (lengths.astype(np.int64) * np.int64(0x9E3779B97F4A7C15 - (1 << 64)))
    return h & 0x7FFFFFFFFFFFFFFF


def row_summary(ds: AlignmentDataset, b=None, five_prime=None,
                score=None) -> dict:
    """Compact per-row duplicate-marking summary (host numpy).

    Everything :func:`resolve_duplicates` needs, and nothing [N, L]-
    shaped except the transient masked reductions here: the 5'-clipped
    position, the quality score, the row key columns, the bucket key
    inputs (read-group, name bytes), and the library id.  Windows of a
    streamed ingest each produce one of these; :func:`concat_summaries`
    splices them for the global resolve.  Pass ``b`` when the batch is
    already fetched to numpy — a device-resident batch is copied across
    the link exactly once — and ``five_prime``/``score`` when the [N, L]
    reductions already ran on the mesh (parallel/dist.distributed_markdup).
    """
    if b is None:
        b = ds.batch.to_numpy()
    n = b.n_rows
    if five_prime is None:
        five_prime = cigar_ops.five_prime_position_np(
            b.start, b.end, b.flags, b.cigar_ops, b.cigar_lens, b.cigar_n
        )
    if score is None:
        quals = np.asarray(b.quals)
        in_read = np.arange(b.lmax)[None, :] < np.asarray(b.lengths)[:, None]
        score = np.where(in_read & (quals >= 15), quals, 0).sum(
            axis=1, dtype=np.int32
        )

    flags = np.asarray(b.flags)
    valid = np.asarray(b.valid)
    mapped = (flags & schema.FLAG_UNMAPPED) == 0

    # per-row candidate keys (ReferencePositionPair.apply):
    # (kind, contig_or_hash, pos, strand); kind 0 = none, 1 = mapped
    # position, 2 = sequence-keyed (unmapped).  Only unmapped rows
    # consume the sequence hash — skip the O(N*L) polynomial for the
    # (typical) mostly-mapped batch.
    seq_hash = np.zeros(n, dtype=np.int64)
    um = np.flatnonzero(~mapped)
    if len(um):
        seq_hash[um] = _sequence_hashes(
            np.asarray(b.bases)[um], np.asarray(b.lengths)[um]
        )
    reverse = (flags & schema.FLAG_REVERSE) != 0
    row_key = np.zeros((n, 4), dtype=np.int64)
    row_key[:, 0] = np.where(mapped, 1, 2)
    row_key[:, 1] = np.where(mapped, np.asarray(b.contig_idx), seq_hash)
    row_key[:, 2] = np.where(mapped, five_prime, 0)
    row_key[:, 3] = np.where(mapped, reverse.astype(np.int64), 0)

    lib_ids = (
        ds.read_groups.library_ids()
        if len(ds.read_groups)
        else np.array([], np.int32)
    )
    rgidx = np.asarray(b.read_group_idx)
    lib_per_row = np.where(
        rgidx >= 0,
        lib_ids[np.clip(rgidx, 0, None)] if len(lib_ids) else -1,
        -1,
    ).astype(np.int64)

    return dict(
        flags=flags,
        valid=valid,
        score=score,
        row_key=row_key,
        rg_idx=rgidx.astype(np.int64),
        lib_per_row=lib_per_row,
        name_bytes=StringColumn.of(ds.sidecar.names).to_fixed_bytes(),
    )


def concat_summaries(parts: list[dict]) -> dict:
    """Splice window summaries into one global summary (names re-padded
    to a common byte width so the fixed-width unique stays exact)."""
    if len(parts) == 1:
        return parts[0]
    w = max(p["name_bytes"].dtype.itemsize for p in parts)
    dt = np.dtype(f"S{max(w, 1)}")
    out = {}
    for k in parts[0]:
        cols = [p[k] for p in parts]
        if k == "name_bytes":
            cols = [c.astype(dt) for c in cols]
        out[k] = np.concatenate(cols)
    return out


def _unique_inverse_fixed_bytes(names: np.ndarray) -> np.ndarray:
    """``np.unique(names, return_inverse=True)[1]`` for fixed-width byte
    names, via big-endian integer views when the width allows.

    memcmp order on null-padded fixed-width bytes == numeric order of
    the big-endian word(s), so the inverse ids are IDENTICAL to the
    S-dtype unique's — just ~4x faster (integer radix-ish sort instead
    of string compares; this was the single hottest step of the global
    duplicate resolve on a 1M-read input)."""
    n = len(names)
    w = names.dtype.itemsize
    if n == 0 or w > 16:
        return np.unique(names, return_inverse=True)[1]
    nw = 8 if w <= 8 else 16
    padded = np.zeros((n, nw), np.uint8)
    padded[:, :w] = names.view(np.uint8).reshape(n, w)
    words = padded.view(">u8").astype(np.uint64)
    if nw == 8:
        return np.unique(words[:, 0], return_inverse=True)[1]
    hi, lo = words[:, 0], words[:, 1]
    order = np.lexsort((lo, hi))
    sh, sl = hi[order], lo[order]
    new = np.ones(n, bool)
    new[1:] = (sh[1:] != sh[:-1]) | (sl[1:] != sl[:-1])
    inv = np.empty(n, np.int64)
    inv[order] = np.cumsum(new) - 1
    return inv


def resolve_duplicates(s: dict, sort_device=None,
                       sort_info: dict | None = None) -> np.ndarray:
    """Global group-subgroup-argmax cascade over row summaries -> bool[N]
    duplicate mask.  One lexsort over the bucket table; row order across
    windows is the tie-break order, matching the reference's partition
    concatenation.

    ``sort_device`` routes the 9-key lexsort cascade — the measured
    1.56 s pure-host serial stage of the streamed barrier (BENCH_r05
    ``resolve_s``) — through the device sort of the packed summary keys
    (:func:`adam_tpu.parallel.dist.device_lexsort`; bitwise the host
    permutation, host fallback on any failure).  ``None`` keeps the
    host ``np.lexsort``; pass a jax device (the pool/mesh's device 0)
    or the string ``"default"`` for the default device.  ``sort_info``
    receives ``{"device_sort": bool}`` — whether the device sort
    actually delivered (False on its internal host fallback), so the
    caller's telemetry reports the outcome, not the request."""
    flags = s["flags"]
    valid = s["valid"]
    n = len(flags)
    if n == 0:
        return np.zeros(0, dtype=bool)

    # ----- bucket ids: dense (rg, name) -> id (SingleReadBucket) -------
    names = s["name_bytes"]
    name_inv = _unique_inverse_fixed_bytes(names)
    rg = s["rg_idx"]
    key = (rg + 1) * (name_inv.max() + 1 if len(name_inv) else 1) + name_inv
    key = np.where(valid, key, -1)
    vrows = np.flatnonzero(valid)
    uniq, inv = np.unique(key[vrows], return_inverse=True)
    bucket_of = np.full(n, -1, dtype=np.int64)
    bucket_of[vrows] = inv
    n_buckets = len(uniq)
    if n_buckets == 0:
        return np.zeros(n, dtype=bool)

    mapped = (flags & schema.FLAG_UNMAPPED) == 0
    primary = (flags & (schema.FLAG_SECONDARY | schema.FLAG_SUPPLEMENTARY)) == 0
    first = (flags & schema.FLAG_FIRST_OF_PAIR) != 0
    second = (flags & schema.FLAG_SECOND_OF_PAIR) != 0
    row_key = s["row_key"]
    read_score = s["score"]

    in_bucket = bucket_of >= 0
    candidate = in_bucket & (((mapped & primary)) | ~mapped)

    # ordering inside a bucket: mapped-primary candidates first, then row
    # order (the reference's primaryMapped ++ unmapped concatenation)
    prio = (~mapped).astype(np.int64) * n + np.arange(n, dtype=np.int64)
    BIG = np.int64(2) * n * n + n

    def first_row(mask: np.ndarray) -> np.ndarray:
        """Per-bucket row with minimal prio among masked rows (-1 none)."""
        sel = np.full(n_buckets, BIG, dtype=np.int64)
        rows = np.flatnonzero(mask)
        np.minimum.at(sel, bucket_of[rows], prio[rows])
        out = np.where(sel < BIG, sel % n, -1)
        return out

    first_sel = first_row(candidate & first)
    second_sel = first_row(candidate & second)
    frag_sel = first_row(candidate)

    # bucket score: sum of primary-mapped read scores
    bucket_score = np.zeros(n_buckets, dtype=np.int64)
    sc_rows = np.flatnonzero(in_bucket & valid & mapped & primary)
    np.add.at(bucket_score, bucket_of[sc_rows], read_score[sc_rows].astype(np.int64))

    # library per bucket (library of the first read, in row order)
    lib_per_row = s["lib_per_row"]
    lead = first_row(in_bucket)
    bucket_lib = np.where(lead >= 0, lib_per_row[np.clip(lead, 0, None)], -1)

    # ----- per-bucket left/right keys ----------------------------------
    has_pair = (first_sel >= 0) | (second_sel >= 0)
    left_arr = np.zeros((n_buckets, 4), dtype=np.int64)
    right_arr = np.zeros((n_buckets, 4), dtype=np.int64)
    lk_rows = np.where(has_pair, first_sel, frag_sel)
    use_lk = lk_rows >= 0
    left_arr[use_lk] = row_key[lk_rows[use_lk]]
    rk_rows = np.where(has_pair, second_sel, -1)
    use_rk = rk_rows >= 0
    right_arr[use_rk] = row_key[rk_rows[use_rk]]

    # ----- group by (library, left), subgroup by right, mark -----------
    # lexicographic order (lib, L0..L3, R0..R3) with adjacent small-range
    # fields packed into shared words: kind < 4, strand < 2, and
    # |pos| < 2^40, so (lib<<2)|kind, (Lpos<<3)|(Lstrand<<2)|Rkind and
    # (Rpos<<1)|Rstrand preserve the 9-key order in 5 stable sorts
    # (full-range int64 hash keys L1/R1 stay unpacked)
    k1 = (bucket_lib << 2) | left_arr[:, 0]
    k3 = (left_arr[:, 2] << 3) | (left_arr[:, 3] << 2) | right_arr[:, 0]
    k5 = (right_arr[:, 2] << 1) | right_arr[:, 3]
    sort_keys = (k5, right_arr[:, 1], k3, left_arr[:, 1], k1)
    if sort_device is not None:
        from adam_tpu.parallel.dist import device_lexsort

        group_order = device_lexsort(
            sort_keys,
            None if sort_device == "default" else sort_device,
            info=sort_info,
        )
    else:
        if sort_info is not None:
            sort_info["device_sort"] = False
        group_order = np.lexsort(sort_keys)
    go = group_order
    sl = np.concatenate([bucket_lib[go, None], left_arr[go]], axis=1)
    sr = right_arr[go]
    new_left = np.ones(len(go), dtype=bool)
    new_left[1:] = (sl[1:] != sl[:-1]).any(axis=1)
    new_right = new_left.copy()
    new_right[1:] |= (sr[1:] != sr[:-1]).any(axis=1)

    left_id = np.cumsum(new_left) - 1       # per sorted bucket
    sub_id = np.cumsum(new_right) - 1
    n_left = int(left_id[-1]) + 1
    n_sub = int(sub_id[-1]) + 1
    sub_starts = np.flatnonzero(new_right)
    # left group of each subgroup / subgroup count per left group
    sub_left = left_id[sub_starts]
    subs_per_left = np.bincount(sub_left, minlength=n_left)

    group_skip = np.zeros(n_left, dtype=bool)
    group_skip[left_id[new_left]] = sl[new_left, 1] == 0  # left kind None

    sub_is_frag = sr[sub_starts, 0] == 0
    sub_only_frag = sub_is_frag & (subs_per_left[sub_left] == 1)
    sub_keep_best = (sub_only_frag | ~sub_is_frag) & ~group_skip[sub_left]
    sub_mark_all = sub_is_frag & (subs_per_left[sub_left] > 1) & ~group_skip[sub_left]

    # best bucket per subgroup: max score, first (stable order) wins
    score_sorted = bucket_score[go]
    max_sc = np.maximum.reduceat(score_sorted, sub_starts)
    pos = np.arange(len(go), dtype=np.int64)
    is_max = score_sorted == max_sc[sub_id]
    first_best = np.full(n_sub, len(go), dtype=np.int64)
    rows_max = np.flatnonzero(is_max)
    np.minimum.at(first_best, sub_id[rows_max], pos[rows_max])

    marked_sub = sub_keep_best | sub_mark_all
    primary_dup_sorted = marked_sub[sub_id]
    secondary_dup_sorted = primary_dup_sorted.copy()
    # unmark the best bucket of keep-best subgroups (primaries only)
    best_pos = first_best[np.flatnonzero(sub_keep_best)]
    primary_dup_sorted[best_pos] = False

    primary_dup = np.zeros(n_buckets, dtype=bool)
    secondary_dup = np.zeros(n_buckets, dtype=bool)
    primary_dup[go] = primary_dup_sorted
    secondary_dup[go] = secondary_dup_sorted

    # ----- back to rows ------------------------------------------------
    row_bucket = np.clip(bucket_of, 0, None)
    dup = np.where(
        mapped & primary,
        primary_dup[row_bucket],
        np.where(mapped, secondary_dup[row_bucket], False),
    )
    dup &= valid & (bucket_of >= 0)
    return dup


def apply_duplicate_flags(flags: np.ndarray, dup: np.ndarray) -> np.ndarray:
    return np.where(
        dup, flags | schema.FLAG_DUPLICATE, flags & ~schema.FLAG_DUPLICATE
    ).astype(np.int32)


def mark_duplicates(
    ds: AlignmentDataset, backend: str | None = None
) -> AlignmentDataset:
    """Single-batch duplicate marking.  ``backend`` follows the shared
    per-residue flag (:func:`adam_tpu.pipelines.bqsr.bqsr_backend`):
    ``device`` runs the [N, L] key/score reductions on the chip (the
    default when one is attached); the host twins otherwise."""
    from adam_tpu.pipelines.bqsr import bqsr_backend

    b = ds.batch.to_numpy()
    if b.n_rows == 0:
        return ds
    if bqsr_backend(backend) == "device":
        five, score = markdup_columns_device(ds.batch)
        s = row_summary(ds, b, five_prime=five, score=score)
    else:
        s = row_summary(ds, b)
    dup = resolve_duplicates(s)
    new_flags = apply_duplicate_flags(np.asarray(b.flags), dup)
    return ds.with_batch(b.replace(flags=new_flags))
