"""Region joins, coverage, and sorted pairing.

Distributed-primitive parity (SURVEY §2 [DIST] rows):

* :class:`NonoverlappingRegions` / :func:`broadcast_region_join` —
  semantics of ``rdd/BroadcastRegionJoin.scala`` (:65-130, index at
  :169-301): build a small merged-region index from the left side,
  replicate it (the broadcast), key the right side by binary search, join
  within groups. Here the index is two sorted key arrays and the "binary
  search per record" is one ``searchsorted`` over the whole batch.
* :class:`GenomeBins` / :func:`shuffle_region_join` — semantics of
  ``rdd/ShuffleRegionJoin.scala`` (:72-134, bins :140-193, sweep
  :223-290): fixed-size genome bins, both sides replicated into every bin
  they overlap, per-bin sort-merge join, and the dedupe rule that a pair
  is emitted only where at least one side *starts* in the bin. Bins are
  the unit that maps onto mesh shards in the multi-chip layout
  (:mod:`adam_tpu.parallel`).
* :func:`find_coverage_regions` — ``rdd/Coverage.scala:55-190``: minimal
  disjoint non-adjacent region set covering every covered base. The
  reference needs windowing + groupBy + a per-window sweep + a collapse
  pass; columnar merge does it in one sort+scan.
* :func:`sliding` / :func:`pair` / :func:`pair_with_ends` —
  ``rdd/PairingRDD.scala:54-130`` over sorted arrays.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from adam_tpu.models.dictionaries import SequenceDictionary
from adam_tpu.ops import intervals as iv
from adam_tpu.parallel.partitioner import GenomeBins


@dataclass(frozen=True)
class IntervalArrays:
    """Columnar interval set: the argument/return type of the joins."""

    contig: np.ndarray  # i64[N] contig index into a SequenceDictionary
    start: np.ndarray  # i64[N]
    end: np.ndarray  # i64[N]

    def __len__(self):
        return len(self.start)

    @staticmethod
    def of(contig, start, end) -> "IntervalArrays":
        return IntervalArrays(
            np.asarray(contig, np.int64),
            np.asarray(start, np.int64),
            np.asarray(end, np.int64),
        )


class NonoverlappingRegions:
    """Merged-region index over an interval set — the broadcast side.

    The reference stores distinct-union endpoints and walks them with
    ``binaryPointSearch`` (BroadcastRegionJoin.scala:197-227). Here the
    merged groups live as sorted columnar arrays; queries resolve to
    contiguous group-id ranges in two vectorized searches.
    """

    def __init__(self, regions: IntervalArrays):
        if len(regions) == 0:
            raise ValueError("regions list must be non-empty")
        m_c, m_s, m_e, group = iv.merge_intervals(
            regions.contig, regions.start, regions.end
        )
        self.m_contig, self.m_start, self.m_end = m_c, m_s, m_e
        self.group_of_input = group

    def __len__(self):
        return len(self.m_start)

    def regions_for(self, query: IntervalArrays):
        """Per-query [lo, hi) merged-group range (findOverlappingRegions)."""
        return iv.overlap_group_ranges(
            self.m_contig, self.m_start, self.m_end,
            query.contig, query.start, query.end,
        )

    def has_regions_for(self, query: IntervalArrays) -> np.ndarray:
        lo, hi = self.regions_for(query)
        return hi > lo


def broadcast_region_join(left: IntervalArrays, right: IntervalArrays):
    """(li, ri) index pairs of overlapping left/right intervals.

    Equivalent output to BroadcastRegionJoin.partitionAndJoin
    (BroadcastRegionJoin.scala:65-130); callers carry their own payloads
    and gather with the returned indices (columnar replacement for the
    RDD[(T, U)] of the reference).
    """
    return iv.overlap_join(
        left.contig, left.start, left.end,
        right.contig, right.start, right.end,
    )


def shuffle_region_join(
    left: IntervalArrays,
    right: IntervalArrays,
    seq_dict: SequenceDictionary,
    bin_size: int = 1_000_000,
):
    """(li, ri) overlap pairs via genome-binned sort-merge join.

    Mirrors ShuffleRegionJoin.partitionAndJoin (:72-134): both sides are
    replicated into every bin they overlap, each bin joins independently
    (this is the per-shard unit for the mesh), and a pair is kept only if
    at least one side starts inside the bin — the chromsweep dedupe rule
    (SortedIntervalPartitionJoin filter, ShuffleRegionJoin.scala:262-267).
    """
    bins = GenomeBins(bin_size, seq_dict)
    out_l, out_r = [], []

    # rows on contigs outside the dictionary (negative / out-of-range ids)
    # cannot land in any genome bin — exclude them rather than crash
    n_contigs = len(seq_dict.names)
    l_keep = np.flatnonzero((left.contig >= 0) & (left.contig < n_contigs))
    r_keep = np.flatnonzero((right.contig >= 0) & (right.contig < n_contigs))
    if len(l_keep) < len(left) or len(r_keep) < len(right):
        left = IntervalArrays.of(
            left.contig[l_keep], left.start[l_keep], left.end[l_keep]
        )
        right = IntervalArrays.of(
            right.contig[r_keep], right.start[r_keep], right.end[r_keep]
        )
        li, ri = shuffle_region_join(left, right, seq_dict, bin_size)
        return l_keep[li], r_keep[ri]

    l_lo = bins.start_bin(left.contig, left.start)
    l_hi = bins.end_bin(left.contig, left.end) + 1
    r_lo = bins.start_bin(right.contig, right.start)
    r_hi = bins.end_bin(right.contig, right.end) + 1
    li_rep, l_bin = iv.expand_ranges(l_lo, l_hi)
    ri_rep, r_bin = iv.expand_ranges(r_lo, r_hi)

    # per-bin independent joins: iterate only over bins both sides touch
    active = np.intersect1d(l_bin, r_bin)
    l_order = np.argsort(l_bin, kind="stable")
    r_order = np.argsort(r_bin, kind="stable")
    l_bin_sorted, r_bin_sorted = l_bin[l_order], r_bin[r_order]
    for b in active:
        lsel = li_rep[l_order[np.searchsorted(l_bin_sorted, b):
                              np.searchsorted(l_bin_sorted, b, "right")]]
        rsel = ri_rep[r_order[np.searchsorted(r_bin_sorted, b):
                              np.searchsorted(r_bin_sorted, b, "right")]]
        pl, pr = iv.overlap_join(
            left.contig[lsel], left.start[lsel], left.end[lsel],
            right.contig[rsel], right.start[rsel], right.end[rsel],
        )
        if len(pl) == 0:
            continue
        gl, gr = lsel[pl], rsel[pr]
        _, bstart, bend = bins.dedupe_region(int(b))
        keep = (
            (left.start[gl] >= bstart) & (left.start[gl] < bend)
        ) | ((right.start[gr] >= bstart) & (right.start[gr] < bend))
        out_l.append(gl[keep])
        out_r.append(gr[keep])

    if not out_l:
        z = np.zeros(0, np.int64)
        return z, z
    return np.concatenate(out_l), np.concatenate(out_r)


def find_coverage_regions(regions: IntervalArrays) -> IntervalArrays:
    """Minimal disjoint non-adjacent covering set (Coverage.scala:55-78)."""
    m_c, m_s, m_e, _ = iv.merge_intervals(
        regions.contig, regions.start, regions.end, adjacent=True
    )
    return IntervalArrays(m_c, m_s, m_e)


def depth_at(
    sites: IntervalArrays, reads: IntervalArrays
) -> np.ndarray:
    """Read depth at each site start — the `depth` command core
    (adam-cli CalculateDepth.scala:41, via BroadcastRegionJoin + count)."""
    return iv.point_depth(
        reads.contig, reads.start, reads.end, sites.contig, sites.start
    )


# ------------------------------------------------------------- pairing

def sliding(sorted_values: np.ndarray, width: int) -> np.ndarray:
    """All width-length windows of a sorted array, in order
    (PairingRDD.sliding, rdd/PairingRDD.scala:54-68). Returns
    ``[N-width+1, width]`` — a strided view, no copy, and the same
    expression is jittable for device windows."""
    v = np.asarray(sorted_values)
    n = len(v)
    if n < width:
        return v[:0].reshape(0, width)
    return np.lib.stride_tricks.sliding_window_view(v, width, axis=0)


def pair(sorted_values: np.ndarray):
    """Consecutive pairs (PairingRDD.pair, :82-87)."""
    v = np.asarray(sorted_values)
    return v[:-1], v[1:]


def pair_with_ends(sorted_values: np.ndarray):
    """Consecutive pairs with None-padded ends (PairingRDD.pairWithEnds,
    :108-128) as host lists of optional values."""
    v = list(np.asarray(sorted_values))
    if not v:
        return []
    padded = [None] + v + [None]
    return list(zip(padded[:-1], padded[1:]))
