"""Streamed, overlapped flagship transform.

The reference's ``Bam2ADAM`` queue-and-workers design
(adam-cli/src/main/scala/org/bdgenomics/adam/cli/Bam2ADAM.scala:55-111)
promoted to the whole ``transform`` pipeline
(adam-cli/.../Transform.scala:101-163): instead of load-everything then
run-each-stage-serially, the input is tokenized in windows and the
pipeline runs as three overlapped passes with two global barriers:

  pass A   ingest thread tokenizes window i+1 (threaded C++) while the
           main thread computes window i's duplicate-marking summary and
           indel-event list — compact per-row columns, never [N, L]
           temporaries.
  barrier  global duplicate resolution (one lexsort cascade over the
           spliced summaries) and global target merge — the same
           decisions the single-batch path makes, so window edges are
           invisible (a duplicate group or realignment target spanning
           two windows resolves exactly as in one batch).
  pass B   per-window realignment-candidate split (pre-BQSR quals, as
           the reference composes: markdup -> realign -> BQSR,
           Transform.scala:121-144) + BQSR observation of each window's
           remainder under the resolved duplicate flags.
  tail     rows mapped to realignment targets (gathered across all
           windows, so boundary-spanning targets see all their reads)
           realign together — device sweep kernels — then the realigned
           part is observed with its POST-realignment alignments (the
           composition-order-visible piece of adamBQSR-after-realign).
  barrier  merge histograms, solve the recalibration table.
  pass C   per-window recalibration apply, while a writer pool encodes
           finished windows to Parquet part files (the Spark executor
           part-file layout: ``out.adam/part-*``); the realigned part
           applies and lands in the final part file.

Wall-clock goal: max(stage) instead of sum(stages) — host codecs and
device kernels run at the same time, which is what a TPU-attached host
should be doing.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats.batch import ReadBatch
from adam_tpu.formats.strings import StringColumn

_SENTINEL = object()


def _ingest_windows(path: str, window_reads: int, out_q: queue.Queue,
                    abort: threading.Event):
    """Ingest thread body: tokenize windows, push (batch, side, header).

    ``abort`` unblocks the bounded put when the consumer dies mid-stream
    — otherwise the thread (and the decoded input it holds) would be
    pinned for the life of the process.
    """

    def put(item) -> bool:
        while not abort.is_set():
            try:
                out_q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    try:
        p = str(path)
        base = p[:-3] if p.endswith(".gz") else p
        from adam_tpu.io import sam as sam_io

        if base.endswith(".bam"):
            it = sam_io.iter_bam_batches(p, batch_reads=window_reads)
        else:
            it = sam_io.iter_sam_batches(p, batch_reads=window_reads)
        for batch, side, header in it:
            if not put((batch, side, header)):
                return
        put(_SENTINEL)
    except BaseException as e:  # surface in the consumer
        put(e)


def _write_part(out_dir: str, part_idx: int, ds: AlignmentDataset,
                compression: str) -> None:
    from adam_tpu.io import parquet

    parquet.save_alignments(
        os.path.join(out_dir, f"part-r-{part_idx:05d}.parquet"),
        ds.batch, ds.sidecar, ds.header, compression=compression,
    )


def transform_streamed(
    path: str,
    out_path: str,
    *,
    mark_duplicates: bool = True,
    recalibrate: bool = True,
    realign: bool = True,
    known_snps=None,
    known_indels=None,
    consensus_model: str = "reads",
    window_reads: int = 262_144,
    compression: str = "zstd",
    n_writers: int = 3,
    max_indel_size: int | None = None,
    max_consensus_number: int | None = None,
    lod_threshold: float | None = None,
    max_target_size: int | None = None,
    dump_observations: Optional[str] = None,
) -> dict:
    """Run the flagship transform as a streamed, overlapped pipeline.

    Output is a Parquet part-file directory (the reference's Spark
    executor layout); ``adam_tpu.io.context.load_alignments`` reads it
    back as one dataset.  Returns phase wall-times + read count.
    """
    from adam_tpu.pipelines import bqsr as bqsr_mod
    from adam_tpu.pipelines import markdup as md_mod
    from adam_tpu.pipelines import realign as realign_mod

    t_start = time.perf_counter()
    stats: dict = {}
    os.makedirs(out_path, exist_ok=True)
    if known_indels is not None and consensus_model == "reads":
        # supplying known indels implies the knowns consensus model (the
        # reference's -known_indels flag semantics; realign_indels only
        # consults the table under that model)
        consensus_model = "knowns"
    mis, mcn, lod, mts = realign_mod.resolve_tuning(
        max_indel_size, max_consensus_number, lod_threshold, max_target_size
    )

    # ---- pass A: ingest || summaries + events --------------------------
    in_q: queue.Queue = queue.Queue(maxsize=3)
    abort = threading.Event()
    ingest = threading.Thread(
        target=_ingest_windows, args=(path, window_reads, in_q, abort),
        daemon=True,
    )
    ingest.start()

    windows: list[AlignmentDataset] = []
    summaries: list[dict] = []
    events = []
    header = None
    t = time.perf_counter()
    try:
        while True:
            item = in_q.get()
            if item is _SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            batch, side, header = item
            ds = AlignmentDataset(batch, side, header)
            windows.append(ds)
            if mark_duplicates:
                summaries.append(md_mod.row_summary(ds))
            if realign:
                events.append(
                    realign_mod.extract_indel_event_arrays(
                        batch.to_numpy(), max_indel_size=mis
                    )
                )
    except BaseException:
        abort.set()
        raise
    ingest.join()
    stats["ingest_pass_s"] = time.perf_counter() - t
    n_reads = int(sum(int(w.batch.valid.sum()) for w in windows))
    stats["n_reads"] = n_reads
    if header is None or not windows:
        stats["total_s"] = time.perf_counter() - t_start
        return stats

    # ---- barrier 1: resolve duplicates + merge targets ----------------
    t = time.perf_counter()
    if mark_duplicates and summaries:
        dup = md_mod.resolve_duplicates(md_mod.concat_summaries(summaries))
        off = 0
        for i, w in enumerate(windows):
            n = w.batch.n_rows
            b = w.batch.to_numpy()
            new_flags = md_mod.apply_duplicate_flags(
                np.asarray(b.flags), dup[off : off + n]
            )
            windows[i] = w.with_batch(b.replace(flags=new_flags))
            off += n
        del summaries
    targets = (
        realign_mod.merge_events(
            np.concatenate(events, axis=0) if events
            else np.zeros((0, 5), np.int64),
            header.seq_dict.names, mts,
        )
        if realign
        else []
    )
    stats["resolve_s"] = time.perf_counter() - t

    # ---- pass B: candidate split (pre-BQSR, reference order) ----------
    t = time.perf_counter()
    candidates: list[AlignmentDataset] = []
    window_valid: list[int] = []
    obs_parts = []
    for i, w in enumerate(windows):
        n_valid = w.batch.n_rows
        if targets:
            cand, w, n_valid = realign_mod.split_realign_candidates(
                w, targets, header.seq_dict.names
            )
            if cand is not None:
                candidates.append(cand)
            windows[i] = w
        window_valid.append(n_valid)
    stats["split_s"] = time.perf_counter() - t

    def _observe_remainders():
        # non-candidate rows are untouched by realignment, so their
        # observations are identical on either side of it — which lets
        # this host pass hide under the realign sweeps' device drain
        t0 = time.perf_counter()
        if recalibrate:
            for i, w in enumerate(windows):
                if window_valid[i]:
                    total, mism, _rg, g = bqsr_mod._observe_device(
                        w, known_snps
                    )
                    obs_parts.append(
                        (np.asarray(total), np.asarray(mism), g)
                    )
        stats["observe_s"] = time.perf_counter() - t0

    # ---- tail: realign the gathered candidates (observing remainders
    # under the device wait), then observe the realigned part with its
    # post-realignment alignments (markdup -> realign -> BQSR, the
    # reference's Transform composition) ---------------------------------
    t = time.perf_counter()
    realigned: Optional[AlignmentDataset] = None
    if candidates:
        cand = AlignmentDataset.concat(candidates)
        realigned = realign_mod.realign_indels(
            cand,
            consensus_model=consensus_model,
            known_indels=known_indels,
            max_indel_size=mis,
            max_consensus_number=mcn,
            lod_threshold=lod,
            max_target_size=mts,
            overlap_work=_observe_remainders,
        )
        if recalibrate and realigned.batch.n_rows:
            total, mism, _rg, g = bqsr_mod._observe_device(
                realigned, known_snps
            )
            obs_parts.append((np.asarray(total), np.asarray(mism), g))
    else:
        _observe_remainders()
    # the tail wall minus the overlapped observe time = realign's own
    # share (the stage table should not double-charge the hidden work)
    stats["realign_s"] = (
        time.perf_counter() - t - stats.get("observe_s", 0.0)
    )

    # ---- barrier 2: merge histograms, solve the table ------------------
    t = time.perf_counter()
    table = None
    gl = 0
    if recalibrate and obs_parts:
        total, mism, gl = bqsr_mod.merge_observations(obs_parts)
        if dump_observations:
            bqsr_mod.dump_observation_csv(
                total, mism, header.read_groups.names + ["null"], gl,
                dump_observations,
            )
        table = bqsr_mod.solve_recalibration_table(total, mism)
    stats["solve_s"] = time.perf_counter() - t

    # ---- pass C: apply || part writes ----------------------------------
    t = time.perf_counter()
    write_errs: list[BaseException] = []
    futures = []
    with ThreadPoolExecutor(max_workers=max(1, n_writers)) as pool:
        # the realigned part applies and submits FIRST: it is the
        # largest part, so its encode+write should overlap the window
        # applies instead of draining serially after them
        if realigned is not None:
            if table is not None:
                realigned = bqsr_mod.apply_recalibration(
                    realigned, table, gl
                )
            futures.append(
                pool.submit(
                    _write_part, out_path, len(windows), realigned,
                    compression,
                )
            )
        for i, w in enumerate(windows):
            if table is not None:
                w = bqsr_mod.apply_recalibration(w, table, gl)
            windows[i] = None  # free as we go
            if window_valid[i]:
                futures.append(
                    pool.submit(_write_part, out_path, i, w, compression)
                )
        stats["apply_split_s"] = time.perf_counter() - t

        t = time.perf_counter()
        for f in futures:
            err = f.exception()
            if err is not None:
                write_errs.append(err)
    if write_errs:
        raise write_errs[0]
    stats["write_wait_s"] = time.perf_counter() - t
    stats["total_s"] = time.perf_counter() - t_start

    # Mirror the stage walls into the named-timer registry so
    # ``-print_metrics`` decomposes the streamed flagship the way the
    # reference's Metrics listener decomposes a Spark job (stage rows on
    # top, the codec/write timers recorded inside tokenize/save below
    # them sum to the same wall).
    from adam_tpu.utils import instrumentation as ins

    for key, label in (
        ("ingest_pass_s", "Streamed Pass A (ingest + summaries)"),
        ("resolve_s", "Streamed Barrier (dup resolve + targets)"),
        ("split_s", "Streamed Pass B (candidate split)"),
        ("observe_s", "Streamed BQSR Observe (hidden under sweeps)"),
        ("realign_s", "Streamed Tail (realign net of overlap)"),
        ("solve_s", "Streamed Barrier (solve recalibration)"),
        ("apply_split_s", "Streamed Pass C (apply)"),
        ("write_wait_s", "Streamed Write Wait"),
    ):
        if key in stats:
            ins.TIMERS.add(label, int(stats[key] * 1e9))
    return stats
