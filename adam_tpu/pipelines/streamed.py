"""Streamed, overlapped flagship transform.

The reference's ``Bam2ADAM`` queue-and-workers design
(adam-cli/src/main/scala/org/bdgenomics/adam/cli/Bam2ADAM.scala:55-111)
promoted to the whole ``transform`` pipeline
(adam-cli/.../Transform.scala:101-163): instead of load-everything then
run-each-stage-serially, the input is tokenized in windows and the
pipeline runs as three overlapped passes with two global barriers:

  pass A   ingest thread tokenizes window i+1 (threaded C++) while the
           main thread computes window i's duplicate-marking summary and
           indel-event list — compact per-row columns, never [N, L]
           temporaries.
  barrier  global duplicate resolution (one lexsort cascade over the
           spliced summaries) and global target merge — the same
           decisions the single-batch path makes, so window edges are
           invisible (a duplicate group or realignment target spanning
           two windows resolves exactly as in one batch).
  pass B   per-window realignment-candidate split (pre-BQSR quals, as
           the reference composes: markdup -> realign -> BQSR,
           Transform.scala:121-144) + BQSR observation of each window's
           remainder under the resolved duplicate flags.
  tail     rows mapped to realignment targets (gathered across all
           windows, so boundary-spanning targets see all their reads)
           realign together — device sweep kernels — then the realigned
           part is observed with its POST-realignment alignments (the
           composition-order-visible piece of adamBQSR-after-realign).
  barrier  merge histograms, solve the recalibration table.
  pass C   per-window recalibration apply, while a double-buffered
           writer pool encodes finished windows to Parquet part files
           (the Spark executor part-file layout: ``out.adam/part-*``);
           the realigned part applies and lands in the final part file.
           On the device backend the apply is a chip-side table gather,
           double-buffered: window i+1's gather runs while window i's
           recalibrated quals fetch and its part encodes.

With a chip attached the per-residue passes default to the device
kernels (``ADAM_TPU_BQSR_BACKEND`` overrides; see
:func:`adam_tpu.pipelines.bqsr.bqsr_backend`): the markdup [N, L]
key/score reductions dispatch during pass A's ingest overlap, every
window's BQSR observe scatter-adds on device and is fetched (compact
histograms only) at the merge barrier, and pass C gathers recalibrated
quals on device.

With more than one chip attached the device work additionally fans out
across a :class:`adam_tpu.parallel.device_pool.DevicePool`: window *i*'s
markdup reductions, observe scatter-adds and apply table-gathers land on
device ``i % n`` (``--devices N`` / ``ADAM_TPU_DEVICES`` select; the
``n == 1`` topology keeps the single-chip path bit-for-bit), each device
runs a double buffer deep in-flight queue, the solved recalibration
table is replicated once per device, and the per-device observe
histograms merge host-side at the barrier in window order — so the
multi-chip output is bit-identical to the single-chip one.  A compile
prewarm on the first window compiles the grid-quantized kernel set once
per device concurrently, so 20-40 s cold remote compiles never land
inside a window.

Wall-clock goal: max(stage) instead of sum(stages) — host codecs and
device kernels run at the same time, which is what a TPU-attached host
should be doing.
"""

from __future__ import annotations

import hashlib
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.parallel import device_pool as dp_mod
from adam_tpu.utils import faults
from adam_tpu.utils import health as health_mod
from adam_tpu.utils import telemetry as tele
from adam_tpu.utils.transfer import device_fetch

log = logging.getLogger(__name__)

_SENTINEL = object()

#: Sentinel for "the device path is gone — run this window on the host
#: backend" (returned by the per-run ``_pick_device`` closure after the
#: last pool device is evicted, or after the single default chip fails).
_HOST = object()

#: Sentinel for "this window's observe was submitted to the cross-job
#: coalescer and its future parked on the caller's ``defer`` list" —
#: pass B submits every window before resolving any, so the coalescer
#: sees the whole window set and the job thread never serializes on a
#: single fused dispatch.
_DEFERRED = object()


class RunCancelled(BaseException):
    """Cooperative stop at a window boundary (the multi-job service's
    graceful drain, docs/ROBUSTNESS.md "Fault-isolated multi-job
    scheduling"): raised out of the per-window ``pacer`` hook.  In
    pass C the pipeline closes the writer pool GRACEFULLY first — every
    part already submitted publishes durably and journals — then
    re-raises, so a drained job's journal resumes exactly where the
    drain stopped it.  A ``BaseException`` on purpose: the device
    recovery paths catch ``Exception`` broadly, and a drain request
    must never be mistaken for a chip failure."""


def _ingest_windows(path: str, window_reads: int, out_q: queue.Queue,
                    abort: threading.Event, tr: tele.Tracer):
    """Ingest thread body: tokenize windows, push (batch, side, header).

    ``abort`` unblocks the bounded put when the consumer dies mid-stream
    — otherwise the thread (and the decoded input it holds) would be
    pinned for the life of the process.  ``tr`` records one
    ``streamed.tokenize`` span per window on this thread's track.
    """

    def put(item) -> bool:
        while not abort.is_set():
            try:
                out_q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    try:
        p = str(path)
        base = p[:-3] if p.endswith(".gz") else p
        from adam_tpu.io import sam as sam_io

        if base.endswith(".bam"):
            it = sam_io.iter_bam_batches(p, batch_reads=window_reads)
        else:
            it = sam_io.iter_sam_batches(p, batch_reads=window_reads)
        i = 0
        while True:
            with tr.span(tele.SPAN_TOKENIZE, window=i):
                item = next(it, _SENTINEL)
            if item is _SENTINEL:
                break
            if not put(item):
                return
            # chaos-harness kill point: one arrival per tokenized window
            faults.point("proc.kill", device="ingest")
            i += 1
        put(_SENTINEL)
    except BaseException as e:  # surface in the consumer
        put(e)


def _part_path(out_dir: str, part_idx: int) -> str:
    # the io/parquet part-naming contract: the numeric index IS the
    # window index (realigned tail part = n_windows), so the streamed
    # run journal can map published parts back onto the window plan
    from adam_tpu.io.parquet import part_path

    return part_path(out_dir, part_idx)


def _write_part(out_dir: str, part_idx: int, ds: AlignmentDataset,
                compression: str) -> None:
    """Synchronous single-part write (the sharded/multihost executors'
    sink; the streamed pipeline itself goes through PartWriterPool)."""
    from adam_tpu.io import parquet

    parquet.save_alignments(
        _part_path(out_dir, part_idx),
        ds.batch, ds.sidecar, ds.header, compression=compression,
    )


def _start_heartbeat(tr: tele.Tracer, progress: Optional[str],
                     include_global: bool = True):
    """Build+start the live progress heartbeat, or None (the default —
    zero construction, the spans' disabled-overhead contract).

    Samples the run tracer AND the global TRACE (retry/fault counters
    and the transfer ledger land on the latter); when no other
    observability sink already enabled global recording, it is flipped
    on for the heartbeat's lifetime and :func:`_stop_heartbeat`
    restores the flag AND resets the tracer — a ``--progress``-only run
    neither exports nor accumulates global telemetry, so back-to-back
    library runs in one process can't sum each other's counters into
    the beat.

    ``include_global=False`` (the multi-job service) samples the run
    tracer alone: concurrent jobs absorb their tracers into the global
    TRACE as they finish, and a survivor's beat summing that shared
    state would count its neighbors' work as its own."""
    sink = progress if progress is not None else tele.progress_sink_from_env()
    if not sink:
        return None
    hb = tele.Heartbeat(
        [tr, tele.TRACE] if include_global else [tr], sink
    )
    hb._hb_restore_recording = include_global and not tele.TRACE.recording
    if hb._hb_restore_recording:
        tele.TRACE.recording = True
    hb.start()
    return hb


def _stop_heartbeat(hb, ok: bool = True) -> None:
    """Idempotent heartbeat teardown (final ``done`` line + recording
    restore) — called from the normal finish path *before* the run
    tracer folds into the global TRACE (a post-absorb sample would
    double-count every counter) and again from the wrapper's
    ``finally`` for the exception paths, which pass ``ok=False`` so
    the final line does not read as a completed run."""
    if hb is None:
        return
    hb.stop(ok=ok)
    if getattr(hb, "_hb_restore_recording", False):
        tele.TRACE.recording = False
        # recording was OFF before this run, so nothing else is reading
        # the global tracer: drop what the heartbeat window recorded
        # into it, or a later run in the same process (library use,
        # tests) would sum this run's parquet counters into its own
        tele.TRACE.reset()
        hb._hb_restore_recording = False


def _inflight_per_device(queues: list) -> dict:
    """Heartbeat provider body: per-device in-flight depth sampled from
    the live dispatch deques (read-only; a concurrent mutation mid-
    iteration just skips this beat — the next one resamples)."""
    per: dict = {}
    for dq, dev_idx in queues:
        try:
            items = list(dq)
        except RuntimeError:
            continue
        for item in items:
            dev = item[dev_idx]
            key = "default" if dev is None else str(dp_mod._attr_id(dev))
            per[key] = per.get(key, 0) + 1
    return per


def transform_streamed(
    path: str,
    out_path: str,
    *,
    mark_duplicates: bool = True,
    recalibrate: bool = True,
    realign: bool = True,
    known_snps=None,
    known_indels=None,
    consensus_model: str = "reads",
    window_reads: int = 262_144,
    compression: str = "zstd",
    n_writers: int = 3,
    max_indel_size: int | None = None,
    max_consensus_number: int | None = None,
    lod_threshold: float | None = None,
    max_target_size: int | None = None,
    dump_observations: Optional[str] = None,
    known_table: Optional[tuple] = None,
    devices: Optional[int] = None,
    partitioner: Optional[str] = None,
    progress: Optional[str] = None,
    run_dir: Optional[str] = None,
    resume: bool = False,
    pacer=None,
    device_pool=None,
    coalescer=None,
    trace: Optional[str] = None,
) -> dict:
    """Run the flagship transform as a streamed, overlapped pipeline.

    Output is a Parquet part-file directory (the reference's Spark
    executor layout); ``adam_tpu.io.context.load_alignments`` reads it
    back as one dataset.  Returns phase wall-times + read count.

    ``devices`` caps the device-pool fan-out (default: every attached
    device, or ``ADAM_TPU_DEVICES``); only the ``device`` backend uses
    it, and ``devices=1`` is exactly the single-chip path.

    ``partitioner`` selects how device work places across those chips
    (``--partitioner`` / ``ADAM_TPU_PARTITIONER``): ``"pool"`` (the
    default) round-robins whole windows, ``"mesh"`` shards every
    window's [N, L] arrays over a ``batch``
    :class:`~jax.sharding.Mesh`, ``psum``s the pass-B observe
    histograms on-device so barrier 2 fetches ONE merged table instead
    of one per window, and keeps the solved recalibration table
    device-resident through pass C.  Output is bit-identical across
    modes; a mesh failure degrades to the pool path mid-run
    (``device.mesh.degraded``), preserving the eviction/replay
    contract (docs/ROBUSTNESS.md).

    ``progress`` names a live-heartbeat sink (``"stderr"`` or a file
    path; default: ``ADAM_TPU_PROGRESS``, off when unset): a daemon
    thread emits one NDJSON line (schema
    :data:`~adam_tpu.utils.telemetry.HEARTBEAT_FIELDS`) every
    ``ADAM_TPU_PROGRESS_INTERVAL_S`` seconds.

    ``run_dir`` enables the durable window-granular resume journal
    (docs/ROBUSTNESS.md): each output window is recorded complete after
    its part's atomic+fsync'd publish, observe histograms and the
    solved recalibration table persist as atomic sidecars, and with
    ``resume=True`` a rerun after an arbitrary process kill skips the
    completed windows — bit-identical to an uninterrupted run.  A
    resume whose input content, flag composition or window plan differs
    from the journal's fingerprint is refused with a clean restart
    (stale parts discarded), never mixed output.

    ``pacer`` and ``device_pool`` are the multi-job service's seams
    (``adam_tpu/serve``): ``pacer(phase, index)`` is called once per
    window at the pass-A and pass-C boundaries — the scheduler's
    fairness interleaver blocks there to weight windows across
    concurrent jobs, and raises :class:`RunCancelled` to stop the run
    gracefully at that boundary (parts already submitted still publish
    and journal).  ``device_pool`` (a
    :class:`~adam_tpu.parallel.device_pool.DevicePool` or
    :class:`~adam_tpu.parallel.device_pool.PoolLease`) substitutes a
    shared pool for the run's own, so concurrent jobs place windows on
    the same chips; pacing and pool sharing change only where and when
    work runs, never the output bytes.

    ``coalescer`` (a :class:`~adam_tpu.serve.batching.CoalescerClient`)
    routes this run's per-window device dispatches through the
    scheduler's cross-job :class:`~adam_tpu.serve.batching.WindowCoalescer`
    so concurrent jobs' windows merge into ONE fused dispatch per pass
    (docs/SERVING.md "Continuous batching & quotas").  Device backend +
    pool partitioner only (the mesh already fuses the device set per
    window); a coalesced window that fails falls back to this run's own
    solo dispatch path — byte-identical output either way.

    ``trace`` is the run's trace context (docs/OBSERVABILITY.md): the
    job-scoped trace_id minted at gateway/scheduler admission.  Solo
    runs mint their own, so every run is traceable.  The run tracer
    stamps every span it records with it, and it selects this run's
    events in the gateway ``/trace`` export and incident bundles.
    Tracing changes attribution metadata only, never output bytes.

    ``known_table`` is a pre-solved recalibration table ``(u8[n_rg,
    N_QUAL, n_cyc, N_DINUC] ndarray, gl)`` — the known-sites workflow,
    where the table shipped with the cohort instead of being discovered
    from this input.  It REPLACES the solved table at barrier 2 (the
    observe pass and the histogram merge still run, so
    ``dump_observations`` and the resume sidecars see the same
    artifacts), and it arms the fused B→C megakernel tier
    (docs/PERF.md "Megakernel tier"): with the applied table known at
    ingest, each eligible window's observe scatter-add and apply+pack
    gather compose into ONE donated dispatch
    (``bqsr.fused_bc_dispatch``), eliminating the per-window barrier-2
    round-trip.  Output bytes are identical fused or not
    (``ADAM_TPU_FUSED_BC=0`` is the unfused A/B leg).
    """
    from adam_tpu.utils import incidents

    # Per-run tracer, ALWAYS recording: the returned stats dict is a
    # derived view of its span data (telemetry.streamed_stats_view), so
    # the two can never disagree.  The handful of stage/window spans it
    # records per run is negligible next to the work; it folds into the
    # global TRACE at the end when telemetry is enabled.
    tr = tele.Tracer(recording=True)
    if trace is None:
        trace = tele.mint_trace_id()
    tr.set_trace(trace)
    tele.activate_trace(trace)
    # solo runs with a durable run dir arm the incident recorder on it;
    # under the scheduler it is already armed on the service run root
    # (install-first wins — a job must not re-point the service's)
    armed_incidents = False
    if run_dir is not None and not incidents.installed():
        incidents.install(run_dir)
        armed_incidents = True
    # a paced run is a multi-job service job: its heartbeat must carry
    # job-scoped counters only (see _start_heartbeat's include_global)
    hb = _start_heartbeat(tr, progress, include_global=pacer is None)
    try:
        stats = _transform_streamed_impl(
            path, out_path, tr, hb,
            mark_duplicates=mark_duplicates, recalibrate=recalibrate,
            realign=realign, known_snps=known_snps,
            known_indels=known_indels, consensus_model=consensus_model,
            window_reads=window_reads, compression=compression,
            n_writers=n_writers, max_indel_size=max_indel_size,
            max_consensus_number=max_consensus_number,
            lod_threshold=lod_threshold, max_target_size=max_target_size,
            dump_observations=dump_observations, known_table=known_table,
            devices=devices,
            partitioner=partitioner, run_dir=run_dir, resume=resume,
            pacer=pacer, device_pool=device_pool, coalescer=coalescer,
        )
        # perf-ledger booking (utils/perfledger.py): every completed
        # run books its bench-diff keys — into the armed service root
        # under the scheduler (one longitudinal history per service),
        # else this run's own durable run_dir.  The sentinel judges
        # the new entry against the rolling median baseline; a flagged
        # regression emits a perf.regression bundle and charges the
        # SLO budget.  Booking failures never fail the run.
        from adam_tpu.utils import perfledger

        ledger_root = perfledger.ledger_root() or run_dir
        if ledger_root is not None and perfledger.booking_enabled():
            try:
                perfledger.sentinel(
                    ledger_root, tr.snapshot(), run_id=trace,
                )
            except Exception:
                log.warning("perf-ledger booking failed", exc_info=True)
        return stats
    except BaseException:
        # crashed run: the final heartbeat line must carry ok=false —
        # a tailing consumer reading done=true as "completed" would
        # otherwise mark a failed run green
        _stop_heartbeat(hb, ok=False)
        raise
    finally:
        # normal completion already stopped it (inside _finish_trace,
        # before the absorb); this is a no-op backstop
        _stop_heartbeat(hb)
        tele.deactivate_trace(trace)
        if armed_incidents:
            incidents.uninstall()


def _transform_streamed_impl(
    path: str,
    out_path: str,
    tr: tele.Tracer,
    hb,
    *,
    mark_duplicates: bool,
    recalibrate: bool,
    realign: bool,
    known_snps,
    known_indels,
    consensus_model: str,
    window_reads: int,
    compression: str,
    n_writers: int,
    max_indel_size: int | None,
    max_consensus_number: int | None,
    lod_threshold: float | None,
    max_target_size: int | None,
    dump_observations: Optional[str],
    known_table: Optional[tuple],
    devices: Optional[int],
    partitioner: Optional[str],
    run_dir: Optional[str],
    resume: bool,
    pacer=None,
    device_pool=None,
    coalescer=None,
) -> dict:
    from adam_tpu.parallel import partitioner as part_mod
    from adam_tpu.pipelines import bqsr as bqsr_mod
    from adam_tpu.pipelines import markdup as md_mod
    from adam_tpu.pipelines import realign as realign_mod

    # live in-flight deques the heartbeat provider samples: (deque,
    # index of the device element in its items)
    hb_queues: list = []
    t_start_ns = time.monotonic_ns()
    stats: dict = {}
    # one backend decision for every per-residue pass in this run: the
    # device kernels (BQSR observe/apply scatter-gathers, markdup [N, L]
    # reductions) are the default when a chip is attached, the host
    # kernels otherwise; ADAM_TPU_BQSR_BACKEND overrides
    backend = bqsr_mod.bqsr_backend()
    use_device = backend == "device"
    stats["bqsr_backend"] = backend
    # multi-chip fan-out: window i's device work round-robins to device
    # i % n; None means single-device (the pre-pool path, bit-for-bit)
    dpool = None
    if use_device:
        # a shared pool (the multi-job service's lease) substitutes for
        # the run's own — same duck-typed surface, shared eviction state
        dpool = (
            device_pool if device_pool is not None
            else dp_mod.make_pool(devices)
        )
    stats["n_devices"] = dpool.n if dpool is not None else (
        1 if use_device else 0
    )
    # device health / hedging / SDC audit (utils/health.py,
    # docs/ROBUSTNESS.md "Device health, hedging, and SDC audit"): the
    # process-wide scoreboard feeds placement (probation devices are
    # excluded until their re-admission probe passes — and the mesh
    # construction below spans only the healthy subset); pass C hedges
    # in-flight windows past ADAM_TPU_HEDGE_FACTOR x the apply kernel's
    # observed p99, and deterministically samples ADAM_TPU_AUDIT_RATE
    # of windows for a host dual-compute bit comparison — a mismatch
    # quarantines the producing chip and replays the window from the
    # host copy, so the published part is clean either way.
    health_board = health_mod.BOARD
    sdc_audit_rate = health_mod.audit_rate() if use_device else 0.0
    stats["audit_rate"] = sdc_audit_rate
    # execution partitioner (--partitioner / ADAM_TPU_PARTITIONER):
    # "pool" round-robins whole windows; "mesh" shards every window
    # over a batch Mesh spanning the same device set, psums the
    # observe histograms on-device and keeps the solved table resident
    # through pass C.  The pool stays constructed either way — it IS
    # the degrade target when the mesh path fails mid-run.
    exec_mode = part_mod.resolve_execution_mode(partitioner)
    mesh_part = None
    if use_device and exec_mode == "mesh":
        try:
            import jax

            if device_pool is not None:
                # a shared-pool job's mesh spans exactly the leased
                # device set, so collectives never touch chips outside
                # the scheduler's pool
                mesh_devs = list(device_pool.devices)
            else:
                n_mesh = dp_mod.resolve_device_count(devices)
                mesh_devs = jax.local_devices()[:n_mesh]
            # mesh construction consults the health scoreboard: a
            # collective spans every mesh device, so ONE probation
            # chip would poison every window — build the mesh over the
            # healthy subset (all-blocked falls back to the full set;
            # availability beats health, and the pool degrade path
            # still owns mid-run failures)
            mesh_devs = part_mod.healthy_subset(mesh_devs, health_board)
            mesh_part = part_mod.MeshPartitioner(mesh_devs)
        except Exception as e:
            log.warning(
                "mesh partitioner unavailable (%s); using the pool path",
                e,
            )
    exec_state = {
        "mesh": mesh_part,
        "mode": "mesh" if mesh_part is not None else "pool",
    }
    stats["partitioner"] = exec_state["mode"]
    # pass-C packed-column fetch (ADAM_TPU_PACKED_COLS, default on for
    # the device backend): the apply kernel emits the flat encode-ready
    # SANGER qual payload on device and the d2h fetch ships
    # sum(lengths) bytes instead of the [N, L] matrix; the writer pool
    # assembles the arrow column zero-copy over the fetched buffer
    # (io/arrow_pack).  Host/degraded windows fall back to the matrix
    # path, byte-identically.
    from adam_tpu.ops.colpack import packed_columns_enabled

    use_packed = use_device and packed_columns_enabled()
    stats["packed_columns"] = use_packed
    # kernel backend (ADAM_TPU_KERNEL_BACKEND, ops/kernel_backend): the
    # Pallas/XLA selector every per-residue body reads at trace time.
    # Gauged once — the backend is a process-wide decision, and the
    # analyzer/bench artifacts attribute kernel walls against it.
    from adam_tpu.ops.kernel_backend import kernel_backend

    stats["kernel_backend"] = kernel_backend()
    tr.gauge(
        tele.G_KERNEL_BACKEND,
        1 if stats["kernel_backend"] == "pallas" else 0,
    )
    # device-resident windows (ADAM_TPU_RESIDENT, default on for the
    # device backend; docs/PERF.md "Device-resident windows"): each
    # window's bases/quals/lengths/flags/rg land on device ONCE at
    # ingest — pinned per pool device, or as one mesh-sharded placement
    # — and every pass dispatches against the handle; the later passes
    # ship only their per-pass inputs (bit-packed MD masks, post-split
    # validity bools), so the ledger's per-pass h2d collapses to the
    # one ingest entry.  With packed columns on, pass C upgrades to the
    # fused bases+quals pack (the bases half of the packed tail).
    use_resident = use_device and dp_mod.resident_windows_enabled()
    stats["resident_windows"] = 0
    # cross-job window batching (serve/batching.py): the scheduler's
    # coalescer client merges this job's per-window dispatches with its
    # neighbors' into one fused dispatch per pass.  Device backend
    # only; the per-hook guards additionally skip it while the mesh
    # partitioner is live (the mesh already fuses the device set).
    if not use_device:
        coalescer = None
    stats["batched"] = coalescer is not None

    def _win_nbytes(b) -> int:
        """A window's grant size (bytes) for the fairness ring / quota
        leg: the per-residue payload the device passes actually move."""
        try:
            return int(b.bases.nbytes) + int(b.quals.nbytes)
        except AttributeError:
            return 0
    # pass-B windows folded into the mesh's device-resident observe
    # accumulator, kept referenced so a degrade can replay them through
    # the pool/host path; the host-side merge lists live up here too so
    # the degrade hook can append to them from any pass
    mesh_obs: list = []
    obs_parts: list = []
    obs_replays: list = []
    obs_windows: list = []
    # megakernel tier (docs/PERF.md): window idx -> (producing device |
    # "mesh", packed2 apply handle) stashed by the fused B→C dispatch in
    # pass B — pass C pops and FETCHES these instead of dispatching an
    # apply.  A window absent here takes the separate-pass path.
    fused_handles: dict = {}
    if use_device:
        tr.gauge(tele.G_POOL_DEVICES, stats["n_devices"])
    if hb is not None:
        # HBM sampling keys must match the device=<k> span attribution,
        # so the heartbeat polls exactly the run's device set
        if mesh_part is not None:
            hb.set_devices(mesh_part.devices)
        elif dpool is not None:
            hb.set_devices(dpool.devices)
        hb.set_provider(lambda: {
            "inflight_per_device": _inflight_per_device(hb_queues),
            # live mode, not the launch mode: a degraded mesh run
            # reports "pool" from its next beat on
            "partitioner": exec_state["mode"] if use_device else None,
        })
    os.makedirs(out_path, exist_ok=True)
    # purge a crashed run's staging dir: io/parquet publishes each part
    # by atomic rename out of out_path/_temporary, so a SIGKILL'd run
    # leaves its torn files THERE (readers ignore the _-prefixed dir),
    # never as truncated part-*.parquet — and a rerun starts clean
    from adam_tpu.io.parquet import purge_stale_staging

    purge_stale_staging(out_path)

    # ---- resilience (docs/ROBUSTNESS.md): a device that spends its
    # retry budget is evicted and its in-flight windows replay on the
    # survivors; when the last device is gone, every remaining
    # per-residue pass runs on the native/numpy host backend.  Output
    # stays bit-identical on every path: the barrier merges are
    # window-ordered and the backends are bit-parity twins
    # (tests/test_backend_parity.py).
    res = {"device_lost": False}

    def _host_backend() -> str:
        from adam_tpu import native

        return "native" if native.available() else "numpy"

    # ---- device-resident windows: handle registry + lifecycle ----------
    # window index -> ResidentWindow; the live-bytes ledger backs the
    # no-HBM-growth invariant (gauge returns to 0 as pass C releases)
    resident_map: dict = {}
    resident_live = {"bytes": 0, "made": 0}

    def _make_resident(win, ds):
        """Place window ``win``'s resident payload at ingest (pinned on
        its round-robin pool device, or mesh-sharded).  Best-effort: a
        failed placement just leaves the window on the legacy
        re-ship-per-pass path."""
        if not use_resident or res["device_lost"]:
            return
        b = ds.batch.to_numpy()
        mp = exec_state["mesh"]
        try:
            # the one per-window h2d: attributed to the ledger's
            # ``ingest`` bucket, which the analyzer's residency verdict
            # compares against the (≈0) observe/apply buckets
            with tele.pass_scope("ingest"):
                if mp is not None:
                    rw = part_mod.mesh_resident_window(b, win, mp)
                else:
                    dev = _pick_device(win)
                    if dev is _HOST:
                        return
                    rw = dp_mod.make_resident_window(b, win, dev)
        except Exception as e:
            log.warning(
                "resident placement of window %d failed (%s); the "
                "window re-ships per pass", win, e,
            )
            return
        resident_map[win] = rw
        resident_live["bytes"] += rw.nbytes
        resident_live["made"] += 1
        tr.count(tele.C_RESIDENT_WINDOWS)
        tr.count(tele.C_RESIDENT_BYTES, rw.nbytes)
        tr.gauge(tele.G_RESIDENT_LIVE, resident_live["bytes"])

    def _release_resident(win, drop=False):
        """Release window ``win``'s handle (the refcounted base ref —
        after its pass-C fetch, or at the skip/fault sites); ``drop``
        marks a fault-path drop (eviction, degrade) for the counters."""
        rw = resident_map.pop(win, None)
        if rw is None:
            return
        rw.drop() if drop else rw.release()
        resident_live["bytes"] -= rw.nbytes
        tr.count(
            tele.C_RESIDENT_EVICTED if drop else tele.C_RESIDENT_RELEASED
        )
        tr.gauge(tele.G_RESIDENT_LIVE, resident_live["bytes"])

    def _drop_resident_on(dev):
        """An evicted device takes its pinned windows with it: their
        later passes re-ship from the host-retained ingest copy."""
        for win, rw in list(resident_map.items()):
            if rw.device is dev:
                _release_resident(win, drop=True)

    def _drop_all_resident():
        for win in list(resident_map):
            _release_resident(win, drop=True)

    def _evict_or_lose(dev, exc) -> bool:
        """Evict a failed device; True = survivors remain, False = the
        device path is gone (callers fall back to the host backend)."""
        _drop_resident_on(dev)
        if dpool is not None:
            dpool.evict(dev, reason=str(exc), tracer=tr)
            if dpool.alive_devices():
                return True
        else:
            log.error(
                "device path failed (%s); running the rest of this "
                "pipeline on the %s host backend", exc, _host_backend(),
            )
        res["device_lost"] = True
        _drop_all_resident()
        return False

    def _pick_device(win):
        """Window's round-robin device: a jax device (pool), None (the
        single-chip default device), or _HOST once the path is lost."""
        if res["device_lost"]:
            return _HOST
        if dpool is None:
            return None
        try:
            return dpool.device(win)
        except dp_mod.AllDevicesEvicted:
            res["device_lost"] = True
            return _HOST

    def _on_survivors(win, device_fn, host_fn):
        """THE recovery loop, shared by every dispatch/replay site: run
        ``device_fn(dev)`` on window's round-robin device, evicting and
        walking to the next survivor on failure (transient retries
        already happened inside the call), ``host_fn()`` once the
        device path is lost."""
        while True:
            dev = _pick_device(win)
            if dev is _HOST:
                return host_fn()
            try:
                return device_fn(dev)
            except Exception as e:
                _evict_or_lose(dev, e)

    def _mesh_degrade(exc, where: str = ""):
        """A mesh collective failed past its retry budget: abandon the
        mesh for the rest of the run (the accumulator on a dying device
        set is no longer trustworthy) and fall back to the pool path —
        bit-identical by the backend-parity contract.  Windows already
        folded into the accumulator replay through the pool/host
        observe under a ``device.pool.replay`` umbrella, so a dead
        mesh costs the replayed windows, never the run."""
        mp = exec_state["mesh"]
        if mp is None:
            return
        exec_state["mesh"] = None
        exec_state["mode"] = "pool"
        stats["partitioner"] = "pool"
        # mesh-sharded resident handles die with the mesh: the pool
        # path takes their windows over by re-shipping from the
        # host-retained ingest copy (docs/ROBUSTNESS.md)
        _drop_all_resident()
        # fused B→C outputs sharded over the dying mesh are no longer
        # trustworthy either: forget them, so pass C re-applies those
        # windows through the separate-pass pool/host path
        fused_handles.clear()
        tr.count(tele.C_MESH_DEGRADED)
        log.error(
            "mesh partitioner failed%s (%s); degrading to the pool path"
            "%s", f" at {where}" if where else "", exc,
            (f" and replaying {len(mesh_obs)} accumulated window(s)"
             if mesh_obs else ""),
        )
        mp.reset_accumulator()
        if mesh_obs:
            with tr.span(tele.SPAN_POOL_REPLAY, device="mesh"), \
                    dp_mod.replay_scope():
                for i, w in list(mesh_obs):
                    got = _observe_window(i, w)
                    if got is not None:
                        obs_parts.append(got[0])
                        obs_replays.append(got[1])
                        obs_windows.append(i)
            mesh_obs.clear()
    if known_indels is not None and consensus_model == "reads":
        # supplying known indels implies the knowns consensus model (the
        # reference's -known_indels flag semantics; realign_indels only
        # consults the table under that model)
        consensus_model = "knowns"
    mis, mcn, lod, mts = realign_mod.resolve_tuning(
        max_indel_size, max_consensus_number, lod_threshold, max_target_size
    )

    # ---- durable window-granular resume (docs/ROBUSTNESS.md) -----------
    # The journal fingerprints input content identity + the full
    # output-bit-affecting flag composition (the backend/device count is
    # deliberately EXCLUDED: the kernels are bit-parity twins, so a
    # resume on different hardware is still bit-identical).  Window
    # completion is recorded only after a part's durable publish, via
    # the writer pool's on_published hook below.
    journal = None
    if run_dir:
        from adam_tpu.pipelines import checkpoint as ck_mod

        fp = ck_mod.compose_fingerprint({
            "schema": "adam_tpu.streamed/1",
            "input": ck_mod.input_fingerprint(path),
            "mark_duplicates": mark_duplicates,
            "recalibrate": recalibrate,
            "realign": realign,
            "consensus_model": consensus_model,
            "window_reads": window_reads,
            "compression": compression,
            "max_indel_size": mis,
            "max_consensus_number": mcn,
            "lod_threshold": lod,
            "max_target_size": mts,
            "known_snps": known_snps,
            "known_indels": known_indels,
            # a known-sites table changes the applied (= output) bytes:
            # content-digested into the fingerprint, so a resume under a
            # different table is refused instead of mixing output.  The
            # key is absent (not None) without one — discovered-table
            # journals keep their pre-existing fingerprints.
            **({"known_table": (
                hashlib.sha256(
                    np.ascontiguousarray(known_table[0], np.uint8)
                    .tobytes()
                ).hexdigest(),
                int(known_table[1]),
            )} if known_table is not None else {}),
        })
        journal = ck_mod.RunJournal(
            run_dir, fp, out_path, resume=resume, tracer=tr
        )

    # ---- megakernel tier (docs/PERF.md "Megakernel tier") -------------
    # With the applied table known BEFORE pass B — a known-sites run, or
    # a -dump_observations resume whose journal already holds the solved
    # table (re-observing only for the merge artifacts) — each eligible
    # window's observe and apply+pack fuse into one donated dispatch.
    # Eligibility mirrors the packed2 fast path (device backend + packed
    # columns + resident windows); the cross-job coalescer owns its own
    # fusion, so a coalesced run keeps the separate passes.
    fused_table = None
    if (
        recalibrate and use_device and use_packed and use_resident
        and coalescer is None and bqsr_mod.fused_bc_enabled()
    ):
        if known_table is not None:
            fused_table = (
                np.ascontiguousarray(known_table[0], np.uint8),
                int(known_table[1]),
            )
        elif journal is not None and journal.resumed and dump_observations:
            # the dump forces a full re-observe (resume_table stays
            # None below), but the journal's solved table — identical
            # to what this merge will re-solve, same input + sidecar
            # histograms — is already the applied table
            lt = journal.load_table()
            if lt is not None:
                fused_table = (
                    np.ascontiguousarray(lt[0], np.uint8), int(lt[1])
                )
    stats["fused_bc"] = fused_table is not None
    tr.gauge(tele.G_FUSED_BC, 1 if fused_table is not None else 0)

    # ---- pass A: ingest || summaries + events --------------------------
    in_q: queue.Queue = queue.Queue(maxsize=3)
    abort = threading.Event()
    ingest = threading.Thread(
        target=_ingest_windows, args=(path, window_reads, in_q, abort, tr),
        daemon=True,
    )
    ingest.start()

    windows: list[AlignmentDataset] = []
    summaries: list[dict] = []
    events = []
    header = None
    n_reads = 0
    # device in-flight queue of (window idx, ds, lazy (five, score)):
    # depth 2 on the single-device path (the classic double buffer);
    # with a pool, a double buffer PER device (2n) — round-robin keeps
    # the drain order == window order, so summaries stay window-ordered
    # and the duplicate resolve is bitwise independent of n
    md_depth = 2 if dpool is None else 2 * dpool.n
    pend_cols: deque = deque()
    hb_queues.append((pend_cols, 2))  # items: (win, ds, dev, cols)

    def _md_dispatch(win, batch, coalesce=True):
        """Dispatch one window's [N, L] markdup reductions -> (device,
        lazy cols), walking to the next survivor after a spent retry
        budget; None = compute the summary on the host instead.  Under
        the mesh partitioner the window shards across every device at
        once (device tag ``"mesh"``); a mesh failure degrades to the
        pool path and re-dispatches here.  With a coalescer attached
        (and the pool partitioner live) the window submits to the
        cross-job batch instead — device tag ``"batch"``, cols a
        future; a coalesce failure re-enters here with
        ``coalesce=False``."""
        mp = exec_state["mesh"]
        if (
            coalesce and coalescer is not None and mp is None
            and not res["device_lost"]
        ):
            try:
                fut = coalescer.submit_markdup(
                    win, batch, resident_map.get(win)
                )
                return "batch", fut
            except Exception as e:
                log.warning(
                    "coalesced markdup submit of window %d failed "
                    "(%s); dispatching solo", win, e,
                )
        if mp is not None:
            try:
                cols = md_mod.markdup_columns_dispatch(
                    batch, mesh=mp, resident=resident_map.get(win)
                )
                tr.count(tele.C_DEVICE_DISPATCHED)
                tr.count(tele.C_MESH_DISPATCHED)
                return "mesh", cols
            except Exception as e:
                _mesh_degrade(e, "pass-A markdup")

        def on_device(dev):
            # the dispatch validates the handle itself (device match +
            # aliveness), so a replay on a survivor just re-ships
            cols = md_mod.markdup_columns_dispatch(
                batch, device=dev, resident=resident_map.get(win)
            )
            tr.count(tele.C_DEVICE_DISPATCHED)
            return dev, cols

        return _on_survivors(win, on_device, lambda: None)

    def _summarize(win, ds, dev, cols):
        if dev == "batch":
            # coalesced window: the future resolves to host (five,
            # score) slices bitwise the solo columns; a fused-dispatch
            # failure falls back to this window's own solo path (which
            # owns eviction/replay/host-degrade)
            try:
                five, score = cols.result()
            except Exception as e:
                log.warning(
                    "coalesced markdup of window %d fell back to the "
                    "solo dispatch (%s)", win, e,
                )
                nxt = _md_dispatch(win, ds.batch, coalesce=False)
                if nxt is None:
                    summaries.append(md_mod.row_summary(ds))
                    return
                dev, cols = nxt
            else:
                summaries.append(md_mod.row_summary(
                    ds, five_prime=five, score=score
                ))
                return
        while cols is not None:
            try:
                with tr.span(tele.SPAN_MD_FETCH):
                    five = np.asarray(device_fetch(cols[0]))
                    score = np.asarray(device_fetch(cols[1]))
            except Exception as e:
                # fetch failed past the transfer layer's retry budget:
                # evict the chip (or abandon the mesh) and replay the
                # window's reductions on what remains (the loop
                # re-fetches), host when nothing is left
                with tr.span(tele.SPAN_POOL_REPLAY, window=win,
                             **dp_mod.span_attrs(dev)), \
                        dp_mod.replay_scope():
                    if dev == "mesh":
                        _mesh_degrade(e, "pass-A markdup fetch")
                    else:
                        _evict_or_lose(dev, e)
                    nxt = _md_dispatch(win, ds.batch)
                if nxt is None:
                    break
                dev, cols = nxt
                continue
            tr.count(tele.C_DEVICE_FETCHED)
            summaries.append(
                md_mod.row_summary(ds, five_prime=five, score=score)
            )
            return
        summaries.append(md_mod.row_summary(ds))

    # ---- long-tail shape prewarm ---------------------------------------
    # The window-0 prewarm covers only window 0's grid; residual windows
    # (a short final ingest window drops to a smaller pow2 row grid) and
    # the realigned tail part land on shapes it never saw and used to
    # cold-compile INSIDE their window (the measured grid-1024 0.26 s
    # `device.compile.in_window` entry, docs/PERF.md).  Re-prewarm on
    # FIRST SIGHT of each new grid shape instead — the process-wide
    # dedupe cache makes repeats (and warm bench runs) free.
    seen_grid_shapes: set = set()

    def _prewarm_window_shapes(ds):
        mp = exec_state["mesh"]
        if (mp is None and dpool is None) or res["device_lost"]:
            return
        b = ds.batch.to_numpy()
        from adam_tpu.formats.batch import (
            grid_cigar_cols, grid_cols, grid_rows,
        )

        key = (
            grid_rows(b.n_rows), grid_cols(b.lmax),
            grid_cigar_cols(
                b.cigar_ops.shape[1] if b.cigar_ops.ndim == 2 else 1
            ),
            exec_state["mode"],
        )
        if key in seen_grid_shapes:
            return
        seen_grid_shapes.add(key)
        n_rg = len(ds.read_groups) + 1
        t_pw = time.monotonic_ns()
        try:
            if mp is not None:
                entries = []
                if mark_duplicates:
                    entries.append(
                        part_mod.mesh_markdup_prewarm_entry(b, mp)
                    )
                if recalibrate:
                    entries.append(
                        part_mod.mesh_observe_prewarm_entry(b, n_rg, mp)
                    )
                    if use_resident:
                        entries.append(
                            part_mod.mesh_observe_packed_prewarm_entry(
                                b, n_rg, mp
                            )
                        )
                    if fused_table is not None:
                        # the megakernel the fused tier will dispatch,
                        # at the KNOWN table's cycle width
                        entries.append(
                            part_mod.mesh_fused_bc_prewarm_entry(
                                b, n_rg, fused_table[0].shape[2], mp
                            )
                        )
                mp.prewarm(entries, tracer=tr)
            else:
                from adam_tpu.parallel.device_pool import (
                    streamed_prewarm_entries,
                )

                dpool.prewarm(
                    streamed_prewarm_entries(
                        b, n_rg, mark_duplicates=mark_duplicates,
                        recalibrate=recalibrate,
                        packed_apply=use_packed,
                        resident=use_resident,
                        fused_n_cyc=(
                            fused_table[0].shape[2]
                            if fused_table is not None else None
                        ),
                    ),
                    tracer=tr,
                )
        finally:
            # the umbrella records the WALL (the stats view subtracts
            # it back out of the enclosing pass's row)
            tr.add_span(
                tele.SPAN_POOL_PREWARM, t_pw,
                time.monotonic_ns() - t_pw,
            )

    def _prewarm_observe_shape(ds):
        """Tail hook: warm the observe kernel at the REALIGNED part's
        grid before its in-window dispatch (its row/lane grid rarely
        matches any ingest window's)."""
        mp = exec_state["mesh"]
        if (
            not recalibrate or res["device_lost"]
            or (mp is None and dpool is None)
        ):
            return
        b = ds.batch.to_numpy()
        n_rg = len(ds.read_groups) + 1
        t_pw = time.monotonic_ns()
        try:
            if mp is not None:
                entries = [part_mod.mesh_observe_prewarm_entry(b, n_rg, mp)]
                if use_resident:
                    entries.append(
                        part_mod.mesh_observe_packed_prewarm_entry(
                            b, n_rg, mp
                        )
                    )
                if fused_table is not None:
                    entries.append(
                        part_mod.mesh_fused_bc_prewarm_entry(
                            b, n_rg, fused_table[0].shape[2], mp
                        )
                    )
                mp.prewarm(entries, tracer=tr)
            else:
                entries = [dp_mod.observe_prewarm_entry(b, n_rg)]
                if use_resident:
                    entries.append(
                        dp_mod.observe_packed_prewarm_entry(b, n_rg)
                    )
                if fused_table is not None:
                    entries.append(
                        dp_mod.fused_bc_prewarm_entry(
                            b, n_rg, fused_table[0].shape[2]
                        )
                    )
                dpool.prewarm(entries, tracer=tr)
        finally:
            tr.add_span(
                tele.SPAN_POOL_PREWARM, t_pw,
                time.monotonic_ns() - t_pw,
            )

    # transfer-ledger pass attribution: every h2d put / d2h fetch on
    # this thread inside the scope lands under the pass's bucket in the
    # snapshot's ``transfers`` section (prewarm shadows with its own)
    with tr.span(tele.SPAN_PASS_A), tele.pass_scope("a"):
        try:
            while True:
                item = in_q.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, BaseException):
                    raise item
                batch, side, header = item
                ds = AlignmentDataset(batch, side, header)
                windows.append(ds)
                win = len(windows) - 1
                # reads counted PER WINDOW (not once at pass-A exit):
                # the live heartbeat's reads/s derives from this counter
                # mid-ingest; the end-of-run total is identical
                n_window_reads = int(batch.valid.sum())
                n_reads += n_window_reads
                tr.count(tele.C_READS_INGESTED, n_window_reads)
                tr.count(tele.C_WINDOWS_INGESTED)
                # chaos-harness kill point: one arrival per pass-A window
                faults.point("proc.kill", device="pass_a")
                # multi-job fairness / graceful drain: the scheduler's
                # interleaver grants this job one window (or raises
                # RunCancelled at this boundary — nothing is in flight
                # for this window yet, so the resume re-runs it).  The
                # grant carries the window's byte size, so the fairness
                # ring can reason in bytes-per-grant (quota Retry-After)
                if pacer is not None:
                    pacer("pass_a", win, _win_nbytes(batch))
                # compile the grid-quantized kernel set for this
                # window's grid shape BEFORE its device work — a
                # 20-40 s cold remote compile must never serialize
                # inside a window.  First sight of each shape only
                # (window 0 plus any residual-grid stragglers);
                # process-wide cache makes warm runs a no-op.
                if use_device:
                    _prewarm_window_shapes(ds)
                    # ingest-once H2D: the window's resident payload
                    # places NOW — markdup keys, observe and apply all
                    # dispatch against this one placement
                    _make_resident(win, ds)
                if mark_duplicates:
                    # dispatch window i's [N, L] key/score reductions
                    # (on device i % n under a pool), then drain the
                    # oldest in-flight window once the queue is full —
                    # its columns had the whole queue depth to compute
                    # on their chip.  _md_dispatch handles eviction and
                    # returns None on the host paths.
                    disp = _md_dispatch(win, batch) if use_device else None
                    if disp is not None:
                        pend_cols.append((win, ds) + disp)
                        tr.gauge(tele.G_DEVICE_INFLIGHT, len(pend_cols))
                        if len(pend_cols) >= md_depth:
                            _summarize(*pend_cols.popleft())
                    else:
                        # the device path may have just died with OLDER
                        # windows still in flight: drain them first —
                        # summaries must stay window-ordered, or the
                        # resolve barrier's offset slices apply
                        # duplicate flags to the wrong windows' rows
                        while pend_cols:
                            _summarize(*pend_cols.popleft())
                        _summarize(win, ds, None, None)
                if realign:
                    events.append(
                        realign_mod.extract_indel_event_arrays(
                            batch.to_numpy(), max_indel_size=mis
                        )
                    )
            while pend_cols:
                _summarize(*pend_cols.popleft())
        except BaseException:
            abort.set()
            raise
        ingest.join()
    stats["n_reads"] = n_reads
    # pin/validate the window plan and fix the resumable set: a window
    # (or the realigned tail part, index n_windows) whose part the
    # journal records as durably published is skipped in pass C
    if journal is not None:
        journal.confirm_plan(len(windows))
    done_parts = (
        journal.completed_windows() if journal is not None else frozenset()
    )
    n_resumed = len(done_parts)
    stats["windows_resumed"] = n_resumed
    if n_resumed:
        tr.count(tele.C_RESUME_WINDOWS_SKIPPED, n_resumed)
        # no total in the message: whether a realigned tail part exists
        # (the +1) is not known until the candidate split
        log.info(
            "resume: %d output window(s) already durably published; "
            "re-executing only the remainder", n_resumed,
        )
    if hb is not None:
        hb.set_total(len(windows))
    if header is None or not windows:
        tr.add_span(tele.SPAN_TOTAL, t_start_ns,
                    time.monotonic_ns() - t_start_ns)
        stats.update(tele.streamed_stats_view(tr.snapshot()))
        _finish_trace(tr, stats, hb)
        return stats

    # ---- barrier 1: resolve duplicates + merge targets ----------------
    def _resolve_sort_device():
        """Where the duplicate-resolve lexsort runs: a pool/mesh device
        (the packed summary keys sort on-chip via dist.device_lexsort,
        bitwise the host permutation) or None for the host np.lexsort.
        ``ADAM_TPU_RESOLVE_SORT={device,host}`` overrides the
        device-when-available default."""
        mode = os.environ.get("ADAM_TPU_RESOLVE_SORT", "").strip().lower()
        if mode and mode not in ("device", "host"):
            # the tuning-var contract every other ADAM_TPU_* knob keeps:
            # a typo warns and degrades to the default, never silently
            # does something else
            log.warning(
                "ADAM_TPU_RESOLVE_SORT=%r is not one of ('device', "
                "'host'); using the device-when-available default", mode,
            )
            mode = ""
        if mode == "host":
            return None
        if not use_device:
            # explicit override only: host backends keep the host sort
            # unless the operator asks for the default jax device
            return "default" if mode == "device" else None
        if res["device_lost"]:
            return None
        mp = exec_state["mesh"]
        if mp is not None:
            return mp.devices[0]
        if dpool is not None:
            alive = dpool.alive_devices()
            return alive[0] if alive else None
        return "default"

    with tr.span(tele.SPAN_RESOLVE), tele.pass_scope("resolve"):
        if mark_duplicates and summaries:
            sort_dev = _resolve_sort_device()
            sort_info: dict = {}
            dup = md_mod.resolve_duplicates(
                md_mod.concat_summaries(summaries), sort_device=sort_dev,
                sort_info=sort_info,
            )
            # gauge the OUTCOME, not the request: device_lexsort falls
            # back to the host np.lexsort internally on failure, and the
            # analyzer's "[device sort]" tag must never claim a win the
            # host sort actually delivered
            tr.gauge(
                tele.G_RESOLVE_DEVICE_SORT,
                1 if sort_info.get("device_sort") else 0,
            )
            off = 0
            for i, w in enumerate(windows):
                n = w.batch.n_rows
                b = w.batch.to_numpy()
                new_flags = md_mod.apply_duplicate_flags(
                    np.asarray(b.flags), dup[off : off + n]
                )
                windows[i] = w.with_batch(b.replace(flags=new_flags))
                off += n
            del summaries
        targets = (
            realign_mod.merge_events(
                np.concatenate(events, axis=0) if events
                else np.zeros((0, 5), np.int64),
                header.seq_dict.names, mts,
            )
            if realign
            else []
        )

    # ---- pass B: candidate split (pre-BQSR, reference order) ----------
    # (obs_parts/obs_replays/obs_windows — the host-side merge lists,
    # window-index attributed — are defined up top so the mesh degrade
    # hook can replay into them from any pass)
    with tr.span(tele.SPAN_SPLIT):
        candidates: list[AlignmentDataset] = []
        window_valid: list[int] = []
        for i, w in enumerate(windows):
            n_valid = w.batch.n_rows
            if targets:
                cand, w, n_valid = realign_mod.split_realign_candidates(
                    w, targets, header.seq_dict.names
                )
                if cand is not None:
                    candidates.append(cand)
                windows[i] = w
            window_valid.append(n_valid)

    # post-barrier-2 resume: the solved recalibration table persisted by
    # a previous run short-circuits the whole observe pass — a crash
    # after barrier 2 resumes straight into pass C without re-observing
    # anything.  -dump_observations forces a full re-merge (the CSV is
    # derived from the merged histograms, which the table alone cannot
    # reproduce); per-window sidecars still spare the device work.
    resume_table = None
    if journal is not None and recalibrate and not dump_observations:
        resume_table = journal.load_table()

    def _observe_host(w):
        total, mism, _rg, g = bqsr_mod._observe_device(
            w, known_snps, _host_backend() if use_device else backend
        )
        return device_fetch(total), device_fetch(mism), g

    def _obs_replay(i, w, dev):
        """Recovery hook for window i's barrier fetch: evict the chip
        that held its lazy histograms and recompute on a survivor (the
        host backend when none remain), returning host arrays."""

        def on_device(nd):
            total, mism, _rg, g = bqsr_mod._observe_device(
                w, known_snps, backend, device=nd
            )
            return device_fetch(total), device_fetch(mism), g

        def replay(exc):
            with tr.span(tele.SPAN_POOL_REPLAY, window=i,
                         **dp_mod.span_attrs(dev)), dp_mod.replay_scope():
                _evict_or_lose(dev, exc)
                return _on_survivors(i, on_device, lambda: _observe_host(w))

        return replay

    def _observe_window(i, w, defer=None, coalesce=True):
        """Observe one window -> ((total, mism, g), replay hook) for
        the host-side merge, or **None when the histograms were folded
        into the mesh's device-resident accumulator** (nothing comes
        home until barrier 2 fetches the one merged table), or
        ``_DEFERRED`` when the window rode the cross-job coalescer and
        its future was parked on ``defer`` (pass B resolves them after
        every window has submitted).  Walks
        dispatch failures to the next survivor and to the host backend
        when the pool is gone; a mesh failure degrades to the pool path
        and replays the accumulated windows.  A histogram persisted by
        a previous run (the barrier sidecars) loads instead of
        recomputing — identical int64 sums, so the merge stays
        bit-identical.  ``coalesce=False`` skips the coalescer (the
        fused-failure fallback re-enters here solo)."""
        if journal is not None and journal.resumed:
            got = journal.load_observation(i)
            if got is not None:
                tr.count(tele.C_RESUME_HISTOGRAMS_LOADED)
                return (np.asarray(got[0]), np.asarray(got[1]),
                        got[2]), None
        if not use_device:
            return _observe_host(w), None
        # megakernel tier: with the applied table already known, this
        # window's observe AND its pass-C apply+pack ride ONE donated
        # dispatch — the packed2 handle parks in fused_handles for pass
        # C to FETCH (no second dispatch).  Any ineligibility (no live
        # resident handle, table narrower than the window's grid)
        # returns None from the dispatch and the window falls through
        # to the separate passes below, bitwise identical by
        # construction (fused_bc_body is a pure composition of the two
        # pass bodies).
        if fused_table is not None and not res["device_lost"]:
            rw = resident_map.get(i)
            if rw is not None:
                # chaos-harness kill point: the mid-fused-dispatch leg
                # of the kill-and-resume matrix (nothing persisted yet
                # — a resume replays the window, fused or not)
                faults.point("proc.kill", device="fused_bc")
                mp_f = exec_state["mesh"]
                if mp_f is not None:
                    try:
                        with tele.pass_scope("observe"):
                            got = bqsr_mod.fused_bc_dispatch(
                                w, fused_table[0], known_snps, backend,
                                mesh=mp_f, resident=rw,
                            )
                            if got is not None:
                                handle, (total, mism, _rg, g) = got
                                mp_f.accumulate(total, mism, g)
                    except Exception as e:
                        _mesh_degrade(e, "pass-B fused dispatch")
                        # fall through: separate passes on the pool
                    else:
                        if got is not None:
                            mesh_obs.append((i, w))
                            fused_handles[i] = ("mesh", handle)
                            tr.count(tele.C_DEVICE_DISPATCHED)
                            tr.count(tele.C_MESH_DISPATCHED)
                            tr.count(tele.C_FUSED_DISPATCHED)
                            return None
                else:
                    try:
                        with tele.pass_scope("observe"):
                            got = bqsr_mod.fused_bc_dispatch(
                                w, fused_table[0], known_snps, backend,
                                device=rw.device, resident=rw,
                            )
                    except Exception as e:
                        # past the retry budget: evict the pinned chip
                        # (its resident handles drop with it) and fall
                        # through to the separate-pass survivor walk
                        _evict_or_lose(rw.device, e)
                    else:
                        if got is not None:
                            handle, (total, mism, _rg, g) = got
                            fused_handles[i] = (rw.device, handle)
                            tr.count(tele.C_DEVICE_DISPATCHED)
                            tr.count(tele.C_FUSED_DISPATCHED)
                            # histograms merge exactly like the solo
                            # observe's; a failed barrier fetch evicts
                            # and recomputes through the same hook
                            return (
                                (total, mism, g),
                                _obs_replay(i, w, rw.device),
                            )
        mp = exec_state["mesh"]
        if mp is not None:
            try:
                with tele.pass_scope("observe"):
                    total, mism, _rg, g = bqsr_mod._observe_device(
                        w, known_snps, backend, mesh=mp,
                        resident=resident_map.get(i),
                    )
                    mp.accumulate(total, mism, g)
                mesh_obs.append((i, w))
                tr.count(tele.C_DEVICE_DISPATCHED)
                tr.count(tele.C_MESH_DISPATCHED)
                return None
            except Exception as e:
                _mesh_degrade(e, "pass-B observe")
                # fall through: this window re-dispatches on the pool

        if coalesce and coalescer is not None \
                and exec_state["mesh"] is None \
                and not res["device_lost"]:
            # cross-job batching: this window's observe rides a fused
            # dispatch; its read-group band of the fused histogram is
            # bitwise the solo scatter-add, so the barrier merge (and
            # everything downstream) cannot tell the difference.  Any
            # failure falls through to the solo pool path below.
            try:
                fut = coalescer.submit_observe(
                    i, w, known_snps, resident_map.get(i)
                )
            except Exception as e:
                log.warning(
                    "coalesced observe submit of window %d failed "
                    "(%s); dispatching solo", i, e,
                )
            else:
                if defer is not None:
                    defer.append((i, w, fut))
                    return _DEFERRED
                try:
                    with tele.pass_scope("observe"):
                        got = fut.result()
                except Exception as e:
                    log.warning(
                        "coalesced observe of window %d fell back to "
                        "the solo dispatch (%s)", i, e,
                    )
                else:
                    return got, None

        def on_device(dev):
            total, mism, _rg, g = bqsr_mod._observe_device(
                w, known_snps, backend, device=dev,
                resident=resident_map.get(i),
            )
            tr.count(tele.C_DEVICE_DISPATCHED)
            return (total, mism, g), _obs_replay(i, w, dev)

        with tele.pass_scope("observe"):
            return _on_survivors(
                i, on_device, lambda: (_observe_host(w), None)
            )

    def _observe_remainders():
        # non-candidate rows are untouched by realignment, so their
        # observations are identical on either side of it — which lets
        # this host pass hide under the realign sweeps' device drain.
        # On the device backend the histograms come back LAZY: every
        # window's scatter-add queues on the chip and the compact
        # tables are fetched together at the merge barrier.
        if resume_table is not None:
            # the solved table is already persisted: no observation can
            # change it, so the pass is pure waste on a resume
            return
        with tr.span(tele.SPAN_OBSERVE):
            if recalibrate:
                # coalesced windows park their futures here and resolve
                # AFTER every window has submitted: the coalescer sees
                # the job's whole window set at once (maximal fusion)
                # and the job thread keeps the solo path's overlap
                # instead of serializing on each fused dispatch
                deferred: list = []
                for i, w in enumerate(windows):
                    if window_valid[i]:
                        # chaos-harness kill point: one arrival per
                        # observed window — the mid-pass-B leg of the
                        # kill-and-resume matrix (nothing persisted
                        # yet: a resume replays every un-persisted
                        # observation, resident or not)
                        faults.point("proc.kill", device="pass_b")
                        # pool: window i's scatter-add queues on device
                        # i % n and its compact table merges host-side
                        # at the barrier.  mesh: the window shards over
                        # EVERY device, the histograms psum on-device
                        # and fold into the device-resident accumulator
                        # (_observe_window returns None) — barrier 2
                        # fetches one merged table, not one per window.
                        got = _observe_window(i, w, defer=deferred)
                        if got is _DEFERRED:
                            continue
                        if got is not None:
                            obs_parts.append(got[0])
                            obs_replays.append(got[1])
                            obs_windows.append(i)
                for i, w, fut in deferred:
                    try:
                        with tele.pass_scope("observe"):
                            got = (fut.result(), None)
                    except Exception as e:
                        log.warning(
                            "coalesced observe of window %d fell back "
                            "to the solo dispatch (%s)", i, e,
                        )
                        got = _observe_window(i, w, coalesce=False)
                    if got is not None:
                        obs_parts.append(got[0])
                        obs_replays.append(got[1])
                        obs_windows.append(i)

    # ---- tail: realign the gathered candidates (observing remainders
    # under the device wait), then observe the realigned part with its
    # post-realignment alignments (markdup -> realign -> BQSR, the
    # reference's Transform composition) ---------------------------------
    t_tail_ns = time.monotonic_ns()
    realigned: Optional[AlignmentDataset] = None
    # resume fast path for the realign tail: when the realigned part
    # (index n_windows) is already durably published AND its
    # contribution to the recalibration table is recoverable (the
    # solved table itself, or its persisted observe histogram), the
    # whole candidate realign — the GEMM sweeps — is skippable.  The
    # sidecar is LOADED here, not just probed: an unreadable sidecar
    # must force the re-realign, or the merged table would silently
    # miss the realigned part's observations.
    skip_realign = False
    r_obs = None
    if (
        candidates and journal is not None and journal.resumed
        and len(windows) in done_parts
    ):
        if not recalibrate or resume_table is not None:
            skip_realign = True
        else:
            r_obs = journal.load_observation(len(windows))
            skip_realign = r_obs is not None
    if candidates and not skip_realign:
        cand = AlignmentDataset.concat(candidates)
        tr.count(tele.C_CANDIDATE_ROWS, int(cand.batch.n_rows))
        # fan the sweep GEMM buckets across the run's device set
        # (probe-paced weighted round-robin) instead of queueing them
        # all on the default device while the rest of the pool idles
        sweep_devs = None
        if use_device and not res["device_lost"]:
            if exec_state["mesh"] is not None:
                sweep_devs = exec_state["mesh"].devices
            elif dpool is not None:
                alive = dpool.alive_devices()
                sweep_devs = alive if len(alive) > 1 else None
        with tele.pass_scope("sweep"):
            # the sweep scope covers the realign GEMM dispatch+drain;
            # the overlapped observe pass shadows it with its own scope
            realigned = realign_mod.realign_indels(
                cand,
                consensus_model=consensus_model,
                known_indels=known_indels,
                max_indel_size=mis,
                max_consensus_number=mcn,
                lod_threshold=lod,
                max_target_size=mts,
                overlap_work=_observe_remainders,
                sweep_devices=sweep_devs,
            )
        if recalibrate and realigned.batch.n_rows and resume_table is None:
            # the realigned part's grid shape rarely matches any ingest
            # window's: warm its observe kernel before the dispatch
            _prewarm_observe_shape(realigned)
            # the realigned part is a window too: place it resident so
            # its observe AND its pass-C apply dispatch off one ingest
            # placement, like every streamed window
            _make_resident(len(windows), realigned)
            got = _observe_window(len(windows), realigned)
            if got is not None:
                obs_parts.append(got[0])
                obs_replays.append(got[1])
                obs_windows.append(len(windows))
        # subtract the observe wall from the tail ONLY when realign
        # reports it genuinely ran under the sweeps' device drain — on
        # the serial paths (Python fallback, no dispatched sweeps) the
        # old unconditional subtraction understated realign's serial
        # wall and inflated the derived cfg4 bench line
        hidden = bool(
            getattr(_observe_remainders, "overlap_ran_in_dispatch", False)
        )
    elif skip_realign:
        # journaled realigned part + recoverable table contribution:
        # observe the remaining windows (persisted histograms load, the
        # rest recompute) and splice the realigned part's persisted
        # histogram in at its window-plan position — the same part
        # order the uninterrupted run merges
        _observe_remainders()
        if r_obs is not None:
            tr.count(tele.C_RESUME_HISTOGRAMS_LOADED)
            obs_parts.append(
                (np.asarray(r_obs[0]), np.asarray(r_obs[1]), r_obs[2])
            )
            obs_replays.append(None)
            obs_windows.append(len(windows))
        hidden = False
    else:
        _observe_remainders()
        # no realignment ran: the tail wall IS the observe pass
        hidden = False
    tr.add_span(tele.SPAN_TAIL, t_tail_ns, time.monotonic_ns() - t_tail_ns)
    tr.gauge(tele.G_OBSERVE_HIDDEN, 1 if hidden else 0)
    stats["observe_overlap_hidden"] = hidden

    # ---- barrier 2: merge histograms, solve the table ------------------
    table = None
    gl = 0
    _mp_b2 = exec_state["mesh"]
    have_acc = _mp_b2 is not None and _mp_b2.has_accumulated()
    if resume_table is not None:
        # post-barrier-2 resume: the persisted table IS the barrier's
        # output (solved from the identical window histograms), so the
        # merge and solve are skipped wholesale
        table = np.ascontiguousarray(resume_table[0], np.uint8)
        gl = int(resume_table[1])
        tr.add_span(tele.SPAN_SOLVE, time.monotonic_ns(), 0)
    elif recalibrate and (obs_parts or have_acc):
        # chaos-harness kill point: barrier-2 entry (nothing persisted
        # yet — a resume replays every un-persisted observation)
        faults.point("proc.kill", device="barrier2")

        if have_acc:
            # THE mesh payoff: one compact merged table per distinct
            # grid width comes home — not one fetched copy per window.
            # A failed fetch degrades: the accumulated windows replay
            # through the pool/host observe into obs_parts.
            try:
                with tele.pass_scope("observe"):
                    acc_parts = exec_state["mesh"].fetch_accumulated(tr)
                tr.count(tele.C_DEVICE_FETCHED, len(acc_parts))
                mesh_obs.clear()
                for tt, mm, g_acc in acc_parts:
                    obs_parts.append(
                        (np.asarray(tt), np.asarray(mm), int(g_acc))
                    )
                    obs_replays.append(None)
                    # no single source window: the accumulator sums
                    # many — None suppresses the per-window sidecar
                    obs_windows.append(None)
            except Exception as e:
                _mesh_degrade(e, "barrier-2 accumulator fetch")

        def _persist_obs(win, tt, mm, g):
            # one atomic sidecar per window, written at the barrier as
            # each histogram becomes host-resident (idempotent: windows
            # whose sidecar loaded above rewrite nothing).  Best-effort
            # — the sidecars only ACCELERATE a resume; a full disk on
            # the run dir must not kill an otherwise healthy run.
            if journal is None:
                return
            try:
                journal.save_observation(win, tt, mm, g)
            except OSError as e:
                log.warning(
                    "observe sidecar persist failed for window %d: %s",
                    win, e,
                )

        # count only the parts that are genuinely device-resident at
        # the barrier — after a mid-run degradation some (or all) parts
        # are host-computed and the merge fetches nothing for them
        n_dev_parts = sum(
            1 for t, _m, _g in obs_parts if not isinstance(t, np.ndarray)
        )
        with tr.span(tele.SPAN_OBS_MERGE), tele.pass_scope("observe"):
            total, mism, gl = bqsr_mod.merge_observations(
                obs_parts, replays=obs_replays, tracer=tr,
                window_ids=obs_windows, on_part=_persist_obs,
            )
        if n_dev_parts:
            tr.count(tele.C_DEVICE_FETCHED, n_dev_parts)
        # the replay hooks close over every window's dataset: release
        # them NOW or pass C's free-as-we-go (windows[idx] = None)
        # frees nothing and peak residency becomes ALL windows at once
        obs_parts.clear()
        obs_replays.clear()
        # solve excludes the fetch: the stage rows are disjoint and sum
        # to the barrier wall
        with tr.span(tele.SPAN_SOLVE):
            if dump_observations:
                bqsr_mod.dump_observation_csv(
                    total, mism, header.read_groups.names + ["null"], gl,
                    dump_observations,
                )
            if known_table is not None:
                # known-sites run: the supplied table IS the applied
                # table, fused or not — the solve is skipped, while the
                # merge above still produced the sidecars/CSV a
                # discovered-table run would.  The table's own grid
                # width replaces the merge's (its cycle axis geometry,
                # not this input's, centers the apply gather).
                table = np.ascontiguousarray(known_table[0], np.uint8)
                gl = int(known_table[1])
            else:
                table = bqsr_mod.solve_recalibration_table(total, mism)
        if journal is not None:
            try:
                journal.save_table(table, gl)
            except OSError as e:
                log.warning("recalibration-table persist failed: %s", e)
        # chaos-harness kill point: barrier-2 exit (table persisted — a
        # resume goes straight into pass C)
        faults.point("proc.kill", device="barrier2")
    else:
        if recalibrate and known_table is not None:
            # no observations to merge (e.g. every window resumed), but
            # the known table still applies in pass C
            table = np.ascontiguousarray(known_table[0], np.uint8)
            gl = int(known_table[1])
        tr.add_span(tele.SPAN_SOLVE, time.monotonic_ns(), 0)

    # ---- pass C: apply || encode || part writes ------------------------
    # Three overlapped resources: the chip (device table gathers,
    # double-buffered so window i+1's gather runs while window i
    # fetches), the host CPU (OQ stash + arrow encode in the pool's
    # encoder threads), and the disk (the pool's dedicated write thread).
    from adam_tpu.io.parquet import PartWriterPool

    # the realigned part applies and submits FIRST: it is the largest
    # part, so its encode+write should overlap the window applies
    # instead of draining serially after them.  Windows whose part the
    # journal records as durably published are skipped outright — the
    # resume's whole point — and their decoded batches freed now.
    parts: list = []
    if realigned is not None and len(windows) not in done_parts:
        parts.append((len(windows), realigned))
    parts.extend(
        (i, w) for i, w in enumerate(windows)
        if window_valid[i] and i not in done_parts
    )
    for i in done_parts:
        if i < len(windows):
            windows[i] = None
    # windows with no part to write (resumed, or fully realigned away)
    # have no pass-C fetch to release them at — release their resident
    # handles now, so HBM tracks exactly the parts still in flight
    _part_idxs = {idx for idx, _w in parts}
    for win in list(resident_map):
        if win not in _part_idxs:
            _release_resident(win)
    stats["windows_fresh"] = len(parts)
    if hb is not None:
        # the part count THIS process will write (residual windows drop
        # out, the realigned part joins, resumed windows are skipped):
        # the heartbeat's ETA extrapolates parts_written against this —
        # windows_total itself stays the pass-A window count, so a
        # progress ratio can never exceed 1
        hb.set_parts_total(len(parts))

    from adam_tpu.io.parquet import part_index as parquet_part_index

    def _on_published(p):
        # writer-pool publish hook (write thread): the part's bytes are
        # durably on disk — record its window complete in the journal
        idx = parquet_part_index(p)
        if idx is not None:
            journal.record_window(idx, os.path.basename(p))

    # 3 parts in flight to start: one writing, one encoding, one being
    # applied/submitted — each stage's resource stays busy.  Under
    # adaptive sizing the pool may widen admission while submits gate,
    # but never past 2x this bound (each admitted part pins a decoded
    # window, so the cap is a memory bound as much as a concurrency
    # one — io/parquet.PartWriterPool).
    pool = PartWriterPool(
        n_encoders=max(1, n_writers - 1), inflight_parts=3,
        compression=compression,
        on_published=_on_published if journal is not None else None,
        tracer=tr,
    )

    def _submit(idx, ds, packed=None):
        # multi-job fairness / graceful drain: one grant per output
        # part.  A RunCancelled here is caught by the pass-C wrapper
        # below, which closes the writer pool GRACEFULLY — this part is
        # lost (it re-executes on resume) but every previously
        # submitted part still publishes and journals.
        if pacer is not None:
            pacer("pass_c", idx, _win_nbytes(ds.batch))
        # chaos-harness kill point: one arrival per fresh part submit
        faults.point("proc.kill", device="pass_c")
        pool.submit(_part_path(out_path, idx), ds.batch, ds.sidecar,
                    ds.header, packed=packed)

    # ---- SDC audit (shared by the pool, mesh and coalesced pass-C
    # paths — docs/ROBUSTNESS.md "Device health, hedging, and SDC
    # audit"): every path that publishes device-produced bytes is
    # auditable, or ADAM_TPU_AUDIT_RATE would silently protect only
    # solo pool runs while the multi-tenant serving modes ship
    # unaudited bits.
    def _host_audit_apply(w):
        return bqsr_mod.apply_recalibration(w, table, gl, _host_backend())

    def _audit_matches(done, p_packed, host_ds) -> bool:
        """Bit-compare the device-produced pass-C result against the
        host parity twin's recompute — the SDC audit's verdict.
        Packed payloads compare in the packed domain (the very bytes
        the Arrow column publishes), matrix results compare the whole
        post-apply qual matrix."""
        from adam_tpu.formats import schema
        from adam_tpu.io.arrow_pack import pack_matrix_host

        hb = host_ds.batch.to_numpy()
        if p_packed is None:
            return np.array_equal(
                np.asarray(done.batch.to_numpy().quals),
                np.asarray(hb.quals),
            )
        pq = getattr(p_packed, "quals", p_packed)
        exp_q = pack_matrix_host(
            np.asarray(hb.quals),
            bqsr_mod._apply_pack_lens(hb),
            schema.QUAL_SANGER_LUT256,
        )
        if not (np.array_equal(pq.buf, exp_q.buf)
                and np.array_equal(pq.lens, exp_q.lens)):
            return False
        pb = getattr(p_packed, "bases", None)
        if pb is not None:
            # the bases half rides its own fetch: audit it too, or a
            # flipped base byte would publish undetected
            exp_b = pack_matrix_host(
                np.asarray(hb.bases),
                bqsr_mod._apply_pack_lens_bases(hb),
                schema.BASE_DECODE_LUT256,
            )
            if not (np.array_equal(pb.buf, exp_b.buf)
                    and np.array_equal(pb.lens, exp_b.lens)):
                return False
        return True

    def _audit_result(p_idx, prod_dev, pre_ds, done, p_packed):
        """Dual-compute audit of a sampled window: recompute
        ``pre_ds`` (the window's pre-apply dataset) on the host parity
        twin and bit-compare.  A mismatch counts
        ``device.audit.mismatch`` and replaces the result with the
        host recompute — the published part is clean either way; when
        a single producing chip is attributable (``prod_dev``, the
        pool path) it is additionally QUARANTINED through the
        scoreboard (its resident handles drop, later windows avoid it
        until a clean re-admission probe).  Mesh collectives and
        coalesced dispatches have no single producing chip — their
        mismatches republish and count, and the operator reads the
        counter.  Returns the (possibly replaced) ``(done,
        p_packed)``."""
        tr.count(tele.C_AUDIT_SAMPLED)
        with tr.span(
            tele.SPAN_AUDIT_CHECK, window=p_idx,
            **(dp_mod.span_attrs(prod_dev) if prod_dev is not None
               else {}),
        ):
            host_ds = _host_audit_apply(pre_ds)
            matched = _audit_matches(done, p_packed, host_ds)
        if matched:
            return done, p_packed
        tr.count(tele.C_AUDIT_MISMATCH)
        log.error(
            "SDC audit: window %d's device result does not match the "
            "host recompute — %s and publishing the host bytes", p_idx,
            f"quarantining device {dp_mod._attr_id(prod_dev)}"
            if prod_dev is not None
            else "no single producing chip to quarantine",
        )
        if prod_dev is not None:
            health_board.quarantine(
                prod_dev,
                reason=f"sdc audit mismatch on window {p_idx}",
                tracer=tr,
            )
            _drop_resident_on(prod_dev)
        from adam_tpu.utils import incidents

        incidents.maybe_record(
            "audit.mismatch",
            device=dp_mod._attr_id(prod_dev)
            if prod_dev is not None else None,
            window=p_idx, trace_id=tr.trace, tracer=tr,
            reason="SDC dual-compute mismatch on window %d; host bytes "
                   "published" % p_idx,
        )
        return host_ds, None

    def _apply_parts_mesh(plist):
        """Mesh pass C: the solved table places ONCE, replicated, and
        stays device-resident while every window's [N, L] gather shards
        over the mesh (double-buffered: window j+1's collective runs
        while window j fetches).  Returns the parts still to do — ``[]``
        on success, or (on a mesh failure) the in-flight + undispatched
        remainder for the pool path to finish, bit-identically."""
        mp = exec_state["mesh"]
        try:
            # the once-per-run table placement gets its own transfer
            # bucket: the "apply" bucket stays per-window traffic, so
            # the analyzer's ingest-only verdict compares marginals
            with tele.pass_scope("table"):
                tbl_dev = mp.put_replicated(
                    np.ascontiguousarray(table, np.uint8)
                )
            # re-warm the mesh apply against the SOLVED table's real
            # width, one entry per distinct window grid shape (the
            # pool path's apply_prewarm_entry semantics)
            seen_dims = {}
            for item in plist:
                bw = item[1].batch
                seen_dims.setdefault((bw.n_rows, bw.lmax), item[1])
            t_pwc = time.monotonic_ns()
            pw_entries = []
            for w in seen_dims.values():
                bw = w.batch.to_numpy()
                if use_packed and use_resident:
                    # the resident bases+quals pack2, plus the
                    # quals-only pack a dead handle falls back to
                    pw_entries.append(part_mod.mesh_apply_prewarm_entry(
                        bw, table.shape[0], table.shape[2], mp,
                        pack2=True,
                    ))
                if use_packed:
                    pw_entries.append(part_mod.mesh_apply_prewarm_entry(
                        bw, table.shape[0], table.shape[2], mp,
                        pack=True,
                    ))
                else:
                    pw_entries.append(part_mod.mesh_apply_prewarm_entry(
                        bw, table.shape[0], table.shape[2], mp,
                    ))
            mp.prewarm(pw_entries, tracer=tr)
            tr.add_span(
                tele.SPAN_POOL_PREWARM_C, t_pwc,
                time.monotonic_ns() - t_pwc,
            )
        except Exception as e:
            _mesh_degrade(e, "pass-C table placement")
            rem = list(plist)
            for j in range(len(plist)):
                plist[j] = None  # only the handed-off list may pin
            return rem
        pend: deque = deque()
        hb_queues.append((pend, 1))  # items: (idx, "mesh", handle)
        k = 0

        def _remainder(exc, where):
            # hand the un-finished work to the pool: in-flight handles
            # still carry their pre-recalibration datasets
            _mesh_degrade(exc, where)
            rem = [
                (i, bqsr_mod.apply_handle_dataset(h))
                for i, _tag, h in pend
            ]
            pend.clear()
            rem.extend(p for p in plist[k:] if p is not None)
            # the handed-off list must be the ONLY thing pinning the
            # remaining windows: the pool loop frees rem entries as it
            # dispatches, but the original parts list would keep every
            # dataset resident through the rest of pass C
            for j in range(len(plist)):
                plist[j] = None
            return rem

        while k < len(plist) or pend:
            # every device works each window, so the classic depth-2
            # double buffer is the whole pipeline depth
            if k < len(plist) and len(pend) < 2:
                idx, w = plist[k]
                fh = fused_handles.pop(idx, None)
                if fh is not None:
                    # megakernel tier: pass B's fused dispatch already
                    # produced this window's packed columns — the
                    # handle joins the in-flight queue FETCH-ONLY (no
                    # second dispatch, no dispatch count)
                    pend.append((idx, "mesh", fh[1]))
                    tr.gauge(tele.G_DEVICE_INFLIGHT, len(pend))
                    plist[k] = None
                    k += 1
                    continue
                try:
                    with tr.span(
                        tele.SPAN_APPLY_DISPATCH, window=idx,
                        device="mesh",
                    ):
                        handle = bqsr_mod.apply_recalibration_dispatch(
                            w, tbl_dev, gl, backend, mesh=mp,
                            pack=use_packed,
                            resident=resident_map.get(idx),
                        )
                except Exception as e:
                    return _remainder(e, "pass-C apply dispatch")
                tr.count(tele.C_DEVICE_DISPATCHED)
                tr.count(tele.C_MESH_DISPATCHED)
                pend.append((idx, "mesh", handle))
                tr.gauge(tele.G_DEVICE_INFLIGHT, len(pend))
                plist[k] = None  # must not pin every window
                k += 1
                continue
            p_idx, _tag, p_handle = pend[0]
            try:
                with tr.span(
                    tele.SPAN_APPLY_FETCH, window=p_idx, device="mesh",
                ):
                    done, p_packed = (
                        bqsr_mod.apply_recalibration_finish_packed(
                            p_handle
                        )
                    )
            except Exception as e:
                return _remainder(e, "pass-C apply fetch")
            pend.popleft()
            tr.count(tele.C_DEVICE_FETCHED)
            # SDC audit: mesh collectives have no single producing chip
            # to quarantine, but a sampled mismatch still counts and
            # the host bytes still publish
            if sdc_audit_rate > 0 and health_mod.audit_due(
                p_idx, sdc_audit_rate
            ):
                done, p_packed = _audit_result(
                    p_idx, None,
                    bqsr_mod.apply_handle_dataset(p_handle),
                    done, p_packed,
                )
            # OUTSIDE the mesh try blocks, like the pool path: a writer-
            # pool fail-fast error is an output failure, not a mesh
            # failure — it must abort the run with its own attribution,
            # never trigger a degrade-and-replay
            _submit(p_idx, done, p_packed)
            # refcounted release after pass C: the window's device
            # arrays free as its part submits (the host copy lives on
            # in the writer pool until the part publishes)
            _release_resident(p_idx)
            if p_idx < len(windows):
                windows[p_idx] = None  # free as we go
        return []

    def _apply_parts_pool(plist):
        # replicate the solved u8 table once per pool device
        # (~4 MB each) instead of re-shipping it per window
        dev_tables = None
        if dpool is not None:
            tbl_c = np.ascontiguousarray(table, np.uint8)
            # replicas keyed by ORIGINAL pool index (stable
            # under eviction); dead devices get no replica —
            # _pick_device never hands them out.  Placed via
            # putter so the per-device table replication shows
            # up in the h2d transfer ledger.
            alive_now = dpool.alive_devices()
            # own transfer bucket (once-per-run, not per-window): see
            # the mesh table placement above
            with tele.pass_scope("table"):
                dev_tables = [
                    dp_mod.putter(d)(tbl_c) if d in alive_now
                    else None
                    for d in dpool.devices
                ]
            # re-warm the apply gather against the SOLVED
            # table's real width: merge_observations can widen
            # the table past window 0's grid, which pass A's
            # prewarm assumed — uniform-lmax inputs dedupe this
            # to a no-op against the process-wide cache.  One
            # entry per distinct window grid shape.
            from adam_tpu.parallel.device_pool import (
                apply_prewarm_entry,
            )

            seen_dims = {}
            for item in plist:
                bw = item[1].batch
                seen_dims.setdefault(
                    (bw.n_rows, bw.lmax), item[1]
                )
            t_pwc = time.monotonic_ns()
            pw_entries = []
            for w in seen_dims.values():
                bw = w.batch.to_numpy()
                if use_packed and use_resident:
                    # the resident bases+quals pack2 (what pass C will
                    # actually dispatch), beside the quals-only pack a
                    # dead handle falls back to
                    pw_entries.append(apply_prewarm_entry(
                        bw, table.shape[0], table.shape[2],
                        pack=True, resident=True,
                    ))
                if use_packed:
                    pw_entries.append(apply_prewarm_entry(
                        bw, table.shape[0], table.shape[2], pack=True,
                    ))
                # the plain gather stays warm on every leg: eviction
                # replays re-apply with pack=False on a survivor, and
                # one entry covers both twins (resident warms the
                # donating variant alongside the plain one)
                pw_entries.append(apply_prewarm_entry(
                    bw, table.shape[0], table.shape[2],
                    resident=use_resident,
                ))
            dpool.prewarm(pw_entries, tracer=tr)
            # umbrella wall for the re-warm: the stats view
            # folds it into prewarm_s and subtracts it from
            # apply_split_s, so compile time never shows up as
            # host encode/submit time
            tr.add_span(
                tele.SPAN_POOL_PREWARM_C, t_pwc,
                time.monotonic_ns() - t_pwc,
            )
        # in-flight queue of (part idx, device, handle): depth
        # 2 single-device (the classic double buffer); with a
        # pool a double buffer per device — window j+1's gather
        # on chip B runs while window j fetches from chip A
        apply_depth = 2 if dpool is None else 2 * dpool.n
        pend_q: deque = deque()
        hb_queues.append((pend_q, 1))  # items: (idx, dev, handle)

        def _host_apply(w):
            return bqsr_mod.apply_recalibration(
                w, table, gl, _host_backend()
            )

        def _device_table(dev):
            if dpool is None:
                return table
            i = dpool.devices.index(dev)
            if dev_tables[i] is None:
                # a device with no replica joined placement mid-pass
                # (a health-probation chip re-admitted by its probe):
                # place its table copy now, once
                with tele.pass_scope("table"):
                    dev_tables[i] = dp_mod.putter(dev)(
                        np.ascontiguousarray(table, np.uint8)
                    )
            return dev_tables[i]

        def _replay_apply(p_idx, dev, w, exc):
            """Window p_idx's apply died on ``dev``: evict it
            and re-run dispatch+fetch synchronously on a
            survivor, host backend when none remain."""

            def on_device(nd):
                h = bqsr_mod.apply_recalibration_dispatch(
                    w, _device_table(nd), gl, backend, device=nd
                )
                return bqsr_mod.apply_recalibration_finish(h)

            with tr.span(tele.SPAN_POOL_REPLAY, window=p_idx,
                         **dp_mod.span_attrs(dev)), \
                    dp_mod.replay_scope():
                _evict_or_lose(dev, exc)
                return _on_survivors(
                    p_idx, on_device, lambda: _host_apply(w)
                )

        def _solo_apply_sync(p_idx, w):
            """Synchronous solo apply -> (dataset, packed | None) for a
            window whose coalesced dispatch failed: the normal survivor
            walk with the same packed/resident fast paths the solo
            dispatch loop uses, host backend when the device path is
            gone — byte-identical output either way."""

            def on_device(nd):
                h = bqsr_mod.apply_recalibration_dispatch(
                    w, _device_table(nd), gl, backend, device=nd,
                    pack=use_packed, resident=resident_map.get(p_idx),
                )
                return bqsr_mod.apply_recalibration_finish_packed(h)

            return _on_survivors(
                p_idx, on_device, lambda: (_host_apply(w), None)
            )

        def _hedge_redispatch(p_idx, p_dev, p_handle):
            """The speculative twin of a late window -> (closure, nd):
            synchronous dispatch+fetch on another alive device ``nd``,
            from the host-retained dataset (the PR 13 replay contract)
            — output bytes identical by kernel determinism + backend
            parity.  Raises when no alternate device exists (the
            caller then never fires the hedge)."""
            others = [
                d for d in dpool.alive_devices() if d is not p_dev
            ] if dpool is not None else []
            if not others:
                raise RuntimeError("no alternate device to hedge on")
            nd = others[p_idx % len(others)]
            w = bqsr_mod.apply_handle_dataset(p_handle)

            def run():
                with tr.span(
                    tele.SPAN_APPLY_DISPATCH, window=p_idx, hedge=1,
                    **dp_mod.span_attrs(nd),
                ):
                    h = bqsr_mod.apply_recalibration_dispatch(
                        w, _device_table(nd), gl, backend, device=nd,
                        pack=use_packed,
                    )
                return bqsr_mod.apply_recalibration_finish_packed(h)

            return run, nd

        def _fetch_one():
            p_idx, p_dev, p_handle = pend_q.popleft()
            if p_dev == "batch":
                # coalesced window: the future resolves to a standard
                # dispatch handle whose payload is already host-
                # resident (the coalescer fetched the fused output
                # once and split it per job)
                p_packed = None
                try:
                    handle = p_handle.result()
                    with tr.span(
                        tele.SPAN_APPLY_FETCH, window=p_idx,
                        device="batch",
                    ):
                        done, p_packed = (
                            bqsr_mod.apply_recalibration_finish_packed(
                                handle
                            )
                        )
                except Exception as e:
                    log.warning(
                        "coalesced apply of window %d fell back to "
                        "the solo dispatch (%s)", p_idx, e,
                    )
                    done, p_packed = _solo_apply_sync(
                        p_idx, p_handle.dataset
                    )
                else:
                    # SDC audit, fused-fetch success only: a fused
                    # dispatch has no single producing chip to
                    # quarantine, but a sampled mismatch still counts
                    # and the host bytes still publish.  The fallback
                    # branch may have applied on the HOST — auditing
                    # host bytes against a host recompute can never
                    # mismatch and would just double the window's cost
                    if sdc_audit_rate > 0 and health_mod.audit_due(
                        p_idx, sdc_audit_rate
                    ):
                        done, p_packed = _audit_result(
                            p_idx, None, p_handle.dataset, done,
                            p_packed,
                        )
                _submit(p_idx, done, p_packed)
                _release_resident(p_idx)
                return
            attrs = dp_mod.span_attrs(p_dev)
            p_packed = None
            prod_dev = p_dev  # the device whose bits we end up using
            # hedged dispatch (Dean & Barroso): once the apply kernel
            # has a pooled p99, an in-flight window past
            # ADAM_TPU_HEDGE_FACTOR x p99 speculatively re-dispatches
            # on another alive device from the host-retained copy —
            # first result wins, bytes identical by parity
            thr = None
            if (
                dpool is not None and not res["device_lost"]
                and p_dev is not None
                and len(dpool.alive_devices()) > 1
            ):
                thr = health_board.hedge_threshold("bqsr.apply")
            try:
                t_fetch = time.monotonic()
                hedged = False
                with tr.span(
                    tele.SPAN_APPLY_FETCH, window=p_idx, **attrs
                ):
                    if thr is None:
                        done, p_packed = (
                            bqsr_mod.apply_recalibration_finish_packed(
                                p_handle
                            )
                        )
                    else:
                        nd_box: list = []

                        def _hedge_fn():
                            run, nd = _hedge_redispatch(
                                p_idx, p_dev, p_handle
                            )
                            nd_box.append(nd)
                            return run()

                        (done, p_packed), winner, hedged = (
                            dp_mod.hedged_call(
                                lambda: (
                                    bqsr_mod
                                    .apply_recalibration_finish_packed(
                                        p_handle
                                    )
                                ),
                                _hedge_fn, thr, tracer=tr,
                            )
                        )
                        if winner == "hedge":
                            prod_dev = nd_box[0]
                            # the primary lost to a COLD re-dispatch on
                            # a peer: the strongest straggler signal —
                            # without it a chip whose every window
                            # hedges would never accrue a latency
                            # penalty (its own wall never finishes, so
                            # observe_latency has nothing true to feed)
                            health_board.note_hedge_lost(
                                p_dev, "bqsr.apply", tracer=tr
                            )
                tr.count(tele.C_DEVICE_FETCHED)
                if not hedged and p_dev is not None:
                    # feed the scoreboard's per-kernel latency pool
                    # (hedge-inflated walls stay out of it; a LOST
                    # race penalizes through note_hedge_lost above).
                    # Only REAL device attributions feed it — the
                    # poolless default-device path's None would accrue
                    # EWMAs on a phantom "default" key that no pool can
                    # probe and that the cross-device best-peer check
                    # would read as a (stale, fast) peer in a LATER
                    # pooled run on this process-wide board
                    health_board.observe_latency(
                        "bqsr.apply", p_dev,
                        time.monotonic() - t_fetch, tracer=tr,
                    )
            except Exception as e:
                # the replay re-applies synchronously (survivor chip or
                # host backend) and returns a matrix-path dataset —
                # its part encodes through the legacy column builders
                done = _replay_apply(
                    p_idx, p_dev,
                    bqsr_mod.apply_handle_dataset(p_handle), e,
                )
                p_packed = None
            else:
                # SDC audit: a deterministic ADAM_TPU_AUDIT_RATE sample
                # of windows dual-computes on the host parity twin and
                # bit-compares; a mismatch quarantines the producing
                # chip and the HOST bytes publish
                if sdc_audit_rate > 0 and health_mod.audit_due(
                    p_idx, sdc_audit_rate
                ):
                    done, p_packed = _audit_result(
                        p_idx, prod_dev,
                        bqsr_mod.apply_handle_dataset(p_handle),
                        done, p_packed,
                    )
            _submit(p_idx, done, p_packed)
            # refcounted release after pass C: the window's device
            # arrays free as its part submits (the host copy lives on
            # in the writer pool until the part publishes)
            _release_resident(p_idx)

        for j in range(len(plist)):
            idx, w = plist[j]
            plist[j] = None  # the list must not pin every window

            if coalescer is not None and not res["device_lost"]:
                # cross-job batching: the window's apply rides a fused
                # dispatch (per-job table band + per-job payload split
                # on the fetch); the future joins the same in-flight
                # queue as a solo handle, so the double buffer and the
                # writer-pool overlap are unchanged
                try:
                    fut = coalescer.submit_apply(
                        idx, w, table, pack=use_packed,
                        resident=resident_map.get(idx),
                    )
                except Exception as e:
                    log.warning(
                        "coalesced apply submit of window %d failed "
                        "(%s); dispatching solo", idx, e,
                    )
                else:
                    pend_q.append((idx, "batch", fut))
                    tr.gauge(tele.G_DEVICE_INFLIGHT, len(pend_q))
                    del w
                    if idx < len(windows):
                        windows[idx] = None  # free as we go
                    if len(pend_q) >= apply_depth:
                        _fetch_one()
                    continue

            fh = fused_handles.pop(idx, None)
            if fh is not None:
                # megakernel tier: the packed columns are already on
                # the producing chip from pass B's fused dispatch —
                # fetch-only (no second dispatch, no dispatch count).
                # A failed fetch takes _fetch_one's normal replay path:
                # evict and re-apply separately on a survivor/host,
                # byte-identical by the parity contract.
                pend_q.append((idx, fh[0], fh[1]))
                tr.gauge(tele.G_DEVICE_INFLIGHT, len(pend_q))
                del w
                if idx < len(windows):
                    windows[idx] = None  # free as we go
                if len(pend_q) >= apply_depth:
                    _fetch_one()
                continue

            def _dispatch_one(dev, idx=idx, w=w):
                with tr.span(
                    tele.SPAN_APPLY_DISPATCH, window=idx,
                    **dp_mod.span_attrs(dev),
                ):
                    handle = bqsr_mod.apply_recalibration_dispatch(
                        w, _device_table(dev), gl, backend,
                        device=dev, pack=use_packed,
                        resident=resident_map.get(idx),
                    )
                tr.count(tele.C_DEVICE_DISPATCHED)
                return dev, handle

            # round-robin by WINDOW index (not parts position): the
            # resident handle was pinned at ingest by _pick_device(win),
            # and an index mismatch here would silently re-ship every
            # window (placement never affects output bytes either way)
            got = _on_survivors(idx, _dispatch_one, lambda: None)
            if got is None:  # device path lost: apply host-side
                _submit(idx, _host_apply(w))
            else:
                pend_q.append((idx,) + got)
                tr.gauge(tele.G_DEVICE_INFLIGHT, len(pend_q))
            del w
            if idx < len(windows):
                windows[idx] = None  # free as we go
            if len(pend_q) >= apply_depth:
                _fetch_one()
        while pend_q:
            _fetch_one()

    try:
        # the span wraps apply+submit only; the device dispatch/fetch
        # walls inside it are their own DISJOINT child spans, so the
        # derived apply_split_s (pass C minus dispatch minus fetch) sums
        # with them to the pass wall instead of double-counting it
        with tr.span(tele.SPAN_PASS_C), tele.pass_scope("apply"):
            if table is not None and use_device and not res["device_lost"]:
                todo = parts
                if exec_state["mesh"] is not None:
                    todo = _apply_parts_mesh(parts)
                if todo:
                    _apply_parts_pool(todo)
            else:
                # host path — also the full-degradation path: with the
                # device backend lost, the per-residue apply runs on
                # the native/numpy twin (bit-identical by parity)
                apply_backend = (
                    _host_backend() if use_device else backend
                )
                for j in range(len(parts)):
                    idx, w = parts[j]
                    parts[j] = None  # the list must not pin every window
                    if table is not None:
                        w = bqsr_mod.apply_recalibration(
                            w, table, gl, apply_backend
                        )
                    if idx < len(windows):
                        windows[idx] = None  # free as we go
                    _submit(idx, w)
                    _release_resident(idx)
    except RunCancelled:
        # graceful drain at a pass-C boundary: close the pool NON-abort
        # so every part already submitted encodes, publishes durably
        # and journals via on_published — the drained job's resume
        # starts exactly past them.  A worker error surfacing from the
        # drain-time close replaces the cancellation (it is a real
        # output failure, not a drain artifact).
        with tr.span(tele.SPAN_WRITE_WAIT):
            pool.close()
        raise
    except BaseException:
        try:  # drain the pool + discard its unpublished temp parts,
            # but surface the apply-path error
            pool.close(abort=True)
        except BaseException:
            pass
        raise
    with tr.span(tele.SPAN_WRITE_WAIT):
        pool.close()
    # backstop: any handle pass C had no fetch to release (edge paths)
    # frees here, so the live-bytes gauge ends at 0 on every clean run
    for win in list(resident_map):
        _release_resident(win)
    stats["resident_windows"] = resident_live["made"]
    if use_device:
        # run-end health publish: every tracked device's scoreboard
        # state lands in the snapshot's `health` section (the
        # analyzer's "Device health" rows), beside the transition
        # counters recorded as they happened
        health_board.publish(tr)
    tr.add_span(tele.SPAN_TOTAL, t_start_ns,
                time.monotonic_ns() - t_start_ns)

    # Timing keys are a DERIVED VIEW of the run tracer's span data —
    # the span-derived view and the stats dict cannot disagree.
    stats.update(tele.streamed_stats_view(tr.snapshot()))
    _finish_trace(tr, stats, hb)
    return stats


def _finish_trace(tr: tele.Tracer, stats: dict, hb=None) -> None:
    """End-of-run telemetry plumbing: stop the heartbeat (BEFORE the
    absorb below — a post-absorb sample would double-count every
    counter the run tracer shares with the global TRACE), mirror the
    derived stage walls into the named-timer registry (so
    ``-print_metrics`` decomposes the streamed flagship the way the
    reference's Metrics listener decomposes a Spark job) and fold the
    run tracer's events/metrics into the global TRACE when telemetry
    is enabled."""
    from adam_tpu.utils import instrumentation as ins

    _stop_heartbeat(hb)

    for key, label in (
        ("prewarm_s", "Streamed Device Prewarm (per-device compiles)"),
        ("ingest_pass_s", "Streamed Pass A (ingest + summaries)"),
        ("md_cols_fetch_s", "Streamed MarkDup Columns (device fetch)"),
        ("resolve_s", "Streamed Barrier (dup resolve + targets)"),
        ("split_s", "Streamed Pass B (candidate split)"),
        ("observe_s", "Streamed BQSR Observe (hidden under sweeps)"),
        ("realign_s", "Streamed Tail (realign net of overlap)"),
        ("obs_merge_fetch_s", "Streamed Observe Merge (device fetch)"),
        ("solve_s", "Streamed Barrier (solve recalibration)"),
        ("apply_device_dispatch_s", "Streamed Pass C (device dispatch)"),
        ("apply_device_fetch_s", "Streamed Pass C (device fetch)"),
        ("apply_split_s", "Streamed Pass C (apply)"),
        ("write_wait_s", "Streamed Write Wait"),
    ):
        if key in stats:
            ins.TIMERS.add(label, int(stats[key] * 1e9))
    if tele.TRACE.recording:
        tele.TRACE.absorb(tr)
