"""Checkpoint / restart: stage materialization + the streamed run journal.

The reference delegates fault tolerance to Spark lineage recompute;
SURVEY §5 told the TPU build to decide its own story. Two layers:

* **Stage materialization** (:class:`StageCheckpointer` /
  :func:`run_stages`) — each completed pipeline stage can persist its
  full dataset to Parquet under a checkpoint directory with a manifest
  recording stage order and completion, and a rerun of the same
  pipeline resumes from the last completed stage instead of recomputing
  (the moral equivalent of the reference chaining `transform` runs
  through files, made automatic). Inputs stay re-shardable because the
  checkpoint is the columnar Parquet store any mesh shape can reload.

* **Window-granular durable resume** (:class:`RunJournal`) — the
  streamed pipeline's journal (``--run-dir`` / ``--resume``,
  docs/ROBUSTNESS.md "Durable window-granular resume"): a fingerprinted
  per-run record of which output windows are durably published, plus
  atomic sidecars for each window's pass-B observe histogram and the
  solved recalibration table, so an arbitrary host-process death
  (SIGKILL, OOM, preemption) costs only the incomplete windows — and a
  resume against changed inputs or a changed flag composition is
  REFUSED with a clean restart, never silently mixed output.

Both layers share one fingerprint discipline
(:func:`input_fingerprint` / :func:`compose_fingerprint`): resume
validity is decided by input content identity + flag composition, not
by trusting whatever happens to be on disk.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Callable, Optional, Sequence

from adam_tpu.utils.durability import atomic_write_bytes, atomic_write_json

logger = logging.getLogger(__name__)

_MANIFEST = "MANIFEST.json"

# ---------------------------------------------------------------------------
# Fingerprints: input content identity + flag composition
# ---------------------------------------------------------------------------

#: Inputs at or under this size hash fully; larger ones hash
#: size + head + tail windows of this size (a WGS-scale BAM must not
#: cost a full re-read just to *start* a resume).
_FULL_HASH_LIMIT = 64 << 20
_EDGE_HASH_BYTES = 8 << 20


def input_fingerprint(path: str) -> str:
    """Content-identity digest of an input file (or columnar store dir).

    Files up to 64 MiB digest in full; larger files digest
    ``size + first 8 MiB + last 8 MiB`` — cheap to recompute at resume
    time, and any append, truncation or edit near either end (how SAM/
    BAM files actually change) flips it.  Directories (a ``.adam``
    store) digest the sorted non-underscore entry list with sizes.
    The path itself is NOT part of the identity: the same bytes moved
    elsewhere still resume.
    """
    h = hashlib.sha256()
    p = os.path.abspath(path)
    if os.path.isdir(p):
        h.update(b"dir:")
        for name in sorted(os.listdir(p)):
            if name.startswith(("_", ".")):
                continue
            try:
                size = os.path.getsize(os.path.join(p, name))
            except OSError:
                size = -1
            h.update(f"{name}={size};".encode())
        return h.hexdigest()
    size = os.path.getsize(p)
    h.update(f"file:{size};".encode())
    with open(p, "rb") as fh:
        if size <= _FULL_HASH_LIMIT:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        else:
            remaining = _EDGE_HASH_BYTES
            while remaining:
                chunk = fh.read(min(remaining, 1 << 20))
                if not chunk:
                    break
                h.update(chunk)
                remaining -= len(chunk)
            fh.seek(size - _EDGE_HASH_BYTES)
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()


def _canon(v):
    """JSON-able canonical form of one fingerprint field (numpy arrays
    and array tuples — the known-SNP/indel tables — digest by content)."""
    import numpy as np

    if isinstance(v, np.ndarray):
        a = np.ascontiguousarray(v)
        return {
            "ndarray": hashlib.sha256(a.tobytes()).hexdigest(),
            "dtype": str(a.dtype),
            "shape": list(a.shape),
        }
    if isinstance(v, (list, tuple)):
        return [_canon(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _canon(v[k]) for k in sorted(v)}
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, np.generic):
        return v.item()
    # objects exposing array fields (SnpTable-style): digest their dict
    d = getattr(v, "__dict__", None)
    if d:
        return _canon(d)
    return repr(v)


def compose_fingerprint(fields: dict) -> str:
    """Stable digest of a flag-composition dict (include the
    :func:`input_fingerprint` as one of the fields)."""
    doc = json.dumps(_canon(fields), sort_keys=True)
    return hashlib.sha256(doc.encode()).hexdigest()


class StageCheckpointer:
    """Tracks stage completion under ``directory``.

    The manifest stores the ordered stage list plus an optional
    input/flag ``fingerprint`` (:func:`compose_fingerprint`); a stage is
    resumable only if the recorded order matches the current pipeline's
    prefix AND the fingerprints agree — a changed flag composition *or a
    changed input* invalidates the stage stores instead of silently
    reloading data derived from different bytes.
    """

    def __init__(self, directory: str, stages: Sequence[str],
                 fingerprint: Optional[str] = None):
        self.dir = directory
        self.stages = list(stages)
        self.fingerprint = fingerprint
        os.makedirs(directory, exist_ok=True)
        self._completed: list[str] = []
        mpath = os.path.join(directory, _MANIFEST)
        m = None
        if os.path.exists(mpath):
            try:
                with open(mpath) as fh:
                    m = json.load(fh)
                if not isinstance(m, dict):
                    raise ValueError(f"manifest is {type(m).__name__}, "
                                     "not an object")
            except (OSError, ValueError) as e:
                # a torn/corrupt manifest (crashed writer, disk hiccup)
                # must cost a recompute, not brick every future resume
                logger.warning(
                    "checkpoint manifest %s is unreadable (%s); treating "
                    "as no checkpoint and restarting", mpath, e,
                )
                m = None
        if m is not None:
            if m.get("stages") != self.stages:
                logger.warning(
                    "checkpoint dir %s was built for stages %s (now %s); "
                    "ignoring old checkpoints", directory,
                    m.get("stages"), self.stages,
                )
            elif (fingerprint is not None
                  and m.get("fingerprint") != fingerprint):
                # a legacy manifest (no fingerprint) is indistinguishable
                # from a changed input: recompute — never resume stage
                # stores that may derive from different bytes/flags
                logger.warning(
                    "checkpoint dir %s was built for a different input/"
                    "flag fingerprint (%s, now %s); ignoring old "
                    "checkpoints", directory, m.get("fingerprint"),
                    fingerprint,
                )
            else:
                self._completed = [
                    s for s in m.get("completed", [])
                    if os.path.exists(self.path(s))
                ]

    def path(self, stage: str) -> str:
        return os.path.join(self.dir, f"{stage}.adam")

    def last_completed(self) -> Optional[str]:
        """Deepest stage that completed as a prefix of the stage list."""
        last = None
        for s in self.stages:
            if s in self._completed:
                last = s
            else:
                break
        return last

    def mark(self, stage: str) -> None:
        # idempotent: a rerun that re-executes an already-recorded stage
        # (or a caller double-marking) must not grow duplicate
        # `completed` entries — last_completed() walks a prefix, and a
        # duplicated list would also re-duplicate on every rewrite
        if stage not in self._completed:
            self._completed.append(stage)
        mpath = os.path.join(self.dir, _MANIFEST)
        # temp + fsync + atomic rename (utils/durability): a crash
        # mid-write leaves either the old complete manifest or the new
        # one, never a torn file (the init path tolerates even that),
        # and a power loss after the rename cannot lose the bytes
        doc = {"stages": self.stages, "completed": self._completed}
        if self.fingerprint is not None:
            doc["fingerprint"] = self.fingerprint
        atomic_write_json(mpath, doc)


def run_stages(
    ds,
    stages: Sequence[tuple[str, Callable]],
    checkpoint_dir: Optional[str] = None,
    fingerprint: Optional[str] = None,
):
    """Run ``(name, fn)`` stages over a dataset with optional
    checkpoint-restart.

    With a checkpoint dir, each stage's output is materialized to
    Parquet and recorded; a rerun resumes after the deepest completed
    stage (loading its store) instead of recomputing.  ``fingerprint``
    (:func:`compose_fingerprint` over the input identity + flag values)
    invalidates stale stores from a different input or composition.
    """
    if not checkpoint_dir:
        for _, fn in stages:
            ds = fn(ds)
        return ds

    from adam_tpu.api.datasets import AlignmentDataset

    ck = StageCheckpointer(checkpoint_dir, [n for n, _ in stages],
                           fingerprint=fingerprint)
    resume_after = ck.last_completed()
    skipping = resume_after is not None
    if skipping:
        logger.info("resuming after checkpointed stage %r", resume_after)
        ds = AlignmentDataset.load(ck.path(resume_after))
    for name, fn in stages:
        if skipping:
            if name == resume_after:
                skipping = False
            continue
        ds = fn(ds)
        ds.save(ck.path(name))
        ck.mark(name)
    return ds


# ---------------------------------------------------------------------------
# Window-granular durable resume: the streamed run journal
# ---------------------------------------------------------------------------
class RunJournal:
    """Durable resume state for one streamed run (``--run-dir``).

    Layout under ``run_dir`` (docs/ROBUSTNESS.md "Durable
    window-granular resume")::

        JOURNAL.json           fingerprint, window plan, completed
                               window -> part-name map (rewritten
                               whole via temp + fsync + os.replace on
                               every append — the PR 4 writer contract)
        obs/window-NNNNN.npz   one atomic sidecar per window's pass-B
                               observe histogram (total, mism, gl),
                               written at the merge barrier
        table.npz              the solved recalibration table + gl,
                               written once after barrier 2

    A window is recorded complete ONLY after its Parquet part is
    durably published (fsync + atomic rename, the
    ``PartWriterPool.on_published`` hook), so every journal entry is
    backed by readable bytes.  On resume, the journal re-validates the
    fingerprint (input content identity + flag composition + window
    plan): any mismatch — including a torn/corrupt journal file — is
    REFUSED with a clean restart (journal, sidecars AND previously
    published parts are discarded), never silently mixed output.
    """

    SCHEMA = "adam_tpu.run_journal/1"
    JOURNAL_NAME = "JOURNAL.json"
    OBS_DIR_NAME = "obs"
    TABLE_NAME = "table.npz"

    def __init__(self, run_dir: str, fingerprint: str, out_dir: str,
                 resume: bool = False, tracer=None):
        self.dir = run_dir
        self.out_dir = out_dir
        self.fingerprint = fingerprint
        self._tracer = tracer
        self._lock = threading.Lock()
        self._windows: dict[int, str] = {}
        self._n_windows: Optional[int] = None
        self.resumed = False
        os.makedirs(run_dir, exist_ok=True)
        os.makedirs(self._obs_dir, exist_ok=True)
        if resume:
            self.resumed = self._load()
            if not self.resumed:
                self._count_refused()
        if not self.resumed:
            with self._lock:
                self._start_fresh_locked()

    @classmethod
    def peek(cls, run_dir: str) -> Optional[dict]:
        """Read-only summary of a run dir's journal — the multi-job
        scheduler's crash-recovery scan (``adam_tpu/serve``) uses it to
        report how much of an incomplete job survived.  Returns
        ``{"fingerprint", "n_windows", "completed"}`` or ``None`` when
        absent/unreadable/not-a-journal.  No side effects and no
        validation authority: the resume decision itself stays with
        ``__init__``'s fingerprint/refusal rules."""
        path = os.path.join(run_dir, cls.JOURNAL_NAME)
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict) or doc.get("schema") != cls.SCHEMA:
            return None
        return {
            "fingerprint": doc.get("fingerprint"),
            "n_windows": doc.get("n_windows"),
            "completed": len(doc.get("windows") or {}),
        }

    # ---- paths ---------------------------------------------------------
    @property
    def _journal_path(self) -> str:
        return os.path.join(self.dir, self.JOURNAL_NAME)

    @property
    def _obs_dir(self) -> str:
        return os.path.join(self.dir, self.OBS_DIR_NAME)

    @property
    def _table_path(self) -> str:
        return os.path.join(self.dir, self.TABLE_NAME)

    def observation_path(self, win: int) -> str:
        return os.path.join(self._obs_dir, f"window-{win:05d}.npz")

    # ---- lifecycle -----------------------------------------------------
    def _count_refused(self) -> None:
        from adam_tpu.utils import telemetry as tele

        (self._tracer or tele.TRACE).count(tele.C_RESUME_REFUSED)

    def _load(self) -> bool:
        """Validate + load an existing journal; False = refuse (the
        caller restarts clean)."""
        path = self._journal_path
        if not os.path.exists(path):
            logger.warning(
                "--resume requested but %s has no journal; starting a "
                "fresh run", self.dir,
            )
            return False
        try:
            with open(path) as fh:
                doc = json.load(fh)
            if not isinstance(doc, dict):
                raise ValueError(f"journal is {type(doc).__name__}, "
                                 "not an object")
        except (OSError, ValueError) as e:
            # torn/corrupt journal: a clean restart, never a guess at
            # which windows might be complete
            logger.warning(
                "run journal %s is unreadable (%s); refusing resume and "
                "restarting clean", path, e,
            )
            return False
        if doc.get("schema") != self.SCHEMA:
            logger.warning(
                "run journal %s has schema %r (want %r); refusing resume "
                "and restarting clean", path, doc.get("schema"),
                self.SCHEMA,
            )
            return False
        if doc.get("fingerprint") != self.fingerprint:
            logger.warning(
                "run journal %s was recorded for a different input/flag "
                "fingerprint (%s, now %s); refusing resume and restarting "
                "clean — a resume against changed inputs would silently "
                "mix stale and fresh windows", path,
                doc.get("fingerprint"), self.fingerprint,
            )
            return False
        try:
            windows = {
                int(k): str(v) for k, v in (doc.get("windows") or {}).items()
            }
            n_windows = doc.get("n_windows")
            if n_windows is not None:
                n_windows = int(n_windows)
        except (TypeError, ValueError) as e:
            logger.warning(
                "run journal %s has malformed window records (%s); "
                "refusing resume and restarting clean", path, e,
            )
            return False
        # every journaled part must still be readable bytes on disk —
        # an externally deleted part silently degrades that window to
        # "incomplete" (it re-executes), never to a hole in the output
        kept = {}
        for win, name in windows.items():
            part = os.path.join(self.out_dir, name)
            if os.path.isfile(part) and os.path.getsize(part) > 0:
                kept[win] = name
            else:
                logger.warning(
                    "journaled part %s for window %d is missing; that "
                    "window will re-execute", part, win,
                )
        self._windows = kept
        self._n_windows = n_windows
        return True

    def _start_fresh_locked(self) -> None:
        """Discard every prior artifact — journal, sidecars, and the
        previously published parts (stale output from a different run
        must never mix with this one's).  Caller holds ``self._lock``
        (the journal can be rewound from ``confirm_plan`` while the
        writer pool's ``record_window`` callbacks are live)."""
        self._windows = {}
        self._n_windows = None
        for p in (self._journal_path, self._table_path):
            try:
                os.unlink(p)
            except OSError:
                pass
        try:
            for name in os.listdir(self._obs_dir):
                try:
                    os.unlink(os.path.join(self._obs_dir, name))
                except OSError:
                    pass
        except OSError:
            pass
        from adam_tpu.io.parquet import part_index

        if os.path.isdir(self.out_dir):
            for name in os.listdir(self.out_dir):
                if part_index(name) is not None:
                    try:
                        os.unlink(os.path.join(self.out_dir, name))
                    except OSError:
                        pass
        self._flush_locked()

    def confirm_plan(self, n_windows: int) -> None:
        """Pin (or re-validate) the window plan once pass A fixes it.
        The fingerprint already covers input identity + window sizing,
        so a mismatch here means the journal lies (manual edits, a
        collision): degrade to a clean restart rather than trust it."""
        with self._lock:
            if self.resumed and self._n_windows is not None \
                    and self._n_windows != n_windows:
                logger.warning(
                    "run journal %s recorded %d windows but this input "
                    "tokenizes to %d; discarding the journal and "
                    "restarting clean", self._journal_path,
                    self._n_windows, n_windows,
                )
                self.resumed = False
                self._count_refused()
                self._start_fresh_locked()
            self._n_windows = n_windows
            self._flush_locked()

    # ---- window completion ---------------------------------------------
    def completed_windows(self) -> frozenset:
        """Window/part indices durably complete from a prior run."""
        with self._lock:
            return frozenset(self._windows) if self.resumed else frozenset()

    def record_window(self, win: int, part: str) -> None:
        """Durably record window ``win`` as complete (its part file
        ``part`` — a name under ``out_dir`` — is already published).
        Idempotent; safe from the writer pool's publish thread."""
        with self._lock:
            if self._windows.get(win) == part:
                return
            self._windows[win] = part
            self._flush_locked()

    def _flush_locked(self) -> None:
        atomic_write_json(self._journal_path, {
            "schema": self.SCHEMA,
            "fingerprint": self.fingerprint,
            "n_windows": self._n_windows,
            "windows": {str(k): v for k, v in sorted(self._windows.items())},
        })

    # ---- observe-histogram / table sidecars ----------------------------
    def has_observation(self, win: int) -> bool:
        return os.path.isfile(self.observation_path(win))

    @staticmethod
    def _npz_bytes(**arrays) -> bytes:
        import io

        import numpy as np

        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        return buf.getvalue()

    def save_observation(self, win, total, mism, gl) -> None:
        """Persist one window's observe histogram (atomic, idempotent)."""
        import numpy as np

        path = self.observation_path(win)
        if os.path.exists(path):
            return
        atomic_write_bytes(path, self._npz_bytes(
            total=np.asarray(total), mism=np.asarray(mism),
            gl=np.int64(gl),
        ))

    def load_observation(self, win: int):
        """-> (total, mism, gl) host arrays, or None (absent/unreadable
        — the window simply re-observes)."""
        import numpy as np

        path = self.observation_path(win)
        if not os.path.isfile(path):
            return None
        try:
            with np.load(path) as z:
                return z["total"], z["mism"], int(z["gl"])
        except Exception as e:
            logger.warning(
                "observe sidecar %s is unreadable (%s); window %d will "
                "re-observe", path, e, win,
            )
            return None

    def save_table(self, table, gl) -> None:
        """Persist the solved recalibration table (once, after barrier 2)."""
        import numpy as np

        atomic_write_bytes(self._table_path, self._npz_bytes(
            table=np.asarray(table), gl=np.int64(gl),
        ))

    def load_table(self):
        """-> (table, gl), or None when absent/unreadable."""
        import numpy as np

        if not (self.resumed and os.path.isfile(self._table_path)):
            return None
        try:
            with np.load(self._table_path) as z:
                return z["table"], int(z["gl"])
        except Exception as e:
            logger.warning(
                "recalibration-table sidecar %s is unreadable (%s); "
                "re-solving from observations", self._table_path, e,
            )
            return None
