"""Stage checkpoint / restart for composed pipelines.

The reference delegates fault tolerance to Spark lineage recompute;
SURVEY §5 told the TPU build to decide its own story. The decision:
**stage materialization** — each completed pipeline stage can persist
its full dataset to Parquet under a checkpoint directory with a manifest
recording stage order and completion, and a rerun of the same pipeline
resumes from the last completed stage instead of recomputing (the moral
equivalent of the reference chaining `transform` runs through files,
made automatic). Inputs stay re-shardable because the checkpoint is the
columnar Parquet store any mesh shape can reload.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Callable, Optional, Sequence

logger = logging.getLogger(__name__)

_MANIFEST = "MANIFEST.json"


class StageCheckpointer:
    """Tracks stage completion under ``directory``.

    The manifest stores the ordered stage list; a stage is resumable only
    if the recorded order matches the current pipeline's prefix (a
    changed flag composition invalidates downstream checkpoints).
    """

    def __init__(self, directory: str, stages: Sequence[str]):
        self.dir = directory
        self.stages = list(stages)
        os.makedirs(directory, exist_ok=True)
        self._completed: list[str] = []
        mpath = os.path.join(directory, _MANIFEST)
        m = None
        if os.path.exists(mpath):
            try:
                with open(mpath) as fh:
                    m = json.load(fh)
                if not isinstance(m, dict):
                    raise ValueError(f"manifest is {type(m).__name__}, "
                                     "not an object")
            except (OSError, ValueError) as e:
                # a torn/corrupt manifest (crashed writer, disk hiccup)
                # must cost a recompute, not brick every future resume
                logger.warning(
                    "checkpoint manifest %s is unreadable (%s); treating "
                    "as no checkpoint and restarting", mpath, e,
                )
                m = None
        if m is not None:
            if m.get("stages") == self.stages:
                self._completed = [
                    s for s in m.get("completed", [])
                    if os.path.exists(self.path(s))
                ]
            else:
                logger.warning(
                    "checkpoint dir %s was built for stages %s (now %s); "
                    "ignoring old checkpoints", directory,
                    m.get("stages"), self.stages,
                )

    def path(self, stage: str) -> str:
        return os.path.join(self.dir, f"{stage}.adam")

    def last_completed(self) -> Optional[str]:
        """Deepest stage that completed as a prefix of the stage list."""
        last = None
        for s in self.stages:
            if s in self._completed:
                last = s
            else:
                break
        return last

    def mark(self, stage: str) -> None:
        self._completed.append(stage)
        mpath = os.path.join(self.dir, _MANIFEST)
        tmp = mpath + ".tmp"
        # temp + atomic rename: a crash mid-write leaves either the old
        # complete manifest or the new one, never a torn file (and the
        # init path above tolerates even that)
        try:
            with open(tmp, "w") as fh:
                json.dump(
                    {"stages": self.stages, "completed": self._completed},
                    fh,
                )
            os.replace(tmp, mpath)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise


def run_stages(
    ds,
    stages: Sequence[tuple[str, Callable]],
    checkpoint_dir: Optional[str] = None,
):
    """Run ``(name, fn)`` stages over a dataset with optional
    checkpoint-restart.

    With a checkpoint dir, each stage's output is materialized to
    Parquet and recorded; a rerun resumes after the deepest completed
    stage (loading its store) instead of recomputing.
    """
    if not checkpoint_dir:
        for _, fn in stages:
            ds = fn(ds)
        return ds

    from adam_tpu.api.datasets import AlignmentDataset

    ck = StageCheckpointer(checkpoint_dir, [n for n, _ in stages])
    resume_after = ck.last_completed()
    skipping = resume_after is not None
    if skipping:
        logger.info("resuming after checkpointed stage %r", resume_after)
        ds = AlignmentDataset.load(ck.path(resume_after))
    for name, fn in stages:
        if skipping:
            if name == resume_after:
                skipping = False
            continue
        ds = fn(ds)
        ds.save(ck.path(name))
        ck.mark(name)
    return ds
