"""Read trimming — fixed-length and adaptive (quality-profile) variants.

Covers the surface of ``rdd/read/correction/TrimReads.scala``:

* ``trim_reads(ds, trim_start, trim_end)`` — fixed trim of every read
  (``TrimReads.apply(rdd, trimStart, trimEnd)``, :111-133): drops bases
  and quals, rewrites the CIGAR with hard clips (excising deletions /
  reference skips that are trimmed through, :255-341), shifts
  ``start``/``end`` when alignment-match bases are trimmed, and trims the
  MD tag (:163-240).
* ``trim_low_quality_read_groups(ds, phred_threshold)`` — the adaptive
  variant (:39-109): per (read group, cycle) mean quality profile, trim
  the leading/trailing cycles whose mean phred is below the threshold.

TPU-first split: the quality profile is a device kernel (scatter-add of
log success probabilities into a dense ``[n_rg, Lmax]`` histogram — the
analog of the reference's ``reduceByKeyLocally`` over ``((rg, pos),
logp)`` pairs); base/qual trimming is a vectorized shift of the batch
columns; only the variable-length CIGAR/MD rewrite stays host-side,
like the realignment writer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch
from adam_tpu.ops import phred
from adam_tpu.utils.transfer import device_fetch

# ------------------------------------------------------------------ profile


@functools.partial(jax.jit, static_argnums=(1,))
def quality_profile_kernel(batch: ReadBatch, n_rg: int):
    """Sum of log success probabilities and counts per (read group, cycle).

    Reads with no read group land in bin ``n_rg`` (the reference keys them
    by a null record-group name, TrimReads.scala:145-153).
    """
    n, lmax = batch.quals.shape
    pos_ok = (jnp.arange(lmax)[None, :] < batch.lengths[:, None]) & (
        batch.valid & batch.has_qual
    )[:, None]
    logp = jnp.log(phred.phred_to_success_probability(batch.quals))
    rg = jnp.where(batch.read_group_idx < 0, n_rg, batch.read_group_idx)
    flat_bins = rg[:, None] * lmax + jnp.arange(lmax)[None, :]
    size = (n_rg + 1) * lmax
    sums = jax.ops.segment_sum(
        jnp.where(pos_ok, logp, 0.0).reshape(-1), flat_bins.reshape(-1), size
    )
    counts = jax.ops.segment_sum(
        pos_ok.astype(jnp.int32).reshape(-1), flat_bins.reshape(-1), size
    )
    return sums.reshape(n_rg + 1, lmax), counts.reshape(n_rg + 1, lmax)


def mean_quality_profile(batch: ReadBatch, n_rg: int):
    """Per-(rg, cycle) mean phred: successProbabilityToPhred(exp(sum/count))
    (TrimReads.scala:76-87)."""
    sums, counts = quality_profile_kernel(batch.to_device(), n_rg)
    sums, counts = device_fetch(sums), device_fetch(counts)
    means = np.full(sums.shape, -1, np.int64)
    nz = counts > 0
    succ = np.exp(sums[nz] / counts[nz])
    means[nz] = np.floor(-10.0 * np.log10(1.0 - succ) + 0.5).astype(np.int64)
    return means, counts


def trim_lengths(mean_quals: np.ndarray, counts: np.ndarray, threshold: int):
    """takeWhile(mean < threshold) from each end (TrimReads.scala:89-92)."""
    idx = np.flatnonzero(counts > 0)
    if idx.size == 0:
        return 0, 0
    quals = mean_quals[idx]
    below = quals < threshold
    if below.all():
        # every cycle fails the threshold: the whole read would go —
        # callers with strict=False then skip the group entirely, so
        # surface the silent no-op (deviation from pure takeWhile ends)
        import logging

        logging.getLogger(__name__).warning(
            "trim: every cycle of a read group's quality profile is below "
            "threshold %d; reads in this group will be left untrimmed "
            "unless strict", threshold,
        )
        return len(quals), 0
    return int(np.argmin(below)), int(np.argmin(below[::-1]))


# ------------------------------------------------------------- cigar / md


def trim_cigar(
    ops: np.ndarray, lens: np.ndarray, n: int, trim_start: int, trim_end: int,
    start: int, end: int,
):
    """Trim a CIGAR, returning
    ``(elems, new_start, new_end, aligned_front, aligned_back)``.

    Mirrors TrimReads.trimCigar (:255-341): D/N runs hit while trimming
    are excised whole (advancing the reference coordinate by their full
    length); trimmed segments are replaced with hard clips.

    Deviations where the reference silently corrupts records: existing
    H/P operators consume no read bases, so they never count against the
    trim budget — leading/trailing hard clips merge into the emitted
    clip run instead of being decremented like matches.  The returned
    ``aligned_front``/``aligned_back`` are the number of M/=/X bases
    actually trimmed from each end — the counts MD trimming needs (MD
    covers aligned bases only, not soft clips or insertions).
    """
    elems = [(int(lens[i]), int(ops[i])) for i in range(n)]

    def trim_front(elems, trim, pos, step):
        out = list(elems)
        h = 0  # existing hard clips on this end, merged into the new clip
        aligned = 0
        while out and out[0][1] == schema.CIGAR_H:
            h += out.pop(0)[0]
        while trim > 0 and out:
            ln, op = out[0]
            if op in (schema.CIGAR_D, schema.CIGAR_N):
                out.pop(0)
                pos += step * ln
                continue
            if op in (schema.CIGAR_H, schema.CIGAR_P):
                out.pop(0)  # consumes no read bases; budget untouched
                continue
            if ln == 1:
                out.pop(0)
            else:
                out[0] = (ln - 1, op)
            if op in (schema.CIGAR_M, schema.CIGAR_EQ, schema.CIGAR_X):
                pos += step
                aligned += 1
            trim -= 1
        return out, pos, h, aligned

    elems, start, h_front, al_front = trim_front(elems, trim_start, start, +1)
    rev, end, h_back, al_back = trim_front(elems[::-1], trim_end, end, -1)
    elems = rev[::-1]
    if trim_start + h_front > 0:
        elems.insert(0, (trim_start + h_front, schema.CIGAR_H))
    if trim_end + h_back > 0:
        elems.append((trim_end + h_back, schema.CIGAR_H))
    return elems, start, end, al_front, al_back


def _md_tokens(md: str) -> list:
    """MD string -> [int match | 'A' mismatch | '^ACG' deletion] tokens."""
    toks, i = [], 0
    while i < len(md):
        c = md[i]
        if c.isdigit():
            j = i
            while j < len(md) and md[j].isdigit():
                j += 1
            toks.append(int(md[i:j]))
            i = j
        elif c == "^":
            j = i + 1
            while j < len(md) and md[j].isalpha():
                j += 1
            toks.append(md[i:j])
            i = j
        else:
            toks.append(c)
            i += 1
    return toks


def _md_string(toks: list) -> str:
    """Emit tokens with match counts (0 where absent) between events."""
    out, need_num = [], True
    for t in toks:
        if isinstance(t, int):
            out.append(str(t))
            need_num = False
        else:
            if need_num:
                out.append("0")
            out.append(t)
            need_num = True
    if need_num:
        out.append("0")
    return "".join(out)


def trim_md_tag(md: str, trim_start: int, trim_end: int) -> str:
    """Trim aligned bases off an MD tag (TrimReads.trimMdTag, :163-240).

    Deletions hit while trimming are excised without consuming trim
    length (they consume reference, not read, bases).
    """
    toks = _md_tokens(md)

    def trim_front(toks, trim):
        out = list(toks)
        while trim > 0 and out:
            t = out[0]
            if isinstance(t, str) and t.startswith("^"):
                out.pop(0)
            elif isinstance(t, str):
                out.pop(0)
                trim -= 1
            else:  # match run
                if t == 0:
                    out.pop(0)
                else:
                    out[0] = t - 1
                    trim -= 1
        return out

    toks = trim_front(toks, trim_start)
    toks = trim_front(toks[::-1], trim_end)[::-1]
    return _md_string(toks)


# ------------------------------------------------------------------- apply


def _shift_columns(b: ReadBatch, ts: int, te: int, rows: np.ndarray) -> ReadBatch:
    """Vectorized drop of ts leading / te trailing bases for ``rows``."""
    bases = np.array(b.bases)
    quals = np.array(b.quals)
    lengths = np.array(b.lengths)
    lmax = bases.shape[1]
    new_len = np.maximum(lengths[rows] - ts - te, 0)
    keep = np.arange(lmax)[None, :] < new_len[:, None]
    pad_cols = ((0, 0), (0, ts))
    g = np.pad(bases[rows][:, ts:], pad_cols, constant_values=schema.BASE_PAD)
    bases[rows] = np.where(keep, g, schema.BASE_PAD)
    gq = np.pad(quals[rows][:, ts:], pad_cols, constant_values=schema.QUAL_PAD)
    quals[rows] = np.where(keep, gq, schema.QUAL_PAD)
    lengths[rows] = new_len
    return b.replace(bases=bases, quals=quals, lengths=lengths)


def trim_reads(
    ds: AlignmentDataset, trim_start: int = -1, trim_end: int = -1,
    rg_idx: int | None = None, strict: bool = True,
) -> AlignmentDataset:
    """Fixed trim of ``trim_start``/``trim_end`` bases (negative = 0).

    ``rg_idx`` restricts the trim to one read group (the adaptive
    variant's per-group loop, TrimReads.scala:64-96).  With
    ``strict=False``, reads too short for the trim are left untouched
    instead of raising (the adaptive path uses this: a group's
    profile-derived trim must not be fatal for its shortest reads).
    """
    ts, te = max(trim_start, 0), max(trim_end, 0)
    if ts == 0 and te == 0:
        return ds
    b = ds.batch.to_numpy()
    side = ds.sidecar
    mask = np.asarray(b.valid).copy()
    if rg_idx is not None:
        mask &= np.asarray(b.read_group_idx) == rg_idx
    too_short = np.asarray(b.lengths) <= ts + te
    if strict and bool((mask & too_short).any()):
        raise ValueError("cannot trim more than the length of the read")
    mask &= ~too_short
    rows = np.flatnonzero(mask)
    if rows.size == 0:
        return ds

    b = _shift_columns(b, ts, te, rows)

    # CIGAR / start / end / MD rewrite, host-side per affected row.
    cigar_ops = np.array(b.cigar_ops)
    cigar_lens = np.array(b.cigar_lens)
    cigar_n = np.array(b.cigar_n)
    start = np.array(b.start)
    end = np.array(b.end)
    new_md = list(side.md)
    new_elems: dict[int, list] = {}
    cmax = b.cmax
    for i in rows:
        i = int(i)
        if cigar_n[i] == 0:
            continue
        elems, s, e, al_front, al_back = trim_cigar(
            cigar_ops[i], cigar_lens[i], int(cigar_n[i]), ts, te,
            int(start[i]), int(end[i]),
        )
        new_elems[i] = elems
        start[i], end[i] = s, e
        if side.md[i] is not None:
            # MD covers aligned bases only — trim it by the number of
            # M/=/X bases removed, not the raw read-base trim
            new_md[i] = trim_md_tag(side.md[i], al_front, al_back)
        cmax = max(cmax, len(elems))
    if cmax > b.cmax:
        b = b.widen(b.lmax, cmax)
        cigar_ops = np.array(b.cigar_ops)
        cigar_lens = np.array(b.cigar_lens)
    for i, elems in new_elems.items():
        cigar_ops[i] = schema.CIGAR_PAD
        cigar_lens[i] = 0
        for j, (ln, op) in enumerate(elems):
            cigar_ops[i, j] = op
            cigar_lens[i, j] = ln
        cigar_n[i] = len(elems)

    b = b.replace(
        cigar_ops=cigar_ops, cigar_lens=cigar_lens, cigar_n=cigar_n,
        start=start, end=end,
    )
    from dataclasses import replace as dc_replace

    rowset = set(int(r) for r in rows)
    side = dc_replace(
        side,
        md=new_md,
        trimmed_from_start=[
            v + (ts if k in rowset else 0)
            for k, v in enumerate(side.trimmed_from_start)
        ],
        trimmed_from_end=[
            v + (te if k in rowset else 0)
            for k, v in enumerate(side.trimmed_from_end)
        ],
    )
    return ds.with_batch(b, side)


def trim_low_quality_read_groups(
    ds: AlignmentDataset, phred_threshold: int = 20
) -> AlignmentDataset:
    """Adaptive trim: per-read-group mean quality profile, trim cycles
    below ``phred_threshold`` from each end (TrimReads.scala:39-109)."""
    n_rg = len(ds.header.read_groups.names)
    means, counts = mean_quality_profile(ds.batch, n_rg)
    out = ds
    for rg in range(n_rg + 1):
        ts, te = trim_lengths(means[rg], counts[rg], phred_threshold)
        if ts == 0 and te == 0:
            continue
        out = trim_reads(
            out, ts, te, rg_idx=rg if rg < n_rg else -1, strict=False
        )
    return out
