from adam_tpu.pipelines import markdup, sort

__all__ = ["markdup", "sort"]
