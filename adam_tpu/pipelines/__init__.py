from adam_tpu.pipelines import markdup, region_join, sort

__all__ = ["markdup", "region_join", "sort"]
