"""Sort reads by reference position.

Semantics of ``adamSortReadsByReferencePosition``
(rdd/read/AlignmentRecordRDDFunctions.scala:245-258): mapped reads order
by (referenceName, start) with reference names compared
**lexicographically** (ReferencePosition's ordering is on the name
string); unmapped reads sort after every mapped read (the reference keys
them "ZZZ"+readName — a skew-avoidance trick), ordered by read name.

Device formulation: contig names become lexicographic ranks, each read
gets one packed i64 key, and a single stable sort orders the batch.
Unmapped reads get the max contig rank; their name ordering is resolved
host-side (names live in the sidecar).
"""

from __future__ import annotations

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.models.positions import pack_position_key


def sort_keys(ds: AlignmentDataset) -> np.ndarray:
    """Permutation that coordinate-sorts the dataset's valid rows."""
    b = ds.batch.to_numpy()
    names = ds.seq_dict.names
    # lexicographic rank of each contig index
    order = np.argsort(np.array(names, dtype=object), kind="stable") if names else np.array([], np.int64)
    rank_of = np.empty(max(len(names), 1), dtype=np.int64)
    rank_of[order] = np.arange(len(names)) if len(names) else 0

    from adam_tpu.formats import schema

    contig = np.asarray(b.contig_idx)
    # mapped-ness is the FLAG bit, not position presence: placed-unmapped
    # reads (FLAG 0x4 with mate's RNAME/POS) still sort last, like the
    # reference's keying on getReadMapped.
    mapped = (
        ((np.asarray(b.flags) & schema.FLAG_UNMAPPED) == 0)
        & (contig >= 0)
        & np.asarray(b.valid)
    )
    ranks = np.where(mapped, rank_of[np.clip(contig, 0, max(len(names) - 1, 0))], len(names))
    keys = pack_position_key(ranks.astype(np.int32), np.where(mapped, b.start, 0))

    rows = np.flatnonzero(np.asarray(b.valid))
    mapped_rows = rows[mapped[rows]]
    unmapped_rows = rows[~mapped[rows]]
    mapped_sorted = mapped_rows[np.argsort(keys[mapped_rows], kind="stable")]
    name_arr = np.array([ds.sidecar.names[i] for i in unmapped_rows], dtype=object)
    unmapped_sorted = unmapped_rows[np.argsort(name_arr, kind="stable")]
    return np.concatenate([mapped_sorted, unmapped_sorted])


def sort_by_reference_position(ds: AlignmentDataset) -> AlignmentDataset:
    return ds.take_rows(sort_keys(ds))
