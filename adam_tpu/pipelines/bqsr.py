"""Base Quality Score Recalibration (BQSR).

Two-pass algorithm with the exact semantics of the reference's
``rdd/read/recalibration/`` package:

* **Observe** (BaseQualityRecalibration.scala:55-85): canonical reads
  (primary, mapped, not duplicate, qual present, 0 < mapq < 255, passed
  vendor QC) contribute one observation per residue that has quality > 0,
  a regular ACGT base, a reference position (not an insertion/soft-clip)
  and is not masked by the known-SNPs table.  The covariate key is
  (read group, reported quality, cycle, dinucleotide)
  (CycleCovariate.scala:23-49, DinucCovariate.scala:24-66).
* **Recalibrate** (Recalibrator.scala:28-165): every read with qualities
  gets per-residue recalibrated quality from the log-space delta stack
  global -> per-quality -> per-cycle/per-dinuc, bounded to Q50
  (RecalibrationTable), applied only to residues with reported quality >=
  Q5 (minAcceptableQuality, BaseQualityRecalibration.scala:50).

TPU formulation: the covariate key space is **dense** — (rg, 94 quals,
2L+1 cycles, 17 dinucs) — so the reference's HashMap-aggregate becomes a
scatter-add histogram on device, combined across chips with a `psum`, and
the recalibration table lookups become marginal reductions + gathers:
no strings, no hashing, one fused kernel per pass.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.models.snp_table import SnpTable
from adam_tpu.ops import cigar as cigar_ops
from adam_tpu.ops.mdtag import batch_md_arrays
from adam_tpu.ops.phred import PHRED_TO_ERROR
from adam_tpu.utils import telemetry as _tele
from adam_tpu.utils.transfer import device_fetch

N_QUAL = 94  # valid phred range 0..93 (QualityScore.scala)
N_DINUC = 17  # 16 (prev,cur) pairs + index 16 = None ("NN")
DINUC_NONE = 16
MIN_ACCEPTABLE_QUALITY = 5
MAX_QUAL = 50


# --------------------------------------------------------------------------
# Per-residue kernel backend selection
# --------------------------------------------------------------------------
BACKENDS = ("device", "native", "numpy")
_CHIP_PRESENT: Optional[bool] = None


def chip_present() -> bool:
    """True when an accelerator (non-CPU jax device) is attached.

    Probed once per process: ``jax.devices()`` initializes the backend,
    which on the tunneled chip can take seconds — never in a hot loop.
    """
    global _CHIP_PRESENT
    if _CHIP_PRESENT is None:
        try:
            _CHIP_PRESENT = any(
                d.platform not in ("cpu",) for d in jax.devices()
            )
        except Exception:
            _CHIP_PRESENT = False
    return _CHIP_PRESENT


def bqsr_backend(override: Optional[str] = None) -> str:
    """Resolve the per-residue pass backend: ``device`` (jit scatter/
    gather kernels on the attached chip), ``native`` (threaded C++ host
    walks), or ``numpy`` (pure-host vectorized twins).

    Order: explicit ``override`` arg, then ``ADAM_TPU_BQSR_BACKEND``,
    then the topology default — **device when a chip is present** (the
    round-5 tunnel re-measured ~1.1 GB/s, so the [N, L] traffic that
    justified the host-first split no longer does; see docs/PERF.md),
    native on CPU-only hosts with the toolchain, numpy otherwise.
    """
    b = (override or os.environ.get("ADAM_TPU_BQSR_BACKEND", "")).strip().lower()
    if b:
        if b not in BACKENDS:
            src = (
                "backend argument" if override
                else "ADAM_TPU_BQSR_BACKEND"
            )
            raise ValueError(
                f"{src}={b!r}: expected one of {BACKENDS}"
            )
        return b
    if chip_present():
        return "device"
    from adam_tpu import native

    return "native" if native.available() else "numpy"


# --------------------------------------------------------------------------
# Covariates (device)
# --------------------------------------------------------------------------
def compute_cycles(lengths, flags, lmax: int):
    """Sequencer cycle per residue -> i32[N, L].

    (initial, increment): forward/first (1, +1); forward/second (-1, -1);
    reverse/first (L, -1); reverse/second (-L, +1) — CycleCovariate.scala:31-49;
    'second' means paired && secondOfPair, everything else is 'first'.
    """
    rev = (flags & schema.FLAG_REVERSE) != 0
    second = ((flags & schema.FLAG_PAIRED) != 0) & (
        (flags & schema.FLAG_SECOND_OF_PAIR) != 0
    )
    L = lengths.astype(jnp.int32)
    initial = jnp.where(
        rev,
        jnp.where(second, -L, L),
        jnp.where(second, -1, 1),
    )
    increment = jnp.where(rev, jnp.where(second, 1, -1), jnp.where(second, -1, 1))
    pos = jnp.arange(lmax, dtype=jnp.int32)[None, :]
    return initial[:, None] + increment[:, None] * pos


def compute_cycles_np(lengths, flags, lmax: int):
    """Host twin of :func:`compute_cycles` (vectorized numpy)."""
    rev = (np.asarray(flags) & schema.FLAG_REVERSE) != 0
    second = ((np.asarray(flags) & schema.FLAG_PAIRED) != 0) & (
        (np.asarray(flags) & schema.FLAG_SECOND_OF_PAIR) != 0
    )
    L = np.asarray(lengths).astype(np.int32)
    initial = np.where(rev, np.where(second, -L, L), np.where(second, -1, 1))
    increment = np.where(rev, np.where(second, 1, -1), np.where(second, -1, 1))
    pos = np.arange(lmax, dtype=np.int32)[None, :]
    return initial[:, None] + increment[:, None] * pos


def compute_dinucs_np(bases, lengths, flags, lmax: int):
    """Host twin of :func:`compute_dinucs` (vectorized numpy)."""
    comp = np.asarray(schema.BASE_COMPLEMENT)
    bases = np.asarray(bases)
    rev = ((np.asarray(flags) & schema.FLAG_REVERSE) != 0)[:, None]
    prev_f = np.pad(bases[:, :-1], ((0, 0), (1, 0)),
                    constant_values=schema.BASE_N)
    next_b = np.pad(bases[:, 1:], ((0, 0), (0, 1)),
                    constant_values=schema.BASE_N)
    cur = np.where(rev, comp[bases], bases)
    prev = np.where(rev, comp[next_b], prev_f)
    i = np.arange(lmax)[None, :]
    lens = np.asarray(lengths)
    in_read = i < lens[:, None]
    first_machine = np.where(rev, i == (lens[:, None] - 1), i == 0)
    regular = (cur < 4) & (prev < 4)
    ok = in_read & ~first_machine & regular
    idx = prev.astype(np.int32) * 4 + cur.astype(np.int32)
    return np.where(ok, idx, DINUC_NONE)


def compute_dinucs(bases, lengths, flags, lmax: int):
    """Dinucleotide index per residue -> i32[N, L] in [0, 16].

    Forward: (seq[i-1], seq[i]); reverse: (comp(seq[i+1]), comp(seq[i])) —
    i.e. the machine-order previous base (DinucCovariate.scala:24-50).
    None (index 16) at the machine-order first base or when either base
    is not a regular ACGT.
    """
    comp = jnp.asarray(schema.BASE_COMPLEMENT)
    rev = ((flags & schema.FLAG_REVERSE) != 0)[:, None]
    cur_f = bases
    prev_f = jnp.pad(bases[:, :-1], ((0, 0), (1, 0)), constant_values=schema.BASE_N)
    next_b = jnp.pad(bases[:, 1:], ((0, 0), (0, 1)), constant_values=schema.BASE_N)
    cur = jnp.where(rev, comp[cur_f], cur_f)
    prev = jnp.where(rev, comp[next_b], prev_f)
    i = jnp.arange(lmax)[None, :]
    in_read = i < lengths[:, None]
    first_machine = jnp.where(rev, i == (lengths[:, None] - 1), i == 0)
    regular = (cur < 4) & (prev < 4)
    ok = in_read & ~first_machine & regular
    idx = prev.astype(jnp.int32) * 4 + cur.astype(jnp.int32)
    return jnp.where(ok, idx, DINUC_NONE)


# --------------------------------------------------------------------------
# Observation pass
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("n_rg", "lmax"))
def observe_kernel(
    bases, quals, lengths, flags, read_group_idx,
    residue_ok, is_mismatch, read_ok,
    n_rg: int, lmax: int,
):
    """Scatter-add residue observations into the dense covariate histogram.

    Returns (total, mismatches) as i64[n_rg, N_QUAL, 2*lmax+1, N_DINUC].
    """
    n_cyc = 2 * lmax + 1
    cycles = compute_cycles(lengths, flags, lmax)
    dinucs = compute_dinucs(bases, lengths, flags, lmax)
    q = jnp.clip(quals.astype(jnp.int32), 0, N_QUAL - 1)
    # reads without a read group get their own bin (index n_rg - 1 of the
    # n_rg = len(groups)+1 bins), like the reference's null readGroup key
    rg = jnp.where(read_group_idx >= 0, read_group_idx, n_rg - 1).astype(jnp.int32)
    include = residue_ok & read_ok[:, None]

    # i32 keys and counts: int64 is emulated on the TPU vector unit and
    # the scatter-add dominates the pass; a single batch shard can't
    # overflow 2^31 observations (callers psum in i64 across shards)
    flat_key = (
        ((rg[:, None] * N_QUAL + q) * n_cyc + (cycles + lmax)) * N_DINUC + dinucs
    ).astype(jnp.int32)
    size = n_rg * N_QUAL * n_cyc * N_DINUC
    flat_key = jnp.where(include, flat_key, 0).ravel()
    ones = include.astype(jnp.int32).ravel()
    mm = (include & is_mismatch).astype(jnp.int32).ravel()
    total = jnp.zeros(size, jnp.int32).at[flat_key].add(ones)
    mism = jnp.zeros(size, jnp.int32).at[flat_key].add(mm)
    shape = (n_rg, N_QUAL, n_cyc, N_DINUC)
    return (
        total.reshape(shape).astype(jnp.int64),
        mism.reshape(shape).astype(jnp.int64),
    )


def observe_packed_body(
    bases, quals, lengths, flags, read_group_idx,
    res_bits, mm_bits, read_ok,
    n_rg: int, lmax: int,
):
    """Traceable observe pass over **bit-packed** per-pass masks
    (``res_bits``/``mm_bits``: u8[N, L/8] from
    ``colpack.pack_mask_bits``) — the resident-window dispatch variant:
    bases/quals/lengths/flags/rg come from the window's ingest-resident
    device arrays, and the only per-residue h2d payload is the two
    packed masks (8x smaller than the booleans the plain kernel
    ships).  Unpacks on device, then runs the exact scatter-add of
    :func:`observe_kernel` — bitwise the same histograms.

    Backend-selected at trace time (``ops/kernel_backend``): under
    ``pallas`` the covariate keys stay XLA (cheap, fusible) and the
    memory-bound scatter-add over the bit-packed masks runs in
    :func:`adam_tpu.ops.pallas_observe.observe_hist_pallas` — bits
    unpack in-register, the histogram accumulates in VMEM.  Every jit
    holding this body keys its cache on the backend."""
    from adam_tpu.ops.kernel_backend import kernel_backend

    if kernel_backend() == "pallas":
        from adam_tpu.ops.pallas_observe import observe_hist_pallas

        n_cyc = 2 * lmax + 1
        cycles = compute_cycles(lengths, flags, lmax)
        dinucs = compute_dinucs(bases, lengths, flags, lmax)
        q = jnp.clip(quals.astype(jnp.int32), 0, N_QUAL - 1)
        rg = jnp.where(
            read_group_idx >= 0, read_group_idx, n_rg - 1
        ).astype(jnp.int32)
        flat_key = (
            ((rg[:, None] * N_QUAL + q) * n_cyc + (cycles + lmax))
            * N_DINUC + dinucs
        ).astype(jnp.int32)
        size = n_rg * N_QUAL * n_cyc * N_DINUC
        total, mism = observe_hist_pallas(
            flat_key, res_bits, mm_bits, read_ok, size
        )
        shape = (n_rg, N_QUAL, n_cyc, N_DINUC)
        return (
            total.reshape(shape).astype(jnp.int64),
            mism.reshape(shape).astype(jnp.int64),
        )
    from adam_tpu.ops.colpack import unpack_mask_body

    residue_ok = unpack_mask_body(res_bits, lmax)
    is_mismatch = unpack_mask_body(mm_bits, lmax)
    return observe_kernel.__wrapped__(
        bases, quals, lengths, flags, read_group_idx,
        residue_ok, is_mismatch, read_ok, n_rg, lmax,
    )


#: Lazily-built jit variants keyed by (kind, donate): the donating
#: twins are DISTINCT executables from the plain ones (donation is part
#: of the jit wrapper), so the prewarm must warm exactly the variant a
#: dispatch will call — both sides resolve through this one registry
#: with the same (kind, donate) decision, which is what keeps the
#: compile ledger's donated-signature executables deduped against the
#: prewarm (device.compile.in_window stays 0).
_JIT_VARIANTS: dict = {}
_JIT_VARIANTS_LOCK = threading.Lock()


def jit_variant(kind: str, donate: bool = False):
    """The jit for one kernel ``kind`` (``observe_packed`` / ``apply``
    / ``apply_pack`` / ``apply_pack2`` / ``fused_bc``) with or without
    buffer donation.  Donation aliases the dead-after-apply inputs into
    the outputs (the resident quals buffer becomes the packed qual
    column, the resident bases buffer the packed base column; the
    observe variant donates its per-pass mask temporaries), halving
    pass-C's per-window HBM footprint — only offered where the runtime
    honors it (``device_pool.donation_ok``; CPU runtimes warn and
    copy).

    Keyed by ``(kind, donate, kernel_backend())``: the bodies branch on
    the Pallas/XLA backend at *trace* time, so a backend flip must
    reach a fresh jit rather than a stale executable (and the compile
    ledger keys the same way — see utils/compile_ledger)."""
    from adam_tpu.ops.kernel_backend import kernel_backend

    key = (kind, bool(donate), kernel_backend())
    fn = _JIT_VARIANTS.get(key)
    if fn is not None:
        return fn
    with _JIT_VARIANTS_LOCK:
        fn = _JIT_VARIANTS.get(key)
        if fn is not None:
            return fn
        if not donate and kind == "apply":
            # apply_table_body has no backend branch: the module-level
            # jit stays the one executable either way
            fn = apply_table_kernel
        else:
            body, statics, donums = {
                "observe_packed": (
                    observe_packed_body, ("n_rg", "lmax"), (5, 6)
                ),
                "apply": (apply_table_body, ("lmax",), (1,)),
                "apply_pack": (apply_pack_body, ("lmax", "size"), (1,)),
                "apply_pack2": (
                    apply_pack2_body, ("lmax", "size"), (0, 1)
                ),
                "fused_bc": (
                    fused_bc_body, ("n_rg", "lmax", "size"), (0, 1, 5, 6)
                ),
            }[kind]
            kw = {"static_argnames": statics}
            if donate:
                kw["donate_argnums"] = donums
            fn = partial(jax.jit, **kw)(body)
        _JIT_VARIANTS[key] = fn
    return fn


def observe_kernel_np(
    bases, quals, lengths, flags, read_group_idx,
    residue_ok, is_mismatch, read_ok,
    n_rg: int, lmax: int,
):
    """Host twin of :func:`observe_kernel` (bincount over the same fused
    i32 covariate keys) — the ``numpy`` backend and the differential
    oracle for the device scatter-add."""
    n_cyc = 2 * lmax + 1
    cycles = compute_cycles_np(lengths, flags, lmax)
    dinucs = compute_dinucs_np(bases, lengths, flags, lmax)
    q = np.clip(np.asarray(quals).astype(np.int32), 0, N_QUAL - 1)
    rg = np.where(
        np.asarray(read_group_idx) >= 0, np.asarray(read_group_idx), n_rg - 1
    ).astype(np.int32)
    include = np.asarray(residue_ok) & np.asarray(read_ok)[:, None]
    flat_key = (
        ((rg[:, None] * N_QUAL + q) * n_cyc + (cycles + lmax)) * N_DINUC
        + dinucs
    ).astype(np.int64)
    size = n_rg * N_QUAL * n_cyc * N_DINUC
    shape = (n_rg, N_QUAL, n_cyc, N_DINUC)
    total = np.bincount(flat_key[include], minlength=size).astype(np.int64)
    mism = np.bincount(
        flat_key[include & np.asarray(is_mismatch)], minlength=size
    ).astype(np.int64)
    return total.reshape(shape), mism.reshape(shape)


class ObservationTable:
    """Dense covariate histogram + CSV emission compatible with the
    reference's ObservationTable.toCSV (GATK-style)."""

    def __init__(self, total: np.ndarray, mismatches: np.ndarray,
                 rg_names: list[str], lmax: int):
        self.total = np.asarray(total)
        self.mismatches = np.asarray(mismatches)
        self.rg_names = rg_names
        self.lmax = lmax

    @staticmethod
    def _dinuc_str(idx: int) -> str:
        if idx == DINUC_NONE:
            return "NN"
        return "ACGT"[idx // 4] + "ACGT"[idx % 4]

    @staticmethod
    def empirical_quality(total, mismatches):
        """Bayes with Beta(1,1): (1+mm)/(2+total) -> phred with Scala
        math.round = floor(x+0.5) (ObservationTable.scala:55-59,
        PhredUtils rounding).  Vectorized numpy."""
        p = (1.0 + np.asarray(mismatches)) / (2.0 + np.asarray(total))
        return np.floor(-10.0 * np.log10(p) + 0.5).astype(np.int64)

    def to_csv(self) -> str:
        lines = ["ReadGroup,ReportedQ,Cycle,Dinuc,TotalCount,MismatchCount,EmpiricalQ,IsSkipped"]
        rg_idx, q_idx, c_idx, d_idx = np.nonzero(self.total)
        totals = self.total[rg_idx, q_idx, c_idx, d_idx]
        mms = self.mismatches[rg_idx, q_idx, c_idx, d_idx]
        emp = self.empirical_quality(totals, mms)
        for rg, q, c, d, t, m, e in zip(rg_idx, q_idx, c_idx, d_idx, totals, mms, emp):
            fields = [
                self.rg_names[rg],
                str(int(q)),
                str(int(c) - self.lmax),
                self._dinuc_str(int(d)),
                str(int(t)),
                str(int(m)),
                str(int(e)),
            ]
            if d == DINUC_NONE:
                fields.append("**")
            lines.append(",".join(fields))
        return "\n".join(lines)


def _observe_device(
    ds: AlignmentDataset, known_snps: Optional[SnpTable] = None,
    backend: Optional[str] = None, device=None, mesh=None, resident=None,
):
    """Run the observation pass -> (total, mism, rg_names, lmax).

    Backend dispatch (:func:`bqsr_backend`):

    * ``device`` — the jit scatter-add histogram (:func:`observe_kernel`)
      on the attached chip.  The histograms come back **lazy** (device
      arrays): per-window dispatches queue asynchronously and callers
      fetch the compact [n_rg, 94, 2L+1, 17] tables at the merge barrier
      (the sharded psum variant lives in parallel/dist.distributed_observe).
    * ``native`` — the threaded C++ cigar/MD walk; histograms are host
      numpy arrays and downstream table math stays host-side.  Falls
      back to the device kernel when the toolchain is unavailable.
    * ``numpy`` — :func:`observe_kernel_np`, the pure-host oracle.

    ``device``: explicit jax device for the ``device`` backend's
    scatter-add (the multi-chip pool's round-robin target); ``None``
    keeps the default device.  ``mesh``: a
    :class:`~adam_tpu.parallel.partitioner.MeshPartitioner` — the
    window's [N, L] arrays shard over its ``batch`` axis, the
    scatter-add runs per shard and the histograms ``psum`` on-device;
    the returned (total, mism) are lazy *replicated* device arrays the
    streamed pipeline folds into its device-resident accumulator
    instead of fetching per window.  Downstream consumers dispatch on
    ``isinstance(total, np.ndarray)`` so each path stays on its side of
    the device link.  ``resident``: the window's ingest-resident device
    payload (``device_pool.ResidentWindow``) — bases/quals/lengths/
    flags/rg dispatch off the handle and only the bit-packed per-pass
    masks ship (``colpack.pack_mask_bits``); a dead or mismatched
    handle falls back to the full re-ship, bitwise the same
    histograms."""
    backend = bqsr_backend(backend)
    from adam_tpu.parallel.device_pool import span_attrs

    # span carries the resolved backend so device-vs-host attribution is
    # visible per window in the flight recorder; mesh dispatches land on
    # the collective "mesh" track (they occupy every device at once)
    attrs = {"device": "mesh"} if mesh is not None else span_attrs(device)
    with _tele.TRACE.span(
        _tele.SPAN_BQSR_OBSERVE, backend=backend,
        reads=int(ds.batch.n_rows), **attrs,
    ):
        return _observe_impl(ds, known_snps, backend, device, mesh,
                             resident)


def observe_read_mask(b, has_md: np.ndarray) -> np.ndarray:
    """The canonical-read filter of the observe pass (primary, mapped,
    not duplicate, qual present, 0 < mapq < 255, passed vendor QC, MD
    present) -> bool[N].  ONE copy of the expression, shared by the
    solo dispatch below and the cross-job coalescer's fused grid
    (serve/batching.py) — bitwise the same filter on either path."""
    flags = np.asarray(b.flags)
    return (
        np.asarray(b.valid)
        & ((flags & schema.FLAG_UNMAPPED) == 0)
        & ((flags & (schema.FLAG_SECONDARY | schema.FLAG_SUPPLEMENTARY)) == 0)
        & ((flags & schema.FLAG_DUPLICATE) == 0)
        & ((flags & schema.FLAG_FAILED_QC) == 0)
        & np.asarray(b.has_qual)
        & (np.asarray(b.mapq) > 0)
        & (np.asarray(b.mapq) != 255)
        & has_md
    )


def observe_residue_mask(
    ds: AlignmentDataset, b, known_snps: Optional[SnpTable]
) -> np.ndarray:
    """The per-residue observe filter (q > 0, regular ACGT base,
    aligned to reference, not a known SNP) -> bool[N, L] — shared by
    the device/numpy solo paths and the coalescer's fused payload."""
    ref_pos = cigar_ops.reference_positions_np(
        b.cigar_ops, b.cigar_lens, b.cigar_n, b.start, b.lmax
    )
    quals = np.asarray(b.quals)
    rok = (
        (quals > 0) & (quals < schema.QUAL_PAD)
        & (np.asarray(b.bases) < 4) & (ref_pos >= 0)
    )
    if known_snps is not None and len(known_snps):
        rok &= ~known_snps.mask_positions(
            ds.seq_dict.names, np.asarray(b.contig_idx), ref_pos
        )
    return rok


def observe_inputs(ds: AlignmentDataset, known_snps=None) -> tuple:
    """Host-side observe-pass inputs for one window ->
    ``(b, read_ok, residue_ok, is_mm, n_rg)`` — exactly the arrays the
    device scatter-add consumes.  The cross-job coalescer
    (serve/batching.py) builds its fused ``[N_total, L]`` grid from
    these, so a coalesced window's per-job histogram slice is bitwise
    the solo kernel's output."""
    b = ds.batch.to_numpy()
    is_mm, _, has_md = batch_md_arrays(
        ds.batch, ds.sidecar, need_ref_codes=False
    )
    read_ok = observe_read_mask(b, has_md)
    residue_ok = observe_residue_mask(ds, b, known_snps)
    return b, read_ok, residue_ok, is_mm, len(ds.read_groups) + 1


def _observe_impl(
    ds: AlignmentDataset, known_snps: Optional[SnpTable], backend: str,
    device=None, mesh=None, resident=None,
):
    b = ds.batch.to_numpy()
    lmax = b.lmax
    from adam_tpu import native
    from adam_tpu.formats.strings import StringColumn

    n = b.n_rows
    md_col = StringColumn.of(ds.sidecar.md)
    use_native = (
        backend == "native" and native.available() and len(md_col) >= n
    )
    if use_native:
        # the native walk parses each read's MD inline — no host-side
        # [N, L] mismatch mask, no vectorized MD tokenize pass
        is_mm = None
        has_md = md_col.valid[:n] & np.asarray(b.valid)
    else:
        is_mm, _, has_md = batch_md_arrays(
            ds.batch, ds.sidecar, need_ref_codes=False
        )

    read_ok = observe_read_mask(b, has_md)

    # one extra bin for RG-less reads (the reference's null readGroup)
    n_rg = len(ds.read_groups) + 1
    from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np

    g = grid_rows(b.n_rows)
    gl = grid_cols(lmax)
    snp_active = known_snps is not None and len(known_snps)
    residue_ok = None
    snp_keys = None
    if snp_active and use_native:
        # known-SNP masking runs inside the native kernel's cigar walk
        # (sorted site-key binary search per residue) — the [N, L] i64
        # position matrix (~3 GB at WGS batch sizes) never materializes
        snp_keys = known_snps.site_keys(ds.seq_dict.names)

    def _python_residue_mask():
        # device/numpy backends: residue filter built host-side (the
        # module-level helper, shared with the cross-job coalescer)
        return observe_residue_mask(ds, b, known_snps)

    nat = None
    if use_native:
        nat = native.bqsr_observe(
            b.bases, b.quals, b.lengths, b.flags, b.read_group_idx,
            b.cigar_ops, b.cigar_lens, b.cigar_n,
            None, is_mm, read_ok, n_rg, gl,
            contig_idx=b.contig_idx, start=b.start, snp_keys=snp_keys,
            md_buf=md_col.buf, md_off=md_col.offsets[: n + 1],
        )
    if nat is not None:
        total, mism = nat  # host arrays: downstream table math stays host
    else:
        if is_mm is None:
            is_mm, _, _hm = batch_md_arrays(
                ds.batch, ds.sidecar, need_ref_codes=False
            )
        if residue_ok is None:
            residue_ok = _python_residue_mask()
        if backend == "numpy":
            total, mism = observe_kernel_np(
                b.bases, b.quals, b.lengths, b.flags, b.read_group_idx,
                residue_ok, is_mm, read_ok, n_rg, lmax,
            )
            # center the [-lmax, lmax] cycle slots inside the grid-width
            # table so every backend returns the same [.., 2*gl+1, ..]
            # shape (merge_observations pads by gl, not lmax)
            if gl != lmax:
                shape = (n_rg, N_QUAL, 2 * gl + 1, N_DINUC)
                t2 = np.zeros(shape, np.int64)
                m2 = np.zeros(shape, np.int64)
                off = gl - lmax
                t2[:, :, off : off + 2 * lmax + 1, :] = total
                m2[:, :, off : off + 2 * lmax + 1, :] = mism
                total, mism = t2, m2
        elif mesh is not None:
            from adam_tpu.utils import compile_ledger, faults
            from adam_tpu.utils import retry as _retry

            gm = mesh.rows_for(g)
            rw = resident
            if rw is not None and not (
                rw.alive and rw.device == "mesh"
                and rw.g == gm and rw.gl == gl
            ):
                rw = None
            if rw is not None:
                from adam_tpu.ops.colpack import pack_mask_bits

                res_pk = pack_mask_bits(
                    pad_rows_np(residue_ok, gm, False, cols=gl)
                )
                mm_pk = pack_mask_bits(
                    pad_rows_np(is_mm, gm, False, cols=gl)
                )
                rd_pad = pad_rows_np(read_ok, gm, False)

                def dispatch_mesh_resident():
                    # per-attempt placement of the small per-pass
                    # inputs keeps the retry idempotent even when the
                    # donating variant consumed a prior attempt's masks
                    faults.point("device.dispatch")
                    return mesh.observe_window_resident(
                        rw, res_pk, mm_pk, rd_pad, n_rg, gl
                    )

                with compile_ledger.track(
                    ("mesh.observe_packed", gm, gl, n_rg),
                    mesh.ledger_key(),
                ):
                    total, mism = _retry.retry_call(
                        dispatch_mesh_resident,
                        site="bqsr.observe.dispatch",
                    )
                rg_names = ds.read_groups.names + ["null"]
                return total, mism, rg_names, gl

            def dispatch_mesh():
                # the sharded placement + collective dispatch re-run as
                # one idempotent unit, exactly like the pool path
                faults.point("device.dispatch")
                return mesh.observe_window((
                    # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                    pad_rows_np(b.bases, gm, schema.BASE_PAD, cols=gl),
                    # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                    pad_rows_np(b.quals, gm, schema.QUAL_PAD, cols=gl),
                    pad_rows_np(b.lengths, gm, 0),
                    pad_rows_np(b.flags, gm, schema.FLAG_UNMAPPED),
                    pad_rows_np(b.read_group_idx, gm, -1),
                    pad_rows_np(residue_ok, gm, False, cols=gl),
                    pad_rows_np(is_mm, gm, False, cols=gl),
                    pad_rows_np(read_ok, gm, False),
                ), n_rg, gl)

            # ledger key == the mesh prewarm entry key: an in-window
            # miss here is a mesh prewarm coverage gap
            with compile_ledger.track(
                ("mesh.observe", gm, gl, n_rg), mesh.ledger_key()
            ):
                total, mism = _retry.retry_call(
                    dispatch_mesh, site="bqsr.observe.dispatch"
                )
        else:
            from adam_tpu.parallel.device_pool import donation_ok, putter
            from adam_tpu.utils import compile_ledger, faults
            from adam_tpu.utils import retry as _retry

            _put = putter(device)
            rw = resident
            if rw is not None and not (
                rw.alive and rw.device is device
                and rw.g == g and rw.gl == gl
            ):
                rw = None
            if rw is not None:
                from adam_tpu.ops.colpack import pack_mask_bits

                res_pk = pack_mask_bits(
                    pad_rows_np(residue_ok, g, False, cols=gl)
                )
                mm_pk = pack_mask_bits(
                    pad_rows_np(is_mm, g, False, cols=gl)
                )
                rd_pad = pad_rows_np(read_ok, g, False)

                def dispatch_resident():
                    # ingest-once H2D: the five resident arrays stay
                    # put; only the bit-packed masks + read filter ship
                    # (fresh placements per attempt, so the donating
                    # variant's consumed masks never re-enter a retry)
                    faults.point("device.dispatch", device=device)
                    return jit_variant(
                        "observe_packed", donation_ok(device)
                    )(
                        *rw.args(), _put(res_pk), _put(mm_pk),
                        _put(rd_pad), n_rg, gl,
                    )

                # ledger key == observe_packed_prewarm_entry's key
                with compile_ledger.track(
                    ("bqsr.observe_packed", g, gl, n_rg), device
                ):
                    total, mism = _retry.retry_call(
                        dispatch_resident, site="bqsr.observe.dispatch"
                    )
                rg_names = ds.read_groups.names + ["null"]
                return total, mism, rg_names, gl

            def dispatch():
                # ship + scatter-add as one retryable unit: the commit
                # and the jit dispatch are the RPCs that drop on a
                # tunneled chip, and re-running them is idempotent
                faults.point("device.dispatch", device=device)
                return observe_kernel(
                    # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                    _put(pad_rows_np(b.bases, g, schema.BASE_PAD, cols=gl)),
                    # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                    _put(pad_rows_np(b.quals, g, schema.QUAL_PAD, cols=gl)),
                    _put(pad_rows_np(b.lengths, g, 0)),
                    _put(pad_rows_np(b.flags, g, schema.FLAG_UNMAPPED)),
                    _put(pad_rows_np(b.read_group_idx, g, -1)),
                    _put(pad_rows_np(residue_ok, g, False, cols=gl)),
                    _put(pad_rows_np(is_mm, g, False, cols=gl)),
                    _put(pad_rows_np(read_ok, g, False)),
                    n_rg, gl,
                )

            # ledger key == the prewarm entry key ("bqsr.observe"):
            # an in-window miss here is a prewarm coverage gap
            with compile_ledger.track(
                ("bqsr.observe", g, gl, n_rg), device
            ):
                total, mism = _retry.retry_call(
                    dispatch, site="bqsr.observe.dispatch"
                )
    rg_names = ds.read_groups.names + ["null"]
    # visit accounting (BaseQualityRecalibration.scala:99-123's logging)
    # — host-resident histograms only: summing a device-backend result
    # here would block on the scatter-add and fetch per window,
    # silently defeating the lazy dispatch the device path exists for
    import logging

    log = logging.getLogger(__name__)
    if isinstance(total, np.ndarray) and log.isEnabledFor(logging.INFO):
        n_visited = int(np.asarray(total).sum())
        log.info(
            "BQSR observe: %d reads eligible of %d; %d residues visited, "
            "%d residues filtered",
            int(read_ok.sum()), int(np.asarray(b.valid).sum()),
            n_visited,
            int(read_ok.sum() * b.lmax) - n_visited,
        )
    return total, mism, rg_names, gl


def build_observation_table(
    ds: AlignmentDataset, known_snps: Optional[SnpTable] = None
) -> ObservationTable:
    total, mism, rg_names, lmax = _observe_device(ds, known_snps)
    return ObservationTable(
        device_fetch(total), device_fetch(mism), rg_names, lmax
    )


# --------------------------------------------------------------------------
# Recalibration pass
# --------------------------------------------------------------------------
@jax.jit
def recalibration_phred_table(total, mismatches):
    """Materialize the recalibrated quality for every covariate combination
    -> i32[RG, Q, C, D].

    The log-space delta stack (Recalibrator.scala:79-127) is a pure
    function of the covariate key, so it is evaluated once per *table
    cell* rather than per residue — the device analog of the reference
    building a RecalibrationTable on the driver and applying it as a
    lookup.  With E = empirical error (Bayes (1+mm)/(2+total)) and offsets
    accumulating residue logP + previous deltas, missing entries
    (total==0) contribute delta 0; the per-cycle and per-dinuc deltas
    share the same offset.  All transcendentals live on table shapes
    (~1e6 cells), which keeps the x64 XLA fusion tiny — compiling the old
    per-residue [N, L] f64 log stack took minutes on CPU.
    """
    err = jnp.asarray(PHRED_TO_ERROR)

    def emp_log(t, m):  # ln of bayesian error probability
        return jnp.log((1.0 + m) / (2.0 + t))

    # marginals
    g_t = total.sum(axis=(1, 2, 3))  # [RG]
    g_m = mismatches.sum(axis=(1, 2, 3))
    q_levels = jnp.arange(N_QUAL)
    q_t = total.sum(axis=(2, 3))  # [RG, Q]
    q_m = mismatches.sum(axis=(2, 3))
    g_exp = (err[q_levels][None, :] * q_t).sum(axis=1)  # [RG] expected mismatches
    c_t = total.sum(axis=3)  # [RG, Q, C]
    c_m = mismatches.sum(axis=3)
    d_t = total.sum(axis=2)  # [RG, Q, D]
    d_m = mismatches.sum(axis=2)

    residue_logp = jnp.log(err[q_levels])  # [Q]

    g_present = g_t > 0  # [RG]
    global_delta = jnp.where(
        g_present,
        emp_log(g_t, g_m) - jnp.log(g_exp / jnp.maximum(g_t, 1)),
        0.0,
    )

    q_present = g_present[:, None] & (q_t > 0)  # [RG, Q]
    offset1 = residue_logp[None, :] + global_delta[:, None]  # [RG, Q]
    quality_delta = jnp.where(q_present, emp_log(q_t, q_m) - offset1, 0.0)

    offset2 = offset1 + quality_delta  # [RG, Q]
    cyc_delta = jnp.where(
        q_present[:, :, None] & (c_t > 0),
        emp_log(c_t, c_m) - offset2[:, :, None],
        0.0,
    )
    din_delta = jnp.where(
        q_present[:, :, None] & (d_t > 0),
        emp_log(d_t, d_m) - offset2[:, :, None],
        0.0,
    )

    log_p = (
        offset2[:, :, None, None]
        + cyc_delta[:, :, :, None]
        + din_delta[:, :, None, :]
    )
    max_logp = jnp.log(err[MAX_QUAL])
    bounded = jnp.minimum(0.0, jnp.maximum(max_logp, log_p))
    # QualityScore.fromErrorProbability(exp(boundedLogP)) — shared rounding
    from adam_tpu.ops.phred import error_probability_to_phred

    return error_probability_to_phred(jnp.exp(bounded))


def recalibration_phred_table_np(total, mismatches) -> np.ndarray:
    """Host twin of :func:`recalibration_phred_table` (same f64 math on
    the small table shapes; differential-tested for bit parity)."""
    err = np.asarray(PHRED_TO_ERROR)
    total = np.asarray(total, np.float64)
    mismatches = np.asarray(mismatches, np.float64)

    def emp_log(t, m):
        return np.log((1.0 + m) / (2.0 + t))

    g_t = total.sum(axis=(1, 2, 3))
    g_m = mismatches.sum(axis=(1, 2, 3))
    q_levels = np.arange(N_QUAL)
    q_t = total.sum(axis=(2, 3))
    q_m = mismatches.sum(axis=(2, 3))
    g_exp = (err[q_levels][None, :] * q_t).sum(axis=1)
    c_t = total.sum(axis=3)
    c_m = mismatches.sum(axis=3)
    d_t = total.sum(axis=2)
    d_m = mismatches.sum(axis=2)

    residue_logp = np.log(err[q_levels])
    g_present = g_t > 0
    with np.errstate(divide="ignore", invalid="ignore"):
        global_delta = np.where(
            g_present,
            emp_log(g_t, g_m) - np.log(g_exp / np.maximum(g_t, 1)),
            0.0,
        )
        q_present = g_present[:, None] & (q_t > 0)
        offset1 = residue_logp[None, :] + global_delta[:, None]
        quality_delta = np.where(q_present, emp_log(q_t, q_m) - offset1, 0.0)
        offset2 = offset1 + quality_delta
        cyc_delta = np.where(
            q_present[:, :, None] & (c_t > 0),
            emp_log(c_t, c_m) - offset2[:, :, None],
            0.0,
        )
        din_delta = np.where(
            q_present[:, :, None] & (d_t > 0),
            emp_log(d_t, d_m) - offset2[:, :, None],
            0.0,
        )
    log_p = (
        offset2[:, :, None, None]
        + cyc_delta[:, :, :, None]
        + din_delta[:, :, None, :]
    )
    bounded = np.minimum(0.0, np.maximum(np.log(err[MAX_QUAL]), log_p))
    return np.floor(-10.0 * np.log10(np.exp(bounded)) + 0.5).astype(np.int32)


@partial(jax.jit, static_argnames=("lmax",))
def recalibrate_kernel(
    bases, quals, lengths, flags, read_group_idx, has_qual, valid,
    total, mismatches, lmax: int,
):
    """Apply the recalibration table to every residue -> new quals u8[N, L].

    Per-residue work is a single 4-d table gather keyed on
    (rg, reported qual, cycle, dinuc) plus the apply-mask
    (minAcceptableQuality Q5 floor, BaseQualityRecalibration.scala:50).
    """
    phred_table = recalibration_phred_table(total, mismatches)

    n_rg = total.shape[0]
    # RG-less reads use the dedicated last bin, symmetric with observe
    rg = jnp.where(read_group_idx >= 0, read_group_idx, n_rg - 1).astype(jnp.int32)
    q = jnp.clip(quals.astype(jnp.int32), 0, N_QUAL - 1)
    cycles = compute_cycles(lengths, flags, lmax) + lmax
    dinucs = compute_dinucs(bases, lengths, flags, lmax)

    new_q = phred_table[rg[:, None], q, cycles, dinucs]

    in_read = jnp.arange(lmax)[None, :] < lengths[:, None]
    apply_mask = (
        in_read
        & (quals >= MIN_ACCEPTABLE_QUALITY)
        & (quals < schema.QUAL_PAD)
        & has_qual[:, None]
        & valid[:, None]
    )
    return jnp.where(apply_mask, new_q, quals).astype(jnp.uint8)


def apply_table_body(
    bases, quals, lengths, flags, read_group_idx, has_qual, valid,
    phred_table, lmax: int,
):
    """Traceable body of the table application (shared by the plain
    kernel, the fused apply+pack kernel below, and the mesh shard_map
    bodies — ONE copy of the math, so every path is bitwise the same
    gather)."""
    n_rg = phred_table.shape[0]
    gl = (phred_table.shape[2] - 1) // 2
    rg = jnp.where(read_group_idx >= 0, read_group_idx, n_rg - 1).astype(jnp.int32)
    q = jnp.clip(quals.astype(jnp.int32), 0, N_QUAL - 1)
    cycles = compute_cycles(lengths, flags, lmax) + gl
    dinucs = compute_dinucs(bases, lengths, flags, lmax)
    new_q = phred_table[rg[:, None], q, cycles, dinucs]
    in_read = jnp.arange(lmax)[None, :] < lengths[:, None]
    apply_mask = (
        in_read
        & (quals >= MIN_ACCEPTABLE_QUALITY)
        & (quals < schema.QUAL_PAD)
        & has_qual[:, None]
        & valid[:, None]
    )
    return jnp.where(apply_mask, new_q, quals).astype(jnp.uint8)


@partial(jax.jit, static_argnames=("lmax",))
def apply_table_kernel(
    bases, quals, lengths, flags, read_group_idx, has_qual, valid,
    phred_table, lmax: int,
):
    """Apply a pre-solved u8 recalibration table on device -> u8[N, L].

    The per-residue work is one 4-d gather keyed on (rg, reported qual,
    cycle, dinuc) plus the Q5-floor apply mask — the device half of the
    streamed pipeline's pass C (the table itself was solved at the merge
    barrier).  The table's cycle axis spans [-gl, gl] with
    gl = (n_cyc - 1) // 2 >= lmax, so smaller windows gather from the
    middle of a wider merged table."""
    return apply_table_body(
        bases, quals, lengths, flags, read_group_idx, has_qual, valid,
        phred_table, lmax,
    )


def apply_pack_body(
    bases, quals, lengths, flags, read_group_idx, has_qual, valid,
    phred_table, lmax: int, size: int,
):
    """Traceable fused apply + column pack: the table gather of
    :func:`apply_table_body` followed by the on-device SANGER encode
    and row-prefix pack (:mod:`adam_tpu.ops.colpack`) — the encode-ready
    payload the pass-C fetch ships instead of the [N, L] matrix."""
    from adam_tpu.ops.colpack import pack_rows_body, sanger_body

    new_q = apply_table_body(
        bases, quals, lengths, flags, read_group_idx, has_qual, valid,
        phred_table, lmax,
    )
    pack_lens = jnp.where(
        valid & has_qual, lengths.astype(jnp.int64), 0
    )
    return pack_rows_body(sanger_body(new_q), pack_lens, size)


def apply_pack_kernel(
    bases, quals, lengths, flags, read_group_idx, has_qual, valid,
    phred_table, lmax: int, size: int,
):
    """Jit entry point over :func:`apply_pack_body` (the pool path's
    pass-C dispatch when packed columns are on; the mesh path fuses the
    same body per shard in ``parallel/partitioner``).  ``size`` is the
    window's dense grid area — static per (g, gl), so the packed
    variant adds no compile-cache shapes.  Resolves through
    :func:`jit_variant` so the executable is per kernel backend (the
    pack scatter inside branches Pallas/XLA at trace time)."""
    return jit_variant("apply_pack", False)(
        bases, quals, lengths, flags, read_group_idx, has_qual, valid,
        phred_table, lmax, size,
    )


def apply_pack2_body(
    bases, quals, lengths, flags, read_group_idx, has_qual, valid,
    phred_table, lmax: int, size: int,
):
    """Traceable fused apply + BOTH column packs — the bases half of
    the packed tail (deferred by PR 12 until the window was
    device-resident): with bases already on device from ingest, one
    dispatch gathers the recalibrated quals, SANGER-encodes and packs
    them, AND decodes + packs the base codes, so pass C ships two flat
    encode-ready columns (``sum(lengths)`` bytes each) and the host
    never walks either [N, L] matrix.  Returns
    ``(packed_quals, packed_bases)``, each u8[size]."""
    from adam_tpu.ops.colpack import (
        base_decode_body, pack_rows_body, sanger_body,
    )

    new_q = apply_table_body(
        bases, quals, lengths, flags, read_group_idx, has_qual, valid,
        phred_table, lmax,
    )
    qual_lens = jnp.where(
        valid & has_qual, lengths.astype(jnp.int64), 0
    )
    base_lens = jnp.where(valid, lengths.astype(jnp.int64), 0)
    return (
        pack_rows_body(sanger_body(new_q), qual_lens, size),
        pack_rows_body(base_decode_body(bases), base_lens, size),
    )


def apply_pack2_kernel(
    bases, quals, lengths, flags, read_group_idx, has_qual, valid,
    phred_table, lmax: int, size: int,
):
    """Jit entry point over :func:`apply_pack2_body` (the
    resident-window pass-C dispatch when packed columns are on; the
    donating twin lives in :func:`jit_variant`, as does the per-backend
    executable — the pack scatter branches Pallas/XLA at trace
    time)."""
    return jit_variant("apply_pack2", False)(
        bases, quals, lengths, flags, read_group_idx, has_qual, valid,
        phred_table, lmax, size,
    )


def fused_bc_body(
    bases, quals, lengths, flags, read_group_idx,
    res_bits, mm_bits, read_ok, has_qual, valid,
    phred_table, n_rg: int, lmax: int, size: int,
):
    """Traceable fused pass B→C — the megakernel tier's body.

    When the solved recalibration table is already known at dispatch
    time (known-sites runs; discovered-table resumes re-observing for
    the observation dump), the observe scatter-add and the fused
    apply+pack compose into ONE executable over the window's resident
    arrays: the window's histograms AND both flat encode-ready columns
    come out of a single dispatch, and the barrier-2 host round-trip
    (fetch table → re-dispatch apply) disappears from the per-window
    path.  Functionally pure composition of
    :func:`observe_packed_body` (which sees the ORIGINAL quals — same
    as the unfused ordering) and :func:`apply_pack2_body`, so the
    outputs are bitwise the separate passes' outputs.

    Returns ``(total, mism, packed_quals, packed_bases)``."""
    total, mism = observe_packed_body(
        bases, quals, lengths, flags, read_group_idx,
        res_bits, mm_bits, read_ok, n_rg, lmax,
    )
    pq, pb = apply_pack2_body(
        bases, quals, lengths, flags, read_group_idx, has_qual, valid,
        phred_table, lmax, size,
    )
    return total, mism, pq, pb


def fused_bc_kernel(
    bases, quals, lengths, flags, read_group_idx,
    res_bits, mm_bits, read_ok, has_qual, valid,
    phred_table, n_rg: int, lmax: int, size: int,
):
    """Jit entry point over :func:`fused_bc_body` (the donating twin —
    resident bases/quals become the packed columns, the mask
    temporaries are consumed — lives in :func:`jit_variant`, keyed per
    kernel backend like every other variant)."""
    return jit_variant("fused_bc", False)(
        bases, quals, lengths, flags, read_group_idx,
        res_bits, mm_bits, read_ok, has_qual, valid,
        phred_table, n_rg, lmax, size,
    )


def merge_observations(parts: list[tuple], replays=None,
                       tracer=None, window_ids=None,
                       on_part=None) -> tuple:
    """Sum per-window (total, mism, gl) histograms into one global
    (total, mism, gl) — the host-side analog of the sharded psum.

    Cycle slots are centered (index = cycle + gl, table width 2*gl+1),
    so windows with smaller lmax pad into the middle of the widest
    window's table.  Device-resident parts (the lazy ``device`` observe
    backend) are fetched here, at the barrier, via the chunked transfer
    helper — each is a compact [n_rg, 94, 2g+1, 17] table, never [N, L].
    Each device-resident fetch records one ``device.fetch.observe``
    span (``device=<k>`` + ``window=<i>`` attributed) on ``tracer``
    (default: the global TRACE), so whether the n per-window fetches
    serialize on the host thread at barrier 2 — the ROADMAP
    "observe-fetch serialization" item — is directly measurable from a
    trace instead of inferred from the barrier wall.

    ``replays``: optional per-part recovery hooks (parallel list; None
    entries = no hook).  When a part's fetch still fails after the
    transfer layer's retry budget, ``replays[k](exc)`` must return a
    replacement host-resident ``(total, mism, g)`` — the streamed
    pipeline's hook evicts the failed device and recomputes the window
    on a survivor or the host backend, so a dead chip costs one window
    replay instead of the whole run.

    ``window_ids``: optional parallel list of true window indices for
    the span attribution — residual windows drop out of ``parts``, so
    the part position ``k`` is NOT the window index whenever any
    window had zero valid rows.  A ``None`` entry marks a part with no
    single source window (the mesh partitioner's fetched accumulator
    sums many windows): ``on_part`` is skipped for it — a multi-window
    histogram must never persist as one window's sidecar.

    ``on_part``: optional ``on_part(window, total, mism, g)`` callback
    invoked with each part's HOST-resident histogram as it merges
    (after any replay) — the streamed run journal persists its durable
    observe sidecars here, at the barrier, the one point where every
    histogram crosses to the host anyway.
    """
    from adam_tpu.parallel.device_pool import span_attrs
    from adam_tpu.utils.transfer import _resident_device, device_fetch

    tr = tracer if tracer is not None else _tele.TRACE
    gl = max(p[2] for p in parts)
    n_cyc = 2 * gl + 1
    s0 = parts[0][0].shape  # .shape is metadata — no transfer
    shape = (s0[0], s0[1], n_cyc, s0[3])
    total = np.zeros(shape, np.int64)
    mism = np.zeros(shape, np.int64)
    for k, (t, m, g) in enumerate(parts):
        try:
            if isinstance(t, np.ndarray):
                # host-resident part (host backend or a replayed
                # window): nothing crosses the device link — no span,
                # or the "fetch" attribution would count memcpys
                tt = device_fetch(t)
                mm = device_fetch(m)
            else:
                attrs = span_attrs(_resident_device(t))
                win = window_ids[k] if window_ids is not None else k
                with tr.span(_tele.SPAN_OBS_FETCH, window=win, **attrs):
                    tt = device_fetch(t)
                    mm = device_fetch(m)
        except Exception as e:
            replay = replays[k] if replays is not None else None
            if replay is None:
                raise
            tt, mm, g = replay(e)
            tt = np.asarray(tt)
            mm = np.asarray(mm)
        win_id = window_ids[k] if window_ids is not None else k
        if on_part is not None and win_id is not None:
            on_part(win_id, tt, mm, g)
        off = gl - g
        total[:, :, off : off + 2 * g + 1, :] += tt
        mism[:, :, off : off + 2 * g + 1, :] += mm
    return total, mism, gl


def solve_recalibration_table(total, mism) -> np.ndarray:
    """Observation histograms -> compact u8 phred table (the global
    barrier step between the observe and apply passes)."""
    if isinstance(total, np.ndarray):
        return recalibration_phred_table_np(total, mism).astype(np.uint8)
    # adam-tpu: noqa[dispatch-ledger] reason=once-per-run barrier solve on table shapes; a ledger key would demand a solved-width prewarm entry before the solve exists (ROADMAP device-resident windows item)
    tbl = recalibration_phred_table(total, mism)
    return device_fetch(tbl.astype(jnp.uint8))


def dump_observation_csv(total, mism, rg_names, lmax, path) -> None:
    """Write the merged observation histogram as the reference's
    ObservationTable CSV (shared by the monolithic, streamed and sharded
    drivers so the format lives in one place)."""
    obs = ObservationTable(np.asarray(total), np.asarray(mism), rg_names, lmax)
    with open(path, "w") as fh:
        fh.write(obs.to_csv())


def recalibrate_base_qualities(
    ds: AlignmentDataset,
    known_snps: Optional[SnpTable] = None,
    dump_observation_table: Optional[str] = None,
    backend: Optional[str] = None,
) -> AlignmentDataset:
    total, mism, rg_names, lmax = _observe_device(ds, known_snps, backend)
    if dump_observation_table:
        dump_observation_csv(
            device_fetch(total), device_fetch(mism), rg_names, lmax,
            dump_observation_table,
        )
    # the delta-stack table is built from the psum-able histograms, but
    # the *solved* table is compact (n_rg x 94 x cycles x 17, ~4 MB) —
    # table math runs wherever the histograms live: host arrays (the
    # native-observe path) stay host; device arrays use the device
    # kernel and fetch only the tiny u8 table
    phred_table = solve_recalibration_table(total, mism)
    return apply_recalibration(ds, phred_table, lmax, backend)


def apply_recalibration_dispatch(
    ds: AlignmentDataset, phred_table: np.ndarray, gl: int,
    backend: Optional[str] = None, device=None, mesh=None,
    pack: bool = False, resident=None,
):
    """Start the per-residue table application for one window -> opaque
    handle for :func:`apply_recalibration_finish`.

    On the ``device`` backend this ships the window's [N, L] bases/quals
    and *dispatches* the gather kernel without blocking — the streamed
    pipeline double-buffers: window i's result is fetched (and its part
    encoded) while window i+1's gather runs on the chip.  ``device``
    commits the inputs to an explicit chip (multi-chip round-robin);
    ``phred_table`` may be a device-resident array (the pool replicates
    the solved table once per device instead of re-shipping it per
    window; under ``mesh`` it is the replicated placement from
    ``MeshPartitioner.put_replicated`` — placed once, resident for the
    whole pass).  The other backends compute eagerly and the handle is
    just the result.

    ``pack=True`` (device/mesh backends only) dispatches the fused
    apply+pack kernel instead: the handle's payload is the window's
    flat SANGER-encoded qual column (``ops/colpack``), fetched by
    :func:`apply_recalibration_finish_packed` as ``sum(lengths)``
    bytes — the pass-C d2h fetch ships the encode-ready column, never
    the [N, L] matrix.

    ``resident`` (a ``device_pool.ResidentWindow``) dispatches off the
    window's ingest-resident arrays — only the post-split ``has_qual``/
    ``valid`` bools ship — and with ``pack=True`` upgrades to the fused
    bases+quals pack (``apply_pack2_kernel``): BOTH flat encode-ready
    columns come home and the handle finishes as
    ``(ds, io.arrow_pack.PackedColumns)``.  Where the runtime honors
    donation the resident quals/bases buffers become the packed
    outputs.  A dead handle falls back to the non-resident dispatch,
    byte-identically."""
    backend = bqsr_backend(backend)
    from adam_tpu.parallel.device_pool import span_attrs

    attrs = {"device": "mesh"} if mesh is not None else span_attrs(device)
    with _tele.TRACE.span(
        _tele.SPAN_BQSR_APPLY_DISPATCH, backend=backend, **attrs,
    ):
        return _apply_dispatch_impl(
            ds, phred_table, gl, backend, device, mesh, pack, resident
        )


def _apply_pack_lens(b) -> np.ndarray:
    """Host copy of the fused kernel's per-row packed byte counts (the
    offsets side of the Arrow layout — derived here, never fetched)."""
    from adam_tpu.ops.colpack import pack_lengths

    return pack_lengths(b.lengths, b.valid, b.has_qual)


def _apply_pack_lens_bases(b) -> np.ndarray:
    """Per-row packed byte counts for the bases column (every valid row
    carries its sequence, qual-less or not)."""
    from adam_tpu.ops.colpack import pack_lengths

    return pack_lengths(b.lengths, b.valid)


def fused_bc_enabled(default: bool = True) -> bool:
    """Resolve the ``ADAM_TPU_FUSED_BC`` toggle for the megakernel
    tier: ``auto``/unset -> ``default`` (on wherever a window is
    eligible), ``1/on/true`` and ``0/off/false`` force; a typo warns
    and keeps the default (``utils/retry.env_toggle``, the shared
    tuning-var contract).  The off position is the smoke harness's
    unfused A/B leg."""
    from adam_tpu.utils.retry import env_toggle

    return env_toggle("ADAM_TPU_FUSED_BC", default)


def fused_bc_dispatch(
    ds: AlignmentDataset, phred_table: np.ndarray,
    known_snps: Optional[SnpTable] = None, backend: Optional[str] = None,
    device=None, mesh=None, resident=None,
):
    """One fused B→C dispatch for a window whose recalibration table is
    already solved (known-sites runs; discovered-table resumes that
    re-observe for the dump) -> ``(handle, (total, mism, rg_names,
    gl))``, or ``None`` when the fused tier can't take this window.

    The handle is exactly :func:`apply_recalibration_dispatch`'s
    ``packed2`` shape (finished by
    :func:`apply_recalibration_finish_packed`); the histograms are the
    lazy device arrays the observe path would have produced — both out
    of ONE donated executable over the window's ingest-resident
    arrays, so the separate observe dispatch, the barrier-2 apply
    re-dispatch and the round-trip between them all collapse.

    Eligibility: device backend, a live matching ``ResidentWindow``
    handle, and a table at least as wide as the window's column grid
    (``n_cyc >= 2*gl+1`` — the merged table always is for tables
    discovered from the same input).  Anything else returns ``None``
    and the caller falls back to the separate-pass path, which is
    bitwise identical by construction (:func:`fused_bc_body` is a pure
    composition of the two pass bodies)."""
    backend = bqsr_backend(backend)
    if backend != "device" or resident is None:
        return None
    from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np
    from adam_tpu.ops.colpack import fetch_grid, pack_mask_bits
    from adam_tpu.parallel.device_pool import (
        donation_ok, putter, span_attrs,
    )
    from adam_tpu.utils import compile_ledger, faults
    from adam_tpu.utils import retry as _retry

    b = ds.batch.to_numpy()
    n = b.n_rows
    L = b.lmax
    g = grid_rows(n)
    glc = grid_cols(L)
    n_rg = len(ds.read_groups) + 1
    if phred_table.shape[0] != n_rg or phred_table.shape[2] < 2 * glc + 1:
        return None
    n_cyc = phred_table.shape[2]
    rw = resident
    rg_names = ds.read_groups.names + ["null"]

    attrs = {"device": "mesh"} if mesh is not None else span_attrs(device)
    with _tele.TRACE.span(
        _tele.SPAN_FUSED_BC, backend=backend,
        reads=int(ds.batch.n_rows), **attrs,
    ):
        is_mm, _, has_md = batch_md_arrays(
            ds.batch, ds.sidecar, need_ref_codes=False
        )
        read_ok = observe_read_mask(b, has_md)
        residue_ok = observe_residue_mask(ds, b, known_snps)
        pack_lens_q = _apply_pack_lens(b)
        pack_lens_b = _apply_pack_lens_bases(b)

        if mesh is not None:
            gm = mesh.rows_for(g)
            if not (rw.alive and rw.device == "mesh"
                    and rw.g == gm and rw.gl == glc):
                return None
            res_pk = pack_mask_bits(
                pad_rows_np(residue_ok, gm, False, cols=glc)
            )
            mm_pk = pack_mask_bits(pad_rows_np(is_mm, gm, False, cols=glc))
            rd_pad = pad_rows_np(read_ok, gm, False)
            hq_pad = pad_rows_np(b.has_qual, gm, False)
            vd_pad = pad_rows_np(b.valid, gm, False)
            lens_q_pad = pad_rows_np(pack_lens_q, gm, 0)
            lens_b_pad = pad_rows_np(pack_lens_b, gm, 0)

            def dispatch_mesh_fused():
                faults.point("device.dispatch")
                if not rw.alive:
                    # donated shards died under a half-run attempt:
                    # the caller re-runs the separate passes host-ship
                    return None
                try:
                    total, mism, pq, pb = mesh.fused_bc_window(
                        rw, res_pk, mm_pk, rd_pad, hq_pad, vd_pad,
                        phred_table, n_rg, glc,
                    )
                except BaseException:
                    if mesh.apply_supports_donation():
                        rw.mark_consumed()
                    raise
                if mesh.apply_supports_donation():
                    rw.mark_consumed()
                return total, mism, (
                    mesh.packed_payload_slices(pq, lens_q_pad, glc),
                    mesh.packed_payload_slices(pb, lens_b_pad, glc),
                )

            with compile_ledger.track(
                ("mesh.fused_bc", gm, glc, n_rg, n_cyc),
                mesh.ledger_key(),
            ):
                got = _retry.retry_call(
                    dispatch_mesh_fused, site="bqsr.fused_bc.dispatch"
                )
            if got is None:
                return None
            total, mism, (q_slices, b_slices) = got
            handle = (ds, b, ("packed2", q_slices, pack_lens_q,
                              b_slices, pack_lens_b))
            return handle, (total, mism, rg_names, glc)

        if not (rw.alive and rw.device is device
                and rw.g == g and rw.gl == glc):
            return None
        _put = putter(device)
        res_pk = pack_mask_bits(pad_rows_np(residue_ok, g, False, cols=glc))
        mm_pk = pack_mask_bits(pad_rows_np(is_mm, g, False, cols=glc))
        rd_pad = pad_rows_np(read_ok, g, False)
        hq_pad = pad_rows_np(b.has_qual, g, False)
        vd_pad = pad_rows_np(b.valid, g, False)
        total_q = int(pack_lens_q.sum())
        total_b = int(pack_lens_b.sum())
        cut_q = min(g * glc, fetch_grid(total_q))
        cut_b = min(g * glc, fetch_grid(total_b))

        def _placed_table():
            if isinstance(phred_table, np.ndarray):
                return _put(np.ascontiguousarray(phred_table, np.uint8))
            return phred_table  # device-resident (pool-replicated)

        def dispatch_fused():
            faults.point("device.dispatch", device=device)
            if not rw.alive:
                return None
            donate = donation_ok(device)
            try:
                total, mism, pq, pb = jit_variant("fused_bc", donate)(
                    *rw.args(), _put(res_pk), _put(mm_pk), _put(rd_pad),
                    _put(hq_pad), _put(vd_pad), _placed_table(),
                    n_rg, glc, g * glc,
                )
            except BaseException:
                if donate:
                    rw.mark_consumed()
                raise
            if donate:
                rw.mark_consumed()
            return total, mism, pq[:cut_q], pb[:cut_b]

        # ledger key == fused_bc_prewarm_entry's key
        with compile_ledger.track(
            ("bqsr.fused_bc", g, glc, n_rg, n_cyc), device
        ):
            got = _retry.retry_call(
                dispatch_fused, site="bqsr.fused_bc.dispatch"
            )
        if got is None:
            return None
        total, mism, pq, pb = got
        handle = (ds, b, ("packed2", [(pq, total_q)], pack_lens_q,
                          [(pb, total_b)], pack_lens_b))
        return handle, (total, mism, rg_names, glc)


def _apply_dispatch_impl(
    ds: AlignmentDataset, phred_table, gl: int, backend: str, device=None,
    mesh=None, pack: bool = False, resident=None,
):
    b = ds.batch.to_numpy()
    if backend == "device" and mesh is not None:
        from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np
        from adam_tpu.utils import compile_ledger, faults
        from adam_tpu.utils import retry as _retry

        n = b.n_rows
        L = b.lmax
        gm = mesh.rows_for(grid_rows(n))
        glc = grid_cols(L)
        n_rg = phred_table.shape[0]
        n_cyc = phred_table.shape[2]
        rw = resident
        if rw is not None and not (
            rw.alive and rw.device == "mesh"
            and rw.g == gm and rw.gl == glc
        ):
            rw = None
        if rw is not None:
            hq_pad = pad_rows_np(b.has_qual, gm, False)
            vd_pad = pad_rows_np(b.valid, gm, False)
            if pack:
                # the bases half: both flat columns come home, each
                # split into per-shard exact payload slices
                pack_lens_q = _apply_pack_lens(b)
                pack_lens_b = _apply_pack_lens_bases(b)
                lens_q_pad = pad_rows_np(pack_lens_q, gm, 0)
                lens_b_pad = pad_rows_np(pack_lens_b, gm, 0)

                def dispatch_mesh_pack2():
                    faults.point("device.dispatch")
                    if not rw.alive:
                        # donated buffers died under a half-run attempt:
                        # re-ship the quals-only pack from the host copy
                        return None
                    try:
                        pq, pb = mesh.apply_pack2_window(
                            rw, hq_pad, vd_pad, phred_table, glc
                        )
                    except BaseException:
                        if mesh.apply_supports_donation():
                            # the donating collective may have consumed
                            # the resident shards mid-failure: the
                            # handle must never offer them again
                            rw.mark_consumed()
                        raise
                    if mesh.apply_supports_donation():
                        rw.mark_consumed()
                    return (
                        mesh.packed_payload_slices(pq, lens_q_pad, glc),
                        mesh.packed_payload_slices(pb, lens_b_pad, glc),
                    )

                with compile_ledger.track(
                    ("mesh.apply_pack2", gm, glc, n_rg, n_cyc),
                    mesh.ledger_key(),
                ):
                    got = _retry.retry_call(
                        dispatch_mesh_pack2, site="bqsr.apply.dispatch"
                    )
                if got is not None:
                    q_slices, b_slices = got
                    return ds, b, ("packed2", q_slices, pack_lens_q,
                                   b_slices, pack_lens_b)
                rw = None  # handle died: fall through to the re-ship
            else:
                def dispatch_mesh_resident():
                    faults.point("device.dispatch")
                    if not rw.alive:
                        return None
                    return mesh.apply_window_resident(
                        rw, hq_pad, vd_pad, phred_table, glc
                    )[:n, :L]

                with compile_ledger.track(
                    ("mesh.apply", gm, glc, n_rg, n_cyc),
                    mesh.ledger_key(),
                ):
                    new_dev = _retry.retry_call(
                        dispatch_mesh_resident, site="bqsr.apply.dispatch"
                    )
                if new_dev is not None:
                    return ds, b, new_dev
                rw = None
        args = (
            # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
            pad_rows_np(b.bases, gm, schema.BASE_PAD, cols=glc),
            # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
            pad_rows_np(b.quals, gm, schema.QUAL_PAD, cols=glc),
            pad_rows_np(b.lengths, gm, 0),
            pad_rows_np(b.flags, gm, schema.FLAG_UNMAPPED),
            pad_rows_np(b.read_group_idx, gm, -1),
            pad_rows_np(b.has_qual, gm, False),
            pad_rows_np(b.valid, gm, False),
        )
        if pack:
            pack_lens = _apply_pack_lens(b)

            def dispatch_mesh_pack():
                faults.point("device.dispatch")
                packed = mesh.apply_pack_window(args, phred_table, glc)
                # per-shard exact payload slices: shard k's segment of
                # the flat output holds exactly its rows' packed bytes
                # at the segment start (host-known lengths -> host-known
                # split; nothing but real column bytes ever fetches)
                return mesh.packed_payload_slices(
                    packed, pad_rows_np(pack_lens, gm, 0), glc
                )

            with compile_ledger.track(
                ("mesh.apply_pack", gm, glc, n_rg, n_cyc),
                mesh.ledger_key(),
            ):
                slices = _retry.retry_call(
                    dispatch_mesh_pack, site="bqsr.apply.dispatch"
                )
            return ds, b, ("packed", slices, pack_lens)

        def dispatch_mesh():
            faults.point("device.dispatch")
            return mesh.apply_window(args, phred_table, glc)[:n, :L]

        with compile_ledger.track(
            ("mesh.apply", gm, glc, n_rg, n_cyc), mesh.ledger_key()
        ):
            new_dev = _retry.retry_call(
                dispatch_mesh, site="bqsr.apply.dispatch"
            )
        return ds, b, new_dev
    if backend == "device":
        from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np

        n = b.n_rows
        L = b.lmax
        g = grid_rows(n)
        glc = grid_cols(L)
        from adam_tpu.parallel.device_pool import donation_ok, putter
        from adam_tpu.utils import compile_ledger, faults
        from adam_tpu.utils import retry as _retry

        _put = putter(device)
        n_rg = phred_table.shape[0]
        n_cyc = phred_table.shape[2]

        def _placed_table():
            if isinstance(phred_table, np.ndarray):
                return _put(np.ascontiguousarray(phred_table, np.uint8))
            return phred_table  # device-resident (pool-replicated)

        rw = resident
        if rw is not None and not (
            rw.alive and rw.device is device and rw.g == g and rw.gl == glc
        ):
            rw = None
        if rw is not None:
            from adam_tpu.ops.colpack import fetch_grid

            hq_pad = pad_rows_np(b.has_qual, g, False)
            vd_pad = pad_rows_np(b.valid, g, False)
            if pack:
                # the bases half of the packed tail: one fused dispatch
                # emits BOTH flat encode-ready columns off the resident
                # arrays; the fetch ships sum(lengths) bytes each
                pack_lens_q = _apply_pack_lens(b)
                pack_lens_b = _apply_pack_lens_bases(b)
                total_q = int(pack_lens_q.sum())
                total_b = int(pack_lens_b.sum())
                cut_q = min(g * glc, fetch_grid(total_q))
                cut_b = min(g * glc, fetch_grid(total_b))

                def dispatch_pack2():
                    faults.point("device.dispatch", device=device)
                    if not rw.alive:
                        # donated buffers died under a half-run
                        # attempt: re-ship through the fallback below
                        return None
                    donate = donation_ok(device)
                    try:
                        pq, pb = jit_variant("apply_pack2", donate)(
                            *rw.args(), _put(hq_pad), _put(vd_pad),
                            _placed_table(), glc, g * glc,
                        )
                    except BaseException:
                        if donate:
                            rw.mark_consumed()
                        raise
                    if donate:
                        rw.mark_consumed()
                    return pq[:cut_q], pb[:cut_b]

                # ledger key == _apply_entry's resident pack2 key
                with compile_ledger.track(
                    ("bqsr.apply_pack2", g, glc, n_rg, n_cyc), device
                ):
                    got = _retry.retry_call(
                        dispatch_pack2, site="bqsr.apply.dispatch"
                    )
                if got is not None:
                    return ds, b, (
                        "packed2", [(got[0], total_q)], pack_lens_q,
                        [(got[1], total_b)], pack_lens_b,
                    )
                rw = None  # handle died: fall through to the re-ship
            else:
                def dispatch_resident():
                    faults.point("device.dispatch", device=device)
                    if not rw.alive:
                        return None
                    donate = donation_ok(device)
                    try:
                        out = jit_variant("apply", donate)(
                            *rw.args(), _put(hq_pad), _put(vd_pad),
                            _placed_table(), glc,
                        )
                    except BaseException:
                        if donate:
                            rw.mark_consumed()
                        raise
                    if donate:
                        rw.mark_consumed()
                    return out[:n, :L]

                with compile_ledger.track(
                    ("bqsr.apply", g, glc, n_rg, n_cyc), device
                ):
                    new_dev = _retry.retry_call(
                        dispatch_resident, site="bqsr.apply.dispatch"
                    )
                if new_dev is not None:
                    return ds, b, new_dev
                rw = None

        def _placed_args():
            return (
                # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                _put(pad_rows_np(b.bases, g, schema.BASE_PAD, cols=glc)),
                # adam-tpu: noqa[residency] reason=non-resident fallback: residency off, a dead handle, or a replay re-ships from the host ingest copy
                _put(pad_rows_np(b.quals, g, schema.QUAL_PAD, cols=glc)),
                _put(pad_rows_np(b.lengths, g, 0)),
                _put(pad_rows_np(b.flags, g, schema.FLAG_UNMAPPED)),
                _put(pad_rows_np(b.read_group_idx, g, -1)),
                _put(pad_rows_np(b.has_qual, g, False)),
                _put(pad_rows_np(b.valid, g, False)),
                _placed_table(),
            )

        if pack:
            from adam_tpu.ops.colpack import fetch_grid

            pack_lens = _apply_pack_lens(b)
            total = int(pack_lens.sum())
            # bucketed device-side slice (over-fetch < 6.25%, host
            # trims): an exact per-window size would compile one slice
            # program per window
            cut = min(g * glc, fetch_grid(total))

            def dispatch_pack():
                faults.point("device.dispatch", device=device)
                packed = apply_pack_kernel(*_placed_args(), glc, g * glc)
                return packed[:cut]

            # ledger key == apply_pack_prewarm_entry's key (the pass-C
            # re-warm compiles the fused kernel at the solved width)
            with compile_ledger.track(
                ("bqsr.apply_pack", g, glc, n_rg, n_cyc), device
            ):
                packed_dev = _retry.retry_call(
                    dispatch_pack, site="bqsr.apply.dispatch"
                )
            return ds, b, ("packed", [(packed_dev, total)], pack_lens)

        def dispatch():
            faults.point("device.dispatch", device=device)
            return apply_table_kernel(
                *_placed_args(), glc,
            )[:n, :L]  # device-side slice: fetch only real rows/lanes

        # ledger key == the prewarm/apply_prewarm_entry key: the pass-C
        # re-warm compiles against the SOLVED table's width, and an
        # in-window miss here is exactly the "wider merged table"
        # coverage gap PERF.md describes
        with compile_ledger.track(
            ("bqsr.apply", g, glc, n_rg, n_cyc), device
        ):
            new_dev = _retry.retry_call(
                dispatch, site="bqsr.apply.dispatch"
            )
        return ds, b, new_dev
    from adam_tpu import native

    new_quals = None
    if backend == "native":
        new_quals = native.bqsr_apply(
            b.bases, np.asarray(b.quals), b.lengths, b.flags,
            b.read_group_idx, b.has_qual, b.valid, phred_table, gl,
        )
    if new_quals is None:
        new_quals = _apply_table_np(b, phred_table, gl)
    return ds, b, new_quals


def apply_handle_dataset(handle) -> AlignmentDataset:
    """The pre-recalibration dataset inside a dispatch handle — what a
    recovery path re-dispatches when the handle's device died before
    :func:`apply_recalibration_finish` could fetch it."""
    return handle[0]


def _handle_is_packed(handle) -> bool:
    payload = handle[2]
    return isinstance(payload, tuple) and payload[0] in (
        "packed", "packed2"
    )


def apply_recalibration_finish(handle) -> AlignmentDataset:
    """Fetch a dispatched window (chunked transfer for device results)
    and finish the host half: stash pre-recalibration quals as OQ."""
    from adam_tpu.utils.transfer import device_fetch

    if _handle_is_packed(handle):
        return apply_recalibration_finish_packed(handle)[0]
    ds, b, new_quals = handle
    with _tele.TRACE.span(_tele.SPAN_BQSR_APPLY_FETCH):
        new_quals = device_fetch(new_quals)
    return _stash_orig_quals(ds, b, new_quals)


def apply_recalibration_finish_packed(handle):
    """Finish one dispatched window -> ``(dataset, PackedQuals | None)``.

    A ``pack=True`` handle fetches the flat encode-ready qual payload —
    ``sum(lengths)`` bytes, one slice per resident shard — and returns
    it beside the dataset (whose batch keeps its PRE-recalibration
    quals: the OQ stash is the only remaining consumer of the matrix,
    and the writer encodes the qual column straight off the packed
    buffer).  A resident-window ``packed2`` handle additionally fetches
    the flat base column (the bases half of the packed tail) and
    returns a :class:`~adam_tpu.io.arrow_pack.PackedColumns` carrying
    both.  A plain handle behaves exactly like
    :func:`apply_recalibration_finish` and returns ``packed=None``."""
    from adam_tpu.io.arrow_pack import PackedColumns, PackedQuals
    from adam_tpu.utils.transfer import device_fetch

    if not _handle_is_packed(handle):
        return apply_recalibration_finish(handle), None

    def _fetch_col(slices, pack_lens):
        # each slice is bucket-quantized (colpack.fetch_grid) so slice
        # programs stay few; the true payload size rides alongside and
        # the host trims the bucket tail here
        parts = [
            np.asarray(device_fetch(s))[:t] for s, t in slices
        ]
        if len(parts) == 1:
            buf = parts[0]
        elif parts:
            buf = np.concatenate(parts)
        else:  # every row column-less: a valid, all-null column
            buf = np.zeros(0, np.uint8)
        return PackedQuals(buf, pack_lens)

    payload = handle[2]
    if payload[0] == "packed2":
        ds, b, (_tag, q_slices, q_lens, b_slices, b_lens) = handle
        with _tele.TRACE.span(_tele.SPAN_BQSR_APPLY_FETCH):
            packed = PackedColumns(
                quals=_fetch_col(q_slices, q_lens),
                bases=_fetch_col(b_slices, b_lens),
            )
        return _stash_orig_quals(ds, b), packed
    ds, b, (_tag, slices, pack_lens) = handle
    with _tele.TRACE.span(_tele.SPAN_BQSR_APPLY_FETCH):
        packed_q = _fetch_col(slices, pack_lens)
    return _stash_orig_quals(ds, b), packed_q


def apply_recalibration(
    ds: AlignmentDataset, phred_table: np.ndarray, gl: int,
    backend: Optional[str] = None,
) -> AlignmentDataset:
    """Apply a solved recalibration table to one batch/window (the
    Recalibrator.scala:28-60 pass): gather new quals from the compact
    table, stash originals as OQ.  ``gl`` is the table's grid-aligned
    lane count (cycle slots span [-gl, gl])."""
    with _tele.TRACE.span(
        _tele.SPAN_BQSR_APPLY_HOST, backend=bqsr_backend(backend)
    ):
        return apply_recalibration_finish(
            apply_recalibration_dispatch(ds, phred_table, gl, backend)
        )


def _apply_table_np(b, phred_table: np.ndarray, gl: int) -> np.ndarray:
    """Numpy twin of the table application (the ``numpy`` backend and
    the native-unavailable fallback)."""
    n_rg = phred_table.shape[0]
    n_cyc = phred_table.shape[2]
    L = b.lmax
    quals = np.asarray(b.quals)
    rg = np.where(
        np.asarray(b.read_group_idx) >= 0, np.asarray(b.read_group_idx),
        n_rg - 1,
    ).astype(np.int32)
    # fused i32 flat index into the raveled table: one gather,
    # minimal [N, L] temporaries
    idx = compute_cycles_np(b.lengths, b.flags, L)
    idx += gl
    q32 = np.minimum(quals, N_QUAL - 1).astype(np.int32)
    q32 += rg[:, None] * N_QUAL
    q32 *= n_cyc
    idx += q32
    del q32
    idx *= N_DINUC
    idx += compute_dinucs_np(b.bases, b.lengths, b.flags, L)
    new_q = phred_table.ravel()[idx]
    del idx
    in_read = np.arange(L)[None, :] < np.asarray(b.lengths)[:, None]
    apply_mask = (
        in_read
        & (quals >= MIN_ACCEPTABLE_QUALITY)
        & (quals < schema.QUAL_PAD)
        & np.asarray(b.has_qual)[:, None]
        & np.asarray(b.valid)[:, None]
    )
    return np.where(apply_mask, new_q, quals).astype(np.uint8)


def _stash_orig_quals(
    ds: AlignmentDataset, b, new_quals: np.ndarray | None = None
) -> AlignmentDataset:
    """Install recalibrated quals and stash the pre-recalibration matrix
    as OQ (setOrigQual, Recalibrator.scala:36-40) — vectorized: encode
    the old qual matrix as a string column and merge it into rows that
    had no OQ yet.  ``new_quals=None`` (the packed pass-C path) stashes
    OQ only and keeps the batch's quals untouched: the recalibrated
    column travels as the packed payload, never as a matrix."""
    from dataclasses import replace as dc_replace

    from adam_tpu import native
    from adam_tpu.formats.strings import StringColumn

    side = ds.sidecar
    old_oq = StringColumn.of(side.orig_quals)
    set_mask = (
        np.asarray(b.valid) & np.asarray(b.has_qual) & ~old_oq.valid
    )
    stash_lens = np.where(set_mask, np.asarray(b.lengths), 0)
    nat = native.lut_compact_rows(
        np.asarray(b.quals), stash_lens, schema.QUAL_SANGER_LUT256
    )
    if nat is not None:
        # fused LUT+compact pass — no [N, L] ASCII temporary (in-read
        # quals are <= 93, so the clamp in the LUT never fires on them)
        stashed = StringColumn(nat[0], nat[1], set_mask.copy())
    else:
        qmat = (np.asarray(b.quals) + schema.SANGER_OFFSET).astype(np.uint8)
        stashed = StringColumn.from_matrix(qmat, stash_lens, set_mask.copy())
    if not old_oq.valid.any():
        merged = stashed  # no pre-existing OQ anywhere: stash wholesale
    else:
        merged = StringColumn.where(set_mask, stashed, old_oq)
    new_side = dc_replace(side, orig_quals=merged)
    if new_quals is None:
        return ds.with_batch(b, new_side)
    return ds.with_batch(
        b.replace(quals=np.asarray(new_quals)), new_side
    )
