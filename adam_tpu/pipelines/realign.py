"""GATK-style local indel realignment.

Faithful semantics of the reference's ``rdd/read/realignment/`` +
``algorithms/consensus/`` packages, re-shaped for TPU:

1. **Target discovery** (RealignmentTargetFinder.scala:99-121,
   IndelRealignmentTarget.scala:108-143): every I/D CIGAR op (length <=
   maxIndelSize) yields a target (variation region, read span); targets
   sort by read span, merge while overlapping (variation hulls), dedupe
   on equal read spans (TreeSet semantics) and drop spans >
   maxTargetSize.  Here target extraction is a vectorized walk over the
   cigar columns.
2. **Read -> target mapping** (RealignIndels.mapToTarget:72-94): the
   reference's recursive set-halving search, including its exact pruning
   rule and the empty-target skew split ``-1 - start/3000``; vectorized
   so all reads binary-search simultaneously.
3. **Per-target realignment** (RealignIndels.realignTargetGroup:235-387):
   rebuild the reference from MD tags, left-normalize single-indel reads,
   take each indel read's alternate consensus (Consensus.scala:25-70),
   sweep every read over every consensus, accept the best consensus when
   the LOD improvement ((old-new)/10) beats the threshold, and rewrite
   start/CIGAR/MD (+10 mapq, OC/OP provenance tags).
4. The O(|reads| x |offsets| x |readLen|) **sweep**
   (sweepReadOverReferenceForQuality:399-417) is the hot loop: here it is
   one batched device kernel — mismatch-quality(b, o) = totalQual(b) -
   match-correlation(b, o), computed as a per-pair one-hot conv
   (MXU-shaped) over all (read, consensus) pairs of all targets at once.
"""

from __future__ import annotations

import logging
import os
import random
from dataclasses import dataclass, replace as dc_replace
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.models.snp_table import IndelTable
from adam_tpu.ops.mdtag import MdTag, parse_cigar

MAX_INDEL_SIZE = 500
MAX_CONSENSUS_NUMBER = 30
LOD_THRESHOLD = 5.0
MAX_TARGET_SIZE = 3000


# --------------------------------------------------------------------------
# CIGAR list helpers (host)
# --------------------------------------------------------------------------
def cigar_to_string(elems: list[tuple[int, str]]) -> str:
    return "".join(f"{n}{op}" for n, op in elems)


def cigar_read_len(elems) -> int:
    return sum(n for n, op in elems if op in "MIS=X")


def cigar_ref_len(elems) -> int:
    return sum(n for n, op in elems if op in "MDN=X")


def cigar_num_alignment_blocks(elems) -> int:
    return sum(1 for _, op in elems if op == "M")


def _cigar_total_len(elems) -> int:
    """Sum of ALL element lengths (RichCigar.getLength — includes D)."""
    return sum(n for n, _ in elems)


def move_cigar_left(elems: list[tuple[int, str]], index: int):
    """RichCigar.moveLeft semantics (rich/RichCigar.scala:140-186):
    trim one base from the element before ``index``, grow (or create, as
    1M) the element after it.  Replicates the reference's slicing,
    including dropping a 4th element when exactly 4 remain after the
    indel context."""
    if index == 0 or len(elems) < 2:
        return list(elems)
    head = list(elems[: index - 1])
    rest = list(elems[index - 1 :])
    trim = rest[0]
    move = rest[1] if len(rest) > 1 else None
    pad = rest[2] if len(rest) > 2 else None
    after_pad = rest[3:] if len(rest) > 4 else []
    out = list(head)
    if trim[0] > 1:
        out.append((trim[0] - 1, trim[1]))
    if move is not None:
        out.append(move)
    if pad is not None:
        out.append((pad[0] + 1, pad[1]))
    else:
        out.append((1, "M"))
    out += after_pad
    return out


def shift_indel(elems, position: int, shifts: int):
    """NormalizationUtils.shiftIndel (:142-153).

    The reference's well-formedness guard only compares total element
    length (RichCigar.isWellFormed:123-125 against the OLD total), so
    once the element before the indel is fully consumed, further moves
    start trimming the indel itself — the total can stay equal while the
    READ span (S+M+I) grows, and the reference then crashes in
    MdTag.moveAlignment on the out-of-range read index (a walk its
    suite never reaches; observed here on WGS-shaped data as an M span
    overrunning the read).  We additionally pin the read span AND the
    reference span, declining the corrupting move instead of
    reproducing the crash: a trimmed deletion changes the read span at
    constant total, while a trimmed insertion keeps both total and read
    span and silently erases the indel into M, growing the reference
    walk (tests: test_shift_indel_declines_read_length_corruption /
    _insertion_erasure)."""

    cur = list(elems)
    total = _cigar_total_len(cur)
    rlen = cigar_read_len(cur)
    reflen = cigar_ref_len(cur)
    while True:
        new = move_cigar_left(cur, position)
        if (
            shifts == 0
            or _cigar_total_len(new) != total
            or cigar_read_len(new) != rlen
            or cigar_ref_len(new) != reflen
        ):
            return cur
        cur = new
        shifts -= 1


def positions_to_shift(variant: str, preceding: str) -> int:
    """NormalizationUtils.numberOfPositionsToShiftIndel (:115-131)."""
    acc = 0
    v, p = variant, preceding
    while p and v and p[-1] == v[-1]:
        v = v[-1] + v[:-1]
        p = p[:-1]
        acc += 1
    return acc


def left_align_indel(seq: str, cigar: list, md: Optional[MdTag]):
    """NormalizationUtils.leftAlignIndel (:35-100): shift the single indel
    left through repeated sequence.  Returns a new cigar list."""
    indel_pos = -1
    indel_len = 0
    read_pos = ref_pos = 0
    is_insert = False
    for pos, (n, op) in enumerate(cigar):
        if op == "I":
            if indel_pos != -1:
                return list(cigar)
            indel_pos, indel_len, is_insert = pos, n, True
        elif op == "D":
            if indel_pos != -1:
                return list(cigar)
            indel_pos, indel_len = pos, n
        else:
            if indel_pos == -1:
                if op in "MIS=X":
                    read_pos += n
                if op in "MDN=X":
                    ref_pos += n
    if indel_pos == -1:
        return list(cigar)
    if is_insert:
        variant = seq[read_pos : read_pos + indel_len]
    else:
        if md is None:
            return list(cigar)
        ref = md.get_reference(seq, cigar_to_string(cigar))
        variant = ref[ref_pos : ref_pos + indel_len]
    preceding = seq[:read_pos]
    shift = positions_to_shift(variant, preceding)
    return shift_indel(cigar, indel_pos, shift)


# --------------------------------------------------------------------------
# Targets
# --------------------------------------------------------------------------
@dataclass
class RealignmentTarget:
    contig_idx: int
    var_start: int  # -1/-1 when no variation
    var_end: int
    range_start: int
    range_end: int

    @property
    def has_variation(self) -> bool:
        return self.var_start >= 0


def extract_indel_event_arrays(
    b, max_indel_size: int = MAX_INDEL_SIZE
) -> np.ndarray:
    """Per-read I/D events as an ``[n_events, 5]`` i64 array of
    (contig_idx, var_start, var_end, range_start, range_end) — no
    per-event Python objects (the WGS-scale hot path; ~13%% of reads
    carry an indel, so object churn here cost seconds per 1M reads).

    Event order matches the object path: column-major over the cigar
    slots, insertions then deletions per column, row-ascending."""
    n, C = b.cigar_ops.shape
    ops = np.asarray(b.cigar_ops)
    lens = np.asarray(b.cigar_lens).astype(np.int64)
    flags = np.asarray(b.flags)
    active = np.asarray(b.valid) & ((flags & schema.FLAG_UNMAPPED) == 0)
    starts = np.asarray(b.start).astype(np.int64)
    ends = np.asarray(b.end).astype(np.int64)
    contigs = np.asarray(b.contig_idx).astype(np.int64)
    # reference position at each cigar slot = start + exclusive cumsum of
    # ref-consuming op lengths
    r_consume = schema.CIGAR_CONSUMES_REF[np.minimum(ops, 15)].astype(np.int64)
    ref_adv = lens * r_consume
    ref_at = starts[:, None] + np.cumsum(ref_adv, axis=1) - ref_adv
    parts = []
    for k in range(C):
        op = ops[:, k]
        ln = lens[:, k]
        for is_ins in (True, False):
            code = schema.CIGAR_I if is_ins else schema.CIGAR_D
            rows = np.flatnonzero(
                active & (op == code) & (ln <= max_indel_size)
            )
            if not len(rows):
                continue
            vs = ref_at[rows, k]
            ve = vs + 1 if is_ins else vs + ln[rows]
            parts.append(np.stack(
                [contigs[rows], vs, ve, starts[rows], ends[rows]], axis=1
            ))
    if not parts:
        return np.zeros((0, 5), np.int64)
    return np.concatenate(parts, axis=0)


def extract_indel_events(
    b, max_indel_size: int = MAX_INDEL_SIZE
) -> list[RealignmentTarget]:
    """Per-read I/D targets (IndelRealignmentTarget.apply) as objects —
    the array form (:func:`extract_indel_event_arrays`) is the hot
    path; this wrapper exists for API/test compatibility."""
    ev = extract_indel_event_arrays(b, max_indel_size)
    return [
        RealignmentTarget(int(c), int(vs), int(ve), int(rs), int(re))
        for c, vs, ve, rs, re in ev.tolist()
    ]


def find_targets(
    ds: AlignmentDataset,
    max_target_size: int = MAX_TARGET_SIZE,
    max_indel_size: int = MAX_INDEL_SIZE,
):
    """Sorted, merged, deduped target list."""
    b = ds.batch.to_numpy()
    events = extract_indel_event_arrays(b, max_indel_size)
    return merge_events(events, ds.seq_dict.names, max_target_size)


def resolve_tuning(
    max_indel_size=None, max_consensus_number=None,
    lod_threshold=None, max_target_size=None,
) -> tuple[int, int, float, int]:
    """None-coalesce the realignment tuning knobs against the module
    defaults (shared by the streamed/sharded/monolithic drivers)."""
    return (
        MAX_INDEL_SIZE if max_indel_size is None else max_indel_size,
        MAX_CONSENSUS_NUMBER if max_consensus_number is None
        else max_consensus_number,
        LOD_THRESHOLD if lod_threshold is None else lod_threshold,
        MAX_TARGET_SIZE if max_target_size is None else max_target_size,
    )


def merge_events(
    events,
    names: list[str],
    max_target_size: int = MAX_TARGET_SIZE,
):
    """Sort + overlap-merge + dedupe per-read indel events into targets
    (the global barrier of the streamed/sharded paths: per-window event
    lists concatenate here, so targets spanning window or shard edges
    merge exactly as in the single-batch path).

    ``events`` is either a list of :class:`RealignmentTarget` or the
    hot-path ``[n, 5]`` i64 array from
    :func:`extract_indel_event_arrays`; the merge itself runs over plain
    tuples either way (no per-event object churn)."""
    if isinstance(events, np.ndarray):
        ev = events
    else:
        if not events:
            return []
        ev = np.array(
            [
                [t.contig_idx, t.var_start, t.var_end,
                 t.range_start, t.range_end]
                for t in events
            ],
            np.int64,
        )
    if not len(ev):
        return []
    # sort by (contig NAME, range_start, range_end) — the reference
    # orders by referenceName string, not index; lexsort is stable like
    # Python's sorted
    rank_of = {nm: i for i, nm in enumerate(sorted(names))}
    rank = np.array([rank_of[nm] for nm in names], np.int64)
    order = np.lexsort((ev[:, 4], ev[:, 3], rank[ev[:, 0]]))
    rows = ev[order].tolist()

    merged: list[list] = []  # [contig, vs, ve, rs, re] (vs=-1: none)
    for c, vs, ve, rs, re in rows:
        if merged:
            m = merged[-1]
            m_var = m[1] >= 0
            t_var = vs >= 0
            # TargetOrdering.overlap: either variation overlaps the
            # other's read span
            if m[0] == c and (
                (m_var and m[2] > rs and re > m[1])
                or (t_var and ve > m[3] and m[4] > vs)
            ):
                m[1] = (
                    min(m[1], vs) if m_var and t_var
                    else (m[1] if m_var else vs)
                )
                m[2] = (
                    max(m[2], ve) if m_var and t_var
                    else (m[2] if m_var else ve)
                )
                m[3] = min(m[3], rs)
                m[4] = max(m[4], re)
                continue
            if m[0] == c and m[3] == rs and m[4] == re:
                continue  # TreeSet equality on readRange: duplicate drop
        merged.append([c, vs, ve, rs, re])
    return [
        RealignmentTarget(int(c), int(vs), int(ve), int(rs), int(re))
        for c, vs, ve, rs, re in merged
        if re - rs <= max_target_size
    ]


def map_reads_to_targets(
    read_contig_rank, read_start, read_end, mapped_mask,
    target_rank, target_start, target_end,
) -> np.ndarray:
    """Vectorized replica of RealignIndels.mapToTarget's set-halving
    search (:72-94), including its pruning rule and the
    ``-1 - start/3000`` empty-target spreading."""
    n = len(read_start)
    nt = len(target_start)
    lo = np.zeros(n, dtype=np.int64)
    hi = np.full(n, nt, dtype=np.int64)
    while True:
        size = hi - lo
        if (size <= 1).all():
            break
        mult = size > 1
        mid = lo + size // 2
        m = np.clip(mid, 0, nt - 1)
        # lt(targets[mid], read): target orders before read (name,start,end)
        t_key_lt = (
            (target_rank[m] < read_contig_rank)
            | ((target_rank[m] == read_contig_rank) & (target_start[m] < read_start))
            | ((target_rank[m] == read_contig_rank) & (target_start[m] == read_start)
               & (target_end[m] < read_end))
        ) & mapped_mask
        hi = np.where(mult & t_key_lt, mid, hi)
        lo = np.where(mult & ~t_key_lt, mid, lo)
    t = np.clip(lo, 0, nt - 1)
    contains = (
        mapped_mask
        & (target_rank[t] == read_contig_rank)
        & (target_end[t] > read_start)
        & (read_end > target_start[t])
    )
    # Scala's `/` truncates toward zero, so the reference's unmapped
    # (start = -1) sentinel is -1 - 0 = -1; Python's floor division
    # would give -1 - (-1) = 0, a *valid* target index
    empty = np.where(
        read_start >= 0, -1 - read_start // 3000, -1
    ).astype(np.int64)
    return np.where(contains, t, empty)


def map_reads_to_targets_overlap(
    read_contig_rank, read_start, read_end, mapped_mask,
    target_rank, target_start, target_end,
) -> np.ndarray:
    """Interval mapping: each read goes to the *first target whose read
    range it overlaps* (GATK's IntervalListReferenceOrderedData walk).

    The reference's set-halving search (:func:`map_reads_to_targets`)
    keeps the *head* half when the probe orders before the read
    (RealignIndels.scala:87-91), so with more than one target most
    overlapping reads land on a non-overlapping probe and fall out of
    realignment entirely; its own suite only exercises single-target
    sets (RealignIndelsSuite.scala:54-55).  This mode restores the
    stated semantics; ``map_reads_to_targets`` remains for bit-parity.

    Vectorized: targets sorted by (rank, start); with a composite
    coordinate and a running max of target ends, the first overlapping
    target is one searchsorted (cummax is monotone) + one bounds check.
    """
    nt = len(target_start)
    n = len(read_start)
    if nt == 0:
        return np.where(
            read_start >= 0, -1 - read_start // 3000, -1
        ).astype(np.int64)
    BIG = np.int64(1) << 40
    t_s = target_rank * BIG + target_start
    t_e = target_rank * BIG + target_end
    order = np.argsort(t_s, kind="stable")
    t_s, t_e = t_s[order], t_e[order]
    cummax_e = np.maximum.accumulate(t_e)
    r_s = read_contig_rank * BIG + read_start
    r_e = read_contig_rank * BIG + read_end
    j = np.searchsorted(cummax_e, r_s, side="right")
    jc = np.clip(j, 0, nt - 1)
    contains = (
        mapped_mask & (j < nt) & (t_s[jc] < r_e) & (t_e[jc] > r_s)
    )
    # Scala's `/` truncates toward zero, so the reference's unmapped
    # (start = -1) sentinel is -1 - 0 = -1; Python's floor division
    # would give -1 - (-1) = 0, a *valid* target index
    empty = np.where(
        read_start >= 0, -1 - read_start // 3000, -1
    ).astype(np.int64)
    return np.where(contains, order[jc], empty)


def map_batch_to_targets(b, targets, names, mode: str = "overlap") -> np.ndarray:
    """Target index per row of a batch (-k spreading for unmatched rows).
    The candidate filter of the streamed/sharded paths: rows with
    tidx >= 0 are gathered for realignment, everything else passes
    through untouched.

    ``mode="overlap"`` (default) maps every read to the first target it
    overlaps; ``mode="faithful"`` replicates the reference's set-halving
    search bit-for-bit, quirks included (see
    :func:`map_reads_to_targets_overlap` for why they differ).
    """
    if not targets:
        return np.full(b.n_rows, -1, dtype=np.int64)
    rank_of_name = {nm: i for i, nm in enumerate(sorted(names))}
    contig_rank = np.array([rank_of_name[nm] for nm in names], dtype=np.int64)
    t_rank = np.array(
        [contig_rank[t.contig_idx] for t in targets], dtype=np.int64
    )
    t_start = np.array([t.range_start for t in targets], dtype=np.int64)
    t_end = np.array([t.range_end for t in targets], dtype=np.int64)
    flags = np.asarray(b.flags)
    mapped = ((flags & schema.FLAG_UNMAPPED) == 0) & np.asarray(b.valid)
    read_rank = np.where(
        mapped,
        contig_rank[np.clip(np.asarray(b.contig_idx), 0, len(names) - 1)],
        -1,
    )
    fn = (
        map_reads_to_targets_overlap
        if mode == "overlap"
        else map_reads_to_targets
    )
    return fn(
        read_rank, np.asarray(b.start).astype(np.int64),
        np.asarray(b.end).astype(np.int64), mapped, t_rank, t_start, t_end,
    )


# --------------------------------------------------------------------------
# Batched sweep kernel (device)
# --------------------------------------------------------------------------
def _pow2(n: int, minimum: int) -> int:
    return max(minimum, 1 << (max(int(n), 1) - 1).bit_length())


def sweep_bucket_shape(read_len: int, cons_len: int) -> tuple[int, int]:
    """Padded (lr, lc) bucket for one (read, consensus) sweep task.

    The kernel yields ``lc - lr + 1`` offsets but the reference sweeps
    offsets ``o < cons_len - read_len``; when ``lr`` rounds up past
    ``read_len`` the consensus bucket must absorb the padding
    (``lc >= cons_len + lr - read_len``) or tail offsets are silently
    lost (e.g. read_len=100 -> lr=128 with cons_len=250 needs lc=512,
    not 256, to represent offsets 129..149)."""
    lr = _pow2(read_len, 32)
    lc = _pow2(max(cons_len + (lr - read_len), lr + 1), 64)
    return lr, lc


@partial(jax.jit, static_argnames=("off", "rt", "lr"))
def sweep_gemm_kernel(read_codes, read_quals, read_len, read_mask,
                      cons, cons_len, off: int, rt: int, lr: int):
    """MXU-shaped sweep: batched GEMM over (target, consensus) pairs.

    Same math as :func:`sweep_kernel` — mismatchQual(b, o) = totalQual -
    one-hot match correlation, offsets ``o < cons_len - read_len`` — but
    laid out as ``[P, rt, lr*6] x [P, lr*6, off]`` batched matmuls so the
    contraction runs on the MXU instead of a degenerate 6-channel conv
    (measured ~9 GFLOP/s on the conv formulation vs matmul peak).  All
    values are integers: bf16 inputs are exact (quals <= 93 need 7
    mantissa bits), the MXU accumulates in f32 (exact to 2^24), so
    results are bit-identical to the f32 conv path.

    Pair slot ``p`` sweeps reads ``read_codes[p*rt:(p+1)*rt]`` against
    ``cons[p]`` (``lc = off + lr``); every compiled shape depends only on
    the static ``(off, rt, lr)`` tier, never on dataset size.  Padded
    read slots have ``read_mask`` False; padded pairs have ``cons_len``
    0.  Returns (best_q f32[P, rt], best_o i32[P, rt])."""
    P = cons.shape[0]
    rc = read_codes.reshape(P, rt, lr)
    rl = read_len.reshape(P, rt)
    pos = jnp.arange(lr)
    qf = jnp.where(
        (pos[None, None, :] < rl[..., None])
        & read_mask.reshape(P, rt)[..., None],
        read_quals.reshape(P, rt, lr), 0,
    ).astype(jnp.int32)
    A = (
        jax.nn.one_hot(rc, 6, dtype=jnp.bfloat16)
        * qf[..., None].astype(jnp.bfloat16)
    ).reshape(P, rt, lr * 6)
    oh = jax.nn.one_hot(cons, 6, dtype=jnp.bfloat16)       # [P, lc, 6]
    idx = jnp.arange(lr)[:, None] + jnp.arange(off)[None, :]
    B = oh[:, idx, :]                                      # [P, lr, off, 6]
    B = B.transpose(0, 1, 3, 2).reshape(P, lr * 6, off)
    match = jnp.einsum(
        "prk,pko->pro", A, B, preferred_element_type=jnp.float32
    )
    total_q = qf.sum(-1)[..., None].astype(jnp.float32)    # [P, rt, 1]
    mismatch = total_q - match
    valid = (
        jnp.arange(off)[None, None, :]
        < (cons_len[:, None] - rl)[..., None]
    )
    masked = jnp.where(valid, mismatch, jnp.inf)
    best_o = jnp.argmin(masked, -1).astype(jnp.int32)
    best_q = masked.min(-1)
    has = valid.any(-1)
    return jnp.where(has, best_q, jnp.inf), jnp.where(has, best_o, -1)


# pair-batch size per (off, rt) tier: bounds the im2col temporary
# [P, lr, off, 6] bf16 while keeping ~4k tasks per dispatch
def _sweep_gemm_P(off: int, rt: int) -> int:
    base = max(8, (1 << 17) // off)  # 256 at off=512, halving upward
    return max(2, base // (rt // 16)) if rt > 16 else base


@partial(jax.jit, static_argnames=("lr", "lc"))
def sweep_kernel_gather(read_codes, read_quals, read_len, cons_tbl,
                        clen_tbl, cons_idx, lr: int, lc: int):
    """Sweep with a deduplicated consensus table.

    A chunk's tasks reference each consensus once per read in its group,
    so shipping the [CH, lc] consensus rows per-task re-sends every byte
    group-size times over the ~20 MB/s device tunnel.  Instead the
    unique consensus rows travel once and the per-task rows are gathered
    ON DEVICE from the table.
    """
    return sweep_kernel(
        read_codes, read_quals, read_len,
        cons_tbl[cons_idx], clen_tbl[cons_idx], lr, lc,
    )


@partial(jax.jit, static_argnames=("lr", "lc"))
def sweep_kernel(read_codes, read_quals, read_len, cons_codes, cons_len,
                 lr: int, lc: int):
    """For each (read, consensus) pair: mismatch quality at every offset.

    mismatchQual(b, o) = sum_i q_i [read_i != cons_{o+i}]
                       = totalQual(b) - sum_i q_i [read_i == cons_{o+i}]
    with the match-correlation computed as a one-hot conv per pair.
    Valid offsets o in [0, cons_len - read_len) (the reference's
    exclusive sweep loop).  Returns (best_qual i32[B], best_offset i32[B])
    with the smallest offset winning ties; best_offset = -1 when no valid
    offset exists.
    """
    B = read_codes.shape[0]
    in_read = jnp.arange(lr)[None, :] < read_len[:, None]
    q = jnp.where(in_read, read_quals, 0).astype(jnp.float32)
    total_q = q.sum(axis=1)
    # one-hot over the 6 codes (N==N matches, PAD never matches quals=0)
    read_oh = jax.nn.one_hot(read_codes, 6, dtype=jnp.float32) * q[..., None]
    cons_oh = jax.nn.one_hot(cons_codes, 6, dtype=jnp.float32)

    def corr(x, w):
        # x: [lc, 6] one-hot consensus; w: [lr, 6] qual-weighted read
        return jax.lax.conv_general_dilated(
            x[None], w[:, :, None],
            window_strides=(1,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"),
        )[0, :, 0]

    match = jax.vmap(corr)(cons_oh, read_oh)  # [B, lc - lr + 1]
    mismatch = total_q[:, None] - match
    n_off = lc - lr + 1
    offs = jnp.arange(n_off)[None, :]
    valid = offs < (cons_len - read_len)[:, None]  # exclusive upper bound
    masked = jnp.where(valid, mismatch, jnp.inf)
    best_off = jnp.argmin(masked, axis=1).astype(jnp.int32)
    best_q = jnp.min(masked, axis=1)
    has_any = valid.any(axis=1)
    return (
        jnp.where(has_any, best_q, jnp.inf),
        jnp.where(has_any, best_off, -1),
    )


def _group_candidates(b, tidx, mapped):
    """Candidate rows grouped by target, position-sorted within a group
    (the reference sorts the RDD before target mapping).

    Returns ``(srows, goff, gtid)``: flat row indices, group offsets
    (``goff[g]:goff[g+1]`` slices ``srows``), and the target id per
    group.  Shared by the Python and native paths — group iteration
    order drives the rng.sample call sequence, so both paths MUST use
    this exact construction for bit-identical output."""
    sel = np.flatnonzero(mapped & (tidx >= 0))
    if not len(sel):
        z = np.zeros(0, np.int64)
        return z, np.zeros(1, np.int64), z
    order = np.lexsort(
        (sel, np.asarray(b.start)[sel].astype(np.int64), tidx[sel])
    )
    srows = sel[order]
    stid = tidx[srows]
    bounds = np.flatnonzero(np.diff(stid) != 0) + 1
    goff = np.concatenate(
        [np.zeros(1, np.int64), bounds.astype(np.int64),
         np.array([len(srows)], np.int64)]
    )
    gtid = stid[goff[:-1]].astype(np.int64)
    return srows, goff, gtid


def _sum_mismatch_quality(seq: str, ref: str, quals) -> int:
    """sumMismatchQualityIgnoreCigar: positional zip, truncating to the
    shorter string (RealignIndels.scala:429-440) — vectorized byte
    compare instead of a per-char generator."""
    n = min(len(seq), len(ref), len(quals))
    if n == 0:
        return 0
    a = np.frombuffer(seq.encode("ascii"), np.uint8, n)
    b = np.frombuffer(ref.encode("ascii"), np.uint8, n)
    q = np.asarray(quals[:n], np.int64)
    return int(q[a != b].sum())


# --------------------------------------------------------------------------
# Per-target realignment (host orchestration)
# --------------------------------------------------------------------------
@dataclass
class _Read:
    """Host view of one read under realignment.

    ``md`` is parsed lazily — only reads whose CIGAR is not a single M
    run need it (left-alignment, reference slices through indels); for
    the pure-M majority the precomputed ``ref`` string (from the
    vectorized MD tokenizer) and per-row mismatch-qual sums replace all
    per-read MD work.  ``dirty`` marks reads whose alignment changed in
    preprocessing (left-align / SW), which must be written back even
    when the consensus pass leaves them alone.
    """

    row: int
    seq: str
    quals: np.ndarray
    start: int
    cigar: list  # [(len, op)]
    md: Optional[MdTag]
    mapq: int
    ref: Optional[str] = None  # implied reference over the aligned span
    pure: bool = False  # single-M CIGAR
    dirty: bool = False
    codes: Optional[np.ndarray] = None  # base codes (sweep input, cached)

    @property
    def end(self) -> int:
        return self.start + cigar_ref_len(self.cigar)


def _get_reference_from_reads(reads: list[_Read], extra_refs=()):
    """RealignIndels.getReferenceFromReads (:185-215).

    ``extra_refs`` carries (ref, start, end) tuples for reads that exist
    only as columnar rows (the pure clean majority never materialized as
    ``_Read`` objects); they splice into the window exactly as reads do.
    """
    refs = list(extra_refs)
    for r in reads:
        ref = r.ref
        if ref is None and r.md is not None:  # directly-built _Reads
            ref = r.md.get_reference(r.seq, cigar_to_string(r.cigar))
        if ref is not None:
            refs.append((ref, r.start, r.end))
    if not refs:
        raise ValueError("no reads with MD tags in target group")
    refs.sort(key=lambda x: x[1])
    ref, cur = "", refs[0][1]
    ref_start = refs[0][1]
    for s, start, end in refs:
        if end < cur:
            continue
        if cur >= start:
            ref += s[cur - start :]
            cur = end
        else:
            raise ValueError(f"gap at {cur} with {start},{end} rebuilding reference")
    return ref, ref_start, cur


@dataclass(frozen=True)
class Consensus:
    """models/Consensus.scala: an alternate allele to splice into the
    reference — insertion when index spans 1bp."""

    consensus: str
    contig_idx: int
    index_start: int
    index_end: int

    def insert_into_reference(self, reference: str, ref_start: int, ref_end: int) -> str:
        if (self.index_start < ref_start or self.index_start > ref_end
                or self.index_end - 1 < ref_start or self.index_end - 1 > ref_end):
            raise ValueError("consensus and reference do not overlap")
        return (
            reference[: self.index_start - ref_start]
            + self.consensus
            + reference[self.index_end - 1 - ref_start :]
        )


def generate_alternate_consensus(seq: str, start: int, contig_idx: int,
                                 cigar: list) -> Optional[Consensus]:
    """Consensus.generateAlternateConsensus (:25-52)."""
    read_pos = 0
    ref_pos = start
    if sum(1 for _, op in cigar if op in "ID") != 1:
        return None
    for n, op in cigar:
        if op == "I":
            return Consensus(seq[read_pos : read_pos + n], contig_idx,
                             ref_pos, ref_pos + 1)
        if op == "D":
            return Consensus("", contig_idx, ref_pos, ref_pos + n + 1)
        if op in "M=X":
            read_pos += n
            ref_pos += n
        else:
            return None
    return None


def realign_indels(
    ds: AlignmentDataset,
    consensus_model: str = "reads",
    known_indels: Optional[IndelTable] = None,
    max_indel_size: int = MAX_INDEL_SIZE,
    max_consensus_number: int = MAX_CONSENSUS_NUMBER,
    lod_threshold: float = LOD_THRESHOLD,
    max_target_size: int = MAX_TARGET_SIZE,
    sw_weights: tuple = (1.0, -0.333, -0.5, -0.5),
    rng: Optional[random.Random] = None,
    target_mapping: str = "overlap",
    overlap_work=None,
    sweep_devices=None,
) -> AlignmentDataset:
    """GATK-style local realignment (RealignIndels.scala:235-387).

    Dispatches to the native-prep path (C++ per-read string walks +
    vectorized sweep dispatch, native/realign.cpp) when available; the
    pure-Python implementation below remains the semantic oracle (the
    two are differentially tested) and the fallback for the
    ``smithwaterman`` consensus model and native-less installs.

    ``overlap_work``: optional zero-arg callable invoked after the
    device sweeps are dispatched and before their results are fetched —
    host work placed there (e.g. the streamed pipeline's BQSR
    observation pass) runs under the device's queue-drain window, which
    on the time-sliced bench chip is the realign tail's dominant wall.
    Runs exactly once whichever implementation serves the call (a
    once-guard here covers the native path handing off to the fallback
    AFTER it already ran the work).  Whether the work actually ran inside
    the native sweep-dispatch window (i.e. genuinely hidden under the
    device queue drain) is reported back on the callable itself as
    ``overlap_ran_in_dispatch`` — the streamed pipeline's stage table
    only credits the overlap when it really happened (on the Python
    fallback and the no-target early-outs the work runs serially).

    ``sweep_devices``: explicit device set to fan the sweep GEMM
    buckets across (the streamed pipeline passes its pool/mesh device
    set) — chunks place round-robin weighted by
    :class:`~adam_tpu.parallel.device_pool.SweepSchedule` (per-device
    probe TFLOP/s pacing), instead of all landing on the default
    device.  Placement never changes the sweep values, so the output
    is bit-identical regardless of fan-out."""
    if overlap_work is not None:
        _orig_overlap = overlap_work
        _overlap_state = {"done": False}

        def overlap_work(in_dispatch: bool = False):
            if not _overlap_state["done"]:
                _overlap_state["done"] = True
                try:
                    _orig_overlap.overlap_ran_in_dispatch = bool(in_dispatch)
                except (AttributeError, TypeError):
                    pass  # exotic callable: accounting stays pessimistic
                _orig_overlap()

        overlap_work._accepts_in_dispatch = True

    if consensus_model != "smithwaterman" and os.environ.get(
        "ADAM_TPU_REALIGN", ""
    ) != "py":
        out = _realign_indels_native(
            ds, consensus_model, known_indels, max_indel_size,
            max_consensus_number, lod_threshold, max_target_size, rng,
            target_mapping, overlap_work=overlap_work,
            sweep_devices=sweep_devices,
        )
        if out is not None:
            return out
    if overlap_work is not None:
        overlap_work()  # no async device phase on the fallback path
    return _realign_indels_py(
        ds, consensus_model, known_indels, max_indel_size,
        max_consensus_number, lod_threshold, max_target_size, sw_weights,
        rng, target_mapping, sweep_devices=sweep_devices,
    )


def _realign_indels_py(
    ds: AlignmentDataset,
    consensus_model: str = "reads",
    known_indels: Optional[IndelTable] = None,
    max_indel_size: int = MAX_INDEL_SIZE,
    max_consensus_number: int = MAX_CONSENSUS_NUMBER,
    lod_threshold: float = LOD_THRESHOLD,
    max_target_size: int = MAX_TARGET_SIZE,
    sw_weights: tuple = (1.0, -0.333, -0.5, -0.5),
    rng: Optional[random.Random] = None,
    target_mapping: str = "overlap",
    sweep_devices=None,
) -> AlignmentDataset:
    b = ds.batch.to_numpy()
    n = b.n_rows
    if n == 0:
        return ds
    targets = find_targets(ds, max_target_size, max_indel_size)
    if not targets:
        return ds
    names = ds.seq_dict.names
    flags = np.asarray(b.flags)
    mapped = ((flags & schema.FLAG_UNMAPPED) == 0) & np.asarray(b.valid)
    tidx = map_batch_to_targets(b, targets, names, mode=target_mapping)

    # group rows by target, position-sorted within the group — the shared
    # vectorized construction (see _group_candidates for why shared)
    srows, goff, gtid = _group_candidates(b, tidx, mapped)
    groups: dict[int, list[int]] = {
        int(gtid[g]): [int(i) for i in srows[goff[g]:goff[g + 1]]]
        for g in range(len(gtid))
    }

    new_batch = jax.tree.map(np.array, b)  # writable copies
    side = ds.sidecar

    # vectorized per-row MD columns (one native tokenize, no per-read
    # parse): mismatch mask -> to_clean membership + positional orig-qual
    # sums; ref codes -> implied reference for every single-M read
    from adam_tpu.ops.mdtag import batch_md_arrays

    is_mm, ref_codes, has_md_vec = batch_md_arrays(
        ds.batch, side, need_ref_codes=True
    )
    row_has_mm = is_mm.any(axis=1)
    mm_qual = np.where(is_mm, np.asarray(b.quals), 0).sum(axis=1)
    # sparse overrides: only realigned rows get new MD/attrs — the full
    # sidecar is never materialized as python strings (8M reads would
    # cost ~30s just in string churn)
    new_md: dict[int, Optional[str]] = {}
    new_attrs: dict[int, str] = {}
    rng = rng or random.Random(0)

    # ---- phase 1 (host): per group, rebuild reference + consensuses ----
    # bulk per-row precomputation over all grouped rows (one LUT/decode
    # pass instead of a numpy-call per read — the single-core host is the
    # pipeline's scarce resource)
    all_rows = np.concatenate([np.asarray(r) for r in groups.values()]) if groups else np.zeros(0, np.int64)
    seq_of: dict[int, str] = {}
    ref_of: dict[int, str] = {}
    if len(all_rows):
        purev = (
            (np.asarray(b.cigar_n)[all_rows] == 1)
            & (np.asarray(b.cigar_ops)[all_rows, 0] == schema.CIGAR_M)
            & has_md_vec[all_rows]
        )
        prows = all_rows[purev]
        if len(prows):
            ref_of = dict(
                zip(
                    (int(i) for i in prows),
                    schema.decode_bases_bulk(
                        ref_codes[prows], np.asarray(b.lengths)[prows]
                    ),
                )
            )
        # sequences are only needed for rows that materialize a _Read —
        # the pure clean majority (in ref_of, no mismatches) is skipped
        # by the light path below and never decodes
        heavy = all_rows[~(purev & ~row_has_mm[all_rows])]
        if len(heavy):
            seq_of = dict(
                zip(
                    (int(i) for i in heavy),
                    schema.decode_bases_bulk(
                        np.asarray(b.bases)[heavy],
                        np.asarray(b.lengths)[heavy],
                    ),
                )
            )
    _CC = schema.CIGAR_CHARS

    group_ctx = {}
    res_q: dict[int, np.ndarray] = {}  # per target: [n_reads, n_cons]
    res_o: dict[int, np.ndarray] = {}

    # ---- phase 2 machinery, interleaved with phase 1 ------------------
    # tasks are grouped into power-of-two (read, consensus) length
    # buckets so a single max_target_size consensus doesn't inflate
    # every (read x consensus) pair in the batch, and each bucket
    # flushes to the device in FIXED-size chunks (one compiled shape per
    # (lr, lc) bucket — a data-dependent batch dim compiled a fresh
    # kernel per size, 20-40s each through the tunneled compile
    # service).  Chunks dispatch asynchronously *while phase 1 is still
    # building later groups* (quals travel as u8; the kernel widens on
    # device); results stay on device and one fetch pass drains them
    # after the last flush — the chip sweeps target k's pairs while the
    # single-core host rebuilds target k+1's reference.
    CH = 8192   # tasks per dispatch (fixed -> one compiled shape/bucket)
    # consensus slots: large enough that dense data (tasks-per-consensus
    # = group size >= 4) never flushes early on the cons trigger, small
    # enough that the always-full-size table transfer stays ~1 MB
    NC = 2048
    _buckets: dict[tuple[int, int], dict] = {}
    _pending = []  # (chunk tasks, device (best_q, best_o))
    _remaining: dict[int, int] = {}  # target -> sweep results outstanding
    # fan sweep chunks across the pool/mesh device set (probe-paced
    # weighted round-robin); None = the default device, the old behavior
    _sched = None
    if sweep_devices is not None and len(sweep_devices) > 1:
        from adam_tpu.parallel.device_pool import SweepSchedule

        _sched = SweepSchedule(sweep_devices)

    def _flush_bucket(key) -> None:
        lr, lc = key
        st = _buckets.pop(key)
        tasks = st["tasks"]
        # two shape tiers per bucket: small flushes (residuals, small
        # inputs) use a 1024-task shape so a near-empty chunk doesn't
        # pay the full 8192-row compute on slow backends; both tiers
        # stay fixed so the compile-shape set is bounded at two
        ch = CH if len(tasks) > 1024 else 1024
        nc = NC if ch == CH else 1024
        rc = np.full((ch, lr), schema.BASE_PAD, np.uint8)
        rq = np.zeros((ch, lr), np.uint8)
        rl = np.zeros(ch, np.int32)
        ct = np.full((nc, lc), schema.BASE_PAD, np.uint8)
        cl = np.zeros(nc, np.int32)
        for s, codes in enumerate(st["cons"]):
            ct[s, : len(codes)] = codes
            cl[s] = len(codes)
        cidx = np.zeros(ch, np.int32)
        for k, (_t, _ri, _ci, r, cs) in enumerate(tasks):
            rc[k, : len(r.codes)] = r.codes
            rq[k, : len(r.quals)] = r.quals
            rl[k] = len(r.codes)
            cidx[k] = cs
        # padded task rows gather consensus slot 0 and are never read back
        from adam_tpu.parallel.device_pool import putter as _putter
        from adam_tpu.utils import compile_ledger

        dev = _sched.next_device() if _sched is not None else None
        _put = _putter(dev)  # commit + h2d transfer accounting
        with compile_ledger.track(("realign.sweep", ch, lr, nc, lc), dev):
            _pending.append((tasks, sweep_kernel_gather(
                _put(rc), _put(rq), _put(rl),
                _put(ct), _put(cl), _put(cidx), lr, lc,
            )))

    def _enqueue_sweep(task) -> None:
        t, ri, ci, r, cons_codes = task
        key = sweep_bucket_shape(len(r.codes), len(cons_codes))
        st = _buckets.get(key)
        if st is None:
            st = _buckets[key] = {"tasks": [], "cmap": {}, "cons": []}
        cs = st["cmap"].get(id(cons_codes))
        if cs is None:
            cs = len(st["cons"])
            st["cmap"][id(cons_codes)] = cs
            st["cons"].append(cons_codes)
        st["tasks"].append((t, ri, ci, r, cs))
        if len(st["tasks"]) >= CH or len(st["cons"]) >= NC:
            _flush_bucket(key)
    for t, rows in groups.items():
        reads = []
        extra_refs = []
        for i in rows:
            if i in ref_of and not row_has_mm[i]:
                # pure clean majority: never swept, never rewritten —
                # contributes only its reference slice to the window
                # rebuild, so no _Read is materialized at all
                s0 = int(b.start[i])
                extra_refs.append((ref_of[i], s0, s0 + int(b.lengths[i])))
                continue
            L = int(b.lengths[i])
            seq = seq_of[i]
            nc = int(b.cigar_n[i])
            cig = [
                (int(b.cigar_lens[i, k]), _CC[b.cigar_ops[i, k]])
                for k in range(nc)
            ]
            pure = nc == 1 and b.cigar_ops[i, 0] == schema.CIGAR_M
            has_md_i = bool(has_md_vec[i])
            if pure or not has_md_i:
                md = None  # pure-M rows never need a parsed MdTag
            else:
                md = MdTag.parse(side.md[i], int(b.start[i]))
            if not has_md_i:
                ref = None
            elif pure:
                ref = ref_of[i]
            else:
                ref = md.get_reference(seq, cig)
            reads.append(
                _Read(
                    row=i,
                    seq=seq,
                    quals=np.asarray(b.quals[i][:L], np.int32),
                    start=int(b.start[i]),
                    cigar=cig,
                    md=md,
                    mapq=int(b.mapq[i]),
                    ref=ref,
                    pure=pure,
                    codes=np.asarray(b.bases[i][:L]),
                )
            )
        # reads that already match the reference pass through untouched
        to_clean = [
            r for r in reads if not has_md_vec[r.row] or row_has_mm[r.row]
        ]
        if not to_clean:
            continue
        try:
            reference, ref_start, ref_end = _get_reference_from_reads(
                reads, extra_refs
            )
        except ValueError:
            continue
        contig_idx = targets[t].contig_idx

        # preprocess: left-normalize single-indel reads (and SW-realign
        # everything first under the smithwaterman model)
        if consensus_model == "smithwaterman":
            to_clean = _sw_preprocess(
                to_clean, reference, ref_start, sw_weights
            )
        processed = []
        for r in to_clean:
            if cigar_num_alignment_blocks(r.cigar) == 2:
                new_cigar = left_align_indel(r.seq, r.cigar, r.md)
                if new_cigar != r.cigar:
                    md = MdTag.move_alignment(
                        r.ref, r.seq, cigar_to_string(new_cigar), r.start,
                    ) if r.md is not None else None
                    processed.append(
                        dc_replace(r, cigar=new_cigar, md=md, dirty=True)
                    )
                else:
                    processed.append(r)
            else:
                processed.append(r)
        to_clean = processed

        # find consensuses
        consensuses: list[Consensus] = []
        if consensus_model == "knowns" and known_indels is not None:
            region_name = names[contig_idx]
            from adam_tpu.models.positions import ReferenceRegion

            for rec in known_indels.get_indels_in_region(
                ReferenceRegion(region_name, ref_start, ref_end)
            ):
                consensuses.append(
                    Consensus(rec.consensus, contig_idx,
                              rec.region.start, rec.region.end)
                )
        else:
            for r in to_clean:
                if r.md is None:
                    continue
                c = generate_alternate_consensus(
                    r.seq, r.start, contig_idx, r.cigar
                )
                if c is not None:
                    consensuses.append(c)
        # distinct
        seen = set()
        uniq = []
        for c in consensuses:
            key = (c.consensus, c.index_start, c.index_end)
            if key not in seen:
                seen.add(key)
                uniq.append(c)
        consensuses = uniq
        if len(consensuses) > max_consensus_number:
            consensuses = rng.sample(consensuses, max_consensus_number)
        if not consensuses:
            # still keep preprocessing results (readsToClean ++ realigned)
            _write_back(new_batch, side, new_md, new_attrs, to_clean, realigned={})
            continue

        group_ctx[t] = (to_clean, consensuses, reference, ref_start, ref_end)
        res_q[t] = np.full(
            (len(to_clean), len(consensuses)), np.inf, np.float32
        )
        res_o[t] = np.full((len(to_clean), len(consensuses)), -1, np.int32)
        _remaining[t] = len(to_clean) * len(consensuses)
        for ci, c in enumerate(consensuses):
            cons_seq = c.insert_into_reference(reference, ref_start, ref_end)
            cons_codes = schema.encode_bases(cons_seq)  # once per consensus
            for ri, r in enumerate(to_clean):
                _enqueue_sweep((t, ri, ci, r, cons_codes))

    del seq_of, ref_of  # decoded strings live only through phase 1

    # ---- phase 2 drain + phase 3, interleaved ----
    # flush residual chunks, then finish each target the moment its last
    # sweep result lands — the host rewrites completed targets (phase 3)
    # while the device is still computing later chunks, instead of
    # blocking through the whole fetch tail first.  Targets write to
    # disjoint rows, so completion order doesn't affect the output.
    for key in list(_buckets):
        if _buckets[key]["tasks"]:
            _flush_bucket(key)

    def _finish_target(t: int) -> None:
        to_clean, consensuses, reference, ref_start, ref_end = group_ctx[t]

        def _orig_qual(r):
            if r.dirty and r.md is not None:
                return _sum_mismatch_quality(
                    r.seq,
                    r.md.get_reference(r.seq, cigar_to_string(r.cigar)),
                    r.quals,
                )
            if r.pure:  # positional mismatch-qual sum, precomputed
                return int(mm_qual[r.row])
            return _sum_mismatch_quality(r.seq, r.ref or "", r.quals)

        orig_quals = [_orig_qual(r) for r in to_clean]
        pre_total = sum(orig_quals)
        # vectorized consensus scoring over the [n_reads, n_cons] sweep
        # result arrays: per cell take min(sweep, orig) (sweep value
        # truncated to int, as the reference's Int sum does), column
        # totals, best = min with the LATER consensus winning ties
        # (the reference's list-prepend + left fold)
        q = res_q[t]
        o = res_o[t]
        orig = np.asarray(orig_quals, np.int64)
        use = q < orig[:, None]
        qi = np.zeros_like(q, dtype=np.int64)
        qi[use] = q[use].astype(np.int64)
        contrib = np.where(use, qi, orig[:, None])
        totals = contrib.sum(axis=0)
        nc = len(consensuses)
        best_ci = int(nc - 1 - np.argmin(totals[::-1]))
        best_total = int(totals[best_ci])
        best_map = np.where(use[:, best_ci], o[:, best_ci], -1)
        lod = (pre_total - best_total) / 10.0
        # per-target decision logs, the RealignIndels.scala:317-379 trail
        _log = logging.getLogger(__name__)
        _log.debug(
            "On target %d [%d, %d), before realignment, sum was %d; "
            "best consensus %d has sum %d (LOD %.2f)",
            t, ref_start, ref_start + len(reference), pre_total,
            best_ci, best_total, lod,
        )
        realigned = {}
        if lod > lod_threshold:
            cons = consensuses[best_ci]
            for ri, r in enumerate(to_clean):
                o = best_map[ri]
                if o == -1:
                    continue
                new_start = ref_start + o
                if cons.index_start == cons.index_end - 1:  # insertion
                    id_elem = (len(cons.consensus), "I")
                    end_len = len(r.seq) - len(cons.consensus) - (cons.index_start - new_start)
                    end_penalty = -len(cons.consensus)
                else:  # deletion
                    id_elem = (cons.index_end - 1 - cons.index_start, "D")
                    end_len = len(r.seq) - (cons.index_start - new_start)
                    end_penalty = len(cons.consensus)
                head_len = cons.index_start - new_start
                if head_len > 0 and end_len > 0:
                    new_cigar = [(head_len, "M"), id_elem, (end_len, "M")]
                    new_end = new_start + len(r.seq) + end_penalty
                else:
                    # the swept position doesn't span the consensus indel
                    # (read entirely before/after it): a plain gapless
                    # alignment at the new offset.  The reference emits a
                    # negative-length M here (RealignIndels.scala:344-360,
                    # never hit by its single-target suite) — an invalid
                    # CIGAR we decline to reproduce.
                    new_cigar = [(len(r.seq), "M")]
                    new_end = new_start + len(r.seq)
                # a swept offset near the region edge can consume more
                # reference than the rebuilt window holds (insertion
                # consensuses are longer than the reference, so valid
                # consensus offsets can overrun it — another walk the
                # reference leaves unguarded): leave the read unrealigned
                if o + (new_end - new_start) > len(reference):
                    continue
                md = MdTag.move_alignment(
                    reference[o:], r.seq, cigar_to_string(new_cigar), new_start
                )
                realigned[ri] = dc_replace(
                    r, start=new_start, cigar=new_cigar, md=md, mapq=r.mapq + 10
                ), new_end
        _write_back(new_batch, side, new_md, new_attrs, to_clean, realigned)

    from adam_tpu.utils.transfer import device_fetch as _dfetch

    for chunk, out in _pending:
        # drain through the transfer helper so the d2h ledger sees the
        # sweep results (tiny [CH] i32 pairs, but the tunnel rounds
        # them up — per-pass byte attribution must not have dark spots)
        best_q, best_o = _dfetch(out[0]), _dfetch(out[1])
        for k, (t, ri, ci, _, _) in enumerate(chunk):
            res_q[t][ri, ci] = best_q[k]
            res_o[t][ri, ci] = best_o[k]
            _remaining[t] -= 1
            if _remaining[t] == 0:
                _finish_target(t)

    from adam_tpu.formats.strings import StringColumn, with_overrides

    new_side = dc_replace(
        side,
        md=with_overrides(StringColumn.of(side.md), new_md),
        attrs=with_overrides(StringColumn.of(side.attrs), new_attrs),
    )
    return ds.with_batch(new_batch, new_side)


def _sw_preprocess(reads, reference, ref_start, weights):
    """ConsensusGeneratorFromSmithWaterman.preprocessReadsForRealignment
    (:40-70): SW-align each read against the region; accept when <= 2
    alignment blocks, rewriting start/cigar/MD (start from the
    reference's own xStart+regionStart rule)."""
    from adam_tpu.ops.smith_waterman import smith_waterman

    out = []
    w_match, w_mismatch, w_insert, w_delete = weights
    for r in reads:
        aln = smith_waterman(r.seq, reference, w_match, w_mismatch,
                             w_insert, w_delete)
        cigar = parse_cigar(aln.cigar_x)
        if cigar_num_alignment_blocks(cigar) <= 2:
            md = MdTag.from_alignment(
                r.seq, reference[aln.x_start :], aln.cigar_x, ref_start
            )
            out.append(
                dc_replace(r, start=aln.x_start + ref_start, cigar=cigar,
                           md=md, dirty=True)
            )
        else:
            out.append(r)
    return out


def _write_back(new_batch, side, new_md, new_attrs, to_clean, realigned):
    """Apply (possibly realigned) host reads back into the batch.

    MD/attr updates land in the sparse ``new_md``/``new_attrs`` override
    dicts (row -> str), merged into the sidecar columns in one pass at
    the end of realign_indels."""
    cmax = new_batch.cmax
    for ri, r in enumerate(to_clean):
        if ri in realigned:
            rr, new_end = realigned[ri]
            old_start = int(new_batch.start[rr.row])
            old_cigar = schema.decode_cigar(
                new_batch.cigar_ops[rr.row], new_batch.cigar_lens[rr.row],
                int(new_batch.cigar_n[rr.row]),
            )
            tag = f"OC:Z:{old_cigar}\tOP:i:{old_start + 1}"
            cur = new_attrs.get(rr.row, side.attrs[rr.row]) or ""
            new_attrs[rr.row] = cur + "\t" + tag if cur else tag
        elif not r.dirty:
            continue  # alignment untouched: nothing to write
        else:
            rr, new_end = r, None
        cig = cigar_to_string(rr.cigar)
        ops, lens, ncig = schema.encode_cigar(cig, max(cmax, len(rr.cigar)))
        if ncig > cmax:
            raise ValueError("realigned cigar exceeds batch cmax")
        new_batch.cigar_ops[rr.row] = ops[:cmax]
        new_batch.cigar_lens[rr.row] = lens[:cmax]
        new_batch.cigar_n[rr.row] = ncig
        new_batch.start[rr.row] = rr.start
        new_batch.mapq[rr.row] = rr.mapq
        if new_end is not None:
            new_batch.end[rr.row] = new_end
        else:
            new_batch.end[rr.row] = rr.end
        if rr.md is not None:
            new_md[rr.row] = rr.md.to_string()


# --------------------------------------------------------------------------
# Native-prep realignment path
# --------------------------------------------------------------------------
def _pow2_vec(n: np.ndarray, minimum: int) -> np.ndarray:
    """Vectorized ``_pow2``: next power of two, floored at ``minimum``."""
    table = np.int64(1) << np.arange(40, dtype=np.int64)
    idx = np.searchsorted(table, np.maximum(np.asarray(n, np.int64), 1))
    return np.maximum(table[idx], minimum)


def _realign_indels_native(
    ds: AlignmentDataset,
    consensus_model: str,
    known_indels: Optional[IndelTable],
    max_indel_size: int,
    max_consensus_number: int,
    lod_threshold: float,
    max_target_size: int,
    rng: Optional[random.Random],
    target_mapping: str,
    overlap_work=None,
    sweep_devices=None,
):
    """Same decisions as :func:`_realign_indels_py`, with the per-read
    host work (MD parse / reference rebuild / left-normalization /
    consensus generation / MD rewrite) in C++ (native/realign.cpp) and
    the sweep task machinery vectorized.  Returns None when the native
    library is unavailable (caller falls back to the Python path)."""
    import time as _time

    from adam_tpu import native
    from adam_tpu.utils import instrumentation as _ins

    if not native.available():
        return None
    _t0 = _time.perf_counter()

    def _phase(label):
        # phase walls for -print_metrics (SweepReadOverReferenceForQuality
        # -style named timers, instrumentation/Timers.scala:25-81);
        # no-ops unless recording
        nonlocal _t0
        now = _time.perf_counter()
        _ins.TIMERS.add(label, int((now - _t0) * 1e9))
        _t0 = now

    def _overlap_once(in_dispatch: bool = False):
        nonlocal overlap_work
        if overlap_work is not None:
            w, overlap_work = overlap_work, None
            if getattr(w, "_accepts_in_dispatch", False):
                w(in_dispatch=in_dispatch)
            else:
                w()

    b = ds.batch.to_numpy()
    n = b.n_rows
    if n == 0:
        _overlap_once()
        return ds
    targets = find_targets(ds, max_target_size, max_indel_size)
    if not targets:
        _overlap_once()
        return ds
    names = ds.seq_dict.names
    flags = np.asarray(b.flags)
    mapped = ((flags & schema.FLAG_UNMAPPED) == 0) & np.asarray(b.valid)
    tidx = map_batch_to_targets(b, targets, names, mode=target_mapping)
    srows, goff, gtid = _group_candidates(b, tidx, mapped)
    if not len(srows):
        _overlap_once()
        return ds
    G = len(goff) - 1

    from adam_tpu.formats.strings import StringColumn, with_overrides

    side = ds.sidecar
    md_col = StringColumn.of(side.md)
    if len(md_col) >= n:
        md_buf, md_off = md_col.buf, md_col.offsets[: n + 1]
        md_valid = md_col.valid[:n] & np.asarray(b.valid)
    else:
        md_buf = np.zeros(0, np.uint8)
        md_off = np.zeros(n + 1, np.int64)
        md_valid = np.zeros(n, bool)

    # consensuses come from the indel table only under the knowns model
    # WITH a table; otherwise (reads model, or knowns without a table)
    # they are generated from the reads, as the Python path's else-branch
    # does (realign.py:994)
    gen_consensus = not (
        consensus_model == "knowns" and known_indels is not None
    )
    _phase("Realign: target map/group")
    prep = native.realign_prep(
        b, md_buf, md_off, md_valid.astype(np.uint8), srows, goff,
        gen_consensus,
    )
    if prep is None:
        return None
    _phase("Realign: native prep")

    t_status = prep["t_status"]
    t_ref_off = prep["t_ref_off"]
    t_ref_start = prep["t_ref_start"]
    t_ref_end = prep["t_ref_end"]
    ref_all = prep["t_ref_buf"].tobytes().decode("ascii", "replace")
    r_group = prep["r_group"]
    r_row = prep["r_row"]
    r_dirty = prep["r_dirty"].astype(bool)
    r_md_set = prep["r_md_set"].astype(bool)
    r_orig = prep["r_orig_qual"]
    R = len(r_row)
    rg_off = np.searchsorted(r_group, np.arange(G + 1))
    c_group = prep["c_group"]
    cg_off = np.searchsorted(c_group, np.arange(G + 1))
    c_off = prep["c_seq_off"]
    c_all = prep["c_seq_buf"].tobytes().decode("ascii", "replace")
    c_is = prep["c_is"]
    c_ie = prep["c_ie"]

    rng = rng or random.Random(0)
    lengths = np.asarray(b.lengths).astype(np.int64)
    _log = logging.getLogger(__name__)

    # ---- per-group consensus finalize (sampling order == Python path) --
    # grp_cons[g] = list of (cons_str, index_start, index_end)
    grp_cons: list = [None] * G
    for g in range(G):
        if t_status[g] != 0:
            continue
        if rg_off[g + 1] == rg_off[g]:
            continue
        if consensus_model == "knowns" and known_indels is not None:
            from adam_tpu.models.positions import ReferenceRegion

            region_name = names[targets[int(gtid[g])].contig_idx]
            cons = [
                (rec.consensus, rec.region.start, rec.region.end)
                for rec in known_indels.get_indels_in_region(
                    ReferenceRegion(
                        region_name, int(t_ref_start[g]), int(t_ref_end[g])
                    )
                )
            ]
        else:
            cons = [
                (c_all[c_off[k]:c_off[k + 1]], int(c_is[k]), int(c_ie[k]))
                for k in range(cg_off[g], cg_off[g + 1])
            ]
        # distinct (native path pre-dedupes the reads model; the knowns
        # model and the Python path share this exact dedup)
        seen = set()
        uniq = []
        for c in cons:
            if c not in seen:
                seen.add(c)
                uniq.append(c)
        cons = uniq
        if len(cons) > max_consensus_number:
            # random.sample on an index range picks the same positions
            # as sampling the list itself, preserving rng-state parity
            cons = [cons[j] for j in
                    rng.sample(range(len(cons)), max_consensus_number)]
        grp_cons[g] = cons

    # ---- build the spliced consensus sequences + (target, cons) pairs --
    # each pair tile sweeps <= rt reads of one target against one
    # consensus; tiles group by (off, rt) into fixed-shape GEMM batches
    cons_strs: list = []   # spliced full sequences, global ids
    grp_cons_base = np.zeros(G + 1, np.int64)
    for g in range(G):
        cons = grp_cons[g]
        grp_cons_base[g + 1] = grp_cons_base[g] + (len(cons) if cons else 0)
        if not cons:
            continue
        ref_start = int(t_ref_start[g])
        ref_end = int(t_ref_end[g])
        reference = ref_all[t_ref_off[g]:t_ref_off[g + 1]]
        for cs, cis, cie in cons:
            # Consensus.insert_into_reference (realign.py:612-620)
            if (cis < ref_start or cis > ref_end
                    or cie - 1 < ref_start or cie - 1 > ref_end):
                raise ValueError("consensus and reference do not overlap")
            cons_strs.append(
                reference[: cis - ref_start] + cs
                + reference[cie - 1 - ref_start:]
            )

    # flat result layout: per group, ci-major [nc, nr]
    grp_task_base = np.zeros(G + 1, np.int64)
    for g in range(G):
        nr = int(rg_off[g + 1] - rg_off[g])
        nc = int(grp_cons_base[g + 1] - grp_cons_base[g])
        grp_task_base[g + 1] = grp_task_base[g] + nr * nc
    NT = int(grp_task_base[G])
    res_q = np.full(NT, np.inf, np.float32)
    res_o = np.full(NT, -1, np.int32)
    if NT:
        cons_lens = np.array([len(s) for s in cons_strs], np.int64)
        max_cl = int(cons_lens.max()) if len(cons_strs) else 1
        cons_mat = np.full((len(cons_strs), max_cl), schema.BASE_PAD, np.uint8)
        for k, s in enumerate(cons_strs):
            cons_mat[k, : len(s)] = schema.encode_bases(s)

        # pair tiles: rt=16 for small targets, 128-read tiles for large
        p_res, p_n, p_cid, p_lo, p_off = [], [], [], [], []
        for g in range(G):
            cons = grp_cons[g]
            if not cons:
                continue
            nr = int(rg_off[g + 1] - rg_off[g])
            rl_g = lengths[r_row[rg_off[g]:rg_off[g + 1]]]
            for ci in range(len(cons)):
                cid = int(grp_cons_base[g]) + ci
                clen = int(cons_lens[cid])
                base = int(grp_task_base[g]) + ci * nr
                for lo in range(0, nr, 128):
                    nrt = min(128, nr - lo)
                    need = clen - int(rl_g[lo:lo + nrt].min())
                    p_res.append(base + lo)
                    p_n.append(nrt)
                    p_cid.append(cid)
                    p_lo.append(int(rg_off[g]) + lo)
                    p_off.append(max(need, 1))
        p_res = np.asarray(p_res, np.int64)
        p_n = np.asarray(p_n, np.int32)
        p_cid = np.asarray(p_cid, np.int64)
        p_lo = np.asarray(p_lo, np.int64)
        p_rt = np.where(p_n <= 16, 16, 128).astype(np.int32)
        p_offb = _pow2_vec(p_off, 512).astype(np.int64)
        # intermediate 384 tier: WGS-shaped targets need 250-330 offsets,
        # and the sweep's im2col+GEMM cost scales linearly with the
        # padded off — the pow2 jump to 512 wasted ~40% on that band
        p_offb = np.where(
            (p_offb == 512) & (np.asarray(p_off) <= 384), 384, p_offb
        )

        _phase("Realign: consensus + tiles")
        bases_np = np.asarray(b.bases)
        quals_np = np.asarray(b.quals)
        L = bases_np.shape[1]
        lr = int(_pow2_vec(np.array([max(int(lengths.max()), 1)]), 32)[0])
        cols = min(L, lr)

        # rows into the flat to_clean read index -> batch row, as i32
        r_row32 = r_row.astype(np.int32)
        pending = []  # (pair slice indices, device, lazy (best_q, best_o))
        # fan GEMM chunks across the pool/mesh devices (probe-paced
        # weighted round-robin, ROADMAP "realign sweep scheduling"):
        # until now every bucket dispatched to the default device while
        # the rest of the pool idled through the 1.31 s sweep net
        from adam_tpu.parallel.device_pool import putter as _putter

        _sched = None
        if sweep_devices is not None and len(sweep_devices) > 1:
            from adam_tpu.parallel.device_pool import SweepSchedule

            _sched = SweepSchedule(sweep_devices)
        key = p_offb * 1024 + p_rt
        border = np.argsort(key, kind="stable")
        ukeys, ustarts = np.unique(key[border], return_index=True)
        ustarts = np.append(ustarts, len(border))
        for u in range(len(ukeys)):
            seg = border[ustarts[u]:ustarts[u + 1]]
            off = int(ukeys[u] // 1024)
            rt = int(ukeys[u] % 1024)
            P = _sweep_gemm_P(off, rt)
            lc = off + lr
            for s in range(0, len(seg), P):
                part = seg[s:s + P]
                # chunk-local read block [P*rt, lr]: row j*rt+k is read k
                # of pair j — no device gather, and the compiled shape is
                # independent of the dataset size
                rc = np.full((P * rt, lr), schema.BASE_PAD, np.uint8)
                rq = np.zeros((P * rt, lr), np.uint8)
                rl = np.zeros(P * rt, np.int32)
                pm = np.zeros(P * rt, bool)
                ct = np.full((P, lc), schema.BASE_PAD, np.uint8)
                cl = np.zeros(P, np.int32)
                for j, pi in enumerate(part):
                    nrt = int(p_n[pi])
                    lo = int(p_lo[pi])
                    rows_t = r_row32[lo:lo + nrt]
                    rc[j * rt: j * rt + nrt, :cols] = bases_np[rows_t, :cols]
                    rq[j * rt: j * rt + nrt, :cols] = quals_np[rows_t, :cols]
                    rl[j * rt: j * rt + nrt] = lengths[rows_t]
                    pm[j * rt: j * rt + nrt] = True
                    cid = int(p_cid[pi])
                    cc = min(int(cons_lens[cid]), lc)
                    ct[j, :cc] = cons_mat[cid, :cc]
                    cl[j] = cons_lens[cid]
                dev = _sched.next_device() if _sched is not None else None
                _put = _putter(dev)  # commit + h2d transfer accounting
                pending.append((part, dev, sweep_gemm_kernel(
                    _put(rc), _put(rq), _put(rl),
                    _put(pm), _put(ct), _put(cl),
                    off, rt, lr,
                )))

        _phase("Realign: sweep dispatch (host assembly)")
        # host work hides under the device queue drain — genuinely
        # overlapped only when sweeps are actually in flight
        _overlap_once(in_dispatch=bool(pending))
        _phase("Realign: overlapped host work")
        if pending:
            # one fused fetch PER DEVICE: per-chunk fetches each pay a
            # tunnel round trip on the time-sliced chip, and chunks
            # committed to different pool devices cannot concatenate in
            # one computation — so each device's chunks fuse into one
            # drain through the transfer helper (d2h ledger + retry)
            from adam_tpu.utils.transfer import device_fetch as _dfetch

            groups: dict = {}
            for k, (_part, dev, _out) in enumerate(pending):
                gk = id(dev) if dev is not None else None
                groups.setdefault(gk, []).append(k)
            fetched_q: dict = {}
            fetched_o: dict = {}
            for idxs in groups.values():
                gq = np.asarray(_dfetch(jnp.concatenate(
                    [pending[k][2][0].reshape(-1) for k in idxs]
                )))
                go = np.asarray(_dfetch(jnp.concatenate(
                    [pending[k][2][1].reshape(-1) for k in idxs]
                )))
                pos = 0
                for k in idxs:
                    Pc, rtc = pending[k][2][0].shape
                    fetched_q[k] = gq[pos: pos + Pc * rtc].reshape(Pc, rtc)
                    fetched_o[k] = go[pos: pos + Pc * rtc].reshape(Pc, rtc)
                    pos += Pc * rtc
            for k, (part, _dev, _out) in enumerate(pending):
                q2, o2 = fetched_q[k], fetched_o[k]
                for j, pi in enumerate(part):
                    nrt = int(p_n[pi])
                    rb = int(p_res[pi])
                    res_q[rb:rb + nrt] = q2[j, :nrt]
                    res_o[rb:rb + nrt] = o2[j, :nrt]

    _phase("Realign: sweep fetch")
    _overlap_once()  # NT == 0: nothing was dispatched, run it here
    # ---- scoring + rewrite decisions (numpy, one pass per group) -------
    new_batch = jax.tree.map(np.array, b)
    new_md: dict[int, Optional[str]] = {}
    new_attrs: dict[int, str] = {}
    cmax = new_batch.cmax

    # realigned-read accumulators (one native MD-move call at the end)
    ra_rows, ra_g, ra_off, ra_head, ra_midl, ra_mido, ra_end = (
        [], [], [], [], [], [], [])
    ra_start, ra_newend = [], []
    realigned_mask = np.zeros(R, bool)

    for g in range(G):
        cons = grp_cons[g]
        if not cons:
            continue
        nr = int(rg_off[g + 1] - rg_off[g])
        nc = len(cons)
        sl = slice(int(grp_task_base[g]), int(grp_task_base[g + 1]))
        # ci-major flat -> [nr, nc]
        q = res_q[sl].reshape(nc, nr).T
        o = res_o[sl].reshape(nc, nr).T
        orig = r_orig[rg_off[g]:rg_off[g + 1]].astype(np.int64)
        pre_total = int(orig.sum())
        use = q < orig[:, None]
        qi = np.zeros_like(q, dtype=np.int64)
        qi[use] = q[use].astype(np.int64)
        contrib = np.where(use, qi, orig[:, None])
        totals = contrib.sum(axis=0)
        best_ci = int(nc - 1 - np.argmin(totals[::-1]))
        best_total = int(totals[best_ci])
        lod = (pre_total - best_total) / 10.0
        ref_start = int(t_ref_start[g])
        ref_len = int(t_ref_off[g + 1] - t_ref_off[g])
        _log.debug(
            "On target %d [%d, %d), before realignment, sum was %d; "
            "best consensus %d has sum %d (LOD %.2f)",
            int(gtid[g]), ref_start, ref_start + ref_len, pre_total,
            best_ci, best_total, lod,
        )
        if lod <= lod_threshold:
            continue
        cons_str, cis, cie = cons[best_ci]
        best_map = np.where(use[:, best_ci], o[:, best_ci], -1)
        okm = best_map >= 0
        if not okm.any():
            continue
        ridx = np.flatnonzero(okm) + int(rg_off[g])
        om = best_map[okm].astype(np.int64)
        rows_g = r_row[ridx]
        Lr = lengths[rows_g]
        new_start = ref_start + om
        if cis == cie - 1:  # insertion
            id_len = len(cons_str)
            id_op = ord("I")
            end_len = Lr - id_len - (cis - new_start)
            end_pen = -id_len
        else:  # deletion
            id_len = cie - 1 - cis
            id_op = ord("D")
            end_len = Lr - (cis - new_start)
            end_pen = len(cons_str)
        head_len = cis - new_start
        three = (head_len > 0) & (end_len > 0)
        new_end = np.where(three, new_start + Lr + end_pen, new_start + Lr)
        keep = om + (new_end - new_start) <= ref_len
        if not keep.any():
            continue
        k = np.flatnonzero(keep)
        realigned_mask[ridx[k]] = True
        ra_rows.append(rows_g[k])
        ra_g.append(np.full(len(k), g, np.int32))
        ra_off.append(om[k])
        ra_head.append(np.where(three[k], head_len[k], Lr[k]).astype(np.int32))
        ra_midl.append(np.where(three[k], id_len, 0).astype(np.int32))
        ra_mido.append(np.where(three[k], id_op, 0).astype(np.uint8))
        ra_end.append(np.where(three[k], end_len[k], 0).astype(np.int32))
        ra_start.append(new_start[k])
        ra_newend.append(new_end[k])

    # ---- write back: realigned rows ------------------------------------
    if ra_rows:
        rows_a = np.concatenate(ra_rows)
        g_a = np.concatenate(ra_g)
        off_a = np.concatenate(ra_off)
        head_a = np.concatenate(ra_head)
        midl_a = np.concatenate(ra_midl)
        mido_a = np.concatenate(ra_mido)
        end_a = np.concatenate(ra_end)
        start_a = np.concatenate(ra_start)
        newend_a = np.concatenate(ra_newend)
        moved = native.md_move_batch(
            b, rows_a, prep["t_ref_buf"], t_ref_off, g_a, off_a,
            head_a, midl_a, mido_a, end_a, start_a,
        )
        if moved is None:
            return None
        mbuf, moff = moved
        mstr = mbuf.tobytes().decode("ascii")

        three_a = mido_a != 0
        if three_a.any() and cmax < 3:
            raise ValueError("realigned cigar exceeds batch cmax")
        # OC/OP provenance from the pre-realignment columns
        oc = native.cigar_strings(
            np.asarray(b.cigar_ops)[rows_a],
            np.asarray(b.cigar_lens)[rows_a],
            np.asarray(b.cigar_n)[rows_a],
        )
        if oc is not None:
            oc_buf, oc_off = oc
            oc_all = oc_buf.tobytes().decode("ascii")
            old_cigs = [
                oc_all[oc_off[k]:oc_off[k + 1]] for k in range(len(rows_a))
            ]
        else:
            old_cigs = [
                schema.decode_cigar(
                    np.asarray(b.cigar_ops)[r], np.asarray(b.cigar_lens)[r],
                    int(np.asarray(b.cigar_n)[r]),
                )
                for r in rows_a
            ]
        attrs_col = StringColumn.of(side.attrs)
        old_starts = np.asarray(b.start)[rows_a]
        for k, row in enumerate(rows_a):
            row = int(row)
            tag = f"OC:Z:{old_cigs[k]}\tOP:i:{int(old_starts[k]) + 1}"
            cur = attrs_col[row] or ""
            new_attrs[row] = cur + "\t" + tag if cur else tag
            new_md[row] = mstr[moff[k]:moff[k + 1]]
        ops_new = np.zeros((len(rows_a), cmax), np.uint8)
        ops_new[:] = schema.CIGAR_PAD
        lens_new = np.zeros((len(rows_a), cmax), np.int32)
        ncig_new = np.where(three_a, 3, 1).astype(np.int32)
        ops_new[:, 0] = schema.CIGAR_M
        lens_new[:, 0] = head_a
        if three_a.any() and cmax >= 3:
            ops_new[three_a, 1] = np.where(
                mido_a[three_a] == ord("I"), schema.CIGAR_I, schema.CIGAR_D
            )
            lens_new[three_a, 1] = midl_a[three_a]
            ops_new[three_a, 2] = schema.CIGAR_M
            lens_new[three_a, 2] = end_a[three_a]
        new_batch.cigar_ops[rows_a] = ops_new
        new_batch.cigar_lens[rows_a] = lens_new
        new_batch.cigar_n[rows_a] = ncig_new
        new_batch.start[rows_a] = start_a
        new_batch.end[rows_a] = newend_a
        new_batch.mapq[rows_a] = np.asarray(b.mapq)[rows_a] + 10

    # ---- write back: dirty (left-normalized) non-realigned rows --------
    dirty_idx = np.flatnonzero(r_dirty & ~realigned_mask)
    if len(dirty_idx):
        cig_off = prep["r_cigar_off"]
        cig_all = prep["r_cigar_buf"].tobytes().decode("ascii")
        md_off2 = prep["r_md_off"]
        md_all = prep["r_md_buf"].tobytes().decode("ascii")
        for i in dirty_idx:
            row = int(r_row[i])
            cig = cig_all[cig_off[i]:cig_off[i + 1]]
            elems = parse_cigar(cig)
            ops, lens_, ncig = schema.encode_cigar(cig, max(cmax, len(elems)))
            if ncig > cmax:
                raise ValueError("realigned cigar exceeds batch cmax")
            new_batch.cigar_ops[row] = ops[:cmax]
            new_batch.cigar_lens[row] = lens_[:cmax]
            new_batch.cigar_n[row] = ncig
            new_batch.end[row] = int(new_batch.start[row]) + cigar_ref_len(
                elems
            )
            if r_md_set[i]:
                new_md[row] = md_all[md_off2[i]:md_off2[i + 1]]

    new_side = dc_replace(
        side,
        md=with_overrides(StringColumn.of(side.md), new_md),
        attrs=with_overrides(StringColumn.of(side.attrs), new_attrs),
    )
    _phase("Realign: decisions + rewrite")
    return ds.with_batch(new_batch, new_side)


def warm_sweep_shapes(offs=(384, 512, 1024, 2048, 4096), rts=(16, 128),
                      lr: int = 128):
    """Compile the GEMM sweep tiers ahead of a timed run.

    Shapes depend only on the static (off, rt, lr) tier — never on
    dataset size — so a handful of dummy dispatches covers everything a
    real run can hit (each missed shape costs 20-40s through the
    tunneled compile service).  The off tiers must span
    ``pow2(max_target_size + 2*read_len + max_indel_size)`` (~3700 under
    default knobs -> 4096); ``lr`` is ``pow2(max read length)`` of the
    data the timed run will see.  Returns the number of shapes warmed."""
    n = 0
    for off in offs:
        for rt in rts:
            P = _sweep_gemm_P(off, rt)
            lc = off + lr
            bq, _ = sweep_gemm_kernel(
                jnp.zeros((P * rt, lr), jnp.uint8),
                jnp.zeros((P * rt, lr), jnp.uint8),
                jnp.zeros(P * rt, jnp.int32),
                jnp.zeros(P * rt, bool),
                jnp.zeros((P, lc), jnp.uint8),
                jnp.zeros(P, jnp.int32),
                off, rt, lr,
            )
            jax.block_until_ready(bq)
            n += 1
    return n


def candidate_mask(b, targets, names) -> np.ndarray:
    """bool[N]: rows mapped to a realignment target — THE membership
    rule every pipeline's split/re-split/observe must share."""
    return map_batch_to_targets(b, targets, names) >= 0


def mask_out_candidates(ds, targets, names, mask=None):
    """Remainder view of a window/shard: candidate rows masked invalid
    (no keep-side copy; the Parquet encoder and the observe walk both
    filter on ``valid``).  Pass a cached ``mask`` to skip recomputing
    the target mapping."""
    b = ds.batch.to_numpy()
    if mask is None:
        mask = candidate_mask(b, targets, names)
    if not mask.any():
        return ds
    return ds.with_batch(b.replace(valid=np.asarray(b.valid) & ~mask))


def split_realign_candidates(ds, targets, names):
    """Split a window/shard into (candidates, writable remainder).

    Candidate rows (mapped to a realignment target) gather into a new
    dataset; the ~87%% keep-side majority is returned MASKED (valid
    cleared) rather than copied — the Parquet encoder's own row gather
    filters it once at write time.  Shared by the streamed and sharded
    pipelines so their split semantics cannot diverge.  Returns
    (candidates-or-None, remainder, n_remaining_valid)."""
    b = ds.batch.to_numpy()
    cand = candidate_mask(b, targets, names)
    if cand.any():
        candidates = ds.take_rows(np.flatnonzero(cand))
        ds = mask_out_candidates(ds, targets, names, mask=cand)
    else:
        candidates = None
    return candidates, ds, int(np.asarray(ds.batch.valid).sum())
