"""GATK-style local indel realignment.

Faithful semantics of the reference's ``rdd/read/realignment/`` +
``algorithms/consensus/`` packages, re-shaped for TPU:

1. **Target discovery** (RealignmentTargetFinder.scala:99-121,
   IndelRealignmentTarget.scala:108-143): every I/D CIGAR op (length <=
   maxIndelSize) yields a target (variation region, read span); targets
   sort by read span, merge while overlapping (variation hulls), dedupe
   on equal read spans (TreeSet semantics) and drop spans >
   maxTargetSize.  Here target extraction is a vectorized walk over the
   cigar columns.
2. **Read -> target mapping** (RealignIndels.mapToTarget:72-94): the
   reference's recursive set-halving search, including its exact pruning
   rule and the empty-target skew split ``-1 - start/3000``; vectorized
   so all reads binary-search simultaneously.
3. **Per-target realignment** (RealignIndels.realignTargetGroup:235-387):
   rebuild the reference from MD tags, left-normalize single-indel reads,
   take each indel read's alternate consensus (Consensus.scala:25-70),
   sweep every read over every consensus, accept the best consensus when
   the LOD improvement ((old-new)/10) beats the threshold, and rewrite
   start/CIGAR/MD (+10 mapq, OC/OP provenance tags).
4. The O(|reads| x |offsets| x |readLen|) **sweep**
   (sweepReadOverReferenceForQuality:399-417) is the hot loop: here it is
   one batched device kernel — mismatch-quality(b, o) = totalQual(b) -
   match-correlation(b, o), computed as a per-pair one-hot conv
   (MXU-shaped) over all (read, consensus) pairs of all targets at once.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, replace as dc_replace
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.models.snp_table import IndelTable
from adam_tpu.ops.mdtag import MdTag, parse_cigar

MAX_INDEL_SIZE = 500
MAX_CONSENSUS_NUMBER = 30
LOD_THRESHOLD = 5.0
MAX_TARGET_SIZE = 3000


# --------------------------------------------------------------------------
# CIGAR list helpers (host)
# --------------------------------------------------------------------------
def cigar_to_string(elems: list[tuple[int, str]]) -> str:
    return "".join(f"{n}{op}" for n, op in elems)


def cigar_read_len(elems) -> int:
    return sum(n for n, op in elems if op in "MIS=X")


def cigar_ref_len(elems) -> int:
    return sum(n for n, op in elems if op in "MDN=X")


def cigar_num_alignment_blocks(elems) -> int:
    return sum(1 for _, op in elems if op == "M")


def _cigar_total_len(elems) -> int:
    """Sum of ALL element lengths (RichCigar.getLength — includes D)."""
    return sum(n for n, _ in elems)


def move_cigar_left(elems: list[tuple[int, str]], index: int):
    """RichCigar.moveLeft semantics (rich/RichCigar.scala:140-186):
    trim one base from the element before ``index``, grow (or create, as
    1M) the element after it.  Replicates the reference's slicing,
    including dropping a 4th element when exactly 4 remain after the
    indel context."""
    if index == 0 or len(elems) < 2:
        return list(elems)
    head = list(elems[: index - 1])
    rest = list(elems[index - 1 :])
    trim = rest[0]
    move = rest[1] if len(rest) > 1 else None
    pad = rest[2] if len(rest) > 2 else None
    after_pad = rest[3:] if len(rest) > 4 else []
    out = list(head)
    if trim[0] > 1:
        out.append((trim[0] - 1, trim[1]))
    if move is not None:
        out.append(move)
    if pad is not None:
        out.append((pad[0] + 1, pad[1]))
    else:
        out.append((1, "M"))
    out += after_pad
    return out


def shift_indel(elems, position: int, shifts: int):
    """NormalizationUtils.shiftIndel (:142-153).

    The reference's well-formedness guard only compares total element
    length (RichCigar.isWellFormed:123-125 against the OLD total), so
    once the element before the indel is fully consumed, further moves
    start trimming the indel itself — the total can stay equal while the
    READ span (S+M+I) grows, and the reference then crashes in
    MdTag.moveAlignment on the out-of-range read index (a walk its
    suite never reaches; observed here on WGS-shaped data as an M span
    overrunning the read).  We additionally pin the read span AND the
    reference span, declining the corrupting move instead of
    reproducing the crash: a trimmed deletion changes the read span at
    constant total, while a trimmed insertion keeps both total and read
    span and silently erases the indel into M, growing the reference
    walk (tests: test_shift_indel_declines_read_length_corruption /
    _insertion_erasure)."""

    cur = list(elems)
    total = _cigar_total_len(cur)
    rlen = cigar_read_len(cur)
    reflen = cigar_ref_len(cur)
    while True:
        new = move_cigar_left(cur, position)
        if (
            shifts == 0
            or _cigar_total_len(new) != total
            or cigar_read_len(new) != rlen
            or cigar_ref_len(new) != reflen
        ):
            return cur
        cur = new
        shifts -= 1


def positions_to_shift(variant: str, preceding: str) -> int:
    """NormalizationUtils.numberOfPositionsToShiftIndel (:115-131)."""
    acc = 0
    v, p = variant, preceding
    while p and v and p[-1] == v[-1]:
        v = v[-1] + v[:-1]
        p = p[:-1]
        acc += 1
    return acc


def left_align_indel(seq: str, cigar: list, md: Optional[MdTag]):
    """NormalizationUtils.leftAlignIndel (:35-100): shift the single indel
    left through repeated sequence.  Returns a new cigar list."""
    indel_pos = -1
    indel_len = 0
    read_pos = ref_pos = 0
    is_insert = False
    for pos, (n, op) in enumerate(cigar):
        if op == "I":
            if indel_pos != -1:
                return list(cigar)
            indel_pos, indel_len, is_insert = pos, n, True
        elif op == "D":
            if indel_pos != -1:
                return list(cigar)
            indel_pos, indel_len = pos, n
        else:
            if indel_pos == -1:
                if op in "MIS=X":
                    read_pos += n
                if op in "MDN=X":
                    ref_pos += n
    if indel_pos == -1:
        return list(cigar)
    if is_insert:
        variant = seq[read_pos : read_pos + indel_len]
    else:
        if md is None:
            return list(cigar)
        ref = md.get_reference(seq, cigar_to_string(cigar))
        variant = ref[ref_pos : ref_pos + indel_len]
    preceding = seq[:read_pos]
    shift = positions_to_shift(variant, preceding)
    return shift_indel(cigar, indel_pos, shift)


# --------------------------------------------------------------------------
# Targets
# --------------------------------------------------------------------------
@dataclass
class RealignmentTarget:
    contig_idx: int
    var_start: int  # -1/-1 when no variation
    var_end: int
    range_start: int
    range_end: int

    @property
    def has_variation(self) -> bool:
        return self.var_start >= 0


def extract_indel_events(
    b, max_indel_size: int = MAX_INDEL_SIZE
) -> list[RealignmentTarget]:
    """Per-read I/D targets (IndelRealignmentTarget.apply), vectorized
    over the cigar columns."""
    n, C = b.cigar_ops.shape
    ops = np.asarray(b.cigar_ops)
    lens = np.asarray(b.cigar_lens).astype(np.int64)
    flags = np.asarray(b.flags)
    active = np.asarray(b.valid) & ((flags & schema.FLAG_UNMAPPED) == 0)
    ref_pos = np.asarray(b.start).astype(np.int64).copy()
    starts = np.asarray(b.start).astype(np.int64)
    ends = np.asarray(b.end).astype(np.int64)
    contigs = np.asarray(b.contig_idx)
    out = []
    for k in range(C):
        op = ops[:, k]
        ln = lens[:, k]
        ins = active & (op == schema.CIGAR_I) & (ln <= max_indel_size)
        dele = active & (op == schema.CIGAR_D) & (ln <= max_indel_size)
        for i in np.flatnonzero(ins):
            out.append(
                RealignmentTarget(int(contigs[i]), int(ref_pos[i]),
                                  int(ref_pos[i]) + 1, int(starts[i]), int(ends[i]))
            )
        for i in np.flatnonzero(dele):
            out.append(
                RealignmentTarget(int(contigs[i]), int(ref_pos[i]),
                                  int(ref_pos[i]) + int(ln[i]), int(starts[i]),
                                  int(ends[i]))
            )
        consumes_ref = np.isin(op, [schema.CIGAR_M, schema.CIGAR_D,
                                    schema.CIGAR_N, schema.CIGAR_EQ,
                                    schema.CIGAR_X])
        ref_pos += np.where(consumes_ref, ln, 0)
    return out


def _targets_overlap(a: RealignmentTarget, b: RealignmentTarget) -> bool:
    """TargetOrdering.overlap: either variation overlaps the other's span."""
    def ov(vs, ve, rs, re):
        return ve > rs and re > vs

    if a.contig_idx != b.contig_idx:
        return False
    return (a.has_variation and ov(a.var_start, a.var_end, b.range_start, b.range_end)) or (
        b.has_variation and ov(b.var_start, b.var_end, a.range_start, a.range_end)
    )


def find_targets(
    ds: AlignmentDataset,
    max_target_size: int = MAX_TARGET_SIZE,
    max_indel_size: int = MAX_INDEL_SIZE,
):
    """Sorted, merged, deduped target list."""
    b = ds.batch.to_numpy()
    events = extract_indel_events(b, max_indel_size)
    return merge_events(events, ds.seq_dict.names, max_target_size)


def resolve_tuning(
    max_indel_size=None, max_consensus_number=None,
    lod_threshold=None, max_target_size=None,
) -> tuple[int, int, float, int]:
    """None-coalesce the realignment tuning knobs against the module
    defaults (shared by the streamed/sharded/monolithic drivers)."""
    return (
        MAX_INDEL_SIZE if max_indel_size is None else max_indel_size,
        MAX_CONSENSUS_NUMBER if max_consensus_number is None
        else max_consensus_number,
        LOD_THRESHOLD if lod_threshold is None else lod_threshold,
        MAX_TARGET_SIZE if max_target_size is None else max_target_size,
    )


def merge_events(
    events: list[RealignmentTarget],
    names: list[str],
    max_target_size: int = MAX_TARGET_SIZE,
):
    """Sort + overlap-merge + dedupe per-read indel events into targets
    (the global barrier of the streamed/sharded paths: per-window event
    lists concatenate here, so targets spanning window or shard edges
    merge exactly as in the single-batch path)."""
    if not events:
        return []
    events = sorted(
        events, key=lambda t: (names[t.contig_idx], t.range_start, t.range_end)
    )
    merged: list[RealignmentTarget] = []
    for t in events:
        if merged and _targets_overlap(merged[-1], t):
            m = merged[-1]
            merged[-1] = RealignmentTarget(
                m.contig_idx,
                min(m.var_start, t.var_start) if m.has_variation and t.has_variation
                else (m.var_start if m.has_variation else t.var_start),
                max(m.var_end, t.var_end) if m.has_variation and t.has_variation
                else (m.var_end if m.has_variation else t.var_end),
                min(m.range_start, t.range_start),
                max(m.range_end, t.range_end),
            )
        elif merged and (
            merged[-1].contig_idx == t.contig_idx
            and merged[-1].range_start == t.range_start
            and merged[-1].range_end == t.range_end
        ):
            pass  # TreeSet equality on readRange: duplicate dropped
        else:
            merged.append(t)
    return [t for t in merged if t.range_end - t.range_start <= max_target_size]


def map_reads_to_targets(
    read_contig_rank, read_start, read_end, mapped_mask,
    target_rank, target_start, target_end,
) -> np.ndarray:
    """Vectorized replica of RealignIndels.mapToTarget's set-halving
    search (:72-94), including its pruning rule and the
    ``-1 - start/3000`` empty-target spreading."""
    n = len(read_start)
    nt = len(target_start)
    lo = np.zeros(n, dtype=np.int64)
    hi = np.full(n, nt, dtype=np.int64)
    while True:
        size = hi - lo
        if (size <= 1).all():
            break
        mult = size > 1
        mid = lo + size // 2
        m = np.clip(mid, 0, nt - 1)
        # lt(targets[mid], read): target orders before read (name,start,end)
        t_key_lt = (
            (target_rank[m] < read_contig_rank)
            | ((target_rank[m] == read_contig_rank) & (target_start[m] < read_start))
            | ((target_rank[m] == read_contig_rank) & (target_start[m] == read_start)
               & (target_end[m] < read_end))
        ) & mapped_mask
        hi = np.where(mult & t_key_lt, mid, hi)
        lo = np.where(mult & ~t_key_lt, mid, lo)
    t = np.clip(lo, 0, nt - 1)
    contains = (
        mapped_mask
        & (target_rank[t] == read_contig_rank)
        & (target_end[t] > read_start)
        & (read_end > target_start[t])
    )
    # Scala's `/` truncates toward zero, so the reference's unmapped
    # (start = -1) sentinel is -1 - 0 = -1; Python's floor division
    # would give -1 - (-1) = 0, a *valid* target index
    empty = np.where(
        read_start >= 0, -1 - read_start // 3000, -1
    ).astype(np.int64)
    return np.where(contains, t, empty)


def map_reads_to_targets_overlap(
    read_contig_rank, read_start, read_end, mapped_mask,
    target_rank, target_start, target_end,
) -> np.ndarray:
    """Interval mapping: each read goes to the *first target whose read
    range it overlaps* (GATK's IntervalListReferenceOrderedData walk).

    The reference's set-halving search (:func:`map_reads_to_targets`)
    keeps the *head* half when the probe orders before the read
    (RealignIndels.scala:87-91), so with more than one target most
    overlapping reads land on a non-overlapping probe and fall out of
    realignment entirely; its own suite only exercises single-target
    sets (RealignIndelsSuite.scala:54-55).  This mode restores the
    stated semantics; ``map_reads_to_targets`` remains for bit-parity.

    Vectorized: targets sorted by (rank, start); with a composite
    coordinate and a running max of target ends, the first overlapping
    target is one searchsorted (cummax is monotone) + one bounds check.
    """
    nt = len(target_start)
    n = len(read_start)
    if nt == 0:
        return np.where(
            read_start >= 0, -1 - read_start // 3000, -1
        ).astype(np.int64)
    BIG = np.int64(1) << 40
    t_s = target_rank * BIG + target_start
    t_e = target_rank * BIG + target_end
    order = np.argsort(t_s, kind="stable")
    t_s, t_e = t_s[order], t_e[order]
    cummax_e = np.maximum.accumulate(t_e)
    r_s = read_contig_rank * BIG + read_start
    r_e = read_contig_rank * BIG + read_end
    j = np.searchsorted(cummax_e, r_s, side="right")
    jc = np.clip(j, 0, nt - 1)
    contains = (
        mapped_mask & (j < nt) & (t_s[jc] < r_e) & (t_e[jc] > r_s)
    )
    # Scala's `/` truncates toward zero, so the reference's unmapped
    # (start = -1) sentinel is -1 - 0 = -1; Python's floor division
    # would give -1 - (-1) = 0, a *valid* target index
    empty = np.where(
        read_start >= 0, -1 - read_start // 3000, -1
    ).astype(np.int64)
    return np.where(contains, order[jc], empty)


def map_batch_to_targets(b, targets, names, mode: str = "overlap") -> np.ndarray:
    """Target index per row of a batch (-k spreading for unmatched rows).
    The candidate filter of the streamed/sharded paths: rows with
    tidx >= 0 are gathered for realignment, everything else passes
    through untouched.

    ``mode="overlap"`` (default) maps every read to the first target it
    overlaps; ``mode="faithful"`` replicates the reference's set-halving
    search bit-for-bit, quirks included (see
    :func:`map_reads_to_targets_overlap` for why they differ).
    """
    if not targets:
        return np.full(b.n_rows, -1, dtype=np.int64)
    rank_of_name = {nm: i for i, nm in enumerate(sorted(names))}
    contig_rank = np.array([rank_of_name[nm] for nm in names], dtype=np.int64)
    t_rank = np.array(
        [contig_rank[t.contig_idx] for t in targets], dtype=np.int64
    )
    t_start = np.array([t.range_start for t in targets], dtype=np.int64)
    t_end = np.array([t.range_end for t in targets], dtype=np.int64)
    flags = np.asarray(b.flags)
    mapped = ((flags & schema.FLAG_UNMAPPED) == 0) & np.asarray(b.valid)
    read_rank = np.where(
        mapped,
        contig_rank[np.clip(np.asarray(b.contig_idx), 0, len(names) - 1)],
        -1,
    )
    fn = (
        map_reads_to_targets_overlap
        if mode == "overlap"
        else map_reads_to_targets
    )
    return fn(
        read_rank, np.asarray(b.start).astype(np.int64),
        np.asarray(b.end).astype(np.int64), mapped, t_rank, t_start, t_end,
    )


# --------------------------------------------------------------------------
# Batched sweep kernel (device)
# --------------------------------------------------------------------------
def _pow2(n: int, minimum: int) -> int:
    return max(minimum, 1 << (max(int(n), 1) - 1).bit_length())


def sweep_bucket_shape(read_len: int, cons_len: int) -> tuple[int, int]:
    """Padded (lr, lc) bucket for one (read, consensus) sweep task.

    The kernel yields ``lc - lr + 1`` offsets but the reference sweeps
    offsets ``o < cons_len - read_len``; when ``lr`` rounds up past
    ``read_len`` the consensus bucket must absorb the padding
    (``lc >= cons_len + lr - read_len``) or tail offsets are silently
    lost (e.g. read_len=100 -> lr=128 with cons_len=250 needs lc=512,
    not 256, to represent offsets 129..149)."""
    lr = _pow2(read_len, 32)
    lc = _pow2(max(cons_len + (lr - read_len), lr + 1), 64)
    return lr, lc


@partial(jax.jit, static_argnames=("lr", "lc"))
def sweep_kernel_gather(read_codes, read_quals, read_len, cons_tbl,
                        clen_tbl, cons_idx, lr: int, lc: int):
    """Sweep with a deduplicated consensus table.

    A chunk's tasks reference each consensus once per read in its group,
    so shipping the [CH, lc] consensus rows per-task re-sends every byte
    group-size times over the ~20 MB/s device tunnel.  Instead the
    unique consensus rows travel once and the per-task rows are gathered
    ON DEVICE from the table.
    """
    return sweep_kernel(
        read_codes, read_quals, read_len,
        cons_tbl[cons_idx], clen_tbl[cons_idx], lr, lc,
    )


@partial(jax.jit, static_argnames=("lr", "lc"))
def sweep_kernel(read_codes, read_quals, read_len, cons_codes, cons_len,
                 lr: int, lc: int):
    """For each (read, consensus) pair: mismatch quality at every offset.

    mismatchQual(b, o) = sum_i q_i [read_i != cons_{o+i}]
                       = totalQual(b) - sum_i q_i [read_i == cons_{o+i}]
    with the match-correlation computed as a one-hot conv per pair.
    Valid offsets o in [0, cons_len - read_len) (the reference's
    exclusive sweep loop).  Returns (best_qual i32[B], best_offset i32[B])
    with the smallest offset winning ties; best_offset = -1 when no valid
    offset exists.
    """
    B = read_codes.shape[0]
    in_read = jnp.arange(lr)[None, :] < read_len[:, None]
    q = jnp.where(in_read, read_quals, 0).astype(jnp.float32)
    total_q = q.sum(axis=1)
    # one-hot over the 6 codes (N==N matches, PAD never matches quals=0)
    read_oh = jax.nn.one_hot(read_codes, 6, dtype=jnp.float32) * q[..., None]
    cons_oh = jax.nn.one_hot(cons_codes, 6, dtype=jnp.float32)

    def corr(x, w):
        # x: [lc, 6] one-hot consensus; w: [lr, 6] qual-weighted read
        return jax.lax.conv_general_dilated(
            x[None], w[:, :, None],
            window_strides=(1,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"),
        )[0, :, 0]

    match = jax.vmap(corr)(cons_oh, read_oh)  # [B, lc - lr + 1]
    mismatch = total_q[:, None] - match
    n_off = lc - lr + 1
    offs = jnp.arange(n_off)[None, :]
    valid = offs < (cons_len - read_len)[:, None]  # exclusive upper bound
    masked = jnp.where(valid, mismatch, jnp.inf)
    best_off = jnp.argmin(masked, axis=1).astype(jnp.int32)
    best_q = jnp.min(masked, axis=1)
    has_any = valid.any(axis=1)
    return (
        jnp.where(has_any, best_q, jnp.inf),
        jnp.where(has_any, best_off, -1),
    )


def _sum_mismatch_quality(seq: str, ref: str, quals) -> int:
    """sumMismatchQualityIgnoreCigar: positional zip, truncating to the
    shorter string (RealignIndels.scala:429-440) — vectorized byte
    compare instead of a per-char generator."""
    n = min(len(seq), len(ref), len(quals))
    if n == 0:
        return 0
    a = np.frombuffer(seq.encode("ascii"), np.uint8, n)
    b = np.frombuffer(ref.encode("ascii"), np.uint8, n)
    q = np.asarray(quals[:n], np.int64)
    return int(q[a != b].sum())


# --------------------------------------------------------------------------
# Per-target realignment (host orchestration)
# --------------------------------------------------------------------------
@dataclass
class _Read:
    """Host view of one read under realignment.

    ``md`` is parsed lazily — only reads whose CIGAR is not a single M
    run need it (left-alignment, reference slices through indels); for
    the pure-M majority the precomputed ``ref`` string (from the
    vectorized MD tokenizer) and per-row mismatch-qual sums replace all
    per-read MD work.  ``dirty`` marks reads whose alignment changed in
    preprocessing (left-align / SW), which must be written back even
    when the consensus pass leaves them alone.
    """

    row: int
    seq: str
    quals: np.ndarray
    start: int
    cigar: list  # [(len, op)]
    md: Optional[MdTag]
    mapq: int
    ref: Optional[str] = None  # implied reference over the aligned span
    pure: bool = False  # single-M CIGAR
    dirty: bool = False
    codes: Optional[np.ndarray] = None  # base codes (sweep input, cached)

    @property
    def end(self) -> int:
        return self.start + cigar_ref_len(self.cigar)


def _get_reference_from_reads(reads: list[_Read], extra_refs=()):
    """RealignIndels.getReferenceFromReads (:185-215).

    ``extra_refs`` carries (ref, start, end) tuples for reads that exist
    only as columnar rows (the pure clean majority never materialized as
    ``_Read`` objects); they splice into the window exactly as reads do.
    """
    refs = list(extra_refs)
    for r in reads:
        ref = r.ref
        if ref is None and r.md is not None:  # directly-built _Reads
            ref = r.md.get_reference(r.seq, cigar_to_string(r.cigar))
        if ref is not None:
            refs.append((ref, r.start, r.end))
    if not refs:
        raise ValueError("no reads with MD tags in target group")
    refs.sort(key=lambda x: x[1])
    ref, cur = "", refs[0][1]
    ref_start = refs[0][1]
    for s, start, end in refs:
        if end < cur:
            continue
        if cur >= start:
            ref += s[cur - start :]
            cur = end
        else:
            raise ValueError(f"gap at {cur} with {start},{end} rebuilding reference")
    return ref, ref_start, cur


@dataclass(frozen=True)
class Consensus:
    """models/Consensus.scala: an alternate allele to splice into the
    reference — insertion when index spans 1bp."""

    consensus: str
    contig_idx: int
    index_start: int
    index_end: int

    def insert_into_reference(self, reference: str, ref_start: int, ref_end: int) -> str:
        if (self.index_start < ref_start or self.index_start > ref_end
                or self.index_end - 1 < ref_start or self.index_end - 1 > ref_end):
            raise ValueError("consensus and reference do not overlap")
        return (
            reference[: self.index_start - ref_start]
            + self.consensus
            + reference[self.index_end - 1 - ref_start :]
        )


def generate_alternate_consensus(seq: str, start: int, contig_idx: int,
                                 cigar: list) -> Optional[Consensus]:
    """Consensus.generateAlternateConsensus (:25-52)."""
    read_pos = 0
    ref_pos = start
    if sum(1 for _, op in cigar if op in "ID") != 1:
        return None
    for n, op in cigar:
        if op == "I":
            return Consensus(seq[read_pos : read_pos + n], contig_idx,
                             ref_pos, ref_pos + 1)
        if op == "D":
            return Consensus("", contig_idx, ref_pos, ref_pos + n + 1)
        if op in "M=X":
            read_pos += n
            ref_pos += n
        else:
            return None
    return None


def realign_indels(
    ds: AlignmentDataset,
    consensus_model: str = "reads",
    known_indels: Optional[IndelTable] = None,
    max_indel_size: int = MAX_INDEL_SIZE,
    max_consensus_number: int = MAX_CONSENSUS_NUMBER,
    lod_threshold: float = LOD_THRESHOLD,
    max_target_size: int = MAX_TARGET_SIZE,
    sw_weights: tuple = (1.0, -0.333, -0.5, -0.5),
    rng: Optional[random.Random] = None,
    target_mapping: str = "overlap",
) -> AlignmentDataset:
    b = ds.batch.to_numpy()
    n = b.n_rows
    if n == 0:
        return ds
    targets = find_targets(ds, max_target_size, max_indel_size)
    if not targets:
        return ds
    names = ds.seq_dict.names
    flags = np.asarray(b.flags)
    mapped = ((flags & schema.FLAG_UNMAPPED) == 0) & np.asarray(b.valid)
    tidx = map_batch_to_targets(b, targets, names, mode=target_mapping)

    # group rows by target, position-sorted within the group (the
    # reference sorts the RDD before target mapping) — vectorized:
    # lexsort then split at target boundaries, no per-read python loop
    sel = np.flatnonzero(mapped & (tidx >= 0))
    groups: dict[int, list[int]] = {}
    if len(sel):
        order = np.lexsort(
            (sel, np.asarray(b.start)[sel].astype(np.int64), tidx[sel])
        )
        srows = sel[order]
        stid = tidx[srows]
        bounds = np.flatnonzero(np.diff(stid) != 0) + 1
        for chunk in np.split(srows, bounds):
            groups[int(tidx[chunk[0]])] = [int(i) for i in chunk]

    new_batch = jax.tree.map(np.array, b)  # writable copies
    side = ds.sidecar

    # vectorized per-row MD columns (one native tokenize, no per-read
    # parse): mismatch mask -> to_clean membership + positional orig-qual
    # sums; ref codes -> implied reference for every single-M read
    from adam_tpu.ops.mdtag import batch_md_arrays

    is_mm, ref_codes, has_md_vec = batch_md_arrays(
        ds.batch, side, need_ref_codes=True
    )
    row_has_mm = is_mm.any(axis=1)
    mm_qual = np.where(is_mm, np.asarray(b.quals), 0).sum(axis=1)
    # sparse overrides: only realigned rows get new MD/attrs — the full
    # sidecar is never materialized as python strings (8M reads would
    # cost ~30s just in string churn)
    new_md: dict[int, Optional[str]] = {}
    new_attrs: dict[int, str] = {}
    rng = rng or random.Random(0)

    # ---- phase 1 (host): per group, rebuild reference + consensuses ----
    # bulk per-row precomputation over all grouped rows (one LUT/decode
    # pass instead of a numpy-call per read — the single-core host is the
    # pipeline's scarce resource)
    all_rows = np.concatenate([np.asarray(r) for r in groups.values()]) if groups else np.zeros(0, np.int64)
    seq_of: dict[int, str] = {}
    ref_of: dict[int, str] = {}
    if len(all_rows):
        purev = (
            (np.asarray(b.cigar_n)[all_rows] == 1)
            & (np.asarray(b.cigar_ops)[all_rows, 0] == schema.CIGAR_M)
            & has_md_vec[all_rows]
        )
        prows = all_rows[purev]
        if len(prows):
            ref_of = dict(
                zip(
                    (int(i) for i in prows),
                    schema.decode_bases_bulk(
                        ref_codes[prows], np.asarray(b.lengths)[prows]
                    ),
                )
            )
        # sequences are only needed for rows that materialize a _Read —
        # the pure clean majority (in ref_of, no mismatches) is skipped
        # by the light path below and never decodes
        heavy = all_rows[~(purev & ~row_has_mm[all_rows])]
        if len(heavy):
            seq_of = dict(
                zip(
                    (int(i) for i in heavy),
                    schema.decode_bases_bulk(
                        np.asarray(b.bases)[heavy],
                        np.asarray(b.lengths)[heavy],
                    ),
                )
            )
    _CC = schema.CIGAR_CHARS

    group_ctx = {}
    res_q: dict[int, np.ndarray] = {}  # per target: [n_reads, n_cons]
    res_o: dict[int, np.ndarray] = {}

    # ---- phase 2 machinery, interleaved with phase 1 ------------------
    # tasks are grouped into power-of-two (read, consensus) length
    # buckets so a single max_target_size consensus doesn't inflate
    # every (read x consensus) pair in the batch, and each bucket
    # flushes to the device in FIXED-size chunks (one compiled shape per
    # (lr, lc) bucket — a data-dependent batch dim compiled a fresh
    # kernel per size, 20-40s each through the tunneled compile
    # service).  Chunks dispatch asynchronously *while phase 1 is still
    # building later groups* (quals travel as u8; the kernel widens on
    # device); results stay on device and one fetch pass drains them
    # after the last flush — the chip sweeps target k's pairs while the
    # single-core host rebuilds target k+1's reference.
    CH = 8192   # tasks per dispatch (fixed -> one compiled shape/bucket)
    # consensus slots: large enough that dense data (tasks-per-consensus
    # = group size >= 4) never flushes early on the cons trigger, small
    # enough that the always-full-size table transfer stays ~1 MB
    NC = 2048
    _buckets: dict[tuple[int, int], dict] = {}
    _pending = []  # (chunk tasks, device (best_q, best_o))
    _remaining: dict[int, int] = {}  # target -> sweep results outstanding

    def _flush_bucket(key) -> None:
        lr, lc = key
        st = _buckets.pop(key)
        tasks = st["tasks"]
        # two shape tiers per bucket: small flushes (residuals, small
        # inputs) use a 1024-task shape so a near-empty chunk doesn't
        # pay the full 8192-row compute on slow backends; both tiers
        # stay fixed so the compile-shape set is bounded at two
        ch = CH if len(tasks) > 1024 else 1024
        nc = NC if ch == CH else 1024
        rc = np.full((ch, lr), schema.BASE_PAD, np.uint8)
        rq = np.zeros((ch, lr), np.uint8)
        rl = np.zeros(ch, np.int32)
        ct = np.full((nc, lc), schema.BASE_PAD, np.uint8)
        cl = np.zeros(nc, np.int32)
        for s, codes in enumerate(st["cons"]):
            ct[s, : len(codes)] = codes
            cl[s] = len(codes)
        cidx = np.zeros(ch, np.int32)
        for k, (_t, _ri, _ci, r, cs) in enumerate(tasks):
            rc[k, : len(r.codes)] = r.codes
            rq[k, : len(r.quals)] = r.quals
            rl[k] = len(r.codes)
            cidx[k] = cs
        # padded task rows gather consensus slot 0 and are never read back
        _pending.append((tasks, sweep_kernel_gather(
            jnp.asarray(rc), jnp.asarray(rq), jnp.asarray(rl),
            jnp.asarray(ct), jnp.asarray(cl), jnp.asarray(cidx), lr, lc,
        )))

    def _enqueue_sweep(task) -> None:
        t, ri, ci, r, cons_codes = task
        key = sweep_bucket_shape(len(r.codes), len(cons_codes))
        st = _buckets.get(key)
        if st is None:
            st = _buckets[key] = {"tasks": [], "cmap": {}, "cons": []}
        cs = st["cmap"].get(id(cons_codes))
        if cs is None:
            cs = len(st["cons"])
            st["cmap"][id(cons_codes)] = cs
            st["cons"].append(cons_codes)
        st["tasks"].append((t, ri, ci, r, cs))
        if len(st["tasks"]) >= CH or len(st["cons"]) >= NC:
            _flush_bucket(key)
    for t, rows in groups.items():
        reads = []
        extra_refs = []
        for i in rows:
            if i in ref_of and not row_has_mm[i]:
                # pure clean majority: never swept, never rewritten —
                # contributes only its reference slice to the window
                # rebuild, so no _Read is materialized at all
                s0 = int(b.start[i])
                extra_refs.append((ref_of[i], s0, s0 + int(b.lengths[i])))
                continue
            L = int(b.lengths[i])
            seq = seq_of[i]
            nc = int(b.cigar_n[i])
            cig = [
                (int(b.cigar_lens[i, k]), _CC[b.cigar_ops[i, k]])
                for k in range(nc)
            ]
            pure = nc == 1 and b.cigar_ops[i, 0] == schema.CIGAR_M
            has_md_i = bool(has_md_vec[i])
            if pure or not has_md_i:
                md = None  # pure-M rows never need a parsed MdTag
            else:
                md = MdTag.parse(side.md[i], int(b.start[i]))
            if not has_md_i:
                ref = None
            elif pure:
                ref = ref_of[i]
            else:
                ref = md.get_reference(seq, cig)
            reads.append(
                _Read(
                    row=i,
                    seq=seq,
                    quals=np.asarray(b.quals[i][:L], np.int32),
                    start=int(b.start[i]),
                    cigar=cig,
                    md=md,
                    mapq=int(b.mapq[i]),
                    ref=ref,
                    pure=pure,
                    codes=np.asarray(b.bases[i][:L]),
                )
            )
        # reads that already match the reference pass through untouched
        to_clean = [
            r for r in reads if not has_md_vec[r.row] or row_has_mm[r.row]
        ]
        if not to_clean:
            continue
        try:
            reference, ref_start, ref_end = _get_reference_from_reads(
                reads, extra_refs
            )
        except ValueError:
            continue
        contig_idx = targets[t].contig_idx

        # preprocess: left-normalize single-indel reads (and SW-realign
        # everything first under the smithwaterman model)
        if consensus_model == "smithwaterman":
            to_clean = _sw_preprocess(
                to_clean, reference, ref_start, sw_weights
            )
        processed = []
        for r in to_clean:
            if cigar_num_alignment_blocks(r.cigar) == 2:
                new_cigar = left_align_indel(r.seq, r.cigar, r.md)
                if new_cigar != r.cigar:
                    md = MdTag.move_alignment(
                        r.ref, r.seq, cigar_to_string(new_cigar), r.start,
                    ) if r.md is not None else None
                    processed.append(
                        dc_replace(r, cigar=new_cigar, md=md, dirty=True)
                    )
                else:
                    processed.append(r)
            else:
                processed.append(r)
        to_clean = processed

        # find consensuses
        consensuses: list[Consensus] = []
        if consensus_model == "knowns" and known_indels is not None:
            region_name = names[contig_idx]
            from adam_tpu.models.positions import ReferenceRegion

            for rec in known_indels.get_indels_in_region(
                ReferenceRegion(region_name, ref_start, ref_end)
            ):
                consensuses.append(
                    Consensus(rec.consensus, contig_idx,
                              rec.region.start, rec.region.end)
                )
        else:
            for r in to_clean:
                if r.md is None:
                    continue
                c = generate_alternate_consensus(
                    r.seq, r.start, contig_idx, r.cigar
                )
                if c is not None:
                    consensuses.append(c)
        # distinct
        seen = set()
        uniq = []
        for c in consensuses:
            key = (c.consensus, c.index_start, c.index_end)
            if key not in seen:
                seen.add(key)
                uniq.append(c)
        consensuses = uniq
        if len(consensuses) > max_consensus_number:
            consensuses = rng.sample(consensuses, max_consensus_number)
        if not consensuses:
            # still keep preprocessing results (readsToClean ++ realigned)
            _write_back(new_batch, side, new_md, new_attrs, to_clean, realigned={})
            continue

        group_ctx[t] = (to_clean, consensuses, reference, ref_start, ref_end)
        res_q[t] = np.full(
            (len(to_clean), len(consensuses)), np.inf, np.float32
        )
        res_o[t] = np.full((len(to_clean), len(consensuses)), -1, np.int32)
        _remaining[t] = len(to_clean) * len(consensuses)
        for ci, c in enumerate(consensuses):
            cons_seq = c.insert_into_reference(reference, ref_start, ref_end)
            cons_codes = schema.encode_bases(cons_seq)  # once per consensus
            for ri, r in enumerate(to_clean):
                _enqueue_sweep((t, ri, ci, r, cons_codes))

    del seq_of, ref_of  # decoded strings live only through phase 1

    # ---- phase 2 drain + phase 3, interleaved ----
    # flush residual chunks, then finish each target the moment its last
    # sweep result lands — the host rewrites completed targets (phase 3)
    # while the device is still computing later chunks, instead of
    # blocking through the whole fetch tail first.  Targets write to
    # disjoint rows, so completion order doesn't affect the output.
    for key in list(_buckets):
        if _buckets[key]["tasks"]:
            _flush_bucket(key)

    def _finish_target(t: int) -> None:
        to_clean, consensuses, reference, ref_start, ref_end = group_ctx[t]

        def _orig_qual(r):
            if r.dirty and r.md is not None:
                return _sum_mismatch_quality(
                    r.seq,
                    r.md.get_reference(r.seq, cigar_to_string(r.cigar)),
                    r.quals,
                )
            if r.pure:  # positional mismatch-qual sum, precomputed
                return int(mm_qual[r.row])
            return _sum_mismatch_quality(r.seq, r.ref or "", r.quals)

        orig_quals = [_orig_qual(r) for r in to_clean]
        pre_total = sum(orig_quals)
        # vectorized consensus scoring over the [n_reads, n_cons] sweep
        # result arrays: per cell take min(sweep, orig) (sweep value
        # truncated to int, as the reference's Int sum does), column
        # totals, best = min with the LATER consensus winning ties
        # (the reference's list-prepend + left fold)
        q = res_q[t]
        o = res_o[t]
        orig = np.asarray(orig_quals, np.int64)
        use = q < orig[:, None]
        qi = np.zeros_like(q, dtype=np.int64)
        qi[use] = q[use].astype(np.int64)
        contrib = np.where(use, qi, orig[:, None])
        totals = contrib.sum(axis=0)
        nc = len(consensuses)
        best_ci = int(nc - 1 - np.argmin(totals[::-1]))
        best_total = int(totals[best_ci])
        best_map = np.where(use[:, best_ci], o[:, best_ci], -1)
        lod = (pre_total - best_total) / 10.0
        # per-target decision logs, the RealignIndels.scala:317-379 trail
        _log = logging.getLogger(__name__)
        _log.debug(
            "On target %d [%d, %d), before realignment, sum was %d; "
            "best consensus %d has sum %d (LOD %.2f)",
            t, ref_start, ref_start + len(reference), pre_total,
            best_ci, best_total, lod,
        )
        realigned = {}
        if lod > lod_threshold:
            cons = consensuses[best_ci]
            for ri, r in enumerate(to_clean):
                o = best_map[ri]
                if o == -1:
                    continue
                new_start = ref_start + o
                if cons.index_start == cons.index_end - 1:  # insertion
                    id_elem = (len(cons.consensus), "I")
                    end_len = len(r.seq) - len(cons.consensus) - (cons.index_start - new_start)
                    end_penalty = -len(cons.consensus)
                else:  # deletion
                    id_elem = (cons.index_end - 1 - cons.index_start, "D")
                    end_len = len(r.seq) - (cons.index_start - new_start)
                    end_penalty = len(cons.consensus)
                head_len = cons.index_start - new_start
                if head_len > 0 and end_len > 0:
                    new_cigar = [(head_len, "M"), id_elem, (end_len, "M")]
                    new_end = new_start + len(r.seq) + end_penalty
                else:
                    # the swept position doesn't span the consensus indel
                    # (read entirely before/after it): a plain gapless
                    # alignment at the new offset.  The reference emits a
                    # negative-length M here (RealignIndels.scala:344-360,
                    # never hit by its single-target suite) — an invalid
                    # CIGAR we decline to reproduce.
                    new_cigar = [(len(r.seq), "M")]
                    new_end = new_start + len(r.seq)
                # a swept offset near the region edge can consume more
                # reference than the rebuilt window holds (insertion
                # consensuses are longer than the reference, so valid
                # consensus offsets can overrun it — another walk the
                # reference leaves unguarded): leave the read unrealigned
                if o + (new_end - new_start) > len(reference):
                    continue
                md = MdTag.move_alignment(
                    reference[o:], r.seq, cigar_to_string(new_cigar), new_start
                )
                realigned[ri] = dc_replace(
                    r, start=new_start, cigar=new_cigar, md=md, mapq=r.mapq + 10
                ), new_end
        _write_back(new_batch, side, new_md, new_attrs, to_clean, realigned)

    for chunk, out in _pending:
        best_q, best_o = jax.tree.map(np.asarray, out)
        for k, (t, ri, ci, _, _) in enumerate(chunk):
            res_q[t][ri, ci] = best_q[k]
            res_o[t][ri, ci] = best_o[k]
            _remaining[t] -= 1
            if _remaining[t] == 0:
                _finish_target(t)

    from adam_tpu.formats.strings import StringColumn, with_overrides

    new_side = dc_replace(
        side,
        md=with_overrides(StringColumn.of(side.md), new_md),
        attrs=with_overrides(StringColumn.of(side.attrs), new_attrs),
    )
    return ds.with_batch(new_batch, new_side)


def _sw_preprocess(reads, reference, ref_start, weights):
    """ConsensusGeneratorFromSmithWaterman.preprocessReadsForRealignment
    (:40-70): SW-align each read against the region; accept when <= 2
    alignment blocks, rewriting start/cigar/MD (start from the
    reference's own xStart+regionStart rule)."""
    from adam_tpu.ops.smith_waterman import smith_waterman

    out = []
    w_match, w_mismatch, w_insert, w_delete = weights
    for r in reads:
        aln = smith_waterman(r.seq, reference, w_match, w_mismatch,
                             w_insert, w_delete)
        cigar = parse_cigar(aln.cigar_x)
        if cigar_num_alignment_blocks(cigar) <= 2:
            md = MdTag.from_alignment(
                r.seq, reference[aln.x_start :], aln.cigar_x, ref_start
            )
            out.append(
                dc_replace(r, start=aln.x_start + ref_start, cigar=cigar,
                           md=md, dirty=True)
            )
        else:
            out.append(r)
    return out


def _write_back(new_batch, side, new_md, new_attrs, to_clean, realigned):
    """Apply (possibly realigned) host reads back into the batch.

    MD/attr updates land in the sparse ``new_md``/``new_attrs`` override
    dicts (row -> str), merged into the sidecar columns in one pass at
    the end of realign_indels."""
    cmax = new_batch.cmax
    for ri, r in enumerate(to_clean):
        if ri in realigned:
            rr, new_end = realigned[ri]
            old_start = int(new_batch.start[rr.row])
            old_cigar = schema.decode_cigar(
                new_batch.cigar_ops[rr.row], new_batch.cigar_lens[rr.row],
                int(new_batch.cigar_n[rr.row]),
            )
            tag = f"OC:Z:{old_cigar}\tOP:i:{old_start + 1}"
            cur = new_attrs.get(rr.row, side.attrs[rr.row]) or ""
            new_attrs[rr.row] = cur + "\t" + tag if cur else tag
        elif not r.dirty:
            continue  # alignment untouched: nothing to write
        else:
            rr, new_end = r, None
        cig = cigar_to_string(rr.cigar)
        ops, lens, ncig = schema.encode_cigar(cig, max(cmax, len(rr.cigar)))
        if ncig > cmax:
            raise ValueError("realigned cigar exceeds batch cmax")
        new_batch.cigar_ops[rr.row] = ops[:cmax]
        new_batch.cigar_lens[rr.row] = lens[:cmax]
        new_batch.cigar_n[rr.row] = ncig
        new_batch.start[rr.row] = rr.start
        new_batch.mapq[rr.row] = rr.mapq
        if new_end is not None:
            new_batch.end[rr.row] = new_end
        else:
            new_batch.end[rr.row] = rr.end
        if rr.md is not None:
            new_md[rr.row] = rr.md.to_string()
