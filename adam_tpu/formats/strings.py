"""Columnar string storage for host-side sidecar fields.

The reference carries read names / attribute strings / MD tags as fields
on per-read Avro objects.  Keeping a Python ``str`` per read makes every
whole-dataset operation O(N) interpreter work, so the sidecar's native
representation here is **one flat byte buffer + offsets** (the Arrow
string layout): list-like for compatibility (``col[i]`` -> str/None),
but convertible for free to numpy views and pyarrow arrays for
vectorized consumers.

``None``-ability (the reference's null fields, e.g. absent MD tags) is a
validity bitmask, as in Arrow.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Union

import numpy as np

StringLike = Union["StringColumn", Sequence[Optional[str]]]


class StringColumn:
    """Immutable column of optional strings as (buffer, offsets, validity)."""

    __slots__ = ("buf", "offsets", "valid")

    def __init__(self, buf: np.ndarray, offsets: np.ndarray,
                 valid: Optional[np.ndarray] = None):
        self.buf = np.asarray(buf, dtype=np.uint8)
        self.offsets = np.asarray(offsets, dtype=np.int64)
        n = len(self.offsets) - 1
        self.valid = (
            np.ones(n, dtype=bool) if valid is None else np.asarray(valid, bool)
        )

    # ------------------------------------------------------------ builders
    @staticmethod
    def from_list(items: Iterable[Optional[str]]) -> "StringColumn":
        items = list(items)
        valid = np.array([s is not None for s in items], dtype=bool)
        bufs = [s.encode() if isinstance(s, str) else b"" for s in items]
        offsets = np.zeros(len(items) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in bufs], out=offsets[1:])
        buf = (
            np.frombuffer(b"".join(bufs), dtype=np.uint8)
            if offsets[-1]
            else np.zeros(0, np.uint8)
        )
        return StringColumn(buf, offsets, valid)

    @staticmethod
    def of(value: StringLike) -> "StringColumn":
        if isinstance(value, StringColumn):
            return value
        return StringColumn.from_list(value)

    @staticmethod
    def full(n: int, value: Optional[str] = None) -> "StringColumn":
        if value is None:
            return StringColumn(
                np.zeros(0, np.uint8), np.zeros(n + 1, np.int64),
                np.zeros(n, bool),
            )
        b = value.encode()
        offsets = np.arange(n + 1, dtype=np.int64) * len(b)
        return StringColumn(np.frombuffer(b * n, np.uint8).copy(), offsets)

    # ---------------------------------------------------------- list compat
    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i):
        if isinstance(i, slice):
            return self.take(np.arange(len(self))[i])
        i = int(i)
        if i < 0:
            i += len(self)
        if not self.valid[i]:
            return None
        return (
            self.buf[self.offsets[i]:self.offsets[i + 1]]
            .tobytes()
            .decode("utf-8", "replace")
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __eq__(self, other):
        if isinstance(other, (StringColumn, list, tuple)):
            return len(self) == len(other) and all(
                a == b for a, b in zip(self, other)
            )
        return NotImplemented

    def __repr__(self):
        head = ", ".join(repr(self[i]) for i in range(min(3, len(self))))
        return f"StringColumn([{head}{'...' if len(self) > 3 else ''}], n={len(self)})"

    def to_list(self) -> list:
        return list(self)

    # ------------------------------------------------------------- kernels
    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets)

    def take(self, idx) -> "StringColumn":
        idx = np.asarray(idx, dtype=np.int64)
        lens = np.diff(self.offsets)[idx]
        new_off = np.zeros(len(idx) + 1, dtype=np.int64)
        np.cumsum(lens, out=new_off[1:])
        total = int(new_off[-1])
        if len(idx):
            starts = self.offsets[idx]
            from adam_tpu import native

            out = native.span_gather(self.buf, starts, lens, total)
            if out is None:
                # fallback: flat index build (vectorized, no per-row Python)
                out = np.empty(total, dtype=np.uint8)
                out[:] = self.buf[_span_gather_indices(starts, lens)]
        else:
            out = np.empty(0, dtype=np.uint8)
        return StringColumn(out, new_off, self.valid[idx])

    @staticmethod
    def concat(cols: Sequence["StringColumn"]) -> "StringColumn":
        cols = [StringColumn.of(c) for c in cols]
        if not cols:
            return StringColumn.full(0)
        bufs = [c.buf for c in cols]
        n = sum(len(c) for c in cols)
        offsets = np.zeros(n + 1, dtype=np.int64)
        lens = np.concatenate([c.lengths() for c in cols])
        np.cumsum(lens, out=offsets[1:])
        return StringColumn(
            np.concatenate(bufs) if bufs else np.zeros(0, np.uint8),
            offsets,
            np.concatenate([c.valid for c in cols]),
        )

    def to_fixed_bytes(self) -> np.ndarray:
        """-> S{maxlen} numpy array (for np.unique-style exact grouping)."""
        n = len(self)
        lens = self.lengths()
        w = max(1, int(lens.max()) if n else 1)
        if n and self.offsets[-1]:
            from adam_tpu import native

            mat = native.span_gather_strided(
                self.buf, self.offsets[:-1], lens, w
            )
            if mat is not None:
                return mat.view(f"S{w}").ravel()
        mat = np.zeros((n, w), dtype=np.uint8)
        if n and self.offsets[-1]:
            flat = _span_gather_indices(self.offsets[:-1], lens)
            rows = np.repeat(np.arange(n), lens)
            pos = _span_local_positions(lens)
            mat[rows, pos] = self.buf[flat]
        return mat.view(f"S{w}").ravel()

    def unique_inverse(self) -> tuple[np.ndarray, np.ndarray]:
        """-> (unique S-array, inverse i64[N]) — exact, C-speed."""
        u, inv = np.unique(self.to_fixed_bytes(), return_inverse=True)
        return u, inv

    @staticmethod
    def from_matrix(mat: np.ndarray, lens: np.ndarray,
                    valid: Optional[np.ndarray] = None) -> "StringColumn":
        """Build from a padded byte matrix [N, W] + per-row lengths."""
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        lens = np.asarray(lens, dtype=np.int64)
        n, w = mat.shape
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        if n and (lens == w).all():
            # uniform full-width rows: the buffer IS the matrix
            return StringColumn(mat.reshape(-1).copy(), offsets, valid)
        mask = np.arange(w)[None, :] < lens[:, None]
        buf = mat[mask]  # row-major: concatenated row prefixes, in order
        return StringColumn(buf, offsets, valid)

    @staticmethod
    def where(cond: np.ndarray, a: "StringColumn",
              b: "StringColumn") -> "StringColumn":
        """Per-row select: rows with cond True from ``a``, else ``b``."""
        cond = np.asarray(cond, bool)
        la, lb = a.lengths(), b.lengths()
        lens = np.where(cond, la, lb)
        valid = np.where(cond, a.valid, b.valid)
        n = len(cond)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        out = np.empty(int(offsets[-1]), dtype=np.uint8)
        for col, rows in ((a, np.flatnonzero(cond)),
                          (b, np.flatnonzero(~cond))):
            if len(rows) == 0:
                continue
            rl = col.lengths()[rows]
            src = _span_gather_indices(col.offsets[rows], rl)
            dst = _span_gather_indices(offsets[rows], rl)
            out[dst] = col.buf[src]
        return StringColumn(out, offsets, valid)

    def to_arrow(self):
        """Zero-copy conversion to a pyarrow string array (py_buffer
        wraps the numpy memory and holds a reference — no tobytes copy,
        which cost a full buffer duplication per fat column)."""
        import pyarrow as pa

        n = len(self)
        if self.valid.all():
            validity = None
        else:
            validity = pa.array(self.valid).buffers()[1]
        return pa.Array.from_buffers(
            pa.large_string(),
            n,
            [
                validity,
                pa.py_buffer(np.ascontiguousarray(self.offsets)),
                pa.py_buffer(np.ascontiguousarray(self.buf)),
            ],
        )

    @staticmethod
    def from_arrow(arr) -> "StringColumn":
        """pyarrow string/large_string array -> StringColumn."""
        import pyarrow as pa
        import pyarrow.compute as pc

        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        valid = np.asarray(pc.is_valid(arr))
        arr = pc.cast(arr, pa.large_string())
        if arr.offset != 0:
            arr = pa.concat_arrays([arr])  # re-materialize at offset 0
        buffers = arr.buffers()
        offsets = np.frombuffer(buffers[1], dtype=np.int64,
                                count=len(arr) + 1).copy()
        data = (
            np.frombuffer(buffers[2], dtype=np.uint8).copy()
            if buffers[2] is not None
            else np.zeros(0, np.uint8)
        )
        base = offsets[0]
        return StringColumn(data[base:offsets[-1]], offsets - base, valid)


def _span_gather_indices(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Flat source indices covering [starts[i], starts[i]+lens[i]) per row."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    nz = lens > 0
    u = np.unique(lens[nz])
    if len(u) == 1:
        # uniform span width (the common case for fixed-length reads):
        # one broadcasted add instead of repeat+cumsum index machinery
        w = int(u[0])
        return (
            starts[nz][:, None] + np.arange(w, dtype=np.int64)[None, :]
        ).ravel()
    # index = repeat(starts) + (arange within each span)
    out = np.repeat(starts, lens)
    out += _span_local_positions(lens)
    return out


def _span_local_positions(lens: np.ndarray) -> np.ndarray:
    """0,1,..,lens[0]-1, 0,1,..,lens[1]-1, ... as one flat array."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    flat_starts = np.concatenate([[0], np.cumsum(lens[:-1])])
    return np.arange(total, dtype=np.int64) - np.repeat(flat_starts, lens)


def with_overrides(col: "StringColumn", overrides: dict) -> "StringColumn":
    """Replace a sparse set of rows ({row: str|None}) in one vectorized
    pass — the whole column is never materialized as python strings."""
    if not overrides:
        return col
    n = len(col)
    idx = np.fromiter(sorted(overrides), np.int64, len(overrides))
    vals = [overrides[int(i)] for i in idx]
    enc = [v.encode("utf-8") if v is not None else b"" for v in vals]
    lens = np.zeros(n, np.int64)
    lens[idx] = [len(e) for e in enc]
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    buf = np.frombuffer(b"".join(enc), np.uint8)
    valid = col.valid.copy()
    valid[idx] = [v is not None for v in vals]
    repl = StringColumn(buf, offsets, valid)
    mask = np.zeros(n, bool)
    mask[idx] = True
    return StringColumn.where(mask, repl, col)
