"""Columnar variant and genotype batches — the variation data model.

The reference's variation types are Avro records (``Variant``,
``Genotype``, ``VariantCallingAnnotations`` from bdg-formats; aggregated
as ``models/VariantContext.scala``). Here, as with reads
(:mod:`adam_tpu.formats.batch`), the unit is a struct-of-arrays batch:

* :class:`VariantBatch` — device-friendly coordinate/size columns plus a
  host :class:`VariantSidecar` for allele strings, ids, filters, and INFO
  annotations (the VariantCallingAnnotations analog).
* :class:`GenotypeBatch` — one row per (variant, sample) call, carrying
  the ``GenotypeAllele`` pair, depths, quality, and the phred likelihood
  triple; ``variant_idx`` joins back to the VariantBatch row.

Sites are ALWAYS bi-allelic rows: multi-allelic VCF records are split at
ingest with per-allele genotype punch-out, the invariant the reference
establishes in ``converters/VariantContextConverter.convert``
(VariantContextConverter.scala:95-175).

All device columns are fixed width so genotype kernels (allele counting,
quality RMS, Hardy-Weinberg style aggregations) are single vectorized
reductions or ``segment_sum`` calls over the batch.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

import numpy as np

# GenotypeAllele enum codes (order of the bdg-formats GenotypeAllele
# enum referenced at VariantContextConverter.scala:54-63)
ALLELE_REF = 0
ALLELE_ALT = 1
ALLELE_OTHER_ALT = 2
ALLELE_NO_CALL = 3

PL_MISSING = -1


@dataclass
class VariantSidecar:
    """Host-only variable-width columns for a VariantBatch."""

    ref_allele: list = field(default_factory=list)  # str per row
    alt_allele: list = field(default_factory=list)  # str or None (gVCF ref block)
    names: list = field(default_factory=list)  # rs id / VCF ID ('' if '.')
    filters: list = field(default_factory=list)  # list[str] per row ([] = PASS/unfiltered)
    info: list = field(default_factory=list)  # dict per row (INFO annotations)

    def take(self, idx) -> "VariantSidecar":
        idx = np.asarray(idx)
        return VariantSidecar(
            [self.ref_allele[i] for i in idx],
            [self.alt_allele[i] for i in idx],
            [self.names[i] for i in idx],
            [self.filters[i] for i in idx],
            [self.info[i] for i in idx],
        )


@dataclass
class VariantBatch:
    """Bi-allelic variant sites as columnar arrays (Variant record parity:
    contig/start/end/referenceAllele/alternateAllele, the fields set at
    VariantContextConverter.scala:197-206)."""

    contig_idx: np.ndarray  # i32[N], index into SequenceDictionary
    start: np.ndarray  # i64[N], 0-based
    end: np.ndarray  # i64[N], exclusive (start + len(ref))
    ref_len: np.ndarray  # i32[N]
    alt_len: np.ndarray  # i32[N], 0 when alt is None (reference model row)
    qual: np.ndarray  # f32[N], phred-scaled site quality (QUAL; nan if '.')
    filters_applied: np.ndarray  # bool[N]
    passing: np.ndarray  # bool[N] (meaningful when filters_applied)
    sidecar: VariantSidecar = field(default_factory=VariantSidecar)

    def __len__(self):
        return len(self.start)

    @property
    def is_snp(self) -> np.ndarray:
        return (self.ref_len == 1) & (self.alt_len == 1)

    @property
    def is_indel(self) -> np.ndarray:
        return (self.alt_len > 0) & (self.ref_len != self.alt_len)

    def take(self, idx) -> "VariantBatch":
        idx = np.asarray(idx)
        return VariantBatch(
            self.contig_idx[idx], self.start[idx], self.end[idx],
            self.ref_len[idx], self.alt_len[idx], self.qual[idx],
            self.filters_applied[idx], self.passing[idx],
            self.sidecar.take(idx),
        )

    def variant_keys(self, contig_names) -> np.ndarray:
        """Stable join key per site: (contig, start, ref, alt) — the keyBy
        used by joinDatabaseVariantAnnotation and toVariantContext
        (VariationRDDFunctions.scala:55,144)."""
        return np.array(
            [
                f"{contig_names[c]}:{s}:{r}:{a or ''}"
                for c, s, r, a in zip(
                    self.contig_idx, self.start,
                    self.sidecar.ref_allele, self.sidecar.alt_allele,
                )
            ]
        )


@dataclass
class GenotypeBatch:
    """Per-sample calls, one row per (variant, sample).

    Field parity with the Genotype extraction at
    VariantContextConverter.scala:217-245: alleles pair, GQ, DP, AD
    (ref/alt split), phasing, genotype likelihood triple, non-reference
    likelihood triple (gVCF reference model), and the
    splitFromMultiAllelic marker (:166-168).
    """

    variant_idx: np.ndarray  # i32[M] row in the VariantBatch
    sample_idx: np.ndarray  # i32[M] index into `samples`
    alleles: np.ndarray  # i8[M, 2] of ALLELE_* codes
    gq: np.ndarray  # i16[M], -1 missing
    dp: np.ndarray  # i32[M], -1 missing
    ref_depth: np.ndarray  # i32[M], -1 missing (AD[0])
    alt_depth: np.ndarray  # i32[M], -1 missing (AD[1])
    phased: np.ndarray  # bool[M]
    pl: np.ndarray  # i32[M, 3], PL_MISSING where absent
    nonref_pl: np.ndarray  # i32[M, 3], gVCF <NON_REF> likelihoods
    split_from_multiallelic: np.ndarray  # bool[M]
    samples: list = field(default_factory=list)  # sample names
    genotype_filters: list = field(default_factory=list)  # str per row (FT)

    def __len__(self):
        return len(self.variant_idx)

    def take(self, idx) -> "GenotypeBatch":
        idx = np.asarray(idx)
        return replace(
            self,
            variant_idx=self.variant_idx[idx],
            sample_idx=self.sample_idx[idx],
            alleles=self.alleles[idx],
            gq=self.gq[idx],
            dp=self.dp[idx],
            ref_depth=self.ref_depth[idx],
            alt_depth=self.alt_depth[idx],
            phased=self.phased[idx],
            pl=self.pl[idx],
            nonref_pl=self.nonref_pl[idx],
            split_from_multiallelic=self.split_from_multiallelic[idx],
            genotype_filters=[self.genotype_filters[i] for i in idx],
        )


# ------------------------------------------------------------------ stats

def rms_doubles(values: np.ndarray) -> float:
    """Root mean square (GenotypesToVariantsConverter.rms, :32-38)."""
    v = np.asarray(values, np.float64)
    return float(np.sqrt(np.mean(v**2))) if v.size else 0.0


def rms_phred(phreds: np.ndarray) -> int:
    """RMS over phred scores via success-probability space
    (GenotypesToVariantsConverter.rms(Seq[Int]), :46-52)."""
    p = np.asarray(phreds, np.float64)
    if p.size == 0:
        return 0
    succ = 1.0 - 10.0 ** (-p / 10.0)
    r = rms_doubles(succ)
    err = max(1.0 - r, 1e-300)
    return int(round(-10.0 * np.log10(err)))


def variant_quality_from_genotypes(genotype_probs: np.ndarray) -> float:
    """P(at least one variant) = 1 - prod(1 - Pg)
    (GenotypesToVariantsConverter.variantQualityFromGenotypes, :69-70)."""
    v = np.asarray(genotype_probs, np.float64)
    return float(1.0 - np.prod(v))


def allele_counts(
    variants: VariantBatch, genotypes: GenotypeBatch, contig_names
):
    """Observed allele counts per site: for every called allele, Ref maps
    to the reference allele string, Alt to the alternate; OtherAlt/NoCall
    are dropped (AlleleCountHelper.chooseAllele semantics,
    adam-cli AlleleCount.scala:46-64).

    Returns a list of (contig_name, position, allele, count) sorted by
    position then allele.
    """
    vi = np.repeat(genotypes.variant_idx, 2)
    codes = genotypes.alleles.reshape(-1)
    keep = (codes == ALLELE_REF) | (codes == ALLELE_ALT)
    vi, codes = vi[keep], codes[keep]
    out: dict = {}
    side = variants.sidecar
    for v, c in zip(vi, codes):
        allele = side.ref_allele[v] if c == ALLELE_REF else side.alt_allele[v]
        if allele is None:
            continue
        key = (
            contig_names[variants.contig_idx[v]],
            int(variants.start[v]),
            allele,
        )
        out[key] = out.get(key, 0) + 1
    return sorted((k[0], k[1], k[2], n) for k, n in out.items())
