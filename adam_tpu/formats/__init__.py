from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch

__all__ = ["schema", "ReadBatch"]
