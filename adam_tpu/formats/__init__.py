from adam_tpu.formats import features, schema, variants
from adam_tpu.formats.batch import ReadBatch
from adam_tpu.formats.variants import GenotypeBatch, VariantBatch

__all__ = ["features", "schema", "variants", "ReadBatch", "VariantBatch", "GenotypeBatch"]
