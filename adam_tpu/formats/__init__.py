from adam_tpu.formats import schema, variants
from adam_tpu.formats.batch import ReadBatch
from adam_tpu.formats.variants import GenotypeBatch, VariantBatch

__all__ = ["schema", "variants", "ReadBatch", "VariantBatch", "GenotypeBatch"]
