"""Schema constants for the columnar genomic data model.

The reference stores one Avro record per read (``AlignmentRecord`` from
bdg-formats; field list mirrored at
``/root/reference/adam-core/src/main/scala/org/bdgenomics/adam/projections/AlignmentRecordField.scala:29-31``).
We keep the same logical fields but lay them out as struct-of-arrays
columnar batches (see :mod:`adam_tpu.formats.batch`), with the string-ish
fields (bases, quals, CIGAR) encoded as small integers so they live on
device.

Encodings defined here:

* SAM flag bits (identical to the SAM spec the reference's boolean fields
  decompose into).
* 2-3 bit base codes (A,C,G,T,N + PAD) used everywhere on device.
* CIGAR op codes in htsjdk/SAM order (M,I,D,N,S,H,P,=,X).
"""

from __future__ import annotations

import numpy as np

# --------------------------------------------------------------------------
# SAM flag bits.  The reference explodes these into booleans on
# AlignmentRecord (readPaired, properPair, readMapped, ... — see
# converters/SAMRecordConverter.scala:64-101); we keep the packed u16 form
# as a single device column and provide mask helpers.
# --------------------------------------------------------------------------
FLAG_PAIRED = 0x1
FLAG_PROPER_PAIR = 0x2
FLAG_UNMAPPED = 0x4
FLAG_MATE_UNMAPPED = 0x8
FLAG_REVERSE = 0x10
FLAG_MATE_REVERSE = 0x20
FLAG_FIRST_OF_PAIR = 0x40
FLAG_SECOND_OF_PAIR = 0x80
FLAG_SECONDARY = 0x100
FLAG_FAILED_QC = 0x200
FLAG_DUPLICATE = 0x400
FLAG_SUPPLEMENTARY = 0x800

# --------------------------------------------------------------------------
# Base codes.  Dense 0..3 for ACGT makes 2-bit k-mer packing and one-hot
# matmuls trivial; 4 = N/any-ambiguity; 5 = padding beyond read length.
# --------------------------------------------------------------------------
BASE_A = 0
BASE_C = 1
BASE_G = 2
BASE_T = 3
BASE_N = 4
BASE_PAD = 5

_BASE_CHARS = "ACGTN"

# char -> code lookup over the whole byte range (unknown IUPAC codes -> N).
BASE_ENCODE_LUT = np.full(256, BASE_N, dtype=np.uint8)
for _i, _c in enumerate(_BASE_CHARS):
    BASE_ENCODE_LUT[ord(_c)] = _i
    BASE_ENCODE_LUT[ord(_c.lower())] = _i
BASE_ENCODE_LUT[ord("*")] = BASE_PAD

BASE_DECODE_LUT = np.frombuffer(b"ACGTN.", dtype=np.uint8).copy()

# Complement in code space (N -> N, PAD -> PAD).
BASE_COMPLEMENT = np.array(
    [BASE_T, BASE_G, BASE_C, BASE_A, BASE_N, BASE_PAD], dtype=np.uint8
)

QUAL_PAD = 255  # quality value used in padding lanes
SANGER_OFFSET = 33  # phred+33, util/PhredUtils.scala semantics

# Full-byte-range decode LUTs for the native fused decode+compact pass
# (native.lut_compact_rows): code -> ASCII base, qual -> clamped Sanger
# char ('~' = phred 93 cap, the SAM printable ceiling).
BASE_DECODE_LUT256 = BASE_DECODE_LUT[np.minimum(np.arange(256), BASE_PAD)]
QUAL_SANGER_LUT256 = (
    np.minimum(np.arange(256), 93) + SANGER_OFFSET
).astype(np.uint8)


def encode_bases(seq: str | bytes) -> np.ndarray:
    """ASCII sequence -> u8 code array."""
    if isinstance(seq, str):
        seq = seq.encode("ascii")
    return BASE_ENCODE_LUT[np.frombuffer(seq, dtype=np.uint8)]


def decode_bases(codes: np.ndarray, length: int | None = None) -> str:
    codes = np.asarray(codes, dtype=np.uint8)
    if length is not None:
        codes = codes[:length]
    return BASE_DECODE_LUT[np.minimum(codes, BASE_PAD)].tobytes().decode("ascii")


def decode_bases_bulk(codes: np.ndarray, lengths: np.ndarray) -> list[str]:
    """Decode many rows at once: one LUT pass over the [N, L] code matrix,
    one bytes->str decode, then per-row string slicing — ~20x cheaper than
    N ``decode_bases`` calls (each of which pays numpy-call overhead)."""
    codes = np.asarray(codes, dtype=np.uint8)
    if codes.ndim != 2:
        codes = codes.reshape(len(lengths), -1)
    L = codes.shape[1]
    s = BASE_DECODE_LUT[np.minimum(codes, BASE_PAD)].tobytes().decode("ascii")
    return [s[i * L : i * L + int(l)] for i, l in enumerate(lengths)]


def encode_quals(qual: str | bytes) -> np.ndarray:
    """Sanger phred+33 string -> u8 phred values."""
    if isinstance(qual, str):
        qual = qual.encode("ascii")
    return np.frombuffer(qual, dtype=np.uint8) - SANGER_OFFSET


def decode_quals(phred: np.ndarray, length: int | None = None) -> str:
    phred = np.asarray(phred)
    if length is not None:
        phred = phred[:length]
    return (phred.astype(np.uint8) + SANGER_OFFSET).tobytes().decode("ascii")


# --------------------------------------------------------------------------
# CIGAR op codes (SAM binary order, same as htsjdk CigarOperator ordinals
# the reference leans on via rich/RichAlignmentRecord.scala:41-57).
# --------------------------------------------------------------------------
CIGAR_M = 0
CIGAR_I = 1
CIGAR_D = 2
CIGAR_N = 3
CIGAR_S = 4
CIGAR_H = 5
CIGAR_P = 6
CIGAR_EQ = 7
CIGAR_X = 8
CIGAR_PAD = 15  # padding lanes in the [N, Cmax] cigar columns

CIGAR_CHARS = "MIDNSHP=X"
CIGAR_ENCODE = {c: i for i, c in enumerate(CIGAR_CHARS)}

# Op consumes query sequence / reference, as lookup tables over op code.
CIGAR_CONSUMES_QUERY = np.array(
    [1, 1, 0, 0, 1, 0, 0, 1, 1] + [0] * 7, dtype=np.int32
)
CIGAR_CONSUMES_REF = np.array(
    [1, 0, 1, 1, 0, 0, 0, 1, 1] + [0] * 7, dtype=np.int32
)


def encode_cigar(cigar: str, cmax: int) -> tuple[np.ndarray, np.ndarray, int]:
    """CIGAR string -> (ops u8[cmax], lens i32[cmax], n_ops).

    '*' (unavailable) -> zero ops.
    """
    ops = np.full(cmax, CIGAR_PAD, dtype=np.uint8)
    lens = np.zeros(cmax, dtype=np.int32)
    if not cigar or cigar == "*":
        return ops, lens, 0
    n = 0
    num = 0
    for ch in cigar:
        if ch.isdigit():
            num = num * 10 + ord(ch) - 48
        else:
            if n >= cmax:
                raise ValueError(f"CIGAR {cigar!r} exceeds cmax={cmax}")
            ops[n] = CIGAR_ENCODE[ch]
            lens[n] = num
            num = 0
            n += 1
    return ops, lens, n


def decode_cigar(ops: np.ndarray, lens: np.ndarray, n: int) -> str:
    if n == 0:
        return "*"
    return "".join(f"{int(lens[i])}{CIGAR_CHARS[int(ops[i])]}" for i in range(n))


def cigar_str_stats(cigar: str) -> tuple[int, int]:
    """(query_length, reference_length) spanned by a CIGAR string."""
    qlen = rlen = num = 0
    for ch in cigar:
        if ch.isdigit():
            num = num * 10 + ord(ch) - 48
        else:
            op = CIGAR_ENCODE[ch]
            qlen += num * int(CIGAR_CONSUMES_QUERY[op])
            rlen += num * int(CIGAR_CONSUMES_REF[op])
            num = 0
    return qlen, rlen
