"""Projection field enumerations per record type.

Mirrors the reference's ``projections/FieldEnumeration.scala:49-61`` and
the per-type enums (``AlignmentRecordField.scala:29-31``,
``GenotypeField.scala``, ``VariantField.scala``, ``FeatureField.scala``,
``NucleotideContigFragmentField.scala``): a named, validated set of
storage-schema fields per record type, used to push column projection
into the Parquet reads (``io/parquet.py`` ``projection=`` arguments).

Here the enums are plain frozensets of the Parquet column names the
columnar stores actually write; ``validate_projection`` raises on
unknown names so a typo fails loudly at the API boundary rather than
silently reading everything.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

# io/parquet.py to_arrow_alignments column set (AlignmentRecord fields)
ALIGNMENT_FIELDS = frozenset({
    "readName", "sequence", "qual", "flags", "contig", "start", "end",
    "mapq", "cigar", "mateContig", "mateAlignmentStart",
    "inferredInsertSize", "recordGroupName", "attributes",
    "mismatchingPositions", "origQual", "basesTrimmedFromStart",
    "basesTrimmedFromEnd",
})

# save_genotypes variants.parquet columns (VariantField + annotations)
VARIANT_FIELDS = frozenset({
    "contig", "start", "end", "referenceAllele", "alternateAllele",
    "name", "filters", "annotations", "qual", "filtersApplied",
    "filtersPassed", "variantIdx",
})

# save_genotypes genotypes.parquet columns (GenotypeField)
GENOTYPE_FIELDS = frozenset({
    "variantIdx", "sampleId", "allele0", "allele1", "genotypeQuality",
    "readDepth", "referenceReadDepth", "alternateReadDepth", "isPhased",
    "genotypeLikelihoods", "nonReferenceLikelihoods",
    "splitFromMultiAllelic", "genotypeFilters",
})

# save_features columns (FeatureField)
FEATURE_FIELDS = frozenset({
    "contig", "start", "end", "strand", "score", "featureId",
    "featureType", "source", "parentIds", "attributes",
})

# save_fragments columns (NucleotideContigFragmentField)
FRAGMENT_FIELDS = frozenset({
    "contig", "description", "fragmentSequence", "fragmentStartPosition",
    "fragmentNumber", "numberOfFragmentsInContig",
})


def validate_projection(
    projection: Optional[Sequence[str]],
    allowed: Iterable[str],
    essential: Iterable[str],
    what: str,
) -> Optional[list[str]]:
    """-> sorted column list (projection + essentials), or None for all.

    Unknown field names raise ValueError, as the reference's enum-typed
    ``Projection(...)`` constructor makes impossible by construction."""
    if projection is None:
        return None
    allowed = set(allowed)
    bad = sorted(set(projection) - allowed)
    if bad:
        raise ValueError(
            f"unknown {what} projection field(s) {bad}; "
            f"valid: {sorted(allowed)}"
        )
    return sorted(set(projection) | set(essential))
