"""Columnar reference-fragment batches.

The reference chops contigs into fixed-length ``NucleotideContigFragment``
records (default 10 kbp — rdd/ADAMContext.scala:443-456,
converters/FastaConverter.scala:133-185) so a genome becomes a distributed
dataset like any other.  :class:`FragmentBatch` is the columnar analog: one
row per fragment, fixed padded width, device-resident — the natural shard
unit for the genome axis of the mesh, with halo (flank) exchange between
neighbors for windowed ops (FlankReferenceFragments.scala:26-70).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.formats import schema

Array = Any


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class FragmentBatch:
    bases: Array        # u8[N, F] base codes, BASE_PAD beyond length
    lengths: Array      # i32[N]
    contig_idx: Array   # i32[N]
    start: Array        # i64[N]  fragment start on contig
    fragment_number: Array  # i32[N]
    num_fragments: Array    # i32[N] total fragments in contig
    valid: Array        # bool[N]

    @property
    def n_rows(self) -> int:
        return int(self.bases.shape[0])

    @property
    def fmax(self) -> int:
        return int(self.bases.shape[1])

    def replace(self, **kw) -> "FragmentBatch":
        return dataclasses.replace(self, **kw)

    def take(self, idx) -> "FragmentBatch":
        return jax.tree.map(lambda x: jnp.asarray(x)[idx], self)

    def to_numpy(self) -> "FragmentBatch":
        return jax.tree.map(np.asarray, self)

    @staticmethod
    def from_sequences(
        seqs: Sequence[tuple[int, str]],
        fragment_length: int = 10_000,
    ) -> "FragmentBatch":
        """(contig_idx, sequence) pairs -> fragment rows."""
        rows = []
        for contig_idx, seq in seqs:
            nfrag = max(1, -(-len(seq) // fragment_length))
            for k in range(nfrag):
                chunk = seq[k * fragment_length : (k + 1) * fragment_length]
                rows.append((contig_idx, k * fragment_length, k, nfrag, chunk))
        n = len(rows)
        fmax = max((len(r[4]) for r in rows), default=1)
        out = FragmentBatch(
            bases=np.full((n, fmax), schema.BASE_PAD, np.uint8),
            lengths=np.zeros(n, np.int32),
            contig_idx=np.zeros(n, np.int32),
            start=np.zeros(n, np.int64),
            fragment_number=np.zeros(n, np.int32),
            num_fragments=np.zeros(n, np.int32),
            valid=np.ones(n, bool),
        )
        for i, (c, s, k, nf, chunk) in enumerate(rows):
            out.bases[i, : len(chunk)] = schema.encode_bases(chunk)
            out.lengths[i] = len(chunk)
            out.contig_idx[i] = c
            out.start[i] = s
            out.fragment_number[i] = k
            out.num_fragments[i] = nf
        return out

    def extract_region(self, contig_idx: int, start: int, end: int) -> str:
        """Reconstruct [start, end) on a contig from fragments
        (adamGetReferenceString semantics, NucleotideContigFragmentRDDFunctions.scala:61)."""
        b = self.to_numpy()
        pieces = []
        for i in np.argsort(np.asarray(b.start), kind="stable"):
            if not b.valid[i] or int(b.contig_idx[i]) != contig_idx:
                continue
            fs = int(b.start[i])
            fe = fs + int(b.lengths[i])
            lo, hi = max(fs, start), min(fe, end)
            if lo < hi:
                pieces.append(
                    schema.decode_bases(b.bases[i][lo - fs : hi - fs])
                )
        got = "".join(pieces)
        if len(got) != end - start:
            raise KeyError(
                f"region {contig_idx}:{start}-{end} not fully covered by fragments"
            )
        return got
