"""Columnar reference-fragment batches.

The reference chops contigs into fixed-length ``NucleotideContigFragment``
records (default 10 kbp — rdd/ADAMContext.scala:443-456,
converters/FastaConverter.scala:133-185) so a genome becomes a distributed
dataset like any other.  :class:`FragmentBatch` is the columnar analog: one
row per fragment, fixed padded width, device-resident — the natural shard
unit for the genome axis of the mesh, with halo (flank) exchange between
neighbors for windowed ops (FlankReferenceFragments.scala:26-70).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.formats import schema

Array = Any


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class FragmentBatch:
    bases: Array        # u8[N, F] base codes, BASE_PAD beyond length
    lengths: Array      # i32[N]
    contig_idx: Array   # i32[N]
    start: Array        # i64[N]  fragment start on contig
    fragment_number: Array  # i32[N]
    num_fragments: Array    # i32[N] total fragments in contig
    valid: Array        # bool[N]

    @property
    def n_rows(self) -> int:
        return int(self.bases.shape[0])

    @property
    def fmax(self) -> int:
        return int(self.bases.shape[1])

    def replace(self, **kw) -> "FragmentBatch":
        return dataclasses.replace(self, **kw)

    def take(self, idx) -> "FragmentBatch":
        return jax.tree.map(lambda x: jnp.asarray(x)[idx], self)

    def to_numpy(self) -> "FragmentBatch":
        return jax.tree.map(np.asarray, self)

    @staticmethod
    def from_sequences(
        seqs: Sequence[tuple[int, str]],
        fragment_length: int = 10_000,
    ) -> "FragmentBatch":
        """(contig_idx, sequence) pairs -> fragment rows."""
        rows = []
        for contig_idx, seq in seqs:
            nfrag = max(1, -(-len(seq) // fragment_length))
            for k in range(nfrag):
                chunk = seq[k * fragment_length : (k + 1) * fragment_length]
                rows.append((contig_idx, k * fragment_length, k, nfrag, chunk))
        n = len(rows)
        fmax = max((len(r[4]) for r in rows), default=1)
        out = FragmentBatch(
            bases=np.full((n, fmax), schema.BASE_PAD, np.uint8),
            lengths=np.zeros(n, np.int32),
            contig_idx=np.zeros(n, np.int32),
            start=np.zeros(n, np.int64),
            fragment_number=np.zeros(n, np.int32),
            num_fragments=np.zeros(n, np.int32),
            valid=np.ones(n, bool),
        )
        for i, (c, s, k, nf, chunk) in enumerate(rows):
            out.bases[i, : len(chunk)] = schema.encode_bases(chunk)
            out.lengths[i] = len(chunk)
            out.contig_idx[i] = c
            out.start[i] = s
            out.fragment_number[i] = k
            out.num_fragments[i] = nf
        return out

    def extract_region(self, contig_idx: int, start: int, end: int) -> str:
        """Reconstruct [start, end) on a contig from fragments
        (adamGetReferenceString semantics, NucleotideContigFragmentRDDFunctions.scala:61)."""
        b = self.to_numpy()
        pieces = []
        for i in np.argsort(np.asarray(b.start), kind="stable"):
            if not b.valid[i] or int(b.contig_idx[i]) != contig_idx:
                continue
            fs = int(b.start[i])
            fe = fs + int(b.lengths[i])
            lo, hi = max(fs, start), min(fe, end)
            if lo < hi:
                pieces.append(
                    schema.decode_bases(b.bases[i][lo - fs : hi - fs])
                )
        got = "".join(pieces)
        if len(got) != end - start:
            raise KeyError(
                f"region {contig_idx}:{start}-{end} not fully covered by fragments"
            )
        return got


def flank_fragments(fragments: FragmentBatch, flank: int) -> FragmentBatch:
    """Extend each fragment with the first ``flank`` bases of its right
    neighbor on the same contig.

    Host/columnar form of the reference's flanking overlap exchange
    (rdd/contig/FlankReferenceFragments.scala:26-70,
    NucleotideContigFragmentRDDFunctions.flankAdjacentFragments:121) that
    makes k-mers/windows spanning fragment boundaries correct; the
    device-mesh form of the same idea is
    :func:`adam_tpu.parallel.dist.halo_exchange_right`.
    """
    b = fragments.to_numpy()
    n = b.n_rows
    order = np.lexsort(
        (np.asarray(b.start), np.asarray(b.contig_idx), ~np.asarray(b.valid))
    )
    new_len = np.array(b.lengths)
    fmax = b.fmax
    ext = {}
    for j in range(n - 1):
        i, nxt = order[j], order[j + 1]
        if not (b.valid[i] and b.valid[nxt]):
            continue
        if int(b.contig_idx[i]) != int(b.contig_idx[nxt]):
            continue
        # only genome-adjacent fragments exchange flanks; a coordinate gap
        # (subset batches) must not fabricate sequence across it
        if int(b.start[nxt]) != int(b.start[i]) + int(b.lengths[i]):
            continue
        take = min(flank, int(b.lengths[nxt]))
        if take <= 0:
            continue
        ext[int(i)] = b.bases[nxt][:take]
        new_len[i] = int(b.lengths[i]) + take
    width = max(fmax, int(new_len.max(initial=1)))
    bases = np.full((n, width), schema.BASE_PAD, np.uint8)
    bases[:, :fmax] = b.bases
    for i, tail in ext.items():
        bases[i, int(b.lengths[i]): int(new_len[i])] = tail
    return b.replace(bases=bases, lengths=new_len)


def count_contig_kmers(fragments: FragmentBatch, k: int) -> dict[str, int]:
    """k-mer counts over contig fragments, boundary-spanning windows
    included (NucleotideContigFragmentRDDFunctions.countKmers:134)."""
    from adam_tpu.ops import kmer

    flanked = flank_fragments(fragments, k - 1).to_numpy()
    return kmer.histogram_to_dict(
        flanked.bases, flanked.lengths, flanked.valid, k
    )


def to_read_records(fragments: FragmentBatch, contig_names) -> list[dict]:
    """Merge adjacent fragments into synthetic read records.

    The columnar recast of FragmentConverter.convertRdd
    (converters/FragmentConverter.scala:100): per contig, fragments are
    sorted by start and maximal *adjacent* runs (next.start == prev.end)
    are concatenated; each run becomes one AlignmentRecord-shaped dict
    (contig, start, sequence — FragmentConverter.convertFragment).
    Non-adjacent fragments stay separate reads.
    """
    b = fragments.to_numpy()
    rows = np.flatnonzero(np.asarray(b.valid))
    if not len(rows):
        return []
    contig = np.asarray(b.contig_idx)[rows]
    start = np.asarray(b.start)[rows]
    lens = np.asarray(b.lengths)[rows].astype(np.int64)
    order = np.lexsort((start, contig))
    contig, start, lens, rows = (
        contig[order], start[order], lens[order], rows[order],
    )
    # run breaks: new contig, or a gap before this fragment
    prev_end = start + lens
    brk = np.ones(len(rows), bool)
    brk[1:] = (contig[1:] != contig[:-1]) | (start[1:] != prev_end[:-1])

    records: list[dict] = []
    heads = np.flatnonzero(brk)
    bounds = np.append(heads, len(rows))
    bases = np.asarray(b.bases)
    for r in range(len(heads)):
        lo, hi = bounds[r], bounds[r + 1]
        seq = "".join(
            schema.decode_bases(bases[rows[k]][: int(lens[k])])
            for k in range(lo, hi)
        )
        c = int(contig[lo])
        records.append(
            dict(
                name=contig_names[c] if 0 <= c < len(contig_names) else str(c),
                flags=0,
                contig_idx=c,
                start=int(start[lo]),
                mapq=255,
                cigar=f"{len(seq)}M",
                seq=seq,
                qual="*",
            )
        )
    return records
