"""Typed variant-annotation INFO field mapping.

The reference converts a named set of VCF INFO keys into typed fields on
``VariantCallingAnnotations`` / ``DatabaseVariantAnnotation`` instead of
carrying them as opaque strings
(converters/VariantAnnotationConverter.scala:52-155: INFO_KEYS :97-111,
DBNSFP_KEYS :85-90, CLINVAR_KEYS :92-95, OMIM_KEYS :96, COSMIC_KEYS
:79-83 — COSMIC is disabled in the reference's EXTERNAL_DATABASE_KEYS
and therefore here too).

Here the typed fields land as real typed Parquet columns
(``ann_<adamKey>``) in the variants store written by ``anno2adam``:
floats stay float64 columns (value-exact VCF round trips), ints int64,
flags bool — so predicate pushdown works on them — and ``adam2vcf``
restores the original VCF keys on the way out.  Unknown INFO keys keep riding the generic string
map, as in the reference (the attributes catch-all).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# vcf INFO key -> (adam field name, element type).  Types follow the
# reference's attrAs{Int,Long,Float,String,Boolean} converters.
# VariantCallingAnnotations (INFO_KEYS, :97-111)
INFO_KEYS: dict[str, tuple[str, type]] = {
    "ClippingRankSum": ("clippingRankSum", float),
    "DP": ("readDepth", int),
    "FS": ("fisherStrandBiasPValue", float),
    "HaplotypeScore": ("haplotypeScore", float),
    "InbreedingCoeff": ("inbreedingCoefficient", float),
    "MQ": ("rmsMapQ", float),
    "MQ0": ("mapq0Reads", int),
    "MQRankSum": ("mqRankSum", float),
    "NEGATIVE_TRAIN_SITE": ("usedForNegativeTrainingSet", bool),
    "POSITIVE_TRAIN_SITE": ("usedForPositiveTrainingSet", bool),
    "QD": ("variantQualityByDepth", float),
    "ReadPosRankSum": ("readPositionRankSum", float),
    "VQSLOD": ("vqslod", float),
    "culprit": ("culprit", str),
}

# DatabaseVariantAnnotation (OMIM + CLINVAR + DBNSFP, :85-96).  The
# reference's CLINVAR dbSNP header line literally registers the key
# "dbSNP ID" (spaces included); kept verbatim for parity.
DB_KEYS: dict[str, tuple[str, type]] = {
    "VAR": ("omimId", str),
    "dbSNP ID": ("dbSnpId", int),
    "GENEINFO": ("geneSymbol", str),
    "PHYLOP": ("phylop", float),
    "SIFT_PRED": ("siftPred", str),
    "SIFT_SCORE": ("siftScore", float),
    "AA": ("ancestralAllele", str),
}

ANNOTATION_KEYS: dict[str, tuple[str, type]] = {**INFO_KEYS, **DB_KEYS}
_ADAM_TO_VCF = {adam: vcf for vcf, (adam, _t) in ANNOTATION_KEYS.items()}


def _convert(value, typ):
    """attrAs{Int,Float,Boolean,String} semantics: strings parse, flags
    (True) pass through; unparseable values raise like the reference's
    match errors."""
    if typ is bool:
        if isinstance(value, bool):
            return value
        return str(value).lower() in ("true", "1")
    if value is True:  # a flag key observed where a value was expected
        raise ValueError("flag value for non-flag annotation key")
    if typ is int:
        return int(float(value)) if "." in str(value) else int(value)
    if typ is float:
        return float(value)
    return str(value)


def split_typed(info_dicts) -> tuple[dict[str, list], list[dict]]:
    """Partition INFO maps into typed columns + leftover generic maps.

    -> (``{adamKey: [value-or-None per variant]}`` for every known key
    observed at least once, leftover dicts holding only unknown keys).
    """
    observed: dict[str, list] = {}
    leftover: list[dict] = []
    n = len(info_dicts)
    for i, d in enumerate(info_dicts):
        rest = {}
        for k, v in (d or {}).items():
            hit = ANNOTATION_KEYS.get(k)
            # VCF missing marker / unparseable values stay in the
            # generic map verbatim (the reference skips
            # MISSING_VALUE_v4 the same way, VariantAnnotation-
            # Converter.scala:130-134) so round trips stay lossless
            if hit is None or v == ".":
                rest[k] = v
                continue
            adam, typ = hit
            try:
                converted = _convert(v, typ)
            except (ValueError, TypeError):
                rest[k] = v
                continue
            col = observed.get(adam)
            if col is None:
                col = observed[adam] = [None] * n
            col[i] = converted
        leftover.append(rest)
    return observed, leftover


def merge_typed(typed: Optional[dict], info_dicts: list[dict]) -> list[dict]:
    """Inverse of :func:`split_typed`: typed columns -> VCF INFO keys
    layered over the generic maps (typed values win on key collision)."""
    if not typed:
        return info_dicts
    out = [dict(d or {}) for d in info_dicts]
    for adam, col in typed.items():
        vcf_key = _ADAM_TO_VCF.get(adam, adam)
        _a, typ = ANNOTATION_KEYS.get(vcf_key, (adam, str))
        for i, v in enumerate(col):
            if v is None or (
                isinstance(v, (float, np.floating)) and np.isnan(v)
            ):
                continue
            if typ is bool:
                if v:
                    out[i][vcf_key] = True
                continue
            if typ is float:
                # shortest value-exact digits, exponent form where
                # appropriate ('%g' truncated to 6 significant digits:
                # VQSLOD 1234.5678 -> "1234.57").  Integer-valued floats
                # print without the trailing ".0" (MQ=60 stays "60", as
                # '%g' printed it); numpy scalars format at their own
                # width so legacy float32 columns don't emit widening
                # noise.
                fv = float(v)
                if fv.is_integer() and abs(fv) < 1e16:
                    out[i][vcf_key] = str(int(fv))
                else:
                    out[i][vcf_key] = (
                        str(v) if isinstance(v, np.floating) else repr(fv)
                    )
            else:
                out[i][vcf_key] = str(v)
    return out


def arrow_type(adam_key: str):
    """Arrow storage type for a typed annotation column."""
    import pyarrow as pa

    vcf_key = _ADAM_TO_VCF.get(adam_key)
    typ = ANNOTATION_KEYS[vcf_key][1] if vcf_key else str
    if typ is bool:
        return pa.bool_()
    if typ is int:
        return pa.int64()
    if typ is float:
        # float64 so the VCF string -> column -> VCF string round trip
        # is value-exact (float32 storage dropped digits past ~7)
        return pa.float64()
    return pa.string()
