"""Columnar read batches — the device-side data model.

The reference's unit of data is one Avro ``AlignmentRecord`` object per read
flowing through Spark RDDs.  Here the unit is a **batch**: a struct of
padded, masked arrays ``[N, Lmax]`` that lives in HBM and is the argument
to every kernel.  This is what makes ``vmap``/``shard_map`` work and keeps
the MXU fed.

Split of responsibilities:

* :class:`ReadBatch` — pure JAX pytree of arrays.  Safe to pass through
  ``jit``/``shard_map``; every transform is ``ReadBatch -> ReadBatch``.
* :class:`ReadSidecar` — host-only variable-length columns (read names,
  attribute strings, MD tags) kept out of the device path, carried
  alongside by the API layer (:mod:`adam_tpu.api`).

Field parity with the reference's AlignmentRecord (field list at
projections/AlignmentRecordField.scala:29-31): sequence/qual -> ``bases``/
``quals`` (integer-coded), the 12 boolean flag fields -> packed ``flags``,
contig/start/end/mapq/cigar/mate* -> same-named columns, recordGroup* ->
``read_group_idx`` into a :class:`RecordGroupDictionary`, readName/
attributes/mdTag/origQual -> sidecar.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.formats import schema

Array = Any  # jnp.ndarray or np.ndarray


def grid_rows(n: int, minimum: int = 1024) -> int:
    """Device-friendly row count: the next power of two, floored at
    ``minimum``.

    Two reasons to quantize row counts before a device call: (1) the
    persistent compilation cache then sees a handful of shapes instead of
    one per input file, and (2) the TPU compiler's gather/scatter
    strategies have a pathological compile-time hump for mid-size
    irregular row counts (measured: ~50 s at N=98304 vs ~1.5 s at
    N=131072 for the same gather); power-of-two rows stay on the fast
    path.  Padding rows carry valid=False and are masked out by every
    kernel.
    """
    n = max(int(n), 1)
    g = max(minimum, 1 << (n - 1).bit_length())
    return g


def grid_cols(n: int, mult: int = 32) -> int:
    """Device-friendly lane count: next multiple of ``mult``.

    Unaligned minor dims also hurt *transfers*: fetching a u8
    [131072, 100] through the TPU tunnel measured 7.6 MB/s vs 27 MB/s
    for [131072, 104] (sublane-aligned)."""
    return _round_up(max(int(n), 1), mult)


def grid_cigar_cols(width: int) -> int:
    """Cigar-op grid: multiples of 8 instead of :func:`grid_cols`'s 32.

    Op counts are small (typically < 16 on short-read libraries) while
    the [N, C] i32 ``cigar_lens`` matrix ships host->device with every
    pass-A markdup window — at the 32-floor, 3/4 of those tunnel bytes
    were pure padding zeros.  Multiples of 8 stay sublane-aligned for
    the i32 lens (and trivially for the u8 ops) and keep the compile-
    cache shape set bounded; the streamed first-sight re-prewarm covers
    the extra gc values a long-cigar window can introduce."""
    return grid_cols(width, mult=8)


def pad_rows_np(arr, n: int, fill=0, cols: int | None = None):
    """Pad a numpy array's leading axis up to ``n`` rows (and, for 2-d
    arrays when ``cols`` is given, the second axis up to ``cols``) with
    ``fill``."""
    arr = np.asarray(arr)
    extra_rows = n - arr.shape[0]
    extra_cols = (cols - arr.shape[1]) if (cols is not None and arr.ndim > 1) else 0
    if extra_rows == 0 and extra_cols == 0:
        return arr
    pad_width = [(0, extra_rows), (0, extra_cols)] + [(0, 0)] * (arr.ndim - 2)
    return np.pad(arr, pad_width[: arr.ndim], constant_values=fill)


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class ReadBatch:
    """Struct-of-arrays batch of (up to) N reads, padded to [N, L] / [N, C].

    Padding rows have ``valid == False``; padding lanes within a read have
    ``bases == BASE_PAD`` and ``quals == QUAL_PAD``.
    """

    bases: Array          # u8[N, L]   base codes (schema.BASE_*)
    quals: Array          # u8[N, L]   phred values, QUAL_PAD in padding
    lengths: Array        # i32[N]     true read length
    flags: Array          # i32[N]     packed SAM flags
    contig_idx: Array     # i32[N]     index into SequenceDictionary, -1 unmapped
    start: Array          # i64[N]     0-based inclusive, -1 if unmapped
    end: Array            # i64[N]     0-based exclusive (start + ref span)
    mapq: Array           # i32[N]     255 = unavailable
    cigar_ops: Array      # u8[N, C]   schema.CIGAR_* codes, CIGAR_PAD pad
    cigar_lens: Array     # i32[N, C]
    cigar_n: Array        # i32[N]     number of real cigar ops
    mate_contig_idx: Array  # i32[N]   -1 if mate unmapped/absent
    mate_start: Array     # i64[N]
    tlen: Array           # i32[N]    template length (SAM TLEN)
    read_group_idx: Array  # i32[N]   index into RecordGroupDictionary, -1 none
    has_qual: Array       # bool[N]   false when qual was '*' (null in the reference)
    valid: Array          # bool[N]   row mask

    # ---------------------------------------------------------------- sizes
    @property
    def n_rows(self) -> int:
        return int(self.bases.shape[0])

    @property
    def lmax(self) -> int:
        return int(self.bases.shape[1])

    @property
    def cmax(self) -> int:
        return int(self.cigar_ops.shape[1])

    def n_valid(self) -> int:
        return int(np.asarray(self.valid).sum())

    # ------------------------------------------------------------ flag views
    def flag_set(self, bit: int) -> Array:
        return (self.flags & bit) != 0

    @property
    def is_mapped(self) -> Array:
        return (self.flags & schema.FLAG_UNMAPPED) == 0

    @property
    def is_primary(self) -> Array:
        return (self.flags & (schema.FLAG_SECONDARY | schema.FLAG_SUPPLEMENTARY)) == 0

    # ------------------------------------------------------------- reshaping
    def pad_rows(self, n: int) -> "ReadBatch":
        """Pad to exactly ``n`` rows (valid=False padding)."""
        cur = self.n_rows
        if cur == n:
            return self
        if cur > n:
            raise ValueError(f"cannot pad {cur} rows down to {n}")
        extra = n - cur

        def pad(x, fill):
            pad_width = [(0, extra)] + [(0, 0)] * (x.ndim - 1)
            return np.pad(np.asarray(x), pad_width, constant_values=fill)

        return ReadBatch(
            bases=pad(self.bases, schema.BASE_PAD),
            quals=pad(self.quals, schema.QUAL_PAD),
            lengths=pad(self.lengths, 0),
            flags=pad(self.flags, schema.FLAG_UNMAPPED),
            contig_idx=pad(self.contig_idx, -1),
            start=pad(self.start, -1),
            end=pad(self.end, -1),
            mapq=pad(self.mapq, 255),
            cigar_ops=pad(self.cigar_ops, schema.CIGAR_PAD),
            cigar_lens=pad(self.cigar_lens, 0),
            cigar_n=pad(self.cigar_n, 0),
            mate_contig_idx=pad(self.mate_contig_idx, -1),
            mate_start=pad(self.mate_start, -1),
            tlen=pad(self.tlen, 0),
            read_group_idx=pad(self.read_group_idx, -1),
            has_qual=pad(self.has_qual, False),
            valid=pad(self.valid, False),
        )

    def take(self, idx: Array) -> "ReadBatch":
        """Row gather preserving residency: numpy batches gather on the
        host, device batches on the device.  (Coercing to jnp here used
        to ship every host window through the tunneled chip — a 9x pass
        regression on the flagship bench.)"""
        return jax.tree.map(lambda x: x[idx], self)

    def replace(self, **kw) -> "ReadBatch":
        return dataclasses.replace(self, **kw)

    def to_numpy(self) -> "ReadBatch":
        return jax.tree.map(np.asarray, self)

    def to_device(self) -> "ReadBatch":
        return jax.tree.map(jnp.asarray, self)

    # ----------------------------------------------------------- constructors
    @staticmethod
    def empty(n: int = 0, lmax: int = 0, cmax: int = 0) -> "ReadBatch":
        return ReadBatch(
            bases=np.full((n, lmax), schema.BASE_PAD, np.uint8),
            quals=np.full((n, lmax), schema.QUAL_PAD, np.uint8),
            lengths=np.zeros(n, np.int32),
            flags=np.full(n, schema.FLAG_UNMAPPED, np.int32),
            contig_idx=np.full(n, -1, np.int32),
            start=np.full(n, -1, np.int64),
            end=np.full(n, -1, np.int64),
            mapq=np.full(n, 255, np.int32),
            cigar_ops=np.full((n, cmax), schema.CIGAR_PAD, np.uint8),
            cigar_lens=np.zeros((n, cmax), np.int32),
            cigar_n=np.zeros(n, np.int32),
            mate_contig_idx=np.full(n, -1, np.int32),
            mate_start=np.full(n, -1, np.int64),
            tlen=np.zeros(n, np.int32),
            read_group_idx=np.full(n, -1, np.int32),
            has_qual=np.zeros(n, bool),
            valid=np.zeros(n, bool),
        )

    @staticmethod
    def concat(batches: Sequence["ReadBatch"]) -> "ReadBatch":
        """Concatenate along rows, widening L/C to the max."""
        batches = [b for b in batches if b.n_rows]
        if not batches:
            return ReadBatch.empty()
        lmax = max(b.lmax for b in batches)
        cmax = max(b.cmax for b in batches)
        batches = [b.widen(lmax, cmax).to_numpy() for b in batches]
        return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *batches)

    def widen(self, lmax: int, cmax: int) -> "ReadBatch":
        """Grow the per-read padding lanes to lmax/cmax."""
        if lmax == self.lmax and cmax == self.cmax:
            return self

        def padlane(x, width, fill):
            x = np.asarray(x)
            if x.shape[1] == width:
                return x
            return np.pad(x, [(0, 0), (0, width - x.shape[1])], constant_values=fill)

        return self.replace(
            bases=padlane(self.bases, lmax, schema.BASE_PAD),
            quals=padlane(self.quals, lmax, schema.QUAL_PAD),
            cigar_ops=padlane(self.cigar_ops, cmax, schema.CIGAR_PAD),
            cigar_lens=padlane(self.cigar_lens, cmax, 0),
        )


@dataclass
class ReadSidecar:
    """Host-side variable-length columns, parallel to ReadBatch rows.

    String fields are stored columnar (:class:`StringColumn`: one flat
    byte buffer + offsets, Arrow layout) so whole-dataset operations stay
    vectorized; plain lists are accepted anywhere and normalized on
    construction.  Element access (``side.md[i]``) returns str/None either
    way.
    """

    names: Any = field(default_factory=list)       # read names
    attrs: Any = field(default_factory=list)       # raw SAM tag strings ("NM:i:0\tAS:i:75")
    md: Any = field(default_factory=list)          # MD tag string or None
    orig_quals: Any = field(default_factory=list)  # OQ or None
    # basesTrimmedFromStart/End bookkeeping (AlignmentRecord fields set by
    # TrimReads.trimRead, rdd/read/correction/TrimReads.scala:363-368)
    trimmed_from_start: Any = None
    trimmed_from_end: Any = None

    def __post_init__(self):
        from adam_tpu.formats.strings import StringColumn

        self.names = StringColumn.of(self.names)
        self.attrs = StringColumn.of(self.attrs)
        self.md = StringColumn.of(self.md)
        self.orig_quals = StringColumn.of(self.orig_quals)
        n = len(self.names)
        if self.trimmed_from_start is None:
            self.trimmed_from_start = np.zeros(n, np.int32)
        else:
            self.trimmed_from_start = np.asarray(
                self.trimmed_from_start, np.int32
            )
        if self.trimmed_from_end is None:
            self.trimmed_from_end = np.zeros(n, np.int32)
        else:
            self.trimmed_from_end = np.asarray(self.trimmed_from_end, np.int32)

    def take(self, idx) -> "ReadSidecar":
        idx = np.asarray(idx)
        return ReadSidecar(
            names=self.names.take(idx),
            attrs=self.attrs.take(idx),
            md=self.md.take(idx),
            orig_quals=self.orig_quals.take(idx),
            trimmed_from_start=self.trimmed_from_start[idx],
            trimmed_from_end=self.trimmed_from_end[idx],
        )

    @staticmethod
    def concat(sides: Sequence["ReadSidecar"]) -> "ReadSidecar":
        from adam_tpu.formats.strings import StringColumn

        if not sides:
            return ReadSidecar()
        return ReadSidecar(
            names=StringColumn.concat([s.names for s in sides]),
            attrs=StringColumn.concat([s.attrs for s in sides]),
            md=StringColumn.concat([s.md for s in sides]),
            orig_quals=StringColumn.concat([s.orig_quals for s in sides]),
            trimmed_from_start=np.concatenate(
                [np.asarray(s.trimmed_from_start, np.int32) for s in sides]
            ),
            trimmed_from_end=np.concatenate(
                [np.asarray(s.trimmed_from_end, np.int32) for s in sides]
            ),
        )

    def __len__(self) -> int:
        return len(self.names)


def pack_reads(
    records: Sequence[dict],
    lmax: int | None = None,
    cmax: int | None = None,
    round_rows_to: int = 1,
) -> tuple[ReadBatch, ReadSidecar]:
    """Build a (ReadBatch, ReadSidecar) from parsed per-read dicts.

    Each record dict carries: name, flags, contig_idx, start (0-based, -1
    unmapped), mapq, cigar (string), seq (string), qual (phred string or
    '*'), mate_contig_idx, mate_start, tlen, read_group_idx, attrs (raw tag
    string), md (or None).
    """
    n = len(records)
    if n == 0:
        return ReadBatch.empty(), ReadSidecar()
    if lmax is None:
        lmax = max((len(r["seq"]) if r["seq"] not in ("*", None) else 0) for r in records)
        lmax = max(lmax, 1)
    if cmax is None:
        cmax = 1
        for r in records:
            c = r.get("cigar") or "*"
            cmax = max(cmax, sum(1 for ch in c if not ch.isdigit()))
    nrows = _round_up(n, round_rows_to)

    b = ReadBatch.empty(nrows, lmax, cmax)
    b = jax.tree.map(np.array, b)  # writable copies
    s_names, s_attrs, s_md, s_oq, s_tfs, s_tfe = [], [], [], [], [], []

    for i, r in enumerate(records):
        seq = r["seq"] if r["seq"] not in ("*", None) else ""
        qual = r.get("qual")
        L = len(seq)
        if L:
            b.bases[i, :L] = schema.encode_bases(seq)
        if qual and qual != "*":
            b.quals[i, : len(qual)] = schema.encode_quals(qual)
            b.has_qual[i] = True
        elif L:
            b.quals[i, :L] = 0
        b.lengths[i] = L
        b.flags[i] = r["flags"]
        b.contig_idx[i] = r.get("contig_idx", -1)
        start = r.get("start", -1)
        b.start[i] = start
        b.mapq[i] = r.get("mapq", 255)
        cig = r.get("cigar") or "*"
        ops, lens, ncig = schema.encode_cigar(cig, cmax)
        b.cigar_ops[i] = ops
        b.cigar_lens[i] = lens
        b.cigar_n[i] = ncig
        _, rlen = schema.cigar_str_stats(cig) if cig != "*" else (0, 0)
        # end = start for mapped reads whose CIGAR consumes no reference
        # (e.g. fully soft-clipped); -1 is reserved for unplaced reads.
        b.end[i] = start + rlen if start >= 0 else -1
        b.mate_contig_idx[i] = r.get("mate_contig_idx", -1)
        b.mate_start[i] = r.get("mate_start", -1)
        b.tlen[i] = r.get("tlen", 0)
        b.read_group_idx[i] = r.get("read_group_idx", -1)
        b.valid[i] = True

        s_names.append(r.get("name", ""))
        s_attrs.append(r.get("attrs", ""))
        s_md.append(r.get("md"))
        s_oq.append(r.get("orig_qual"))
        s_tfs.append(r.get("trimmed_from_start", 0))
        s_tfe.append(r.get("trimmed_from_end", 0))

    # padding rows keep empty sidecar slots so columns stay row-parallel
    pad = nrows - n
    side = ReadSidecar(
        names=s_names + [""] * pad,
        attrs=s_attrs + [""] * pad,
        md=s_md + [None] * pad,
        orig_quals=s_oq + [None] * pad,
        trimmed_from_start=np.asarray(s_tfs + [0] * pad, np.int32),
        trimmed_from_end=np.asarray(s_tfe + [0] * pad, np.int32),
    )
    return b, side
