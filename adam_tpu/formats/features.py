"""Columnar genomic features — GTF/BED/narrowPeak data model.

The reference's unit is one Avro ``Feature`` record per row
(``rdd/features/FeatureParser.scala``). Here features are one
struct-of-arrays :class:`FeatureBatch`: coordinates/strand/score live as
device-friendly columns (so overlap filtering, coverage, and region
joins run through :mod:`adam_tpu.ops.intervals` unchanged), while ids,
types, parents, and attribute maps stay in a host sidecar.

Features frequently arrive without a sequence dictionary, so the batch
carries its own contig-name table; :meth:`FeatureBatch.intervals` adapts
rows to the join layer.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

STRAND_FORWARD = 1
STRAND_REVERSE = -1
STRAND_INDEPENDENT = 0


def strand_code(s: str) -> int:
    """'+'/'-'/other -> Forward/Reverse/Independent
    (GTFParser strand match, FeatureParser.scala:93-98)."""
    return {"+": STRAND_FORWARD, "-": STRAND_REVERSE}.get(s, STRAND_INDEPENDENT)


@dataclass
class FeatureSidecar:
    feature_id: list = field(default_factory=list)  # str ('' if absent)
    feature_type: list = field(default_factory=list)  # 'gene'/'exon'/peak name/...
    source: list = field(default_factory=list)  # str
    parent_ids: list = field(default_factory=list)  # list[str] per row
    attributes: list = field(default_factory=list)  # dict per row

    def take(self, idx) -> "FeatureSidecar":
        idx = np.asarray(idx)
        return FeatureSidecar(
            [self.feature_id[i] for i in idx],
            [self.feature_type[i] for i in idx],
            [self.source[i] for i in idx],
            [self.parent_ids[i] for i in idx],
            [self.attributes[i] for i in idx],
        )


@dataclass
class FeatureBatch:
    contig_idx: np.ndarray  # i32[N] into `contig_names`
    start: np.ndarray  # i64[N], 0-based
    end: np.ndarray  # i64[N], exclusive
    strand: np.ndarray  # i8[N] of STRAND_* codes
    score: np.ndarray  # f32[N], nan when absent ('.')
    contig_names: list = field(default_factory=list)
    sidecar: FeatureSidecar = field(default_factory=FeatureSidecar)

    def __len__(self):
        return len(self.start)

    def take(self, idx) -> "FeatureBatch":
        idx = np.asarray(idx)
        return FeatureBatch(
            self.contig_idx[idx], self.start[idx], self.end[idx],
            self.strand[idx], self.score[idx], self.contig_names,
            self.sidecar.take(idx),
        )

    def intervals(self, contig_names=None):
        """Adapter to the region-join layer.

        The batch's private contig table need not match anyone else's
        index space: pass the target ``contig_names`` (e.g. from a
        SequenceDictionary) to remap; rows on contigs unknown to the
        target become empty intervals on contig -1, which can overlap
        nothing (a half-open overlap needs start < other.end AND
        end > other.start) — not even each other. With no argument the
        batch's own table is used — only valid when both join sides
        share it.
        """
        from adam_tpu.pipelines.region_join import IntervalArrays

        if contig_names is None:
            return IntervalArrays.of(self.contig_idx, self.start, self.end)
        target = {n: i for i, n in enumerate(contig_names)}
        remap = np.array(
            [target.get(n, -1) for n in self.contig_names], np.int64
        )
        contig = remap[self.contig_idx]
        unknown = contig < 0
        return IntervalArrays.of(
            contig,
            np.where(unknown, 0, self.start),
            np.where(unknown, 0, self.end),
        )

    def filter_by_overlapping_region(
        self, contig_name: str, start: int, end: int
    ) -> "FeatureBatch":
        """Overlap filter (GeneFeatureRDDFunctions.filterByOverlappingRegion,
        rdd/features/GeneFeatureRDDFunctions.scala:127-135) as one mask."""
        if contig_name not in self.contig_names:
            return self.take(np.zeros(0, np.int64))
        ci = self.contig_names.index(contig_name)
        keep = (
            (self.contig_idx == ci) & (self.start < end) & (self.end > start)
        )
        return self.take(np.flatnonzero(keep))


class FeatureBatchBuilder:
    """Row-at-a-time accumulator used by the parsers."""

    def __init__(self, contig_names=None):
        self.names = list(contig_names or [])
        self._idx = {n: i for i, n in enumerate(self.names)}
        self.rows = dict(contig=[], start=[], end=[], strand=[], score=[])
        self.side = FeatureSidecar()

    def contig_id(self, name: str) -> int:
        if name not in self._idx:
            self._idx[name] = len(self.names)
            self.names.append(name)
        return self._idx[name]

    def add(self, contig, start, end, strand=STRAND_INDEPENDENT,
            score=np.nan, feature_id="", feature_type="", source="",
            parent_ids=(), attributes=None):
        self.rows["contig"].append(self.contig_id(contig))
        self.rows["start"].append(start)
        self.rows["end"].append(end)
        self.rows["strand"].append(strand)
        self.rows["score"].append(score)
        self.side.feature_id.append(feature_id)
        self.side.feature_type.append(feature_type)
        self.side.source.append(source)
        self.side.parent_ids.append(list(parent_ids))
        self.side.attributes.append(dict(attributes or {}))

    def build(self) -> FeatureBatch:
        return FeatureBatch(
            np.asarray(self.rows["contig"], np.int32),
            np.asarray(self.rows["start"], np.int64),
            np.asarray(self.rows["end"], np.int64),
            np.asarray(self.rows["strand"], np.int8),
            np.asarray(self.rows["score"], np.float32),
            self.names,
            self.side,
        )
