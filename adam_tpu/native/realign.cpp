// Native realignment prep + MD rewrite kernels.
//
// Host-side C++ port of the per-read string walks of GATK-style indel
// realignment: MD tag parse / getReference / moveAlignment / toString
// (adam_tpu/ops/mdtag.py, mirroring the reference util/MdTag.scala:47-532),
// left-normalization (pipelines/realign.py:77-183, reference
// NormalizationUtils.scala:35-153) and per-target reference rebuild +
// consensus generation (pipelines/realign.py phase 1, reference
// RealignIndels.scala:185-304, Consensus.scala:25-52).
//
// The device sweep and all accept/rewrite *decisions* stay in Python
// (numpy); this file only removes the per-read interpreter work that
// dominated the realign stage's host time.  Semantics must match the
// Python implementations bit-for-bit — the GATK golden parity tests
// (artificial.realigned.sam) run against both paths.
//
// Exposed via ctypes from adam_tpu/native/__init__.py; compiled into the
// same shared object as adamtok.cpp.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace {

// ---- schema constants (formats/schema.py) -------------------------------
constexpr uint8_t CIG_M = 0, CIG_I = 1, CIG_D = 2, CIG_N = 3, CIG_S = 4,
                  CIG_H = 5, CIG_P = 6, CIG_EQ = 7, CIG_X = 8;
const char* CIGAR_CHARS = "MIDNSHP=X";
const char* BASE_DECODE = "ACGTN.";  // code -> char

inline uint8_t base_encode(char c) {
  // schema.BASE_ENCODE_LUT: ACGTN (either case) -> 0..4, '*' -> 5,
  // anything else (IUPAC ambiguity etc.) -> N (4)
  switch (c) {
    case 'A': case 'a': return 0;
    case 'C': case 'c': return 1;
    case 'G': case 'g': return 2;
    case 'T': case 't': return 3;
    case 'N': case 'n': return 4;
    case '*': return 5;
    default: return 4;
  }
}

inline bool is_md_base(char c) {
  // mdtag.py _BASES: full IUPAC ambiguity alphabet (uppercased input)
  switch (c) {
    case 'A': case 'G': case 'C': case 'T': case 'N': case 'U': case 'K':
    case 'M': case 'R': case 'S': case 'W': case 'B': case 'V': case 'H':
    case 'D': case 'X': case 'Y':
      return true;
    default:
      return false;
  }
}

struct CigEl {
  int32_t len;
  char op;
  bool operator==(const CigEl& o) const { return len == o.len && op == o.op; }
};
using Cigar = std::vector<CigEl>;

std::string cigar_to_string(const Cigar& c) {
  std::string s;
  char buf[16];
  for (const auto& e : c) {
    int n = snprintf(buf, sizeof buf, "%d%c", e.len, e.op);
    s.append(buf, n);
  }
  return s;
}

int64_t cigar_read_len(const Cigar& c) {  // ops in "MIS=X"
  int64_t n = 0;
  for (const auto& e : c)
    if (e.op == 'M' || e.op == 'I' || e.op == 'S' || e.op == '=' ||
        e.op == 'X')
      n += e.len;
  return n;
}

int64_t cigar_ref_len(const Cigar& c) {  // ops in "MDN=X"
  int64_t n = 0;
  for (const auto& e : c)
    if (e.op == 'M' || e.op == 'D' || e.op == 'N' || e.op == '=' ||
        e.op == 'X')
      n += e.len;
  return n;
}

int64_t cigar_total_len(const Cigar& c) {
  int64_t n = 0;
  for (const auto& e : c) n += e.len;
  return n;
}

int cigar_num_m_blocks(const Cigar& c) {
  int n = 0;
  for (const auto& e : c) n += e.op == 'M';
  return n;
}

// ---- MD tag --------------------------------------------------------------
struct Md {
  int64_t start = 0;
  // absolute reference positions, ascending by construction of parse
  std::vector<std::pair<int64_t, char>> mm;    // mismatches: pos -> ref base
  std::vector<std::pair<int64_t, char>> dels;  // deletions: pos -> ref base
  std::vector<std::pair<int64_t, int64_t>> matches;  // [start, end) ranges
};

// MdTag.parse (mdtag.py:53-94).  Returns false on malformed input.
// Input is uppercased on the fly (parse does `md.upper()`).
bool md_parse(const uint8_t* s, int64_t n, int64_t ref_start, Md& out) {
  out.start = ref_start;
  out.mm.clear();
  out.dels.clear();
  out.matches.clear();
  if (n == 0 || (n == 1 && s[0] == '0')) return true;
  int64_t off = 0;
  int64_t pos = ref_start;
  auto read_matches = [&]() -> bool {
    int64_t st = off;
    int64_t len = 0;
    while (off < n && s[off] >= '0' && s[off] <= '9') {
      len = len * 10 + (s[off] - '0');
      ++off;
    }
    if (off == st) return false;  // digits required
    if (len > 0) out.matches.emplace_back(pos, pos + len);
    pos += len;
    return true;
  };
  if (!read_matches()) return false;
  while (off < n) {
    if (s[off] == '^') {
      ++off;
      int64_t st = off;
      while (off < n) {
        char c = (char)toupper(s[off]);
        if (!is_md_base(c)) break;
        out.dels.emplace_back(pos, c);
        ++pos;
        ++off;
      }
      if (off == st) return false;
    } else {
      int64_t st = off;
      while (off < n) {
        char c = (char)toupper(s[off]);
        if (!is_md_base(c)) break;
        out.mm.emplace_back(pos, c);
        ++pos;
        ++off;
      }
      if (off == st) return false;
    }
    if (!read_matches()) return false;
  }
  return true;
}

// MdTag.get_reference (mdtag.py:205-256).  err: 0 ok, 2 IndexError
// (CIGAR overruns read), 3 ValueError (missing deleted base / bad op).
int md_get_reference(const Md& md, const std::string& seq, const Cigar& cig,
                     std::string& out) {
  int64_t ref_pos = md.start;
  int64_t read_pos = 0;
  out.clear();
  for (const auto& e : cig) {
    char op = e.op;
    int64_t length = e.len;
    if (op == 'M' || op == '=' || op == 'X') {
      if (read_pos + length > (int64_t)seq.size()) return 2;
      size_t seg0 = out.size();
      out.append(seq, read_pos, length);
      if (!md.mm.empty()) {
        auto lo = std::lower_bound(
            md.mm.begin(), md.mm.end(), std::make_pair(ref_pos, (char)0));
        for (auto it = lo; it != md.mm.end() && it->first < ref_pos + length;
             ++it)
          if (it->second) out[seg0 + (it->first - ref_pos)] = it->second;
      }
      read_pos += length;
      ref_pos += length;
    } else if (op == 'D') {
      for (int64_t k = 0; k < length; ++k) {
        auto it = std::lower_bound(md.dels.begin(), md.dels.end(),
                                   std::make_pair(ref_pos, (char)0));
        if (it == md.dels.end() || it->first != ref_pos) return 3;
        out.push_back(it->second);
        ++ref_pos;
      }
    } else if (op == 'I' || op == 'S') {
      read_pos += length;
    } else if (op == 'H' || op == 'P') {
      // no-op
    } else {
      return 3;
    }
  }
  return 0;
}

// MdTag.move_alignment (mdtag.py:134-186).  err: 0 ok, 2 IndexError,
// 3 ValueError (unhandled op).
int md_move_alignment(const char* reference, int64_t ref_len,
                      const std::string& seq, const Cigar& cig,
                      int64_t read_start, Md& out) {
  out.start = read_start;
  out.mm.clear();
  out.dels.clear();
  out.matches.clear();
  int64_t ref_pos = 0;
  int64_t read_pos = 0;
  for (const auto& e : cig) {
    char op = e.op;
    int64_t length = e.len;
    if (op == 'M') {
      if (ref_pos + length > ref_len || read_pos + length > (int64_t)seq.size())
        return 2;
      const char* r = reference + ref_pos;
      const char* s = seq.data() + read_pos;
      if (memcmp(r, s, length) == 0) {
        out.matches.emplace_back(ref_pos + read_start,
                                 ref_pos + length + read_start);
      } else {
        int64_t prev = -1;
        for (int64_t j = 0; j <= length; ++j) {
          bool diff = j < length && r[j] != s[j];
          if (diff) {
            out.mm.emplace_back(ref_pos + j + read_start, r[j]);
            if (j > prev + 1)
              out.matches.emplace_back(ref_pos + prev + 1 + read_start,
                                       ref_pos + j + read_start);
            prev = j;
          }
        }
        if (length > prev + 1)
          out.matches.emplace_back(ref_pos + prev + 1 + read_start,
                                   ref_pos + length + read_start);
      }
      read_pos += length;
      ref_pos += length;
    } else if (op == 'D') {
      if (ref_pos + length > ref_len) return 2;
      for (int64_t j = 0; j < length; ++j)
        out.dels.emplace_back(ref_pos + j + read_start,
                              reference[ref_pos + j]);
      ref_pos += length;
    } else if (op == 'I' || op == 'S') {
      read_pos += length;
    } else if (op == 'H' || op == 'P') {
      // no-op
    } else {
      return 3;
    }
  }
  return 0;
}

// MdTag.to_string (mdtag.py:259-287): canonical event-walk emission.
std::string md_to_string(const Md& md) {
  if (md.matches.empty() && md.mm.empty() && md.dels.empty()) return "0";
  int64_t end = md.start;  // largest covered position (inclusive)
  bool any = false;
  for (const auto& m : md.matches) {
    end = any ? std::max(end, m.second - 1) : m.second - 1;
    any = true;
  }
  for (const auto& p : md.mm) {
    end = any ? std::max(end, p.first) : p.first;
    any = true;
  }
  for (const auto& p : md.dels) {
    end = any ? std::max(end, p.first) : p.first;
    any = true;
  }
  // events sorted by (pos, is_del, base) — Python tuple ordering
  struct Ev {
    int64_t p;
    bool is_del;
    char base;
  };
  std::vector<Ev> events;
  events.reserve(md.mm.size() + md.dels.size());
  for (const auto& p : md.mm) events.push_back({p.first, false, p.second});
  for (const auto& p : md.dels) events.push_back({p.first, true, p.second});
  std::sort(events.begin(), events.end(), [](const Ev& a, const Ev& b) {
    if (a.p != b.p) return a.p < b.p;
    if (a.is_del != b.is_del) return !a.is_del;
    return a.base < b.base;
  });
  std::string out;
  char buf[24];
  int64_t prev_end = md.start;
  bool last_was_del = false;
  for (const auto& ev : events) {
    int64_t run = ev.p - prev_end;
    if (ev.is_del) {
      if (run > 0 || !last_was_del) {
        out.append(buf, snprintf(buf, sizeof buf, "%lld", (long long)run));
        out.push_back('^');
      }
      out.push_back(ev.base);
      last_was_del = true;
    } else {
      out.append(buf, snprintf(buf, sizeof buf, "%lld", (long long)run));
      out.push_back(ev.base);
      last_was_del = false;
    }
    prev_end = ev.p + 1;
  }
  out.append(buf,
             snprintf(buf, sizeof buf, "%lld", (long long)(end + 1 - prev_end)));
  return out;
}

// ---- left normalization (realign.py:77-183) ------------------------------

// RichCigar.moveLeft semantics (realign.py:77-101), including the
// reference's dropped-4th-element slicing quirk.
Cigar move_cigar_left(const Cigar& elems, int index) {
  if (index == 0 || elems.size() < 2) return elems;
  Cigar out(elems.begin(), elems.begin() + (index - 1));
  std::vector<CigEl> rest(elems.begin() + (index - 1), elems.end());
  const CigEl trim = rest[0];
  const CigEl* move = rest.size() > 1 ? &rest[1] : nullptr;
  const CigEl* pad = rest.size() > 2 ? &rest[2] : nullptr;
  if (trim.len > 1) out.push_back({trim.len - 1, trim.op});
  if (move) out.push_back(*move);
  if (pad)
    out.push_back({pad->len + 1, pad->op});
  else
    out.push_back({1, 'M'});
  if (rest.size() > 4)  // == 4 drops the 4th element (reference quirk)
    out.insert(out.end(), rest.begin() + 3, rest.end());
  return out;
}

// shift_indel (realign.py:104-136): pinned total/read/ref spans.
Cigar shift_indel(const Cigar& elems, int position, int64_t shifts) {
  Cigar cur = elems;
  const int64_t total = cigar_total_len(cur);
  const int64_t rlen = cigar_read_len(cur);
  const int64_t reflen = cigar_ref_len(cur);
  while (true) {
    Cigar nw = move_cigar_left(cur, position);
    if (shifts == 0 || cigar_total_len(nw) != total ||
        cigar_read_len(nw) != rlen || cigar_ref_len(nw) != reflen)
      return cur;
    cur = std::move(nw);
    --shifts;
  }
}

// positions_to_shift (realign.py:139-147): rotate-right compare walk.
int64_t positions_to_shift(const std::string& variant,
                           const std::string& preceding) {
  std::string v = variant, p = preceding;
  int64_t acc = 0;
  while (!p.empty() && !v.empty() && p.back() == v.back()) {
    // v = v[-1] + v[:-1]
    v.insert(v.begin(), v.back());
    v.pop_back();
    p.pop_back();
    ++acc;
  }
  return acc;
}

// left_align_indel (realign.py:150-183).  md may be null (absent).
// err out-param propagates get_reference failures.
Cigar left_align_indel(const std::string& seq, const Cigar& cigar,
                       const Md* md, int* err) {
  *err = 0;
  int indel_pos = -1;
  int64_t indel_len = 0;
  int64_t read_pos = 0, ref_pos = 0;
  bool is_insert = false;
  for (size_t i = 0; i < cigar.size(); ++i) {
    const auto& e = cigar[i];
    if (e.op == 'I') {
      if (indel_pos != -1) return cigar;
      indel_pos = (int)i;
      indel_len = e.len;
      is_insert = true;
    } else if (e.op == 'D') {
      if (indel_pos != -1) return cigar;
      indel_pos = (int)i;
      indel_len = e.len;
    } else if (indel_pos == -1) {
      char op = e.op;
      if (op == 'M' || op == 'I' || op == 'S' || op == '=' || op == 'X')
        read_pos += e.len;
      if (op == 'M' || op == 'D' || op == 'N' || op == '=' || op == 'X')
        ref_pos += e.len;
    }
  }
  if (indel_pos == -1) return cigar;
  std::string variant;
  if (is_insert) {
    variant = seq.substr(std::min((size_t)read_pos, seq.size()),
                         std::min((size_t)indel_len,
                                  seq.size() - std::min((size_t)read_pos,
                                                        seq.size())));
  } else {
    if (md == nullptr) return cigar;
    std::string ref;
    int rc = md_get_reference(*md, seq, cigar, ref);
    if (rc != 0) {
      *err = rc;
      return cigar;
    }
    variant = ref.substr(std::min((size_t)ref_pos, ref.size()),
                         std::min((size_t)indel_len,
                                  ref.size() - std::min((size_t)ref_pos,
                                                        ref.size())));
  }
  std::string preceding = seq.substr(0, std::min((size_t)read_pos, seq.size()));
  int64_t shift = positions_to_shift(variant, preceding);
  return shift_indel(cigar, indel_pos, shift);
}

// Consensus.generateAlternateConsensus (realign.py:623-641).
// Returns true when a consensus exists; fills (seq, index_start, index_end).
bool generate_alternate_consensus(const std::string& seq, int64_t start,
                                  const Cigar& cigar, std::string& cons,
                                  int64_t& idx_start, int64_t& idx_end) {
  int n_id = 0;
  for (const auto& e : cigar) n_id += (e.op == 'I' || e.op == 'D');
  if (n_id != 1) return false;
  int64_t read_pos = 0;
  int64_t ref_pos = start;
  for (const auto& e : cigar) {
    if (e.op == 'I') {
      cons = seq.substr(std::min((size_t)read_pos, seq.size()),
                        std::min((size_t)e.len,
                                 seq.size() - std::min((size_t)read_pos,
                                                       seq.size())));
      idx_start = ref_pos;
      idx_end = ref_pos + 1;
      return true;
    }
    if (e.op == 'D') {
      cons.clear();
      idx_start = ref_pos;
      idx_end = ref_pos + e.len + 1;
      return true;
    }
    if (e.op == 'M' || e.op == '=' || e.op == 'X') {
      read_pos += e.len;
      ref_pos += e.len;
    } else {
      return false;
    }
  }
  return false;
}

// ---- prep output ---------------------------------------------------------
struct PrepOut {
  // per group (G entries)
  std::vector<int32_t> t_status;  // 0 ok, 1 ref-gap skip, 2 no to_clean
  std::vector<std::string> t_ref;
  std::vector<int64_t> t_ref_start, t_ref_end;
  // per to_clean read, flattened in (group, to_clean order)
  std::vector<int32_t> r_group;
  std::vector<int64_t> r_row;
  std::vector<std::string> r_cigar;  // non-empty only when dirty
  std::vector<std::string> r_md;     // moved MD string when dirty+has md
  std::vector<uint8_t> r_md_set;     // r_md meaningful (may be "0")
  std::vector<uint8_t> r_dirty, r_pure;
  std::vector<int64_t> r_orig_qual;
  // per consensus candidate, flattened, deduped per group, order kept
  std::vector<int32_t> c_group;
  std::vector<std::string> c_seq;
  std::vector<int64_t> c_is, c_ie;
  int err = 0;        // 0 / 1 md-parse / 2 IndexError / 3 ValueError
  int64_t err_row = -1;
};

struct ReadState {
  int64_t row;
  std::string seq;
  Cigar cigar;
  Md md;
  bool has_md_eff;  // parsed md present (non-pure reads with MD)
  bool raw_has_md;  // the row has an MD string at all
  std::string ref;  // implied reference (empty+flag when absent)
  bool has_ref;
  bool pure;
  bool dirty;
  bool has_mm;  // any MD mismatch mapping inside an M/=/X op (in read)
  int64_t start;
  int64_t mm_qual;  // pure rows: MD-derived positional mismatch qual sum
};

// sumMismatchQualityIgnoreCigar (realign.py:526-536)
int64_t sum_mismatch_quality(const std::string& seq, const std::string& ref,
                             const uint8_t* quals, int64_t qlen) {
  int64_t n = std::min((int64_t)seq.size(), std::min((int64_t)ref.size(), qlen));
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i)
    if (seq[i] != ref[i]) acc += quals[i];
  return acc;
}

}  // namespace

extern "C" {

// MD-tag mismatch reference offsets (relative to the alignment start),
// ascending.  Lenient: malformed MD yields however many offsets parsed
// before the error (the vectorized tokenizer's tolerance).  Returns the
// count written (capped at cap).  Shared with adamtok.cpp's BQSR
// observe walk so the host never materializes [N, L] mismatch masks.
int64_t md_mismatch_offsets(const uint8_t* s, int64_t n, int64_t* out,
                            int64_t cap) {
  // reusable parse scratch: this runs once per read inside the BQSR
  // observe hot loop, so the Md vectors must not reallocate per call
  thread_local Md md;
  md_parse(s, n, 0, md);  // partial results kept on malformed input
  int64_t k = 0;
  for (const auto& p : md.mm) {
    if (k >= cap) break;
    out[k++] = p.first;
  }
  return k;
}

// Phase-1 prep over candidate target groups.  See realign.py phase 1.
// Columns are the candidate batch's; groups are (grows flat rows, goff
// offsets).  gen_consensus=0 for the "knowns" model.
void* realign_prep(
    const uint8_t* bases, const uint8_t* quals, int64_t N, int64_t L,
    const int32_t* lengths, const int64_t* start,
    const uint8_t* cigar_ops, const int32_t* cigar_lens,
    const int32_t* cigar_n, int64_t C,
    const uint8_t* md_buf, const int64_t* md_off, const uint8_t* md_valid,
    const int64_t* grows, const int64_t* goff, int64_t G,
    int gen_consensus) {
  auto* out = new PrepOut();
  out->t_status.assign(G, 0);
  out->t_ref.resize(G);
  out->t_ref_start.assign(G, 0);
  out->t_ref_end.assign(G, 0);

  std::vector<ReadState> reads;
  // (ref string, start, end) for the pure-clean majority rows
  std::vector<std::pair<std::string, std::pair<int64_t, int64_t>>> extra;

  for (int64_t g = 0; g < G && out->err == 0; ++g) {
    reads.clear();
    extra.clear();
    bool any_to_clean = false;
    for (int64_t k = goff[g]; k < goff[g + 1]; ++k) {
      int64_t i = grows[k];
      int64_t len_i = lengths[i];
      int32_t nc = cigar_n[i];
      bool has_md_i = md_valid[i] != 0;
      bool pure = nc == 1 && cigar_ops[i * C] == CIG_M;

      // decode seq from codes
      std::string seq(len_i, 'N');
      for (int64_t p = 0; p < len_i; ++p)
        seq[p] = BASE_DECODE[std::min<uint8_t>(bases[i * L + p], 5)];

      Md md;
      bool md_parsed = false;
      if (has_md_i) {
        const uint8_t* ms = md_buf + md_off[i];
        int64_t mn = md_off[i + 1] - md_off[i];
        md_parsed = md_parse(ms, mn, start[i], md);
        if (!md_parsed && !pure) {
          // the Python path raises from MdTag.parse for non-pure rows;
          // pure rows go through the lenient vectorized tokenizer
          out->err = 1;
          out->err_row = i;
          break;
        }
      }

      Cigar cig(nc);
      for (int32_t k2 = 0; k2 < nc; ++k2)
        cig[k2] = {cigar_lens[i * C + k2],
                   CIGAR_CHARS[std::min<uint8_t>(cigar_ops[i * C + k2], 8)]};

      // row_has_mm + mm_qual: MD mismatches mapped through the cigar to
      // read positions inside M/=/X ops (ops/mdtag.py batch_md_arrays)
      bool has_mm = false;
      int64_t mm_qual = 0;
      std::string pure_ref;
      if (has_md_i && md_parsed && !md.mm.empty()) {
        int64_t read_pos = 0, ref_off = 0;
        size_t mi = 0;
        for (const auto& e : cig) {
          bool q = e.op == 'M' || e.op == 'I' || e.op == 'S' || e.op == '=' ||
                   e.op == 'X';
          bool r = e.op == 'M' || e.op == 'D' || e.op == 'N' || e.op == '=' ||
                   e.op == 'X';
          if (q && r) {
            while (mi < md.mm.size() &&
                   md.mm[mi].first - start[i] < ref_off + e.len) {
              int64_t ro = md.mm[mi].first - start[i];
              if (ro >= ref_off) {
                int64_t rp = read_pos + (ro - ref_off);
                if (rp >= 0 && rp < L) {
                  has_mm = true;
                  mm_qual += quals[i * L + rp];
                }
              }
              ++mi;
            }
          } else if (r) {
            while (mi < md.mm.size() &&
                   md.mm[mi].first - start[i] < ref_off + e.len)
              ++mi;  // mismatch recorded inside a non-query op: not in_m
          }
          if (q) read_pos += e.len;
          if (r) ref_off += e.len;
        }
      }

      if (pure && has_md_i) {
        // implied reference from codes: seq patched at mismatch read
        // positions with the *code-mapped* MD base (IUPAC -> N), exactly
        // as the vectorized ref_codes path produces it
        pure_ref = seq;
        for (const auto& p : md.mm) {
          int64_t rp = p.first - start[i];
          if (rp >= 0 && rp < len_i)
            pure_ref[rp] = BASE_DECODE[base_encode(p.second)];
        }
        if (!has_mm) {
          // pure clean majority: reference contribution only
          extra.push_back({std::move(pure_ref),
                           {start[i], start[i] + len_i}});
          continue;
        }
      }

      ReadState rs;
      rs.row = i;
      rs.seq = std::move(seq);
      rs.cigar = std::move(cig);
      rs.raw_has_md = has_md_i;
      rs.has_md_eff = has_md_i && !pure;  // pure rows skip MdTag.parse
      if (rs.has_md_eff) rs.md = std::move(md);
      rs.pure = pure;
      rs.dirty = false;
      rs.has_mm = has_mm;
      rs.start = start[i];
      rs.mm_qual = mm_qual;
      rs.has_ref = false;
      if (!has_md_i) {
        // ref stays absent
      } else if (pure) {
        rs.ref = std::move(pure_ref);
        rs.has_ref = true;
      } else {
        int rc = md_get_reference(rs.md, rs.seq, rs.cigar, rs.ref);
        if (rc != 0) {
          out->err = rc;
          out->err_row = i;
          break;
        }
        rs.has_ref = true;
      }
      if (!has_md_i || has_mm) any_to_clean = true;
      reads.push_back(std::move(rs));
    }
    if (out->err != 0) break;
    if (!any_to_clean) {
      out->t_status[g] = 2;
      continue;
    }

    // _get_reference_from_reads (realign.py:572-599): refs = extra_refs
    // then reads (row order), stable-sorted by start
    {
      std::vector<std::pair<int64_t, const std::string*>> refs;
      std::vector<int64_t> ref_ends;
      std::vector<std::pair<std::pair<int64_t, int64_t>, const std::string*>>
          spans;
      for (const auto& ex : extra)
        spans.push_back({{ex.second.first, ex.second.second}, &ex.first});
      for (const auto& r : reads)
        if (r.has_ref)
          spans.push_back(
              {{r.start, r.start + cigar_ref_len(r.cigar)}, &r.ref});
      if (spans.empty()) {
        out->t_status[g] = 1;  // "no reads with MD tags" ValueError -> skip
        continue;
      }
      std::stable_sort(spans.begin(), spans.end(),
                       [](const auto& a, const auto& b) {
                         return a.first.first < b.first.first;
                       });
      std::string ref;
      int64_t cur = spans[0].first.first;
      int64_t ref_start = cur;
      bool gap = false;
      for (const auto& sp : spans) {
        int64_t s0 = sp.first.first, e0 = sp.first.second;
        if (e0 < cur) continue;
        if (cur >= s0) {
          ref.append(*sp.second, cur - s0, std::string::npos);
          cur = e0;
        } else {
          gap = true;
          break;
        }
      }
      if (gap) {
        out->t_status[g] = 1;
        continue;
      }
      out->t_ref[g] = std::move(ref);
      out->t_ref_start[g] = ref_start;
      out->t_ref_end[g] = cur;
    }

    // preprocess + emit to_clean reads (left-normalize 2-M-block reads)
    size_t cons_seen_base = out->c_seq.size();
    {
      bool emitted_any = false;
      for (size_t ri = 0; ri < reads.size(); ++ri) {
        auto& r = reads[ri];
        // to_clean membership (realign.py:844-846): no MD, or any MD
        // mismatch mapping inside an M op
        if (r.raw_has_md && !r.has_mm) continue;  // clean: skip
        // left-normalize single-indel (2 M-block) reads
        if (cigar_num_m_blocks(r.cigar) == 2) {
          int lerr = 0;
          Cigar nw = left_align_indel(r.seq, r.cigar,
                                      r.has_md_eff ? &r.md : nullptr, &lerr);
          if (lerr != 0) {
            out->err = lerr;
            out->err_row = r.row;
            break;
          }
          if (!(nw == r.cigar)) {
            if (r.has_md_eff) {
              Md moved;
              int rc = md_move_alignment(r.ref.data(), r.ref.size(), r.seq,
                                         nw, r.start, moved);
              if (rc != 0) {
                out->err = rc;
                out->err_row = r.row;
                break;
              }
              r.md = std::move(moved);
            }
            r.cigar = std::move(nw);
            r.dirty = true;
          }
        }
        // orig_qual (realign.py:957-966 _orig_qual)
        int64_t oq;
        const uint8_t* q = quals + r.row * L;
        if (r.dirty && r.has_md_eff) {
          std::string ref2;
          int rc = md_get_reference(r.md, r.seq, r.cigar, ref2);
          if (rc != 0) {
            out->err = rc;
            out->err_row = r.row;
            break;
          }
          oq = sum_mismatch_quality(r.seq, ref2, q, lengths[r.row]);
        } else if (r.pure) {
          oq = r.mm_qual;
        } else {
          oq = sum_mismatch_quality(r.seq, r.has_ref ? r.ref : std::string(),
                                    q, lengths[r.row]);
        }

        out->r_group.push_back((int32_t)g);
        out->r_row.push_back(r.row);
        out->r_cigar.push_back(r.dirty ? cigar_to_string(r.cigar)
                                       : std::string());
        if (r.dirty && r.has_md_eff) {
          out->r_md.push_back(md_to_string(r.md));
          out->r_md_set.push_back(1);
        } else {
          out->r_md.push_back(std::string());
          out->r_md_set.push_back(0);
        }
        out->r_dirty.push_back(r.dirty ? 1 : 0);
        out->r_pure.push_back(r.pure ? 1 : 0);
        out->r_orig_qual.push_back(oq);
        emitted_any = true;

        // consensus generation (reads model), post-preprocess cigar
        if (gen_consensus && r.has_md_eff) {
          std::string cons;
          int64_t cis, cie;
          if (generate_alternate_consensus(r.seq, r.start, r.cigar, cons,
                                           cis, cie)) {
            bool dup = false;
            for (size_t ci = cons_seen_base; ci < out->c_seq.size(); ++ci)
              if (out->c_is[ci] == cis && out->c_ie[ci] == cie &&
                  out->c_seq[ci] == cons) {
                dup = true;
                break;
              }
            if (!dup) {
              out->c_group.push_back((int32_t)g);
              out->c_seq.push_back(std::move(cons));
              out->c_is.push_back(cis);
              out->c_ie.push_back(cie);
            }
          }
        }
      }
      if (out->err != 0) break;
      if (!emitted_any) out->t_status[g] = 2;
    }
  }
  return out;
}

void realign_prep_dims(void* vh, int64_t* n_reads, int64_t* cigar_bytes,
                       int64_t* md_bytes, int64_t* n_cons, int64_t* cons_bytes,
                       int64_t* ref_bytes, int64_t* err, int64_t* err_row) {
  auto* h = static_cast<PrepOut*>(vh);
  *n_reads = (int64_t)h->r_row.size();
  int64_t cb = 0, mb = 0, sb = 0, rb = 0;
  for (const auto& s : h->r_cigar) cb += s.size();
  for (const auto& s : h->r_md) mb += s.size();
  for (const auto& s : h->c_seq) sb += s.size();
  for (const auto& s : h->t_ref) rb += s.size();
  *cigar_bytes = cb;
  *md_bytes = mb;
  *n_cons = (int64_t)h->c_seq.size();
  *cons_bytes = sb;
  *ref_bytes = rb;
  *err = h->err;
  *err_row = h->err_row;
}

void realign_prep_fill(
    void* vh,
    // per group
    int32_t* t_status, uint8_t* t_ref_buf, int64_t* t_ref_off,
    int64_t* t_ref_start, int64_t* t_ref_end,
    // per read
    int32_t* r_group, int64_t* r_row, uint8_t* r_cigar_buf,
    int64_t* r_cigar_off, uint8_t* r_md_buf, int64_t* r_md_off,
    uint8_t* r_md_set, uint8_t* r_dirty, uint8_t* r_pure,
    int64_t* r_orig_qual,
    // per consensus
    int32_t* c_group, uint8_t* c_seq_buf, int64_t* c_seq_off, int64_t* c_is,
    int64_t* c_ie) {
  auto* h = static_cast<PrepOut*>(vh);
  const int64_t G = (int64_t)h->t_status.size();
  int64_t off = 0;
  for (int64_t g = 0; g < G; ++g) {
    t_status[g] = h->t_status[g];
    t_ref_off[g] = off;
    memcpy(t_ref_buf + off, h->t_ref[g].data(), h->t_ref[g].size());
    off += h->t_ref[g].size();
    t_ref_start[g] = h->t_ref_start[g];
    t_ref_end[g] = h->t_ref_end[g];
  }
  t_ref_off[G] = off;
  const int64_t R = (int64_t)h->r_row.size();
  int64_t coff = 0, moff = 0;
  for (int64_t i = 0; i < R; ++i) {
    r_group[i] = h->r_group[i];
    r_row[i] = h->r_row[i];
    r_cigar_off[i] = coff;
    memcpy(r_cigar_buf + coff, h->r_cigar[i].data(), h->r_cigar[i].size());
    coff += h->r_cigar[i].size();
    r_md_off[i] = moff;
    memcpy(r_md_buf + moff, h->r_md[i].data(), h->r_md[i].size());
    moff += h->r_md[i].size();
    r_md_set[i] = h->r_md_set[i];
    r_dirty[i] = h->r_dirty[i];
    r_pure[i] = h->r_pure[i];
    r_orig_qual[i] = h->r_orig_qual[i];
  }
  r_cigar_off[R] = coff;
  r_md_off[R] = moff;
  const int64_t CN = (int64_t)h->c_seq.size();
  int64_t soff = 0;
  for (int64_t i = 0; i < CN; ++i) {
    c_group[i] = h->c_group[i];
    c_seq_off[i] = soff;
    memcpy(c_seq_buf + soff, h->c_seq[i].data(), h->c_seq[i].size());
    soff += h->c_seq[i].size();
    c_is[i] = h->c_is[i];
    c_ie[i] = h->c_ie[i];
  }
  c_seq_off[CN] = soff;
}

void realign_prep_free(void* vh) { delete static_cast<PrepOut*>(vh); }

// Batched MdTag.move_alignment + to_string for the rewrite phase
// (realign.py:1032-1037).  Each record k realigns read rows[k] against
// ref[tloc[k]] shifted by offs[k], with a 1- or 3-element cigar
// (head M / mid I|D / end M; mid_op==0 -> single M of head_len).
// Returns bytes written, or -(needed) when out_cap is too small;
// *err/*err_row report the first failing record (err codes as above).
int64_t md_move_batch(
    const uint8_t* bases, int64_t N, int64_t L, const int32_t* lengths,
    const int64_t* rows, int64_t K,
    const uint8_t* ref_buf, const int64_t* ref_off,
    const int32_t* tloc, const int64_t* offs,
    const int32_t* head_len, const int32_t* mid_len, const uint8_t* mid_op,
    const int32_t* end_len, const int64_t* new_start,
    uint8_t* out_buf, int64_t out_cap, int64_t* out_off,
    int64_t* err, int64_t* err_row) {
  *err = 0;
  *err_row = -1;
  std::vector<std::string> results(K);
  int64_t total = 0;
  for (int64_t k = 0; k < K; ++k) {
    int64_t row = rows[k];
    int64_t len_i = lengths[row];
    std::string seq(len_i, 'N');
    for (int64_t p = 0; p < len_i; ++p)
      seq[p] = BASE_DECODE[std::min<uint8_t>(bases[row * L + p], 5)];
    Cigar cig;
    if (mid_op[k] == 0) {
      cig.push_back({head_len[k], 'M'});
    } else {
      cig.push_back({head_len[k], 'M'});
      cig.push_back({mid_len[k], (char)mid_op[k]});
      cig.push_back({end_len[k], 'M'});
    }
    const uint8_t* rb = ref_buf + ref_off[tloc[k]] + offs[k];
    int64_t rlen = ref_off[tloc[k] + 1] - ref_off[tloc[k]] - offs[k];
    Md moved;
    int rc = md_move_alignment((const char*)rb, rlen, seq, cig, new_start[k],
                               moved);
    if (rc != 0) {
      *err = rc;
      *err_row = row;
      return 0;
    }
    results[k] = md_to_string(moved);
    total += results[k].size();
  }
  if (total > out_cap) return -total;
  int64_t off = 0;
  for (int64_t k = 0; k < K; ++k) {
    out_off[k] = off;
    memcpy(out_buf + off, results[k].data(), results[k].size());
    off += results[k].size();
  }
  out_off[K] = off;
  return total;
}

}  // extern "C"
