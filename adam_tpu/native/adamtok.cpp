// Native ingest kernels for adam_tpu: SAM tokenizer, BGZF decompressor,
// BAM record parser.
//
// The reference delegates this layer to JVM libraries (htsjdk record
// codecs, hadoop-bam splitting); here it is a small C++ library driven
// through ctypes that fills preallocated numpy arrays — the host-side
// analog of the reference's SAMRecordConverter
// (converters/SAMRecordConverter.scala:38-130) running at native speed so
// the TPU is never input-starved.
//
// Threading model: two-pass. A scan pass splits the input at record
// boundaries into per-thread chunks and sizes every output buffer; the
// fill pass writes disjoint ranges concurrently, then variable-width
// buffers (attrs/MD/OQ, which can shrink vs. their scan-pass capacity)
// are compacted serially.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <zlib.h>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr uint8_t BASE_N = 4;
constexpr uint8_t BASE_PAD = 5;
constexpr uint8_t CIGAR_PAD = 15;
constexpr uint8_t QUAL_PAD = 255;

struct Luts {
  uint8_t base[256];
  int8_t cigar[256];
  uint8_t bam_seq[16];  // BAM 4-bit "=ACMGRSVTWYHKDBN" -> code
  Luts() {
    memset(base, BASE_N, sizeof(base));
    base[uint8_t('A')] = 0; base[uint8_t('a')] = 0;
    base[uint8_t('C')] = 1; base[uint8_t('c')] = 1;
    base[uint8_t('G')] = 2; base[uint8_t('g')] = 2;
    base[uint8_t('T')] = 3; base[uint8_t('t')] = 3;
    base[uint8_t('*')] = BASE_PAD;
    memset(cigar, -1, sizeof(cigar));
    const char* ops = "MIDNSHP=X";
    for (int i = 0; ops[i]; ++i) cigar[uint8_t(ops[i])] = int8_t(i);
    const char* bs = "=ACMGRSVTWYHKDBN";
    for (int i = 0; i < 16; ++i) {
      switch (bs[i]) {
        case 'A': bam_seq[i] = 0; break;
        case 'C': bam_seq[i] = 1; break;
        case 'G': bam_seq[i] = 2; break;
        case 'T': bam_seq[i] = 3; break;
        default: bam_seq[i] = BASE_N;
      }
    }
  }
};
const Luts LUT;

// op consumes reference? (M,D,N,=,X)
inline bool consumes_ref(int op) {
  return op == 0 || op == 2 || op == 3 || op == 7 || op == 8;
}

inline int64_t parse_i64(const uint8_t* p, const uint8_t* end, bool* ok) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) { neg = (*p == '-'); ++p; }
  if (p >= end) { *ok = false; return 0; }
  int64_t v = 0;
  for (; p < end; ++p) {
    if (*p < '0' || *p > '9') { *ok = false; return 0; }
    v = v * 10 + (*p - '0');
  }
  *ok = true;
  return neg ? -v : v;
}

// shared row-range fan-out: fn(lo, hi) over [0, N) on up to nthreads
// threads (serial below 4096 rows, where thread spawn outweighs work)
template <class F>
void parallel_rows(int64_t N, int nthreads, F fn) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads == 1 || N < 4096) {
    fn(int64_t(0), N);
    return;
  }
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t)
    ts.emplace_back(fn, N * t / nthreads, N * (t + 1) / nthreads);
  for (auto& t : ts) t.join();
}

using Dict = std::unordered_map<std::string, int32_t>;

Dict build_dict(const uint8_t* buf, const int64_t* off, int32_t n) {
  Dict d;
  d.reserve(size_t(n) * 2);
  for (int32_t i = 0; i < n; ++i) {
    d.emplace(std::string(reinterpret_cast<const char*>(buf) + off[i],
                          size_t(off[i + 1] - off[i])), i);
  }
  return d;
}

inline int32_t dict_lookup(const Dict& d, const uint8_t* p, size_t len) {
  auto it = d.find(std::string(reinterpret_cast<const char*>(p), len));
  return it == d.end() ? -1 : it->second;
}

// One-entry memo in front of dict_lookup: SAM rows repeat the same
// RNAME (coordinate- or name-grouped inputs) for long runs, so a byte
// compare against the previous field skips the hash+string round trip.
struct MemoLookup {
  const Dict* d;
  std::string last;
  int32_t last_val = -2;  // -2: empty memo (-1 is a legit miss value)
  explicit MemoLookup(const Dict& dict) : d(&dict) {}
  int32_t operator()(const uint8_t* p, size_t len) {
    if (last_val != -2 && len == last.size() &&
        memcmp(p, last.data(), len) == 0)
      return last_val;
    last.assign(reinterpret_cast<const char*>(p), len);
    last_val = dict_lookup(*d, p, len);
    return last_val;
  }
};

// Positions of the first ``want`` tabs in [ls, le) -> fe[]; returns the
// count found.  AVX2: compare 32 bytes at a time and walk the movemask
// bits (~0.1 byte-compares/byte vs the scalar walk's 1); loads never
// cross ``le`` so chunk ends are safe.
inline int line_tabs(const uint8_t* ls, const uint8_t* le,
                     const uint8_t** fe, int want) {
  int found = 0;
#if defined(__AVX2__)
  const uint8_t* p = ls;
  const __m256i vt = _mm256_set1_epi8('\t');
  while (p < le && found < want) {
    size_t blk = size_t(le - p) < 32 ? size_t(le - p) : 32;
    __m256i v;
    if (blk == 32) {
      v = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(p));
    } else {
      uint8_t tmp[32] = {0};
      memcpy(tmp, p, blk);
      v = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(tmp));
    }
    uint32_t m = uint32_t(_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, vt)));
    if (blk < 32) m &= (uint32_t(1) << blk) - 1;
    while (m && found < want) {
      fe[found++] = p + __builtin_ctz(m);
      m &= m - 1;
    }
    p += blk;
  }
  return found;
#else
  for (const uint8_t* q = ls; q < le && found < want; ++q)
    if (*q == '\t') fe[found++] = q;
  return found;
#endif
}

// ASCII sequence -> base codes (A/C/G/T case-insensitive, '*' -> PAD,
// everything else -> N), the vector twin of LUT.base.
inline void encode_bases(const uint8_t* src, uint8_t* dst, int64_t L) {
  int64_t j = 0;
#if defined(__AVX2__)
  const __m256i up_mask = _mm256_set1_epi8(char(0xDF));
  const __m256i cA = _mm256_set1_epi8('A'), cC = _mm256_set1_epi8('C');
  const __m256i cG = _mm256_set1_epi8('G'), cT = _mm256_set1_epi8('T');
  const __m256i cStar = _mm256_set1_epi8('*');
  const __m256i v0 = _mm256_setzero_si256(), v1 = _mm256_set1_epi8(1);
  const __m256i v2 = _mm256_set1_epi8(2), v3 = _mm256_set1_epi8(3);
  const __m256i vN = _mm256_set1_epi8(char(BASE_N));
  const __m256i vPad = _mm256_set1_epi8(char(BASE_PAD));
  for (; j + 32 <= L; j += 32) {
    __m256i raw = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(src + j));
    __m256i up = _mm256_and_si256(raw, up_mask);
    __m256i r = vN;
    r = _mm256_blendv_epi8(r, v0, _mm256_cmpeq_epi8(up, cA));
    r = _mm256_blendv_epi8(r, v1, _mm256_cmpeq_epi8(up, cC));
    r = _mm256_blendv_epi8(r, v2, _mm256_cmpeq_epi8(up, cG));
    r = _mm256_blendv_epi8(r, v3, _mm256_cmpeq_epi8(up, cT));
    r = _mm256_blendv_epi8(r, vPad, _mm256_cmpeq_epi8(raw, cStar));
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + j), r);
  }
#endif
  for (; j < L; ++j) dst[j] = LUT.base[src[j]];
}

// ---------------------------------------------------------------- SAM ----

struct SamDims {
  int64_t n_records = 0;
  int64_t name_bytes = 0;
  int64_t tag_bytes = 0;  // raw tag-region bytes (capacity for attrs/MD/OQ)
  int32_t lmax = 0;
  int32_t cmax = 0;
  bool malformed = false;
};

struct SamChunk {
  int64_t begin = 0, end = 0;     // byte range in buf
  SamDims dims;
  int64_t rec0 = 0;               // record index base
  int64_t name0 = 0;              // name buffer base (exact)
  int64_t tag0 = 0;               // attrs/md/oq capacity-region base
  int64_t attr_used = 0, md_used = 0, oq_used = 0;
};

struct SamHandle {
  const uint8_t* buf = nullptr;
  int64_t n = 0;
  std::vector<SamChunk> chunks;
  SamDims total;
};

void sam_scan_chunk(const uint8_t* buf, SamChunk* c) {
  const uint8_t* p = buf + c->begin;
  const uint8_t* end = buf + c->end;
  SamDims& d = c->dims;
  const uint8_t* tabs[11];
  while (p < end) {
    const uint8_t* nl = static_cast<const uint8_t*>(
        memchr(p, '\n', size_t(end - p)));
    const uint8_t* le = nl ? nl : end;
    const uint8_t* ls = p;
    p = nl ? nl + 1 : end;
    if (le > ls && le[-1] == '\r') --le;
    if (le == ls || *ls == '@') continue;
    ++d.n_records;
    // 11 mandatory fields need 10 tabs; an 11th tab opens the tag region
    int nt = line_tabs(ls, le, tabs, 11);
    if (nt < 10) { d.malformed = true; return; }
    d.name_bytes += tabs[0] - ls;
    if (nt == 11) d.tag_bytes += (le - (tabs[10] + 1)) + 1;
    const uint8_t* ss = tabs[8] + 1;
    const uint8_t* se = tabs[9];
    int32_t L = 0;
    if (!(se - ss == 1 && *ss == '*')) L = int32_t(se - ss);
    if (L > d.lmax) d.lmax = L;
    const uint8_t* cs = tabs[4] + 1;
    const uint8_t* ce = tabs[5];
    int32_t nc = 0;
    if (!(ce - cs == 1 && *cs == '*')) {
      for (const uint8_t* q = cs; q < ce; ++q)
        if (*q < '0' || *q > '9') ++nc;
    }
    if (nc > d.cmax) d.cmax = nc;
  }
}

struct SamOut {
  int32_t *flags, *contig_idx, *mapq, *mate_contig_idx, *tlen, *rg_idx,
      *lengths, *cigar_lens, *cigar_n;
  int64_t *start, *end, *mate_start;
  uint8_t *has_qual, *bases, *quals, *cigar_ops;
  int64_t lmax, cmax;
  uint8_t *name_buf, *attr_buf, *md_buf, *oq_buf;
  int64_t *name_off, *attr_off, *md_off, *oq_off;
  uint8_t *md_present, *oq_present;
};

bool sam_fill_chunk(const uint8_t* buf, SamChunk* c, const Dict& contigs,
                    const Dict& rgs, SamOut* o) {
  const uint8_t* p = buf + c->begin;
  const uint8_t* end = buf + c->end;
  int64_t r = c->rec0;
  int64_t npos = c->name0;
  int64_t apos = c->tag0, mpos = c->tag0, qpos = c->tag0;
  const int64_t acap = c->tag0 + c->dims.tag_bytes;
  MemoLookup contig_memo(contigs), rnext_memo(contigs), rg_memo(rgs);
  const uint8_t* tabs[11];
  while (p < end) {
    const uint8_t* nl = static_cast<const uint8_t*>(
        memchr(p, '\n', size_t(end - p)));
    const uint8_t* le = nl ? nl : end;
    const uint8_t* ls = p;
    p = nl ? nl + 1 : end;
    if (le > ls && le[-1] == '\r') --le;
    if (le == ls || *ls == '@') continue;
    // split first 11 fields off the SIMD tab index
    int nt = line_tabs(ls, le, tabs, 11);
    if (nt < 10) return false;
    const uint8_t* f[11];
    const uint8_t* fe[11];
    f[0] = ls;
    for (int k = 0; k < 10; ++k) {
      fe[k] = tabs[k];
      f[k + 1] = tabs[k] + 1;
    }
    fe[10] = nt == 11 ? tabs[10] : le;
    const uint8_t* tags = nt == 11 ? tabs[10] + 1 : le + 1;

    bool ok = true, allok = true;
    int64_t flag = parse_i64(f[1], fe[1], &ok); allok &= ok;
    int64_t pos1 = parse_i64(f[3], fe[3], &ok); allok &= ok;
    int64_t mapq = parse_i64(f[4], fe[4], &ok); allok &= ok;
    int64_t pnext = parse_i64(f[7], fe[7], &ok); allok &= ok;
    int64_t tl = parse_i64(f[8], fe[8], &ok); allok &= ok;
    if (!allok) return false;

    o->flags[r] = int32_t(flag);
    o->mapq[r] = int32_t(mapq);
    o->tlen[r] = int32_t(tl);

    bool rname_star = (fe[2] - f[2] == 1 && *f[2] == '*');
    int32_t ci = rname_star ? -1 : contig_memo(f[2], size_t(fe[2] - f[2]));
    o->contig_idx[r] = ci;
    int64_t start = (!rname_star && pos1 > 0) ? pos1 - 1 : -1;
    o->start[r] = start;

    bool rnext_star = (fe[6] - f[6] == 1 && *f[6] == '*');
    bool rnext_eq = (fe[6] - f[6] == 1 && *f[6] == '=');
    o->mate_contig_idx[r] =
        rnext_star ? -1
                   : (rnext_eq ? ci : rnext_memo(f[6], size_t(fe[6] - f[6])));
    o->mate_start[r] = pnext > 0 ? pnext - 1 : -1;

    // name
    size_t nlen = size_t(fe[0] - f[0]);
    memcpy(o->name_buf + npos, f[0], nlen);
    o->name_off[r] = npos;
    npos += nlen;

    // sequence + qualities
    uint8_t* brow = o->bases + r * o->lmax;
    uint8_t* qrow = o->quals + r * o->lmax;
    memset(brow, BASE_PAD, size_t(o->lmax));
    memset(qrow, QUAL_PAD, size_t(o->lmax));
    int32_t L = 0;
    if (!(fe[9] - f[9] == 1 && *f[9] == '*')) {
      L = int32_t(fe[9] - f[9]);
      encode_bases(f[9], brow, L);
    }
    o->lengths[r] = L;
    bool qual_star = (fe[10] - f[10] == 1 && *f[10] == '*');
    if (!qual_star) {
      int32_t QL = int32_t(fe[10] - f[10]);
      for (int32_t k = 0; k < QL && k < o->lmax; ++k)
        qrow[k] = uint8_t(f[10][k] - 33);
      o->has_qual[r] = 1;
    } else {
      o->has_qual[r] = 0;
      for (int32_t k = 0; k < L; ++k) qrow[k] = 0;
    }

    // cigar
    uint8_t* crow = o->cigar_ops + r * o->cmax;
    int32_t* clrow = o->cigar_lens + r * o->cmax;
    memset(crow, CIGAR_PAD, size_t(o->cmax));
    memset(clrow, 0, size_t(o->cmax) * 4);
    int32_t nc = 0;
    int64_t ref_span = 0;
    if (!(fe[5] - f[5] == 1 && *f[5] == '*')) {
      int64_t num = 0;
      for (const uint8_t* q = f[5]; q < fe[5]; ++q) {
        if (*q >= '0' && *q <= '9') {
          num = num * 10 + (*q - '0');
        } else {
          int8_t op = LUT.cigar[*q];
          if (op < 0 || nc >= o->cmax) return false;
          crow[nc] = uint8_t(op);
          clrow[nc] = int32_t(num);
          if (consumes_ref(op)) ref_span += num;
          num = 0;
          ++nc;
        }
      }
    }
    o->cigar_n[r] = nc;
    o->end[r] = start >= 0 ? start + ref_span : -1;

    // tags: extract MD/OQ/RG, everything else -> attrs
    o->attr_off[r] = apos;
    o->md_off[r] = mpos;
    o->oq_off[r] = qpos;
    o->md_present[r] = 0;
    o->oq_present[r] = 0;
    int32_t rg = -1;
    bool rg_seen = false;
    int64_t attr_start = apos;
    const uint8_t* t = tags;
    while (t <= le && t < le) {
      const uint8_t* te = static_cast<const uint8_t*>(
          memchr(t, '\t', size_t(le - t)));
      if (!te) te = le;
      size_t tlen_ = size_t(te - t);
      if (tlen_ >= 5 && t[2] == ':' && t[4] == ':') {
        if (t[0] == 'M' && t[1] == 'D' && t[3] == 'Z') {
          mpos = o->md_off[r];  // duplicate MD: last one wins (overwrite)
          memcpy(o->md_buf + mpos, t + 5, tlen_ - 5);
          mpos += tlen_ - 5;
          o->md_present[r] = 1;
          t = te + 1;
          continue;
        }
        if (t[0] == 'O' && t[1] == 'Q' && t[3] == 'Z') {
          qpos = o->oq_off[r];  // duplicate OQ: last one wins
          memcpy(o->oq_buf + qpos, t + 5, tlen_ - 5);
          qpos += tlen_ - 5;
          o->oq_present[r] = 1;
          t = te + 1;
          continue;
        }
        if (t[0] == 'R' && t[1] == 'G' && t[3] == 'Z' && !rg_seen) {
          // First RG tag becomes the column; an RG naming a group absent
          // from the header stays in attrs so round-trip preserves it.
          rg_seen = true;
          rg = rg_memo(t + 5, tlen_ - 5);
          if (rg >= 0) {
            t = te + 1;
            continue;
          }
        }
      }
      if (apos + int64_t(tlen_) + 1 > acap) return false;
      if (apos > attr_start) o->attr_buf[apos++] = '\t';
      memcpy(o->attr_buf + apos, t, tlen_);
      apos += tlen_;
      t = te + 1;
    }
    o->rg_idx[r] = rg;
    ++r;
  }
  // close the per-chunk offsets with sentinel end positions
  c->attr_used = apos - c->tag0;
  c->md_used = mpos - c->tag0;
  c->oq_used = qpos - c->tag0;
  return true;
}

// ---------------------------------------------------------------- BGZF ----

struct BgzfBlock {
  int64_t comp_off;   // offset of deflate payload
  int64_t comp_len;
  int64_t out_off;
  int64_t out_len;
  uint32_t crc;       // expected CRC32 of the decompressed payload
};

struct BgzfHandle {
  const uint8_t* buf;
  int64_t n;
  std::vector<BgzfBlock> blocks;
  int64_t out_bytes = 0;
  int64_t consumed = 0;
};

// returns header length and total block size via *bsize; -1 if not BGZF
// (bad magic / no BC subfield), -2 if the header is cut short by the end
// of the buffer (streaming windows need more bytes, not an error)
int64_t bgzf_block_header(const uint8_t* p, int64_t avail, int64_t* bsize) {
  if (avail >= 1 && p[0] != 0x1f) return -1;
  if (avail >= 2 && p[1] != 0x8b) return -1;
  if (avail >= 3 && p[2] != 8) return -1;
  if (avail >= 4 && !(p[3] & 4)) return -1;
  if (avail < 18) return -2;
  uint16_t xlen = uint16_t(p[10]) | (uint16_t(p[11]) << 8);
  if (avail < 12 + xlen) return -2;
  const uint8_t* x = p + 12;
  const uint8_t* xe = x + xlen;
  while (x + 4 <= xe) {
    uint8_t si1 = x[0], si2 = x[1];
    uint16_t slen = uint16_t(x[2]) | (uint16_t(x[3]) << 8);
    if (si1 == 66 && si2 == 67 && slen == 2) {
      *bsize = int64_t(uint16_t(x[4]) | (uint16_t(x[5]) << 8)) + 1;
      return 12 + xlen;
    }
    x += 4 + slen;
  }
  return -1;
}

// ---------------------------------------------------------------- BAM ----

struct BamHandle {
  const uint8_t* buf;     // decompressed BAM stream
  int64_t n;
  int64_t records_off;
  std::vector<int64_t> rec_off;  // offset of each record's block_size field
  int64_t name_bytes = 0;
  int64_t tag_bytes = 0;  // capacity estimate for stringified tags
  int64_t consumed = 0;
  int32_t lmax = 0, cmax = 0;
};

int bam_tags_to_text(const uint8_t* t, const uint8_t* te, char* out,
                     int64_t cap, int64_t* used, int32_t* rg,
                     const Dict& rgs, char* md, int64_t* md_len,
                     char* oq, int64_t* oq_len) {
  int64_t w = 0;
  *md_len = -1;
  *oq_len = -1;
  bool rg_seen = false;
  auto put = [&](const char* s, int64_t len) -> bool {
    if (w + len > cap) return false;
    memcpy(out + w, s, size_t(len));
    w += len;
    return true;
  };
  char tmp[64];
  while (t + 3 <= te) {
    char tag0 = char(t[0]), tag1 = char(t[1]), typ = char(t[2]);
    t += 3;
    if (typ == 'Z' || typ == 'H') {
      const uint8_t* z = static_cast<const uint8_t*>(
          memchr(t, 0, size_t(te - t)));
      if (!z) return -1;
      int64_t len = z - t;
      if (tag0 == 'M' && tag1 == 'D' && typ == 'Z') {
        memcpy(md, t, size_t(len)); *md_len = len;
      } else if (tag0 == 'O' && tag1 == 'Q' && typ == 'Z') {
        memcpy(oq, t, size_t(len)); *oq_len = len;
      } else if (tag0 == 'R' && tag1 == 'G' && typ == 'Z' && !rg_seen) {
        // First RG tag becomes the column; keep unresolvable RG in attrs.
        rg_seen = true;
        *rg = dict_lookup(rgs, t, size_t(len));
        if (*rg < 0) {
          if (w) { if (!put("\t", 1)) return -1; }
          if (!put("RG:Z:", 5) ||
              !put(reinterpret_cast<const char*>(t), len))
            return -1;
        }
      } else {
        if (w) { if (!put("\t", 1)) return -1; }
        int n = snprintf(tmp, sizeof(tmp), "%c%c:%c:", tag0, tag1, typ);
        if (!put(tmp, n) || !put(reinterpret_cast<const char*>(t), len))
          return -1;
      }
      t = z + 1;
      continue;
    }
    // fixed-width values: verify the bytes exist before reading them
    int64_t fixed = (typ == 'A' || typ == 'c' || typ == 'C') ? 1
                    : (typ == 's' || typ == 'S')             ? 2
                    : (typ == 'i' || typ == 'I' || typ == 'f') ? 4
                    : (typ == 'B')                            ? 5
                                                              : -1;
    if (fixed < 0 || t + fixed > te) return -1;
    if (w) { if (!put("\t", 1)) return -1; }
    int n;
    switch (typ) {
      case 'A':
        n = snprintf(tmp, sizeof(tmp), "%c%c:A:%c", tag0, tag1, char(*t));
        t += 1;
        if (!put(tmp, n)) return -1;
        break;
      case 'c': case 'C': case 's': case 'S': case 'i': case 'I': {
        int64_t v;
        if (typ == 'c') { v = int8_t(t[0]); t += 1; }
        else if (typ == 'C') { v = t[0]; t += 1; }
        else if (typ == 's') { v = int16_t(t[0] | (t[1] << 8)); t += 2; }
        else if (typ == 'S') { v = uint16_t(t[0] | (t[1] << 8)); t += 2; }
        else if (typ == 'i') {
          v = int32_t(uint32_t(t[0]) | (uint32_t(t[1]) << 8) |
                      (uint32_t(t[2]) << 16) | (uint32_t(t[3]) << 24));
          t += 4;
        } else {
          v = int64_t(uint32_t(t[0]) | (uint32_t(t[1]) << 8) |
                      (uint32_t(t[2]) << 16) | (uint32_t(t[3]) << 24));
          t += 4;
        }
        n = snprintf(tmp, sizeof(tmp), "%c%c:i:%lld", tag0, tag1,
                     static_cast<long long>(v));
        if (!put(tmp, n)) return -1;
        break;
      }
      case 'f': {
        float fv;
        memcpy(&fv, t, 4);
        t += 4;
        n = snprintf(tmp, sizeof(tmp), "%c%c:f:%g", tag0, tag1, double(fv));
        if (!put(tmp, n)) return -1;
        break;
      }
      case 'B': {
        char sub = char(*t);
        uint32_t cnt;
        memcpy(&cnt, t + 1, 4);
        t += 5;
        int size;
        switch (sub) {
          case 'c': case 'C': size = 1; break;
          case 's': case 'S': size = 2; break;
          case 'i': case 'I': case 'f': size = 4; break;
          default: return -1;  // unknown array subtype
        }
        if (t + int64_t(cnt) * size > te) return -1;  // corrupt count
        n = snprintf(tmp, sizeof(tmp), "%c%c:B:%c", tag0, tag1, sub);
        if (!put(tmp, n)) return -1;
        for (uint32_t k = 0; k < cnt; ++k) {
          const uint8_t* e = t + k * size;
          if (sub == 'f') {
            float fv; memcpy(&fv, e, 4);
            n = snprintf(tmp, sizeof(tmp), ",%g", double(fv));
          } else {
            int64_t v;
            switch (sub) {
              case 'c': v = int8_t(e[0]); break;
              case 'C': v = e[0]; break;
              case 's': v = int16_t(e[0] | (e[1] << 8)); break;
              case 'S': v = uint16_t(e[0] | (e[1] << 8)); break;
              case 'i': v = int32_t(uint32_t(e[0]) | (uint32_t(e[1]) << 8) |
                                    (uint32_t(e[2]) << 16) |
                                    (uint32_t(e[3]) << 24)); break;
              default:  v = int64_t(uint32_t(e[0]) | (uint32_t(e[1]) << 8) |
                                    (uint32_t(e[2]) << 16) |
                                    (uint32_t(e[3]) << 24)); break;
            }
            n = snprintf(tmp, sizeof(tmp), ",%lld",
                         static_cast<long long>(v));
          }
          if (!put(tmp, n)) return -1;
        }
        t += int64_t(cnt) * size;
        break;
      }
      default:
        return -1;
    }
  }
  *used = w;
  return 0;
}

}  // namespace

// ----------------------------------------------------- BAM encoding ----

namespace bamenc {  // NOLINT — internal helpers

// SAM text tag field ("NM:i:5") -> binary BAM tag bytes appended to out
// (nullptr = size-only pass).  Returns bytes produced, or -1 on a
// malformed field.
inline int64_t tag_to_bin(const uint8_t* f, const uint8_t* fe, uint8_t* out) {
  if (fe - f < 5 || f[2] != ':' || f[4] != ':') return -1;
  const uint8_t* val = f + 5;
  int64_t vlen = fe - val;
  char typ = char(f[3]);
  int64_t w = 0;
  // strtof needs a NUL terminator; the attrs buffer has none, so copy the
  // bounded [p, pe) field into a stack buffer before parsing (ADVICE r2)
  auto parse_f32 = [](const uint8_t* p, const uint8_t* pe) -> float {
    char buf[64];
    size_t n = size_t(pe - p);
    if (n >= sizeof(buf)) n = sizeof(buf) - 1;
    memcpy(buf, p, n);
    buf[n] = 0;
    return strtof(buf, nullptr);
  };
  auto put8 = [&](uint8_t v) { if (out) out[w] = v; ++w; };
  auto put_bytes = [&](const uint8_t* p, int64_t n) {
    if (out) memcpy(out + w, p, size_t(n));
    w += n;
  };
  auto parse_num = [&](const uint8_t* p, const uint8_t* pe, int64_t* ok_v,
                       bool* ok) {
    bool o = true;
    int64_t v = parse_i64(p, pe, &o);
    *ok = o;
    *ok_v = v;
  };
  put8(f[0]);
  put8(f[1]);
  switch (typ) {
    case 'A':
      if (vlen != 1) return -1;
      put8('A');
      put8(val[0]);
      break;
    case 'i': {
      bool ok;
      int64_t v;
      parse_num(val, fe, &v, &ok);
      if (!ok) return -1;
      int32_t v32 = int32_t(v);
      put8('i');
      put_bytes(reinterpret_cast<uint8_t*>(&v32), 4);
      break;
    }
    case 'f': {
      float fv = parse_f32(val, fe);
      put8('f');
      put_bytes(reinterpret_cast<uint8_t*>(&fv), 4);
      break;
    }
    case 'Z':
    case 'H':
      put8(uint8_t(typ));
      put_bytes(val, vlen);
      put8(0);
      break;
    case 'B': {
      if (vlen < 1) return -1;
      char sub = char(val[0]);
      put8('B');
      put8(uint8_t(sub));
      // count elements
      uint32_t cnt = 0;
      for (const uint8_t* p = val + 1; p < fe; ++p)
        if (*p == ',') ++cnt;
      put_bytes(reinterpret_cast<uint8_t*>(&cnt), 4);
      const uint8_t* p = val + 1;
      while (p < fe && *p == ',') {
        ++p;
        const uint8_t* q = p;
        while (q < fe && *q != ',') ++q;
        if (sub == 'f') {
          float fv = parse_f32(p, q);
          put_bytes(reinterpret_cast<uint8_t*>(&fv), 4);
        } else {
          bool ok;
          int64_t v;
          parse_num(p, q, &v, &ok);
          if (!ok) return -1;
          switch (sub) {
            case 'c': case 'C': {
              uint8_t b = uint8_t(v); put_bytes(&b, 1); break;
            }
            case 's': case 'S': {
              uint16_t s16 = uint16_t(v);
              put_bytes(reinterpret_cast<uint8_t*>(&s16), 2);
              break;
            }
            case 'i': case 'I': {
              uint32_t u32 = uint32_t(v);
              put_bytes(reinterpret_cast<uint8_t*>(&u32), 4);
              break;
            }
            default: return -1;
          }
        }
        p = q;
      }
      break;
    }
    default:
      return -1;
  }
  return w;
}

// All tags for one record (attrs text + MD/OQ/RG appended in the writer's
// order) -> binary; out == nullptr for the size pass.
inline int64_t tags_to_bin(
    const uint8_t* attr, int64_t attr_len,
    const uint8_t* md, int64_t md_len, bool has_md,
    const uint8_t* oq, int64_t oq_len, bool has_oq,
    const uint8_t* rg, int64_t rg_len, bool has_rg,
    uint8_t* out) {
  int64_t w = 0;
  const uint8_t* p = attr;
  const uint8_t* pe = attr + attr_len;
  while (p < pe) {
    const uint8_t* q = static_cast<const uint8_t*>(
        memchr(p, '\t', size_t(pe - p)));
    const uint8_t* fe = q ? q : pe;
    if (fe > p) {
      int64_t n = tag_to_bin(p, fe, out ? out + w : nullptr);
      if (n < 0) return -1;
      w += n;
    }
    p = q ? q + 1 : pe;
  }
  auto put_z = [&](char a, char b, const uint8_t* v, int64_t n) {
    if (out) {
      out[w] = uint8_t(a);
      out[w + 1] = uint8_t(b);
      out[w + 2] = 'Z';
      memcpy(out + w + 3, v, size_t(n));
      out[w + 3 + n] = 0;
    }
    w += n + 4;
  };
  if (has_md) put_z('M', 'D', md, md_len);
  if (has_oq) put_z('O', 'Q', oq, oq_len);
  if (has_rg) put_z('R', 'G', rg, rg_len);
  return w;
}

}  // namespace bamenc

extern "C" {

int adamtok_version() { return 5; }

// ------------------------------------------------------ BQSR observe ----

// Dense covariate histogram: the host twin of pipelines/bqsr.
// observe_kernel (scatter-add over (rg, qual, cycle, dinuc)), used on
// single-device topologies where there is no cross-chip psum to win;
// per-thread local histograms merged at the end keep it deterministic.
// residue_ok may be nullptr: the aligned-to-reference filter (M/=/X
// spans) plus q>0 / base<4 checks are then computed from the cigar
// columns in-loop — no [N, L] mask or position array ever materializes
// on the host (known-SNP masking passes an explicit mask instead).
// snp_keys (may be null): sorted (contig << 40 | ref_pos) known-SNP site
// keys; residues at those reference positions are skipped (the dbSNP
// masking of BaseQualityRecalibration) without any [N, L] host mask.
int64_t md_mismatch_offsets(const uint8_t* s, int64_t n, int64_t* out,
                            int64_t cap);  // realign.cpp

void bqsr_observe(
    const uint8_t* bases, const uint8_t* quals, const int32_t* lengths,
    const int32_t* flags, const int32_t* rg_idx,
    const uint8_t* cigar_ops, const int32_t* cigar_lens,
    const int32_t* cigar_n, int64_t cmax,
    const int32_t* contig_idx, const int64_t* start,
    const int64_t* snp_keys, int64_t n_snps,
    const uint8_t* residue_ok, const uint8_t* is_mm, const uint8_t* read_ok,
    const uint8_t* md_buf, const int64_t* md_off,
    int64_t N, int64_t lmax, int32_t n_rg, int64_t gl,
    int64_t* total, int64_t* mism, int nthreads) {
  static const uint8_t kComp[6] = {3, 2, 1, 0, 4, 5};
  constexpr int32_t kNQual = 94, kNDinuc = 17, kDinucNone = 16;
  const int64_t n_cyc = 2 * gl + 1;
  const int64_t size = int64_t(n_rg) * kNQual * n_cyc * kNDinuc;
  memset(total, 0, size_t(size) * 8);
  memset(mism, 0, size_t(size) * 8);
  if (nthreads < 1) nthreads = 1;
  int nt = (N < 4096) ? 1 : nthreads;
  // each thread owns a private histogram pair (16 bytes/cell); cap the
  // fan-out so the scratch stays under ~1 GB even for many read groups
  constexpr int64_t kScratchBudget = 1LL << 30;
  int64_t max_nt = kScratchBudget / (size * 16);
  if (max_nt < 1) max_nt = 1;
  if (nt > max_nt) nt = int(max_nt);
  std::vector<std::vector<int64_t>> loc_t(nt), loc_m(nt);
  auto work = [&](int t, int64_t lo, int64_t hi) {
    auto& lt = loc_t[t];
    auto& lm = loc_m[t];
    lt.assign(size_t(size), 0);
    lm.assign(size_t(size), 0);
    // per-thread scratch: aligned-span flags + reference positions +
    // inline-parsed MD mismatch offsets (is_mm == nullptr mode)
    std::vector<uint8_t> aligned(static_cast<size_t>(lmax), 0);
    std::vector<int64_t> refp(static_cast<size_t>(lmax), -1);
    std::vector<int64_t> mm_ro(static_cast<size_t>(4 * lmax + 8), 0);
    const bool mask_snps = snp_keys && n_snps > 0;
    for (int64_t i = lo; i < hi; ++i) {
      if (!read_ok[i]) continue;
      const uint8_t* bs = bases + i * lmax;
      const uint8_t* q = quals + i * lmax;
      const uint8_t* rok = residue_ok ? residue_ok + i * lmax : nullptr;
      const uint8_t* mm = is_mm ? is_mm + i * lmax : nullptr;
      int64_t n_mm = 0, mp = 0;
      if (!mm && md_buf && md_off) {
        n_mm = md_mismatch_offsets(md_buf + md_off[i],
                                   md_off[i + 1] - md_off[i], mm_ro.data(),
                                   int64_t(mm_ro.size()));
        // count == cap means the scratch may have truncated a
        // pathological MD tag; grow and re-parse rather than silently
        // dropping tail mismatches from the histogram
        while (n_mm == int64_t(mm_ro.size())) {
          mm_ro.resize(mm_ro.size() * 2);
          n_mm = md_mismatch_offsets(md_buf + md_off[i],
                                     md_off[i + 1] - md_off[i],
                                     mm_ro.data(), int64_t(mm_ro.size()));
        }
      }
      int64_t L = lengths[i];
      int32_t fl = flags[i];
      bool rev = fl & 0x10;
      bool second = (fl & 0x1) && (fl & 0x80);
      int64_t initial = rev ? (second ? -L : L) : (second ? -1 : 1);
      int64_t inc = rev ? (second ? 1 : -1) : (second ? -1 : 1);
      int32_t rg = rg_idx[i] >= 0 && rg_idx[i] < n_rg ? rg_idx[i] : n_rg - 1;
      // per-read SNP window: one binary search to the first site key at
      // or past this read's start, then a merge pointer over the
      // ascending refp walk — O(1) amortized per residue instead of a
      // log2(n_snps) search at every aligned base
      const int64_t* snp_it = nullptr;
      const int64_t* snp_end = nullptr;
      if (mask_snps && !rok) {
        int64_t key0 =
            (int64_t(contig_idx ? contig_idx[i] : 0) << 40) |
            (start ? start[i] : 0);
        snp_end = snp_keys + n_snps;
        snp_it = std::lower_bound(snp_keys, snp_end, key0);
      }
      if (!rok || !mm) {
        // mark query positions consumed by reference-aligned ops (M/=/X),
        // recording each one's reference position for SNP masking
        static const uint8_t kQ[16] = {1, 1, 0, 0, 1, 0, 0, 1, 1,
                                       0, 0, 0, 0, 0, 0, 0};
        memset(aligned.data(), 0, size_t(lmax));
        int64_t qp = 0;
        int64_t rp = start ? start[i] : 0;
        int nc = cigar_n[i] > cmax ? int(cmax) : cigar_n[i];
        for (int k = 0; k < nc && qp < lmax; ++k) {
          uint8_t op = cigar_ops[i * cmax + k] & 15;
          int64_t len = cigar_lens[i * cmax + k];
          if (len < 0) len = 0;
          bool cq = kQ[op];
          bool cr = consumes_ref(op);
          if (cq && cr) {
            int64_t stop = qp + len;
            if (stop > lmax) stop = lmax;
            for (int64_t j2 = qp; j2 < stop; ++j2) {
              aligned[size_t(j2)] = 1;
              refp[size_t(j2)] = rp + (j2 - qp);
            }
          }
          if (cq) qp += len;
          if (cr) rp += len;
        }
      }
      for (int64_t j = 0; j < L && j < lmax; ++j) {
        if (rok) {
          if (!rok[j]) continue;
        } else {
          if (!aligned[size_t(j)] || q[j] == 0 || q[j] >= QUAL_PAD ||
              bs[j] >= 4)
            continue;
          if (mask_snps) {
            int64_t key =
                (int64_t(contig_idx ? contig_idx[i] : 0) << 40) |
                refp[size_t(j)];
            while (snp_it != snp_end && *snp_it < key) ++snp_it;
            if (snp_it != snp_end && *snp_it == key) continue;
          }
        }
        int64_t cyc = initial + inc * j + gl;
        uint8_t cur = bs[j], prev;
        bool first_machine;
        if (rev) {
          cur = kComp[cur > 5 ? 5 : cur];
          uint8_t nb = (j + 1 < L) ? bs[j + 1] : 5;
          prev = kComp[nb > 5 ? 5 : nb];
          first_machine = (j == L - 1);
        } else {
          prev = j ? bs[j - 1] : 5;
          first_machine = (j == 0);
        }
        int32_t din = (!first_machine && cur < 4 && prev < 4)
                          ? int32_t(prev) * 4 + cur
                          : kDinucNone;
        int32_t qi = q[j] < kNQual ? q[j] : kNQual - 1;
        int64_t key =
            ((int64_t(rg) * kNQual + qi) * n_cyc + cyc) * kNDinuc + din;
        ++lt[size_t(key)];
        bool j_mm;
        if (mm) {
          j_mm = mm[j];
        } else {
          // merge inline-parsed MD mismatch offsets against the walk's
          // ascending reference positions (both relative to start[i])
          int64_t ro = refp[size_t(j)] - (start ? start[i] : 0);
          while (mp < n_mm && mm_ro[size_t(mp)] < ro) ++mp;
          j_mm = mp < n_mm && mm_ro[size_t(mp)] == ro;
        }
        if (j_mm) ++lm[size_t(key)];
      }
    }
  };
  if (nt == 1) {
    work(0, 0, N);
  } else {
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; ++t)
      ts.emplace_back(work, t, N * t / nt, N * (t + 1) / nt);
    for (auto& t : ts) t.join();
  }
  for (int t = 0; t < nt; ++t) {
    for (int64_t k = 0; k < size; ++k) {
      total[k] += loc_t[size_t(t)][size_t(k)];
      mism[k] += loc_m[size_t(t)][size_t(k)];
    }
  }
}

// ----------------------------------------------------- CIGAR strings ----

// Columnar cigars -> concatenated run-length strings + offsets ('*' for
// cigar-less rows). Returns total bytes, -2 if cap too small.
int64_t cigar_strings(
    const uint8_t* ops, const int32_t* lens, const int32_t* n_ops,
    int64_t N, int64_t C, uint8_t* out, int64_t cap, int64_t* offsets,
    int nthreads) {
  std::vector<int64_t> sizes(size_t(N) + 1, 0);
  auto emit = [&](int64_t i, uint8_t* w) -> int64_t {
    int nc = n_ops[i] > C ? int(C) : n_ops[i];
    if (nc == 0) {
      if (w) *w = '*';
      return 1;
    }
    int64_t n_w = 0;
    for (int k = 0; k < nc; ++k) {
      char tmp[16];
      int n = snprintf(tmp, sizeof tmp, "%d", lens[i * C + k]);
      if (w) memcpy(w + n_w, tmp, size_t(n));
      n_w += n;
      if (w) w[n_w] = "MIDNSHP=X??????\?"[ops[i * C + k] & 0xF];
      ++n_w;
    }
    return n_w;
  };
  auto pass = [&](bool fill) {
    auto work = [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; ++i) {
        if (fill) emit(i, out + sizes[size_t(i)]);
        else sizes[size_t(i) + 1] = emit(i, nullptr);
      }
    };
    parallel_rows(N, nthreads, work);
  };
  pass(false);
  for (int64_t i = 0; i < N; ++i) sizes[size_t(i) + 1] += sizes[size_t(i)];
  if (sizes[size_t(N)] > cap) return -2;
  pass(true);
  memcpy(offsets, sizes.data(), size_t(N + 1) * 8);
  return sizes[size_t(N)];
}

// ------------------------------------------------------ FASTQ encode ----

// Format selected rows as FASTQ records (convertToFastq semantics:
// reverse-strand reads are reverse-complemented back to sequencer
// orientation, quals reversed; /1 /2 suffixes for paired reads when
// add_suffix). Two-pass like sam_encode. Returns bytes, -2 if cap small.
int64_t fastq_encode(
    const int32_t* flags, const int32_t* lengths,
    const uint8_t* select, const uint8_t* bases, const uint8_t* quals,
    int64_t lmax, const uint8_t* name_buf, const int64_t* name_off,
    int add_suffix, int64_t N, uint8_t* out, int64_t cap, int nthreads) {
  static const char kBase[6] = {'A', 'C', 'G', 'T', 'N', '.'};
  static const uint8_t kComp[6] = {3, 2, 1, 0, 4, 5};
  if (nthreads < 1) nthreads = 1;
  std::vector<int64_t> sizes(size_t(N) + 1, 0);

  auto emit = [&](int64_t i, uint8_t* w) -> int64_t {
    int64_t n_w = 0;
    auto putc_ = [&](char c) {
      if (w) w[n_w] = uint8_t(c);
      ++n_w;
    };
    int64_t L = lengths[i];
    if (L > lmax) L = lmax;
    int32_t fl = flags[i];
    bool rev = fl & 0x10;
    putc_('@');
    int64_t nm = name_off[i + 1] - name_off[i];
    if (w) memcpy(w + n_w, name_buf + name_off[i], size_t(nm));
    n_w += nm;
    if (add_suffix && (fl & 0x1)) {
      putc_('/');
      putc_((fl & 0x40) ? '1' : '2');
    }
    putc_('\n');
    const uint8_t* bs = bases + i * lmax;
    for (int64_t j = 0; j < L; ++j) {
      uint8_t c = rev ? bs[L - 1 - j] : bs[j];
      if (c > 5) c = 5;
      putc_(kBase[rev ? kComp[c] : c]);
    }
    putc_('\n');
    putc_('+');
    putc_('\n');
    const uint8_t* q = quals + i * lmax;
    for (int64_t j = 0; j < L; ++j)
      putc_(char(uint8_t(q[rev ? L - 1 - j : j] + 33)));
    putc_('\n');
    return n_w;
  };

  auto pass = [&](bool fill) {
    auto work = [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; ++i) {
        if (!select[i]) continue;
        if (fill) emit(i, out + sizes[size_t(i)]);
        else sizes[size_t(i) + 1] = emit(i, nullptr);
      }
    };
    parallel_rows(N, nthreads, work);
  };
  pass(false);
  for (int64_t i = 0; i < N; ++i) sizes[size_t(i) + 1] += sizes[size_t(i)];
  if (sizes[size_t(N)] > cap) return -2;
  pass(true);
  return sizes[size_t(N)];
}

// -------------------------------------------------------- BQSR apply ----

// Apply the recalibration phred table to every residue: the host twin of
// pipelines/bqsr.recalibrate_kernel's gather stage (cycle and dinuc
// covariates recomputed per residue, CycleCovariate.scala:31-49 /
// DinucCovariate.scala:24-50 semantics, Q5 floor + pad/valid masks).
void bqsr_apply(
    const uint8_t* bases, const uint8_t* quals, const int32_t* lengths,
    const int32_t* flags, const int32_t* rg_idx, const uint8_t* has_qual,
    const uint8_t* valid, int64_t N, int64_t lmax,
    const uint8_t* table, int32_t n_rg, int32_t n_cyc, int64_t gl,
    uint8_t* out, int nthreads) {
  static const uint8_t kComp[6] = {3, 2, 1, 0, 4, 5};  // A<->T C<->G
  constexpr int32_t kNQual = 94, kNDinuc = 17, kDinucNone = 16;
  constexpr uint8_t kQualPad = 255, kMinQ = 5;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* bs = bases + i * lmax;
      const uint8_t* q = quals + i * lmax;
      uint8_t* w = out + i * lmax;
      memcpy(w, q, size_t(lmax));
      if (!valid[i] || !has_qual[i]) continue;
      int64_t L = lengths[i];
      int32_t fl = flags[i];
      bool rev = fl & 0x10;
      bool second = (fl & 0x1) && (fl & 0x80);
      int64_t initial = rev ? (second ? -L : L) : (second ? -1 : 1);
      int64_t inc = rev ? (second ? 1 : -1) : (second ? -1 : 1);
      int32_t rg = rg_idx[i] >= 0 && rg_idx[i] < n_rg ? rg_idx[i] : n_rg - 1;
      const uint8_t* rg_table =
          table + size_t(rg) * kNQual * n_cyc * kNDinuc;
      for (int64_t j = 0; j < L && j < lmax; ++j) {
        uint8_t qv = q[j];
        if (qv < kMinQ || qv >= kQualPad) continue;
        int64_t cyc = initial + inc * j + gl;
        // machine-order previous base (reverse strand: complement of j+1)
        uint8_t cur = bs[j], prev;
        bool first_machine;
        if (rev) {
          cur = kComp[cur > 5 ? 5 : cur];
          uint8_t nb = (j + 1 < L) ? bs[j + 1] : 5;
          prev = kComp[nb > 5 ? 5 : nb];
          first_machine = (j == L - 1);
        } else {
          prev = j ? bs[j - 1] : 5;
          first_machine = (j == 0);
        }
        int32_t din = (!first_machine && cur < 4 && prev < 4)
                          ? int32_t(prev) * 4 + cur
                          : kDinucNone;
        int32_t qi = qv < kNQual ? qv : kNQual - 1;
        w[j] = rg_table[(int64_t(qi) * n_cyc + cyc) * kNDinuc + din];
      }
    }
  };
  parallel_rows(N, nthreads, work);
}

// -------------------------------------------------------- SAM encode ----

// Format valid rows as SAM text lines (the writer's format_sam_records
// semantics: 1-based positions with 0 for unplaced, '=' RNEXT
// shortening, MD/OQ/RG tags appended after the raw attrs).  Two passes
// like bam_encode.  Returns bytes written, -2 if cap too small.
int64_t sam_encode(
    const int32_t* flags, const int32_t* contig_idx, const int64_t* start,
    const int32_t* mapq, const int32_t* mate_contig_idx,
    const int64_t* mate_start, const int32_t* tlen, const int32_t* lengths,
    const uint8_t* has_qual, const uint8_t* valid,
    const uint8_t* bases, const uint8_t* quals, int64_t lmax,
    const uint8_t* cigar_ops, const int32_t* cigar_lens,
    const int32_t* cigar_n, int64_t cmax,
    const uint8_t* name_buf, const int64_t* name_off,
    const uint8_t* attr_buf, const int64_t* attr_off,
    const uint8_t* md_buf, const int64_t* md_off, const uint8_t* md_present,
    const uint8_t* oq_buf, const int64_t* oq_off, const uint8_t* oq_present,
    const int32_t* rg_idx, const uint8_t* rg_buf, const int64_t* rg_off,
    int32_t n_rgs,
    const uint8_t* ctg_buf, const int64_t* ctg_off, int32_t n_ctgs,
    int64_t N, uint8_t* out, int64_t cap, int nthreads) {
  static const char kBase[6] = {'A', 'C', 'G', 'T', 'N', '.'};
  if (nthreads < 1) nthreads = 1;
  std::vector<int64_t> sizes(size_t(N) + 1, 0);

  std::atomic<int> oob{0};
  auto emit = [&](int64_t i, uint8_t* w) -> int64_t {
    // w == nullptr: size-only.  Out-of-range contig/RG indices mark the
    // whole encode as failed (-1) so the caller's Python fallback can
    // surface the corruption loudly instead of writing a wrong file.
    if (contig_idx[i] >= n_ctgs || mate_contig_idx[i] >= n_ctgs ||
        rg_idx[i] >= n_rgs)
      oob.store(1);
    int64_t n_w = 0;
    auto put = [&](const uint8_t* p, int64_t n) {
      if (w) memcpy(w + n_w, p, size_t(n));
      n_w += n;
    };
    auto putc_ = [&](char c) {
      if (w) w[n_w] = uint8_t(c);
      ++n_w;
    };
    auto put_int = [&](int64_t v) {
      char tmp[24];
      int n = snprintf(tmp, sizeof tmp, "%lld", (long long)v);
      put(reinterpret_cast<uint8_t*>(tmp), n);
    };
    auto put_span = [&](const uint8_t* b2, const int64_t* off, int64_t k) {
      put(b2 + off[k], off[k + 1] - off[k]);
    };
    put_span(name_buf, name_off, i);
    putc_('\t');
    put_int(flags[i]);
    putc_('\t');
    int32_t c = contig_idx[i];
    if (c >= 0 && c < n_ctgs) put_span(ctg_buf, ctg_off, c);
    else putc_('*');
    putc_('\t');
    put_int(start[i] >= 0 ? start[i] + 1 : 0);
    putc_('\t');
    put_int(mapq[i] >= 0 ? mapq[i] : 0);
    putc_('\t');
    int32_t nc = cigar_n[i];
    if (nc == 0) {
      putc_('*');
    } else {
      for (int32_t k = 0; k < nc; ++k) {
        put_int(cigar_lens[i * cmax + k]);
        putc_("MIDNSHP=X??????\?"[cigar_ops[i * cmax + k] & 0xF]);
      }
    }
    putc_('\t');
    int32_t mc = mate_contig_idx[i];
    if (mc < 0) putc_('*');
    else if (mc == c && c >= 0) putc_('=');
    else if (mc < n_ctgs) put_span(ctg_buf, ctg_off, mc);
    else putc_('*');
    putc_('\t');
    put_int(mate_start[i] >= 0 ? mate_start[i] + 1 : 0);
    putc_('\t');
    put_int(tlen[i]);
    putc_('\t');
    int64_t L = lengths[i];
    if (L == 0) {
      putc_('*');
    } else {
      const uint8_t* bs = bases + i * lmax;
      for (int64_t j = 0; j < L; ++j)
        putc_(kBase[bs[j] > 5 ? 5 : bs[j]]);
    }
    putc_('\t');
    if (L == 0 || !has_qual[i]) {
      putc_('*');
    } else {
      const uint8_t* q = quals + i * lmax;
      for (int64_t j = 0; j < L; ++j)
        putc_(char(uint8_t(q[j] + 33)));
    }
    int64_t al = attr_off[i + 1] - attr_off[i];
    if (al) {
      putc_('\t');
      put(attr_buf + attr_off[i], al);
    }
    if (md_present[i]) {
      put(reinterpret_cast<const uint8_t*>("\tMD:Z:"), 6);
      put_span(md_buf, md_off, i);
    }
    if (oq_present[i]) {
      put(reinterpret_cast<const uint8_t*>("\tOQ:Z:"), 6);
      put_span(oq_buf, oq_off, i);
    }
    int32_t r = rg_idx[i];
    if (r >= 0 && r < n_rgs) {
      put(reinterpret_cast<const uint8_t*>("\tRG:Z:"), 6);
      put_span(rg_buf, rg_off, r);
    }
    putc_('\n');
    return n_w;
  };

  auto pass = [&](bool fill) {
    auto work = [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; ++i) {
        if (!valid[i]) continue;
        if (fill) emit(i, out + sizes[size_t(i)]);
        else sizes[size_t(i) + 1] = emit(i, nullptr);
      }
    };
    parallel_rows(N, nthreads, work);
  };
  pass(false);
  if (oob.load()) return -1;
  for (int64_t i = 0; i < N; ++i) sizes[size_t(i) + 1] += sizes[size_t(i)];
  if (sizes[size_t(N)] > cap) return -2;
  pass(true);
  return sizes[size_t(N)];
}

// -------------------------------------------------------- BAM encode ----

// Encode valid rows into a BAM record stream (the inverse of
// bamtok_fill; tags from the stringified attrs + MD/OQ/RG sidecars).
// Two passes: per-record sizes (threaded) -> exclusive offsets -> fill
// (threaded).  Returns bytes written, -1 on malformed tag text, -2 if
// ``cap`` is too small.
int64_t bam_encode(
    const int32_t* flags, const int32_t* contig_idx, const int64_t* start,
    const int32_t* mapq, const int32_t* mate_contig_idx,
    const int64_t* mate_start, const int32_t* tlen, const int32_t* lengths,
    const uint8_t* has_qual, const uint8_t* valid,
    const uint8_t* bases, const uint8_t* quals, int64_t lmax,
    const uint8_t* cigar_ops, const int32_t* cigar_lens,
    const int32_t* cigar_n, int64_t cmax,
    const uint8_t* name_buf, const int64_t* name_off,
    const uint8_t* attr_buf, const int64_t* attr_off,
    const uint8_t* md_buf, const int64_t* md_off, const uint8_t* md_present,
    const uint8_t* oq_buf, const int64_t* oq_off, const uint8_t* oq_present,
    const int32_t* rg_idx, const uint8_t* rg_buf, const int64_t* rg_off,
    int32_t n_rgs, int32_t n_refs, int64_t N, uint8_t* out, int64_t cap,
    int nthreads) {
  static const uint8_t kNib[6] = {1, 2, 4, 8, 15, 0};  // A C G T N PAD
  if (nthreads < 1) nthreads = 1;
  std::vector<int64_t> sizes(size_t(N) + 1, 0);
  std::atomic<int> bad{0};

  auto tag_parts = [&](int64_t i, const uint8_t** a, int64_t* al,
                       const uint8_t** md, int64_t* mdl, bool* hmd,
                       const uint8_t** oq, int64_t* oql, bool* hoq,
                       const uint8_t** rg, int64_t* rgl, bool* hrg) {
    *a = attr_buf + attr_off[i];
    *al = attr_off[i + 1] - attr_off[i];
    *hmd = md_present[i] != 0;
    *md = md_buf + md_off[i];
    *mdl = md_off[i + 1] - md_off[i];
    *hoq = oq_present[i] != 0;
    *oq = oq_buf + oq_off[i];
    *oql = oq_off[i + 1] - oq_off[i];
    int32_t r = rg_idx[i];
    *hrg = r >= 0 && r < n_rgs;
    if (*hrg) {
      *rg = rg_buf + rg_off[r];
      *rgl = rg_off[r + 1] - rg_off[r];
    } else {
      *rg = nullptr;
      *rgl = 0;
    }
  };

  auto size_one = [&](int64_t i) -> int64_t {
    if (!valid[i]) return 0;
    if (rg_idx[i] >= n_rgs) return -1;  // corrupt batch: fail loudly
    // an out-of-range refID would poison the BAM silently (sam_encode's
    // contig lookup fails loudly; mirror that here)
    if (contig_idx[i] >= n_refs || mate_contig_idx[i] >= n_refs) return -1;
    const uint8_t *a, *md, *oq, *rg;
    int64_t al, mdl, oql, rgl;
    bool hmd, hoq, hrg;
    tag_parts(i, &a, &al, &md, &mdl, &hmd, &oq, &oql, &hoq, &rg, &rgl, &hrg);
    int64_t tagsz = bamenc::tags_to_bin(a, al, md, mdl, hmd, oq, oql, hoq,
                                        rg, rgl, hrg, nullptr);
    if (tagsz < 0) return -1;
    int64_t L = lengths[i];
    int64_t nm = name_off[i + 1] - name_off[i];
    return 4 + 32 + nm + 1 + 4 * int64_t(cigar_n[i]) + (L + 1) / 2 + L +
           tagsz;
  };

  {
    auto work = [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; ++i) {
        int64_t s = size_one(i);
        if (s < 0) { bad.store(1); return; }
        sizes[size_t(i) + 1] = s;
      }
    };
    parallel_rows(N, nthreads, work);
  }
  if (bad.load()) return -1;
  for (int64_t i = 0; i < N; ++i) sizes[size_t(i) + 1] += sizes[size_t(i)];
  int64_t total = sizes[size_t(N)];
  if (total > cap) return -2;

  auto fill_one = [&](int64_t i) {
    if (!valid[i]) return;
    uint8_t* w = out + sizes[size_t(i)];
    int64_t block = sizes[size_t(i) + 1] - sizes[size_t(i)] - 4;
    int32_t bs32 = int32_t(block);
    memcpy(w, &bs32, 4); w += 4;
    int64_t nm = name_off[i + 1] - name_off[i];
    int64_t L = lengths[i];
    int32_t hdr[4];
    hdr[0] = contig_idx[i];
    hdr[1] = start[i] >= 0 ? int32_t(start[i]) : -1;
    memcpy(w, hdr, 8); w += 8;
    *w++ = uint8_t(nm + 1);
    *w++ = uint8_t(mapq[i] & 0xFF);
    uint16_t bin16 = 0;
    memcpy(w, &bin16, 2); w += 2;
    uint16_t nc16 = uint16_t(cigar_n[i]);
    memcpy(w, &nc16, 2); w += 2;
    uint16_t fl16 = uint16_t(flags[i] & 0xFFFF);
    memcpy(w, &fl16, 2); w += 2;
    int32_t l32 = int32_t(L);
    memcpy(w, &l32, 4); w += 4;
    int32_t mc = mate_contig_idx[i];
    memcpy(w, &mc, 4); w += 4;
    int32_t mp = mate_start[i] >= 0 ? int32_t(mate_start[i]) : -1;
    memcpy(w, &mp, 4); w += 4;
    int32_t tl32 = tlen[i];
    memcpy(w, &tl32, 4); w += 4;
    memcpy(w, name_buf + name_off[i], size_t(nm)); w += nm;
    *w++ = 0;
    for (int32_t k = 0; k < cigar_n[i]; ++k) {
      uint32_t c = (uint32_t(cigar_lens[i * cmax + k]) << 4) |
                   (cigar_ops[i * cmax + k] & 0xF);
      memcpy(w, &c, 4); w += 4;
    }
    const uint8_t* bs = bases + i * lmax;
    for (int64_t j = 0; j + 1 < L + 1; j += 2) {
      uint8_t hi = kNib[bs[j] > 5 ? 5 : bs[j]];
      uint8_t lo = (j + 1 < L) ? kNib[bs[j + 1] > 5 ? 5 : bs[j + 1]] : 0;
      *w++ = uint8_t((hi << 4) | lo);
    }
    const uint8_t* q = quals + i * lmax;
    if (has_qual[i]) {
      for (int64_t j = 0; j < L; ++j)
        *w++ = (q[j] == QUAL_PAD) ? 0xFF : q[j];
    } else {
      memset(w, 0xFF, size_t(L));
      w += L;
    }
    const uint8_t *a, *md, *oq, *rg;
    int64_t al, mdl, oql, rgl;
    bool hmd, hoq, hrg;
    tag_parts(i, &a, &al, &md, &mdl, &hmd, &oq, &oql, &hoq, &rg, &rgl, &hrg);
    bamenc::tags_to_bin(a, al, md, mdl, hmd, oq, oql, hoq, rg, rgl, hrg, w);
  };

  {
    auto work = [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; ++i) fill_one(i);
    };
    parallel_rows(N, nthreads, work);
  }
  return total;
}


// ------------------------------------------------------- CIGAR walks ----

// Parse CIGAR strings (flat byte buffer + row offsets, Arrow string
// layout) into columnar (ops u8[N, C], lens i32[N, C], n_ops i32[N]).
// '*' or empty rows get n_ops 0.  Returns -1 if any row has more than C
// ops (caller sized C from a host-side count) — never writes OOB.
int cigar_cols(const uint8_t* buf, const int64_t* offsets, int64_t N,
               int64_t C, uint8_t* ops, int32_t* lens, int32_t* n_ops,
               int nthreads) {
  static int8_t code[256];
  static bool init = false;
  if (!init) {
    for (int i = 0; i < 256; ++i) code[i] = -1;
    const char* cs = "MIDNSHP=X";
    for (int i = 0; cs[i]; ++i) code[uint8_t(cs[i])] = int8_t(i);
    init = true;
  }
  if (nthreads < 1) nthreads = 1;
  std::atomic<int> bad{0};
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint8_t* row_ops = ops + i * C;
      int32_t* row_lens = lens + i * C;
      for (int64_t k = 0; k < C; ++k) {
        row_ops[k] = 15;  // CIGAR_PAD
        row_lens[k] = 0;
      }
      int64_t s = offsets[i], e = offsets[i + 1];
      int n = 0;
      if (e - s == 1 && buf[s] == '*') {
        n_ops[i] = 0;
        continue;
      }
      int64_t num = 0;
      bool ok = true;
      for (int64_t p = s; p < e; ++p) {
        uint8_t ch = buf[p];
        if (ch >= '0' && ch <= '9') {
          num = num * 10 + (ch - '0');
          if (num > INT32_MAX) { ok = false; break; }
        } else {
          int8_t c = code[ch];
          if (c < 0 || n >= C) { ok = false; break; }
          row_ops[n] = uint8_t(c);
          row_lens[n] = int32_t(num);
          num = 0;
          ++n;
        }
      }
      if (!ok) { bad.store(1); n = 0; }
      n_ops[i] = n;
    }
  };
  parallel_rows(N, nthreads, work);
  return bad.load() ? -1 : 0;
}

// Per-base reference positions from columnar CIGARs: out[i, j] = reference
// position of query base j of read i, or -1 when the base is not aligned
// (insertion / soft clip / padding).  The host twin of the device kernel in
// ops/cigar.py (RichAlignmentRecord.referencePositions semantics,
// rich/RichAlignmentRecord.scala:200-229); a straight nested walk per read,
// threaded over rows.
void ref_positions(const uint8_t* ops, const int32_t* lens,
                   const int32_t* n_ops, const int64_t* start,
                   int64_t N, int64_t C, int64_t L, int64_t* out,
                   int nthreads) {
  // consumes-query / consumes-ref tables for op codes 0..15 (M I D N S H P = X)
  static const uint8_t kQ[16] = {1, 1, 0, 0, 1, 0, 0, 1, 1, 0, 0, 0, 0, 0, 0, 0};
  static const uint8_t kR[16] = {1, 0, 1, 1, 0, 0, 0, 1, 1, 0, 0, 0, 0, 0, 0, 0};
  if (nthreads < 1) nthreads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t* row = out + i * L;
      for (int64_t j = 0; j < L; ++j) row[j] = -1;
      int64_t q = 0;
      int64_t r = start[i];
      int nc = n_ops[i];
      if (nc > C) nc = int(C);
      for (int k = 0; k < nc && q < L; ++k) {
        uint8_t op = ops[i * C + k] & 15;
        int64_t len = lens[i * C + k];
        if (len < 0) len = 0;
        bool cq = kQ[op], cr = kR[op];
        if (cq && cr) {
          int64_t stop = q + len;
          if (stop > L) stop = L;
          for (int64_t j = q; j < stop; ++j) row[j] = r + (j - q);
        }
        if (cq) q += len;
        if (cr) r += len;
      }
    }
  };
  parallel_rows(N, nthreads, work);
}

// ------------------------------------------------------------------ SAM --

void* samtok_scan(const uint8_t* buf, int64_t n, int64_t body_off,
                  int nthreads) {
  auto* h = new SamHandle;
  h->buf = buf;
  h->n = n;
  if (nthreads < 1) nthreads = 1;
  if (body_off < 0) body_off = 0;
  if (body_off > n) body_off = n;  // header-only file without trailing \n
  // chunk at line boundaries
  std::vector<int64_t> cuts{body_off};
  for (int i = 1; i < nthreads; ++i) {
    int64_t target = body_off + (n - body_off) * i / nthreads;
    const uint8_t* nl = static_cast<const uint8_t*>(
        memchr(buf + target, '\n', size_t(n - target)));
    int64_t cut = nl ? (nl - buf) + 1 : n;
    if (cut > cuts.back()) cuts.push_back(cut);
  }
  cuts.push_back(n);
  h->chunks.resize(cuts.size() - 1);
  std::vector<std::thread> ts;
  for (size_t i = 0; i < h->chunks.size(); ++i) {
    h->chunks[i].begin = cuts[i];
    h->chunks[i].end = cuts[i + 1];
    ts.emplace_back(sam_scan_chunk, buf, &h->chunks[i]);
  }
  for (auto& t : ts) t.join();
  int64_t rec = 0, nameb = 0, tagb = 0;
  for (auto& c : h->chunks) {
    if (c.dims.malformed) {
      delete h;
      return nullptr;
    }
    c.rec0 = rec;
    c.name0 = nameb;
    c.tag0 = tagb;
    rec += c.dims.n_records;
    nameb += c.dims.name_bytes;
    tagb += c.dims.tag_bytes;
    h->total.lmax = std::max(h->total.lmax, c.dims.lmax);
    h->total.cmax = std::max(h->total.cmax, c.dims.cmax);
  }
  h->total.n_records = rec;
  h->total.name_bytes = nameb;
  h->total.tag_bytes = tagb;
  return h;
}

void samtok_dims(void* vh, int64_t* n_records, int32_t* lmax, int32_t* cmax,
                 int64_t* name_bytes, int64_t* tag_bytes) {
  auto* h = static_cast<SamHandle*>(vh);
  *n_records = h->total.n_records;
  *lmax = h->total.lmax;
  *cmax = h->total.cmax;
  *name_bytes = h->total.name_bytes;
  *tag_bytes = h->total.tag_bytes;
}

int samtok_fill(
    void* vh, const uint8_t* contig_buf, const int64_t* contig_off,
    int32_t n_contigs, const uint8_t* rg_buf, const int64_t* rg_off,
    int32_t n_rgs, int32_t* flags, int32_t* contig_idx, int64_t* start,
    int64_t* end_, int32_t* mapq, int32_t* mate_contig_idx,
    int64_t* mate_start, int32_t* tlen, int32_t* rg_idx, int32_t* lengths,
    uint8_t* has_qual, uint8_t* bases, uint8_t* quals, int64_t lmax,
    uint8_t* cigar_ops, int32_t* cigar_lens, int32_t* cigar_n, int64_t cmax,
    uint8_t* name_buf, int64_t* name_off, uint8_t* attr_buf,
    int64_t* attr_off, uint8_t* md_buf, int64_t* md_off, uint8_t* md_present,
    uint8_t* oq_buf, int64_t* oq_off, uint8_t* oq_present,
    int64_t* attr_bytes, int64_t* md_bytes, int64_t* oq_bytes) {
  auto* h = static_cast<SamHandle*>(vh);
  Dict contigs = build_dict(contig_buf, contig_off, n_contigs);
  Dict rgs = build_dict(rg_buf, rg_off, n_rgs);
  SamOut o{flags, contig_idx, mapq, mate_contig_idx, tlen, rg_idx,
           lengths, cigar_lens, cigar_n, start, end_, mate_start,
           has_qual, bases, quals, cigar_ops, lmax, cmax,
           name_buf, attr_buf, md_buf, oq_buf,
           name_off, attr_off, md_off, oq_off, md_present, oq_present};
  std::vector<std::thread> ts;
  std::vector<uint8_t> oks(h->chunks.size(), 0);
  for (size_t i = 0; i < h->chunks.size(); ++i) {
    ts.emplace_back([&, i]() {
      oks[i] = sam_fill_chunk(h->buf, &h->chunks[i], contigs, rgs, &o) ? 1 : 0;
    });
  }
  for (auto& t : ts) t.join();
  for (auto ok : oks)
    if (!ok) return 1;
  // compact attrs/md/oq: slide each chunk's used region left
  int64_t aw = 0, mw = 0, qw = 0;
  for (auto& c : h->chunks) {
    int64_t n_rec = c.dims.n_records;
    if (c.attr_used && aw != c.tag0)
      memmove(attr_buf + aw, attr_buf + c.tag0, size_t(c.attr_used));
    if (c.md_used && mw != c.tag0)
      memmove(md_buf + mw, md_buf + c.tag0, size_t(c.md_used));
    if (c.oq_used && qw != c.tag0)
      memmove(oq_buf + qw, oq_buf + c.tag0, size_t(c.oq_used));
    int64_t da = aw - c.tag0, dm = mw - c.tag0, dq = qw - c.tag0;
    for (int64_t r = c.rec0; r < c.rec0 + n_rec; ++r) {
      attr_off[r] += da;
      md_off[r] += dm;
      oq_off[r] += dq;
    }
    aw += c.attr_used;
    mw += c.md_used;
    qw += c.oq_used;
  }
  int64_t nrec = h->total.n_records;
  attr_off[nrec] = aw;
  md_off[nrec] = mw;
  oq_off[nrec] = qw;
  name_off[nrec] = h->total.name_bytes;
  *attr_bytes = aw;
  *md_bytes = mw;
  *oq_bytes = qw;
  return 0;
}

void samtok_free(void* vh) { delete static_cast<SamHandle*>(vh); }

// ----------------------------------------------------------------- BGZF --

// partial_ok: a truncated final block (streaming window) ends the scan
// instead of failing; bgzf_consumed() then reports how many input bytes
// belong to complete blocks.
void* bgzf_scan2(const uint8_t* buf, int64_t n, int partial_ok) {
  auto* h = new BgzfHandle;
  h->buf = buf;
  h->n = n;
  int64_t off = 0, out = 0;
  while (off < n) {
    int64_t bsize = 0;
    int64_t hl = bgzf_block_header(buf + off, n - off, &bsize);
    if (hl < 0 || bsize < hl + 8 || off + bsize > n) {
      bool truncated = hl == -2 || (hl >= 0 && off + bsize > n);
      if (partial_ok && truncated) break;
      delete h;
      return nullptr;
    }
    uint32_t crc, isize;
    memcpy(&crc, buf + off + bsize - 8, 4);
    memcpy(&isize, buf + off + bsize - 4, 4);
    if (isize) {
      h->blocks.push_back(
          {off + hl, bsize - hl - 8, out, int64_t(isize), crc});
      out += isize;
    }
    off += bsize;
  }
  h->out_bytes = out;
  h->consumed = off;
  return h;
}

void* bgzf_scan(const uint8_t* buf, int64_t n) {
  return bgzf_scan2(buf, n, 0);
}

int64_t bgzf_consumed(void* vh) {
  return static_cast<BgzfHandle*>(vh)->consumed;
}

void bgzf_dims(void* vh, int64_t* n_blocks, int64_t* out_bytes) {
  auto* h = static_cast<BgzfHandle*>(vh);
  *n_blocks = int64_t(h->blocks.size());
  *out_bytes = h->out_bytes;
}

int bgzf_fill(void* vh, uint8_t* out, int nthreads) {
  auto* h = static_cast<BgzfHandle*>(vh);
  if (nthreads < 1) nthreads = 1;
  std::vector<uint8_t> oks(size_t(nthreads), 1);
  std::vector<std::thread> ts;
  int64_t nb = int64_t(h->blocks.size());
  for (int t = 0; t < nthreads; ++t) {
    ts.emplace_back([&, t]() {
      int64_t b0 = nb * t / nthreads, b1 = nb * (t + 1) / nthreads;
      for (int64_t b = b0; b < b1; ++b) {
        const BgzfBlock& blk = h->blocks[size_t(b)];
        z_stream zs;
        memset(&zs, 0, sizeof(zs));
        if (inflateInit2(&zs, -15) != Z_OK) { oks[size_t(t)] = 0; return; }
        zs.next_in = const_cast<uint8_t*>(h->buf + blk.comp_off);
        zs.avail_in = uInt(blk.comp_len);
        zs.next_out = out + blk.out_off;
        zs.avail_out = uInt(blk.out_len);
        int rc = inflate(&zs, Z_FINISH);
        inflateEnd(&zs);
        if (rc != Z_STREAM_END || zs.total_out != uLong(blk.out_len) ||
            uint32_t(crc32(0, out + blk.out_off, uInt(blk.out_len))) !=
                blk.crc) {
          oks[size_t(t)] = 0;
          return;
        }
      }
    });
  }
  for (auto& t : ts) t.join();
  for (auto ok : oks)
    if (!ok) return 1;
  return 0;
}

void bgzf_free(void* vh) { delete static_cast<BgzfHandle*>(vh); }

// BGZF compression: deflate independent blocks in parallel.
// Layout per block: 18-byte header (incl. BC extra field) + deflate
// payload + crc32 + isize.  Caller provides the worst-case output buffer.
int bgzf_compress(const uint8_t* in, int64_t n, int64_t block_size,
                  uint8_t* out, int64_t out_cap, int64_t* out_len,
                  int nthreads, int level) {
  if (block_size <= 0) block_size = 0xff00;
  int64_t n_blocks = n ? (n + block_size - 1) / block_size : 0;
  std::vector<int64_t> lens(size_t(n_blocks), 0);
  std::vector<std::vector<uint8_t>> payloads;
  payloads.resize(size_t(n_blocks));
  if (nthreads < 1) nthreads = 1;
  std::vector<std::thread> ts;
  std::vector<uint8_t> oks(size_t(nthreads), 1);
  for (int t = 0; t < nthreads; ++t) {
    ts.emplace_back([&, t]() {
      for (int64_t b = n_blocks * t / nthreads;
           b < n_blocks * (t + 1) / nthreads; ++b) {
        int64_t off = b * block_size;
        int64_t len = std::min(block_size, n - off);
        auto& pl = payloads[size_t(b)];
        pl.resize(size_t(compressBound(uLong(len))) + 16);
        z_stream zs;
        memset(&zs, 0, sizeof(zs));
        if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8,
                         Z_DEFAULT_STRATEGY) != Z_OK) {
          oks[size_t(t)] = 0;
          return;
        }
        zs.next_in = const_cast<uint8_t*>(in + off);
        zs.avail_in = uInt(len);
        zs.next_out = pl.data();
        zs.avail_out = uInt(pl.size());
        int rc = deflate(&zs, Z_FINISH);
        deflateEnd(&zs);
        if (rc != Z_STREAM_END) { oks[size_t(t)] = 0; return; }
        pl.resize(zs.total_out);
        lens[size_t(b)] = int64_t(zs.total_out);
      }
    });
  }
  for (auto& t : ts) t.join();
  for (auto ok : oks)
    if (!ok) return 1;
  int64_t w = 0;
  for (int64_t b = 0; b < n_blocks; ++b) {
    int64_t off = b * block_size;
    int64_t len = std::min(block_size, n - off);
    int64_t total = 18 + lens[size_t(b)] + 8;
    if (w + total > out_cap) return 1;
    uint8_t* p = out + w;
    const uint8_t hdr[12] = {0x1f, 0x8b, 8, 4, 0, 0, 0, 0, 0, 0xff, 6, 0};
    memcpy(p, hdr, 12);
    p[12] = 'B'; p[13] = 'C'; p[14] = 2; p[15] = 0;
    uint16_t bsize = uint16_t(total - 1);
    p[16] = uint8_t(bsize & 0xff);
    p[17] = uint8_t(bsize >> 8);
    memcpy(p + 18, payloads[size_t(b)].data(), size_t(lens[size_t(b)]));
    uint32_t crc = uint32_t(crc32(0, in + off, uInt(len)));
    uint32_t isz = uint32_t(len);
    memcpy(p + 18 + lens[size_t(b)], &crc, 4);
    memcpy(p + 18 + lens[size_t(b)] + 4, &isz, 4);
    w += total;
  }
  static const uint8_t EOF_BLOCK[28] = {
      0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0, 0, 0xff, 0x06, 0x00, 0x42,
      0x43, 0x02, 0x00, 0x1b, 0x00, 0x03, 0x00, 0, 0, 0, 0, 0, 0, 0, 0};
  if (w + 28 > out_cap) return 1;
  memcpy(out + w, EOF_BLOCK, 28);
  w += 28;
  *out_len = w;
  return 0;
}

// ------------------------------------------------------------------ BAM --

// partial_ok: a record truncated by the end of a streaming window ends
// the scan (bamtok_consumed() reports the bytes covered by complete
// records); structurally malformed records still fail the scan.
void* bamtok_scan2(const uint8_t* buf, int64_t n, int64_t records_off,
                   int partial_ok) {
  auto* h = new BamHandle;
  h->buf = buf;
  h->n = n;
  h->records_off = records_off;
  int64_t off = records_off;
  while (off + 4 <= n) {
    int32_t bs;
    memcpy(&bs, buf + off, 4);
    if (bs < 32 || off + 4 + bs > n) {
      if (bs == 0) break;
      if (partial_ok && bs >= 32 && off + 4 + bs > n) break;
      delete h;
      return nullptr;
    }
    const uint8_t* rec = buf + off + 4;
    int32_t l_read_name = rec[8];
    uint16_t n_cigar;
    memcpy(&n_cigar, rec + 12, 2);
    int32_t l_seq;
    memcpy(&l_seq, rec + 16, 4);
    int64_t tag_bin =
        bs - 32 - l_read_name - 4 * int64_t(n_cigar) - (int64_t(l_seq) + 1) / 2 - l_seq;
    // Reject malformed records here so bamtok_fill never reads out of
    // bounds; the caller falls back to the pure-Python parser.
    if (l_read_name < 1 || l_seq < 0 || tag_bin < 0) {
      delete h;
      return nullptr;
    }
    h->rec_off.push_back(off);
    h->name_bytes += l_read_name - 1;
    if (l_seq > h->lmax) h->lmax = l_seq;
    if (n_cigar > h->cmax) h->cmax = n_cigar;
    h->tag_bytes += tag_bin * 6 + 48;
    off += 4 + bs;
  }
  h->consumed = off;
  return h;
}

void* bamtok_scan(const uint8_t* buf, int64_t n, int64_t records_off) {
  return bamtok_scan2(buf, n, records_off, 0);
}

int64_t bamtok_consumed(void* vh) {
  return static_cast<BamHandle*>(vh)->consumed;
}

void bamtok_dims(void* vh, int64_t* n_records, int32_t* lmax, int32_t* cmax,
                 int64_t* name_bytes, int64_t* tag_bytes) {
  auto* h = static_cast<BamHandle*>(vh);
  *n_records = int64_t(h->rec_off.size());
  *lmax = h->lmax;
  *cmax = h->cmax;
  *name_bytes = h->name_bytes;
  *tag_bytes = h->tag_bytes;
}

int bamtok_fill(
    void* vh, const uint8_t* rg_buf, const int64_t* rg_off, int32_t n_rgs,
    int32_t* flags, int32_t* contig_idx, int64_t* start, int64_t* end_,
    int32_t* mapq, int32_t* mate_contig_idx, int64_t* mate_start,
    int32_t* tlen, int32_t* rg_idx, int32_t* lengths, uint8_t* has_qual,
    uint8_t* bases, uint8_t* quals, int64_t lmax, uint8_t* cigar_ops,
    int32_t* cigar_lens, int32_t* cigar_n, int64_t cmax, uint8_t* name_buf,
    int64_t* name_off, uint8_t* attr_buf, int64_t* attr_off, uint8_t* md_buf,
    int64_t* md_off, uint8_t* md_present, uint8_t* oq_buf, int64_t* oq_off,
    uint8_t* oq_present, int64_t* attr_bytes, int64_t* md_bytes,
    int64_t* oq_bytes, int nthreads) {
  auto* h = static_cast<BamHandle*>(vh);
  Dict rgs = build_dict(rg_buf, rg_off, n_rgs);
  int64_t nrec = int64_t(h->rec_off.size());
  if (nthreads < 1) nthreads = 1;

  // per-thread record ranges with prefix-summed buffer bases
  std::vector<int64_t> r0(size_t(nthreads) + 1);
  for (int t = 0; t <= nthreads; ++t) r0[size_t(t)] = nrec * t / nthreads;
  // name bytes are exact; compute prefix per range serially (cheap)
  std::vector<int64_t> nbase(size_t(nthreads) + 1, 0),
      tbase(size_t(nthreads) + 1, 0);
  {
    int64_t nb = 0, tb = 0;
    int t = 0;
    for (int64_t r = 0; r <= nrec; ++r) {
      while (t <= nthreads && r == r0[size_t(t)]) {
        nbase[size_t(t)] = nb;
        tbase[size_t(t)] = tb;
        ++t;
      }
      if (r == nrec) break;
      const uint8_t* rec = h->buf + h->rec_off[size_t(r)] + 4;
      int32_t bs;
      memcpy(&bs, h->buf + h->rec_off[size_t(r)], 4);
      int32_t l_read_name = rec[8];
      uint16_t n_cigar;
      memcpy(&n_cigar, rec + 12, 2);
      int32_t l_seq;
      memcpy(&l_seq, rec + 16, 4);
      nb += l_read_name - 1;
      int64_t tag_bin = bs - 32 - l_read_name - 4 * int64_t(n_cigar) -
                        (int64_t(l_seq) + 1) / 2 - l_seq;
      tb += tag_bin * 6 + 48;
    }
  }

  std::vector<uint8_t> oks(size_t(nthreads), 1);
  std::vector<int64_t> used_a(size_t(nthreads), 0),
      used_m(size_t(nthreads), 0), used_q(size_t(nthreads), 0);
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t) {
    ts.emplace_back([&, t]() {
      int64_t npos = nbase[size_t(t)];
      int64_t apos = tbase[size_t(t)], mpos = tbase[size_t(t)],
              qpos = tbase[size_t(t)];
      int64_t acap = tbase[size_t(t) + 1];
      for (int64_t r = r0[size_t(t)]; r < r0[size_t(t) + 1]; ++r) {
        int32_t bs;
        memcpy(&bs, h->buf + h->rec_off[size_t(r)], 4);
        const uint8_t* rec = h->buf + h->rec_off[size_t(r)] + 4;
        const uint8_t* rec_end = rec + bs;
        int32_t ref_id, pos, l_seq, next_ref, next_pos, tl;
        memcpy(&ref_id, rec, 4);
        memcpy(&pos, rec + 4, 4);
        int32_t l_read_name = rec[8];
        int32_t mq = rec[9];
        uint16_t n_cigar, flag;
        memcpy(&n_cigar, rec + 12, 2);
        memcpy(&flag, rec + 14, 2);
        memcpy(&l_seq, rec + 16, 4);
        memcpy(&next_ref, rec + 20, 4);
        memcpy(&next_pos, rec + 24, 4);
        memcpy(&tl, rec + 28, 4);
        flags[r] = flag;
        contig_idx[r] = ref_id;
        start[r] = ref_id >= 0 ? pos : -1;
        mapq[r] = mq;
        mate_contig_idx[r] = next_ref;
        mate_start[r] = next_ref >= 0 ? next_pos : -1;
        tlen[r] = tl;
        const uint8_t* p = rec + 32;
        memcpy(name_buf + npos, p, size_t(l_read_name - 1));
        name_off[r] = npos;
        npos += l_read_name - 1;
        p += l_read_name;
        uint8_t* crow = cigar_ops + r * cmax;
        int32_t* clrow = cigar_lens + r * cmax;
        memset(crow, CIGAR_PAD, size_t(cmax));
        memset(clrow, 0, size_t(cmax) * 4);
        int64_t ref_span = 0;
        for (int k = 0; k < n_cigar; ++k) {
          uint32_t c;
          memcpy(&c, p + 4 * k, 4);
          crow[k] = uint8_t(c & 0xf);
          clrow[k] = int32_t(c >> 4);
          if (consumes_ref(int(c & 0xf))) ref_span += c >> 4;
        }
        cigar_n[r] = n_cigar;
        end_[r] = start[r] >= 0 ? start[r] + ref_span : -1;
        p += 4 * int64_t(n_cigar);
        uint8_t* brow = bases + r * lmax;
        uint8_t* qrow = quals + r * lmax;
        memset(brow, BASE_PAD, size_t(lmax));
        memset(qrow, QUAL_PAD, size_t(lmax));
        for (int32_t k = 0; k < l_seq; ++k) {
          uint8_t nib = (k & 1) ? (p[k >> 1] & 0xf) : (p[k >> 1] >> 4);
          brow[k] = LUT.bam_seq[nib];
        }
        lengths[r] = l_seq;
        p += (int64_t(l_seq) + 1) / 2;
        bool all_ff = l_seq > 0;
        for (int32_t k = 0; k < l_seq; ++k)
          if (p[k] != 0xff) { all_ff = false; break; }
        if (l_seq && !all_ff) {
          memcpy(qrow, p, size_t(l_seq));
          has_qual[r] = 1;
        } else {
          has_qual[r] = 0;
          for (int32_t k = 0; k < l_seq; ++k) qrow[k] = 0;
        }
        p += l_seq;
        // tags
        int32_t rg = -1;
        int64_t aused = 0, mlen = -1, qlen = -1;
        attr_off[r] = apos;
        md_off[r] = mpos;
        oq_off[r] = qpos;
        if (bam_tags_to_text(p, rec_end,
                             reinterpret_cast<char*>(attr_buf) + apos,
                             acap - apos, &aused, &rg, rgs,
                             reinterpret_cast<char*>(md_buf) + mpos, &mlen,
                             reinterpret_cast<char*>(oq_buf) + qpos,
                             &qlen) != 0) {
          oks[size_t(t)] = 0;
          return;
        }
        apos += aused;
        md_present[r] = mlen >= 0 ? 1 : 0;
        if (mlen > 0) mpos += mlen;
        oq_present[r] = qlen >= 0 ? 1 : 0;
        if (qlen > 0) qpos += qlen;
        rg_idx[r] = rg;
      }
      used_a[size_t(t)] = apos - tbase[size_t(t)];
      used_m[size_t(t)] = mpos - tbase[size_t(t)];
      used_q[size_t(t)] = qpos - tbase[size_t(t)];
    });
  }
  for (auto& t : ts) t.join();
  for (auto ok : oks)
    if (!ok) return 1;
  // compact
  int64_t aw = 0, mw = 0, qw = 0;
  for (int t = 0; t < nthreads; ++t) {
    int64_t base = tbase[size_t(t)];
    if (used_a[size_t(t)] && aw != base)
      memmove(attr_buf + aw, attr_buf + base, size_t(used_a[size_t(t)]));
    if (used_m[size_t(t)] && mw != base)
      memmove(md_buf + mw, md_buf + base, size_t(used_m[size_t(t)]));
    if (used_q[size_t(t)] && qw != base)
      memmove(oq_buf + qw, oq_buf + base, size_t(used_q[size_t(t)]));
    int64_t da = aw - base, dm = mw - base, dq = qw - base;
    for (int64_t r = r0[size_t(t)]; r < r0[size_t(t) + 1]; ++r) {
      attr_off[r] += da;
      md_off[r] += dm;
      oq_off[r] += dq;
    }
    aw += used_a[size_t(t)];
    mw += used_m[size_t(t)];
    qw += used_q[size_t(t)];
  }
  attr_off[nrec] = aw;
  md_off[nrec] = mw;
  oq_off[nrec] = qw;
  name_off[nrec] = h->name_bytes;
  *attr_bytes = aw;
  *md_bytes = mw;
  *oq_bytes = qw;
  return 0;
}

void bamtok_free(void* vh) { delete static_cast<BamHandle*>(vh); }

// Gather variable-width byte spans [starts[i], starts[i]+lens[i]) from src
// into a packed destination — the StringColumn row-gather (take) kernel.
// One memcpy per row beats the numpy repeat/arange index machinery (three
// full-size int64 temporaries) on the single-core hosts this runs on.
void span_gather(const uint8_t* src, const int64_t* starts,
                 const int64_t* lens, int64_t n, uint8_t* out) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t l = lens[i];
    if (l > 0) {
      memcpy(out + off, src + starts[i], size_t(l));
      off += l;
    }
  }
}

// Strided variant: row i's span lands at out + i*w (rows pre-zeroed by
// the caller) — the StringColumn.to_fixed_bytes layout for np.unique
// grouping, one memcpy per row instead of three fancy-index passes.
void span_gather_strided(const uint8_t* src, const int64_t* starts,
                         const int64_t* lens, int64_t n, int64_t w,
                         uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    int64_t l = lens[i];
    if (l > 0) memcpy(out + i * w, src + starts[i], size_t(l));
  }
}

// Padded byte matrix [N, W] -> LUT-mapped, length-compacted string
// buffer (row i's first lens[i] bytes land at out + off[i]).  One fused
// pass replacing the numpy LUT gather + mask-compress pair that
// dominated the Parquet part encode (sequence/qual columns: codes ->
// ASCII bases, quals -> clamped Sanger chars).  ``off`` is the caller's
// exclusive cumsum of lens (also the arrow offsets vector).
void lut_compact_rows(const uint8_t* mat, const int32_t* lens,
                      const int64_t* off, int64_t N, int64_t W,
                      const uint8_t* lut, uint8_t* out, int nthreads) {
  parallel_rows(N, nthreads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t l = lens[i];
      if (l <= 0) continue;
      if (l > W) l = W;
      const uint8_t* src = mat + i * W;
      uint8_t* dst = out + off[i];
      for (int64_t j = 0; j < l; ++j) dst[j] = lut[src[j]];
    }
  });
}

// Byte offset of every ``stride``-th line start in buf[begin:n], plus
// the end-of-last-line offset as the final entry.  Returns the number
// of offsets written (<= cap), or -1 if cap is too small.  Replaces the
// numpy whole-buffer newline scan (bool compare + flatnonzero over the
// input, ~0.5 s/GB) with one memchr walk, for the windowed SAM reader.
int64_t line_index_strided(const uint8_t* buf, int64_t n, int64_t begin,
                           int64_t stride, int64_t* out, int64_t cap) {
  if (stride < 1) stride = 1;
  int64_t written = 0;
  int64_t line = 0;
  int64_t pos = begin;
  while (pos < n) {
    if (line % stride == 0) {
      if (written >= cap) return -1;
      out[written++] = pos;
    }
    const void* nl = memchr(buf + pos, '\n', size_t(n - pos));
    pos = nl ? (static_cast<const uint8_t*>(nl) - buf) + 1 : n;
    ++line;
  }
  if (written >= cap) return -1;
  out[written++] = n;  // end offset (an unterminated final line included)
  return written;
}

}  // extern "C"
