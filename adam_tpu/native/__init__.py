"""Native (C++) ingest kernels, loaded via ctypes.

Compiled on demand with g++ from :file:`adamtok.cpp` and cached next to
the source keyed by a source hash.  Everything here degrades gracefully:
if the toolchain is unavailable or a file is malformed, callers fall back
to the pure-Python codecs (same semantics, slower).

This is the runtime layer the reference delegates to htsjdk/hadoop-bam
(JVM-native record codecs); here it is a small C++ library so host-side
ingest keeps pace with the TPU compute path.
"""

from __future__ import annotations

import ctypes as ct
import functools
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional, Sequence

import numpy as np

from adam_tpu.utils import instrumentation as _instr
from adam_tpu.utils import telemetry as _tele

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "adamtok.cpp")
_SRC_REALIGN = os.path.join(_DIR, "realign.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ct.CDLL] = None
_LOAD_FAILED = False


def _timed(timer_name: str):
    """Record a native dispatch under the instrumentation registry (the
    InstrumentedOutputFormat analog, rdd/ADAMRDDFunctions.scala:161-164)
    AND as a telemetry span of the same name on the calling thread's
    flight-recorder track (the timer table aggregates; the span shows
    where the dispatch sat in the streamed overlap): no-op unless
    recording was switched on."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _instr.TIMERS.time(timer_name), _tele.TRACE.span(timer_name):
                return fn(*args, **kwargs)

        return wrapper

    return deco

_i64p = ct.POINTER(ct.c_int64)
_i32p = ct.POINTER(ct.c_int32)
_u8p = ct.POINTER(ct.c_uint8)


def _cpu_fingerprint() -> str:
    """ISA feature fingerprint of this host (the 'flags' line of
    /proc/cpuinfo, or the platform string elsewhere)."""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    return " ".join(sorted(line.split(":", 1)[1].split()))
    except OSError:
        pass
    import platform

    return platform.machine() + " " + platform.processor()


_BASE_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC"]
# -march=native first: the scan/fill/LUT hot loops vectorize well on the
# AVX2/AVX-512 hosts this runs on.  The plain build is the fallback for
# toolchains/CPUs where that flag fails; the cache tag includes the flag
# set so a flag change cannot serve a stale .so.
_FLAG_SETS = [_BASE_FLAGS + ["-march=native"], _BASE_FLAGS]


@functools.lru_cache(maxsize=1)
def _compiler_fingerprint() -> str:
    """First line of ``g++ --version`` — in the cache tag so a toolchain
    upgrade (ABI/codegen change) can never serve a stale binary."""
    try:
        res = subprocess.run(
            ["g++", "--version"], capture_output=True, timeout=20
        )
        if res.returncode == 0 and res.stdout:
            return res.stdout.decode("utf-8", "replace").splitlines()[0]
    except Exception:
        pass
    return "unknown-compiler"


def _cache_dir() -> str:
    """Build-cache directory: ``ADAM_TPU_NATIVE_CACHE`` override, else a
    per-user cache dir (XDG) — never inside the package tree, so opaque
    host-specific binaries cannot end up in version control."""
    env = os.environ.get("ADAM_TPU_NATIVE_CACHE")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "adam_tpu", "native")


def _build_so() -> Optional[str]:
    try:
        h = hashlib.sha256()
        for path in (_SRC, _SRC_REALIGN):
            with open(path, "rb") as fh:
                h.update(fh.read())
    except OSError:
        return None  # missing source: degrade to the Python fallbacks
    h.update(_compiler_fingerprint().encode())
    src_hash = h.copy()
    build_dir = _cache_dir()
    for flags in _FLAG_SETS:
        h = src_hash.copy()
        h.update(" ".join(flags).encode())
        if "-march=native" in flags:
            # a native-ISA binary is host-specific: key the cache on the
            # CPU's feature set so a shared cache dir can never serve an
            # AVX-512 build to a host that would SIGILL on it
            h.update(_cpu_fingerprint().encode())
        tag = h.hexdigest()[:16]
        so_path = os.path.join(build_dir, f"adamtok_{tag}.so")
        if os.path.exists(so_path):
            return so_path
        try:
            os.makedirs(build_dir, exist_ok=True)
            with tempfile.TemporaryDirectory(dir=build_dir) as td:
                tmp = os.path.join(td, "adamtok.so")
                cmd = (
                    ["g++"] + flags
                    + ["-o", tmp, _SRC, _SRC_REALIGN, "-lz", "-pthread"]
                )
                res = subprocess.run(cmd, capture_output=True, timeout=240)
                if res.returncode != 0:
                    continue
                os.replace(tmp, so_path)
            return so_path
        except Exception:
            continue
    return None


def _lib() -> Optional[ct.CDLL]:
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        so = _build_so()
        if so is None:
            _LOAD_FAILED = True
            return None
        try:
            lib = ct.CDLL(so)
            lib.adamtok_version.restype = ct.c_int
            lib.samtok_scan.restype = ct.c_void_p
            lib.samtok_scan.argtypes = [_u8p, ct.c_int64, ct.c_int64, ct.c_int]
            lib.samtok_dims.argtypes = [
                ct.c_void_p, _i64p, _i32p, _i32p, _i64p, _i64p,
            ]
            lib.samtok_fill.restype = ct.c_int
            _out_cols = [
                _i32p, _i32p, _i64p, _i64p, _i32p, _i32p, _i64p, _i32p,
                _i32p, _i32p, _u8p,                     # ...has_qual
                _u8p, _u8p, ct.c_int64,                 # bases, quals, lmax
                _u8p, _i32p, _i32p, ct.c_int64,         # cigar_*, cmax
                _u8p, _i64p,                            # name
                _u8p, _i64p,                            # attrs
                _u8p, _i64p, _u8p,                      # md
                _u8p, _i64p, _u8p,                      # oq
                _i64p, _i64p, _i64p,                    # byte counts out
            ]
            lib.samtok_fill.argtypes = (
                [ct.c_void_p, _u8p, _i64p, ct.c_int32, _u8p, _i64p,
                 ct.c_int32] + _out_cols
            )
            lib.samtok_free.argtypes = [ct.c_void_p]
            lib.bgzf_scan.restype = ct.c_void_p
            lib.bgzf_scan.argtypes = [_u8p, ct.c_int64]
            lib.bgzf_dims.argtypes = [ct.c_void_p, _i64p, _i64p]
            lib.bgzf_fill.restype = ct.c_int
            lib.bgzf_fill.argtypes = [ct.c_void_p, _u8p, ct.c_int]
            lib.bgzf_free.argtypes = [ct.c_void_p]
            lib.bgzf_compress.restype = ct.c_int
            lib.bgzf_compress.argtypes = [
                _u8p, ct.c_int64, ct.c_int64, _u8p, ct.c_int64, _i64p,
                ct.c_int, ct.c_int,
            ]
            lib.bamtok_scan.restype = ct.c_void_p
            lib.bamtok_scan.argtypes = [_u8p, ct.c_int64, ct.c_int64]
            lib.bamtok_dims.argtypes = [
                ct.c_void_p, _i64p, _i32p, _i32p, _i64p, _i64p,
            ]
            lib.bamtok_fill.restype = ct.c_int
            lib.bamtok_fill.argtypes = (
                [ct.c_void_p, _u8p, _i64p, ct.c_int32] + _out_cols
                + [ct.c_int]
            )
            lib.bamtok_free.argtypes = [ct.c_void_p]
            lib.bgzf_scan2.restype = ct.c_void_p
            lib.bgzf_scan2.argtypes = [_u8p, ct.c_int64, ct.c_int]
            lib.bgzf_consumed.restype = ct.c_int64
            lib.bgzf_consumed.argtypes = [ct.c_void_p]
            lib.bamtok_scan2.restype = ct.c_void_p
            lib.bamtok_scan2.argtypes = [
                _u8p, ct.c_int64, ct.c_int64, ct.c_int,
            ]
            lib.bamtok_consumed.restype = ct.c_int64
            lib.bamtok_consumed.argtypes = [ct.c_void_p]
            lib.ref_positions.argtypes = [
                _u8p, _i32p, _i32p, _i64p,
                ct.c_int64, ct.c_int64, ct.c_int64, _i64p, ct.c_int,
            ]
            lib.cigar_cols.restype = ct.c_int
            lib.cigar_cols.argtypes = [
                _u8p, _i64p, ct.c_int64, ct.c_int64,
                _u8p, _i32p, _i32p, ct.c_int,
            ]
            lib.bqsr_observe.argtypes = [
                _u8p, _u8p, _i32p, _i32p, _i32p,
                _u8p, _i32p, _i32p, ct.c_int64,
                _i32p, _i64p, _i64p, ct.c_int64,
                _u8p, _u8p, _u8p,
                _u8p, _i64p,
                ct.c_int64, ct.c_int64, ct.c_int32, ct.c_int64,
                _i64p, _i64p, ct.c_int,
            ]
            lib.cigar_strings.restype = ct.c_int64
            lib.cigar_strings.argtypes = [
                _u8p, _i32p, _i32p, ct.c_int64, ct.c_int64,
                _u8p, ct.c_int64, _i64p, ct.c_int,
            ]
            lib.fastq_encode.restype = ct.c_int64
            lib.fastq_encode.argtypes = [
                _i32p, _i32p, _u8p, _u8p, _u8p, ct.c_int64,
                _u8p, _i64p, ct.c_int, ct.c_int64, _u8p, ct.c_int64,
                ct.c_int,
            ]
            lib.bqsr_apply.argtypes = [
                _u8p, _u8p, _i32p, _i32p, _i32p, _u8p, _u8p,
                ct.c_int64, ct.c_int64,
                _u8p, ct.c_int32, ct.c_int32, ct.c_int64,
                _u8p, ct.c_int,
            ]
            lib.sam_encode.restype = ct.c_int64
            lib.sam_encode.argtypes = [
                _i32p, _i32p, _i64p, _i32p, _i32p, _i64p, _i32p, _i32p,
                _u8p, _u8p,
                _u8p, _u8p, ct.c_int64,
                _u8p, _i32p, _i32p, ct.c_int64,
                _u8p, _i64p,
                _u8p, _i64p,
                _u8p, _i64p, _u8p,
                _u8p, _i64p, _u8p,
                _i32p, _u8p, _i64p, ct.c_int32,
                _u8p, _i64p, ct.c_int32,
                ct.c_int64, _u8p, ct.c_int64, ct.c_int,
            ]
            lib.bam_encode.restype = ct.c_int64
            lib.bam_encode.argtypes = [
                _i32p, _i32p, _i64p, _i32p, _i32p, _i64p, _i32p, _i32p,
                _u8p, _u8p,
                _u8p, _u8p, ct.c_int64,
                _u8p, _i32p, _i32p, ct.c_int64,
                _u8p, _i64p,
                _u8p, _i64p,
                _u8p, _i64p, _u8p,
                _u8p, _i64p, _u8p,
                _i32p, _u8p, _i64p, ct.c_int32, ct.c_int32,
                ct.c_int64, _u8p, ct.c_int64, ct.c_int,
            ]
            lib.span_gather.argtypes = [_u8p, _i64p, _i64p, ct.c_int64, _u8p]
            lib.span_gather_strided.argtypes = [
                _u8p, _i64p, _i64p, ct.c_int64, ct.c_int64, _u8p,
            ]
            lib.lut_compact_rows.argtypes = [
                _u8p, _i32p, _i64p, ct.c_int64, ct.c_int64, _u8p, _u8p,
                ct.c_int,
            ]
            lib.line_index_strided.restype = ct.c_int64
            lib.line_index_strided.argtypes = [
                _u8p, ct.c_int64, ct.c_int64, ct.c_int64, _i64p, ct.c_int64,
            ]
            lib.realign_prep.restype = ct.c_void_p
            lib.realign_prep.argtypes = [
                _u8p, _u8p, ct.c_int64, ct.c_int64,            # bases/quals/N/L
                _i32p, _i64p,                                  # lengths/start
                _u8p, _i32p, _i32p, ct.c_int64,                # cigar cols + C
                _u8p, _i64p, _u8p,                             # md buf/off/valid
                _i64p, _i64p, ct.c_int64,                      # grows/goff/G
                ct.c_int,                                      # gen_consensus
            ]
            lib.realign_prep_dims.argtypes = [
                ct.c_void_p, _i64p, _i64p, _i64p, _i64p, _i64p, _i64p,
                _i64p, _i64p,
            ]
            lib.realign_prep_fill.argtypes = [
                ct.c_void_p,
                _i32p, _u8p, _i64p, _i64p, _i64p,              # targets
                _i32p, _i64p, _u8p, _i64p, _u8p, _i64p, _u8p,  # reads
                _u8p, _u8p, _i64p,
                _i32p, _u8p, _i64p, _i64p, _i64p,              # consensuses
            ]
            lib.realign_prep_free.argtypes = [ct.c_void_p]
            lib.md_move_batch.restype = ct.c_int64
            lib.md_move_batch.argtypes = [
                _u8p, ct.c_int64, ct.c_int64, _i32p,
                _i64p, ct.c_int64,
                _u8p, _i64p,
                _i32p, _i64p,
                _i32p, _i32p, _u8p, _i32p, _i64p,
                _u8p, ct.c_int64, _i64p,
                _i64p, _i64p,
            ]
            _LIB = lib
        except Exception:
            _LOAD_FAILED = True
    return _LIB


def available() -> bool:
    return _lib() is not None


def _nthreads() -> int:
    env = os.environ.get("ADAM_TPU_NATIVE_THREADS")
    if env:
        return max(1, int(env))
    return max(1, min(16, os.cpu_count() or 1))


def _as_u8(data) -> np.ndarray:
    if isinstance(data, (bytes, bytearray, memoryview)):
        return np.frombuffer(data, dtype=np.uint8)
    return np.ascontiguousarray(data, dtype=np.uint8)


def _pretouch(arr: np.ndarray) -> np.ndarray:
    """Fault in a fresh allocation's pages single-threaded before handing
    it to the threaded C++ fills: concurrent first-touch faults from many
    threads serialize on the kernel's mmap lock (measured: a fresh 3.2 GB
    output faulted by 16 threads took 60 s vs 0.75 s pre-touched)."""
    flat = arr.reshape(-1).view(np.uint8)
    if flat.nbytes >= 1 << 20:
        flat[:: 4096] = 0
    return arr


_DUMMY = np.zeros(1, np.uint8)  # stand-in pointer for zero-size buffers


def _u8_ptr(a: np.ndarray):
    if len(a) == 0:
        a = _DUMMY
    return a.ctypes.data_as(_u8p)


def _str_dict(names: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
    from adam_tpu.formats.strings import StringColumn

    c = StringColumn.from_list(list(names))
    return c.buf, c.offsets


@_timed(_instr.TOKENIZE_INPUT)
def tokenize_sam(data, body_off: int, contig_names: Sequence[str],
                 rg_names: Sequence[str]) -> Optional[dict]:
    """Tokenize SAM body lines into columnar arrays; None -> fall back."""
    lib = _lib()
    if lib is None:
        return None
    buf = _as_u8(data)
    h = lib.samtok_scan(_u8_ptr(buf), len(buf), body_off, _nthreads())
    if not h:
        return None
    try:
        n = ct.c_int64()
        lmax = ct.c_int32()
        cmax = ct.c_int32()
        nameb = ct.c_int64()
        tagb = ct.c_int64()
        lib.samtok_dims(h, ct.byref(n), ct.byref(lmax), ct.byref(cmax),
                        ct.byref(nameb), ct.byref(tagb))
        n, L, C = n.value, max(1, lmax.value), max(1, cmax.value)
        nameb, tagb = nameb.value, tagb.value

        out = _alloc_columns(n, L, C, nameb, tagb)
        cbuf, coff = _str_dict(contig_names)
        gbuf, goff = _str_dict(rg_names)
        ab = ct.c_int64()
        mb = ct.c_int64()
        qb = ct.c_int64()
        rc = lib.samtok_fill(
            h,
            _u8_ptr(cbuf), coff.ctypes.data_as(_i64p), len(contig_names),
            _u8_ptr(gbuf), goff.ctypes.data_as(_i64p), len(rg_names),
            out["flags"].ctypes.data_as(_i32p),
            out["contig_idx"].ctypes.data_as(_i32p),
            out["start"].ctypes.data_as(_i64p),
            out["end"].ctypes.data_as(_i64p),
            out["mapq"].ctypes.data_as(_i32p),
            out["mate_contig_idx"].ctypes.data_as(_i32p),
            out["mate_start"].ctypes.data_as(_i64p),
            out["tlen"].ctypes.data_as(_i32p),
            out["rg_idx"].ctypes.data_as(_i32p),
            out["lengths"].ctypes.data_as(_i32p),
            _u8_ptr(out["has_qual"]),
            _u8_ptr(out["bases"].reshape(-1)), _u8_ptr(out["quals"].reshape(-1)),
            ct.c_int64(L),
            _u8_ptr(out["cigar_ops"].reshape(-1)),
            out["cigar_lens"].ctypes.data_as(_i32p),
            out["cigar_n"].ctypes.data_as(_i32p),
            ct.c_int64(C),
            _u8_ptr(out["name_buf"]), out["name_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["attr_buf"]), out["attr_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["md_buf"]), out["md_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["md_present"]),
            _u8_ptr(out["oq_buf"]), out["oq_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["oq_present"]),
            ct.byref(ab), ct.byref(mb), ct.byref(qb),
        )
        if rc != 0:
            return None
        out["attr_buf"] = out["attr_buf"][: ab.value]
        out["md_buf"] = out["md_buf"][: mb.value]
        out["oq_buf"] = out["oq_buf"][: qb.value]
        return out
    finally:
        lib.samtok_free(h)


def _alloc_columns(n: int, L: int, C: int, nameb: int, tagb: int) -> dict:
    out = dict(
        n=n, lmax=L, cmax=C,
        flags=np.empty(n, np.int32),
        contig_idx=np.empty(n, np.int32),
        start=np.empty(n, np.int64),
        end=np.empty(n, np.int64),
        mapq=np.empty(n, np.int32),
        mate_contig_idx=np.empty(n, np.int32),
        mate_start=np.empty(n, np.int64),
        tlen=np.empty(n, np.int32),
        rg_idx=np.empty(n, np.int32),
        lengths=np.empty(n, np.int32),
        has_qual=np.empty(n, np.uint8),
        bases=np.empty((n, L), np.uint8),
        quals=np.empty((n, L), np.uint8),
        cigar_ops=np.empty((n, C), np.uint8),
        cigar_lens=np.empty((n, C), np.int32),
        cigar_n=np.empty(n, np.int32),
        name_buf=np.empty(max(1, nameb), np.uint8)[:nameb],
        name_off=np.empty(n + 1, np.int64),
        attr_buf=np.empty(max(1, tagb), np.uint8),
        attr_off=np.empty(n + 1, np.int64),
        md_buf=np.empty(max(1, tagb), np.uint8),
        md_off=np.empty(n + 1, np.int64),
        md_present=np.empty(n, np.uint8),
        oq_buf=np.empty(max(1, tagb), np.uint8),
        oq_off=np.empty(n + 1, np.int64),
        oq_present=np.empty(n, np.uint8),
    )
    for v in out.values():
        if isinstance(v, np.ndarray):
            _pretouch(v)
    return out


@_timed(_instr.BGZF_CODEC)
def bgzf_decompress(data) -> Optional[bytes]:
    """Block-parallel BGZF decode; None if not BGZF / native unavailable."""
    lib = _lib()
    if lib is None:
        return None
    buf = _as_u8(data)
    h = lib.bgzf_scan(_u8_ptr(buf), len(buf))
    if not h:
        return None
    try:
        nb = ct.c_int64()
        ob = ct.c_int64()
        lib.bgzf_dims(h, ct.byref(nb), ct.byref(ob))
        out = _pretouch(np.empty(max(1, ob.value), np.uint8))
        if lib.bgzf_fill(h, _u8_ptr(out), _nthreads()) != 0:
            return None
        return out[: ob.value].tobytes()
    finally:
        lib.bgzf_free(h)


@_timed(_instr.BGZF_CODEC)
def bgzf_decompress_partial(data) -> Optional[tuple[bytes, int]]:
    """Streaming-window BGZF decode: decompress the *complete* blocks in
    ``data`` -> (decompressed bytes, input bytes consumed); a truncated
    final block is left for the caller's next window.  None if the data
    is not BGZF or the native library is unavailable."""
    lib = _lib()
    if lib is None:
        return None
    buf = _as_u8(data)
    h = lib.bgzf_scan2(_u8_ptr(buf), len(buf), 1)
    if not h:
        return None
    try:
        nb = ct.c_int64()
        ob = ct.c_int64()
        lib.bgzf_dims(h, ct.byref(nb), ct.byref(ob))
        out = _pretouch(np.empty(max(1, ob.value), np.uint8))
        if lib.bgzf_fill(h, _u8_ptr(out), _nthreads()) != 0:
            return None
        return out[: ob.value].tobytes(), int(lib.bgzf_consumed(h))
    finally:
        lib.bgzf_free(h)


@_timed(_instr.BGZF_CODEC)
def bgzf_compress(
    data, level: int = 6, block_size: int = 0xFF00
) -> Optional[bytes]:
    """Block-parallel BGZF encode (+EOF block); None if unavailable."""
    lib = _lib()
    if lib is None:
        return None
    buf = _as_u8(data)
    n = len(buf)
    block = min(max(1, block_size), 0xFF00)  # BSIZE is a u16 total-size field
    n_blocks = (n + block - 1) // block if n else 0
    cap = n + n_blocks * 64 + n // 512 + 1024
    out = np.empty(cap, np.uint8)
    out_len = ct.c_int64()
    rc = lib.bgzf_compress(
        _u8_ptr(buf), ct.c_int64(n), ct.c_int64(block), _u8_ptr(out),
        ct.c_int64(cap), ct.byref(out_len), ct.c_int(_nthreads()),
        ct.c_int(level),
    )
    if rc != 0:
        return None
    return out[: out_len.value].tobytes()


@_timed(_instr.TOKENIZE_INPUT)
def tokenize_bam(raw, records_off: int,
                 rg_names: Sequence[str],
                 partial: bool = False) -> Optional[dict]:
    """Parse decompressed BAM records into columnar arrays.

    With ``partial=True`` (streaming windows) a record truncated at the
    end of ``raw`` stops the scan instead of failing, and the result
    carries ``out["consumed"]`` — the byte offset after the last
    complete record — so the caller can carry the tail into the next
    window."""
    lib = _lib()
    if lib is None:
        return None
    buf = _as_u8(raw)
    h = lib.bamtok_scan2(_u8_ptr(buf), len(buf), records_off,
                         1 if partial else 0)
    if not h:
        return None
    try:
        n = ct.c_int64()
        lmax = ct.c_int32()
        cmax = ct.c_int32()
        nameb = ct.c_int64()
        tagb = ct.c_int64()
        lib.bamtok_dims(h, ct.byref(n), ct.byref(lmax), ct.byref(cmax),
                        ct.byref(nameb), ct.byref(tagb))
        n, L, C = n.value, max(1, lmax.value), max(1, cmax.value)
        out = _alloc_columns(n, L, C, nameb.value, tagb.value)
        gbuf, goff = _str_dict(rg_names)
        ab = ct.c_int64()
        mb = ct.c_int64()
        qb = ct.c_int64()
        rc = lib.bamtok_fill(
            h,
            _u8_ptr(gbuf), goff.ctypes.data_as(_i64p), len(rg_names),
            out["flags"].ctypes.data_as(_i32p),
            out["contig_idx"].ctypes.data_as(_i32p),
            out["start"].ctypes.data_as(_i64p),
            out["end"].ctypes.data_as(_i64p),
            out["mapq"].ctypes.data_as(_i32p),
            out["mate_contig_idx"].ctypes.data_as(_i32p),
            out["mate_start"].ctypes.data_as(_i64p),
            out["tlen"].ctypes.data_as(_i32p),
            out["rg_idx"].ctypes.data_as(_i32p),
            out["lengths"].ctypes.data_as(_i32p),
            _u8_ptr(out["has_qual"]),
            _u8_ptr(out["bases"].reshape(-1)), _u8_ptr(out["quals"].reshape(-1)),
            ct.c_int64(L),
            _u8_ptr(out["cigar_ops"].reshape(-1)),
            out["cigar_lens"].ctypes.data_as(_i32p),
            out["cigar_n"].ctypes.data_as(_i32p),
            ct.c_int64(C),
            _u8_ptr(out["name_buf"]), out["name_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["attr_buf"]), out["attr_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["md_buf"]), out["md_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["md_present"]),
            _u8_ptr(out["oq_buf"]), out["oq_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["oq_present"]),
            ct.byref(ab), ct.byref(mb), ct.byref(qb),
            ct.c_int(_nthreads()),
        )
        if rc != 0:
            return None
        out["attr_buf"] = out["attr_buf"][: ab.value]
        out["md_buf"] = out["md_buf"][: mb.value]
        out["oq_buf"] = out["oq_buf"][: qb.value]
        out["consumed"] = int(lib.bamtok_consumed(h))
        return out
    finally:
        lib.bamtok_free(h)


def ref_positions(cigar_ops, cigar_lens, cigar_n, start, lmax: int):
    """Per-base reference positions -> i64[N, lmax]; None if native
    unavailable.

    Threaded C++ CIGAR walk; the fallback is
    :func:`adam_tpu.ops.cigar.reference_positions_np`.
    """
    lib = _lib()
    if lib is None:
        return None
    ops = np.ascontiguousarray(cigar_ops, np.uint8)
    lens = np.ascontiguousarray(cigar_lens, np.int32)
    n_ops = np.ascontiguousarray(cigar_n, np.int32)
    st = np.ascontiguousarray(start, np.int64)
    N, C = ops.shape
    out = _pretouch(np.empty((N, lmax), np.int64))
    lib.ref_positions(
        _u8_ptr(ops), lens.ctypes.data_as(_i32p), n_ops.ctypes.data_as(_i32p),
        st.ctypes.data_as(_i64p),
        ct.c_int64(N), ct.c_int64(C), ct.c_int64(lmax),
        out.ctypes.data_as(_i64p), ct.c_int(_nthreads()),
    )
    return out


def cigar_cols(buf: np.ndarray, offsets: np.ndarray, cmax: int):
    """CIGAR strings (flat u8 buffer + offsets) -> (ops u8[N, C],
    lens i32[N, C], n_ops i32[N]); None if native unavailable or any row
    overflows ``cmax``."""
    lib = _lib()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, np.uint8)
    offsets = np.ascontiguousarray(offsets, np.int64)
    n = len(offsets) - 1
    C = max(1, int(cmax))
    ops = np.empty((n, C), np.uint8)
    lens = np.empty((n, C), np.int32)
    n_ops = np.empty(n, np.int32)
    rc = lib.cigar_cols(
        _u8_ptr(buf), offsets.ctypes.data_as(_i64p),
        ct.c_int64(n), ct.c_int64(C),
        _u8_ptr(ops.reshape(-1)), lens.ctypes.data_as(_i32p),
        n_ops.ctypes.data_as(_i32p), ct.c_int(_nthreads()),
    )
    if rc != 0:
        return None
    return ops, lens, n_ops


def _encode_prep(batch, side, rg_names: Sequence[str]):
    """Shared marshalling for the SAM/BAM encoders: numpy-ified batch,
    sidecar StringColumns (None when the sidecar is shorter than the
    padded batch -> caller falls back), RG dict, and the common leading
    ctypes argument list."""
    import jax

    from adam_tpu.formats.strings import StringColumn

    b = jax.tree.map(lambda x: np.asarray(x), batch)
    n = b.n_rows
    names = StringColumn.of(side.names)
    attrs = StringColumn.of(side.attrs)
    md = StringColumn.of(side.md)
    oq = StringColumn.of(side.orig_quals)
    if len(names) < n or len(attrs) < n or len(md) < n or len(oq) < n:
        return None

    c64 = lambda x: np.ascontiguousarray(x, np.int64)  # noqa: E731
    c32 = lambda x: np.ascontiguousarray(x, np.int32)  # noqa: E731
    cu8 = lambda x: np.ascontiguousarray(x, np.uint8)  # noqa: E731

    gbuf, goff = _str_dict(rg_names)
    # keep every marshalled array alive for the duration of the call
    keep = dict(
        flags=c32(b.flags), contig_idx=c32(b.contig_idx), start=c64(b.start),
        mapq=c32(b.mapq), mate_contig_idx=c32(b.mate_contig_idx),
        mate_start=c64(b.mate_start), tlen=c32(b.tlen),
        lengths=c32(b.lengths), has_qual=cu8(np.asarray(b.has_qual)),
        valid=cu8(np.asarray(b.valid)),
        bases=cu8(b.bases).reshape(-1), quals=cu8(b.quals).reshape(-1),
        cigar_ops=cu8(b.cigar_ops).reshape(-1),
        cigar_lens=c32(b.cigar_lens), cigar_n=c32(b.cigar_n),
        md_valid=cu8(np.asarray(md.valid)),
        oq_valid=cu8(np.asarray(oq.valid) & (oq.lengths() > 0)),
        rg_idx=c32(b.read_group_idx), gbuf=gbuf, goff=goff,
    )
    args = [
        keep["flags"].ctypes.data_as(_i32p),
        keep["contig_idx"].ctypes.data_as(_i32p),
        keep["start"].ctypes.data_as(_i64p),
        keep["mapq"].ctypes.data_as(_i32p),
        keep["mate_contig_idx"].ctypes.data_as(_i32p),
        keep["mate_start"].ctypes.data_as(_i64p),
        keep["tlen"].ctypes.data_as(_i32p),
        keep["lengths"].ctypes.data_as(_i32p),
        _u8_ptr(keep["has_qual"]),
        _u8_ptr(keep["valid"]),
        _u8_ptr(keep["bases"]),
        _u8_ptr(keep["quals"]),
        ct.c_int64(b.lmax),
        _u8_ptr(keep["cigar_ops"]),
        keep["cigar_lens"].ctypes.data_as(_i32p),
        keep["cigar_n"].ctypes.data_as(_i32p),
        ct.c_int64(b.cmax),
        _u8_ptr(names.buf), names.offsets.ctypes.data_as(_i64p),
        _u8_ptr(attrs.buf), attrs.offsets.ctypes.data_as(_i64p),
        _u8_ptr(md.buf), md.offsets.ctypes.data_as(_i64p),
        _u8_ptr(keep["md_valid"]),
        _u8_ptr(oq.buf), oq.offsets.ctypes.data_as(_i64p),
        _u8_ptr(keep["oq_valid"]),
        keep["rg_idx"].ctypes.data_as(_i32p),
        _u8_ptr(gbuf), goff.ctypes.data_as(_i64p), ct.c_int32(len(rg_names)),
    ]
    # common capacity terms: names + cigars + seq/qual + sidecar strings
    lens = np.where(b.valid, b.lengths, 0).astype(np.int64)
    base_cap = (
        int(names.offsets[-1])
        + 12 * int(np.asarray(b.cigar_n, np.int64).sum())
        + int(lens.sum()) * 2
        + int(attrs.offsets[-1]) + int(md.offsets[-1]) + int(oq.offsets[-1])
        + (max((len(s) for s in rg_names), default=0) + 8) * n
    )
    keep["_strings"] = (names, attrs, md, oq)
    return n, args, base_cap, keep


@_timed(_instr.SAM_ENCODE)
def bam_encode(batch, side, rg_names: Sequence[str],
               n_refs: int) -> Optional[bytes]:
    """Encode a (ReadBatch, ReadSidecar) into the BAM record stream
    (everything after the reference list); None -> caller falls back to
    the pure-Python writer.  ``n_refs`` bounds contig/mate refIDs — an
    out-of-range index fails the encode rather than emitting a BAM whose
    refID points outside the reference list."""
    lib = _lib()
    if lib is None:
        return None
    prep = _encode_prep(batch, side, rg_names)
    if prep is None:
        return None
    n, args, base_cap, keep = prep
    cap = int(n * 80 + base_cap)
    out = _pretouch(np.empty(cap, np.uint8))
    got = lib.bam_encode(
        *args, ct.c_int32(int(n_refs)), ct.c_int64(n), _u8_ptr(out),
        ct.c_int64(cap), ct.c_int(_nthreads()),
    )
    if got < 0:
        return None
    return out[:got].tobytes()


@_timed(_instr.SAM_ENCODE)
def sam_encode(batch, side, rg_names: Sequence[str],
               contig_names: Sequence[str]) -> Optional[bytes]:
    """Format a (ReadBatch, ReadSidecar) as SAM text lines (no header);
    None -> caller falls back to the pure-Python formatter."""
    lib = _lib()
    if lib is None:
        return None
    prep = _encode_prep(batch, side, rg_names)
    if prep is None:
        return None
    n, args, base_cap, keep = prep
    cbuf, coff = _str_dict(contig_names)
    max_name = (max((len(s) for s in contig_names), default=1) + 2) * 2
    cap = int(n * (140 + max_name) + base_cap)
    out = _pretouch(np.empty(cap, np.uint8))
    got = lib.sam_encode(
        *args,
        _u8_ptr(cbuf), coff.ctypes.data_as(_i64p),
        ct.c_int32(len(contig_names)),
        ct.c_int64(n), _u8_ptr(out), ct.c_int64(cap), ct.c_int(_nthreads()),
    )
    if got < 0:
        return None
    return out[:got].tobytes()


@_timed(_instr.APPLY_WALK)
def bqsr_apply(bases, quals, lengths, flags, rg_idx, has_qual, valid,
               table_u8, gl: int):
    """Threaded host application of the BQSR recalibration table ->
    new quals u8[N, L]; None if native unavailable."""
    lib = _lib()
    if lib is None:
        return None
    bases = np.ascontiguousarray(bases, np.uint8)
    quals = np.ascontiguousarray(quals, np.uint8)
    n, lmax = bases.shape
    table = np.ascontiguousarray(table_u8, np.uint8)
    n_rg, _, n_cyc, _ = table.shape
    out = _pretouch(np.empty((n, lmax), np.uint8))
    lib.bqsr_apply(
        _u8_ptr(bases.reshape(-1)), _u8_ptr(quals.reshape(-1)),
        np.ascontiguousarray(lengths, np.int32).ctypes.data_as(_i32p),
        np.ascontiguousarray(flags, np.int32).ctypes.data_as(_i32p),
        np.ascontiguousarray(rg_idx, np.int32).ctypes.data_as(_i32p),
        _u8_ptr(np.ascontiguousarray(has_qual, np.uint8)),
        _u8_ptr(np.ascontiguousarray(valid, np.uint8)),
        ct.c_int64(n), ct.c_int64(lmax),
        _u8_ptr(table.reshape(-1)), ct.c_int32(n_rg), ct.c_int32(n_cyc),
        ct.c_int64(gl), _u8_ptr(out.reshape(-1)), ct.c_int(_nthreads()),
    )
    return out


@_timed(_instr.OBSERVE_WALK)
def bqsr_observe(bases, quals, lengths, flags, rg_idx,
                 cigar_ops, cigar_lens, cigar_n,
                 residue_ok, is_mm, read_ok, n_rg: int, gl: int,
                 contig_idx=None, start=None, snp_keys=None,
                 md_buf=None, md_off=None):
    """Threaded host covariate histogram -> (total, mism) i64 arrays of
    shape [n_rg, 94, 2*gl+1, 17]; None if native unavailable.

    ``residue_ok`` may be None: the aligned/q>0/base<4 residue filter is
    then derived from the cigar columns inside the kernel, so no [N, L]
    mask ever materializes.  ``is_mm`` may also be None when
    ``md_buf``/``md_off`` (the sidecar MD string column) are given: the
    kernel parses each read's MD inline during the same walk instead of
    consuming a host-tokenized [N, L] mismatch mask.  Known-SNP masking
    likewise runs in-kernel:
    pass ``contig_idx``/``start`` plus ``snp_keys`` (sorted i64
    ``contig << 40 | pos`` site keys) and masked residues are skipped
    during the same cigar walk — no host-side [N, L] position matrix."""
    lib = _lib()
    if lib is None:
        return None
    bases = np.ascontiguousarray(bases, np.uint8)
    quals = np.ascontiguousarray(quals, np.uint8)
    n, lmax = bases.shape
    c_ops = np.ascontiguousarray(cigar_ops, np.uint8)
    cmax = c_ops.shape[1] if c_ops.ndim == 2 else 0
    n_cyc = 2 * gl + 1
    shape = (n_rg, 94, n_cyc, 17)
    total = _pretouch(np.empty(shape, np.int64))
    mism = _pretouch(np.empty(shape, np.int64))
    if residue_ok is not None:
        rok_arr = np.ascontiguousarray(residue_ok, np.uint8).reshape(-1)
        rok_ptr = _u8_ptr(rok_arr)
    else:
        rok_ptr = ct.cast(None, _u8p)
    if snp_keys is not None and len(snp_keys) and residue_ok is None:
        ci_arr = np.ascontiguousarray(contig_idx, np.int32)
        st_arr = np.ascontiguousarray(start, np.int64)
        sk_arr = np.ascontiguousarray(snp_keys, np.int64)
        ci_ptr = ci_arr.ctypes.data_as(_i32p)
        st_ptr = st_arr.ctypes.data_as(_i64p)
        sk_ptr = sk_arr.ctypes.data_as(_i64p)
        n_snps = len(sk_arr)
    else:
        ci_ptr = ct.cast(None, _i32p)
        st_ptr = ct.cast(None, _i64p)
        sk_ptr = ct.cast(None, _i64p)
        n_snps = 0
    if is_mm is not None:
        mm_arr = np.ascontiguousarray(is_mm, np.uint8).reshape(-1)
        mm_ptr = _u8_ptr(mm_arr)
        mdb_ptr = ct.cast(None, _u8p)
        mdo_ptr = ct.cast(None, _i64p)
    else:
        if md_buf is None or md_off is None:
            return None
        mdb_arr = np.ascontiguousarray(md_buf, np.uint8)
        mdo_arr = np.ascontiguousarray(md_off, np.int64)
        mm_ptr = ct.cast(None, _u8p)
        mdb_ptr = _u8_ptr(mdb_arr)
        mdo_ptr = mdo_arr.ctypes.data_as(_i64p)
    lib.bqsr_observe(
        _u8_ptr(bases.reshape(-1)), _u8_ptr(quals.reshape(-1)),
        np.ascontiguousarray(lengths, np.int32).ctypes.data_as(_i32p),
        np.ascontiguousarray(flags, np.int32).ctypes.data_as(_i32p),
        np.ascontiguousarray(rg_idx, np.int32).ctypes.data_as(_i32p),
        _u8_ptr(c_ops.reshape(-1)),
        np.ascontiguousarray(cigar_lens, np.int32).ctypes.data_as(_i32p),
        np.ascontiguousarray(cigar_n, np.int32).ctypes.data_as(_i32p),
        ct.c_int64(cmax),
        ci_ptr, st_ptr, sk_ptr, ct.c_int64(n_snps),
        rok_ptr,
        mm_ptr,
        _u8_ptr(np.ascontiguousarray(read_ok, np.uint8)),
        mdb_ptr, mdo_ptr,
        ct.c_int64(n), ct.c_int64(lmax), ct.c_int32(n_rg), ct.c_int64(gl),
        total.ctypes.data_as(_i64p), mism.ctypes.data_as(_i64p),
        ct.c_int(_nthreads()),
    )
    return total, mism


@_timed(_instr.FASTQ_ENCODE)
def fastq_encode(batch, side, select, add_suffix: bool) -> Optional[bytes]:
    """Format selected rows as FASTQ text; None -> python fallback."""
    lib = _lib()
    if lib is None:
        return None
    import jax

    from adam_tpu.formats.strings import StringColumn

    b = jax.tree.map(lambda x: np.asarray(x), batch)
    n = b.n_rows
    names = StringColumn.of(side.names)
    if len(names) < n:
        return None
    lens = np.where(select, b.lengths, 0).astype(np.int64)
    cap = int(int(names.offsets[-1]) + 2 * int(lens.sum()) + 16 * n + 64)
    out = _pretouch(np.empty(cap, np.uint8))
    got = lib.fastq_encode(
        np.ascontiguousarray(b.flags, np.int32).ctypes.data_as(_i32p),
        np.ascontiguousarray(b.lengths, np.int32).ctypes.data_as(_i32p),
        _u8_ptr(np.ascontiguousarray(select, np.uint8)),
        _u8_ptr(np.ascontiguousarray(b.bases, np.uint8).reshape(-1)),
        _u8_ptr(np.ascontiguousarray(b.quals, np.uint8).reshape(-1)),
        ct.c_int64(b.lmax),
        _u8_ptr(names.buf), names.offsets.ctypes.data_as(_i64p),
        ct.c_int(1 if add_suffix else 0),
        ct.c_int64(n), _u8_ptr(out), ct.c_int64(cap), ct.c_int(_nthreads()),
    )
    if got < 0:
        return None
    return out[:got].tobytes()


def cigar_strings(cigar_ops, cigar_lens, cigar_n):
    """Columnar cigars -> (buf u8, offsets i64[N+1]) run-length strings
    ('*' when no ops); None if native unavailable."""
    lib = _lib()
    if lib is None:
        return None
    ops = np.ascontiguousarray(cigar_ops, np.uint8)
    lens = np.ascontiguousarray(cigar_lens, np.int32)
    n_ops = np.ascontiguousarray(cigar_n, np.int32)
    n, C = ops.shape if ops.ndim == 2 else (len(n_ops), 0)
    if C == 0:
        off = np.arange(n + 1, dtype=np.int64)
        return np.full(n, ord("*"), np.uint8), off
    cap = int(12 * int(np.minimum(n_ops, C).clip(0).sum()) + n + 64)
    out = _pretouch(np.empty(cap, np.uint8))
    offsets = np.empty(n + 1, np.int64)
    got = lib.cigar_strings(
        _u8_ptr(ops.reshape(-1)), lens.ctypes.data_as(_i32p),
        n_ops.ctypes.data_as(_i32p), ct.c_int64(n), ct.c_int64(C),
        _u8_ptr(out), ct.c_int64(cap), offsets.ctypes.data_as(_i64p),
        ct.c_int(_nthreads()),
    )
    if got < 0:
        return None
    return out[:got], offsets




def _spans_in_bounds(starts: np.ndarray, lens: np.ndarray, size: int) -> bool:
    """Corrupt-offset guard shared by the span gather wrappers: negative
    lens from non-monotonic offsets would otherwise overflow out buffers."""
    if not len(starts):
        return True
    return (
        int((starts + lens).max()) <= size
        and int(starts.min()) >= 0
        and int(lens.min()) >= 0
    )

def span_gather(src: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                total: int):
    """Packed gather of byte spans [starts[i], starts[i]+lens[i]) ->
    u8[total]; None if native unavailable.  The StringColumn.take hot
    path."""
    lib = _lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, np.uint8)
    starts = np.ascontiguousarray(starts, np.int64)
    lens = np.ascontiguousarray(lens, np.int64)
    if not _spans_in_bounds(starts, lens, src.size):
        return None  # corrupt offsets: numpy path's fail-safe instead
    out = np.empty(int(total), np.uint8)
    lib.span_gather(
        _u8_ptr(src), starts.ctypes.data_as(_i64p),
        lens.ctypes.data_as(_i64p), ct.c_int64(len(starts)), _u8_ptr(out),
    )
    return out


def lut_compact_rows(mat: np.ndarray, lens: np.ndarray, lut: np.ndarray):
    """Padded byte matrix [N, W] -> (LUT-mapped compact string buffer,
    i64 arrow offsets); None if native unavailable.

    One fused pass standing in for the numpy pair
    ``LUT[mat]`` + ``StringColumn.from_matrix`` that dominated the
    Parquet part encode (sequence/qual columns)."""
    lib = _lib()
    if lib is None:
        return None
    mat = np.ascontiguousarray(mat, np.uint8)
    n, w = mat.shape
    lens32 = np.clip(np.asarray(lens), 0, w).astype(np.int32)
    lut = np.ascontiguousarray(lut, np.uint8)
    if lut.size < 256:
        return None
    off = np.zeros(n + 1, np.int64)
    np.cumsum(lens32, out=off[1:])
    out = _pretouch(np.empty(max(1, int(off[-1])), np.uint8))
    lib.lut_compact_rows(
        _u8_ptr(mat.reshape(-1)), lens32.ctypes.data_as(_i32p),
        off.ctypes.data_as(_i64p), ct.c_int64(n), ct.c_int64(w),
        _u8_ptr(lut), _u8_ptr(out), _nthreads(),
    )
    return out[: int(off[-1])], off


def line_index_strided(data, begin: int, stride: int):
    """Byte offsets of every ``stride``-th line start in ``data[begin:]``
    plus the final end offset -> i64 array; None if native unavailable.

    The windowed SAM reader's replacement for a whole-buffer numpy
    newline scan."""
    lib = _lib()
    if lib is None:
        return None
    buf = _as_u8(data)
    n = len(buf)
    stride = max(1, int(stride))
    cap = (n - int(begin)) // stride + 3
    out = np.empty(cap, np.int64)
    got = lib.line_index_strided(
        _u8_ptr(buf), ct.c_int64(n), ct.c_int64(begin),
        ct.c_int64(stride), out.ctypes.data_as(_i64p), ct.c_int64(cap),
    )
    if got < 0:
        return None
    return out[:got]


def realign_prep(b, md_col_buf, md_col_off, md_valid, grows, goff,
                 gen_consensus: bool):
    """Native phase-1 realignment prep (see native/realign.cpp).

    ``b`` is a numpy ReadBatch view of the candidate rows; groups are the
    flat row list + offsets.  Returns a dict of per-target, per-to-clean-
    read and per-consensus arrays, or None when native is unavailable.
    Raises the same exception classes the Python path raises (ValueError
    for malformed MD / missing deleted bases, IndexError for CIGAR
    overruns)."""
    lib = _lib()
    if lib is None:
        return None
    bases = np.ascontiguousarray(b.bases, np.uint8)
    quals = np.ascontiguousarray(b.quals, np.uint8)
    N, L = bases.shape
    lengths = np.ascontiguousarray(b.lengths, np.int32)
    start = np.ascontiguousarray(b.start, np.int64)
    ops = np.ascontiguousarray(b.cigar_ops, np.uint8)
    lens = np.ascontiguousarray(b.cigar_lens, np.int32)
    n_ops = np.ascontiguousarray(b.cigar_n, np.int32)
    C = ops.shape[1]
    md_buf = np.ascontiguousarray(md_col_buf, np.uint8)
    md_off = np.ascontiguousarray(md_col_off, np.int64)
    md_val = np.ascontiguousarray(md_valid, np.uint8)
    grows = np.ascontiguousarray(grows, np.int64)
    goff = np.ascontiguousarray(goff, np.int64)
    G = len(goff) - 1
    h = lib.realign_prep(
        _u8_ptr(bases), _u8_ptr(quals), ct.c_int64(N), ct.c_int64(L),
        lengths.ctypes.data_as(_i32p), start.ctypes.data_as(_i64p),
        _u8_ptr(ops.reshape(-1)), lens.ctypes.data_as(_i32p),
        n_ops.ctypes.data_as(_i32p), ct.c_int64(C),
        _u8_ptr(md_buf), md_off.ctypes.data_as(_i64p), _u8_ptr(md_val),
        grows.ctypes.data_as(_i64p), goff.ctypes.data_as(_i64p),
        ct.c_int64(G), ct.c_int(1 if gen_consensus else 0),
    )
    if not h:
        return None
    try:
        dims = [np.zeros(1, np.int64) for _ in range(8)]
        lib.realign_prep_dims(
            ct.c_void_p(h), *[d.ctypes.data_as(_i64p) for d in dims]
        )
        (n_reads, cigar_bytes, md_bytes, n_cons, cons_bytes, ref_bytes,
         err, err_row) = (int(d[0]) for d in dims)
        if err:
            if err == 2:
                raise IndexError(
                    f"realign prep: CIGAR overruns read at row {err_row}"
                )
            raise ValueError(
                f"realign prep: malformed MD/alignment at row {err_row}"
            )
        out = {
            "t_status": np.zeros(G, np.int32),
            "t_ref_buf": np.zeros(max(ref_bytes, 1), np.uint8),
            "t_ref_off": np.zeros(G + 1, np.int64),
            "t_ref_start": np.zeros(G, np.int64),
            "t_ref_end": np.zeros(G, np.int64),
            "r_group": np.zeros(n_reads, np.int32),
            "r_row": np.zeros(n_reads, np.int64),
            "r_cigar_buf": np.zeros(max(cigar_bytes, 1), np.uint8),
            "r_cigar_off": np.zeros(n_reads + 1, np.int64),
            "r_md_buf": np.zeros(max(md_bytes, 1), np.uint8),
            "r_md_off": np.zeros(n_reads + 1, np.int64),
            "r_md_set": np.zeros(n_reads, np.uint8),
            "r_dirty": np.zeros(n_reads, np.uint8),
            "r_pure": np.zeros(n_reads, np.uint8),
            "r_orig_qual": np.zeros(n_reads, np.int64),
            "c_group": np.zeros(n_cons, np.int32),
            "c_seq_buf": np.zeros(max(cons_bytes, 1), np.uint8),
            "c_seq_off": np.zeros(n_cons + 1, np.int64),
            "c_is": np.zeros(n_cons, np.int64),
            "c_ie": np.zeros(n_cons, np.int64),
        }
        lib.realign_prep_fill(
            ct.c_void_p(h),
            out["t_status"].ctypes.data_as(_i32p),
            _u8_ptr(out["t_ref_buf"]),
            out["t_ref_off"].ctypes.data_as(_i64p),
            out["t_ref_start"].ctypes.data_as(_i64p),
            out["t_ref_end"].ctypes.data_as(_i64p),
            out["r_group"].ctypes.data_as(_i32p),
            out["r_row"].ctypes.data_as(_i64p),
            _u8_ptr(out["r_cigar_buf"]),
            out["r_cigar_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["r_md_buf"]),
            out["r_md_off"].ctypes.data_as(_i64p),
            _u8_ptr(out["r_md_set"]),
            _u8_ptr(out["r_dirty"]),
            _u8_ptr(out["r_pure"]),
            out["r_orig_qual"].ctypes.data_as(_i64p),
            out["c_group"].ctypes.data_as(_i32p),
            _u8_ptr(out["c_seq_buf"]),
            out["c_seq_off"].ctypes.data_as(_i64p),
            out["c_is"].ctypes.data_as(_i64p),
            out["c_ie"].ctypes.data_as(_i64p),
        )
        return out
    finally:
        lib.realign_prep_free(ct.c_void_p(h))


def md_move_batch(b, rows, ref_buf, ref_off, tloc, offs,
                  head_len, mid_len, mid_op, end_len, new_start):
    """Batched MdTag.move_alignment + canonical to_string for realigned
    reads.  Returns (md_buf u8, md_off i64) or None when unavailable."""
    lib = _lib()
    if lib is None:
        return None
    bases = np.ascontiguousarray(b.bases, np.uint8)
    N, L = bases.shape
    lengths = np.ascontiguousarray(b.lengths, np.int32)
    rows = np.ascontiguousarray(rows, np.int64)
    K = len(rows)
    ref_buf = np.ascontiguousarray(ref_buf, np.uint8)
    ref_off = np.ascontiguousarray(ref_off, np.int64)
    tloc = np.ascontiguousarray(tloc, np.int32)
    offs = np.ascontiguousarray(offs, np.int64)
    head_len = np.ascontiguousarray(head_len, np.int32)
    mid_len = np.ascontiguousarray(mid_len, np.int32)
    mid_op = np.ascontiguousarray(mid_op, np.uint8)
    end_len = np.ascontiguousarray(end_len, np.int32)
    new_start = np.ascontiguousarray(new_start, np.int64)
    # MD length bound: digits+bases over the span plus deletion bases
    cap = int(K * (L + 64) + int(mid_len.sum()) + 64)
    err = np.zeros(1, np.int64)
    err_row = np.zeros(1, np.int64)
    for _ in range(2):
        out = np.zeros(max(cap, 1), np.uint8)
        out_off = np.zeros(K + 1, np.int64)
        got = lib.md_move_batch(
            _u8_ptr(bases), ct.c_int64(N), ct.c_int64(L),
            lengths.ctypes.data_as(_i32p),
            rows.ctypes.data_as(_i64p), ct.c_int64(K),
            _u8_ptr(ref_buf), ref_off.ctypes.data_as(_i64p),
            tloc.ctypes.data_as(_i32p), offs.ctypes.data_as(_i64p),
            head_len.ctypes.data_as(_i32p), mid_len.ctypes.data_as(_i32p),
            _u8_ptr(mid_op), end_len.ctypes.data_as(_i32p),
            new_start.ctypes.data_as(_i64p),
            _u8_ptr(out), ct.c_int64(cap), out_off.ctypes.data_as(_i64p),
            err.ctypes.data_as(_i64p), err_row.ctypes.data_as(_i64p),
        )
        if int(err[0]):
            if int(err[0]) == 2:
                raise IndexError(
                    f"md_move_batch: alignment overrun at row {int(err_row[0])}"
                )
            raise ValueError(
                f"md_move_batch: bad alignment at row {int(err_row[0])}"
            )
        if got >= 0:
            return out[:got], out_off
        cap = -got
    return None


def span_gather_strided(src: np.ndarray, starts: np.ndarray,
                        lens: np.ndarray, w: int):
    """Gather byte spans into a zero-padded [n, w] matrix (row-strided);
    None if native unavailable.  StringColumn.to_fixed_bytes hot path."""
    lib = _lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, np.uint8)
    starts = np.ascontiguousarray(starts, np.int64)
    lens = np.ascontiguousarray(lens, np.int64)
    n = len(starts)
    if not _spans_in_bounds(starts, lens, src.size) or (
        n and int(lens.max()) > w
    ):
        return None  # corrupt offsets: preserve the numpy fail-safe
    out = np.zeros((n, int(w)), np.uint8)
    lib.span_gather_strided(
        _u8_ptr(src), starts.ctypes.data_as(_i64p),
        lens.ctypes.data_as(_i64p), ct.c_int64(n), ct.c_int64(int(w)),
        _u8_ptr(out),
    )
    return out
