"""ADAM ACTIONS command group (ADAMMain.scala:32-48).

depth, count_kmers, count_contig_kmers, transform, adam2fastq, plugin,
flatten — each docstring cites the reference command it mirrors.
"""

from __future__ import annotations

import sys

import numpy as np

from adam_tpu.cli.main import Command
from adam_tpu.utils import instrumentation as ins


def _write_kmer_counts(counts: dict, output: str, print_histogram: bool):
    """Shared '(kmer, count)' text output + optional count histogram
    (the saveAsTextFile tail of CountReadKmers/CountContigKmers).
    k-mer counts stay ints; q-mer weights stay floats."""
    if print_histogram:
        hist: dict[int, int] = {}
        for v in counts.values():
            hist[int(v)] = hist.get(int(v), 0) + 1
        for k in sorted(hist):
            print((k, hist[k]))
    with open(output, "w") as fh:
        for kmer, v in counts.items():
            fh.write(f"{kmer}, {v}\n")


class CalculateDepth(Command):
    """Read depth at each variant of a VCF via broadcast region join
    (adam-cli CalculateDepth.scala:41-120)."""

    name = "depth"
    description = "Calculate the depth from a given ADAM file, at each variant in a VCF"

    @classmethod
    def configure(cls, p):
        p.add_argument("adam", metavar="ADAM",
                       help="The read file to use to calculate depths")
        p.add_argument("vcf", metavar="VCF",
                       help="The VCF containing the sites at which to calculate depths")
        p.add_argument("-cartesian", action="store_true",
                       help="use a cartesian join, then filter")
        p.add_argument("-stream", action="store_true",
                       help="out-of-core: stream the reads through a "
                            "genome-bin shard spill and join one bin at "
                            "a time (bounded memory on WGS-scale input)")
        p.add_argument("-bin_size", type=int, default=1_000_000,
                       help="genome bin width for -stream (default 1Mbp)")

    @classmethod
    def run(cls, args):
        from adam_tpu.api.datasets import AlignmentDataset, GenotypeDataset
        from adam_tpu.io import context
        from adam_tpu.pipelines.region_join import (
            IntervalArrays,
            broadcast_region_join,
        )

        proj = None
        if str(args.adam).endswith((".adam", ".parquet")):
            # depth only joins on coordinates: push the projection down
            # so payload columns (sequence/qual/attrs) are never read
            proj = ["contig", "start", "end", "flags"]
        if args.stream:
            # out-of-core path (VERDICT r4 missing #1): header first for
            # the dictionary, then windows through the bin spill
            header = context.load_header(args.adam)
            gt = GenotypeDataset.load(
                args.vcf, contig_names=header.seq_dict.names
            )
            sites = IntervalArrays.of(
                gt.variants.contig_idx,
                gt.variants.start,
                gt.variants.start + 1,
            )
            from adam_tpu.parallel.sharded_join import streamed_depth

            depth = streamed_depth(
                context.iter_alignment_batches(args.adam, projection=proj),
                sites, header.seq_dict, bin_size=args.bin_size,
            )
        else:
            kw = {"projection": proj} if proj else {}
            ds = AlignmentDataset.load(args.adam, **kw)
            b = ds.batch.to_numpy()
            mapped = np.flatnonzero(
                np.asarray(b.is_mapped) & np.asarray(b.valid)
            )
            reads = IntervalArrays.of(
                b.contig_idx[mapped], b.start[mapped], b.end[mapped]
            )
            gt = GenotypeDataset.load(
                args.vcf, contig_names=ds.seq_dict.names
            )
            sites = IntervalArrays.of(
                gt.variants.contig_idx,
                gt.variants.start,
                gt.variants.start + 1,  # variant *position*, as the
                # reference keys it
            )
            si, _ri = broadcast_region_join(sites, reads)
            depth = np.bincount(si, minlength=len(sites))
        names = gt.variants.sidecar.names
        # gt.contig_names is the extended space: it includes VCF-only
        # contigs appended past the read dictionary
        contig_names = gt.contig_names
        print("location\tname\tdepth")
        order = np.lexsort((gt.variants.start, gt.variants.contig_idx))
        for i in order:
            loc = "%s:%d" % (
                contig_names[gt.variants.contig_idx[i]],
                int(gt.variants.start[i]),
            )
            print("%20s\t%15s\t% 5d" % (loc, names[i] or ".", int(depth[i])))
        return 0


class CountReadKmers(Command):
    """k-mers/q-mers from a read dataset (CountReadKmers.scala:30-100)."""

    name = "count_kmers"
    description = "Counts the k-mers/q-mers from a read dataset."

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT")
        p.add_argument("output", metavar="OUTPUT",
                       help="Location for storing k-mer counts")
        p.add_argument("kmer_length", metavar="KMER_LENGTH", type=int)
        p.add_argument("-countQmers", action="store_true",
                       help="counts q-mers instead of k-mers")
        p.add_argument("-printHistogram", action="store_true",
                       help="prints a histogram of counts")
        p.add_argument("-repartition", type=int, default=-1,
                       help="accepted for parity; batches need no repartition")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import context

        with ins.TIMERS.time(ins.LOAD_ALIGNMENTS):
            kw = {}
            if str(args.input).endswith((".adam", ".parquet")):
                kw["projection"] = ["sequence", "qual"]
            ds = context.load_alignments(args.input, **kw)
        with ins.TIMERS.time(ins.COUNT_KMERS):
            if args.countQmers:
                counts = ds.count_qmers(args.kmer_length)
            else:
                counts = ds.count_kmers(args.kmer_length)
        _write_kmer_counts(counts, args.output, args.printHistogram)
        return 0


class CountContigKmers(Command):
    """k-mers over reference contigs (CountContigKmers.scala:29-90)."""

    name = "count_contig_kmers"
    description = "Counts the k-mers/q-mers from a contig dataset."

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT",
                       help="The ADAM or FASTA file to count kmers from")
        p.add_argument("output", metavar="OUTPUT")
        p.add_argument("kmer_length", metavar="KMER_LENGTH", type=int)
        p.add_argument("-printHistogram", action="store_true")

    @classmethod
    def run(cls, args):
        from adam_tpu.formats.fragments import count_contig_kmers
        from adam_tpu.io import context, parquet

        if str(args.input).endswith((".fa", ".fasta", ".fa.gz", ".fasta.gz")):
            fragments, _sd, _desc = context.load_fasta(args.input)
        else:
            fragments, _sd, _desc = parquet.load_fragments(args.input)
        with ins.TIMERS.time(ins.COUNT_KMERS):
            counts = count_contig_kmers(fragments, args.kmer_length)
        _write_kmer_counts(counts, args.output, args.printHistogram)
        return 0


class Transform(Command):
    """THE pipeline — flag-composed read preprocessing
    (Transform.scala:101-179; same stage order)."""

    name = "transform"
    description = ("Convert SAM/BAM to ADAM format and optionally perform "
                   "read pre-processing transformations")

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT",
                       help="The ADAM, BAM or SAM file to apply the transforms to")
        p.add_argument("output", metavar="OUTPUT",
                       help="Location to write the transformed data")
        p.add_argument("-sort_reads", action="store_true")
        p.add_argument("-mark_duplicate_reads", action="store_true")
        p.add_argument("-recalibrate_base_qualities", action="store_true")
        p.add_argument("-dump_observations", default=None,
                       help="local path to dump BQSR observations to (CSV)")
        p.add_argument("-known_snps", default=None,
                       help="sites-only VCF giving location of known SNPs")
        p.add_argument(
            "-known_recalibration_table", default=None,
            help="npz with 'table' (u8[n_rg, qual, cycle, dinuc]) and "
            "'gl' — apply this pre-solved recalibration table instead "
            "of solving one at barrier 2 (the known-sites workflow; a "
            "previous run's --run-dir table sidecar is directly "
            "reusable).  Arms the fused B→C megakernel tier "
            "(docs/PERF.md); -streaming only",
        )
        p.add_argument("-realign_indels", action="store_true")
        p.add_argument("-known_indels", default=None,
                       help="VCF of known INDELs; without it the consensus-from-reads model is used")
        p.add_argument("-max_indel_size", type=int, default=500)
        p.add_argument("-max_consensus_number", type=int, default=30)
        p.add_argument("-log_odds_threshold", type=float, default=5.0)
        p.add_argument("-max_target_size", type=int, default=3000)
        p.add_argument("-trimReads", action="store_true")
        p.add_argument("-trimFromStart", type=int, default=0)
        p.add_argument("-trimFromEnd", type=int, default=0)
        p.add_argument("-trimReadGroup", default=None)
        p.add_argument("-qualityBasedTrim", action="store_true")
        p.add_argument("-qualityThreshold", type=int, default=20)
        p.add_argument("-trimBeforeBQSR", action="store_true")
        p.add_argument(
            "-repartition", type=int, default=-1,
            help="no-op: columnar batches have no RDD partition count; "
            "sharding is chosen by the device mesh (logged when set)",
        )
        p.add_argument(
            "-coalesce", type=int, default=-1,
            help="no-op: columnar batches have no RDD partition count; "
            "sharding is chosen by the device mesh (logged when set)",
        )
        p.add_argument("-sort_fastq_output", action="store_true")
        p.add_argument(
            "-checkpoint_dir", default=None,
            help="materialize each completed stage to Parquet here and "
            "resume after the deepest completed stage on rerun (the "
            "framework's failure-recovery story: stage checkpoint-restart "
            "over re-shardable columnar stores)",
        )
        p.add_argument(
            "--report", dest="report", default=None, metavar="PATH",
            help="write the analyzer run report (per-device busy/idle "
            "attribution, barrier decomposition, critical path, latency "
            "quantiles — the 'adam-tpu analyze' view of this run) to "
            "PATH on completion; -streaming only",
        )
        p.add_argument(
            "-streaming", action="store_true",
            help="run the transform as the streamed, overlapped windowed "
            "pipeline (ingest || device kernels || part-file writes; "
            "pipelines/streamed.py) — output becomes a Parquet part-file "
            "directory; requires a markdup/BQSR/realign stage set",
        )
        p.add_argument(
            "-window_reads", type=int, default=262_144,
            help="ingest window size in reads for -streaming — the unit "
            "of overlap, device round-robin and durable resume",
        )
        p.add_argument(
            "--run-dir", dest="run_dir", default=None, metavar="DIR",
            help="durable window-granular resume journal for the "
            "-streaming pipeline (docs/ROBUSTNESS.md): records each "
            "output window as complete after its part's atomic+fsync'd "
            "publish and persists observe-histogram/recalibration-table "
            "sidecars, so a killed run can resume instead of restarting",
        )
        p.add_argument(
            "--resume", dest="resume", action="store_true",
            help="resume a killed -streaming run from --run-dir's "
            "journal: completed windows are skipped, output stays "
            "bit-identical to an uninterrupted run; a journal recorded "
            "for different input bytes, flags or window plan is refused "
            "with a clean restart (never mixed output)",
        )
        p.add_argument(
            "-shards", type=int, default=0,
            help="run as the composed out-of-core sharded pipeline over N "
            "genome-bin shards (parallel/sharded.py): windowed ingest "
            "shuffles to 5'-clipped-position bins, per-shard passes with "
            "global duplicate/target barriers, boundary-correct realign "
            "tail — the one-host embodiment of the multi-host execution "
            "shape; supports the markdup/BQSR/realign stage set on "
            "SAM/BAM input",
        )
        p.add_argument(
            "-backend", default="tpu", choices=["tpu", "spark"],
            help="execution backend: 'tpu' runs the pipeline here; "
            "'spark' is the embedding mode — the caller (a Spark "
            "mapPartitions closure) ships Arrow record batches through "
            "AlignmentDataset.from_arrow/to_arrow and this process acts "
            "as the per-partition kernel executor",
        )
        p.add_argument("-force_load_bam", action="store_true")
        p.add_argument("-force_load_fastq", action="store_true")
        p.add_argument("-force_load_ifastq", action="store_true")
        p.add_argument("-force_load_parquet", action="store_true")

    @classmethod
    def run(cls, args):
        from adam_tpu.api.datasets import GenotypeDataset
        from adam_tpu.io import context

        if args.backend == "spark":
            # embedding mode: this process is the per-partition executor —
            # the Spark driver pipes Arrow IPC partition batches through
            # stdin/stdout (AlignmentDataset.from_arrow -> stages ->
            # to_arrow); file paths are ignored (pass "-" "-")
            from adam_tpu.api.datasets import GenotypeDataset as _GD
            from adam_tpu.api.spark_executor import StageConfig, serve

            cfg = StageConfig(
                mark_duplicates=bool(args.mark_duplicate_reads),
                recalibrate=bool(args.recalibrate_base_qualities),
                realign=bool(args.realign_indels),
            )
            if args.known_snps:
                cfg.known_snps = _GD.load(args.known_snps).snp_table()
            if args.known_indels:
                cfg.known_indels = _GD.load(args.known_indels).indel_table()
            import logging

            served = serve(cfg)
            logging.getLogger(__name__).info(
                "spark executor drained: %d partitions", served
            )
            return 0

        # the observability sinks only the -streaming pipeline produces:
        # warn up front (covers -shards AND the plain path) instead of
        # exiting 0 with a silently missing artifact — main() already
        # enabled recording for --report, so the mistake costs real time
        if getattr(args, "report", None) and not args.streaming:
            print(
                "transform: --report is only produced by the -streaming "
                f"pipeline; {args.report} will not be written (use "
                "--metrics-json/--trace-out + 'adam-tpu analyze' for "
                "other modes)",
                file=sys.stderr,
            )
        if getattr(args, "progress", None) and not args.streaming:
            print(
                "transform: --progress heartbeat is emitted by the "
                "-streaming pipeline only; no lines will be written",
                file=sys.stderr,
            )
        if getattr(args, "resume", None) and not getattr(args, "run_dir",
                                                         None):
            print(
                "transform: --resume needs the journal directory; pass "
                "--run-dir DIR (the same DIR the killed run journaled "
                "into)",
                file=sys.stderr,
            )
            return 2
        if getattr(args, "run_dir", None) and not args.streaming:
            print(
                "transform: --run-dir/--resume journal the -streaming "
                "pipeline only; use -checkpoint_dir for the composed "
                "stage pipeline",
                file=sys.stderr,
            )
            return 2
        if args.shards and args.shards < 0:
            print(f"transform -shards must be positive (got {args.shards})",
                  file=sys.stderr)
            return 2
        if args.window_reads < 1:
            print(
                f"transform -window_reads must be positive (got "
                f"{args.window_reads})", file=sys.stderr,
            )
            return 2
        if args.shards and args.streaming:
            print(
                "transform -shards and -streaming are mutually exclusive "
                "execution modes; pass one or the other",
                file=sys.stderr,
            )
            return 2
        if args.shards or args.streaming:
            # windowed execution modes share validation and knowns/tuning
            # plumbing: -shards N routes through the composed sharded
            # pipeline, -streaming through the overlapped windowed one
            mode = "-shards" if args.shards else "-streaming"
            ok_stages = not (
                args.trimReads or args.qualityBasedTrim or args.sort_reads
            )
            base = str(args.input)
            if base.endswith(".gz"):
                base = base[:-3]
            if (
                not ok_stages
                or not base.endswith((".sam", ".bam"))
                or args.force_load_fastq
                or args.force_load_ifastq
                or args.force_load_parquet
            ):
                print(
                    f"transform {mode} supports the markdup/BQSR/realign "
                    "stage set on windowed SAM/BAM input; drop it for "
                    "trim/sort pipelines or other formats",
                    file=sys.stderr,
                )
                return 2
            from adam_tpu.api.datasets import GenotypeDataset as _GD

            known = indels = None
            contig_names = None
            if args.known_snps or args.known_indels:
                contig_names = context.load_header(args.input).seq_dict.names
            if args.known_snps:
                known = _GD.load(
                    args.known_snps, contig_names=contig_names
                ).snp_table()
            if args.known_indels:
                indels = _GD.load(
                    args.known_indels, contig_names=contig_names
                ).indel_table()
            kw = dict(
                mark_duplicates=bool(args.mark_duplicate_reads),
                recalibrate=bool(args.recalibrate_base_qualities),
                realign=bool(args.realign_indels),
                known_snps=known,
                known_indels=indels,
                compression=args.parquet_compression_codec,
                max_indel_size=args.max_indel_size,
                max_consensus_number=args.max_consensus_number,
                lod_threshold=args.log_odds_threshold,
                max_target_size=args.max_target_size,
                dump_observations=args.dump_observations,
            )
            if mode == "-shards":
                from adam_tpu.parallel.sharded import transform_sharded

                transform_sharded(
                    args.input, args.output, args.shards, **kw
                )
            else:
                from adam_tpu.pipelines.streamed import transform_streamed

                if getattr(args, "report", None):
                    # pre-flight the report path BEFORE the (potentially
                    # hours-long) run: a typo'd directory must fail in
                    # milliseconds, not after the pipeline finishes
                    try:
                        with open(args.report, "a"):
                            pass
                    except OSError as e:
                        print(f"transform: cannot write --report "
                              f"{args.report}: {e}", file=sys.stderr)
                        return 2
                known_tbl = None
                if getattr(args, "known_recalibration_table", None):
                    import numpy as _np

                    with _np.load(args.known_recalibration_table) as z:
                        known_tbl = (
                            _np.asarray(z["table"], _np.uint8),
                            int(z["gl"]),
                        )
                transform_streamed(
                    args.input, args.output,
                    window_reads=args.window_reads,
                    devices=getattr(args, "devices", None),
                    partitioner=getattr(args, "partitioner", None),
                    progress=getattr(args, "progress", None),
                    run_dir=getattr(args, "run_dir", None),
                    resume=bool(getattr(args, "resume", False)),
                    known_table=known_tbl, **kw,
                )
                if getattr(args, "report", None):
                    # the analyzer view of THIS run: trace-grade (gap
                    # analysis + critical path) — main() enabled
                    # recording because --report was passed, so the
                    # global TRACE holds the absorbed run events
                    from adam_tpu.utils import analyzer
                    from adam_tpu.utils import telemetry as tele

                    report = analyzer.analyze(tele.TRACE.to_chrome_trace())
                    try:
                        with open(args.report, "w") as fh:
                            fh.write(analyzer.render_report(report) + "\n")
                    except OSError as e:
                        # the dataset is already written and valid: a
                        # report-write failure (disk filled mid-run)
                        # must not turn success into a crash
                        print(f"transform: report write to "
                              f"{args.report} failed: {e}",
                              file=sys.stderr)
            return 0

        with ins.TIMERS.time(ins.LOAD_ALIGNMENTS):
            if args.force_load_bam:
                ds = context.load_bam(args.input)
            elif args.force_load_fastq:
                ds = context.load_fastq(args.input)
            elif args.force_load_ifastq:
                ds = context.load_interleaved_fastq(
                    args.input, stringency=args.stringency
                )
            elif args.force_load_parquet:
                ds = context.load_parquet_alignments(args.input)
            else:
                ds = context.load_alignments(
                    args.input, stringency=args.stringency
                )

        if args.repartition != -1 or args.coalesce != -1:
            import logging

            logging.getLogger(__name__).warning(
                "-repartition/-coalesce are no-ops here: columnar batches "
                "have no RDD partition count (sharding follows the device "
                "mesh)"
            )

        stages = []

        if args.trimReads:
            def _trim(ds):
                with ins.TIMERS.time(ins.TRIM_READS):
                    rg_idx = None
                    if args.trimReadGroup is not None:
                        rg_idx = ds.header.read_groups.names.index(
                            args.trimReadGroup
                        )
                    from adam_tpu.pipelines import trim

                    return trim.trim_reads(
                        ds, args.trimFromStart, args.trimFromEnd, rg_idx=rg_idx
                    )
            stages.append(("trim", _trim))

        if args.qualityBasedTrim and args.trimBeforeBQSR:
            stages.append((
                "quality_trim",
                lambda ds: ds.trim_low_quality_read_groups(
                    args.qualityThreshold
                ),
            ))

        if args.mark_duplicate_reads:
            def _markdup(ds):
                with ins.TIMERS.time(ins.MARK_DUPLICATES):
                    return ds.mark_duplicates()
            stages.append(("mark_duplicates", _markdup))

        if args.realign_indels:
            def _realign(ds):
                with ins.TIMERS.time(ins.REALIGN_INDELS):
                    kw = dict(
                        max_indel_size=args.max_indel_size,
                        max_consensus_number=args.max_consensus_number,
                        lod_threshold=args.log_odds_threshold,
                        max_target_size=args.max_target_size,
                    )
                    if args.known_indels:
                        gt = GenotypeDataset.load(
                            args.known_indels, contig_names=ds.seq_dict.names
                        )
                        return ds.realign_indels(
                            consensus_model="knowns",
                            known_indels=gt.indel_table(), **kw,
                        )
                    return ds.realign_indels(consensus_model="reads", **kw)
            stages.append(("realign_indels", _realign))

        if args.recalibrate_base_qualities:
            def _bqsr(ds):
                with ins.TIMERS.time(ins.BQSR):
                    known = None
                    if args.known_snps:
                        gt = GenotypeDataset.load(
                            args.known_snps, contig_names=ds.seq_dict.names
                        )
                        known = gt.snp_table()
                    return ds.recalibrate_base_qualities(
                        known_snps=known,
                        dump_observation_table=args.dump_observations,
                    )
            stages.append(("bqsr", _bqsr))

        if args.qualityBasedTrim and not args.trimBeforeBQSR:
            stages.append((
                "quality_trim",
                lambda ds: ds.trim_low_quality_read_groups(
                    args.qualityThreshold
                ),
            ))

        if args.sort_reads:
            def _sort(ds):
                with ins.TIMERS.time(ins.SORT_READS):
                    return ds.sort_by_reference_position()
            stages.append(("sort", _sort))

        from adam_tpu.pipelines.checkpoint import (
            compose_fingerprint,
            input_fingerprint,
            run_stages,
        )

        fp = None
        if args.checkpoint_dir:
            # input content identity + every stage-affecting flag value:
            # a rerun over different bytes (or retuned knobs) must
            # invalidate the stage stores instead of silently reloading
            # them (the stage list alone only catches REORDERED flags)
            fp = compose_fingerprint({
                "input": input_fingerprint(args.input),
                "trimFromStart": args.trimFromStart,
                "trimFromEnd": args.trimFromEnd,
                "trimReadGroup": args.trimReadGroup,
                "qualityThreshold": args.qualityThreshold,
                # known-sites files fingerprint by CONTENT, not path:
                # editing sites in place must invalidate the stores
                "known_snps": (
                    input_fingerprint(args.known_snps)
                    if args.known_snps else None
                ),
                "known_indels": (
                    input_fingerprint(args.known_indels)
                    if args.known_indels else None
                ),
                "max_indel_size": args.max_indel_size,
                "max_consensus_number": args.max_consensus_number,
                "log_odds_threshold": args.log_odds_threshold,
                "max_target_size": args.max_target_size,
            })
        ds = run_stages(ds, stages, checkpoint_dir=args.checkpoint_dir,
                        fingerprint=fp)

        with ins.TIMERS.time(ins.SAVE_OUTPUT):
            if args.sort_fastq_output and str(args.output).endswith(
                (".fq", ".fastq")
            ):
                # adamSaveAsFastq(sort=true): name-sorted FASTQ export
                import numpy as np

                from adam_tpu.formats.strings import StringColumn

                names = StringColumn.of(ds.sidecar.names).to_fixed_bytes()
                order = np.argsort(names, kind="stable")
                ds = ds.take_rows(order)
            ds.save(args.output, compression=args.parquet_compression_codec)
        return 0


class Serve(Command):
    """Multi-job transform service (adam_tpu/serve; docs/ROBUSTNESS.md
    "Fault-isolated multi-job scheduling"): run N concurrent streamed
    transform jobs on one shared device pool with admission control,
    per-tenant weighted fairness, job quarantine, graceful SIGTERM
    drain and whole-process crash recovery from the run-root."""

    name = "serve"
    description = ("Run concurrent streamed transform jobs on a shared "
                   "device pool (bounded slots, per-tenant fairness, "
                   "quarantine, graceful drain, crash recovery)")

    @classmethod
    def configure(cls, p):
        p.add_argument(
            "run_root", metavar="RUN_ROOT",
            help="durable service state root: one subdirectory per job "
            "(JOB.json + run/ journal + heartbeat.ndjson); on startup "
            "every incomplete job found here resumes bit-identically "
            "from its journal",
        )
        p.add_argument(
            "--jobs", dest="jobs", default=None, metavar="FILE",
            help="JSON manifest of jobs to submit (see "
            "adam_tpu/api/transform_service.py for the format); jobs "
            "already tracked in RUN_ROOT are skipped, so re-running "
            "the same command after a crash only resumes",
        )
        p.add_argument(
            "--max-jobs", dest="max_jobs", type=int, default=2,
            metavar="N",
            help="bounded job slots: submissions beyond N receive a "
            "typed Busy rejection (the CLI's own manifest loop polls "
            "until a slot frees; default 2)",
        )
        p.add_argument(
            "--job-retries", dest="job_retries", type=int, default=None,
            metavar="N",
            help="resume a failing job from its journal N times before "
            "quarantining it (default ADAM_TPU_SCHED_JOB_RETRIES or 1; "
            "quarantine frees the job's slot and devices, its journal "
            "stays resumable, surviving jobs are untouched)",
        )
        p.add_argument(
            "--batch", dest="batch", action="store_true", default=None,
            help="continuous cross-job window batching "
            "(serve/batching.py, docs/SERVING.md): concurrent jobs' "
            "windows merge into one fused device dispatch per pass, "
            "WFQ-ordered, with a bounded coalescing delay "
            "(ADAM_TPU_BATCH_WAIT_MS, default 25 ms); every job's "
            "output stays byte-identical to its solo run.  Default: "
            "ADAM_TPU_BATCH, off",
        )
        p.add_argument(
            "--quota", dest="quota", default=None, metavar="SPEC",
            help="per-tenant rolling-window budgets, e.g. "
            "'tenantA:bytes=512M,compute=10s;*:bytes=1G' (window "
            "ADAM_TPU_QUOTA_WINDOW_S, default 60 s): an over-budget "
            "tenant's submissions are refused with a typed quota "
            "rejection (HTTP 429 on the gateway) carrying a "
            "budget-derived Retry-After; other tenants are untouched.  "
            "Default: ADAM_TPU_QUOTA, none",
        )
        p.add_argument(
            "--slo", dest="slo", default=None, metavar="SPEC",
            help="declarative service-level objectives, e.g. "
            "'tenantA:p99(sched.job.run)<30s;*:avail>=0.99' "
            "(utils/slo.py, docs/OBSERVABILITY.md): per-tenant or "
            "service-wide (*) latency/availability/throughput "
            "objectives judged over rolling windows "
            "(ADAM_TPU_SLO_WINDOW_S, default 300 s short / 12x long); "
            "error-budget state persists in RUN_ROOT/SLO_BUDGET.json, "
            "a corroborated fast burn fires an slo.burn incident "
            "bundle, and GET /slo + /metrics expose compliance and "
            "burn.  Default: ADAM_TPU_SLO, none",
        )
        p.add_argument(
            "--listen", dest="listen", default=None, metavar="HOST:PORT",
            help="serve the HTTP gateway on HOST:PORT (port 0 = OS-"
            "assigned; the bound address publishes durably to "
            "RUN_ROOT/gateway.json): idempotency-keyed PUT submission, "
            "typed 429/503 back-pressure with Retry-After, NDJSON "
            "heartbeat streaming, Range-resumable part fetch "
            "(docs/SERVING.md).  The process then runs until SIGTERM, "
            "which drains gracefully: stop accepting -> 503 -> "
            "scheduler drain -> every journal settled -> exit 0",
        )

    @classmethod
    def run(cls, args):
        import signal
        import threading
        import time as time_mod
        from collections import deque

        from adam_tpu.api.transform_service import (
            TransformService,
            load_jobs_manifest,
        )
        from adam_tpu.serve.job import Admitted

        specs = []
        if args.jobs:
            try:
                specs = load_jobs_manifest(args.jobs)
            except (OSError, ValueError) as e:
                print(f"serve: {e}", file=sys.stderr)
                return 2
        listen = None
        if args.listen:
            from adam_tpu.gateway.protocol import parse_listen

            try:
                listen = parse_listen(args.listen)
            except ValueError as e:
                print(f"serve: {e}", file=sys.stderr)
                return 2
        svc = TransformService(
            args.run_root,
            max_jobs=args.max_jobs,
            devices=getattr(args, "devices", None),
            partitioner=getattr(args, "partitioner", None),
            job_retries=args.job_retries,
            batching=args.batch,
            quota=args.quota,
            slo=args.slo,
        )
        gw = None
        if listen is not None:
            from adam_tpu.gateway.server import GatewayServer

            gw = GatewayServer(svc, *listen)
            gw.start()
            print(f"serve: gateway listening on {gw.url} "
                  f"(discovery: {args.run_root}/gateway.json)")
        # SIGTERM/SIGINT = graceful drain: the handler only flips an
        # event (signal-safe); the submission loop below performs the
        # actual drain — admissions stop, every job finishes its
        # in-flight windows, fsyncs its journal, and we exit 0
        drain_req = threading.Event()
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(
                    sig, lambda _s, _f: drain_req.set()
                )
            except (ValueError, OSError):  # non-main thread / platform
                pass
        drained = False
        try:
            recovered = svc.recover()
            if recovered:
                print(f"serve: resumed {len(recovered)} incomplete "
                      f"job(s) from {args.run_root}: "
                      f"{', '.join(recovered)}")
            tracked = set(svc.status()["jobs"])
            pending = deque(s for s in specs if s.job_id not in tracked)
            skipped = len(specs) - len(pending)
            if skipped:
                print(f"serve: {skipped} manifest job(s) already "
                      "tracked in the run root; not resubmitting")
            while True:
                if drain_req.is_set() and not drained:
                    # drain ordering (docs/SERVING.md): the gateway
                    # stops accepting FIRST (new submissions bounce
                    # with a typed 503 while live event streams and
                    # part fetches keep flowing), then the scheduler
                    # drains every lane to a window boundary
                    if gw is not None:
                        gw.stop_accepting()
                    svc.request_drain()
                    drained = True
                    pending.clear()
                # has_capacity gates the poll so waiting for a slot
                # doesn't count one sched.jobs.rejected per tick
                if pending and svc.scheduler.has_capacity():
                    got = svc.submit(pending[0])
                    if isinstance(got, Admitted):
                        print(f"serve: admitted {got.job_id}")
                        pending.popleft()
                        continue
                    if got.kind != "capacity":
                        print(f"serve: {pending[0].job_id} refused "
                              f"({got.reason})", file=sys.stderr)
                        pending.popleft()
                        continue
                    # lost a capacity race: poll for a freed slot below
                if not pending and svc.wait(timeout=0.25):
                    # a gateway keeps the service alive for remote
                    # submissions until a drain is requested — idle is
                    # the steady state, not the exit condition, and it
                    # must BLOCK (on the drain signal, for a prompt
                    # SIGTERM response), not spin through instant
                    # wait() returns
                    if gw is None or drained:
                        break
                    drain_req.wait(timeout=0.25)
                if pending:
                    time_mod.sleep(0.1)
        finally:
            for sig, h in prev_handlers.items():
                try:
                    signal.signal(sig, h)
                except (ValueError, OSError):
                    pass
            # settled before the listener dies: close() ends event
            # streams only after every JOB.json above is durable
            if gw is not None:
                gw.close()
            svc.close()
        status = svc.status()
        bad = 0
        for jid, view in sorted(status["jobs"].items()):
            line = f"serve: job {jid}: {view['state']}"
            if view.get("windows_durable"):
                # parts, not windows: the realign tail part rides past
                # the window plan, so the count can exceed n_windows
                line += f" ({view['windows_durable']} durable part(s))"
            if view.get("error"):
                line += f" — {view['error']}"
            print(line)
            if view["state"] == "quarantined":
                bad += 1
        if drained:
            print("serve: drained cleanly (journals durable; rerun "
                  "this command to resume)")
            return 0
        return 1 if bad else 0


class Adam2Fastq(Command):
    """Export reads to FASTQ, optionally splitting pairs
    (Adam2Fastq.scala:25-80)."""

    name = "adam2fastq"
    description = "Convert BAM to FASTQ files"

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT")
        p.add_argument("output", metavar="OUTPUT")
        p.add_argument("output2", metavar="OUTPUT2", nargs="?", default=None,
                       help="all second-in-pair reads go here, if provided")
        p.add_argument("-no-projection", dest="no_projection",
                       action="store_true")
        p.add_argument("-repartition", type=int, default=-1)

    @classmethod
    def run(cls, args):
        from adam_tpu.io import context

        kw = {}
        if not args.no_projection and str(args.input).endswith(
            (".adam", ".parquet")
        ):
            kw["projection"] = ["readName", "sequence", "qual", "flags"]
        ds = context.load_alignments(args.input, **kw)
        if args.output2:
            ds.save_paired_fastq(
                args.output, args.output2, stringency=args.stringency
            )
        else:
            from adam_tpu.io import fastq

            fastq.write_fastq(args.output, ds.batch, ds.sidecar)
        return 0


class PluginExecutor(Command):
    """Load and run a user plugin (PluginExecutor.scala:41-125)."""

    name = "plugin"
    description = "Executes an AdamPlugin"

    @classmethod
    def configure(cls, p):
        p.add_argument("plugin", metavar="PLUGIN",
                       help="dotted path of the AdamPlugin to run")
        p.add_argument("input", metavar="INPUT")
        p.add_argument("-access_control", default=None,
                       help="dotted path of an AccessControl class")
        p.add_argument("-plugin_args", default="",
                       help="string of args passed to the plugin, split on spaces")

    @classmethod
    def run(cls, args):
        from adam_tpu import plugins

        plugin = plugins.load_plugin(args.plugin)
        ac = None
        if args.access_control:
            cls_ = plugins.load_plugin(args.access_control,
                                       base=plugins.AccessControl)
            ac = cls_
        out = plugins.execute_plugin(
            plugin, args.input, args.plugin_args.split(), ac
        )
        if out is not None:
            for row in out:
                print(row)
        return 0


class Flatten(Command):
    """Flatten nested Parquet columns for SQL engines
    (Flatten.scala:32-90 + util/Flattener.scala)."""

    name = "flatten"
    description = ("Convert a ADAM format file to a version with a flattened "
                   "schema, suitable for querying with tools like Impala")

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT")
        p.add_argument("output", metavar="OUTPUT")

    @classmethod
    def run(cls, args):
        from adam_tpu.utils.flattener import flatten_parquet

        flatten_parquet(args.input, args.output,
                        compression=args.parquet_compression_codec)
        return 0


class _GatewayCommand(Command):
    """Shared plumbing for the remote-client verbs: URL resolution
    (a gateway URL or a serve run-root with gateway.json) and the
    connection-error -> exit-2 convention."""

    @staticmethod
    def client(args):
        from adam_tpu.gateway.client import GatewayClient, resolve_url

        return GatewayClient(resolve_url(args.url))

    @staticmethod
    def add_url(p):
        p.add_argument(
            "url", metavar="URL|RUN_ROOT",
            help="gateway address (http://host:port) or a serve "
            "run-root directory carrying gateway.json (written by "
            "'adam-tpu serve --listen')",
        )


class Submit(_GatewayCommand):
    """Remote job submission over the HTTP gateway (adam_tpu/gateway;
    docs/SERVING.md): idempotency-keyed PUTs, duplicate-safe across
    client retries and gateway restarts, honoring 429/503 Retry-After
    with the retry policy's seeded-jitter backoff."""

    name = "submit"
    description = ("Submit transform jobs to a running adam-tpu "
                   "gateway over HTTP (idempotent, back-pressure "
                   "aware)")

    @classmethod
    def configure(cls, p):
        cls.add_url(p)
        p.add_argument(
            "--jobs", dest="jobs", required=True, metavar="FILE",
            help="JSON jobs manifest (the 'adam-tpu serve --jobs' "
            "format; see adam_tpu/api/transform_service.py)",
        )
        p.add_argument(
            "--deadline", dest="deadline", type=float, default=None,
            metavar="S",
            help="give up on back-pressured submissions after S "
            "seconds (default: wait as long as the gateway says to)",
        )
        p.add_argument(
            "--wait", dest="wait", action="store_true",
            help="after submitting, poll until every job reaches a "
            "terminal state (exit 1 if any quarantined)",
        )

    @classmethod
    def run(cls, args):
        from adam_tpu.api.transform_service import load_jobs_manifest
        from adam_tpu.gateway.client import GatewayBusy, GatewayError

        try:
            specs = load_jobs_manifest(args.jobs)
        except (OSError, ValueError) as e:
            print(f"submit: {e}", file=sys.stderr)
            return 2
        try:
            client = cls.client(args)
        except ValueError as e:
            print(f"submit: {e}", file=sys.stderr)
            return 2
        try:
            for spec in specs:
                got = client.submit_with_retry(
                    spec.job_id, spec.to_doc(),
                    deadline_s=args.deadline,
                )
                state = got.get("state", "?")
                dup = " (already submitted)" if got.get("duplicate") \
                    else ""
                print(f"submit: {spec.job_id}: {state}{dup}")
        except GatewayBusy as e:
            print(f"submit: {e}", file=sys.stderr)
            return 1
        except (GatewayError, OSError) as e:
            print(f"submit: {e}", file=sys.stderr)
            return 2
        if not args.wait:
            return 0
        bad = 0
        try:
            for spec in specs:
                view = client.wait(spec.job_id)
                print(f"submit: {spec.job_id} -> {view['state']}")
                if view["state"] == "quarantined":
                    bad += 1
        except (GatewayError, OSError) as e:
            print(f"submit: {e}", file=sys.stderr)
            return 2
        return 1 if bad else 0


class ServiceStatus(_GatewayCommand):
    """Point-in-time service (or per-job) status over the gateway."""

    name = "status"
    description = ("Print a running adam-tpu gateway's service status "
                   "(or one job's) as JSON")

    @classmethod
    def configure(cls, p):
        cls.add_url(p)
        p.add_argument("job", metavar="JOB", nargs="?", default=None,
                       help="one job id (default: the whole service)")

    @classmethod
    def run(cls, args):
        import json

        from adam_tpu.gateway.client import GatewayError

        try:
            doc = cls.client(args).status(args.job)
        except ValueError as e:
            print(f"status: {e}", file=sys.stderr)
            return 2
        except (GatewayError, OSError) as e:
            print(f"status: {e}", file=sys.stderr)
            return 2
        print(json.dumps(doc, indent=1, default=str))
        return 0


class FetchResults(_GatewayCommand):
    """Byte-exact result download over the gateway: every published
    part of the job, sha256-verified, Range-resumable — a fetch
    SIGKILLed mid-download reruns and completes from where it died
    (docs/SERVING.md resumable-fetch semantics)."""

    name = "fetch"
    description = ("Download a job's output parts from a gateway "
                   "(sha256-verified, Range-resumable)")

    @classmethod
    def configure(cls, p):
        cls.add_url(p)
        p.add_argument("job", metavar="JOB")
        p.add_argument("dest", metavar="DEST_DIR",
                       help="local directory the parts land in")

    @classmethod
    def run(cls, args):
        from adam_tpu.gateway.client import GatewayError

        try:
            client = cls.client(args)
            fetched = client.fetch(args.job, args.dest)
        except ValueError as e:
            print(f"fetch: {e}", file=sys.stderr)
            return 2
        except GatewayError as e:
            print(f"fetch: {e}", file=sys.stderr)
            return 1
        except OSError as e:
            print(f"fetch: {e}", file=sys.stderr)
            return 2
        for name in sorted(fetched):
            print(f"fetch: {name} -> {fetched[name]} (sha256 verified)")
        if not fetched:
            print(f"fetch: job {args.job!r} has no published parts yet",
                  file=sys.stderr)
            return 1
        return 0


class CancelJob(_GatewayCommand):
    """Cancel one running job at its next window boundary: in-flight
    parts publish, the journal stays durable and resumable, the job
    lands 'interrupted' (a re-submission resumes it)."""

    name = "cancel"
    description = ("Cancel a running job on a gateway at its next "
                   "window boundary (journal stays resumable)")

    @classmethod
    def configure(cls, p):
        cls.add_url(p)
        p.add_argument("job", metavar="JOB")

    @classmethod
    def run(cls, args):
        from adam_tpu.gateway.client import GatewayError

        try:
            doc = cls.client(args).cancel(args.job)
        except ValueError as e:
            print(f"cancel: {e}", file=sys.stderr)
            return 2
        except GatewayError as e:
            print(f"cancel: {e}", file=sys.stderr)
            return 1
        except OSError as e:
            print(f"cancel: {e}", file=sys.stderr)
            return 2
        print(f"cancel: {doc.get('job_id')} cancelling (stops at its "
              "next window boundary; journal stays resumable)")
        return 0


COMMANDS = [
    CalculateDepth,
    CountReadKmers,
    CountContigKmers,
    Transform,
    Serve,
    Submit,
    ServiceStatus,
    FetchResults,
    CancelJob,
    Adam2Fastq,
    PluginExecutor,
    Flatten,
]
