"""Command registry + lifecycle — ADAMMain / ADAMSparkCommand analog.

``python -m adam_tpu.cli.main <command> [args]`` (or the ``adam-tpu``
console script). The registry mirrors ``ADAMMain.scala:30-72`` — three
groups, same command names. The lifecycle mirrors
``ADAMCommand.scala:43-91``: parse args, optionally enable the metrics
registry, run, print the timing report on ``-print_metrics``.
"""

from __future__ import annotations

import argparse
import contextlib
import sys

from adam_tpu.utils import instrumentation as ins
from adam_tpu.utils import telemetry as tele


class Command:
    """One CLI subcommand: subclasses set name/description and implement
    configure/run (ADAMCommandCompanion + ADAMCommand)."""

    name: str = ""
    description: str = ""

    @classmethod
    def configure(cls, parser: argparse.ArgumentParser) -> None:
        pass

    @classmethod
    def run(cls, args: argparse.Namespace) -> int | None:
        raise NotImplementedError


def add_common_args(parser: argparse.ArgumentParser) -> None:
    """Args4jBase + ParquetArgs flags shared by every command
    (Args4j.scala:23-28, ParquetArgs.scala:24-35)."""
    parser.add_argument(
        "-print_metrics", action="store_true",
        help="print metrics to the log on completion (timer table plus "
        "the telemetry counters/gauges recorded under it)",
    )
    parser.add_argument(
        "--metrics-json", dest="metrics_json", default=None, metavar="PATH",
        help="write the telemetry snapshot (spans, counters, gauges, and "
        "the timer table as machine-readable JSON) to PATH on completion",
    )
    parser.add_argument(
        "--trace-out", dest="trace_out", default=None, metavar="PATH",
        help="write the flight recorder as a Chrome-trace JSON file "
        "loadable in chrome://tracing or Perfetto (per-thread tracks "
        "show the streamed tokenize/dispatch/encode/write overlap)",
    )
    parser.add_argument(
        "--progress", dest="progress", nargs="?", const="stderr",
        default=None, metavar="PATH",
        help="emit a live NDJSON progress heartbeat every few seconds "
        "(windows done/total, reads/s, bytes written, per-device "
        "in-flight depth, retry/fault/evict counters, ETA) to stderr, "
        "or to PATH when given; also honored from ADAM_TPU_PROGRESS, "
        "period from ADAM_TPU_PROGRESS_INTERVAL_S (streamed transform "
        "only; schema in docs/OBSERVABILITY.md)",
    )
    parser.add_argument(
        "--devices", dest="devices", type=int, default=None, metavar="N",
        help="fan device work out over N attached chips (the streamed "
        "pipeline round-robins windows across them; default: all "
        "attached, or ADAM_TPU_DEVICES; N=1 forces the single-device "
        "path; requests beyond the attached count are capped)",
    )
    parser.add_argument(
        "--partitioner", dest="partitioner", default=None,
        choices=["pool", "mesh"],
        help="how the streamed pipeline places device work across the "
        "chips: 'pool' (default) round-robins whole windows with "
        "host-side histogram merges; 'mesh' shards every window over a "
        "batch Mesh, psums the BQSR observe histograms on-device (one "
        "merged table crosses at barrier 2 instead of one per window) "
        "and keeps the solved table device-resident through pass C — "
        "bit-identical output, degrades to 'pool' on device failure; "
        "also honored from ADAM_TPU_PARTITIONER",
    )
    parser.add_argument(
        "--fault-spec", dest="fault_spec", default=None, metavar="SPEC",
        help="arm deterministic fault injection at named pipeline "
        "points (testing/CI only; e.g. 'device.dispatch=transient,"
        "every=3' — grammar in docs/ROBUSTNESS.md; also honored from "
        "ADAM_TPU_FAULTS)",
    )
    parser.add_argument(
        "--xprof-dir", dest="xprof_dir", default=None, metavar="DIR",
        help="wrap the command in a jax profiler trace written to DIR "
        "(xprof/TensorBoard view of the device work; reentrant-safe "
        "no-op if a trace is already active)",
    )
    parser.add_argument(
        "-log_level", default="warning",
        choices=["debug", "info", "warning", "error"],
        help="adam_tpu logging verbosity (debug shows per-target "
        "realignment LOD decisions and BQSR visit accounting)",
    )
    parser.add_argument(
        "-stringency", default="lenient",
        choices=["strict", "lenient", "silent"],
        help="validation stringency for malformed-input handling "
        "(FASTQ pairing/export paths)",
    )
    parser.add_argument(
        "-parquet_compression_codec", default="zstd",
        choices=["uncompressed", "snappy", "gzip", "zstd"],
        help="parquet compression codec",
    )
    parser.add_argument(
        "-parquet_block_size", type=int, default=128 * 1024 * 1024,
        help="parquet block size (accepted for parity; row-group sizing)",
    )
    parser.add_argument(
        "-parquet_page_size", type=int, default=1024 * 1024,
        help="parquet page size (accepted for parity)",
    )
    parser.add_argument(
        "-parquet_disable_dictionary", action="store_true",
        help="disable parquet dictionary encoding (accepted for parity)",
    )


def command_groups():
    from adam_tpu.cli import actions, conversions, devtools, printers

    return [
        ("ADAM ACTIONS", actions.COMMANDS),
        ("CONVERSION OPERATIONS", conversions.COMMANDS),
        ("PRINT", printers.COMMANDS),
        ("DEVELOPMENT", devtools.COMMANDS),
    ]


def _usage() -> str:
    out = ["", "Usage: adam-tpu COMMAND", ""]
    for group, commands in command_groups():
        out.append(group)
        for cmd in commands:
            out.append(f"{cmd.name:>20} : {cmd.description}")
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    registry = {c.name: c for _, cmds in command_groups() for c in cmds}
    if not argv or argv[0] in ("-h", "--help"):
        print(_usage())
        return 0
    name, rest = argv[0], argv[1:]
    if name not in registry:
        print(f"unknown command: {name}", file=sys.stderr)
        print(_usage(), file=sys.stderr)
        return 1
    cmd = registry[name]
    parser = argparse.ArgumentParser(
        prog=f"adam-tpu {name}", description=cmd.description,
        # reference flags are single-dash long options (args4j); argparse
        # prefix matching would make flag typos silently match — disable
        allow_abbrev=False,
    )
    add_common_args(parser)
    cmd.configure(parser)
    args = parser.parse_args(rest)
    import logging

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
    )
    # any observability sink switches recording on: the timer table, the
    # JSON snapshot, the Chrome trace and the analyzer report all read
    # the same run (--progress self-manages via the heartbeat instead)
    want_metrics = bool(
        args.print_metrics or args.metrics_json or args.trace_out
        or getattr(args, "report", None)
    )
    ins.TIMERS.recording = want_metrics
    tele.TRACE.recording = want_metrics
    if args.fault_spec:
        from adam_tpu.utils import faults

        try:
            faults.install(args.fault_spec)
        except ValueError as e:
            print(f"--fault-spec: {e}", file=sys.stderr)
            return 2
    xprof = (
        ins.device_trace(args.xprof_dir) if args.xprof_dir
        else contextlib.nullcontext()
    )
    try:
        with xprof:
            rc = cmd.run(args)
    except BrokenPipeError:  # e.g. `adam-tpu print ... | head`
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0
    finally:
        if args.print_metrics:
            try:
                print(ins.TIMERS.report())
                print(tele.TRACE.report())
            except BrokenPipeError:
                pass
        for path, dump in (
            (args.metrics_json, tele.TRACE.dump_json),
            (args.trace_out, tele.TRACE.dump_chrome_trace),
        ):
            if path:
                try:
                    dump(path)
                except OSError as e:
                    print(f"telemetry export to {path} failed: {e}",
                          file=sys.stderr)
    return int(rc or 0)


if __name__ == "__main__":
    sys.exit(main())
