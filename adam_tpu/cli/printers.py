"""PRINT command group (ADAMMain.scala:61-72).

print, print_genes, flagstat, print_tags, listdict, allelecount,
buildinfo, view.
"""

from __future__ import annotations

import sys

import numpy as np

from adam_tpu.cli.main import Command
from adam_tpu.formats import schema
from adam_tpu.utils import instrumentation as ins


class PrintAdam(Command):
    """Print parquet rows (PrintADAM.scala:31-110); -pretty emits
    indented JSON like the reference's pretty Avro-JSON mode."""

    name = "print"
    description = "Print an ADAM formatted file"

    @classmethod
    def configure(cls, p):
        p.add_argument("files", metavar="FILE(S)", nargs="+")
        p.add_argument("-o", dest="output", default=None,
                       help="output to a (local) file")
        p.add_argument("-pretty", action="store_true",
                       help="display raw, pretty-formatted JSON")
        p.add_argument("-projection", default=None,
                       help="comma-separated column names to read "
                            "(pushed down to the Parquet scan)")

    @classmethod
    def run(cls, args):
        import json

        import pyarrow.parquet as pq

        cols = (
            [c.strip() for c in args.projection.split(",") if c.strip()]
            if args.projection else None
        )
        out = open(args.output, "w") if args.output else sys.stdout
        try:
            for path in args.files:
                table = pq.read_table(path, columns=cols)
                for row in table.to_pylist():
                    if args.pretty:
                        out.write(json.dumps(row, indent=2, default=str) + "\n")
                    else:
                        out.write(json.dumps(row, default=str) + "\n")
        finally:
            if args.output:
                out.close()
        return 0


class PrintGenes(Command):
    """Gene models from a GTF (PrintGenes.scala:28-70; same format)."""

    name = "print_genes"
    description = ("Load a GTF file containing gene annotations and print "
                   "the corresponding gene models")

    @classmethod
    def configure(cls, p):
        p.add_argument("gtf", metavar="GTF")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import features as fio
        from adam_tpu.models.genes import as_genes

        feats = fio.read_features(args.gtf, fmt="gtf")
        for gene in as_genes(feats):
            parts = ["Gene %s (%s)" % (gene.id, ",".join(gene.names))]
            for t in gene.transcripts:
                parts.append(
                    "\n\tTranscript %s %s:%d-%d:%s (%d exons)" % (
                        t.id, t.region.referenceName, t.region.start,
                        t.region.end, "+" if t.strand else "-", len(t.exons),
                    )
                )
            print("".join(parts))
        return 0


class FlagStat(Command):
    """samtools-flagstat clone (adam-cli FlagStat.scala:28-60 -> core
    rdd/read/FlagStat.scala:84-119)."""

    name = "flagstat"
    description = "Print statistics on reads in an ADAM file (similar to samtools flagstat)"

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import context
        from adam_tpu.ops.flagstat import flagstat, format_flagstat

        kw = {}
        if str(args.input).endswith((".adam", ".parquet")):
            kw["projection"] = [
                "flags", "mapq", "readName", "sequence", "contig", "start",
                "mateContig", "mateAlignmentStart",
            ]
        ds = context.load_alignments(args.input, **kw)
        with ins.TIMERS.time(ins.FLAGSTAT):
            failed, passed = flagstat(ds.batch)
        print(format_flagstat(failed, passed))
        return 0


class PrintTags(Command):
    """Values/counts of attribute tags (PrintTags.scala:28-75)."""

    name = "print_tags"
    description = "Prints the values and counts of all tags in a set of records"

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT")
        p.add_argument("-list", dest="list_n", default=None,
                       help="also list the first N attribute fields")
        p.add_argument("-count", dest="count", default=None,
                       help="comma-separated tag names to print values/counts for")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import context

        ds = context.load_alignments(args.input)
        b = ds.batch.to_numpy()
        ok = np.asarray(b.valid) & (
            (np.asarray(b.flags) & schema.FLAG_FAILED_QC) == 0
        )
        rows = np.flatnonzero(ok)
        attrs = [ds.sidecar.attrs[i] for i in rows]
        if args.list_n is not None:
            for a in attrs[: int(args.list_n)]:
                print(a)
        to_count = set(args.count.split(",")) if args.count else set()
        tag_counts: dict[str, int] = {}
        value_counts: dict[str, dict] = {t: {} for t in to_count}
        for a in attrs:
            if not a:
                continue
            for tag_str in a.split("\t"):
                name = tag_str.split(":", 1)[0]
                tag_counts[name] = tag_counts.get(name, 0) + 1
                if name in to_count:
                    val = tag_str.split(":", 2)[-1]
                    value_counts[name][val] = value_counts[name].get(val, 0) + 1
        for tag, count in sorted(tag_counts.items()):
            print("%3s\t%d" % (tag, count))
            if tag in to_count:
                for value, vc in sorted(value_counts[tag].items()):
                    print("\t%10d\t%s" % (vc, value))
        print("Total: %d" % len(rows))
        return 0


class ListDict(Command):
    """Print the sequence dictionary (ListDict.scala:27-55)."""

    name = "listdict"
    description = "Print the contents of an ADAM sequence dictionary"

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import context

        ds = context.load_alignments(args.input)
        for rec in ds.seq_dict.records:
            print("%s\t%d" % (rec.name, rec.length))
        return 0


class AlleleCount(Command):
    """Allele frequencies per site (AlleleCount.scala:28-80)."""

    name = "allelecount"
    description = "Calculate Allele frequencies"

    @classmethod
    def configure(cls, p):
        p.add_argument("adam", metavar="ADAM", help="ADAM variant data or VCF")
        p.add_argument("output", metavar="Output")

    @classmethod
    def run(cls, args):
        from adam_tpu.api.datasets import GenotypeDataset

        gt = GenotypeDataset.load(args.adam)
        with open(args.output, "w") as fh:
            for chrom, pos, allele, count in gt.allele_count():
                fh.write("%s\t%s\t%s\t%d\n" % (chrom, pos, allele, count))
        return 0


class BuildInformation(Command):
    """Build metadata (BuildInformation.scala + git-commit-id parity)."""

    name = "buildinfo"
    description = "Display build information (use this for bug reports)"

    @classmethod
    def run(cls, args):
        import platform

        import jax

        import adam_tpu

        print("adam-tpu version: %s" % adam_tpu.__version__)
        print("jax version: %s" % jax.__version__)
        print("python: %s" % platform.python_version())
        print("backend: %s" % jax.default_backend())
        return 0


class View(Command):
    """samtools-view clone: -f/-F/-g/-G bit filters, -c count, SAM to
    stdout (View.scala:28-160)."""

    name = "view"
    description = "View certain reads from an alignment-record file."

    @classmethod
    def configure(cls, p):
        p.add_argument("input", metavar="INPUT")
        p.add_argument("output", metavar="OUTPUT", nargs="?", default=None)
        p.add_argument("-f", dest="match_all", type=int, default=0,
                       help="restrict to reads matching ALL bits in N")
        p.add_argument("-F", dest="mismatch_all", type=int, default=0,
                       help="restrict to reads matching NONE of the bits in N")
        p.add_argument("-g", dest="match_some", type=int, default=0,
                       help="restrict to reads matching ANY of the bits in N")
        p.add_argument("-G", dest="mismatch_some", type=int, default=0,
                       help="restrict to reads mismatching at least one bit in N")
        p.add_argument("-c", dest="print_count", action="store_true",
                       help="print count of matching records")
        p.add_argument("-o", dest="output_flag", default=None)

    # the twelve per-bit predicates of View.getFilters (View.scala:103-127);
    # 0x8 requires the read to be paired, matching the reference's
    # mate-mapped quirk
    @staticmethod
    def _bit_predicate(flags: np.ndarray, bit: int) -> np.ndarray:
        if bit == 0x8:
            return ((flags & 0x1) != 0) & ((flags & 0x8) != 0)
        return (flags & bit) != 0

    @classmethod
    def _mask(cls, flags: np.ndarray, args) -> np.ndarray:
        bits = [1 << i for i in range(12)]
        keep = np.ones(len(flags), bool)
        for bit in bits:
            pred = cls._bit_predicate(flags, bit)
            if args.match_all & bit:
                keep &= pred
            if args.mismatch_all & bit:
                keep &= ~pred
        for group, want in ((args.match_some, True),
                            (args.mismatch_some, False)):
            if group:
                some = np.zeros(len(flags), bool)
                for bit in bits:
                    if group & bit:
                        some |= cls._bit_predicate(flags, bit) == want
                keep &= some
        return keep

    @classmethod
    def run(cls, args):
        from adam_tpu.io import context, sam

        output = args.output or args.output_flag
        ds = context.load_alignments(args.input)
        b = ds.batch.to_numpy()
        keep = cls._mask(np.asarray(b.flags), args) & np.asarray(b.valid)
        ds = ds.take_rows(np.flatnonzero(keep))
        if output:
            ds.save(output)
        elif args.print_count:
            print(len(ds))
        else:
            for line in sam.format_sam_records(ds.batch, ds.sidecar, ds.header):
                sys.stdout.write(line + "\n")
        return 0


class Analyze(Command):
    """Run report from a telemetry artifact (utils/analyzer.py): the
    post-hoc half of the observability layer — per-device busy/idle
    attribution, barrier decomposition, the critical path and latency
    quantiles from a ``--metrics-json`` snapshot or ``--trace-out``
    Chrome trace, no re-run required."""

    name = "analyze"
    description = ("Analyze a telemetry snapshot or Chrome trace into a "
                   "run report (device utilization, barrier stalls, "
                   "critical path, latency quantiles)")

    @classmethod
    def configure(cls, p):
        p.add_argument(
            "input", metavar="ARTIFACT",
            help="a --metrics-json snapshot or --trace-out Chrome trace "
            "(auto-detected; a trace additionally yields idle-gap "
            "analysis and the critical path)",
        )
        p.add_argument(
            "-json", dest="json_out", default=None, metavar="PATH",
            help="also write the analysis as machine-readable JSON",
        )

    @classmethod
    def run(cls, args):
        import json

        from adam_tpu.utils import analyzer

        try:
            # analyze_path folds sibling incidents/, SLO_BUDGET.json
            # and PERF_LEDGER.ndjson into the report's Incidents/SLO/
            # Perf-trend sections
            report = analyzer.analyze_path(args.input)
        except (OSError, ValueError) as e:
            print(f"analyze: {e}", file=sys.stderr)
            return 2
        print(analyzer.render_report(report))
        if args.json_out:
            try:
                with open(args.json_out, "w") as fh:
                    json.dump(report, fh, indent=1, default=str)
            except OSError as e:
                print(f"analyze: cannot write {args.json_out}: {e}",
                      file=sys.stderr)
                return 2
        return 0


class Top(Command):
    """Live dashboard over a streamed run's ``--progress`` heartbeat
    file (utils/top.py): the interactive half of the observability
    layer — tails the NDJSON stream, renders a refreshing one-screen
    view (progress bar, reads/s, tunnel bytes, HBM, per-device
    in-flight depth, retry/evict counters, ETA), and exits cleanly on
    the final ``done=true`` line.  Read-only: attach/detach freely
    while the run is live."""

    name = "top"
    description = ("Live terminal dashboard tailing a streamed run's "
                   "--progress heartbeat file, or a serve run-root "
                   "directory for the multi-job view (exits on done)")

    @classmethod
    def configure(cls, p):
        p.add_argument(
            "heartbeat", metavar="HEARTBEAT.ndjson|RUN_ROOT",
            nargs="?", default=None,
            help="the NDJSON file a streamed transform is writing via "
            "--progress PATH (or ADAM_TPU_PROGRESS=PATH); may not "
            "exist yet — top waits for the first line.  A DIRECTORY "
            "(an 'adam-tpu serve' run-root) switches to the multi-job "
            "view: every <job>/heartbeat.ndjson under it aggregates "
            "into one dashboard with per-job rows + pool totals, "
            "tolerating jobs appearing and finishing mid-watch",
        )
        p.add_argument(
            "--url", dest="url", default=None, metavar="URL",
            help="tail a REMOTE serve run-root through its HTTP "
            "gateway (http://host:port, from 'adam-tpu serve "
            "--listen'): the same multi-job dashboard, fed by the "
            "gateway's cursor-resumable NDJSON event streams instead "
            "of local files; exit codes keep the 0/1/2 contract",
        )
        p.add_argument(
            "-interval", type=float, default=0.5,
            help="refresh period in seconds (default 0.5)",
        )
        p.add_argument(
            "-once", "--once", dest="once", action="store_true",
            help="render a single frame from the newest line and exit "
            "(scripting/CI mode; exit 2 when the file has no lines) — "
            "the usual 0/1/2 codes, so CI legs and incident-bundle "
            "captures can gate on it",
        )
        p.add_argument(
            "-max_wait", type=float, default=None, metavar="S",
            help="give up (exit 2) when no done=true arrives within S "
            "seconds (default: follow forever)",
        )

    @classmethod
    def run(cls, args):
        import os

        from adam_tpu.utils import top as top_mod

        if (args.heartbeat is None) == (args.url is None):
            print("top: give exactly one of HEARTBEAT.ndjson|RUN_ROOT "
                  "or --url", file=sys.stderr)
            return 2
        if args.url is not None:
            return top_mod.follow_url(
                args.url, interval=max(0.05, args.interval),
                once=args.once, max_wait_s=args.max_wait,
            )
        if os.path.isdir(args.heartbeat):
            return top_mod.follow_root(
                args.heartbeat, interval=max(0.05, args.interval),
                once=args.once, max_wait_s=args.max_wait,
            )
        return top_mod.follow(
            args.heartbeat, interval=max(0.05, args.interval),
            once=args.once, max_wait_s=args.max_wait,
        )


class Incidents(Command):
    """List the anomaly-triggered incident bundles a run (or serve
    run-root) recorded (utils/incidents.py): one row per bundle —
    trigger, device, window, trace id, reason — newest last.  Each
    bundle is a self-contained JSON file carrying the flight-recorder
    ring tail, a metrics snapshot, the health board, and the
    triggering job's Chrome trace; point ``adam-tpu analyze`` at a
    telemetry artifact beside them for the folded report view."""

    name = "incidents"
    description = ("List anomaly-triggered incident bundles under a "
                   "run dir or serve run-root (trigger, device, "
                   "window, trace id; bundles are self-contained JSON)")

    @classmethod
    def configure(cls, p):
        p.add_argument(
            "run_dir", metavar="RUN_DIR",
            help="a run dir or serve run-root (bundles live under its "
            "incidents/ subdirectory), or the incidents/ dir itself",
        )
        p.add_argument(
            "-json", dest="json_out", action="store_true",
            help="print the bundle summaries as JSON instead of a table",
        )

    @classmethod
    def run(cls, args):
        import json
        import time as time_mod

        from adam_tpu.utils import incidents as incidents_mod

        rows = incidents_mod.list_bundles(args.run_dir)
        if args.json_out:
            print(json.dumps(
                {"schema": incidents_mod.INCIDENT_SCHEMA + "+list",
                 "incidents": rows}, indent=1,
            ))
            return 0
        if not rows:
            print(f"incidents: none under {args.run_dir}")
            return 0
        print(f"{'WHEN':<20} {'TRIGGER':<18} {'DEVICE':<14} "
              f"{'WINDOW':>6} {'TRACE':<17} REASON")
        for r in rows:
            ts = r.get("ts")
            when = (
                time_mod.strftime("%Y-%m-%d %H:%M:%S",
                                  time_mod.localtime(ts))
                if isinstance(ts, (int, float)) else "-"
            )
            window = r.get("window")
            print(
                f"{when:<20} {str(r.get('trigger') or '-'):<18} "
                f"{str(r.get('device') or '-'):<14} "
                f"{window if window is not None else '-':>6} "
                f"{str(r.get('trace_id') or '-'):<17} "
                f"{r.get('reason') or ''}"
            )
        return 0


COMMANDS = [
    PrintAdam,
    PrintGenes,
    FlagStat,
    PrintTags,
    ListDict,
    AlleleCount,
    BuildInformation,
    View,
    Analyze,
    Top,
    Incidents,
]
