"""CONVERSION OPERATIONS command group (ADAMMain.scala:49-60).

bam2adam, vcf2adam, anno2adam, adam2vcf, fasta2adam, features2adam,
wigfix2bed.
"""

from __future__ import annotations

from adam_tpu.cli.main import Command
from adam_tpu.utils import instrumentation as ins


class Bam2Adam(Command):
    """SAM/BAM -> columnar Parquet without the distributed engine — the
    reference's non-Spark multithreaded converter (Bam2ADAM.scala:31-120,
    htsjdk reader -> blocking queue -> N writer threads). The codec layer
    does its own block-parallel BGZF work; -num_threads is accepted for
    parity."""

    name = "bam2adam"
    description = "Single-node BAM to ADAM converter (Note: the 'transform' command can take SAM or BAM as input)"

    @classmethod
    def configure(cls, p):
        p.add_argument("bam", metavar="BAM")
        p.add_argument("adam", metavar="ADAM")
        p.add_argument("-samtools_validation", default="lenient",
                       help="accepted for parity")
        p.add_argument("-num_threads", type=int, default=4)
        p.add_argument("-queue_size", type=int, default=10000,
                       help="accepted for parity")

    @classmethod
    def run(cls, args):
        from adam_tpu import native
        from adam_tpu.io import context, parquet

        if str(args.bam).endswith(".bam") and native.available():
            # streaming path: WGS-scale BAMs never fit in memory; windowed
            # BGZF decode -> record tokenize -> parquet row groups
            import pyarrow.parquet as pq

            from adam_tpu.io import sam as sam_io

            writer = None
            n = 0
            with ins.TIMERS.time(ins.SAVE_OUTPUT):
                for batch, side, header in sam_io.iter_bam_batches(args.bam):
                    table = parquet.to_arrow_alignments(batch, side, header)
                    if writer is None:
                        writer = pq.ParquetWriter(
                            args.adam, table.schema,
                            compression=args.parquet_compression_codec,
                        )
                    writer.write_table(table)
                    n += table.num_rows
                if writer is not None:
                    writer.close()
            if writer is not None:
                print(f"bam2adam: streamed {n} reads")
                return 0
            # empty BAM: fall through to the whole-file path for the header

        with ins.TIMERS.time(ins.LOAD_ALIGNMENTS):
            ds = context.load_alignments(args.bam)
        with ins.TIMERS.time(ins.SAVE_OUTPUT):
            parquet.save_alignments(
                args.adam, ds.batch, ds.sidecar, ds.header,
                compression=args.parquet_compression_codec,
            )
        return 0


class Vcf2Adam(Command):
    """VCF -> columnar genotype/variant Parquet (Vcf2ADAM.scala:28-70)."""

    name = "vcf2adam"
    description = "Convert a VCF file to the corresponding ADAM format"

    @classmethod
    def configure(cls, p):
        p.add_argument("vcf", metavar="VCF")
        p.add_argument("adam", metavar="ADAM")
        p.add_argument("-onlyvariants", action="store_true",
                       help="output only variants, not genotypes")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import parquet, vcf

        variants, genotypes, seq_dict = vcf.read_vcf(args.vcf)
        if args.onlyvariants:
            import numpy as np

            genotypes = genotypes.take(np.zeros(0, np.int64))
        parquet.save_genotypes(
            args.adam, variants, genotypes, seq_dict,
            compression=args.parquet_compression_codec,
        )
        return 0


class VcfAnnotation2Adam(Command):
    """VCF annotation database -> ADAM variant-annotation Parquet
    (VcfAnnotation2ADAM.scala:46-90; INFO fields ride the variant
    sidecar as the DatabaseVariantAnnotation analog)."""

    name = "anno2adam"
    description = "Convert a annotation file (in VCF format) to the corresponding ADAM format"

    @classmethod
    def configure(cls, p):
        p.add_argument("vcf", metavar="VCF")
        p.add_argument("adam", metavar="ADAM")
        p.add_argument("-current-db", dest="current_db", default=None,
                       help="existing annotation store to merge with")

    @classmethod
    def run(cls, args):
        import numpy as np

        from adam_tpu.formats.variants import VariantBatch, VariantSidecar
        from adam_tpu.io import parquet, vcf
        from adam_tpu.models.dictionaries import (
            SequenceDictionary,
            SequenceRecord,
        )

        variants, genotypes, seq_dict = vcf.read_vcf(args.vcf)
        genotypes = genotypes.take(np.zeros(0, np.int64))
        if args.current_db:
            # merge with the existing store on variant key; rows from the
            # new VCF supersede old ones (the joinWithVariantAnnotation
            # merge, VcfAnnotation2ADAM.scala:70-85)
            old_v, _og, old_sd = parquet.load_genotypes(args.current_db)
            names = [r.name for r in seq_dict.records]
            old_names = [r.name for r in old_sd.records]
            new_keys = set(variants.variant_keys(names))
            keep = np.array(
                [
                    i for i, k in enumerate(old_v.variant_keys(old_names))
                    if k not in new_keys
                ],
                np.int64,
            )
            old_v = old_v.take(keep)
            name_idx = {n: i for i, n in enumerate(names)}
            records = list(seq_dict.records)
            for r in old_sd.records:
                if r.name not in name_idx:
                    name_idx[r.name] = len(records)
                    records.append(SequenceRecord(r.name, r.length))
            seq_dict = SequenceDictionary(tuple(records))
            remap = np.array([name_idx[n] for n in old_names], np.int64)
            s_new, s_old = variants.sidecar, old_v.sidecar
            variants = VariantBatch(
                contig_idx=np.concatenate(
                    [variants.contig_idx, remap[old_v.contig_idx]]
                ).astype(np.int32),
                start=np.concatenate([variants.start, old_v.start]),
                end=np.concatenate([variants.end, old_v.end]),
                ref_len=np.concatenate([variants.ref_len, old_v.ref_len]),
                alt_len=np.concatenate([variants.alt_len, old_v.alt_len]),
                qual=np.concatenate([variants.qual, old_v.qual]),
                filters_applied=np.concatenate(
                    [variants.filters_applied, old_v.filters_applied]
                ),
                passing=np.concatenate([variants.passing, old_v.passing]),
                sidecar=VariantSidecar(
                    ref_allele=s_new.ref_allele + s_old.ref_allele,
                    alt_allele=s_new.alt_allele + s_old.alt_allele,
                    names=s_new.names + s_old.names,
                    filters=s_new.filters + s_old.filters,
                    info=s_new.info + s_old.info,
                ),
            )
        parquet.save_genotypes(
            args.adam, variants, genotypes, seq_dict,
            compression=args.parquet_compression_codec,
        )
        return 0


class Adam2Vcf(Command):
    """ADAM genotype Parquet -> VCF (ADAM2Vcf.scala:30-76)."""

    name = "adam2vcf"
    description = "Convert an ADAM variant to the VCF ADAM format"

    @classmethod
    def configure(cls, p):
        p.add_argument("adam", metavar="ADAM")
        p.add_argument("vcf", metavar="VCF")
        p.add_argument("-coalesce", type=int, default=-1,
                       help="accepted for parity")
        p.add_argument("-sort_on_save", action="store_true")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import parquet, vcf

        variants, genotypes, seq_dict = parquet.load_genotypes(args.adam)
        vcf.write_vcf(args.vcf, variants, genotypes, seq_dict,
                      args.sort_on_save)
        return 0


class Fasta2Adam(Command):
    """FASTA -> fragment Parquet (Fasta2ADAM.scala:25-76)."""

    name = "fasta2adam"
    description = "Converts a text FASTA sequence file into an ADAMNucleotideContig Parquet file which represents assembled sequences."

    @classmethod
    def configure(cls, p):
        p.add_argument("fasta", metavar="FASTA")
        p.add_argument("adam", metavar="ADAM")
        p.add_argument("-fragment_length", type=int, default=10000)
        p.add_argument("-verbose", action="store_true")
        p.add_argument("-reads", default=None,
                       help="reads file for a sequence dictionary to use instead")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import context, parquet

        fragments, seq_dict, descriptions = context.load_fasta(
            args.fasta, args.fragment_length
        )
        if args.reads:
            ds = context.load_alignments(args.reads)
            if len(ds.seq_dict.names) > 0:
                seq_dict = ds.seq_dict
        if args.verbose:
            print("Loaded dictionary:")
            for r in seq_dict.records:
                print(f"  {r.name}\t{r.length}")
        parquet.save_fragments(
            args.adam, fragments, seq_dict, descriptions,
            compression=args.parquet_compression_codec,
        )
        return 0


class Features2Adam(Command):
    """GTF/BED/narrowPeak -> feature Parquet (Features2ADAM.scala:28-60)."""

    name = "features2adam"
    description = "Convert a file with sequence features into corresponding ADAM format"

    @classmethod
    def configure(cls, p):
        p.add_argument("features", metavar="FEATURES",
                       help="feature file (gtf/gff/bed/narrowpeak)")
        p.add_argument("adam", metavar="ADAM")

    @classmethod
    def run(cls, args):
        from adam_tpu.io import features as fio
        from adam_tpu.io import parquet

        feats = fio.read_features(args.features)
        parquet.save_features(args.adam, feats,
                              compression=args.parquet_compression_codec)
        return 0


class WigFix2Bed(Command):
    """Locally convert a wigFix file to BED (Wiggle2Bed.scala:40-81;
    non-distributed in the reference too)."""

    name = "wigfix2bed"
    description = "Locally convert a wigFix file to BED format"

    @classmethod
    def configure(cls, p):
        p.add_argument("wig", metavar="WIG", nargs="?", default=None,
                       help="input wigFix file (default: stdin)")
        p.add_argument("-o", dest="output", default=None,
                       help="output BED file (default: stdout)")

    @classmethod
    def run(cls, args):
        import sys

        from adam_tpu.io.features import wigfix_to_bed_lines

        fin = open(args.wig) if args.wig else sys.stdin
        fout = open(args.output, "w") if args.output else sys.stdout
        try:
            for row in wigfix_to_bed_lines(fin):
                fout.write(row + "\n")
        finally:
            if args.wig:
                fin.close()
            if args.output:
                fout.close()
        return 0


COMMANDS = [
    Bam2Adam,
    Vcf2Adam,
    VcfAnnotation2Adam,
    Adam2Vcf,
    Fasta2Adam,
    Features2Adam,
    WigFix2Bed,
]
