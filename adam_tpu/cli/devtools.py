"""Development / CI commands (no reference analog — this repo's
contract tooling face, like ``analyze`` and ``top`` are its
observability face)."""

from __future__ import annotations

import argparse

from adam_tpu.cli.main import Command


class Check(Command):
    """``adam-tpu check`` — the AST-based contract checker
    (adam_tpu/staticcheck; docs/STATIC_ANALYSIS.md).  Deliberately
    importable without jax: CI gates on it before any device code
    runs."""

    name = "check"
    description = ("Run the static contract checker (device-sync, "
                   "compile-ledger, durability, fault-point and lock "
                   "discipline)")

    @classmethod
    def configure(cls, parser: argparse.ArgumentParser) -> None:
        from adam_tpu.staticcheck import cli as check_cli

        check_cli.configure(parser)

    @classmethod
    def run(cls, args: argparse.Namespace) -> int:
        from adam_tpu.staticcheck import cli as check_cli

        return check_cli.run(args)


COMMANDS = [Check]
