"""Development / CI commands (no reference analog — this repo's
contract tooling face, like ``analyze`` and ``top`` are its
observability face)."""

from __future__ import annotations

import argparse

from adam_tpu.cli.main import Command


class Check(Command):
    """``adam-tpu check`` — the AST-based contract checker
    (adam_tpu/staticcheck; docs/STATIC_ANALYSIS.md).  Deliberately
    importable without jax: CI gates on it before any device code
    runs."""

    name = "check"
    description = ("Run the static contract checker (device-sync, "
                   "compile-ledger, durability, fault-point and lock "
                   "discipline)")

    @classmethod
    def configure(cls, parser: argparse.ArgumentParser) -> None:
        from adam_tpu.staticcheck import cli as check_cli

        check_cli.configure(parser)

    @classmethod
    def run(cls, args: argparse.Namespace) -> int:
        from adam_tpu.staticcheck import cli as check_cli

        return check_cli.run(args)


class Perf(Command):
    """``adam-tpu perf`` — the perf-ledger trend table + regression
    sentinel (utils/perfledger.py, docs/OBSERVABILITY.md "The perf
    ledger").  Importable without jax: the ledger is plain NDJSON, so
    CI can gate on a run root no matter where it was produced."""

    name = "perf"
    description = ("Render a run root's PERF_LEDGER.ndjson trend and "
                   "flag regressions vs the rolling median baseline "
                   "(exit 1 when the newest run regressed)")

    @classmethod
    def configure(cls, parser: argparse.ArgumentParser) -> None:
        parser.add_argument(
            "root", metavar="RUN_ROOT",
            help="run root holding PERF_LEDGER.ndjson (or the ledger "
            "file itself)",
        )
        parser.add_argument(
            "--threshold", type=float, default=None, metavar="PCT",
            help="direction-aware regression threshold in percent "
            "(default ADAM_TPU_PERF_THRESHOLD, 25)",
        )
        parser.add_argument(
            "--baseline-n", dest="baseline_n", type=int, default=None,
            metavar="N",
            help="rolling-median baseline depth (default "
            "ADAM_TPU_PERF_BASELINE_N, 5)",
        )
        parser.add_argument(
            "--json", dest="json_out", action="store_true",
            help="emit the trend as one machine-readable JSON document "
            "(schema adam_tpu.perf_trend/1) instead of the table",
        )

    @classmethod
    def run(cls, args: argparse.Namespace) -> int:
        import json
        import sys
        import time

        from adam_tpu.utils import perfledger

        entries = perfledger.read_ledger(args.root)
        if not entries:
            print(f"perf: no ledger entries under {args.root!r} "
                  f"({perfledger.LEDGER_FILENAME})", file=sys.stderr)
            return 2
        rows = perfledger.trend(
            entries, n=args.baseline_n, threshold_pct=args.threshold,
        )
        newest_regressions = rows[-1]["regressions"] if rows else []
        if args.json_out:
            print(json.dumps({
                "schema": "adam_tpu.perf_trend/1",
                "root": args.root,
                "n_entries": len(entries),
                "rows": rows,
                "regressions": newest_regressions,
                "ok": not newest_regressions,
            }, indent=1))
            return 1 if newest_regressions else 0
        print(f"{'#':>3}  {'when':19}  {'run':>12}  {'total_s':>9}"
              f"  {'keys':>5}  regressions")
        for r in rows:
            when = (time.strftime("%Y-%m-%d %H:%M:%S",
                                  time.localtime(r["ts"]))
                    if r.get("ts") else "-")
            run = str(r.get("run_id") or "-")[-12:]
            total = (f"{r['total_s']:9.3f}" if r.get("total_s")
                     is not None else f"{'-':>9}")
            mark = (", ".join(
                f"{x['key']} {x['delta_pct']:+.1f}%"
                for x in r["regressions"]) or
                ("(baseline)" if r["index"]
                 < perfledger.MIN_BASELINE_RUNS else "none"))
            print(f"{r['index']:>3}  {when:19}  {run:>12}  {total}"
                  f"  {r['n_keys']:>5}  {mark}")
        if newest_regressions:
            print(f"\nperf: newest run regressed "
                  f"{len(newest_regressions)} key(s) vs the rolling "
                  "median baseline", file=sys.stderr)
            return 1
        return 0


COMMANDS = [Check, Perf]
