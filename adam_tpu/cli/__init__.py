"""Command-line layer — the adam-cli module of the reference.

Registry and lifecycle in :mod:`adam_tpu.cli.main` (ADAMMain.scala:26-110
/ ADAMCommand.scala:43-91); commands grouped as the reference groups them:
:mod:`.actions` (ADAM ACTIONS), :mod:`.conversions` (CONVERSION
OPERATIONS), :mod:`.printers` (PRINT).
"""
