"""adam_tpu — a TPU-native genomics read-processing framework.

A from-scratch re-design of the capabilities of ADAM (the Spark/Parquet
genomics platform, see /root/reference) built idiomatically on JAX/XLA:

* Reads, variants, genotypes, features and reference fragments are
  struct-of-arrays **columnar batches** (padded + masked), not
  record-per-object Avro — so every transform is a batched array program
  that XLA can tile onto the MXU.
* The per-partition hot loops of the reference (BQSR, indel realignment,
  duplicate marking, Smith-Waterman, k-mer counting, flagstat) are JAX
  kernels: scatter-add covariate histograms, wavefront DP, segment
  reductions, packed-integer k-mer sort/unique.
* Spark's shuffle/broadcast/aggregate roles are played by XLA collectives
  (`psum`, `all_to_all`, `ppermute`) over a genome-sharded `jax.sharding.Mesh`.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

* ``adam_tpu.formats``   — L0': schema constants + columnar batch types
* ``adam_tpu.models``    — L3: genomic coordinates, dictionaries, tables
* ``adam_tpu.io``        — L1/L2: SAM/BAM/FASTQ/FASTA/VCF/Parquet/2bit IO
* ``adam_tpu.ops``       — L5: pure device kernels
* ``adam_tpu.pipelines`` — L6: distributed read transforms
* ``adam_tpu.parallel``  — L4: mesh, partitioners, collective shuffles
* ``adam_tpu.api``       — L7: user-facing dataset classes + plugin API
* ``adam_tpu.cli``       — L8: command line (transform, flagstat, ...)
* ``adam_tpu.plugins``   — L7: user-plugin API (ADAMPlugin analog)
* ``adam_tpu.utils``     — L9 + misc: named-timer registry, flattener, ...
"""

import os

# pyarrow's bundled mimalloc segfaults in mi_thread_init when arrow spawns
# IO threads after short-lived Python threads that touched mimalloc TLS
# have exited (exactly the streamed transform's writer-pool shape) — pin
# the system allocator before pyarrow initializes.  io/parquet.py repeats
# this via set_memory_pool for processes that imported pyarrow first.
os.environ.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")

import jax

# Genomic coordinates, flattened genome offsets and 2-bit packed k-mers all
# need 64-bit integers (human genome ~3.1e9 bp > 2^31; k=21 k-mer = 42 bits),
# so importing adam_tpu enables jax x64 process-wide. Device arrays stay
# explicitly i32 wherever ranges allow, so unrelated JAX code keeps its
# dtypes as long as it spells them out; set ADAM_TPU_NO_X64=1 to opt out
# (k-mer packing and packed position keys then fall back to host numpy).
if not os.environ.get("ADAM_TPU_NO_X64"):
    jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: CLI-style invocations pay first-compile
# once per (kernel, shape) across *processes*, not per run — the analog of
# the JVM's warmed JIT staying resident in the Spark executor. Opt out with
# ADAM_TPU_NO_COMPILE_CACHE=1; override location with ADAM_TPU_COMPILE_CACHE.
if not os.environ.get("ADAM_TPU_NO_COMPILE_CACHE"):
    _cache_dir = os.environ.get("ADAM_TPU_COMPILE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "adam_tpu", "xla"
    )
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # unwritable FS — run without the cache
        pass

__version__ = "0.1.0"

from adam_tpu.formats.batch import ReadBatch  # noqa: E402,F401
from adam_tpu.models.dictionaries import (  # noqa: E402,F401
    SequenceDictionary,
    SequenceRecord,
    RecordGroupDictionary,
    RecordGroup,
)
from adam_tpu.models.positions import (  # noqa: E402,F401
    ReferencePosition,
    ReferenceRegion,
)
