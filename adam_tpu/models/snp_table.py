"""Known-variant tables.

``SnpTable`` (models/SnpTable.scala:28-97): per-contig sets of known SNP
positions, built empty, from a sites-only VCF-like file (contig, 1-based
pos, id, ref — one masked site per ref base), or from variants.
``IndelTable`` (models/IndelTable.scala:26-90): known indels for the
knowns-based realignment consensus model.

Device form: positions are kept as sorted i64 arrays per contig so batch
masking is a vectorized ``searchsorted`` membership test (the broadcast
role of the Spark-side table).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from adam_tpu.models.positions import ReferenceRegion


class SnpTable:
    def __init__(self, table: dict[str, np.ndarray] | None = None):
        # contig name -> sorted unique i64 positions
        self.table = {
            k: np.unique(np.asarray(v, dtype=np.int64))
            for k, v in (table or {}).items()
        }

    @staticmethod
    def from_file(path: str) -> "SnpTable":
        """Sites-only VCF-ish file: TAB columns (contig, 1-based pos, id,
        ref, ...); every base of ref masks one site (SnpTable.scala:66-90)."""
        with open(path) as fh:
            return SnpTable.from_lines(fh)

    @staticmethod
    def from_lines(lines) -> "SnpTable":
        table: dict[str, list[int]] = {}
        for line in lines:
            if not line.strip() or line.startswith("#"):
                continue
            parts = line.rstrip("\n").split("\t")
            contig, pos, ref = parts[0], int(parts[1]) - 1, parts[3]
            assert pos >= 0 and ref
            for i in range(len(ref)):
                table.setdefault(contig, []).append(pos + i)
        return SnpTable(table)

    @staticmethod
    def from_variants(variants) -> "SnpTable":
        """From (contig, 0-based pos) pairs (the loadVariants path)."""
        table: dict[str, list[int]] = {}
        for contig, pos in variants:
            table.setdefault(contig, []).append(pos)
        return SnpTable(table)

    def site_keys(self, contig_names: list[str]) -> np.ndarray:
        """Sorted composite ``contig_index << 40 | position`` site keys
        for the native observe kernel's in-walk masking."""
        keys = []
        for ci, name in enumerate(contig_names):
            arr = self.table.get(name)
            if arr is not None and len(arr):
                keys.append((np.int64(ci) << 40) | arr.astype(np.int64))
        if not keys:
            return np.zeros(0, np.int64)
        return np.sort(np.concatenate(keys))

    def contains(self, contig: str, pos: int) -> bool:
        arr = self.table.get(contig)
        if arr is None or not len(arr):
            return False
        i = np.searchsorted(arr, pos)
        return i < len(arr) and arr[i] == pos

    def mask_positions(self, contig_names: list[str], contig_idx, positions) -> np.ndarray:
        """Vectorized membership test -> bool mask of known-SNP sites.

        ``contig_idx`` is per-row i32[N] (one contig per read);
        ``positions`` is i64[N, L] per-base reference positions (< 0 =
        no position -> False).  Row-wise contig selection avoids
        materializing an N x L contig matrix.
        """
        contig_idx = np.asarray(contig_idx)
        positions = np.asarray(positions)
        out = np.zeros(positions.shape, dtype=bool)
        for ci, name in enumerate(contig_names):
            arr = self.table.get(name)
            if arr is None or not len(arr):
                continue
            rows = np.flatnonzero(contig_idx == ci)
            if not len(rows):
                continue
            pos = positions[rows]
            idx = np.searchsorted(arr, pos)
            idx_clipped = np.minimum(idx, len(arr) - 1)
            out[rows] = (arr[idx_clipped] == pos) & (pos >= 0)
        return out

    def __len__(self) -> int:
        return sum(len(v) for v in self.table.values())


@dataclass(frozen=True)
class IndelRecord:
    region: ReferenceRegion
    consensus: str  # inserted bases, or "" for deletion


class IndelTable:
    """Known indels per contig (IndelTable.scala:26-66)."""

    def __init__(self, table: dict[str, list[IndelRecord]] | None = None):
        self.table = dict(table or {})

    @staticmethod
    def from_variants(variants) -> "IndelTable":
        """From (contig, 0-based pos, ref, alt) tuples: insertion when
        len(ref)==1<len(alt) — consensus is alt minus anchor base at the
        anchor position; deletion when len(alt)==1<len(ref) — region spans
        the deleted bases (IndelTable.scala:43-64)."""
        table: dict[str, list[IndelRecord]] = {}
        for contig, pos, ref, alt in variants:
            if len(ref) == 1 and len(alt) > 1:
                rec = IndelRecord(
                    ReferenceRegion(contig, pos, pos + 1), alt[1:]
                )
            elif len(alt) == 1 and len(ref) > 1:
                rec = IndelRecord(
                    ReferenceRegion(contig, pos + 1, pos + len(ref)), ""
                )
            else:
                continue
            table.setdefault(contig, []).append(rec)
        return IndelTable(table)

    def get_indels_in_region(self, region: ReferenceRegion) -> list[IndelRecord]:
        return [
            r
            for r in self.table.get(region.referenceName, [])
            if r.region.overlaps(region)
        ]
