"""Genomic coordinate system.

Host-side value types with the semantics of the reference's
``models/ReferencePosition.scala:86`` and ``models/ReferenceRegion.scala:125``
(overlaps / merge / hull / intersection at :143-229), plus the integer
encodings used on device:

* a position on device is ``(contig_idx: i32, pos: i64)`` — contig *index*
  into a :class:`~adam_tpu.models.dictionaries.SequenceDictionary` rather
  than a name string;
* a total order over positions is the packed key
  ``(contig_idx + 1) << POS_BITS | pos`` (unmapped = contig -1 sorts with
  key 0 prefix handled by the sort pipeline), giving single-key radix/lex
  sorts on device.

All coordinates are 0-based, end-exclusive (same convention as the
reference).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import total_ordering

import numpy as np

# 2^40 bp per contig is comfortably above any real contig length; leaves
# 23 bits for contig index inside a signed i64 key.
POS_BITS = 40
POS_MASK = (1 << POS_BITS) - 1


def pack_position_key(contig_idx, pos):
    """(contig_idx, pos) -> sortable i64 key. Works on numpy or jnp arrays.

    Unmapped (contig_idx < 0) packs to key < 2^POS_BITS so mapped reads sort
    after all-unmapped only if caller wants that; the sort pipeline instead
    sends unmapped to the end explicitly (semantics of
    AlignmentRecordRDDFunctions.scala:249-256, where unmapped reads sort
    last keyed by name).
    """
    if hasattr(contig_idx, "astype"):  # numpy path (jnp arrays handled below)
        c = contig_idx.astype(np.int64) + 1
        p = pos.astype(np.int64)
    else:
        import jax.numpy as jnp

        if isinstance(contig_idx, jnp.ndarray) or isinstance(pos, jnp.ndarray):
            c = jnp.asarray(contig_idx, jnp.int64) + 1
            p = jnp.asarray(pos, jnp.int64)
        else:
            c = np.int64(contig_idx) + 1
            p = np.int64(pos)
    return (c << POS_BITS) | (p & POS_MASK)


def unpack_position_key(key):
    return (key >> POS_BITS) - 1, key & POS_MASK


@total_ordering
@dataclass(frozen=True)
class ReferencePosition:
    """A point on a contig (reference name form, host side)."""

    referenceName: str
    pos: int

    def __lt__(self, other: "ReferencePosition"):
        return (self.referenceName, self.pos) < (other.referenceName, other.pos)


@total_ordering
@dataclass(frozen=True)
class ReferenceRegion:
    """Half-open interval [start, end) on a contig.

    Semantics match models/ReferenceRegion.scala: ``merge`` requires
    overlap-or-adjacency, ``hull`` does not; ``distance`` is defined only on
    the same contig (1 for adjacent, matching :188-196).
    """

    referenceName: str
    start: int
    end: int

    def __post_init__(self):
        if self.start < 0 or self.end < self.start:
            raise ValueError(f"malformed region {self}")

    @property
    def width(self) -> int:
        return self.end - self.start

    def contains_point(self, p: ReferencePosition) -> bool:
        return (
            self.referenceName == p.referenceName
            and self.start <= p.pos < self.end
        )

    def contains(self, other: "ReferenceRegion") -> bool:
        return (
            self.referenceName == other.referenceName
            and self.start <= other.start
            and self.end >= other.end
        )

    def overlaps(self, other: "ReferenceRegion") -> bool:
        return (
            self.referenceName == other.referenceName
            and self.end > other.start
            and other.end > self.start
        )

    def is_adjacent(self, other: "ReferenceRegion") -> bool:
        return self.distance(other) == 1

    def distance(self, other: "ReferenceRegion"):
        """Distance in bp; 0 if overlapping, 1 if adjacent, None cross-contig."""
        if self.referenceName != other.referenceName:
            return None
        if self.overlaps(other):
            return 0
        if other.start >= self.end:
            return other.start - self.end + 1
        return self.start - other.end + 1

    def merge(self, other: "ReferenceRegion") -> "ReferenceRegion":
        if not (self.overlaps(other) or self.is_adjacent(other)):
            raise ValueError(f"cannot merge non-adjacent {self} and {other}")
        return self.hull(other)

    def hull(self, other: "ReferenceRegion") -> "ReferenceRegion":
        if self.referenceName != other.referenceName:
            raise ValueError("hull requires same contig")
        return ReferenceRegion(
            self.referenceName,
            min(self.start, other.start),
            max(self.end, other.end),
        )

    def intersection(self, other: "ReferenceRegion") -> "ReferenceRegion":
        if not self.overlaps(other):
            raise ValueError(f"regions {self} and {other} do not overlap")
        return ReferenceRegion(
            self.referenceName,
            max(self.start, other.start),
            min(self.end, other.end),
        )

    def pad(self, by: int, max_end: int | None = None) -> "ReferenceRegion":
        end = self.end + by if max_end is None else min(self.end + by, max_end)
        return ReferenceRegion(self.referenceName, max(0, self.start - by), end)

    def __lt__(self, other: "ReferenceRegion"):
        return (self.referenceName, self.start, self.end) < (
            other.referenceName,
            other.start,
            other.end,
        )


def regions_from_arrays(names, starts, ends):
    """Vector -> list[ReferenceRegion] helper for host post-processing."""
    return [
        ReferenceRegion(n, int(s), int(e))
        for n, s, e in zip(names, np.asarray(starts), np.asarray(ends))
    ]
