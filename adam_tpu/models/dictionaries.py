"""Sequence and read-group dictionaries.

Host-side metadata with the semantics of the reference's
``models/SequenceDictionary.scala:77-119`` (merge with compatibility check)
and ``models/RecordGroupDictionary.scala:62`` (name <-> id mapping).

The dictionary is also the bridge to the device encoding: contig *names*
become dense ``contig_idx`` i32 values; the cumulative-length table
(``offsets``) is what the genome partitioner
(:mod:`adam_tpu.parallel.partitioner`) uses to map positions onto the
device mesh — the role of GenomicPositionPartitioner's cumulative genome
offsets (rdd/GenomicPartitioners.scala:63-85).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional

import numpy as np


@dataclass(frozen=True)
class SequenceRecord:
    name: str
    length: int
    url: Optional[str] = None
    md5: Optional[str] = None
    refseq: Optional[str] = None
    genbank: Optional[str] = None
    assembly: Optional[str] = None
    species: Optional[str] = None

    def compatible_with(self, other: "SequenceRecord") -> bool:
        """Same name -> must agree on length (SequenceDictionary.scala:104-112)."""
        return self.name != other.name or self.length == other.length


@dataclass(frozen=True)
class SequenceDictionary:
    records: tuple[SequenceRecord, ...] = ()

    @staticmethod
    def from_lists(names, lengths) -> "SequenceDictionary":
        return SequenceDictionary(
            tuple(
                SequenceRecord(name=n, length=int(l))
                for n, l in zip(names, lengths)
            )
        )

    @staticmethod
    def from_sam_header_lines(lines: Iterable[str]) -> "SequenceDictionary":
        recs = []
        for line in lines:
            if not line.startswith("@SQ"):
                continue
            fields = dict(
                f.split(":", 1) for f in line.rstrip("\n").split("\t")[1:] if ":" in f
            )
            recs.append(
                SequenceRecord(
                    name=fields["SN"],
                    length=int(fields["LN"]),
                    url=fields.get("UR"),
                    md5=fields.get("M5"),
                    assembly=fields.get("AS"),
                    species=fields.get("SP"),
                )
            )
        return SequenceDictionary(tuple(recs))

    # ------------------------------------------------------------- lookups
    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def __contains__(self, name: str) -> bool:
        return any(r.name == name for r in self.records)

    def __getitem__(self, name: str) -> SequenceRecord:
        for r in self.records:
            if r.name == name:
                return r
        raise KeyError(name)

    def index(self, name: str) -> int:
        """Dense contig index used on device; raises KeyError if absent."""
        for i, r in enumerate(self.records):
            if r.name == name:
                return i
        raise KeyError(name)

    def index_or(self, name: str, default: int = -1) -> int:
        try:
            return self.index(name)
        except KeyError:
            return default

    @property
    def names(self) -> list[str]:
        return [r.name for r in self.records]

    @property
    def lengths(self) -> np.ndarray:
        return np.array([r.length for r in self.records], dtype=np.int64)

    @property
    def offsets(self) -> np.ndarray:
        """Cumulative genome offset of each contig start, plus total length.

        offsets[i] is the flattened-genome coordinate of contig i's base 0;
        offsets[-1] is the total genome length (the role of
        GenomicPositionPartitioner.cumulativeLengths).
        """
        return np.concatenate([[0], np.cumsum(self.lengths)])

    @property
    def total_length(self) -> int:
        return int(self.lengths.sum()) if len(self.records) else 0

    # -------------------------------------------------------------- algebra
    def is_compatible_with(self, other: "SequenceDictionary") -> bool:
        mine = {r.name: r for r in self.records}
        return all(
            mine[o.name].compatible_with(o) for o in other.records if o.name in mine
        )

    def merge(self, other: "SequenceDictionary") -> "SequenceDictionary":
        """Union; error on same-name different-length (":96-119" semantics)."""
        if not self.is_compatible_with(other):
            raise ValueError("incompatible sequence dictionaries")
        seen = {r.name for r in self.records}
        extra = tuple(r for r in other.records if r.name not in seen)
        return SequenceDictionary(self.records + extra)

    def to_sam_header_lines(self) -> list[str]:
        out = []
        for r in self.records:
            fields = [f"@SQ", f"SN:{r.name}", f"LN:{r.length}"]
            if r.url:
                fields.append(f"UR:{r.url}")
            if r.md5:
                fields.append(f"M5:{r.md5}")
            if r.assembly:
                fields.append(f"AS:{r.assembly}")
            if r.species:
                fields.append(f"SP:{r.species}")
            out.append("\t".join(fields))
        return out


@dataclass(frozen=True)
class RecordGroup:
    name: str
    sample: Optional[str] = None
    library: Optional[str] = None
    platform: Optional[str] = None
    platform_unit: Optional[str] = None
    sequencing_center: Optional[str] = None
    description: Optional[str] = None
    run_date: Optional[str] = None
    flow_order: Optional[str] = None
    key_sequence: Optional[str] = None
    predicted_insert_size: Optional[int] = None

    @staticmethod
    def from_sam_header_line(line: str) -> "RecordGroup":
        fields = dict(
            f.split(":", 1) for f in line.rstrip("\n").split("\t")[1:] if ":" in f
        )
        return RecordGroup(
            name=fields["ID"],
            sample=fields.get("SM"),
            library=fields.get("LB"),
            platform=fields.get("PL"),
            platform_unit=fields.get("PU"),
            sequencing_center=fields.get("CN"),
            description=fields.get("DS"),
            run_date=fields.get("DT"),
            flow_order=fields.get("FO"),
            key_sequence=fields.get("KS"),
            predicted_insert_size=(
                int(fields["PI"]) if "PI" in fields else None
            ),
        )

    def to_sam_header_line(self) -> str:
        pairs = [("ID", self.name), ("SM", self.sample), ("LB", self.library),
                 ("PL", self.platform), ("PU", self.platform_unit),
                 ("CN", self.sequencing_center), ("DS", self.description),
                 ("DT", self.run_date), ("FO", self.flow_order),
                 ("KS", self.key_sequence),
                 ("PI", str(self.predicted_insert_size)
                  if self.predicted_insert_size is not None else None)]
        return "\t".join(["@RG"] + [f"{k}:{v}" for k, v in pairs if v is not None])


@dataclass(frozen=True)
class RecordGroupDictionary:
    """Read groups, indexed densely; library lookup used by markdup
    (MarkDuplicates groups by library, MarkDuplicates.scala:78-80)."""

    groups: tuple[RecordGroup, ...] = ()

    @staticmethod
    def from_sam_header_lines(lines: Iterable[str]) -> "RecordGroupDictionary":
        return RecordGroupDictionary(
            tuple(
                RecordGroup.from_sam_header_line(line)
                for line in lines
                if line.startswith("@RG")
            )
        )

    def __len__(self):
        return len(self.groups)

    def __iter__(self):
        return iter(self.groups)

    def index(self, name: str) -> int:
        for i, g in enumerate(self.groups):
            if g.name == name:
                return i
        raise KeyError(name)

    def index_or(self, name: str, default: int = -1) -> int:
        try:
            return self.index(name)
        except KeyError:
            return default

    @property
    def names(self) -> list[str]:
        return [g.name for g in self.groups]

    def library_ids(self) -> np.ndarray:
        """Dense library id per read group (same library -> same id).

        -1-free; reads with read_group_idx == -1 get library id -1 at use
        sites.
        """
        libs: dict[Optional[str], int] = {}
        out = np.zeros(len(self.groups), dtype=np.int32)
        for i, g in enumerate(self.groups):
            key = g.library
            if key not in libs:
                libs[key] = len(libs)
            out[i] = libs[key]
        return out

    def merge(self, other: "RecordGroupDictionary") -> "RecordGroupDictionary":
        seen = {g.name for g in self.groups}
        for g in other.groups:
            if g.name in seen:
                mine = next(x for x in self.groups if x.name == g.name)
                if mine != g:
                    raise ValueError(f"conflicting read group {g.name}")
        extra = tuple(g for g in other.groups if g.name not in seen)
        return RecordGroupDictionary(self.groups + extra)
