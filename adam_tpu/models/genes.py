"""Gene / Transcript / Exon hierarchy assembled from flat features.

Semantics of ``models/Gene.scala`` and
``rdd/features/GeneFeatureRDDFunctions.asGenes``
(GeneFeatureRDDFunctions.scala:35-125): exons and CDS/UTR blocks group
by transcript id, transcripts join their blocks and group by gene id,
genes join their transcripts. The reference needs three groupBys and two
joins over Spark; here the grouping is dictionary maps on the host —
gene models are driver-side metadata in both designs (the heavy
sequence extraction runs over device-resident reference fragments).

Strand convention follows the reference (:29-33): boolean, Forward and
Independent -> True, Reverse -> False.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable

from adam_tpu.formats.features import FeatureBatch, STRAND_REVERSE
from adam_tpu.models.positions import ReferenceRegion

_COMPLEMENT = str.maketrans("ACGTNacgtn", "TGCANtgcan")


def reverse_complement(seq: str) -> str:
    return seq.translate(_COMPLEMENT)[::-1]


@dataclass(frozen=True)
class Exon:
    id: str
    transcript_id: str
    strand: bool
    region: ReferenceRegion

    def extract_sequence(self, reference: str) -> str:
        s = reference[self.region.start : self.region.end]
        return s if self.strand else reverse_complement(s)


@dataclass(frozen=True)
class CDS:
    transcript_id: str
    strand: bool
    region: ReferenceRegion

    def extract_sequence(self, reference: str) -> str:
        s = reference[self.region.start : self.region.end]
        return s if self.strand else reverse_complement(s)


@dataclass(frozen=True)
class UTR:
    transcript_id: str
    strand: bool
    region: ReferenceRegion


@dataclass(frozen=True)
class Transcript:
    id: str
    names: tuple
    gene_id: str
    strand: bool
    exons: tuple
    cds: tuple = ()
    utrs: tuple = ()

    @property
    def region(self) -> ReferenceRegion:
        regions = [e.region for e in self.exons]
        out = regions[0]
        for r in regions[1:]:
            out = out.hull(r)
        return out

    def extract_transcribed_rna_sequence(self, reference: str) -> str:
        """Contiguous min-start..max-end slice, reverse-complemented on
        the reverse strand (Gene.scala:96-106)."""
        lo = min(e.region.start for e in self.exons)
        hi = max(e.region.end for e in self.exons)
        s = reference[lo:hi]
        return s if self.strand else reverse_complement(s)

    def extract_spliced_mrna_sequence(self, reference: str) -> str:
        """Exon sequences concatenated 5'->3' (Gene.scala:137-147)."""
        exs = sorted(self.exons, key=lambda e: e.region.start)
        if not self.strand:
            exs = exs[::-1]
        return "".join(e.extract_sequence(reference) for e in exs)

    def extract_coding_sequence(self, reference: str) -> str:
        """CDS blocks concatenated 5'->3' (Gene.scala:117-126)."""
        blocks = sorted(self.cds, key=lambda c: c.region.start)
        if not self.strand:
            blocks = blocks[::-1]
        return "".join(c.extract_sequence(reference) for c in blocks)


@dataclass(frozen=True)
class Gene:
    id: str
    names: tuple
    strand: bool
    transcripts: tuple

    @property
    def regions(self) -> list:
        """Union of transcript spans (Gene.scala:59-61)."""
        from adam_tpu.ops import intervals as iv
        import numpy as np

        if not self.transcripts:
            return []
        regs = [t.region for t in self.transcripts]
        names = sorted({r.referenceName for r in regs})
        idx = {n: i for i, n in enumerate(names)}
        m_c, m_s, m_e, _ = iv.merge_intervals(
            np.array([idx[r.referenceName] for r in regs]),
            np.array([r.start for r in regs]),
            np.array([r.end for r in regs]),
        )
        return [
            ReferenceRegion(names[c], int(s), int(e))
            for c, s, e in zip(m_c, m_s, m_e)
        ]


def _strand(code: int) -> bool:
    return bool(code != STRAND_REVERSE)


def as_genes(feats: FeatureBatch) -> list[Gene]:
    """Assemble gene models from typed GTF features
    (GeneFeatureRDDFunctions.asGenes, :35-125)."""
    side = feats.sidecar
    names = feats.contig_names

    def region(i: int) -> ReferenceRegion:
        return ReferenceRegion(
            names[feats.contig_idx[i]], int(feats.start[i]), int(feats.end[i])
        )

    exons_by_tx: dict[str, list[Exon]] = {}
    cds_by_tx: dict[str, list[CDS]] = {}
    utrs_by_tx: dict[str, list[UTR]] = {}
    tx_rows: list[int] = []
    gene_rows: list[int] = []

    for i in range(len(feats)):
        ftype = side.feature_type[i]
        if ftype == "exon":
            for tid in side.parent_ids[i]:
                exons_by_tx.setdefault(tid, []).append(
                    Exon(side.feature_id[i], tid, _strand(feats.strand[i]),
                         region(i))
                )
        elif ftype == "CDS":
            for tid in side.parent_ids[i]:
                cds_by_tx.setdefault(tid, []).append(
                    CDS(tid, _strand(feats.strand[i]), region(i))
                )
        elif ftype == "UTR":
            for tid in side.parent_ids[i]:
                utrs_by_tx.setdefault(tid, []).append(
                    UTR(tid, _strand(feats.strand[i]), region(i))
                )
        elif ftype == "transcript":
            tx_rows.append(i)
        elif ftype == "gene":
            gene_rows.append(i)

    # transcripts join exons (inner join: transcripts without exons drop,
    # matching the reference's .join(exonsByTranscript))
    tx_by_gene: dict[str, list[Transcript]] = {}
    for i in tx_rows:
        tid = side.feature_id[i]
        if tid not in exons_by_tx:
            continue
        for gid in side.parent_ids[i]:
            tx_by_gene.setdefault(gid, []).append(
                Transcript(
                    tid, (tid,), gid, _strand(feats.strand[i]),
                    tuple(exons_by_tx[tid]),
                    tuple(cds_by_tx.get(tid, ())),
                    tuple(utrs_by_tx.get(tid, ())),
                )
            )

    # genes left-join transcripts
    return [
        Gene(
            side.feature_id[i],
            (side.feature_id[i],),
            _strand(feats.strand[i]),
            tuple(tx_by_gene.get(side.feature_id[i], ())),
        )
        for i in gene_rows
    ]
