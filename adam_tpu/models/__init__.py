from adam_tpu.models import genes
from adam_tpu.models.positions import ReferencePosition, ReferenceRegion
from adam_tpu.models.dictionaries import (
    SequenceDictionary,
    SequenceRecord,
    RecordGroupDictionary,
    RecordGroup,
)

__all__ = [
    "genes",
    "ReferencePosition",
    "ReferenceRegion",
    "SequenceDictionary",
    "SequenceRecord",
    "RecordGroupDictionary",
    "RecordGroup",
]
