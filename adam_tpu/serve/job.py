"""Job model for the multi-job transform service (``adam_tpu/serve``).

One :class:`JobSpec` describes one streamed transform the scheduler can
run, quarantine, drain and resume; it is deliberately a JSON-roundtrip
value object (``to_doc``/``from_doc``) because whole-process crash
recovery re-reads the spec from the job directory's durably written
``JOB.json`` — everything the pipeline needs to reproduce the run
bit-identically must survive the process (the RunJournal fingerprint
then re-validates that nothing changed underneath, PR 6).

Admission returns **typed results**, never queues unboundedly:
:class:`Admitted` carries the slotted job's id, :class:`Busy` carries a
human-readable reason (at capacity / draining / duplicate) plus the
machine-readable ``kind`` — the front-end decides whether to back off
and retry, exactly like a load-shedding RPC server.
"""

from __future__ import annotations

import re
from dataclasses import asdict, dataclass, field
from typing import Optional

#: Job lifecycle states (persisted verbatim in ``JOB.json``).
PENDING = "pending"
RUNNING = "running"
DONE = "done"
QUARANTINED = "quarantined"
INTERRUPTED = "interrupted"

#: States a crash-recovery scan resumes (``quarantined`` is sticky:
#: auto-resuming a poison job on every service restart would turn one
#: bad input into a crash loop for the whole pool — the operator
#: resubmits explicitly once the cause is fixed).
RESUMABLE_STATES = frozenset({PENDING, RUNNING, INTERRUPTED})

#: Terminal states (the job holds no slot, no lane and no lease).
TERMINAL_STATES = frozenset({DONE, QUARANTINED, INTERRUPTED})

_JOB_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


@dataclass
class JobSpec:
    """One streamed-transform job (the flag subset the streamed
    pipeline supports; known-sites inputs are PATHS so the spec stays a
    JSON value — the job thread loads the tables, and the journal
    fingerprint covers their content)."""

    job_id: str
    input: str
    output: str
    tenant: str = "default"
    #: the tenant's fair share — window grants interleave proportionally
    #: to it across concurrently running tenants (serve/fairness.py)
    weight: float = 1.0
    mark_duplicates: bool = True
    recalibrate: bool = True
    realign: bool = True
    known_snps: Optional[str] = None
    known_indels: Optional[str] = None
    window_reads: int = 262_144
    compression: str = "zstd"
    partitioner: Optional[str] = None
    #: job-scoped trace context (docs/OBSERVABILITY.md "Trace
    #: context"): minted at gateway submission (or by the scheduler
    #: for direct submits), echoed to the client, and — because the
    #: spec round-trips through JOB.json — stable across SIGKILL/
    #: recovery replay, so a job's trace stays ONE trace however many
    #: attempts it took
    trace_id: Optional[str] = None

    def validate(self) -> None:
        if not _JOB_ID_RE.match(self.job_id or ""):
            raise ValueError(
                f"job_id {self.job_id!r} must match {_JOB_ID_RE.pattern} "
                "(it names the job's run directory)"
            )
        if not self.input or not self.output:
            raise ValueError(
                f"job {self.job_id!r} needs both input and output paths"
            )
        if self.weight <= 0:
            raise ValueError(
                f"job {self.job_id!r} weight must be > 0 "
                f"(got {self.weight})"
            )
        if self.window_reads < 1:
            raise ValueError(
                f"job {self.job_id!r} window_reads must be >= 1 "
                f"(got {self.window_reads})"
            )

    def to_doc(self) -> dict:
        return asdict(self)

    @classmethod
    def from_doc(cls, doc: dict) -> "JobSpec":
        known = {f for f in cls.__dataclass_fields__}
        spec = cls(**{k: v for k, v in doc.items() if k in known})
        spec.validate()
        return spec


@dataclass(frozen=True)
class Admitted:
    """Typed admission success: the job holds a slot and is running."""

    job_id: str


@dataclass(frozen=True)
class Busy:
    """Typed admission rejection — the bounded-slots contract: a full
    or draining scheduler REFUSES instead of queueing unboundedly.
    ``kind`` is one of ``capacity`` / ``draining`` / ``duplicate`` /
    ``quota`` (the tenant spent its rolling-window byte/compute
    budget, serve/quota.py — the gateway's 429 quota leg).
    ``retry_after_s``, when set, is a budget-derived hint that
    OVERRIDES the gateway's grant-cadence Retry-After: quota frees on
    the rolling window's schedule, not at job-slot turnover speed."""

    reason: str
    kind: str = "capacity"
    retry_after_s: Optional[int] = None


@dataclass
class JobRecord:
    """Scheduler-side live state for one admitted job (the persisted
    subset mirrors into ``JOB.json`` after every transition)."""

    spec: JobSpec
    state: str = PENDING
    attempts: int = 0
    error: Optional[str] = None
    #: True when this record was rebuilt by the crash-recovery scan —
    #: its first run attempt resumes from the journal instead of
    #: starting fresh
    recovered: bool = False
    #: True once the job's runner thread has fully unwound (terminal
    #: state durably persisted, lease released, lane deregistered) —
    #: ``JobScheduler.wait`` blocks on THIS, not on the state alone, so
    #: a drain that returns guarantees every JOB.json is fsync'd
    settled: bool = False
    stats: Optional[dict] = field(default=None, repr=False)
